"""Benchmark driver — prints ONE JSON line.

North-star config (BASELINE.md): RandomPatchCifar featurization — the
Convolver -> SymmetricRectifier -> Pooler -> ImageVectorizer pipeline of
reference src/main/scala/pipelines/images/cifar/RandomPatchCifar.scala:53-56
at the canonical scale (numFilters=100, 6x6 patches, 32x32x3 images) —
measured as steady-state images/sec/chip on synthetic CIFAR-shaped data.

The reference publishes no throughput numbers (BASELINE.md), so
``vs_baseline`` compares against this repo's own round-1 record when present
(BENCH_r01.json measured a different, trivial metric — the MNIST FFT
pipeline — so the first cifar number re-bases the series at 1.0).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.workloads.cifar_random_patch import (
    RandomCifarConfig,
    build_conv_pipeline,
    learn_filters,
)


def main():
    conf = RandomCifarConfig(
        num_filters=100,
        patch_size=6,
        patch_steps=1,
        pool_size=14,
        pool_stride=13,
        alpha=0.25,
        whitener_size=20000,
        featurize_chunk=1024,
    )
    n_bench = conf.featurize_chunk
    iters = 30

    rng = np.random.default_rng(0)
    # Whitener/filter learning on a small synthetic image set (not timed —
    # the reference fits ZCA driver-side once; the benchmark is the
    # featurization throughput that dominates pipeline wall-clock).
    train_imgs = rng.uniform(0, 255, (512, 32, 32, 3)).astype(np.float32)
    filters, whitener = learn_filters(conf, train_imgs)
    conv_pipe = build_conv_pipeline(conf, filters, whitener)
    feat_fn = jax.jit(conv_pipe.__call__)

    batch = jnp.asarray(
        rng.uniform(0, 255, (n_bench, 32, 32, 3)).astype(np.float32)
    )
    feat_fn(batch).block_until_ready()  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = feat_fn(batch)
    out.block_until_ready()
    dt = time.perf_counter() - t0

    n_chips = len(jax.devices())
    images_per_sec_per_chip = (n_bench * iters) / dt / n_chips
    print(
        json.dumps(
            {
                "metric": "random_patch_cifar_featurize",
                "value": round(images_per_sec_per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": 1.0,
            }
        )
    )


if __name__ == "__main__":
    main()
