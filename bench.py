"""Benchmark driver — prints ONE JSON line.

Provisional benchmark: MnistRandomFFT canonical config (--numFFTs 4
--blockSize 2048, reference README.md:14-24 / BASELINE.json configs) on
synthetic MNIST-shaped data; metric is end-to-end featurize+predict
images/sec/chip.  Will be upgraded to RandomPatchCifar (the north-star
config) once the image stack lands.

The reference publishes no throughput numbers (BASELINE.md), so
``vs_baseline`` is reported as 1.0 by convention: the baseline is accuracy
parity, and any measured throughput is the number to beat in later rounds.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from keystone_tpu.core.pipeline import Pipeline
from keystone_tpu.ops.stats import LinearRectifier, PaddedFFT, RandomSignNode
from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels, ZipVectors
from keystone_tpu.solvers.block import BlockLeastSquaresEstimator


def main():
    image_size = 784
    num_ffts = 4
    block_size = 2048
    num_classes = 10
    n_train = 8192
    n_bench = 16384
    iters = 20

    key = jax.random.PRNGKey(0)
    chains = []
    for _ in range(num_ffts):
        key, sub = jax.random.split(key)
        chains.append(
            Pipeline(
                [
                    RandomSignNode.create(image_size, sub),
                    PaddedFFT(),
                    LinearRectifier(0.0),
                ]
            )
        )

    kx, ky, kb = jax.random.split(key, 3)
    train_x = jax.random.uniform(kx, (n_train, image_size), jnp.float32)
    train_y = jax.random.randint(ky, (n_train,), 0, num_classes)
    labels = ClassLabelIndicatorsFromIntLabels(num_classes)(train_y)

    feats = ZipVectors.apply([chain(train_x) for chain in chains])
    model = BlockLeastSquaresEstimator(block_size, 1, 1e-3).fit(feats, labels)

    @jax.jit
    def predict(batch):
        f = ZipVectors.apply([chain(batch) for chain in chains])
        return jnp.argmax(model(f), axis=-1)

    bench_x = jax.random.uniform(kb, (n_bench, image_size), jnp.float32)
    predict(bench_x).block_until_ready()  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = predict(bench_x)
    out.block_until_ready()
    dt = time.perf_counter() - t0

    n_chips = len(jax.devices())
    images_per_sec_per_chip = (n_bench * iters) / dt / n_chips
    print(
        json.dumps(
            {
                "metric": "mnist_random_fft_featurize_predict",
                "value": round(images_per_sec_per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": 1.0,
            }
        )
    )


if __name__ == "__main__":
    main()
