"""Benchmark driver — prints ONE JSON line.

Primary metric (BASELINE.md north star #1): RandomPatchCifar featurization —
the Convolver -> SymmetricRectifier -> Pooler -> ImageVectorizer pipeline of
reference src/main/scala/pipelines/images/cifar/RandomPatchCifar.scala:53-56
at the canonical scale (numFilters=100, 6x6 patches, 32x32x3 images) —
measured as steady-state images/sec/chip on synthetic CIFAR-shaped data.

Timing methodology (round 3 fix): the device here sits behind a tunneled
transport with ~126 ms host<->device round-trip latency, and repeated
dispatches of the SAME program on the SAME input are deduplicated somewhere
in the stack (measured: 40 identical dispatches complete in the time of ~8
real executions, while a serially-dependent in-graph chain of the same
computation runs 2.4x slower per step — checksums identical).  Rounds 1-2
timed dispatch loops and therefore OVERSTATED throughput; all compute
timings now run as a ``lax.scan`` chain with a serial data dependency and a
non-linear readout inside one compiled program (dedup-impossible,
transfer-free), and fixed costs cancel by differencing a K-length and a
2K-length chain (see timed_chain).  ``vs_baseline`` against r<=2 records
mixes methodologies; the r3 value is the honest baseline going forward.
Residual run-to-run spread on this shared tunneled chip is ~10-15%.

Also reported inside the same JSON line:
- ``mfu`` / ``flops_per_sec``: achieved FLOP/s from XLA's compiled cost
  analysis divided by wall-clock, and the fraction of the chip's peak
  (bf16 systolic-array peak — TPU matmuls run bf16 passes by default).
- ``solve``: BlockLeastSquares fit time on the featurized batch — the
  reference pipeline's wall-clock is featurize + solve, so both are timed.
  The fit is ONE compiled program (solvers/block._fused_bcd_fit);
  ``solve_seconds`` is steady-state wall-clock (one dispatch round-trip on
  this tunneled transport), ``solve_device_seconds`` is chain-measured
  device compute only.
- ``extra_metrics.imagenet_fv_featurize``: north star #2 — the
  SIFT -> PCA-project -> FisherVector ImageNet featurization branch
  (reference ImageNetSiftLcsFV.scala:41-94) in images/sec/chip.
- ``vs_baseline``: this metric divided by the previous round's recorded
  value (BENCH_r*.json), 1.0 when no prior record of the same metric exists.

The reference itself publishes no throughput numbers (BASELINE.md), so the
baseline series is this repo's own round history.
"""

from __future__ import annotations

import glob
import json
import os
import re
import time

import jax
import jax.numpy as jnp
import numpy as np


from keystone_tpu.core import trace as ktrace
import keystone_tpu.core.resilience  # noqa: F401 — adopts "faults" into ktrace.metrics
from keystone_tpu.ops.fisher import FisherVector
from keystone_tpu.ops.sift import SIFTExtractor
from keystone_tpu.solvers.block import BlockLeastSquaresEstimator
from keystone_tpu.solvers.gmm import GaussianMixtureModel
from keystone_tpu.solvers.pca import BatchPCATransformer
from keystone_tpu.workloads.cifar_random_patch import (
    RandomCifarConfig,
    build_conv_pipeline,
    learn_filters,
)

# bf16 systolic-array peak FLOP/s per chip by device kind (public specs).
# f32 inputs still run through bf16 MXU passes under default precision, so
# this is the honest denominator for MFU.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,  # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,  # v6e / Trillium
}

# HBM bandwidth per chip (public specs) — the roofline denominator.  An op
# with arithmetic intensity I FLOP/byte is memory-bound below the ridge
# point (peak_flops / hbm_bw) and its ceiling is I * hbm_bw.
HBM_BW = {
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,
    "TPU v4": 1228e9,
    "TPU v6 lite": 1640e9,
}


def roundtrip_latency() -> float:
    """Host<->device round-trip seconds for a trivial scalar pull."""
    f = jax.jit(lambda x: x + 1.0)
    v = float(f(jnp.float32(0)))
    t0 = time.perf_counter()
    reps = 5
    for _ in range(reps):
        v = float(f(jnp.float32(v)))
    return (time.perf_counter() - t0) / reps


class NoiseFloorError(RuntimeError):
    """timed_chain's differenced compute did not clear the jitter floor."""


def timed_chain(fn, arg, chain_len: int, repeats: int = 3) -> float:
    """Seconds per application of ``fn(arg)``, measured as a lax.scan chain
    with a serial scalar dependency: iteration i's input is perturbed by
    iteration i-1's sum-of-squares readout, so no layer of the stack can
    deduplicate or reorder the executions, the readout is non-linear (see
    the comment in ``step``), and the batch never re-crosses the tunnel.

    Fixed costs (the ~126 ms round-trip, dispatch, the host pull) are
    cancelled by DIFFERENCING chains of length ``chain_len`` and
    ``2*chain_len`` rather than subtracting a separately-measured latency —
    the latency estimate's own +/-30 ms jitter otherwise dominates when the
    chain's compute is tens of milliseconds."""

    def step(a, acc, _):
        out = fn(a + (acc * 1e-30).astype(a.dtype))
        # sum-of-SQUARES readout: a plain sum is linear, and XLA's algebraic
        # simplifier can collapse sum∘conv / sum∘pool into closed forms that
        # skip the very work being timed (observed: a lone conv "measured"
        # 2x above peak FLOP/s under a linear readout)
        return acc + jnp.sum(out * out).astype(jnp.float32), None

    # ``arg`` enters as a runtime parameter, NOT a closure: closed-over
    # arrays are embedded in the lowered program, which blows up remote
    # compile payloads for large operands
    def make_chain(length):
        @jax.jit
        def chain(seed, a):
            acc, _ = jax.lax.scan(
                lambda c, x: step(a, c, x), seed, None, length=length
            )
            return acc

        return chain

    short, long = make_chain(chain_len), make_chain(2 * chain_len)

    # distinct seed per dispatch: a repeat is never a bit-identical program
    # invocation, so the cross-dispatch dedup this function exists to defeat
    # cannot serve a repeat from cache
    float(short(jnp.float32(1.0), arg))  # compile + warm
    float(long(jnp.float32(1.5), arg))
    best_short = best_long = float("inf")
    for i in range(repeats):
        t0 = time.perf_counter()
        float(short(jnp.float32(2.0 + i), arg))
        best_short = min(best_short, time.perf_counter() - t0)
        t0 = time.perf_counter()
        float(long(jnp.float32(20.0 + i), arg))
        best_long = min(best_long, time.perf_counter() - t0)
    diff = best_long - best_short
    # The differenced mins must clear the transport's jitter floor — when the
    # chain's own compute is comparable to the ~±30 ms dispatch noise the
    # difference can go near-zero (or negative) and a silent clamp would
    # report absurdly inflated throughput.  Fail loudly instead: the caller
    # should raise chain_len until the chain compute dominates the noise.
    if diff < 0.1 * best_short:
        raise NoiseFloorError(
            f"timed_chain noise floor: best_long-best_short={diff:.4f}s is "
            f"<10% of best_short={best_short:.4f}s; raise chain_len "
            f"(chain compute does not dominate transport jitter)"
        )
    return diff / chain_len


def timed_chain_auto(fn, arg, chain_len: int, max_len: int = 2048) -> float:
    """timed_chain, doubling chain_len until the differenced compute clears
    the transport-jitter noise floor (for ops whose per-iteration cost is
    not known in advance).  Only the noise-floor signal retries — real
    device/XLA failures (which also subclass RuntimeError) propagate."""
    while True:
        try:
            return timed_chain(fn, arg, chain_len)
        except NoiseFloorError:
            if chain_len * 2 > max_len:
                raise
            chain_len *= 2


def _make_jpeg_tar(
    rng,
    n_images: int,
    size: int,
    labeled: bool = False,
    subsamplings: tuple | None = None,
    qualities: tuple = (90,),
    restart_every: int = 0,
) -> str:
    """Temp tar of random ``size``-px JPEGs for the ingest benches (the
    caller unlinks it).  ``labeled=True`` prefixes members with a 0-9 class
    directory — the name-borne-label layout the CIFAR stream path reads.

    ``subsamplings`` / ``qualities`` cycle PER MEMBER (PIL subsampling
    codes: 0 = 4:4:4, 1 = 4:2:2, 2 = 4:2:0; ``None`` keeps the encoder
    default) and ``restart_every`` adds restart markers every N MCU rows
    on every third member — so the tar exercises the corpus the DEVICE
    decode path (ops.jpeg_device) actually claims, not one
    encoder-default shape."""
    import io
    import tarfile
    import tempfile

    from PIL import Image as PILImage

    with tempfile.NamedTemporaryFile(suffix=".tar", delete=False) as tmp:
        path = tmp.name
    with tarfile.open(path, "w") as tf:
        for i in range(n_images):
            arr = rng.integers(0, 256, (size, size, 3), dtype=np.uint8)
            buf = io.BytesIO()
            kw = {"quality": qualities[i % len(qualities)]}
            if subsamplings is not None:
                kw["subsampling"] = subsamplings[i % len(subsamplings)]
            if restart_every and i % 3 == 0:
                kw["restart_marker_rows"] = restart_every
            PILImage.fromarray(arr).save(buf, format="JPEG", **kw)
            data = buf.getvalue()
            name = f"{i % 10}/img_{i:05d}.jpg" if labeled else f"img_{i:05d}.jpg"
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    return path


def one_hot_pm1(rng, n: int, k: int):
    """+/-1 one-hot label matrix [n, k] — the reference workloads' label
    encoding (ClassLabelIndicators: +1 true class, -1 elsewhere)."""
    return jnp.asarray(2.0 * np.eye(k)[rng.integers(0, k, n)] - 1.0, jnp.float32)


def compiled_cost(jitted_fn, *args) -> tuple[float | None, float | None]:
    """(FLOPs, HBM bytes accessed) of the compiled program from XLA's cost
    analysis — the roofline numerator and denominator.

    Delegates to ``core.profiler.jit_cost`` (ISSUE 14): the profiler is
    the ONE place the raw cost_analysis quirks live; lowering still hits
    the jit cache, so a warm function never compiles twice."""
    from keystone_tpu.core import profiler as kprof

    return kprof.jit_cost(jitted_fn, *args)


def roofline(flops, bytes_accessed, per_iter, peak, bw):
    """Arithmetic intensity, memory-bound ceiling, and achieved fractions."""
    if not (flops and bytes_accessed and peak and bw):
        return {}
    intensity = flops / bytes_accessed
    ceiling = min(intensity * bw, peak)
    achieved = flops / per_iter
    return {
        "intensity_flop_per_byte": round(intensity, 2),
        "ridge_flop_per_byte": round(peak / bw, 1),
        "memory_ceiling_flops": ceiling,
        "fraction_of_ceiling": round(achieved / ceiling, 3),
        # MFU rides in every roofline block (ISSUE 14): fraction of the
        # device PEAK, the cross-round headline bench_diff watches —
        # fraction_of_ceiling above is position vs the memory-bound
        # ceiling, a different (and intensity-dependent) denominator.
        "mfu": round(achieved / peak, 4),
        "hbm_gbps_achieved": round(bytes_accessed / per_iter / 1e9, 1),
    }


def prior_bench_value(metric: str) -> float | None:
    """Most recent BENCH_r*.json record of the same metric."""
    best_round, best_val = -1, None
    for path in glob.glob(os.path.join(os.path.dirname(__file__) or ".", "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            rec = json.load(open(path))
        except (OSError, json.JSONDecodeError):
            continue
        # driver wraps the printed line under "parsed"
        rec = rec.get("parsed", rec)
        if (
            isinstance(rec, dict)
            and rec.get("metric") == metric
            and int(m.group(1)) > best_round
        ):
            best_round, best_val = int(m.group(1)), float(rec["value"])
    return best_val


def bench_cifar_featurize(rng):
    """North star #1: conv featurization + the block solve it feeds."""
    conf = RandomCifarConfig(
        num_filters=100,
        patch_size=6,
        patch_steps=1,
        pool_size=14,
        pool_stride=13,
        alpha=0.25,
        whitener_size=20000,
        featurize_chunk=1024,
    )
    n_bench = conf.featurize_chunk

    train_imgs = rng.uniform(0, 255, (512, 32, 32, 3)).astype(np.float32)
    filters, whitener = learn_filters(conf, train_imgs)
    conv_pipe = build_conv_pipeline(conf, filters, whitener)
    feat_fn = jax.jit(conv_pipe.__call__)

    batch = jnp.asarray(
        rng.uniform(0, 255, (n_bench, 32, 32, 3)).astype(np.float32)
    )
    feats = feat_fn(batch)
    feats.block_until_ready()  # materialize features for the solve below

    per_iter = timed_chain(conv_pipe.__call__, batch, chain_len=128)
    flops, bytes_accessed = compiled_cost(feat_fn, batch)
    images_per_sec = n_bench / per_iter
    flops_per_sec = flops / per_iter if flops else None

    # Solve timing: BlockLeastSquares on the featurized batch (reference
    # RandomPatchCifar.scala:68 — the other half of pipeline wall-clock).
    # The fit is ONE compiled program (solvers/block._fused_bcd_fit); the
    # first call is the compile warm-up, the second is the steady-state
    # wall-clock (dispatch + compute + one scalar pull, minus the measured
    # round-trip), and the chain measurement is device compute only.
    labels = one_hot_pm1(np.random.default_rng(1), n_bench, 10)
    est = BlockLeastSquaresEstimator(4096, num_iter=1, lam=10.0)

    def pull(model):
        # fit returns unsynced device arrays; a scalar host pull is the one
        # sync the tunneled platform honors (block_until_ready can return
        # before execution on this transport)
        float(
            sum(jnp.sum(x[0]) for x in model.xs) + jnp.sum(jnp.asarray(model.b))
        )

    pull(est.fit(feats, labels))  # compile warm-up
    # The timed fit gets a PERTURBED input: re-dispatching the identical
    # program on identical inputs can be served by the transport's dedup
    # cache (observed: solve_seconds collapsing to ~0), the same trap the
    # chain methodology defeats for the featurize timings.  RELATIVE
    # perturbation (an absolute epsilon is below f32 ULP for values >= 32
    # and would round away); synced by a scalar pull, the one sync this
    # transport honors (see the pull() note above).
    feats_t = feats * jnp.float32(1.0 + 1e-6)
    float(jnp.sum(feats_t[0]))
    lat = roundtrip_latency()
    t1 = time.perf_counter()
    pull(est.fit(feats_t, labels))
    solve_secs = max(time.perf_counter() - t1 - lat, 1e-9)

    # Device-compute-only: the same fused fit program in a serial chain.
    from keystone_tpu.solvers.block import _fused_bcd_fit

    def solve_fn(f):
        models, _, _ = _fused_bcd_fit(
            f, labels, jnp.float32(est.lam), f.shape[0], est.num_iter,
            (f.shape[1],), None,
        )
        return models[0]

    solve_device_secs = timed_chain_auto(solve_fn, feats, chain_len=256)

    return {
        "images_per_sec": images_per_sec,
        "flops_per_sec": flops_per_sec,
        "flops_per_image": flops / n_bench if flops else None,
        "bytes_per_image": bytes_accessed / n_bench if bytes_accessed else None,
        "per_iter": per_iter,
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "solve_seconds": solve_secs,
        "solve_examples_per_sec": n_bench / solve_secs,
        "solve_device_seconds": solve_device_secs,
    }


def bench_imagenet_fv_featurize(rng):
    """North star #2: the SIFT -> PCA(64) -> FV(16) ImageNet branch
    (reference ImageNetSiftLcsFV.scala:41-94, descDim=64 vocabSize=16) on
    256x256 grayscale images."""
    n_bench = 64
    h = w = 256
    desc_dim, vocab = 64, 16

    # bf16 intermediates — the workload configuration (imagenet_sift_lcs_fv
    # passes the same; op-level default is f32 for parity-critical callers)
    sift = SIFTExtractor(scale_step=1, compute_dtype=jnp.bfloat16)
    pca = BatchPCATransformer(
        jnp.asarray(rng.normal(size=(128, desc_dim)) / 12.0, jnp.float32)
    )
    gmm = GaussianMixtureModel(  # centers as columns: [d, K]
        jnp.asarray(rng.normal(size=(desc_dim, vocab)), jnp.float32),
        jnp.asarray(rng.uniform(0.5, 1.5, (desc_dim, vocab)), jnp.float32),
        jnp.asarray(np.full(vocab, 1.0 / vocab), jnp.float32),
    )
    fv = FisherVector(gmm)

    def featurize(imgs):
        return fv(pca(sift(imgs)))

    fn = jax.jit(featurize)
    batch = jnp.asarray(rng.uniform(0, 1, (n_bench, h, w)).astype(np.float32))
    per_iter = timed_chain(featurize, batch, chain_len=12)
    flops, bytes_accessed = compiled_cost(fn, batch)
    return {
        "images_per_sec": n_bench / per_iter,
        "flops_per_sec": flops / per_iter if flops else None,
        "per_iter": per_iter,
        "flops": flops,
        "bytes_accessed": bytes_accessed,
    }


def bench_stage_ops(rng):
    """Per-stage timings for the remaining hot ops of the north-star
    pipelines (SURVEY §3.3): GMM EM fit, LCS, ZCA whitening fit, PCA fit —
    featurize and the block solve are covered by the headline metrics.
    Shapes are the production defaults of the workloads that call each op
    (imagenet_sift_lcs_fv: descDim=64 vocabSize=16 LCS(4,16,6);
    cifar_random_patch: 6x6x3 patch ZCA)."""
    from keystone_tpu.ops.lcs import LCSExtractor
    from keystone_tpu.solvers.gmm import GaussianMixtureModelEstimator, _em_step
    from keystone_tpu.solvers.pca import compute_pca
    from keystone_tpu.solvers.whitening import ZCAWhitenerEstimator

    out = {}

    def stage(name):
        """Isolate each stage: one noisy/failed op records an error entry
        instead of discarding every other stage's measurement."""
        def deco(fn):
            try:
                out[name] = fn()
            except Exception as e:  # noqa: BLE001 - recorded, not swallowed
                out[name] = _error_record(e)
        return deco

    @stage("gmm_em_step")
    def _():
        # GMM EM (reference EncEval.cxx:122-151 — the one driver-side C++
        # hot loop): time the compiled EM step at the ImageNet-FV shape.
        n_gmm, d, k = 1 << 18, 64, 16
        x = jnp.asarray(rng.normal(size=(n_gmm, d)).astype(np.float32))
        est = GaussianMixtureModelEstimator(k, max_iter=1)
        gmm0 = est.fit(x)  # warm: init + one EM step compiles

        def em_fn(xx):
            m, v, w, _ = _em_step(
                xx, gmm0.means, gmm0.variances, gmm0.weights,
                jnp.float32(1e-3), est.chunk,
            )
            return m + jnp.sum(v) + jnp.sum(w)

        per_iter = timed_chain_auto(em_fn, x, chain_len=16)
        return {
            "n": n_gmm, "d": d, "k": k,
            "samples_per_sec": round(n_gmm / per_iter, 1),
            "seconds_per_iter": round(per_iter, 5),
        }

    @stage("lcs_featurize")
    def _():
        # LCS featurization (reference LCSExtractor.scala via imagenet LCS
        # branch): 256x256 RGB at the workload defaults.
        n_img = 32
        lcs = LCSExtractor(4, 16, 6)
        imgs = jnp.asarray(
            rng.uniform(0, 1, (n_img, 256, 256, 3)).astype(np.float32)
        )
        per_iter = timed_chain_auto(lambda b: lcs(b), imgs, chain_len=24)
        return {"images_per_sec": round(n_img / per_iter, 1)}

    @stage("zca_fit")
    def _():
        # ZCA whitening fit (reference ZCAWhitener.scala:19-64): the cifar
        # 100k x 108 patch-sample SVD.
        zca_mat = jnp.asarray(
            rng.normal(size=(100_000, 108)).astype(np.float32)
        )
        zca = ZCAWhitenerEstimator()
        per_iter = timed_chain_auto(
            lambda m: zca.fit_single(m).whitener, zca_mat, chain_len=4
        )
        return {"n": 100_000, "d": 108, "seconds": round(per_iter, 4)}

    @stage("pca_fit")
    def _():
        # PCA fit (reference PCA.scala:46-61): SIFT-descriptor sample at
        # the ImageNet shape (128-dim descriptors -> 64 components).
        pca_mat = jnp.asarray(
            rng.normal(size=(1 << 18, 128)).astype(np.float32)
        )
        per_iter = timed_chain_auto(
            lambda m: compute_pca(m, 64), pca_mat, chain_len=4
        )
        return {"n": 1 << 18, "d": 128, "dims": 64,
                "seconds": round(per_iter, 4)}

    @stage("mnist_fft_featurize")
    def _():
        # MnistRandomFFT featurization (reference MnistRandomFFT.scala:
        # 51-60): numFFTs random-sign -> padded-FFT -> rectify, zipped.
        from keystone_tpu.core.pipeline import Pipeline
        from keystone_tpu.ops.stats import (
            LinearRectifier, PaddedFFT, RandomSignNode,
        )
        from keystone_tpu.ops.util import ZipVectors

        key = jax.random.PRNGKey(0)
        chains = []
        for _ in range(4):  # canonical --numFFTs 4
            key, sub = jax.random.split(key)
            chains.append(
                Pipeline([RandomSignNode.create(784, sub), PaddedFFT(),
                          LinearRectifier(0.0)])
            )
        mnist_batch = jnp.asarray(
            rng.normal(size=(4096, 784)).astype(np.float32)
        )

        def mnist_feat(b):
            return ZipVectors.apply([c(b) for c in chains])

        per_iter = timed_chain_auto(mnist_feat, mnist_batch, chain_len=64)
        return {"num_ffts": 4, "examples_per_sec": round(4096 / per_iter, 1)}

    @stage("timit_cosine_features")
    def _():
        # TIMIT cosine random features (reference TimitPipeline.scala:
        # 63-70): one [N, 440] x [440, D] gemm + cos per cosine batch.
        from keystone_tpu.ops.stats import CosineRandomFeatures

        crf = CosineRandomFeatures.create(440, 16384, 0.555, jax.random.PRNGKey(1))
        timit_batch = jnp.asarray(
            rng.normal(size=(4096, 440)).astype(np.float32)
        )
        per_iter = timed_chain_auto(lambda b: crf(b), timit_batch, chain_len=64)
        return {"d_out": 16384, "examples_per_sec": round(4096 / per_iter, 1)}

    @stage("block_solve_multiblock")
    def _():
        # The scanned-BCD path of the fused block solve (reference
        # BlockLinearMapper.scala:147-204 with 4 feature blocks x 2
        # epochs): device compute via the serial chain, at a shape where
        # the lax.scan over stacked blocks actually iterates.
        from keystone_tpu.solvers.block import _fused_bcd_fit

        n_s, d_s, bs_s, k_s = 1024, 3200, 800, 10
        xs_ = jnp.asarray(rng.normal(size=(n_s, d_s)).astype(np.float32))
        ys_ = one_hot_pm1(rng, n_s, k_s)
        widths = (bs_s,) * (d_s // bs_s)

        def solve_fn(f):
            models, _, _ = _fused_bcd_fit(
                f, ys_, jnp.float32(1.0), f.shape[0], 2, widths, None
            )
            return models

        per_iter = timed_chain_auto(solve_fn, xs_, chain_len=64)
        return {
            "n": n_s, "d": d_s, "blocks": len(widths), "epochs": 2,
            "device_seconds": round(per_iter, 5),
            "examples_per_sec": round(n_s / per_iter, 1),
        }

    @stage("bwls_fit")
    def _():
        # BWLS fit (reference BlockWeightedLeastSquares.scala:106-312) —
        # the ImageNet pipeline's solver tail, the whole solve one compiled
        # program.  Beyond steady-state wall, the round-5 rigor ask: device
        # seconds + cost analysis of the fused solve program itself, and a
        # wall breakdown whose components are each measured at the REAL
        # shape the fit runs (VERDICT r5 weak #2: the old breakdown summed
        # to more than wall with nothing saying the components overlapped).
        import keystone_tpu.solvers.weighted as wsolver
        from keystone_tpu.solvers.weighted import (
            BlockWeightedLeastSquaresEstimator,
        )

        n_b, d_b, c_b = 8192, 2048, 64
        xw = jnp.asarray(rng.normal(size=(n_b, d_b)).astype(np.float32))
        yw = one_hot_pm1(rng, n_b, c_b)
        bwls = BlockWeightedLeastSquaresEstimator(
            1024, num_iter=1, lam=0.01, mixture_weight=0.5
        )

        # Capture the exact arguments the fit hands the fused program so it
        # can be AOT-timed in isolation (no duplicated preprocessing
        # logic).  The capture substitutes the NON-donating variant so the
        # captured buffers survive the fit for the isolated timing below;
        # both the warm and the timed fit run through it, so the timed
        # wall never includes a donating-variant compile.
        captured = {}
        orig_exec = wsolver._execute_fused_bwls

        def capture(plan, args, statics):
            captured["args"], captured["statics"] = args, statics
            return wsolver._fused_bwls_fit(*args, *statics)

        wsolver._execute_fused_bwls = capture
        try:
            m0 = bwls.fit(xw, yw)  # warm: compiles every program + captures
            float(sum(jnp.sum(x) for x in m0.xs))  # sync

            # Steady-state wall of the WHOLE fit (perturbed input defeats
            # transport dedup; relative perturbation per the solve-timing
            # note).
            xw_t = xw * jnp.float32(1.0 + 1e-6)
            float(jnp.sum(xw_t[0]))
            t0 = time.perf_counter()
            m1 = bwls.fit(xw_t, yw)
            float(sum(jnp.sum(x) for x in m1.xs))
            wall = time.perf_counter() - t0
        finally:
            wsolver._execute_fused_bwls = orig_exec

        if "args" not in captured:
            # The fit's ladder never reached the fused tier (budget denied
            # it and stepwise/host-staged ran): the AOT isolation below is
            # meaningless — record the wall + the ladder's own audit trail.
            rep = bwls.last_fit_report
            return {
                "n": n_b, "d": d_b, "classes": c_b,
                "wall_seconds": round(wall, 3),
                "note": "fused tier not chosen; AOT solve isolation skipped",
                "solver_report": rep.record() if rep is not None else None,
            }

        # Host prep: argmax pull + argsort + index builds, measured directly.
        t0 = time.perf_counter()
        ci = np.asarray(jnp.argmax(yw, axis=1))
        order_np = np.argsort(ci, kind="stable")
        host_prep = time.perf_counter() - t0

        # The regroup gather timed on the REAL fallback path the fit runs
        # (ADVICE r5 low): p_tot = n + n_max rows, column-chunked takes
        # into a preallocated output — for BOTH the design matrix and the
        # labels, since the fit sorts each.
        n_max_b = int(np.bincount(ci, minlength=c_b).max())
        p_tot_b = n_b + n_max_b
        gather_np = np.concatenate(
            [order_np, np.full(p_tot_b - n_b, n_b, order_np.dtype)]
        )
        gidx = jnp.asarray(gather_np)
        vmask = jnp.asarray((gather_np < n_b).astype(np.float32))[:, None]
        chunk_cols = max(1, wsolver._GATHER_COL_CHUNK // 4)

        def regroup(xx):
            out = jnp.zeros((p_tot_b, xx.shape[1]), xx.dtype)
            for c0 in range(0, xx.shape[1], chunk_cols):
                sl = jax.lax.slice_in_dim(
                    xx, c0, min(c0 + chunk_cols, xx.shape[1]), axis=1
                )
                g = jnp.take(sl, gidx, axis=0, mode="fill", fill_value=0)
                out = wsolver._scatter_cols(out, g * vmask, jnp.int32(c0))
            return out

        regroup_x = timed_chain_auto(regroup, xw, chain_len=64)
        regroup_y = timed_chain_auto(regroup, yw, chain_len=64)
        regroup_dev = regroup_x + regroup_y

        # The fused solve program, AOT-compiled then executed in a serial
        # chain with a perturbed lam operand (same program, fresh input ->
        # no dedup).  args layout: (x, labels_sorted, valid, seg_ids,
        # starts, counts, counts_f, joint_label_mean, nvalid, lam, w).
        args, statics = captured["args"], captured["statics"]
        orig = wsolver._fused_bwls_fit
        compiled = orig.lower(*args, *statics).compile()
        # One cost_analysis reader for the whole repo (core.profiler):
        # same unwrap, same failure posture as every profiled program.
        from keystone_tpu.core import profiler as kprof

        flops, bytes_accessed = kprof.cost_pair(compiled)
        solve_dev = timed_chain_auto(
            lambda xs: orig(
                xs, *args[1:9], args[9] * jnp.float32(1.000001), args[10],
                *statics,
            )[0],
            args[0],
            chain_len=16,
        )
        lat = roundtrip_latency()
        explained = host_prep + regroup_dev + solve_dev + 2 * lat
        rep = bwls.last_fit_report
        return {
            "n": n_b, "d": d_b, "classes": c_b,
            "wall_seconds": round(wall, 3),
            # DISJOINT phases of a fit, each measured independently at the
            # true shape: host prep (argmax pull + argsort), the two sort
            # gathers (design matrix + labels), the fused solve program,
            # and two dispatch round-trips (argmax pull; model pull).
            "wall_breakdown": {
                "host_prep_seconds": round(host_prep, 4),
                "regroup_device_seconds": round(regroup_dev, 4),
                "solve_device_seconds": round(solve_dev, 4),
                "dispatch_roundtrips_seconds": round(2 * lat, 4),
            },
            "wall_explained_seconds": round(explained, 3),
            # >= 0: enqueue/tracing overhead not separately measured;
            # < 0: the independently-measured components overlapped inside
            # wall (async dispatch lets device work run under host prep) —
            # the breakdown is a cost model, NOT a partition of wall.
            "wall_unattributed_seconds": round(wall - explained, 3),
            "roundtrip_latency_seconds": round(lat, 4),
            "solve_flops": flops,
            "solve_bytes_accessed": bytes_accessed,
            "solver_report": rep.record() if rep is not None else None,
        }

    @stage("gmm_em_fit")
    def _():
        # The FULL GMM fit — init + EM to convergence, one compiled loop
        # (reference EncEval.cxx:122-151 runs the whole fit driver-side) —
        # at the ImageNet sampling shape (the 1e6-sample EM cap,
        # ImageNetSiftLcsFV.scala:85-86).  Planted mixture so the
        # convergence path is realistic rather than one-step.
        from keystone_tpu.solvers.gmm import GaussianMixtureModelEstimator

        n_g, d_g, k_g = 1_000_000, 64, 16
        kc, kx, ka = jax.random.split(jax.random.PRNGKey(7), 3)

        @jax.jit
        def make_data():
            centers = jax.random.normal(kc, (k_g, d_g)) * 2.0
            assign = jax.random.randint(ka, (n_g,), 0, k_g)
            return centers[assign] + jax.random.normal(kx, (n_g, d_g)) * 0.5

        x = make_data()  # device-generated: nothing crosses the tunnel
        x.block_until_ready()
        est = GaussianMixtureModelEstimator(k_g)
        est.fit(x)  # warm: compiles init gather + the while_loop fit
        x_t = x * jnp.float32(1.0 + 1e-6)  # dedup-defeating perturbation
        float(jnp.sum(x_t[0]))
        t0 = time.perf_counter()
        est.fit(x_t)
        iters = int(est.last_iterations)  # the one host pull = the sync
        dt = time.perf_counter() - t0
        return {
            "n": n_g, "d": d_g, "k": k_g,
            "iterations": iters,
            "fit_wall_seconds": round(dt, 3),
            "seconds_per_iter": round(dt / max(1, iters), 4),
        }

    return out


def bench_solve_at_scale(rng, shapes=None, bwls_shapes=None, bs=4096):
    """The BCD solve at the largest single-chip-HBM shape that fits
    (VERDICT r4 #2, r5 #1): the flagship one-program claim exercised where
    memory behavior actually matters.  Round-7 discipline (ISSUE 7
    carry-over): every probed shape runs through the ESTIMATOR'S OWN
    degradation ladder — fused -> stepwise -> host-staged, mesh tiers when
    one is ambient — instead of dispatching the fused program directly.
    BENCH_r05 showed all five shapes raw-OOM precisely because the old
    probe predated the ladder: a shape whose FUSED program cannot place
    can still solve on a degraded tier, and that is the number a capacity
    plan needs.  Every attempt — success AND failure — records the
    ladder's full ``last_fit_report`` (per-tier memory_analysis
    breakdowns, denials, OOM step-downs, the tier that ran).  The
    reference's north-star solve is 1.25M x 256k spread across a cluster
    (ImageNetSiftLcsFV.scala:186-188); per chip that is ~40 GB of design
    matrix per 16 GB-HBM v5e at f32, so single-chip proof means the
    largest shape the ladder lands, with the mesh path scaling
    rows/classes out.
    """
    from keystone_tpu.core import autoshard
    from keystone_tpu.core import memory as kmem

    # Synthetic fixed-seed probes: never read or train the real plan log,
    # even on direct invocation.
    autoshard.hermetic_plan_log()
    k_cls = 128
    if shapes is None:
        shapes = [  # (n, d) descending footprint; ~GB = n*d*4/2**30
            (262144, 16384),  # 16.0 GB design matrix — expected deny
            (196608, 16384),  # 12.0 GB
            (163840, 16384),  # 10.0 GB
            (131072, 16384),  # 8.0 GB
            (131072, 8192),   # 4.0 GB
        ]
    budget = kmem.hbm_budget()
    attempts = []
    result = None
    for n, d in shapes:
        rec = {
            "n": n, "d": d,
            "design_matrix_gb": round(n * d * 4 / 2**30, 2),
        }
        est = BlockLeastSquaresEstimator(bs, num_iter=1, lam=10.0)
        try:
            key = jax.random.PRNGKey(n % 97)

            @jax.jit
            def make(key=key, n=n, d=d):
                kx, ky = jax.random.split(key)
                x = jax.random.normal(kx, (n, d), jnp.float32)
                cls = jax.random.randint(ky, (n,), 0, k_cls)
                y = 2.0 * jax.nn.one_hot(cls, k_cls, dtype=jnp.float32) - 1.0
                return x, y

            x, y = make()
            x.block_until_ready()
            # The wall includes the fit's preflight compiles (the ladder's
            # own admission work IS part of solving at this scale).
            t0 = time.perf_counter()
            model = est.fit(x, y)
            float(  # scalar pull = the one sync this transport honors
                sum(jnp.sum(b[0]) for b in model.xs)
                + jnp.sum(jnp.asarray(model.b))
            )
            dt = time.perf_counter() - t0
            rep = est.last_fit_report
            result = {
                **rec, "block_size": bs, "classes": k_cls,
                "blocks": d // bs,
                "wall_seconds": round(dt, 3),
                "examples_per_sec": round(n / dt, 1),
                "chosen_tier": rep.chosen if rep is not None else None,
                # The ladder's audit trail: per-tier memory_analysis for
                # every CONSIDERED tier, denials, OOM step-downs.
                "solver": rep.record() if rep is not None else None,
                "hbm_budget_gb": (
                    round(budget / 2**30, 2) if budget is not None else None
                ),
            }
            model = None  # noqa: F841 — free before the next allocation
            break
        except Exception as e:  # noqa: BLE001 — OOM boundary is data
            rep = est.last_fit_report
            attempts.append({
                **rec,
                "error": f"{type(e).__name__}: {e}"[:160],
                "solver": rep.record() if rep is not None else None,
            })
            x = y = None  # free HBM before the next probe
            kmem.clear_plan_cache()
    if result is None:
        # Even with every BCD shape failed, the BWLS probe still runs (its
        # estimator ladder can succeed via stepwise/host-staged on exactly
        # this kind of memory-starved chip) and the probe's cached
        # executables are still released first.
        kmem.clear_plan_cache()
        return {
            "error": "no probed shape fit",
            "attempts": attempts,
            "bwls": _guarded(
                lambda r: _bench_bwls_at_scale(r, shapes=bwls_shapes), rng
            ),
        }
    result["oom_attempts"] = attempts
    # Release this probe's device buffers and drop every probed shape's
    # executable — the plan cache holds them, and loaded executables can
    # reserve device program memory — BEFORE the nested BWLS bench
    # allocates its own multi-GB matrix; leaving buffers live OOMed the
    # nested probe on 16 GB-HBM chips (ADVICE r5).
    x = y = None  # noqa: F841
    kmem.clear_plan_cache()
    result["bwls"] = _guarded(
        lambda r: _bench_bwls_at_scale(r, shapes=bwls_shapes), rng
    )
    return result


def _bench_bwls_at_scale(rng, shapes=None, bs=4096):
    """The whole class-weighted fit at HBM-stressing scale (VERDICT r4 #2,
    r5 #1), probed through the estimator's OWN admission-control ladder:
    each shape's fit preflights fused/stepwise/host-staged tiers, runs the
    best admitted tier (donating the caller's x once the sorted copy
    exists), and ``last_fit_report`` lands in the record — per-tier
    memory_analysis breakdowns for every probed shape, successes AND
    failures, plus which tier actually solved it."""
    from keystone_tpu.solvers.weighted import BlockWeightedLeastSquaresEstimator

    c = 256
    if shapes is None:
        shapes = [  # (n, d) descending footprint
            (131072, 16384),  # 8.0 GB design matrix
            (131072, 8192),   # 4.0 GB
        ]
    attempts = []
    result = None
    for n, d in shapes:
        rec = {
            "n": n, "d": d, "classes": c, "block_size": bs,
            "design_matrix_gb": round(n * d * 4 / 2**30, 2),
        }
        est = BlockWeightedLeastSquaresEstimator(
            bs, num_iter=1, lam=0.01, mixture_weight=0.25
        )
        try:
            key = jax.random.PRNGKey(11 + d % 13)

            @jax.jit
            def make(key=key, n=n, d=d):
                kx, ky = jax.random.split(key)
                x = jax.random.normal(kx, (n, d), jnp.float32)
                cls = jax.random.randint(ky, (n,), 0, c)
                y = 2.0 * jax.nn.one_hot(cls, c, dtype=jnp.float32) - 1.0
                return x, y

            x, y = make()
            x.block_until_ready()
            # donate=True: the fit frees this x/y once their sorted copies
            # exist — the caller-side half of the 2x class-sort peak.
            # The wall includes the fit's one-time preflight compiles.
            t0 = time.perf_counter()
            model = est.fit(x, y, donate=True)
            float(sum(jnp.sum(b) for b in model.xs))  # scalar pull = sync
            wall = time.perf_counter() - t0
            rep = est.last_fit_report
            result = {
                **rec,
                "fit_wall_seconds": round(wall, 3),
                "examples_per_sec": round(n / wall, 1),
                "solver": rep.record() if rep is not None else None,
            }
            model = None  # noqa: F841 — free before returning to the caller
            break
        except Exception as e:  # noqa: BLE001 — the boundary is data
            rep = est.last_fit_report
            attempts.append({
                **rec,
                "error": f"{type(e).__name__}: {e}"[:160],
                "solver": rep.record() if rep is not None else None,
            })
            x = y = None  # free HBM before the next probe
    if result is None:
        return {"error": "no probed shape fit", "attempts": attempts}
    result["attempts"] = attempts
    return result


def bench_placement(rng):
    """Placement-search section (ISSUE 9): the cost-model-ranked plan
    (core.autoshard) vs the hand-enumerated ladder on the SAME BCD solve,
    across >= 3 design-matrix shapes.

    Per shape, both fits run on identical inputs after a shared warmup fit
    (so neither pays first-compile costs the other skips): ``hand`` walks
    the hand ladder (``plan=False``), ``searched`` runs the ranked
    candidate list (``plan=True``).  The acceptance bars: the searched
    fit's model is BIT-IDENTICAL to the hand fit's (an untrained cost
    model never deviates from the proven default), its wall is <= the hand
    wall within noise, and the search overhead (``search_seconds`` — the
    enumerate + prune + score pass, no compiles) stays under 5% of the fit
    wall.  ``prediction_error`` is the chosen plan's predicted/measured
    ratio — the figure the plan-outcome log's learned calibration drives
    toward 1.0 across runs.
    """
    from keystone_tpu.core import autoshard
    from keystone_tpu.core import memory as kmem

    # Even when invoked directly (the verify one-liner), this section's
    # fixed-rng fits must not read or train the operator's real plan log.
    autoshard.hermetic_plan_log()
    k_cls = 64
    bs = 1024
    shapes = [(16384, 2048), (8192, 4096), (32768, 1024)]
    rows = []
    for n, d in shapes:
        x = jnp.asarray(rng.standard_normal((n, d), dtype=np.float32))
        y = jnp.asarray(
            2.0 * np.eye(k_cls, dtype=np.float32)[
                rng.integers(0, k_cls, n)
            ] - 1.0
        )

        def one_fit(plan, n=n):
            est = BlockLeastSquaresEstimator(bs, num_iter=1, lam=10.0)
            t0 = time.perf_counter()
            model = est.fit(x, y, plan=plan)
            float(  # scalar pull = the one sync this transport honors
                sum(jnp.sum(b) for b in model.xs)
                + jnp.sum(jnp.asarray(model.b))
            )
            return time.perf_counter() - t0, model, est.last_fit_report

        one_fit(False)  # shared warmup: compiles cached for both timed fits
        hand_wall, hand_model, hand_rep = one_fit(False)
        srch_wall, srch_model, srch_rep = one_fit(True)
        bit_identical = bool(
            np.array_equal(np.asarray(hand_model.b), np.asarray(srch_model.b))
            and all(
                np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(hand_model.xs, srch_model.xs)
            )
        )
        placement = srch_rep.placement if srch_rep is not None else None
        rows.append({
            "n": n, "d": d, "block_size": bs, "classes": k_cls,
            "hand_wall_seconds": round(hand_wall, 4),
            "searched_wall_seconds": round(srch_wall, 4),
            "searched_vs_hand": round(srch_wall / hand_wall, 4),
            "hand_chosen": hand_rep.chosen if hand_rep is not None else None,
            "searched_chosen": (
                srch_rep.chosen if srch_rep is not None else None
            ),
            "predictions_bit_identical": bit_identical,
            "search_seconds": (
                placement["search_seconds"] if placement else None
            ),
            "search_overhead_frac": (
                round(placement["search_seconds"] / srch_wall, 5)
                if placement else None
            ),
            "prediction_error": (
                placement["prediction_error"] if placement else None
            ),
            "candidates": len(placement["candidates"]) if placement else 0,
            "pruned": (
                sum(1 for c in placement["candidates"] if c["pruned"])
                if placement else 0
            ),
            "ranking": placement["ranking"] if placement else None,
        })
        hand_model = srch_model = x = y = None  # noqa: F841 — free HBM
        kmem.clear_plan_cache()
    return {
        "shapes": rows,
        "all_bit_identical": all(r["predictions_bit_identical"] for r in rows),
        "max_search_overhead_frac": max(
            (r["search_overhead_frac"] or 0.0) for r in rows
        ),
        # ISSUE 10: executed sharding specs + the cross-program
        # calibration model.
        "spec_execution": _bench_spec_execution(rng),
        "cross_program": _bench_cross_program(rng),
    }


def _bench_spec_execution(rng):
    """Searched-SPEC-vs-default fit wall (ISSUE 10) on >= 2 shapes: under
    a mesh over all live devices, fit once with the default layout
    (``plan=False`` — the hand mesh ladder) and once with a forced replay
    of a SPEC-assignment candidate (same mesh shape, non-default
    per-operand layout, e.g. model-axis-sharded label columns), asserting
    the models BIT-IDENTICAL — a spec layout changes placement, never
    results.  With one device the spec dimension is degenerate; recorded
    honestly instead of faked."""
    from keystone_tpu.core import memory as kmem
    from keystone_tpu.parallel.mesh import make_mesh

    devs = jax.devices()
    if len(devs) < 2:
        return {
            "note": (
                f"single device ({len(devs)}): no non-trivial spec "
                "layouts to execute"
            ),
            "shapes": [],
        }
    model_ax = 2 if len(devs) % 2 == 0 else 1
    mesh = make_mesh(data=len(devs) // model_ax, model=model_ax)
    k_cls = 64
    bs = 1024
    rows = []
    for n, d in [(8192, 2048), (16384, 1024)]:
        x = jnp.asarray(rng.standard_normal((n, d), dtype=np.float32))
        y = jnp.asarray(
            2.0 * np.eye(k_cls, dtype=np.float32)[
                rng.integers(0, k_cls, n)
            ] - 1.0
        )

        def one_fit(plan):
            est = BlockLeastSquaresEstimator(
                bs, num_iter=1, lam=10.0, mesh=mesh
            )
            t0 = time.perf_counter()
            model = est.fit(x, y, plan=plan)
            float(
                sum(jnp.sum(b) for b in model.xs)
                + jnp.sum(jnp.asarray(model.b))
            )
            return time.perf_counter() - t0, model, est.last_fit_report

        # Discover a same-mesh-shape spec candidate from one search pass.
        _w, _m, probe_rep = one_fit(True)
        head_mesh = None
        spec_name = None
        for c in probe_rep.placement["candidates"]:
            if c["name"] == probe_rep.placement["ranking"][0]:
                head_mesh = c["mesh"]
        for c in probe_rep.placement["candidates"]:
            if c.get("specs") and c["mesh"] == head_mesh and not c["pruned"]:
                spec_name = c["name"]
                break
        if spec_name is None:
            rows.append({
                "n": n, "d": d,
                "note": "no executable spec candidate on the head mesh",
            })
            continue
        # Warm BOTH programs before timing: the spec layout is its own jit
        # specialization, so without its own warmup the spec fit would pay
        # a full XLA compile inside the timed region while the default
        # (already compiled by the probe) did not — the same
        # neither-pays-first-compile bar the enclosing section sets.
        one_fit(False)
        one_fit([spec_name])
        def_wall, def_model, _rep = one_fit(False)
        spec_wall, spec_model, spec_rep = one_fit([spec_name])
        rows.append({
            "n": n, "d": d, "mesh": dict(mesh.shape), "spec": spec_name,
            "default_wall_seconds": round(def_wall, 4),
            "spec_wall_seconds": round(spec_wall, 4),
            "spec_vs_default": round(spec_wall / def_wall, 4),
            "chosen": spec_rep.chosen,
            "bit_identical": bool(
                np.array_equal(
                    np.asarray(def_model.b), np.asarray(spec_model.b)
                )
                and all(
                    np.array_equal(np.asarray(a), np.asarray(b))
                    for a, b in zip(def_model.xs, spec_model.xs)
                )
            ),
        })
        x = y = def_model = spec_model = None  # noqa: F841 — free HBM
        kmem.clear_plan_cache()
    return {"mesh": dict(mesh.shape), "shapes": rows}


def _bench_cross_program(rng):
    """Cross-program calibration error (ISSUE 10): train the featurized
    ratio regression (optimize.CalibrationModel) on the plan-log outcomes
    of SHAPE A's fits only, then predict the measured/prior ratio of
    SHAPE B's chosen plan — a shape the model never saw.  Reported as
    ``predicted_over_actual`` (1.0 = perfect transfer) next to the
    untrained prior's own error, so the log shows what the learned model
    buys over the raw roofline."""
    from keystone_tpu.core import autoshard
    from keystone_tpu.core import memory as kmem
    from keystone_tpu.core import optimize as kopt

    k_cls = 32
    bs = 1024

    def fit_once(n, d):
        x = jnp.asarray(rng.standard_normal((n, d), dtype=np.float32))
        y = jnp.asarray(
            2.0 * np.eye(k_cls, dtype=np.float32)[
                rng.integers(0, k_cls, n)
            ] - 1.0
        )
        est = BlockLeastSquaresEstimator(bs, num_iter=1, lam=10.0)
        est.fit(x, y, plan=True)
        return est.last_fit_report.placement

    shape_a, shape_b = (8192, 2048), (16384, 1024)
    # Three measured outcomes of shape A (each appends to the hermetic
    # log; the in-process read cache keeps the rankings untrained).
    fp_a = None
    for _ in range(3):
        fp_a = fit_once(*shape_a)["fingerprint"]
    placement_b = fit_once(*shape_b)
    kmem.clear_plan_cache()
    autoshard.clear_outcome_cache()  # re-read the log written above
    rows_a = [r for r in autoshard.model_rows() if r[0] == fp_a]
    model = kopt.CalibrationModel.fit_rows(rows_a)
    chosen = next(
        (
            c for c in placement_b["candidates"]
            if c["name"] == placement_b["chosen"]
        ),
        None,
    )
    if model is None or chosen is None or not chosen.get("measured_seconds"):
        return {
            "note": "insufficient outcomes to train/evaluate",
            "train_rows": len(rows_a),
        }
    actual = chosen["measured_seconds"] / chosen["raw_seconds"]
    predicted = model.predict_factor(chosen["features"])
    return {
        "trained_on": {"n": shape_a[0], "d": shape_a[1], "rows": len(rows_a)},
        "predicted_on": {"n": shape_b[0], "d": shape_b[1]},
        "candidate": chosen["name"],
        "actual_ratio": round(actual, 4),
        "model_predicted_ratio": round(predicted, 4),
        "predicted_over_actual": round(predicted / actual, 4),
        # the raw prior's factor is 1.0 by definition — its error IS the
        # actual ratio; the model's win is |log| closer to zero.
        "prior_over_actual": round(1.0 / actual, 4),
        "model": model.record(),
    }


def bench_e2e_ingest(rng):
    """Streaming-ingest e2e (ROADMAP "End-to-end ingest overlap"): tar ->
    decode -> featurize(-> solve) through core.ingest — decoder threads fill
    the host ring while the device featurizes the previous batch behind a
    double-buffered H2D.  Three rates per workload, each over the SAME tar:

    * ``decode_images_per_sec``  — stream with the H2D/featurize stages off
      (the producer-side ceiling);
    * ``featurize_images_per_sec`` — H2D + featurize over pre-decoded host
      chunks (the consumer-side ceiling; inputs perturbed so the transport's
      dispatch dedup cannot serve the e2e pass's identical data);
    * ``e2e_images_per_sec`` — the full overlapped pipeline.

    ``overlap_efficiency = e2e / min(decode, featurize)`` — 1.0 means the
    slower stage fully hides the faster one; the target is >= 0.9.  Ring
    depth/stall counters come from the stream's own stats.  Images are
    48 px (the loaders' 36 px MIN_DIM floor rules out true-32px CIFAR
    JPEGs) and CIFAR labels ride in the member names."""
    from keystone_tpu.core.ingest import StreamConfig, stream_batches

    def no_snap():
        # The decode/e2e passes must MEASURE DECODE: an ambient
        # KEYSTONE_SNAPSHOT_DIR would silently serve them from the cache
        # and report shard-read rates as decode rates.  Empty string
        # survives from_env's None-filter and disables the cache.
        return StreamConfig.from_env(snapshot_dir="")

    def rates(tar_path, n_images, batch, feat_fn):
        # decode-only: producer-side ceiling (no H2D, no featurize)
        t0 = time.perf_counter()
        with stream_batches(
            tar_path, batch, transfer=False, config=no_snap()
        ) as st:
            chunks = [b.host for b in st]
        decode_secs = time.perf_counter() - t0
        n_decoded = sum(c.shape[0] for c in chunks)
        assert n_decoded == n_images, (n_decoded, n_images)
        # featurize-only over the pre-decoded chunks; RELATIVE perturbation
        # so the e2e pass (same data) cannot be served from dispatch dedup
        chunks = [c * np.float32(1.0 + 1e-6) for c in chunks]
        np.asarray(feat_fn(jax.device_put(chunks[0])))  # compile warm-up
        t0 = time.perf_counter()
        for c in chunks:
            np.asarray(feat_fn(jax.device_put(c)))
        feat_secs = time.perf_counter() - t0
        del chunks
        # e2e: the overlapped pipeline (decode threads + ring + double-
        # buffered H2D + featurize, synced per consumed batch)
        feats = []
        t0 = time.perf_counter()
        with stream_batches(tar_path, batch, config=no_snap()) as st:
            for b in st:
                feats.append((b.indices, np.asarray(feat_fn(b.device))))
        e2e_secs = time.perf_counter() - t0
        decode_rate = n_images / decode_secs
        feat_rate = n_images / feat_secs
        e2e_rate = n_images / e2e_secs
        # snapshot-warm e2e (ISSUE 7 target: e2e within 10% of the pure-
        # featurize rate): a cold pass materializes the decoded chunks,
        # then the e2e pipeline streams the SHARDS — the decode wall is
        # gone and only shard IO bounds the producer.  The featurize input
        # is perturbed relative to the plain-e2e pass above so the
        # transport's dispatch dedup cannot serve identical work.
        import shutil as _sh
        import tempfile as _tf

        snap_root = _tf.mkdtemp(prefix="bench_e2e_snap_")
        try:
            with stream_batches(
                tar_path, batch, transfer=False,
                config=StreamConfig.from_env(
                    snapshot_dir=snap_root, snapshot_mode="decoded"
                ),
            ) as st_cold:
                for _ in st_cold:
                    pass
            t0 = time.perf_counter()
            with stream_batches(
                tar_path, batch,
                config=StreamConfig.from_env(
                    snapshot_dir=snap_root, snapshot_mode="decoded"
                ),
            ) as st_warm:
                for b in st_warm:
                    np.asarray(feat_fn(b.device * jnp.float32(1.0 + 1e-6)))
            snap_e2e_rate = n_images / (time.perf_counter() - t0)
            warm_chunks_read = st_warm.stats.snapshot_chunks_read
        finally:
            _sh.rmtree(snap_root, ignore_errors=True)
        # What a NON-overlapped pipeline does: decode everything, then
        # featurize (total = t_decode + t_featurize).  e2e/serial_bound is
        # the speedup the overlap actually bought; on a host whose decode
        # threads and featurize compute share the SAME cores (CPU backend)
        # the serial bound — not min(decode, featurize) — is the physical
        # ceiling, so both ratios are recorded.
        serial_bound = n_images / (decode_secs + feat_secs)
        return {
            "images": n_images,
            "batch": batch,
            "decode_images_per_sec": round(decode_rate, 2),
            "featurize_images_per_sec": round(feat_rate, 2),
            "e2e_images_per_sec": round(e2e_rate, 2),
            "overlap_efficiency": round(
                e2e_rate / min(decode_rate, feat_rate), 3
            ),
            "serial_bound_images_per_sec": round(serial_bound, 2),
            "speedup_vs_serial": round(e2e_rate / serial_bound, 3),
            # The decode wall removed: e2e off the materialized snapshot,
            # and its fraction of the pure-featurize ceiling (the ISSUE 7
            # target is >= 0.9 — shard IO is the remaining bound when it
            # falls short).
            "snapshot_e2e_images_per_sec": round(snap_e2e_rate, 2),
            "snapshot_e2e_vs_featurize": round(snap_e2e_rate / feat_rate, 3),
            "snapshot_chunks_read": warm_chunks_read,
            "ring": st.stats.record(),
        }, feats

    out = {"overlap_target": 0.9}

    # -- CIFAR conv featurize (north star #1's pipeline) off a JPEG tar
    from keystone_tpu.workloads.cifar_random_patch import cifar_tar_label

    n_cifar, size, batch = 1024, 48, 128
    tar_path = _make_jpeg_tar(rng, n_cifar, size, labeled=True)
    try:
        conf = RandomCifarConfig(
            num_filters=100, patch_size=6, patch_steps=1, pool_size=14,
            pool_stride=13, whitener_size=20000, featurize_chunk=batch,
        )
        seed_imgs = rng.uniform(0, 255, (256, size, size, 3)).astype(np.float32)
        filters, whitener = learn_filters(conf, seed_imgs)
        feat_fn = jax.jit(build_conv_pipeline(conf, filters, whitener).__call__)
        cifar_rec, feats = rates(tar_path, n_cifar, batch, feat_fn)
        # (-> solve): the streamed features feed the block solve — labels
        # decoded from the member names, the reference pipeline's tail.
        order = np.argsort(np.concatenate([ix for ix, _ in feats]))
        x = jnp.asarray(np.concatenate([f for _, f in feats], axis=0)[order])
        labels = one_hot_pm1(np.random.default_rng(2), n_cifar, 10)
        est = BlockLeastSquaresEstimator(4096, num_iter=1, lam=10.0)
        t0 = time.perf_counter()
        model = est.fit(x, labels)
        float(sum(jnp.sum(b[0]) for b in model.xs))  # scalar pull = sync
        solve_secs = time.perf_counter() - t0
        cifar_rec["solve_seconds"] = round(solve_secs, 3)
        cifar_rec["e2e_solve_images_per_sec"] = round(
            n_cifar / (n_cifar / cifar_rec["e2e_images_per_sec"] + solve_secs),
            2,
        )
        assert cifar_tar_label("3/img_00000.jpg") == 3  # name-borne labels
        out["cifar"] = cifar_rec
    finally:
        os.unlink(tar_path)

    # -- ImageNet-FV branch (north star #2's featurize) off a JPEG tar
    from keystone_tpu.workloads.fv_common import grayscale

    n_fv, size_fv, batch_fv = 96, 256, 16
    tar_path = _make_jpeg_tar(rng, n_fv, size_fv, labeled=True)
    try:
        desc_dim, vocab = 64, 16
        sift = SIFTExtractor(scale_step=1, compute_dtype=jnp.bfloat16)
        pca = BatchPCATransformer(
            jnp.asarray(rng.normal(size=(128, desc_dim)) / 12.0, jnp.float32)
        )
        gmm = GaussianMixtureModel(
            jnp.asarray(rng.normal(size=(desc_dim, vocab)), jnp.float32),
            jnp.asarray(rng.uniform(0.5, 1.5, (desc_dim, vocab)), jnp.float32),
            jnp.asarray(np.full(vocab, 1.0 / vocab), jnp.float32),
        )
        fv = FisherVector(gmm)
        fv_fn = jax.jit(lambda imgs: fv(pca(sift(grayscale(imgs)))))
        out["imagenet_fv"], _ = rates(tar_path, n_fv, batch_fv, fv_fn)
    finally:
        os.unlink(tar_path)

    return out


def bench_optimizer(rng):
    """Pipeline-optimizer section (ISSUE 6): the cost-based auto-Cacher on
    the CIFAR conv >> StandardScaler fit chain, and the closed-loop ingest
    autotuner on a stall-injected stream.

    * ``auto_cache``: the fit pattern — ``chain.fit(x)`` then one fitted
      application to the SAME x (the workload usage) — runs the conv
      featurizer twice uncached and once with the optimizer's memoizing
      Cacher.  Both walls are measured on the same warmed program; the
      features must be bit-identical (the memo replays the fit's arrays).
    * ``autotune``: decode is slowed artificially so the stream starts
      decode-bound at a deliberately-starved static config; the tuned run
      starts from the SAME config with the controller on.  Overlap
      efficiency = e2e rate / the decode-ceiling rate measured at the
      static config — the tuned run must not be below the static one.
    """
    from keystone_tpu.core import optimize
    from keystone_tpu.core.ingest import StreamConfig, stream_batches
    from keystone_tpu.core.pipeline import FunctionTransformer
    from keystone_tpu.loaders import image_loaders
    from keystone_tpu.ops.stats import StandardScaler
    from keystone_tpu.workloads.cifar_random_patch import featurize_chunked

    out = {}

    # -- auto-Cacher: cached vs uncached fit wall over the conv chain
    n, chunk = 2048, 512
    conf = RandomCifarConfig(
        num_filters=100, patch_size=6, patch_steps=1, pool_size=14,
        pool_stride=13, whitener_size=20000, featurize_chunk=chunk,
    )
    imgs = rng.uniform(0, 255, (n, 32, 32, 3)).astype(np.float32)
    filters, whitener = learn_filters(conf, imgs[:512])
    feat_fn = jax.jit(build_conv_pipeline(conf, filters, whitener).__call__)
    # Warm the chunk-shaped compile so both timed fits are steady-state.
    jax.block_until_ready(feat_fn(jnp.zeros((chunk, 32, 32, 3), jnp.float32)))

    def make_chain():
        return FunctionTransformer(
            lambda im: featurize_chunked(feat_fn, np.asarray(im), chunk),
            name="conv_featurize",
        ).then_estimator(StandardScaler())

    t0 = time.perf_counter()
    fitted_u = make_chain().fit(imgs)
    feats_u = jax.block_until_ready(fitted_u(imgs))
    wall_uncached = time.perf_counter() - t0

    opt_chain, plan = optimize.auto_cache_chain(
        make_chain(), imgs[:chunk], dataset_rows=n
    )
    t0 = time.perf_counter()
    fitted_c = opt_chain.fit(imgs)
    feats_c = jax.block_until_ready(fitted_c(imgs))
    wall_cached = time.perf_counter() - t0
    bit_identical = bool(
        np.array_equal(np.asarray(feats_u), np.asarray(feats_c))
    )
    optimize.release_caches(fitted_c)
    out["auto_cache"] = {
        "images": n,
        "uncached_fit_wall_seconds": round(wall_uncached, 3),
        "cached_fit_wall_seconds": round(wall_cached, 3),
        "speedup": round(wall_uncached / wall_cached, 3),
        "predictions_bit_identical": bit_identical,
        "plan": plan.record(),
    }
    feats_u = feats_c = fitted_u = fitted_c = None  # noqa: F841 — free HBM

    # -- closed-loop autotuner on a stall-injected stream
    n_img, size, batch = 192, 48, 16
    tar_path = _make_jpeg_tar(rng, n_img, size)

    small_feat = jax.jit(lambda x: jnp.mean(x, axis=(1, 2, 3)))
    real_decode = image_loaders.decode_image

    def stalled_decode(data):
        time.sleep(0.005)  # the injected stall: decode-bound by fiat
        return real_decode(data)

    def run_stream(cfg, tuner=None):
        t0 = time.perf_counter()
        feats = []
        with stream_batches(tar_path, batch, config=cfg, tuner=tuner) as st:
            for b in st:
                feats.append((b.indices, np.asarray(small_feat(b.dev()))))
        secs = time.perf_counter() - t0
        assert st.join(10.0)
        return n_img / secs, feats, st

    starved = dict(
        decode_threads=1, decode_ahead=0, ring_capacity=2,
        max_decode_threads=8,
    )
    image_loaders.decode_image = stalled_decode
    try:
        # The decode ceiling AT the static config: no featurize, no H2D.
        t0 = time.perf_counter()
        with stream_batches(
            tar_path, batch, transfer=False, config=StreamConfig(**starved)
        ) as st:
            for _ in st:
                pass
        decode_rate = n_img / (time.perf_counter() - t0)
        static_rate, static_feats, _ = run_stream(StreamConfig(**starved))
        tuned_cfg = StreamConfig(**starved, autotune_interval=2)
        # Backend promotion is pinned OFF here, deliberately: the stall is
        # a parent-process monkeypatch that spawned decode workers would
        # bypass, so a process-backend measurement under it is fiction —
        # this section measures the knob-tuning loop; the process
        # backend's real rates live in the jpeg_decode section.
        tuned_rate, tuned_feats, st = run_stream(
            tuned_cfg,
            tuner=optimize.IngestAutotuner(
                interval=2, allow_backend_switch=False
            ),
        )
    finally:
        image_loaders.decode_image = real_decode
        os.unlink(tar_path)

    stream_identical = len(static_feats) == len(tuned_feats) and all(
        np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
        for a, b in zip(static_feats, tuned_feats)
    )
    out["autotune"] = {
        "images": n_img,
        "static_images_per_sec": round(static_rate, 2),
        "tuned_images_per_sec": round(tuned_rate, 2),
        "speedup": round(tuned_rate / static_rate, 3),
        # efficiency vs the ceiling of the STATIC config's decode stage —
        # the tuned run beats 1.0 by widening decode past that config.
        "static_overlap_efficiency": round(static_rate / decode_rate, 3),
        "tuned_overlap_efficiency": round(tuned_rate / decode_rate, 3),
        "output_bit_identical": stream_identical,
        "tuner": st.tuner.record(),
    }
    return out


def _decode_path_breakdown(
    rng, batch: int = 16, n_images: int = 48, size: int = 96
):
    """The ISSUE 13 per-path decode ledger: ONE mixed corpus tar (4:4:4 /
    4:2:2 / 4:2:0, qualities 85/90/95, restart markers — the subset the
    device path claims) measured through three ingest paths:

    * ``host_pool`` — threaded host decode, device featurize;
    * ``device`` — entropy-only host pass, batched dequant+IDCT+upsample+
      colorspace FUSED into the featurize (ops.jpeg_device);
    * ``device_snapshot_warm`` — warm epoch off the device-format
      snapshot tier (pure DMA: zero host decode/transform).

    Each path records e2e, decode-only and featurize-only images/sec plus
    ``overlap_efficiency`` = e2e / min(decode, featurize) (the PR 4
    definition), and the device path records its golden parity vs the
    host decoder.  Every path runs one untimed warmup pass first so
    compile time never pollutes a rate."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from keystone_tpu.core.ingest import StreamConfig, stream_batches

    n = n_images
    tar_path = _make_jpeg_tar(
        rng, n, size, subsamplings=(0, 1, 2), qualities=(85, 90, 95),
        restart_every=2,
    )
    feat = jax.jit(
        lambda x: jnp.stack(
            [jnp.mean(x, axis=(1, 2, 3)), jnp.max(x, axis=(1, 2, 3))],
            axis=1,
        )
    )
    snap_root = tempfile.mkdtemp(prefix="bench_devsnap_")

    def one_pass(transfer, featurize, collect=False, **cfg_kw):
        cfg_kw.setdefault("snapshot_dir", "")  # ambient cache pinned off
        cfg = StreamConfig.from_env(**cfg_kw)
        chunks = []
        t0 = time.perf_counter()
        count = 0
        with stream_batches(
            tar_path, batch, transfer=transfer, config=cfg
        ) as st:
            for b in st:
                if featurize:
                    np.asarray(b.apply(feat))
                if collect:
                    chunks.append(b)
                count += len(b)
        secs = time.perf_counter() - t0
        assert st.join(20.0), "ingest threads leaked"
        assert count == n, (count, n)
        return n / secs, st.stats, chunks

    def feat_only_rate(chunks):
        # warmup already happened in the pass that collected the chunks
        t0 = time.perf_counter()
        for b in chunks:
            np.asarray(b.apply(feat))
        return n / (time.perf_counter() - t0)

    out = {}
    try:
        # -- host thread pool -------------------------------------------------
        one_pass(True, True)  # warmup (jit compiles)
        host_e2e, _s, _ = one_pass(True, True)
        host_dec, _s, host_chunks = one_pass(False, False, collect=True)
        host_feat = feat_only_rate(host_chunks)
        out["host_pool"] = {
            "images_per_sec": round(host_e2e, 2),
            "decode_images_per_sec": round(host_dec, 2),
            "featurize_images_per_sec": round(host_feat, 2),
            "overlap_efficiency": round(
                host_e2e / max(1e-9, min(host_dec, host_feat)), 3
            ),
        }

        # -- device decode (entropy host pass + fused on-device pixels) -------
        one_pass(True, True, decode_mode="device")  # warmup
        dev_e2e, dev_stats, _ = one_pass(True, True, decode_mode="device")
        dev_dec, _s, dev_chunks = one_pass(
            False, False, collect=True, decode_mode="device"
        )
        dev_feat = feat_only_rate(dev_chunks)
        # golden parity: device vs host pixels matched BY MEMBER NAME —
        # the two paths bucket differently (device buckets fold the
        # sampling geometry in), so chunk i holds different images.
        def pixels_by_name(chunks, limit=9):
            got = {}
            for b in chunks:
                px = np.asarray(b.dev())
                for j, nm in enumerate(b.names):
                    if len(got) < limit:
                        got[nm] = px[j]
                if len(got) >= limit:
                    break
            return got

        from keystone_tpu.ops.jpeg_device import GOLDEN_MAX_ABS

        host_px = pixels_by_name(host_chunks)
        dev_px = pixels_by_name(dev_chunks, limit=n)
        common = sorted(set(host_px) & set(dev_px))
        assert common, "no overlapping members between the two paths"
        parity = max(
            float(np.max(np.abs(dev_px[nm] - host_px[nm])))
            for nm in common
        )
        out["device"] = {
            "images_per_sec": round(dev_e2e, 2),
            "decode_images_per_sec": round(dev_dec, 2),  # entropy pass
            "featurize_images_per_sec": round(dev_feat, 2),
            "overlap_efficiency": round(
                dev_e2e / max(1e-9, min(dev_dec, dev_feat)), 3
            ),
            "entropy_decoded": dev_stats.entropy_decoded,
            # the scan hot-loop backend this pass ACTUALLY ran — "native"
            # (ops.native_entropy) or "python" (the portable fallback)
            "entropy_backend": dev_stats.entropy_backend,
            "fallbacks": dev_stats.device_fallbacks,
            "coeff_bytes": dev_stats.coeff_bytes,
            "golden_max_abs_vs_host": parity,
            "within_golden_tolerance": bool(parity <= GOLDEN_MAX_ABS),
        }

        # -- entropy hot-loop backends (ISSUE 19) -----------------------------
        # Direct entropy_decode rates over the SAME corpus members, native
        # vs Python, single-threaded — the isolated cost of the scan loop
        # the backends swap (the e2e device rate above shows what the
        # swap buys the stream).  Native numbers are recorded only when
        # the library actually built; the leg always records which
        # backend the live device path resolved to.
        from keystone_tpu.loaders.image_loaders import _iter_tar_members
        from keystone_tpu.ops import jpeg_device as _jd
        from keystone_tpu.ops import native_entropy as _ne

        members = [d for _nm, d in _iter_tar_members(tar_path)]

        def entropy_rate(backend):
            _jd.entropy_decode(members[0], backend=backend)  # warm LUT cache
            t0 = time.perf_counter()
            for d in members:
                _jd.entropy_decode(d, backend=backend)
            return n / (time.perf_counter() - t0)

        py_rate = entropy_rate("python")
        entropy_leg = {
            "images": n,
            "python_images_per_sec": round(py_rate, 2),
            "backend_live": _jd.entropy_backend(),
            "e2e_device_images_per_sec": round(dev_e2e, 2),
            "e2e_overlap_efficiency": out["device"]["overlap_efficiency"],
        }
        if _ne.available():
            nat_rate = entropy_rate("native")
            entropy_leg["native_images_per_sec"] = round(nat_rate, 2)
            entropy_leg["speedup"] = round(nat_rate / py_rate, 3)
        out["entropy_native"] = entropy_leg

        # -- warm device-format snapshot (pure DMA) ---------------------------
        # cold pass (host decode + device-format tee), untimed
        one_pass(
            True, True, snapshot_dir=snap_root, snapshot_mode="device"
        )
        warm_e2e, warm_stats, _ = one_pass(
            True, True, snapshot_dir=snap_root, snapshot_mode="device"
        )
        warm_dec, _s, _ = one_pass(
            False, False, snapshot_dir=snap_root, snapshot_mode="device"
        )
        out["device_snapshot_warm"] = {
            "images_per_sec": round(warm_e2e, 2),
            "decode_images_per_sec": round(warm_dec, 2),  # shard DMA
            "featurize_images_per_sec": round(host_feat, 2),
            "overlap_efficiency": round(
                warm_e2e / max(1e-9, min(warm_dec, host_feat)), 3
            ),
            "dma_bytes": warm_stats.snapshot_dma_bytes,
            # the acceptance bar: a warm device epoch does ZERO host-side
            # decode/transform — recorded, not assumed
            "zero_host_decode": bool(
                warm_stats.entropy_decoded == 0
                and warm_stats.device_fallbacks == 0
                and warm_stats.snapshot_chunks_read > 0
            ),
        }
    finally:
        os.unlink(tar_path)
        shutil.rmtree(snap_root, ignore_errors=True)
    return out


def bench_decode(rng):
    """Host ingest: JPEG-tar decode throughput — serial, thread-pool,
    PROCESS-pool at 1/2/4/8 workers, and snapshot cold-write vs warm-read
    (reference decodes per-executor in parallel off streamed tars,
    ImageLoaderUtils.scala:60-100).  The thread pool is GIL-bound
    (BENCH_r05: 1.04x); the process pool and the snapshot cache are ISSUE
    7's two attacks on that wall, so their rates sit next to the old
    numbers where the wall's removal is visible.  Speedups are whatever
    the bench host's core budget yields — reported, not assumed, with the
    bounding resource named when scaling falls short."""
    import shutil
    import tempfile

    from keystone_tpu.core.ingest import (
        StreamConfig,
        _host_cores,
        stream_batches,
    )
    from keystone_tpu.core.optimize import advise_snapshot
    from keystone_tpu.loaders.image_loaders import (
        _iter_tar_images,
        decode_threads,
    )

    n_images = 192
    tar_path = _make_jpeg_tar(rng, n_images, 256)

    def timed(threads):
        t0 = time.perf_counter()
        count = sum(1 for _ in _iter_tar_images(tar_path, num_threads=threads))
        dt = time.perf_counter() - t0
        assert count == n_images
        return n_images / dt

    try:
        serial = timed(1)
        threads = decode_threads()
        threaded = timed(threads)
        # Native-vs-PIL at ONE thread: isolates the C++ decoder's gain from
        # thread scaling (which a 1-core bench host cannot show).  Skipped
        # when the user disabled the native decoder on entry — the serial
        # number above is already the PIL path then, and the comparison
        # would silently measure PIL vs PIL.
        import keystone_tpu.loaders.native_decode as nd

        prior = os.environ.get("KEYSTONE_NATIVE_DECODE")
        native_enabled = (prior or "").strip() != "0" and nd.available()
        pil_serial = None
        if native_enabled:
            os.environ["KEYSTONE_NATIVE_DECODE"] = "0"
            try:
                nd.reset()  # re-evaluate the env gate (takes the module lock)
                pil_serial = timed(1)
            finally:
                if prior is None:
                    del os.environ["KEYSTONE_NATIVE_DECODE"]
                else:
                    os.environ["KEYSTONE_NATIVE_DECODE"] = prior
                nd.reset()

        # -- process-pool decode at 1/2/4/8 workers (the GIL-free backend).
        # total = whole stream including the one-time worker spawn (each
        # spawned worker pays a fresh interpreter + package import);
        # steady = images/sec measured from the FIRST chunk's arrival, the
        # rate a long tar actually sustains.
        proc_total, proc_steady = {}, {}
        for w in (1, 2, 4, 8):
            # snapshot pinned OFF: an ambient KEYSTONE_SNAPSHOT_DIR would
            # turn the decode-scaling probe into a shard-read benchmark.
            cfg = StreamConfig.from_env(
                decode_threads=w, decode_ahead=8, ring_capacity=8,
                decode_backend="process", decode_procs=w,
                snapshot_dir="",
            )
            t0 = time.perf_counter()
            t_first = None
            n_done = first_n = 0
            with stream_batches(
                tar_path, 32, transfer=False, config=cfg
            ) as st:
                for b in st:
                    if t_first is None:
                        t_first = time.perf_counter()
                        first_n = len(b)
                    n_done += len(b)
            t_end = time.perf_counter()
            assert st.join(20.0), "decode worker processes leaked"
            assert n_done == n_images, (n_done, n_images)
            proc_total[str(w)] = round(n_images / (t_end - t0), 2)
            if t_first is not None and n_done > first_n and t_end > t_first:
                proc_steady[str(w)] = round(
                    (n_done - first_n) / (t_end - t_first), 2
                )

        # -- snapshot cache: cold write (live decode + shard tee) vs warm
        # read (shards only — the repeat-epoch rate) over the same tar,
        # measured for BOTH shard formats (KEYSTONE_SNAPSHOT_COMPRESS):
        # deflated shards cost cold-pass CPU but shrink the warm pass's IO.
        from keystone_tpu.core import snapshot as ksnap

        snap_variants = {}
        for compress in (True, False):
            snap_root = tempfile.mkdtemp(prefix="bench_snap_")
            prev_env = os.environ.get(ksnap.SNAPSHOT_COMPRESS_ENV)
            os.environ[ksnap.SNAPSHOT_COMPRESS_ENV] = "1" if compress else "0"
            try:
                t0 = time.perf_counter()
                with stream_batches(
                    tar_path, 32, transfer=False,
                    config=StreamConfig.from_env(
                        snapshot_dir=snap_root, snapshot_mode="decoded"
                    ),
                ) as st:
                    n_cold = sum(len(b) for b in st)
                cold_secs = time.perf_counter() - t0
                assert st.join(10.0) and n_cold == n_images
                t0 = time.perf_counter()
                with stream_batches(
                    tar_path, 32, transfer=False,
                    config=StreamConfig.from_env(
                        snapshot_dir=snap_root, snapshot_mode="decoded"
                    ),
                ) as st:
                    n_warm = sum(len(b) for b in st)
                warm_secs = time.perf_counter() - t0
                assert st.join(10.0) and n_warm == n_images
                assert st.stats.snapshot_chunks_read > 0, "warm pass re-decoded"
                [committed] = [
                    s for s in ksnap.list_snapshots(snap_root) if s["valid"]
                ]
                snap_variants["compressed" if compress else "uncompressed"] = {
                    "cold_write_images_per_sec": round(n_images / cold_secs, 2),
                    "warm_read_images_per_sec": round(n_images / warm_secs, 2),
                    "warm_speedup_vs_cold": round(cold_secs / warm_secs, 2),
                    "shard_bytes": committed["bytes"],
                    "cold_secs": cold_secs,
                    "warm_secs": warm_secs,
                }
            finally:
                if prev_env is None:
                    os.environ.pop(ksnap.SNAPSHOT_COMPRESS_ENV, None)
                else:
                    os.environ[ksnap.SNAPSHOT_COMPRESS_ENV] = prev_env
                shutil.rmtree(snap_root, ignore_errors=True)
        # BENCH_r0x row continuity: the top-level cold/warm keys stay, fed
        # by the DEFAULT (compressed) variant.
        cold_secs = snap_variants["compressed"].pop("cold_secs")
        warm_secs = snap_variants["compressed"].pop("warm_secs")
        snap_variants["uncompressed"].pop("cold_secs")
        snap_variants["uncompressed"].pop("warm_secs")
    finally:
        os.unlink(tar_path)
    out = {
        "decode_threads": threads,
        "serial_images_per_sec": round(serial, 2),
        "threaded_images_per_sec": round(threaded, 2),
        "speedup": round(threaded / serial, 2),
        "host_cores": _host_cores(),
        "process_pool_images_per_sec": proc_total,
        "process_pool_steady_images_per_sec": proc_steady,
    }
    best_proc = max((proc_steady or proc_total).values(), default=None)
    if best_proc is not None:
        out["process_best_speedup_vs_serial"] = round(best_proc / serial, 2)
        if out["process_best_speedup_vs_serial"] < 2.0:
            # The acceptance target (>=2x on >=4 workers) needs cores to
            # scale over; name the bounding resource instead of leaving a
            # bare shortfall.
            out["process_scaling_bound"] = (
                f"{_host_cores()} schedulable core(s) on this host bound "
                "process-pool scaling; the backend removes the GIL, not "
                "the core budget"
            )
    out["snapshot"] = {
        "cold_write_images_per_sec": round(n_images / cold_secs, 2),
        "warm_read_images_per_sec": round(n_images / warm_secs, 2),
        "warm_speedup_vs_cold": round(cold_secs / warm_secs, 2),
        "warm_speedup_vs_serial_decode": round(
            (n_images / warm_secs) / serial, 2
        ),
        # Write-path compression (KEYSTONE_SNAPSHOT_COMPRESS, default on):
        # per-format cold/warm rates + on-disk shard bytes, so the
        # CPU-vs-IO trade is measured, not assumed.
        "by_format": snap_variants,
        "compression_ratio": round(
            snap_variants["uncompressed"]["shard_bytes"]
            / max(snap_variants["compressed"]["shard_bytes"], 1),
            2,
        ),
        # The cost-model view of the same numbers: is materializing worth
        # it for a nominal 5-epoch fit at this tar's decoded footprint?
        "advice": advise_snapshot(
            images=n_images,
            bytes_per_image=256 * 256 * 3 * 4,
            decode_images_per_sec=threaded,
            epochs=5,
        ).record(),
    }
    if pil_serial is not None:
        out["pil_serial_images_per_sec"] = round(pil_serial, 2)
        out["native_vs_pil_speedup"] = round(serial / pil_serial, 2)
    else:
        out["native_vs_pil_speedup"] = None  # native decoder disabled/absent
    # ISSUE 13: per-path breakdown over the mixed device-decode corpus —
    # host pool vs device decode vs warm device-snapshot DMA, with
    # overlap efficiency and golden parity recorded per path.
    out["by_path"] = _decode_path_breakdown(rng)
    return out


def bench_serving(rng):
    """Low-latency serving SLOs (ISSUE 8): two fitted pipelines — the
    MnistRandomFFT chain and the RandomPatchCifar conv chain — checkpointed,
    warm-loaded through ``core.serve.load_engine`` (cold start measured:
    restore + per-bucket AOT compile + warmup), then driven by concurrent
    synthetic clients through the dynamic batcher.  Each record carries
    p50/p99 latency, sustained QPS, batcher occupancy, and the
    batched-vs-unbatched QPS ratio (same engine behind a flush-per-request
    server; target >= 2x at bit-equal answers)."""
    import shutil
    import tempfile

    from keystone_tpu.core import serve as kserve
    from keystone_tpu.core.checkpoint import save_pipeline
    from keystone_tpu.core.pipeline import Pipeline
    from keystone_tpu.ops.stats import StandardScaler
    from keystone_tpu.ops.util import (
        ClassLabelIndicatorsFromIntLabels,
        GroupConcatFeaturizer,
        MaxClassifier,
    )
    from keystone_tpu.workloads.cifar_random_patch import featurize_chunked
    from keystone_tpu.workloads.mnist_random_fft import (
        MnistRandomFFTConfig,
        build_featurizer_batches,
    )

    cfg = kserve.ServeConfig(buckets=(1, 4, 16), max_wait_ms=2.0)
    tmp = tempfile.mkdtemp(prefix="bench_serve_")
    engines = {}

    def slo(pipe, example, requests, label):
        from keystone_tpu.core import numerics as kbnum

        stem = os.path.join(tmp, f"{label}_pipe")
        # Fit-time output baseline in the manifest (ISSUE 15): load_engine
        # arms the drift monitor from it, so the serving records carry a
        # real drift verdict (the benched mix IS the fit mix — divergence
        # ~0 is the healthy reading).
        save_pipeline(
            stem, pipe,
            numerics_baseline=kbnum.OutputSketch.for_outputs(
                np.asarray(pipe(jnp.asarray(requests)))
            ).record(),
        )
        engine, cold = kserve.load_engine(
            stem, example, config=cfg, label=label
        )
        engines[label] = engine
        rec = kserve.serve_bench(engine, requests, clients=4, depth=16)
        rec["cold_start"] = cold
        return rec

    out = {}
    try:
        # -- workload 1: the MnistRandomFFT servable chain --------------------
        d, k, n_req = 128, 10, 384
        conf = MnistRandomFFTConfig(
            num_ffts=4, block_size=1024, mnist_image_size=d, num_classes=k
        )
        x = rng.normal(size=(768, d)).astype(np.float32)
        y = rng.integers(0, k, 768)
        gfeat = GroupConcatFeaturizer(build_featurizer_batches(conf))
        feats = gfeat(jnp.asarray(x))
        labels = ClassLabelIndicatorsFromIntLabels(k)(jnp.asarray(y))
        model = BlockLeastSquaresEstimator(
            int(feats.shape[1]), 1, 1e-2
        ).fit(feats, labels)
        out["mnist_fft"] = slo(
            Pipeline([gfeat, model, MaxClassifier()]),
            jax.ShapeDtypeStruct((d,), np.float32),
            x[:n_req],
            "mnist_fft",
        )

        # -- workload 2: the RandomPatchCifar conv servable chain -------------
        # Light conv config: on a CPU bench host the conv is compute-bound
        # and batch-linear, so the batching win is the per-request dispatch
        # overhead — a heavyweight conv would bury it (on TPU hardware the
        # MXU's batch amortization does the burying in the other direction).
        cconf = RandomCifarConfig(
            num_filters=4, patch_size=6, patch_steps=8, pool_size=14,
            pool_stride=13, whitener_size=2000, featurize_chunk=128,
            num_classes=4,
        )
        imgs = rng.uniform(0, 255, (256, 32, 32, 3)).astype(np.float32)
        clabels = rng.integers(0, 4, 256)
        filters, whitener = learn_filters(cconf, imgs)
        conv_pipe = build_conv_pipeline(cconf, filters, whitener)
        conv_fn = jax.jit(conv_pipe.__call__)
        train_conv = featurize_chunked(conv_fn, imgs, cconf.featurize_chunk)
        scaler = StandardScaler().fit(train_conv)
        cmodel = BlockLeastSquaresEstimator(4096, 1, 10.0).fit(
            scaler(train_conv),
            ClassLabelIndicatorsFromIntLabels(4)(jnp.asarray(clabels)),
        )
        out["cifar_conv"] = slo(
            Pipeline([*conv_pipe.nodes, scaler, cmodel, MaxClassifier()]),
            jax.ShapeDtypeStruct((32, 32, 3), np.float32),
            imgs[:192],
            "cifar_conv",
        )

        # -- observability overhead probes ------------------------------------
        # ONE harness, three tiers: the SAME warm engine serves the same
        # request set with a tier off, then on — the p99 ratio IS that
        # tier's cost on a live endpoint.  Telemetry (ISSUE 11, < 2%),
        # profiler (ISSUE 14, <= 5%), numerics probes (ISSUE 15, <= 5%).
        import contextlib as _contextlib

        from keystone_tpu.core import numerics as kbnum
        from keystone_tpu.core import profiler as kbprof
        from keystone_tpu.core import telemetry as ktelemetry

        probe_engine = engines["mnist_fft"]
        probe_reqs = x[:256]

        def overhead_pass(reqs):
            return kserve.serve_bench(
                probe_engine, reqs, clients=4, depth=16,
                unbatched_baseline=False,
            )

        def overhead_probe(off_ctx=None, on_ctx=None, warm_on=False,
                           capture=None):
            """(off record, on record, captured extras): the off pass runs
            under ``off_ctx`` (the telemetry tier is on by DEFAULT, so its
            control arm is the suppressed one), the on pass under
            ``on_ctx`` — preceded, when ``warm_on``, by one small warmup
            pass so first-use setup (cost_analysis, jitted-reducer trace)
            never charges the steady-state bound."""
            with (off_ctx or _contextlib.nullcontext()):
                off = overhead_pass(probe_reqs)
            with (on_ctx or _contextlib.nullcontext()):
                if warm_on:
                    overhead_pass(probe_reqs[:64])
                on = overhead_pass(probe_reqs)
                captured = capture() if capture is not None else {}
            return off, on, captured

        def overhead_rows(off, on, frac_key, target):
            return {
                "requests": int(probe_reqs.shape[0]),
                "p99_off_ms": off["p99_latency_ms"],
                "p99_on_ms": on["p99_latency_ms"],
                "qps_off": off["qps"],
                "qps_on": on["qps"],
                frac_key: round(
                    on["p99_latency_ms"]
                    / max(off["p99_latency_ms"], 1e-9)
                    - 1.0,
                    4,
                ),
                "target_frac": target,
            }

        off, on, _ = overhead_probe(off_ctx=ktelemetry.telemetry_disabled())
        out["telemetry_overhead"] = overhead_rows(
            off, on, "p99_overhead_frac", 0.02
        )

        kbprof.reset_state()
        off, on, prof_ledger = overhead_probe(
            on_ctx=kbprof.profiled(True), warm_on=True,
            capture=lambda: {
                label: row
                for label, row in kbprof.ledger().items()
                if label.startswith("serve:")
            },
        )
        out["profiler_overhead"] = {
            **overhead_rows(off, on, "p99_overhead_frac", 0.05),
            "bit_identical_on": on["predictions_bit_identical"],
            # The per-bucket MFU rows the profiled pass produced — the
            # serve half of the bench "profiler" section's ledger.
            "ledger": prof_ledger,
        }

        kbnum.reset_state()
        off, on, num_sites = overhead_probe(
            on_ctx=kbnum.monitored(True), warm_on=True,
            capture=lambda: {
                site: row
                for site, row in kbnum.site_stats().items()
                if site.startswith("serve.")
            },
        )
        kbnum.reset_state()
        out["numerics_overhead"] = {
            **overhead_rows(off, on, "probe_overhead_frac", 0.05),
            # Probes must be bit-inert online too: the monitored pass's
            # answers stay bit-equal to the offline oracle.
            "bit_identical_on": on["predictions_bit_identical"],
            "output_drift": on.get("output_drift"),
            "sites": num_sites,
        }

        # -- the wire front-end (ISSUE 12) --------------------------------
        # The SAME two warm engines behind a ShapeRouter + WireServer,
        # driven over real localhost sockets by concurrent clients — the
        # headline serving.wire_p99_ms and the router's own route
        # overhead (serving.router_route_overhead_us) are what
        # tools/bench_diff.py regresses on across rounds.
        import sys as _sys
        import threading as _threading

        from keystone_tpu.core import frontend as kfrontend
        from keystone_tpu.core import trace as _ktrace
        from keystone_tpu.core import wire as kwire

        _tools = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"
        )
        if _tools not in _sys.path:
            _sys.path.insert(0, _tools)
        from serve_client import drive as wire_drive

        wire_reqs = {
            "mnist_fft": x[:128],
            "cifar_conv": imgs[:64].astype(np.float32),
        }
        router = kfrontend.ShapeRouter(label="bench_router")
        try:
            router.add_engine(engines["mnist_fft"])
            router.add_engine(engines["cifar_conv"])
            lat_all: list = []
            per_engine: dict = {}
            errors: list = []
            lock = _threading.Lock()
            with kwire.WireServer(router, port=0, label="bench") as ws:

                def wire_client(label, reqs):
                    try:
                        with kwire.WireClient(port=ws.port, timeout=60.0) as c:
                            rec = wire_drive(
                                c, list(reqs), window=8, timeout=120.0
                            )
                        with lock:
                            lats = rec.pop("latencies_ms")
                            lat_all.extend(lats)
                            per_engine.setdefault(label, []).extend(lats)
                    except BaseException as e:  # noqa: BLE001 — recorded
                        errors.append(f"{label}: {type(e).__name__}: {e}")

                ts = [
                    _threading.Thread(target=wire_client, args=(lbl, reqs))
                    for lbl, reqs in wire_reqs.items()
                    for _ in range(2)  # two concurrent clients per shape
                ]
                t0 = time.perf_counter()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(300.0)
                wall = time.perf_counter() - t0
                ws_record = ws.record()
            lat_all.sort()
            pick = lambda q: round(  # noqa: E731
                lat_all[min(len(lat_all) - 1, int(q * len(lat_all)))], 3
            ) if lat_all else 0.0
            overhead = _ktrace.metrics.snapshot()["histograms"].get(
                "router_route_overhead_us", {}
            )
            out["wire"] = {
                "requests": len(lat_all),
                "wall_seconds": round(wall, 3),
                "qps": round(len(lat_all) / wall, 2) if wall > 0 else 0.0,
                "per_shape": {
                    lbl: {
                        "requests": len(v),
                        "p50_ms": round(sorted(v)[len(v) // 2], 3),
                        "p99_ms": round(
                            sorted(v)[min(len(v) - 1, int(0.99 * len(v)))], 3
                        ),
                    }
                    for lbl, v in per_engine.items()
                    if v
                },
                "server": ws_record,
                "router": router.record(),
                "errors": errors,
            }
            out["wire_p50_ms"] = pick(0.50)
            out["wire_p99_ms"] = pick(0.99)
            out["router_route_overhead_us"] = round(
                float(overhead.get("p99", 0.0)), 3
            )
        finally:
            router.close()

        # -- elastic serving (ISSUE 16): ckpt -> foreign mesh -> serve ----
        # The mnist_fft artifact saved above is RELOADED onto an explicit
        # smaller mesh (load_pipeline(mesh=) resharding + mesh-native AOT),
        # served bit-equal against the warm engine's offline oracle, then a
        # MeshEngineFactory-backed router is shrunk mid-flight with requests
        # straddling the swap.  bench_diff regresses on
        # serving.reshard_wall_s and pins serving.reanchor_dropped_requests
        # at zero.
        from keystone_tpu.parallel.mesh import make_mesh, mesh_desc

        devs = jax.devices()
        if len(devs) < 2:
            out["reshard"] = {"skipped": "single-device host"}
        else:
            n_full = 4 if len(devs) >= 4 else 2
            full = make_mesh(data=n_full, model=1, devices=devs[:n_full])
            surviving = make_mesh(
                data=n_full // 2, model=1, devices=devs[: n_full // 2]
            )
            stem = os.path.join(tmp, "mnist_fft_pipe")
            reqs = x[:64]
            oracle = np.asarray(engines["mnist_fft"].offline(reqs))

            t0 = time.perf_counter()
            foreign, fcold = kserve.load_engine(
                stem, jax.ShapeDtypeStruct((d,), np.float32),
                config=cfg, label="mnist_fft_foreign", mesh=surviving,
            )
            answers = np.asarray(foreign.infer(reqs))
            reshard_wall = time.perf_counter() - t0

            # Live device-loss drill: requests in flight across the shrink;
            # every one must answer — dropped stays 0 across rounds.
            factory = kfrontend.MeshEngineFactory(
                lambda shape, dtype, m: kserve.load_engine(
                    stem, jax.ShapeDtypeStruct(shape, dtype),
                    config=cfg, label="mnist_fft_elastic", mesh=m,
                )[0],
                mesh=full,
            )
            drill_router = kfrontend.ShapeRouter(
                factory, label="bench_reanchor"
            )
            dropped, got = 0, []
            try:
                drill_router.add_engine(factory((d,), np.dtype(np.float32)))
                futs = [drill_router.submit(r) for r in reqs[:16]]
                rrec = drill_router.reanchor(
                    surviving, why="bench device-loss drill"
                )
                futs += [drill_router.submit(r) for r in reqs[16:32]]
                for f in futs:
                    try:
                        got.append(np.asarray(f.result(120.0)))
                    except Exception:  # noqa: BLE001 — counted as dropped
                        dropped += 1
            finally:
                drill_router.close()

            out["reshard"] = {
                "full_mesh": mesh_desc(full),
                "surviving_mesh": mesh_desc(surviving),
                "cold_start": fcold,
                "round_trip_bit_equal": bool(np.array_equal(answers, oracle)),
                "reanchor": rrec,
                "drill_requests": 32,
                "drill_bit_equal": bool(
                    len(got) == 32
                    and np.array_equal(np.stack(got), oracle[:32])
                ),
            }
            out["reshard_wall_s"] = round(reshard_wall, 4)
            out["reanchor_dropped_requests"] = dropped
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_profiler(rng):
    """Device cost attribution (ISSUE 14): a laddered BCD fit runs with
    the profiler ON — the per-program MFU ledger rows for the solve
    tiers, the hand-flops-hint-vs-compiled audit table, and the HBM
    watermark sampler's surface (on CPU hosts ``memory_stats`` is
    unavailable and the sampler retires itself; the record says so rather
    than inventing a watermark).  The headline ``solve_mfu`` is the fused
    solve's ledger MFU — the first number the BENCH_r06 hardware round
    reads from this section."""
    from keystone_tpu.core import autoshard
    from keystone_tpu.core import profiler as kprof
    from keystone_tpu.core.resilience import counters as _counters

    autoshard.hermetic_plan_log()
    kprof.reset_state()
    n, d, k = 8192, 1024, 32
    x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    y = one_hot_pm1(rng, n, k)
    with kprof.profiled(True, interval_ms=5.0):
        est = BlockLeastSquaresEstimator(d, 2, 1e-2)
        est.fit(x, y)
        sampler = kprof.sampler()
        sampler_rec = sampler.record() if sampler is not None else None
        ledger = kprof.ledger_record()
    solve_rows = {
        label: row
        for label, row in ledger["programs"].items()
        if label.startswith("bcd_fit")
    }
    solve_mfu = max(
        (row["mfu"] or 0.0 for row in solve_rows.values()), default=None
    )
    audits = ledger["flops_audits"]
    worst_audit = max(
        (
            max(a["ratio"], 1.0 / a["ratio"])
            for a in audits.values()
            if a.get("ratio")
        ),
        default=None,
    )
    # Drift rows this profiled fit appended to the (hermetic) plan log —
    # on hardware these are the calibration evidence; on CPU the column
    # records 0 honestly (no watermark, no drift row).  The once-per-
    # process log cache predates the appends, so drop it before reading.
    autoshard.clear_outcome_cache()
    drift = autoshard.drift_rows()
    return {
        "n": n, "d": d, "classes": k,
        "solve_mfu": solve_mfu,
        "ledger": ledger,
        "flops_audit_worst_factor": (
            round(worst_audit, 3) if worst_audit else None
        ),
        "flops_audits_ok": all(a.get("ok") for a in audits.values()),
        "hbm_sampler": sampler_rec,
        "plan_drift_rows": len(drift),
        "plan_drift_count": _counters.get("plan_drift"),
    }


def bench_multihost(rng):
    """Multi-host elastic serving (ISSUE 17): the REAL 2-process
    ``jax.distributed`` fit+serve (bit-identical to single-process on the
    same shards, crosshost checkpoint reshard timed) and the host-loss
    drill (SIGKILL one serving host mid-flight; survivors re-form,
    reshard, re-anchor; zero request loss).  bench_diff regresses on
    ``multihost.fit_serve_wall_s``, ``multihost.reshard_wall_s``, and
    ``multihost.host_loss.reanchor_wall_s``, and pins
    ``multihost.host_loss.dropped_requests`` at zero.  Where process
    spawn is unavailable the section records zero-base rows and says so
    — never a fake measurement."""
    import shutil
    import tempfile

    from keystone_tpu.parallel.distributed import spawn_available
    from keystone_tpu.workloads import multihost as mh

    if not spawn_available():
        return {
            "available": False,
            "fit_serve_wall_s": 0.0,
            "reshard_wall_s": 0.0,
            "host_loss": {"reanchor_wall_s": 0.0, "dropped_requests": 0},
        }
    tmp = tempfile.mkdtemp(prefix="bench_multihost_")
    try:
        fs = mh.run_two_process_fit_serve(
            tmp, shards_per_host=2, images_per_shard=6, seed=0
        )
        drill = mh.run_host_loss_drill(
            os.path.join(tmp, "drill"), hosts=2, requests=24, seed=0
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "available": True,
        "fit_serve_wall_s": round(float(fs["fit_serve_wall_s"]), 3),
        "reshard_wall_s": round(float(fs["reshard_wall_s"]), 4),
        "bit_identical": fs["bit_identical"],
        "crosshost_bit_equal": fs["crosshost_bit_equal"],
        "n_images": fs["n_images"],
        "leaked_threads": fs["leaked_threads"],
        "host_loss": {
            "mode": drill["mode"],
            "hosts": drill["hosts"],
            "reanchor_wall_s": round(
                float(drill.get("reanchor_wall_s") or 0.0), 4
            ),
            "dropped_requests": int(drill["dropped_requests"]),
            "mismatches": int(drill["mismatches"]),
            "answered": int(drill["answered"]),
            "postmortems": len(drill["postmortems"]),
        },
    }


def bench_lifecycle(rng):
    """Closed-loop model lifecycle (core.lifecycle, ISSUE 18): the
    drift→refit→validate→swap drill from tools/serve_bench.py — a
    shifted mix trips the armed incumbent's drift monitor, the
    controller warm-refits on fresh data, validates on a holdout, and
    hot-swaps the router's engine while a pump thread keeps requests in
    flight.  ``tools/bench_diff.py`` regresses on
    ``lifecycle.refit_wall_s`` / ``lifecycle.swap_wall_s`` /
    ``lifecycle.drift_to_healthy_wall_s`` (lower is better) and pins
    ``lifecycle.dropped_requests`` at zero — the hot-swap's zero-downtime
    claim, re-proven every round."""
    import shutil
    import sys as _sys
    import tempfile

    _tools = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    if _tools not in _sys.path:
        _sys.path.insert(0, _tools)
    from serve_bench import drift_refit_drill

    tmp = tempfile.mkdtemp(prefix="bench_lifecycle_")
    try:
        drill = drift_refit_drill(tmp, requests=24, seed=0)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    # The full cycle record stays in the drill dict; keep the section's
    # top level to the dotted paths the observatory reads.
    return {
        "tripped": drill.get("tripped"),
        "outcome": (drill.get("cycle") or {}).get("outcome"),
        "drift_to_healthy_wall_s": drill.get("drift_to_healthy_wall_s"),
        "refit_wall_s": drill.get("refit_wall_s"),
        "validate_wall_s": drill.get("validate_wall_s"),
        "swap_wall_s": drill.get("swap_wall_s"),
        "in_flight_across_swap": drill.get("in_flight_across_swap"),
        "dropped_requests": drill.get("dropped_requests"),
        "post_swap_bit_equal": drill.get("post_swap_bit_equal"),
        "quality": (drill.get("cycle") or {}).get("quality"),
        "statusz": drill.get("lifecycle"),
        "ok": drill.get("ok", False),
    }


def bench_fleet_observability(rng):
    """Fleet observability plane (core.fleetobs, ISSUE 20): the
    cross-host metrics fabric measured four ways — (1) the pure
    window-merge wall over a synthetic 16-member fleet, (2) a live
    2-agent scrape wall over real sockets, (3) the collector's serving
    cost with the SAME off/on harness as the telemetry/profiler/numerics
    tiers (one warm wire endpoint, same request set, collector detached
    then attached at a hot interval; <= 5% p99, answers bit-equal), and
    (4) the 2-subprocess obs-capture drill (SIGKILL one member
    mid-scrape) whose incident-capture wall and acceptance verdicts ride
    along.  ``tools/bench_diff.py`` regresses on the walls and the
    overhead frac (lower is better) and pins
    ``fleet_observability.drill.dropped_requests`` at zero."""
    import shutil
    import tempfile

    from keystone_tpu.core import fleetobs
    from keystone_tpu.parallel.distributed import spawn_available
    from keystone_tpu.workloads import multihost as mh

    out: dict = {}

    # -- merge wall: pure window math, 16 members x 4 hists x 512 samples.
    member_wins = []
    for _m in range(16):
        win = {}
        for h in range(4):
            samples = np.abs(
                rng.normal(loc=5.0 + h, scale=1.0, size=512)
            ).astype(float).tolist()
            win[f"lat{h}_ms"] = {
                "count": len(samples), "total": float(sum(samples)),
                "min": float(min(samples)), "max": float(max(samples)),
                "samples": samples,
            }
        member_wins.append(win)
    t0 = time.perf_counter()
    merged = {
        name: fleetobs.merge_windows([m[name] for m in member_wins])
        for name in member_wins[0]
    }
    summaries = {k: fleetobs.window_summary(v) for k, v in merged.items()}
    out["merge_wall_s"] = round(time.perf_counter() - t0, 4)
    out["merge_members"] = len(member_wins)
    out["merge_samples"] = int(sum(s["count"] for s in summaries.values()))

    # -- scrape wall: two live in-process agents, one timed scrape (the
    # warm pass absorbs connect + clock sync, as in steady state).
    with fleetobs.ObsAgent(label="bench-a") as a_agent, \
            fleetobs.ObsAgent(label="bench-b") as b_agent:
        col = fleetobs.FleetCollector(
            [f"{a_agent.host}:{a_agent.port}",
             f"{b_agent.host}:{b_agent.port}"],
            interval_s=3600.0, label="bench_fleetobs",
        )
        with col:
            col.scrape_once()
            t0 = time.perf_counter()
            snap = col.scrape_once()
            out["scrape_wall_s"] = round(time.perf_counter() - t0, 4)
            out["scrape_members"] = snap.get("alive")

    # -- collector on/off serve p99: the same off/on discipline as the
    # telemetry/profiler/numerics tiers — ONE warm compute-bound engine
    # (real members spend their wall in GIL-releasing XLA work, so a
    # trivial engine would measure pure scheduler-convoy noise), the
    # SAME request set, the on arm scraped by an attached collector.
    # best-of-3 p99 per arm keeps a shared box's scheduler jitter out of
    # the ratio.
    from keystone_tpu.core import serve as kserve
    from keystone_tpu.core.pipeline import FunctionTransformer

    d = 1024
    w1 = jnp.asarray(rng.standard_normal((d, d)).astype(np.float32))
    probe_pipe = FunctionTransformer(
        lambda v: jnp.tanh(jnp.tanh(v @ w1) @ w1.T) @ w1, name="obsprobe"
    )
    probe_engine = kserve.ServingEngine(
        probe_pipe,
        np.zeros((d,), np.float32),
        config=kserve.ServeConfig.from_env(buckets=(1, 4, 16),
                                           max_wait_ms=2.0),
        label="bench_fleetobs_probe",
    )
    probe_reqs = rng.normal(size=(256, d)).astype(np.float32)

    def serve_pass():
        return kserve.serve_bench(
            probe_engine, probe_reqs, clients=4, depth=16,
            unbatched_baseline=False,
        )

    serve_pass()  # warm: compile every bucket
    p99_off = min(serve_pass()["p99_latency_ms"] for _ in range(3))
    agent = fleetobs.ObsAgent(label="bench_fleetobs_member")
    pcol = fleetobs.FleetCollector(
        [f"{agent.host}:{agent.port}"], interval_s=0.2,
        label="bench_fleetobs_on",
    )
    try:
        pcol.start()
        on_runs = [serve_pass() for _ in range(3)]
        pcol.stop()
        scrapes = pcol.scrapes
    finally:
        pcol.close()
        agent.close()
    p99_on = min(r["p99_latency_ms"] for r in on_runs)
    out["collector_overhead"] = {
        "requests": int(probe_reqs.shape[0]),
        "p99_off_ms": round(p99_off, 4),
        "p99_on_ms": round(p99_on, 4),
        "collector_overhead_frac": round(
            p99_on / max(p99_off, 1e-9) - 1.0, 4
        ),
        "target_frac": 0.05,
        "scrapes_during_on_pass": scrapes,
        # The scraped arm's answers stay bit-equal to the offline
        # oracle — the collector must never perturb served bytes.
        "bit_identical_on": bool(
            all(r["predictions_bit_identical"] for r in on_runs)
        ),
    }

    # -- the obs-capture drill: 2 REAL worker processes, SIGKILL one
    # mid-scrape; one clock-aligned incident bundle or the drill says why.
    if not spawn_available():
        out["drill"] = {"available": False, "dropped_requests": 0}
        out["incident_capture_wall_s"] = 0.0
        return out
    tmp = tempfile.mkdtemp(prefix="bench_fleetobs_")
    try:
        drill = mh.run_obs_capture_drill(
            tmp, hosts=2, requests=16, seed=0, subprocess_mode=True
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    inc = drill.get("incident") or {}
    out["drill"] = {
        "available": True,
        "mode": drill.get("mode"),
        "wall_s": round(float(drill.get("wall_s") or 0.0), 3),
        "scrape_wall_s": drill.get("scrape_wall_s"),
        "counter_sum_ok": drill.get("counter_sum_ok"),
        "p99_match": drill.get("p99_match"),
        "monotone_ok": drill.get("monotone_ok"),
        "obs_member_lost": drill.get("obs_member_lost"),
        "dropped_requests": int(drill.get("dropped_requests") or 0),
        "mismatches": int(drill.get("mismatches") or 0),
        "incident": {
            k: inc.get(k)
            for k in (
                "trigger", "capture_wall_s", "members", "missing",
                "n_events", "survivor_rings_ok", "events_monotone",
                "error",
            )
            if k in inc
        },
    }
    out["incident_capture_wall_s"] = float(inc.get("capture_wall_s") or 0.0)
    return out


def bench_numerics(rng, serving: dict | None = None):
    """Numerics observatory (ISSUE 15): a laddered BCD fit runs MONITORED
    — the per-block κ table lands in ``FitReport.conditioning`` (the
    ACCURACY.md §6 sweep live, with the predictive ``cond_warn`` armed) —
    and the serving probe-overhead measurement from ``bench_serving``
    (same warm engine, observatory off vs on, <= 5% p99 acceptance) is
    folded in as the section's headline rows: ``probe_overhead`` and the
    probed-serve p99 are what ``tools/bench_diff.py`` regresses on across
    rounds."""
    from keystone_tpu.core import numerics as knum
    from keystone_tpu.core.resilience import counters as _counters

    knum.reset_state()
    n, d, k = 4096, 1024, 16
    x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    y = one_hot_pm1(rng, n, k)
    with knum.monitored(True):
        est = BlockLeastSquaresEstimator(d // 2, 1, 1e-2)
        est.fit(x, y)
        cond = (
            list(est.last_fit_report.conditioning or [])
            if est.last_fit_report is not None
            else []
        )
    knum.reset_state()
    probe = (serving or {}).get("numerics_overhead")
    out = {
        "conditioning": cond,
        # kappa=None rows (non-finite gram / estimator failure) are a
        # documented shape — filter them or max() dies on float vs None.
        "kappa_max": max(
            (r["kappa"] for r in cond if r.get("kappa") is not None),
            default=None,
        ),
        "cond_warns": _counters.get("cond_warn"),
        # The serving-path probe overhead (measured in bench_serving on
        # the warm mnist_fft engine) — the bench_diff thresholds read
        # THESE two rows.
        "probe_overhead": probe,
        "probed_serve_p99_ms": (
            probe.get("p99_on_ms") if isinstance(probe, dict) else None
        ),
    }
    return out


def bench_self_diff(record: dict, dirpath: str | None = None) -> dict:
    """Regression observatory (ISSUE 11): compare THIS round's record
    against the newest USABLE prior ``BENCH_r*.json`` (a truncated newest
    round — r05's ``parsed: null`` — falls back to the round before it)
    via ``tools/bench_diff.py``'s thresholds, and embed the verdict in the
    round artifact so every hardware round self-reports regressions."""
    import sys

    root = os.path.dirname(os.path.abspath(__file__)) or "."
    tools_dir = os.path.join(root, "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import bench_diff

    prev = bench_diff.latest_usable_round(dirpath or root)
    if prev is None:
        return {"note": "no usable prior BENCH round on record"}
    num, path, base = prev
    out = bench_diff.compare(base, record)
    out["baseline"] = os.path.basename(path)
    out["baseline_round"] = num
    # The full per-metric table stays in the tool; the embedded section
    # keeps the verdict + the rows that moved (artifact size discipline).
    out.pop("rows", None)
    return out


def _error_record(e: Exception) -> dict:
    return {"error": f"{type(e).__name__}: {e}"[:300]}


def _guarded(fn, rng):
    """Secondary benches must not kill the whole JSON artifact: a transient
    failure (noise-floor miss on a busy shared chip, OOM on a smaller
    device) degrades to an error record; the headline metric stays strict."""
    try:
        return fn(rng)
    except Exception as e:  # noqa: BLE001 - recorded, not swallowed
        return _error_record(e)


def main():
    from keystone_tpu.core import autoshard

    # Hermetic placement search: the bench asserts searched-vs-hand
    # bit-equality and ranking-dependent bars that a TRAINED operator log
    # (~/.keystone_plans.jsonl) could legitimately reorder, and its
    # synthetic shapes must not pollute the log that calibrates real
    # workload fits.  Each bench process gets a throwaway log (the
    # placement/at-scale sections also pin one for direct invocations).
    autoshard.hermetic_plan_log()
    rng = np.random.default_rng(0)
    n_chips = len(jax.devices())
    kind = jax.devices()[0].device_kind
    peak = PEAK_FLOPS.get(kind)
    bw = HBM_BW.get(kind)

    cifar = bench_cifar_featurize(rng)
    fv = _guarded(bench_imagenet_fv_featurize, rng)
    stages = _guarded(bench_stage_ops, rng)
    decode = _guarded(bench_decode, rng)
    e2e = _guarded(bench_e2e_ingest, rng)
    optimizer = _guarded(bench_optimizer, rng)
    serving = _guarded(bench_serving, rng)
    placement = _guarded(bench_placement, rng)
    profiler_sec = _guarded(bench_profiler, rng)
    numerics_sec = _guarded(lambda r: bench_numerics(r, serving), rng)
    multihost_sec = _guarded(bench_multihost, rng)
    lifecycle_sec = _guarded(bench_lifecycle, rng)
    fleetobs_sec = _guarded(bench_fleet_observability, rng)
    at_scale = _guarded(bench_solve_at_scale, rng)

    # ONE atomic registry snapshot feeds both the back-compat "faults" key
    # and the full "metrics" section — two separate snapshot calls could
    # disagree about a fault recorded between them.
    metrics_snapshot = ktrace.metrics.snapshot()

    value = round(cifar["images_per_sec"] / n_chips, 2)
    prior = prior_bench_value("random_patch_cifar_featurize")
    mfu = (
        round(cifar["flops_per_sec"] / (peak * n_chips), 4)
        if cifar["flops_per_sec"] and peak
        else None
    )
    fv_mfu = (
        round(fv["flops_per_sec"] / (peak * n_chips), 4)
        if fv.get("flops_per_sec") and peak
        else None
    )
    record = {
        "metric": "random_patch_cifar_featurize",
        "value": value,
        "unit": "images/sec/chip",
        "vs_baseline": round(value / prior, 4) if prior else 1.0,
        "mfu": mfu,
        "flops_per_sec": cifar["flops_per_sec"],
        "flops_per_image": cifar["flops_per_image"],
        "bytes_per_image": cifar["bytes_per_image"],
        "roofline": roofline(
            cifar["flops"], cifar["bytes_accessed"],
            cifar["per_iter"],
            peak * n_chips if peak else None,
            bw * n_chips if bw else None,
        ),
        "peak_flops_per_chip": peak,
        "solve_seconds": round(cifar["solve_seconds"], 4),
        "solve_examples_per_sec": round(
            cifar["solve_examples_per_sec"], 2
        ),
        "solve_device_seconds": round(cifar["solve_device_seconds"], 6),
        # Degradation ledger for this whole bench process: IO retries,
        # corrupt-member skips, jitter recoveries, OOM step-downs,
        # skew-guard fallbacks... — so BENCH_r06+ rows show the faults the
        # numbers were earned under, not just the perf (empty dict = clean).
        # Kept as its own key for BENCH_r0x row continuity, sourced from
        # the same atomic snapshot as "metrics" below.
        "faults": metrics_snapshot["faults"],
        # The unified metrics registry (core.trace): counters/gauges/
        # histograms accumulated anywhere in the process, faults group
        # included — every bench record carries the full metrics surface.
        "metrics": metrics_snapshot,
        "extra_metrics": {
            "imagenet_fv_featurize": (
                fv
                if "error" in fv
                else {
                    "value": round(fv["images_per_sec"] / n_chips, 2),
                    "unit": "images/sec/chip",
                    "mfu": fv_mfu,
                    "flops_per_sec": fv["flops_per_sec"],
                    "roofline": roofline(
                        fv["flops"], fv["bytes_accessed"],
                        fv["per_iter"],
                        peak * n_chips if peak else None,
                        bw * n_chips if bw else None,
                    ),
                }
            ),
            "stage_ops": stages,
            "solve_at_scale": at_scale,
            "jpeg_decode": decode,
            # Streaming-ingest e2e: tar -> decode -> featurize(-> solve)
            # with decode/featurize overlap (core.ingest); includes the
            # per-stream ring depth/stall counters and the overlap
            # efficiency vs its 0.9 target.
            "e2e": e2e,
            # Pipeline optimizer (core.optimize): auto-Cacher cached-vs-
            # uncached fit wall + decision table, and the closed-loop
            # ingest autotuner's knob trajectory + overlap efficiency on a
            # stall-injected stream.
            "optimizer": optimizer,
            # Low-latency serving (core.serve): per-workload online SLOs —
            # cold start (restore/compile/warmup), p50/p99 latency,
            # sustained QPS, batcher occupancy, batched-vs-unbatched QPS
            # (>= 2x target at bit-equal answers).
            "serving": serving,
            # Placement search (core.autoshard): searched-vs-hand-ladder
            # fit wall on >= 3 BCD shapes (bit-identical models required),
            # the search's enumerate+prune+score overhead as a fraction of
            # fit wall (< 5% bar), and the chosen plan's
            # predicted-vs-measured cost ratio.
            "placement": placement,
            # Device cost attribution (core.profiler, ISSUE 14): the
            # per-program MFU ledger of a profiled BCD fit, the
            # flops-hint audit table, the HBM sampler surface, and the
            # plan-drift row count — the section BENCH_r06 reads for the
            # first hardware MFU/drift numbers.
            "profiler": profiler_sec,
            # Numerics observatory (core.numerics, ISSUE 15): a monitored
            # BCD fit's per-block κ table (the live ACCURACY.md §6 sweep)
            # plus the serving probe-overhead rows (<= 5% p99 acceptance)
            # bench_diff regresses on.
            "numerics": numerics_sec,
            # Multi-host elastic serving (parallel.distributed +
            # workloads.multihost, ISSUE 17): real 2-process fit+serve
            # bit-identity + crosshost reshard wall, and the host-loss
            # drill's re-anchor wall with dropped_requests pinned at 0.
            # Zero-base rows (available: false) where spawn is off.
            "multihost": multihost_sec,
            # Closed-loop model lifecycle (core.lifecycle, ISSUE 18): the
            # drift→refit→validate→swap drill's walls (refit/swap/
            # drift-to-healthy, all lower-is-better across rounds) with
            # dropped_requests pinned at 0 — the zero-downtime hot-swap
            # claim, re-proven every round.
            "lifecycle": lifecycle_sec,
            # Fleet observability plane (core.fleetobs, ISSUE 20): the
            # window-merge and live-scrape walls, the collector's
            # off/on serving p99 (<= 5% bar, answers bit-equal), and the
            # 2-subprocess obs-capture drill's incident-capture wall
            # with dropped_requests pinned at 0.
            "fleet_observability": fleetobs_sec,
        },
    }
    # Regression observatory (ISSUE 11): this round judged against the
    # newest usable prior round's record, verdict embedded in the artifact.
    record["bench_diff"] = _guarded(lambda _rng: bench_self_diff(record), rng)
    # Artifact-truncation guard (VERDICT r5 "Driver artifacts"): the driver
    # keeps a bounded TAIL of stdout, and round 5's record — one JSON line
    # emitted last, after all bench log noise — got cut mid-record
    # (`parsed: null`, headline number lost).  Emit the machine record
    # FIRST, flushed, and keep everything after it (the human-readable
    # summary below) tiny, so any tail window that reaches the end of the
    # output contains the complete JSON line.
    print(json.dumps(record), flush=True)
    ex = record["extra_metrics"]
    print(
        f"# {record['metric']}: {value} images/sec/chip "
        f"(vs_baseline {record['vs_baseline']}, mfu {mfu})"
    )
    fvx = ex["imagenet_fv_featurize"]
    print(
        "# imagenet_fv_featurize: "
        + (fvx.get("error", "") if "error" in fvx else f"{fvx['value']} images/sec/chip")
    )
    sas = ex["solve_at_scale"]
    if "error" in sas:
        print(f"# solve_at_scale: {sas['error'][:120]}")
    else:
        print(
            f"# solve_at_scale: n={sas['n']} d={sas['d']} "
            f"({sas['design_matrix_gb']} GB) in {sas['wall_seconds']} s, "
            f"{len(sas.get('oom_attempts', []))} OOM attempt(s)"
        )
    jd = ex["jpeg_decode"]
    if "error" not in jd:
        print(
            f"# jpeg_decode: serial {jd['serial_images_per_sec']}/s, "
            f"threaded {jd['threaded_images_per_sec']}/s "
            f"(x{jd['speedup']})"
        )
        pp = (
            jd.get("process_pool_steady_images_per_sec")
            or jd.get("process_pool_images_per_sec")
        )
        if pp:
            print(
                f"# jpeg_decode process pool (steady): {pp} "
                f"(best x{jd.get('process_best_speedup_vs_serial')} vs serial)"
            )
        sn = jd.get("snapshot")
        if sn:
            print(
                f"# jpeg_decode snapshot: cold "
                f"{sn['cold_write_images_per_sec']}/s -> warm "
                f"{sn['warm_read_images_per_sec']}/s "
                f"(x{sn['warm_speedup_vs_serial_decode']} vs serial decode)"
            )
        bp = jd.get("by_path")
        if bp:
            dev = bp["device"]
            print(
                "# jpeg_decode by_path e2e: host_pool "
                f"{bp['host_pool']['images_per_sec']}/s, device "
                f"{dev['images_per_sec']}/s (overlap "
                f"{dev['overlap_efficiency']}, parity "
                f"{dev['golden_max_abs_vs_host']}), warm device-snapshot "
                f"{bp['device_snapshot_warm']['images_per_sec']}/s "
                "(zero_host_decode="
                f"{bp['device_snapshot_warm']['zero_host_decode']})"
            )
    e2x = ex["e2e"]
    if "error" in e2x:
        print(f"# e2e: {e2x['error'][:120]}")
    else:
        for wk in ("cifar", "imagenet_fv"):
            r = e2x[wk]
            print(
                f"# e2e {wk}: decode {r['decode_images_per_sec']}/s, "
                f"featurize {r['featurize_images_per_sec']}/s, "
                f"e2e {r['e2e_images_per_sec']}/s "
                f"(overlap {r['overlap_efficiency']}); snapshot-warm e2e "
                f"{r.get('snapshot_e2e_images_per_sec')}/s "
                f"({r.get('snapshot_e2e_vs_featurize')} of featurize)"
            )
    opt = ex["optimizer"]
    if "error" in opt:
        print(f"# optimizer: {opt['error'][:120]}")
    else:
        ac, at = opt["auto_cache"], opt["autotune"]
        print(
            f"# optimizer auto_cache: {ac['uncached_fit_wall_seconds']}s -> "
            f"{ac['cached_fit_wall_seconds']}s (x{ac['speedup']}, "
            f"bit_identical {ac['predictions_bit_identical']})"
        )
        print(
            f"# optimizer autotune: {at['static_images_per_sec']}/s -> "
            f"{at['tuned_images_per_sec']}/s (x{at['speedup']}, "
            f"{at['tuner']['retunes']} retune(s), overlap "
            f"{at['static_overlap_efficiency']} -> "
            f"{at['tuned_overlap_efficiency']})"
        )
    srv = ex["serving"]
    if "error" in srv:
        print(f"# serving: {srv['error'][:120]}")
    else:
        for wk, r in srv.items():
            if not isinstance(r, dict):
                continue  # scalar headline metrics (wire_p99_ms, ...)
            if wk == "telemetry_overhead":
                print(
                    f"# serving telemetry overhead: p99 {r['p99_off_ms']}ms "
                    f"off -> {r['p99_on_ms']}ms on "
                    f"({r['p99_overhead_frac']:+.2%}, target < "
                    f"{r['target_frac']:.0%})"
                )
                continue
            if wk == "profiler_overhead":
                print(
                    f"# serving profiler overhead: p99 {r['p99_off_ms']}ms "
                    f"off -> {r['p99_on_ms']}ms on "
                    f"({r['p99_overhead_frac']:+.2%}, target <= "
                    f"{r['target_frac']:.0%}, bit_identical "
                    f"{r['bit_identical_on']})"
                )
                continue
            if wk == "numerics_overhead":
                print(
                    f"# serving numerics overhead: p99 {r['p99_off_ms']}ms "
                    f"off -> {r['p99_on_ms']}ms probed "
                    f"({r['probe_overhead_frac']:+.2%}, target <= "
                    f"{r['target_frac']:.0%}, bit_identical "
                    f"{r['bit_identical_on']})"
                )
                continue
            if wk == "wire":
                rt = r["router"]["stats"]
                print(
                    f"# serving wire: {r['requests']} requests over real "
                    f"sockets, p50 {srv.get('wire_p50_ms')}ms / p99 "
                    f"{srv.get('wire_p99_ms')}ms, {r['qps']} QPS, route "
                    f"overhead p99 "
                    f"{srv.get('router_route_overhead_us')}us, "
                    f"{rt['routes']} routed / {rt['retires']} retire(s)"
                    + (f", ERRORS {r['errors']}" if r["errors"] else "")
                )
                continue
            burn = r.get("slo", {}).get("window", {}).get("burn_rate")
            print(
                f"# serving {wk}: p50 {r['p50_latency_ms']}ms / p99 "
                f"{r['p99_latency_ms']}ms, {r['qps']} QPS "
                f"(x{r.get('batched_vs_unbatched_qps')} vs unbatched), "
                f"occupancy {r['batcher']['mean_occupancy']}, burn_rate "
                f"{burn}, cold start "
                f"{r['cold_start']['cold_start_seconds']}s, bit_identical "
                f"{r['predictions_bit_identical']}"
            )
    numx = ex["numerics"]
    if "error" in numx:
        print(f"# numerics: {numx['error'][:120]}")
    else:
        po = numx.get("probe_overhead") or {}
        kmax = numx.get("kappa_max")
        print(
            f"# numerics: kappa_max "
            f"{f'{kmax:.3g}' if kmax is not None else 'n/a'} over "
            f"{len(numx['conditioning'])} block(s) "
            f"({numx['cond_warns']} cond_warn), probed-serve p99 "
            f"{numx.get('probed_serve_p99_ms')}ms "
            f"({po.get('probe_overhead_frac', 0.0):+.2%} vs unprobed)"
        )
    prof = ex["profiler"]
    if "error" in prof:
        print(f"# profiler: {prof['error'][:120]}")
    else:
        smp = prof.get("hbm_sampler") or {}
        print(
            f"# profiler: solve_mfu {prof['solve_mfu']}, flops audit worst "
            f"x{prof['flops_audit_worst_factor']} "
            f"(ok={prof['flops_audits_ok']}), drift rows "
            f"{prof['plan_drift_rows']}, sampler "
            + (
                "unavailable (no device memory_stats)"
                if smp.get("unavailable")
                else f"{smp.get('samples', 0)} sample(s)"
            )
        )
    mhx = ex["multihost"]
    if "error" in mhx:
        print(f"# multihost: {mhx['error'][:120]}")
    elif not mhx.get("available"):
        print("# multihost: process spawn unavailable — zero-base rows")
    else:
        hl = mhx["host_loss"]
        print(
            f"# multihost: 2-process fit+serve "
            f"{mhx['fit_serve_wall_s']}s (bit_identical "
            f"{mhx['bit_identical']}, crosshost reshard "
            f"{mhx['reshard_wall_s']}s), host-loss drill ({hl['mode']}) "
            f"reanchor {hl['reanchor_wall_s']}s, "
            f"{hl['dropped_requests']} dropped / {hl['mismatches']} "
            f"mismatched of {hl['answered']}"
        )
    lcx = ex["lifecycle"]
    if "error" in lcx:
        print(f"# lifecycle: {lcx['error'][:120]}")
    else:
        print(
            f"# lifecycle: tripped on {lcx['tripped']}, {lcx['outcome']} in "
            f"{lcx['drift_to_healthy_wall_s']}s (refit "
            f"{lcx['refit_wall_s']}s, swap {lcx['swap_wall_s']}s), "
            f"{lcx['in_flight_across_swap']} in flight across the swap, "
            f"{lcx['dropped_requests']} dropped, bit-equal "
            f"{lcx['post_swap_bit_equal']}"
        )
    fox = ex["fleet_observability"]
    if "error" in fox:
        print(f"# fleet_observability: {fox['error'][:120]}")
    else:
        co = fox["collector_overhead"]
        print(
            f"# fleet_observability: scrape {fox['scrape_wall_s']}s "
            f"({fox['scrape_members']} member(s)), merge "
            f"{fox['merge_wall_s']}s ({fox['merge_samples']} samples), "
            f"collector p99 {co['p99_off_ms']}ms off -> "
            f"{co['p99_on_ms']}ms on "
            f"({co['collector_overhead_frac']:+.2%}, target <= "
            f"{co['target_frac']:.0%}, bit_identical "
            f"{co['bit_identical_on']})"
        )
        fdr = fox["drill"]
        if not fdr.get("available"):
            print("# fleet_observability drill: spawn unavailable — "
                  "zero-base rows")
        else:
            print(
                f"# fleet_observability drill ({fdr['mode']}): incident "
                f"capture {fox['incident_capture_wall_s']}s, counter_sum "
                f"{fdr['counter_sum_ok']}, p99_match {fdr['p99_match']}, "
                f"monotone {fdr['monotone_ok']}, "
                f"{fdr['dropped_requests']} dropped / "
                f"{fdr['mismatches']} mismatched"
            )
    bd = record["bench_diff"]
    if "verdict" in bd:
        print(
            f"# bench_diff vs {bd.get('baseline')}: {bd['verdict']} "
            f"({bd.get('compared')} compared, "
            f"{len(bd.get('regressions', []))} regression(s))"
        )
    else:
        print(f"# bench_diff: {bd.get('note') or bd.get('error')}")
    print(f"# faults: {record['faults'] if record['faults'] else 'none'}")


if __name__ == "__main__":
    main()
