"""Leave-2-out CV over the reference 10-image VOC fixture (ACCURACY.md §2).

Run: env PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python tools/voc_leave2out_cv.py
"""
import sys, os
import numpy as np
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from keystone_tpu.loaders.image_loaders import voc_loader, MultiLabeledImages
from keystone_tpu.workloads.voc_sift_fisher import SIFTFisherConfig, run

data = voc_loader("/root/reference/src/test/resources/images/voc",
                  "/root/reference/src/test/resources/images/voclabels.csv")
n = len(data)
print(f"{n} images; labels per image: {data.labels}")
conf = SIFTFisherConfig(lam=0.05, desc_dim=16, vocab_size=8,
                        num_pca_samples=6000, num_gmm_samples=6000)
rng = np.random.default_rng(0)
perm = rng.permutation(n)
fold_maps, fold_details = [], []
for f in range(5):
    test_idx = set(perm[2*f:2*f+2].tolist())
    tr = [i for i in range(n) if i not in test_idx]
    te = sorted(test_idx)
    sub = lambda idx: MultiLabeledImages([data.images[i] for i in idx],
                                         [data.labels[i] for i in idx],
                                         [data.filenames[i] for i in idx])
    res = run(conf, sub(tr), sub(te))
    train_classes = set(c for i in tr for c in data.labels[i])
    test_classes = sorted(set(c for i in te for c in data.labels[i]))
    # AP over classes present in the test fold AND learnable (seen in train)
    scored = [c for c in test_classes if c in train_classes]
    aps = [res["aps"][c] for c in scored]
    fold_maps.append(float(np.mean(aps)) if aps else float("nan"))
    fold_details.append((te, test_classes, scored, [round(float(a),3) for a in aps]))
    print(f"fold {f}: test={te} test_classes={test_classes} scored={scored} aps={fold_details[-1][3]} foldMAP={fold_maps[-1]:.3f}")
print(f"mean held-out MAP over 5 folds: {np.nanmean(fold_maps):.4f}")
