"""Reproduce the ROOFLINE.md featurize-variant table (run on a real TPU).

Times the shipped fused compact-activation featurizer against the op-by-op
XLA chain and the f32-exactness variant at the bench shape, with XLA
cost-analysis FLOPs/bytes — the measurements behind ops/conv_fused.py's
design.  Usage:  python tools/roofline_probe.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from bench import HBM_BW, PEAK_FLOPS, compiled_cost, timed_chain_auto
from keystone_tpu.workloads.cifar_random_patch import (
    RandomCifarConfig,
    build_conv_pipeline,
    learn_filters,
)


def main():
    # The probe's purpose is reproducing the ROOFLINE.md XLA-variant rows;
    # a stray KEYSTONE_PALLAS=1 would silently swap in the opt-in kernel
    # under the SHIPPED label.
    os.environ.pop("KEYSTONE_PALLAS", None)
    conf = RandomCifarConfig(
        num_filters=100, patch_size=6, patch_steps=1, pool_size=14,
        pool_stride=13, alpha=0.25, whitener_size=20000, featurize_chunk=1024,
    )
    rng = np.random.default_rng(0)
    train = rng.uniform(0, 255, (512, 32, 32, 3)).astype(np.float32)
    filters, whitener = learn_filters(conf, train)
    batch = jnp.asarray(rng.uniform(0, 255, (1024, 32, 32, 3)).astype(np.float32))

    kind = jax.devices()[0].device_kind
    peak, bw = PEAK_FLOPS.get(kind), HBM_BW.get(kind)
    peak_s = f"{peak / 1e12:.0f} TFLOP/s" if peak else "unknown"
    bw_s = f"{bw / 1e9:.0f} GB/s" if bw else "unknown"
    print(f"# device: {kind}  peak={peak_s}  hbm={bw_s}")

    def conv_pipe(fused, dtype=jnp.bfloat16):
        pipe = build_conv_pipeline(conf, filters, whitener, fused=fused)
        if fused:
            pipe.nodes[0].activation_dtype = dtype
        return pipe

    ref = np.asarray(jax.jit(conv_pipe(True).__call__)(batch))
    cases = [
        ("unfused_xla_f32", conv_pipe(False)),
        ("fused_bf16_SHIPPED", conv_pipe(True)),
        ("fused_f32_exact", conv_pipe(True, jnp.float32)),
    ]
    for name, pipe in cases:
        j = jax.jit(pipe.__call__)
        got = np.asarray(j(batch))
        err = float(np.max(np.abs(got - ref)) / np.max(np.abs(ref)))
        per = timed_chain_auto(pipe.__call__, batch, chain_len=64)
        fl, by = compiled_cost(j, batch)
        rec = {
            "case": name,
            "images_per_sec": round(1024 / per, 1),
            "tflops": round(fl / per / 1e12, 2) if fl else None,
            "bytes_per_img": round(by / 1024) if by else None,
            "rel_err_vs_shipped": float(f"{err:.2e}"),
        }
        if fl and by and peak and bw:
            intensity = fl / by
            rec["fraction_of_ceiling"] = round(
                (fl / per) / min(intensity * bw, peak), 3
            )
        print(json.dumps(rec))


if __name__ == "__main__":
    main()
