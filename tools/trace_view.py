#!/usr/bin/env python
"""Summarize a keystone trace file (core.trace output).

Reads a Chrome trace_event JSON (``KEYSTONE_TRACE=out.json`` / ``--trace``)
or a JSONL event log (``*.jsonl``) and prints:

* per-stage totals — spans aggregated by name (count, total/mean/max ms),
  sorted by total time;
* the top-k individual spans by duration;
* instant-event summaries (fault counts by kind, HBM admission decisions);
* streaming-ingest overlap efficiency recomputed FROM span intervals:
  ``max(decode_busy, consume_busy) / wall`` over the ``ingest.decode`` /
  ``ingest.consume`` spans — the same quantity the bench ``e2e`` section
  derives from three separate rate passes, here read off one timeline
  (decode busy time is the union of the parallel decode lanes' intervals).

Usage:
    python tools/trace_view.py /tmp/t.json [--top 10]
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict


def load_events(path: str) -> list:
    """Events from a Chrome trace_event JSON or a JSONL event log."""
    with open(path) as f:
        if path.endswith(".jsonl"):
            return [json.loads(line) for line in f if line.strip()]
        doc = json.load(f)
    if isinstance(doc, dict):
        return doc.get("traceEvents", [])
    return doc  # bare event array — also valid Chrome format


def spans(events: list) -> list:
    return [e for e in events if e.get("ph") == "X"]


def instants(events: list) -> list:
    return [e for e in events if e.get("ph") == "i"]


def per_stage(events: list) -> dict:
    """name -> {count, total_ms, mean_ms, max_ms}, insertion = total desc."""
    agg: dict = defaultdict(lambda: {"count": 0, "total_us": 0.0, "max_us": 0.0})
    for ev in spans(events):
        a = agg[ev["name"]]
        a["count"] += 1
        a["total_us"] += float(ev.get("dur", 0.0))
        a["max_us"] = max(a["max_us"], float(ev.get("dur", 0.0)))
    out = {}
    for name, a in sorted(
        agg.items(), key=lambda kv: kv[1]["total_us"], reverse=True
    ):
        out[name] = {
            "count": a["count"],
            "total_ms": round(a["total_us"] / 1e3, 3),
            "mean_ms": round(a["total_us"] / a["count"] / 1e3, 3),
            "max_ms": round(a["max_us"] / 1e3, 3),
        }
    return out


def top_spans(events: list, k: int = 10) -> list:
    return sorted(
        spans(events), key=lambda e: float(e.get("dur", 0.0)), reverse=True
    )[:k]


def _union_us(intervals: list) -> float:
    """Total covered microseconds of possibly-overlapping [t0, t1) spans —
    parallel decode lanes count wall coverage once, not per thread."""
    total = 0.0
    end = float("-inf")
    for t0, t1 in sorted(intervals):
        if t1 <= end:
            continue
        total += t1 - max(t0, end)
        end = t1
    return total


def overlap_from_spans(events: list) -> dict | None:
    """Streaming-ingest overlap efficiency recomputed from one timeline.

    ``decode_busy`` = union of ``ingest.decode`` span intervals (the wall
    time during which at least one decoder thread was decoding — the
    producer-side ceiling); ``consume_busy`` = union of ``ingest.consume``
    spans (the consumer's featurize time); ``wall`` spans first ingest
    event to last.  A perfectly overlapped pipeline has
    ``wall ≈ max(decode_busy, consume_busy)``, so

        overlap_efficiency = max(decode_busy, consume_busy) / wall

    — the span-interval form of the bench's ``e2e / min(decode_rate,
    featurize_rate)``.  Returns None when the trace has no ingest spans.
    """
    decode, consume, all_ingest = [], [], []
    for ev in spans(events):
        iv = (float(ev["ts"]), float(ev["ts"]) + float(ev.get("dur", 0.0)))
        if ev["name"] in ("ingest.decode", "ingest.entropy_decode"):
            # entropy_decode is the device-decode path's producer-side
            # work (ops.jpeg_device): same lane, same ceiling semantics
            decode.append(iv)
        elif ev["name"] == "ingest.consume":
            consume.append(iv)
        if ev.get("cat") == "ingest":
            all_ingest.append(iv)
    if not decode or not consume:
        return None
    wall = max(t1 for _, t1 in all_ingest) - min(t0 for t0, _ in all_ingest)
    decode_busy = _union_us(decode)
    consume_busy = _union_us(consume)
    return {
        "decode_busy_ms": round(decode_busy / 1e3, 3),
        "consume_busy_ms": round(consume_busy / 1e3, 3),
        "wall_ms": round(wall / 1e3, 3),
        "overlap_efficiency": round(
            max(decode_busy, consume_busy) / wall, 3
        ) if wall > 0 else None,
        "decode_spans": len(decode),
        "consume_spans": len(consume),
    }


def instant_summary(events: list) -> dict:
    """Counts of instant events: faults by kind, admissions by verdict."""
    out: dict = {"faults": defaultdict(int), "hbm_admission": defaultdict(int)}
    for ev in instants(events):
        args = ev.get("args", {})
        if ev["name"] == "fault":
            out["faults"][args.get("kind", "?")] += 1
        elif ev["name"] == "hbm_admission":
            key = "admitted" if args.get("admitted") else "denied"
            out["hbm_admission"][key] += 1
    return {k: dict(v) for k, v in out.items() if v}


def summarize(path: str, top: int = 10) -> str:
    events = load_events(path)
    lines = [f"# {path}: {len(events)} events"]

    stages = per_stage(events)
    lines.append("")
    lines.append("## per-stage totals (spans aggregated by name)")
    lines.append(f"{'name':<40} {'count':>6} {'total_ms':>12} {'mean_ms':>10} {'max_ms':>10}")
    for name, a in stages.items():
        lines.append(
            f"{name:<40} {a['count']:>6} {a['total_ms']:>12.3f} "
            f"{a['mean_ms']:>10.3f} {a['max_ms']:>10.3f}"
        )

    lines.append("")
    lines.append(f"## top {top} spans by duration")
    for ev in top_spans(events, top):
        err = ev.get("args", {}).get("error")
        lines.append(
            f"{ev['name']:<40} {float(ev.get('dur', 0.0)) / 1e3:>10.3f} ms "
            f"tid={ev.get('tid')}" + (f" ERROR={err}" if err else "")
        )

    inst = instant_summary(events)
    if inst:
        lines.append("")
        lines.append("## instants")
        for group, counts in inst.items():
            lines.append(f"{group}: " + ", ".join(
                f"{k}={v}" for k, v in sorted(counts.items())
            ))

    overlap = overlap_from_spans(events)
    if overlap is not None:
        lines.append("")
        lines.append("## ingest overlap (recomputed from span intervals)")
        lines.append(
            f"decode busy {overlap['decode_busy_ms']} ms "
            f"({overlap['decode_spans']} spans), "
            f"consume busy {overlap['consume_busy_ms']} ms "
            f"({overlap['consume_spans']} spans), "
            f"wall {overlap['wall_ms']} ms -> "
            f"overlap_efficiency {overlap['overlap_efficiency']}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("trace_view")
    p.add_argument("path", help="trace file (.json Chrome format or .jsonl)")
    p.add_argument("--top", type=int, default=10, help="top-k spans to list")
    a = p.parse_args(argv)
    print(summarize(a.path, a.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
