#!/usr/bin/env python
"""Summarize a keystone trace file (core.trace output).

Reads a Chrome trace_event JSON (``KEYSTONE_TRACE=out.json`` / ``--trace``)
or a JSONL event log (``*.jsonl``) and prints:

* per-stage totals — spans aggregated by name (count, total/mean/max ms),
  sorted by total time;
* the top-k individual spans by duration;
* instant-event summaries (fault counts by kind, HBM admission decisions);
* streaming-ingest overlap efficiency recomputed FROM span intervals:
  ``max(decode_busy, consume_busy) / wall`` over the ``ingest.decode`` /
  ``ingest.consume`` spans — the same quantity the bench ``e2e`` section
  derives from three separate rate passes, here read off one timeline
  (decode busy time is the union of the parallel decode lanes' intervals).

Usage:
    python tools/trace_view.py /tmp/t.json [--top 10]
    python tools/trace_view.py server.json --stitch client.jsonl

``--stitch`` treats the positional path as a wire SERVER's trace and
merges it with a client trace (``tools/serve_client.py --trace``) into one
cross-process request waterfall joined by wire rid: per request, client
latency decomposes into network time (what the server never saw) plus the
server's own queue / H2D / device-wait / execute / D2H phases.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict


def load_events(path: str) -> list:
    """Events from a Chrome trace_event JSON or a JSONL event log."""
    with open(path) as f:
        if path.endswith(".jsonl"):
            return [json.loads(line) for line in f if line.strip()]
        doc = json.load(f)
    if isinstance(doc, dict):
        return doc.get("traceEvents", [])
    return doc  # bare event array — also valid Chrome format


def spans(events: list) -> list:
    return [e for e in events if e.get("ph") == "X"]


def instants(events: list) -> list:
    return [e for e in events if e.get("ph") == "i"]


def per_stage(events: list) -> dict:
    """name -> {count, total_ms, mean_ms, max_ms}, insertion = total desc."""
    agg: dict = defaultdict(lambda: {"count": 0, "total_us": 0.0, "max_us": 0.0})
    for ev in spans(events):
        a = agg[ev["name"]]
        a["count"] += 1
        a["total_us"] += float(ev.get("dur", 0.0))
        a["max_us"] = max(a["max_us"], float(ev.get("dur", 0.0)))
    out = {}
    for name, a in sorted(
        agg.items(), key=lambda kv: kv[1]["total_us"], reverse=True
    ):
        out[name] = {
            "count": a["count"],
            "total_ms": round(a["total_us"] / 1e3, 3),
            "mean_ms": round(a["total_us"] / a["count"] / 1e3, 3),
            "max_ms": round(a["max_us"] / 1e3, 3),
        }
    return out


def top_spans(events: list, k: int = 10) -> list:
    return sorted(
        spans(events), key=lambda e: float(e.get("dur", 0.0)), reverse=True
    )[:k]


def _union_us(intervals: list) -> float:
    """Total covered microseconds of possibly-overlapping [t0, t1) spans —
    parallel decode lanes count wall coverage once, not per thread."""
    total = 0.0
    end = float("-inf")
    for t0, t1 in sorted(intervals):
        if t1 <= end:
            continue
        total += t1 - max(t0, end)
        end = t1
    return total


def overlap_from_spans(events: list) -> dict | None:
    """Streaming-ingest overlap efficiency recomputed from one timeline.

    ``decode_busy`` = union of ``ingest.decode`` span intervals (the wall
    time during which at least one decoder thread was decoding — the
    producer-side ceiling); ``consume_busy`` = union of ``ingest.consume``
    spans (the consumer's featurize time); ``wall`` spans first ingest
    event to last.  A perfectly overlapped pipeline has
    ``wall ≈ max(decode_busy, consume_busy)``, so

        overlap_efficiency = max(decode_busy, consume_busy) / wall

    — the span-interval form of the bench's ``e2e / min(decode_rate,
    featurize_rate)``.  Returns None when the trace has no ingest spans.
    """
    decode, consume, all_ingest = [], [], []
    for ev in spans(events):
        iv = (float(ev["ts"]), float(ev["ts"]) + float(ev.get("dur", 0.0)))
        if ev["name"] in ("ingest.decode", "ingest.entropy_decode"):
            # entropy_decode is the device-decode path's producer-side
            # work (ops.jpeg_device): same lane, same ceiling semantics
            decode.append(iv)
        elif ev["name"] == "ingest.consume":
            consume.append(iv)
        if ev.get("cat") == "ingest":
            all_ingest.append(iv)
    if not decode or not consume:
        return None
    wall = max(t1 for _, t1 in all_ingest) - min(t0 for t0, _ in all_ingest)
    decode_busy = _union_us(decode)
    consume_busy = _union_us(consume)
    return {
        "decode_busy_ms": round(decode_busy / 1e3, 3),
        "consume_busy_ms": round(consume_busy / 1e3, 3),
        "wall_ms": round(wall / 1e3, 3),
        "overlap_efficiency": round(
            max(decode_busy, consume_busy) / wall, 3
        ) if wall > 0 else None,
        "decode_spans": len(decode),
        "consume_spans": len(consume),
    }


def stitch(server_events: list, client_events: list) -> dict:
    """Cross-process request waterfall: join a wire CLIENT's trace
    (``tools/serve_client.py --trace``: ``client.submit``/``client.answer``
    instants keyed by wire rid + the ``client.clock`` offset meta) with
    the SERVER's trace (``wire.request``/``wire.response`` instants tying
    wire rids to serve request ids; ``serve.request`` spans carrying the
    per-phase decomposition) into one per-request row set decomposing

        client latency = network + server wire time
        server wire time ≈ queue-wait + H2D + device-wait + execute + D2H
                           + answer

    ``network_ms`` is the residual the server never saw (socket + frame
    parse + responder queue on both ends).  Rows join by wire rid — the id
    both processes logged — and the clock offset is reported so the two
    timelines can also be aligned absolutely."""
    c_submit: dict = {}
    c_answer: dict = {}
    clock = None
    for ev in instants(client_events):
        args = ev.get("args", {})
        if ev["name"] == "client.submit":
            c_submit[args.get("rid")] = ev
        elif ev["name"] == "client.answer":
            c_answer[args.get("rid")] = ev
        elif ev["name"] == "client.clock":
            clock = args
    # Server events keyed PER CONNECTION: wire rids are per-connection
    # counters starting at 1, so a server trace holding several clients
    # has colliding rids — joining on rid alone would pair this client's
    # latencies with another connection's phases.
    req_by_conn: dict = {}  # conn -> {rid: wire.request args}
    resp_by_conn: dict = {}
    serve_phases: dict = {}
    for ev in server_events:
        args = ev.get("args", {})
        if ev.get("ph") == "i" and ev.get("name") == "wire.request":
            req_by_conn.setdefault(args.get("conn"), {})[
                args.get("wire_rid")
            ] = args
        elif ev.get("ph") == "i" and ev.get("name") == "wire.response":
            resp_by_conn.setdefault(args.get("conn"), {})[
                args.get("wire_rid")
            ] = args
        elif ev.get("ph") == "X" and ev.get("name") == "serve.request":
            serve_phases[args.get("request_id")] = args

    # Pick THIS client's connection: most answered-rid overlap, with
    # matching trace-context span ids (traced clients send their span on
    # every request, and the server records it) breaking the tie — two
    # identical-window clients overlap on rids but not on span mapping.
    def conn_score(reqs: dict):
        overlap = sum(1 for rid in c_answer if rid in reqs)
        spans = sum(
            1
            for rid, ans in c_answer.items()
            if rid in reqs
            and reqs[rid].get("client_span") is not None
            and reqs[rid].get("client_span")
            == ans.get("args", {}).get("span")
        )
        return (spans, overlap)

    conn = (
        max(req_by_conn, key=lambda c: conn_score(req_by_conn[c]))
        if req_by_conn
        else None
    )
    s_request = req_by_conn.get(conn, {})
    s_response = resp_by_conn.get(conn, {})

    rows = []
    for rid in sorted(set(c_answer) & set(s_request)):
        ans = c_answer[rid]
        args = ans.get("args", {})
        client_ms = float(args.get("ms", 0.0))
        sreq = s_request[rid]
        sresp = s_response.get(rid, {})
        server_ms = float(sresp.get("ms", 0.0))
        row = {
            "wire_rid": rid,
            "client_span": sreq.get("client_span"),
            "request_id": sreq.get("request_id"),
            "client_ms": round(client_ms, 3),
            "server_ms": round(server_ms, 3),
            # What the server never saw: socket transit + framing + the
            # responder/reader queues on both sides.
            "network_ms": round(client_ms - server_ms, 3),
        }
        phases = serve_phases.get(sreq.get("request_id"))
        if phases:
            for key in (
                "queue_wait_ms", "h2d_ms", "device_wait_ms", "execute_ms",
                "d2h_ms", "answer_ms", "pad_overhead_ms",
            ):
                if key in phases:
                    row[key] = phases[key]
        rows.append(row)

    def mean(key: str):
        vals = [r[key] for r in rows if isinstance(r.get(key), (int, float))]
        return round(sum(vals) / len(vals), 3) if vals else None

    return {
        "requests": len(rows),
        # Submits exceed answers when RETRY_AFTER resubmits happened —
        # the backpressure the waterfall's latencies already include.
        "client_submits": len(c_submit),
        "client_requests": len(c_answer),
        "server_requests": len(s_request),
        "server_connections": len(req_by_conn),
        "connection": conn,
        "clock": clock,
        "mean": {
            k: mean(k)
            for k in (
                "client_ms", "server_ms", "network_ms", "queue_wait_ms",
                "h2d_ms", "device_wait_ms", "execute_ms", "d2h_ms",
                "answer_ms",
            )
        },
        "rows": rows,
    }


def stitch_summary(server_path: str, client_path: str, top: int = 10) -> str:
    merged = stitch(load_events(server_path), load_events(client_path))
    lines = [
        f"# stitched waterfall: {merged['requests']} request(s) joined "
        f"({client_path} x {server_path})"
    ]
    if merged.get("clock"):
        lines.append(f"# clock: {merged['clock']}")
    if merged.get("server_connections", 0) > 1:
        lines.append(
            f"# server trace holds {merged['server_connections']} "
            f"connection(s); joined against conn {merged['connection']}"
        )
    m = merged["mean"]
    lines.append(
        f"# mean: client {m['client_ms']}ms = network {m['network_ms']}ms "
        f"+ server {m['server_ms']}ms (queue {m['queue_wait_ms']}ms, "
        f"device {m['execute_ms']}ms)"
    )
    cols = (
        "wire_rid", "client_ms", "network_ms", "server_ms",
        "queue_wait_ms", "h2d_ms", "device_wait_ms", "execute_ms", "d2h_ms",
    )
    lines.append(" ".join(f"{c:>14}" for c in cols))
    for row in merged["rows"][:top]:
        lines.append(
            " ".join(f"{row.get(c, ''):>14}" for c in cols)
        )
    if len(merged["rows"]) > top:
        lines.append(f"... {len(merged['rows']) - top} more row(s)")
    return "\n".join(lines)


def instant_summary(events: list) -> dict:
    """Counts of instant events: faults by kind, admissions by verdict."""
    out: dict = {"faults": defaultdict(int), "hbm_admission": defaultdict(int)}
    for ev in instants(events):
        args = ev.get("args", {})
        if ev["name"] == "fault":
            out["faults"][args.get("kind", "?")] += 1
        elif ev["name"] == "hbm_admission":
            key = "admitted" if args.get("admitted") else "denied"
            out["hbm_admission"][key] += 1
    return {k: dict(v) for k, v in out.items() if v}


def summarize(path: str, top: int = 10) -> str:
    events = load_events(path)
    lines = [f"# {path}: {len(events)} events"]

    stages = per_stage(events)
    lines.append("")
    lines.append("## per-stage totals (spans aggregated by name)")
    lines.append(f"{'name':<40} {'count':>6} {'total_ms':>12} {'mean_ms':>10} {'max_ms':>10}")
    for name, a in stages.items():
        lines.append(
            f"{name:<40} {a['count']:>6} {a['total_ms']:>12.3f} "
            f"{a['mean_ms']:>10.3f} {a['max_ms']:>10.3f}"
        )

    lines.append("")
    lines.append(f"## top {top} spans by duration")
    for ev in top_spans(events, top):
        err = ev.get("args", {}).get("error")
        lines.append(
            f"{ev['name']:<40} {float(ev.get('dur', 0.0)) / 1e3:>10.3f} ms "
            f"tid={ev.get('tid')}" + (f" ERROR={err}" if err else "")
        )

    inst = instant_summary(events)
    if inst:
        lines.append("")
        lines.append("## instants")
        for group, counts in inst.items():
            lines.append(f"{group}: " + ", ".join(
                f"{k}={v}" for k, v in sorted(counts.items())
            ))

    overlap = overlap_from_spans(events)
    if overlap is not None:
        lines.append("")
        lines.append("## ingest overlap (recomputed from span intervals)")
        lines.append(
            f"decode busy {overlap['decode_busy_ms']} ms "
            f"({overlap['decode_spans']} spans), "
            f"consume busy {overlap['consume_busy_ms']} ms "
            f"({overlap['consume_spans']} spans), "
            f"wall {overlap['wall_ms']} ms -> "
            f"overlap_efficiency {overlap['overlap_efficiency']}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("trace_view")
    p.add_argument("path", help="trace file (.json Chrome format or .jsonl)")
    p.add_argument("--top", type=int, default=10, help="top-k spans to list")
    p.add_argument(
        "--stitch", default=None, metavar="CLIENT.jsonl",
        help="treat PATH as the SERVER trace and merge it with this "
        "client trace (serve_client.py --trace) into one request "
        "waterfall joined by wire rid",
    )
    a = p.parse_args(argv)
    if a.stitch:
        print(stitch_summary(a.path, a.stitch, a.top))
        return 0
    print(summarize(a.path, a.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
