#!/usr/bin/env python
"""Fleet observability viewer (ISSUE 20): the live fleet table and the
clock-aligned incident-bundle timeline as human tables.

Three input modes (any combination):

* ``--endpoints host:port,host:port`` — one-shot scrape of live fleet
  members (every :class:`~keystone_tpu.core.wire.WireServer` answers the
  obs frames): member table, fleet counter totals, pooled-window
  histogram summaries, fleet health verdict.
* ``--statusz fleet.json`` — render a saved fleet-statusz snapshot
  (``keystone.fleet_statusz/1``, e.g. from a bench record or a collector
  dump) through the same tables.
* ``--incident incident_*.json`` — render an incident bundle
  (``keystone.incident/1``): the trigger, the per-member ring inventory
  (clock offset, rtt, event counts), and the merged timeline — every
  member's flight events on the COLLECTOR's clock, interleaved in true
  order (``--events N`` bounds the tail shown, default 40).

Usage:
    python tools/fleet_view.py --endpoints 127.0.0.1:7070,127.0.0.1:7071
    python tools/fleet_view.py --incident incident_obs_member_lost_12_0.json

Exit status: 0 = rendered, 2 = nothing renderable (no input given, an
unreadable file, or an unreachable fleet).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.health_view import _fmt, _table  # noqa: E402


def render_fleet_statusz(snap: dict) -> str:
    """The fleet tables for one merged snapshot (collector
    ``fleet_statusz()`` / ``keystone.fleet_statusz/1``)."""
    parts: list[str] = []
    members = snap.get("members") or {}
    if members:
        rows = []
        for key in sorted(members):
            m = members[key]
            rows.append([
                key,
                _fmt(m.get("rank")),
                "up" if m.get("alive") else "LOST",
                _fmt(m.get("pid")),
                _fmt(m.get("scrapes")),
                _fmt(m.get("failures")),
                _fmt(m.get("offset_us"), 6),
                _fmt(m.get("rtt_us"), 4),
            ])
        parts.append("== fleet members ==\n" + _table(
            ["member", "rank", "state", "pid", "scrapes", "failures",
             "clock_offset_us", "rtt_us"],
            rows,
        ))
    verdict = (
        f"fleet '{snap.get('label', '-')}': "
        f"{snap.get('alive', 0)}/{len(members) or snap.get('alive', 0)} "
        f"member(s) up"
        + (" — DEGRADED" if snap.get("degraded") else "")
        + f" (scrapes: {snap.get('scrapes', 0)})"
    )
    parts.append(verdict)
    counters = dict(snap.get("counters") or {})
    for k, v in (snap.get("faults") or {}).items():
        counters.setdefault(k, v)
    if counters:
        rows = [[k, _fmt(counters[k])] for k in sorted(counters)]
        parts.append("== fleet counters (summed) ==\n" + _table(
            ["counter", "total"], rows,
        ))
    hists = snap.get("histograms") or {}
    if hists:
        rows = []
        for name in sorted(hists):
            h = hists[name]
            rows.append([
                name,
                _fmt(h.get("count")),
                _fmt(h.get("mean")),
                _fmt(h.get("p50")),
                _fmt(h.get("p90")),
                _fmt(h.get("p99")),
                _fmt(h.get("max")),
            ])
        parts.append(
            "== fleet latency (pooled windows, not averaged "
            "percentiles) ==\n"
            + _table(
                ["histogram", "count", "mean", "p50", "p90", "p99", "max"],
                rows,
            )
        )
    slo = snap.get("slo") or {}
    if slo:
        rows = []
        for label in sorted(slo):
            s = slo[label]
            w = s.get("window", {})
            rows.append([
                label,
                _fmt(s.get("slo_ms")),
                _fmt(s.get("budget")),
                _fmt(w.get("count")),
                _fmt(w.get("violations")),
                _fmt(w.get("burn_rate")),
            ])
        parts.append("== fleet SLO (pooled error budget) ==\n" + _table(
            ["slo", "slo_ms", "budget", "window_n", "violations",
             "burn_rate"],
            rows,
        ))
    return "\n\n".join(parts)


def render_incident(doc: dict, max_events: int = 40) -> str:
    """The incident bundle: trigger, per-member ring inventory, and the
    tail of the merged clock-aligned timeline."""
    parts: list[str] = []
    trig = doc.get("trigger") or {}
    parts.append(
        f"incident {doc.get('schema', '?')}  "
        f"trigger={trig.get('kind', '?')}  member={trig.get('member', '-')}\n"
        f"  {trig.get('detail', '')}".rstrip()
    )
    members = doc.get("members") or {}
    if members:
        rows = []
        for key in sorted(members):
            m = members[key]
            rows.append([
                key,
                _fmt(m.get("rank")),
                _fmt(m.get("pid")),
                _fmt(m.get("offset_us"), 6),
                _fmt(m.get("rtt_us"), 4),
                _fmt(m.get("events")),
            ])
        parts.append("== member flight rings ==\n" + _table(
            ["member", "rank", "pid", "clock_offset_us", "rtt_us",
             "events"],
            rows,
        ))
    missing = doc.get("missing") or []
    if missing:
        parts.append(
            "missing (unreachable within the capture window): "
            + ", ".join(missing)
        )
    events = [
        e for e in (doc.get("events") or [])
        if isinstance(e.get("ts"), (int, float))
    ]
    if events:
        tail = events[-max_events:]
        rows = []
        for e in tail:
            args = e.get("args") or {}
            detail = ", ".join(
                f"{k}={v}" for k, v in list(args.items())[:3]
            )
            rows.append([
                f"{e['ts'] / 1e6:.6f}",
                str(e.get("member", "-")),
                str(e.get("ph", "-")),
                str(e.get("name", "-"))[:40],
                _fmt(e.get("dur")),
                detail[:60],
            ])
        parts.append(
            f"== clock-aligned timeline (last {len(tail)} of "
            f"{len(events)} events, collector seconds) ==\n"
            + _table(["t_s", "member", "ph", "event", "dur_us", "detail"],
                     rows)
        )
    return "\n\n".join(parts)


def scrape_endpoints(endpoints: str, timeout: float = 10.0) -> dict:
    """One-shot collector over ``host:port,host:port`` — scrape, merge,
    return the fleet statusz snapshot."""
    from keystone_tpu.core import fleetobs

    col = fleetobs.FleetCollector(
        [e.strip() for e in endpoints.split(",") if e.strip()],
        interval_s=3600.0, label="fleet_view", timeout=timeout,
    )
    with col:
        return col.scrape_once()


def main(argv=None) -> int:
    p = argparse.ArgumentParser("fleet_view")
    p.add_argument(
        "--endpoints",
        help="comma-separated host:port members to scrape one-shot",
    )
    p.add_argument(
        "--statusz", help="saved fleet-statusz JSON to render"
    )
    p.add_argument(
        "--incident", help="incident bundle JSON to render as a timeline"
    )
    p.add_argument(
        "--events", type=int, default=40,
        help="max timeline events shown from an incident bundle",
    )
    p.add_argument(
        "--timeout", type=float, default=10.0,
        help="per-member scrape timeout (seconds)",
    )
    a = p.parse_args(argv)
    if not (a.endpoints or a.statusz or a.incident):
        p.print_usage(sys.stderr)
        print(
            "fleet_view: need --endpoints, --statusz, or --incident",
            file=sys.stderr,
        )
        return 2
    parts: list[str] = []
    if a.endpoints:
        snap = scrape_endpoints(a.endpoints, timeout=a.timeout)
        if not snap.get("alive"):
            print(
                f"fleet_view: no member of {a.endpoints} answered",
                file=sys.stderr,
            )
            return 2
        parts.append(render_fleet_statusz(snap))
    if a.statusz:
        try:
            with open(a.statusz) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(
                f"fleet_view: cannot read {a.statusz}: {e}", file=sys.stderr
            )
            return 2
        # accept a bench/serve_bench record embedding the snapshot
        if doc.get("schema") != "keystone.fleet_statusz/1":
            for key in ("fleet_statusz", "fleet_obs"):
                inner = doc.get(key)
                if isinstance(inner, dict):
                    doc = inner.get("statusz", inner)
                    break
        parts.append(render_fleet_statusz(doc))
    if a.incident:
        try:
            with open(a.incident) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(
                f"fleet_view: cannot read {a.incident}: {e}",
                file=sys.stderr,
            )
            return 2
        if doc.get("schema") != "keystone.incident/1":
            print(
                f"fleet_view: {a.incident} is not an incident bundle "
                f"(schema {doc.get('schema')!r})",
                file=sys.stderr,
            )
            return 2
        parts.append(render_incident(doc, max_events=a.events))
    print("\n\n".join(parts))
    return 0


if __name__ == "__main__":
    sys.exit(main())
