#!/usr/bin/env python
"""Snapshot-cache administration (the operational counterpart of
trace_view.py, for core.snapshot roots).

A snapshot root (``KEYSTONE_SNAPSHOT_DIR`` / the workloads'
``--snapshotDir``) accumulates one directory per (tar, decode config,
chunking, featurizer) key, plus ``.tmp-*`` debris from crashed writes.
This tool makes that state inspectable and reclaimable:

    python tools/snapshot_admin.py ROOT list
    python tools/snapshot_admin.py ROOT inspect KEY_PREFIX
    python tools/snapshot_admin.py ROOT evict --key KEY_PREFIX
    python tools/snapshot_admin.py ROOT evict --temps        # crash debris
    python tools/snapshot_admin.py ROOT evict --invalid      # no/bad manifest
    python tools/snapshot_admin.py ROOT evict --stale --tar PATH [--batch N]

* ``list`` — every snapshot with key, mode, images, chunks, on-disk bytes,
  and committed/valid state (uncommitted temp dirs included).
* ``inspect`` — FULL shard validation of one snapshot: every shard's size
  and sha256 re-checked against the manifest (the same check the reader
  runs per chunk); violations listed.
* ``evict`` — remove by key prefix, remove uncommitted temp directories,
  remove directories with missing/invalid manifests, or remove snapshots
  STALE for a given tar (committed for the same tar file names but under
  a key that no longer matches the tar's current identity/config).

The first stdout line is a machine-readable JSON record (same
truncation-proof convention as bench.py/chaos_run.py); a short human
summary follows.  Exit status: 0 ok, 1 bad arguments/validation failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from keystone_tpu.core import snapshot as ksnap  # noqa: E402


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024
    return f"{n} B"


def _stale_dirs(root: str, tar_path: str, batch_size: int | None) -> list:
    """Committed DECODED snapshot dirs for ``tar_path``'s file names whose
    key no longer matches the tar's CURRENT identity/decode config.

    Each candidate's key is recomputed from its OWN manifest-recorded
    chunking (batch size + extra key material, written by the ingest tee),
    so a snapshot is classified stale only when its exact key can be
    recomputed and no longer matches — never because its batch size wasn't
    in a guessed probe list.  A manifest without recorded chunking is left
    alone unless ``batch_size`` supplies the missing value (refuse to
    guess on a destructive operation).  Featurized snapshots are
    deliberately excluded: their keys fold in a featurizer digest this
    tool cannot recompute, so every featurized snapshot would read as
    stale — evict those explicitly by key."""
    want_names = sorted(r["name"] for r in ksnap.tar_identity(tar_path))
    live_keys: dict = {}  # (batch, extra) -> recomputed key
    out = []
    for snap in ksnap.list_snapshots(root):
        if not snap.get("committed") or snap.get("mode") != "decoded":
            continue
        if snap.get("tar_names") != want_names:
            continue
        batch = snap.get("batch_size") or batch_size
        if not batch:
            continue  # no recorded chunking and no --batch: cannot prove stale
        ck = (int(batch), snap.get("extra"))
        if ck not in live_keys:
            live_keys[ck] = ksnap.snapshot_key(
                tar_path, batch_size=ck[0], mode="decoded", extra=ck[1]
            )
        if snap["key"] != live_keys[ck]:
            out.append(snap)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser("snapshot_admin")
    p.add_argument("root", help="snapshot root (KEYSTONE_SNAPSHOT_DIR)")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list", help="inventory every snapshot under the root")
    ins = sub.add_parser(
        "inspect", help="full shard validation (size + sha256) of one key"
    )
    ins.add_argument("key_prefix", help="snapshot key prefix (>= 4 chars)")
    ev = sub.add_parser("evict", help="remove snapshot directories")
    ev.add_argument("--key", default=None, help="evict by key prefix")
    ev.add_argument(
        "--temps", action="store_true",
        help="evict uncommitted .tmp-* directories (crash debris)",
    )
    ev.add_argument(
        "--invalid", action="store_true",
        help="evict directories with missing/invalid manifests",
    )
    ev.add_argument(
        "--stale", action="store_true",
        help="evict snapshots whose key no longer matches --tar's current "
        "identity/decode config",
    )
    ev.add_argument("--tar", default=None, help="tar path for --stale")
    ev.add_argument(
        "--batch", type=int, default=None,
        help="stream batch size for --stale key matching of snapshots "
        "whose manifest predates recorded chunking (normally unneeded: "
        "the recorded batch size is used)",
    )
    a = p.parse_args(argv)

    if a.cmd == "list":
        snaps = ksnap.list_snapshots(a.root)
        record = {
            "metric": "snapshot_admin",
            "op": "list",
            "root": a.root,
            "snapshots": snaps,
            "total_bytes": sum(s.get("bytes", 0) for s in snaps),
        }
        print(json.dumps(record), flush=True)
        if not snaps:
            print(f"# {a.root}: no snapshots")
        for s in snaps:
            if s.get("committed"):
                print(
                    f"# {s['dir']}: mode={s['mode']} images={s['images']} "
                    f"chunks={s['chunks']} {_fmt_bytes(s['bytes'])} "
                    f"key={s['key'][:16]}..."
                )
            else:
                print(
                    f"# {s['dir']}: NOT COMMITTED ({s['reason']}, "
                    f"{_fmt_bytes(s['bytes'])})"
                )
        return 0

    if a.cmd == "inspect":
        if len(a.key_prefix) < 4:
            p.error("inspect wants a key prefix of >= 4 characters")
        problems = ksnap.validate(a.root, a.key_prefix)
        record = {
            "metric": "snapshot_admin",
            "op": "inspect",
            "root": a.root,
            "key_prefix": a.key_prefix,
            "ok": not problems,
            "problems": problems,
        }
        print(json.dumps(record), flush=True)
        if problems:
            for pr in problems:
                print(f"# BAD {pr}")
        else:
            print(f"# {a.key_prefix}: every shard validates")
        return 1 if problems else 0

    # evict
    if not (a.key or a.temps or a.invalid or a.stale):
        p.error("evict wants at least one of --key/--temps/--invalid/--stale")
    if a.stale and not a.tar:
        p.error("--stale needs --tar")
    if a.key and len(a.key) < 4:
        p.error("--key wants a key prefix of >= 4 characters")
    removed = []
    if a.key or a.temps:
        removed += ksnap.evict(a.root, key_prefix=a.key, temps=a.temps)
    if a.invalid:
        # Exact directory names: an invalid dir has no trustworthy key to
        # prefix-match on (and a garbage-derived prefix could sweep up
        # valid snapshots).
        bad = [
            s["dir"]
            for s in ksnap.list_snapshots(a.root)
            if not s.get("committed") and not s["dir"].startswith(".tmp-")
        ]
        if bad:
            removed += ksnap.evict(a.root, names=bad)
    if a.stale:
        for s in _stale_dirs(a.root, a.tar, a.batch):
            removed += ksnap.evict(a.root, key_prefix=s["key"])
    record = {
        "metric": "snapshot_admin",
        "op": "evict",
        "root": a.root,
        "removed": removed,
    }
    print(json.dumps(record), flush=True)
    print(f"# evicted {len(removed)} director{'y' if len(removed) == 1 else 'ies'}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
