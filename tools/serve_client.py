#!/usr/bin/env python
"""Reference wire-protocol client for the keystone serving front-end
(core.wire), and the client process ``tools/serve_bench.py --wire`` spawns.

Connects to a live :class:`~keystone_tpu.core.wire.WireServer`, drives a
seeded request stream with a bounded pipeline window, honors RETRY_AFTER
backpressure (sleep the hint, resubmit — the retried request keeps its
ORIGINAL submit timestamp, so reported latency includes the pushback), and
reports per-request latency percentiles.

The first stdout line is a machine-readable JSON record (the bench.py
truncation-proof convention); human-readable lines follow.

Usage:
    python tools/serve_client.py --port 9123 --shape 16 --requests 64
    python tools/serve_client.py --shape 32x32x3 --requests 16  # env port

The minimal protocol loop, for rolling your own client::

    from keystone_tpu.core.wire import WireClient
    with WireClient(port=9123) as client:
        answer = client.predict(request)           # one request
        answers = client.predict_many(batch, window=8)  # pipelined
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# A client never needs an accelerator — and on TPU hosts it must NOT race
# the serving process for the device lock.  Set before any jax import.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def parse_shape(raw: str) -> tuple:
    """``16`` / ``32x32x3`` / ``scalar`` -> a shape tuple."""
    raw = raw.strip()
    if raw in ("", "scalar"):
        return ()
    return tuple(int(tok) for tok in raw.replace(",", "x").split("x") if tok)


def _percentile(sorted_ms, q):
    if not sorted_ms:
        return 0.0
    return float(sorted_ms[min(len(sorted_ms) - 1, int(q * len(sorted_ms)))])


def drive(client, requests, window: int, timeout: float,
          traced: bool = False, trace_context: bool | None = None) -> dict:
    """Pipelined open-loop drive with per-request latency accounting:
    ``window`` outstanding wire requests; RETRY_AFTER resubmits keep the
    original submit time (backpressure IS latency the client felt).

    ``traced=True`` emits the client's OWN trace events (``client.submit``
    / ``client.answer`` instants keyed by wire rid) — the client half
    ``tools/trace_view.py --stitch`` joins with the server trace into one
    request waterfall.  ``trace_context`` additionally sends each request
    as a T_REQUEST_TRACED frame carrying the client span id; pass False
    against a pre-handshake server (it would answer the unknown frame
    type with an ERROR) — the caller gates it on ``clock_sync()``
    succeeding."""
    if trace_context is None:
        trace_context = traced
    from keystone_tpu.core import trace as ktrace
    from keystone_tpu.core import wire

    n = len(requests)
    t_submit: dict[int, tuple[int, float]] = {}  # rid -> (index, t0)
    latencies = [0.0] * n
    retries = 0
    done = 0
    next_i = 0
    t_start = time.perf_counter()
    end = t_start + timeout
    while done < n:
        if time.perf_counter() >= end:
            raise TimeoutError(f"{done}/{n} answered within {timeout}s")
        while next_i < n and len(t_submit) < max(1, window):
            rid = client.submit(
                requests[next_i],
                client_span=next_i if trace_context else None,
            )
            if traced:
                ktrace.instant("client.submit", rid=rid, span=next_i)
            t_submit[rid] = (next_i, time.perf_counter())
            next_i += 1
        reply = client.read()
        if reply.type == wire.T_RESPONSE:
            idx, t0 = t_submit.pop(reply.request_id)
            latencies[idx] = (time.perf_counter() - t0) * 1e3
            if traced:
                ktrace.instant(
                    "client.answer", rid=reply.request_id, span=idx,
                    ms=round(latencies[idx], 3),
                )
            done += 1
        elif reply.type == wire.T_RETRY_AFTER:
            idx, t0 = t_submit.pop(reply.request_id)
            retries += 1
            time.sleep(min(max(reply.retry_after_s or 0.0, 0.0), 1.0))
            rid = client.submit(
                requests[idx], client_span=idx if trace_context else None
            )
            if traced:
                ktrace.instant("client.submit", rid=rid, span=idx, retry=True)
            t_submit[rid] = (idx, t0)  # latency spans the pushback too
        elif reply.type == wire.T_ERROR:
            raise wire.WireRemoteError(reply.etype, reply.message or "")
    wall = time.perf_counter() - t_start
    lat = sorted(latencies)
    # Raw latencies for the merger (serve_bench --wire): exact cross-client
    # percentiles when the run fits the cap; beyond it an EVEN-STRIDE
    # sample of the sorted list (always keeping the max) — a plain [:cap]
    # prefix would ship only the FASTEST requests and bias the pooled p99
    # low, the exact tail the metric exists to watch.
    cap = 2048
    if len(lat) <= cap:
        sampled = lat
    else:
        stride = -(-len(lat) // cap)  # ceil div
        sampled = lat[::stride]
        if sampled[-1] != lat[-1]:
            sampled.append(lat[-1])
    return {
        "requests": n,
        "wall_seconds": round(wall, 4),
        "qps": round(n / wall, 2) if wall > 0 else 0.0,
        "p50_ms": round(_percentile(lat, 0.50), 3),
        "p99_ms": round(_percentile(lat, 0.99), 3),
        "max_ms": round(lat[-1], 3) if lat else 0.0,
        "retry_after": retries,
        "latencies_ms": [round(v, 3) for v in sampled],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser("serve_client")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument(
        "--port", type=int, default=None,
        help="wire server port (default: KEYSTONE_WIRE_PORT)",
    )
    p.add_argument(
        "--shape", default="16",
        help="request shape: 16, 32x32x3, or 'scalar'",
    )
    p.add_argument("--dtype", default="float32")
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--window", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeout", type=float, default=120.0)
    p.add_argument(
        "--trace", default=None, metavar="OUT.jsonl",
        help="write the client's own JSONL trace (client.submit/"
        "client.answer instants + the clock-offset handshake) for "
        "tools/trace_view.py --stitch",
    )
    a = p.parse_args(argv)

    from keystone_tpu.core import trace as ktrace
    from keystone_tpu.core.wire import WireClient

    shape = parse_shape(a.shape)
    rng = np.random.default_rng(a.seed)
    requests = rng.standard_normal((a.requests, *shape)).astype(a.dtype)

    clock = None
    if a.trace:
        ktrace.enable(a.trace)
    with WireClient(a.host, a.port, timeout=a.timeout) as client:
        rtt = client.ping()
        if a.trace:
            # Clock-offset handshake BEFORE the load: the offset meta
            # event is what lets --stitch place server spans on the
            # client's timeline (and vice versa).
            clock = client.clock_sync()
            ktrace.instant(
                "client.clock",
                **(clock if clock is not None else {"unsupported": True}),
            )
        record = drive(
            client, list(requests), a.window, a.timeout,
            traced=bool(a.trace),
            # A pre-handshake server answered the T_CLOCK probe with an
            # ERROR (clock None): it would do the same to every
            # T_REQUEST_TRACED — degrade to plain REQUESTs, keep the
            # client-side trace.
            trace_context=bool(a.trace) and clock is not None,
        )
    if a.trace:
        ktrace.flush()
        ktrace.disable()
        record["trace"] = a.trace
        record["clock_offset_us"] = (
            clock.get("offset_us") if clock else None
        )
    record.update(
        metric="serve_client",
        host=a.host,
        port=a.port,
        shape=list(shape),
        dtype=a.dtype,
        seed=a.seed,
        window=a.window,
        ping_ms=round(rtt * 1e3, 3),
    )
    # Machine-readable record FIRST, flushed (the bench.py convention).
    print(json.dumps(record), flush=True)
    print(
        f"# serve_client pid {os.getpid()}: {record['requests']} requests "
        f"shape {a.shape} -> p50 {record['p50_ms']}ms, p99 "
        f"{record['p99_ms']}ms, {record['qps']} QPS, "
        f"{record['retry_after']} retry-after"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
