#!/usr/bin/env python
"""Bench regression observatory: diff two bench rounds (``BENCH_r*.json``)
with per-metric thresholds and a machine-readable verdict.

Each bench round is one JSON record (the first stdout line of ``bench.py``,
usually stored wrapped by the driver as ``{"parsed": <record>, "tail": ...}``).
This tool compares a curated set of throughput/latency/efficiency metrics
between a BASE round and a CANDIDATE round and judges each against a
relative threshold in its good direction — a ``higher``-is-better metric
regresses when ``cand < base * (1 - threshold)``; a ``lower``-is-better
metric when ``cand > base * (1 + threshold)``.  Thresholds default to the
observed run-to-run spread of the shared tunneled bench chip (~10-15%)
plus margin; override any metric with ``--metric``.

The first stdout line is the machine-readable JSON verdict (the bench.py
truncation-proof convention); human-readable lines follow.  Exit status:
0 = ok (no regressions), 1 = regression(s), 2 = incomparable (a record is
missing/unparsed — BENCH_r05's truncated ``parsed: null`` is the canonical
case — or no metric exists in both rounds).

Usage:
    python tools/bench_diff.py BENCH_r04.json BENCH_r05.json
    python tools/bench_diff.py BENCH_r03.json BENCH_r04.json \
        --metric value=0.10 --metric extra_metrics.jpeg_decode.speedup=0.5

``bench.py`` runs the same comparison in-process at the end of every round
(the ``bench_diff`` section of its record) against the newest usable prior
round, so the observatory rides along on hardware rounds automatically.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

#: (dotted path, good direction, relative threshold).  Curated rather than
#: exhaustive: these are the metrics whose movement means something across
#: rounds; everything else in the record is context, not a pass/fail bar.
DEFAULT_METRICS: tuple = (
    ("value", "higher", 0.15),
    ("mfu", "higher", 0.15),
    ("solve_seconds", "lower", 0.30),
    ("solve_device_seconds", "lower", 0.30),
    ("extra_metrics.imagenet_fv_featurize.value", "higher", 0.20),
    ("extra_metrics.imagenet_fv_featurize.mfu", "higher", 0.20),
    ("extra_metrics.jpeg_decode.serial_images_per_sec", "higher", 0.25),
    ("extra_metrics.jpeg_decode.threaded_images_per_sec", "higher", 0.25),
    (
        "extra_metrics.jpeg_decode.snapshot.warm_read_images_per_sec",
        "higher", 0.30,
    ),
    # ISSUE 13: the three-path decode ledger (host pool vs device decode
    # vs warm device-snapshot DMA).  Rates are higher-is-better; overlap
    # efficiency regressing means a path's decode/featurize pipelining
    # broke; the device path's golden parity is lower-is-better (a LARGER
    # divergence from the host decoder is a correctness drift, not noise).
    (
        "extra_metrics.jpeg_decode.by_path.host_pool.images_per_sec",
        "higher", 0.30,
    ),
    (
        "extra_metrics.jpeg_decode.by_path.device.images_per_sec",
        "higher", 0.30,
    ),
    (
        "extra_metrics.jpeg_decode.by_path.device_snapshot_warm.images_per_sec",
        "higher", 0.30,
    ),
    (
        "extra_metrics.jpeg_decode.by_path.host_pool.overlap_efficiency",
        "higher", 0.15,
    ),
    (
        "extra_metrics.jpeg_decode.by_path.device.overlap_efficiency",
        "higher", 0.15,
    ),
    (
        "extra_metrics.jpeg_decode.by_path.device.golden_max_abs_vs_host",
        "lower", 0.50,
    ),
    # ISSUE 19: the entropy hot-loop backends (native C vs pure Python).
    # The native rate regressing means the C loop got slower; the Python
    # rate is the portable-fallback floor; the speedup regressing toward
    # 1.0 means the native build stopped paying for itself.
    (
        "extra_metrics.jpeg_decode.by_path.entropy_native."
        "native_images_per_sec",
        "higher", 0.30,
    ),
    (
        "extra_metrics.jpeg_decode.by_path.entropy_native."
        "python_images_per_sec",
        "higher", 0.30,
    ),
    (
        "extra_metrics.jpeg_decode.by_path.entropy_native.speedup",
        "higher", 0.30,
    ),
    ("extra_metrics.e2e.cifar.e2e_images_per_sec", "higher", 0.25),
    ("extra_metrics.e2e.cifar.overlap_efficiency", "higher", 0.15),
    ("extra_metrics.e2e.imagenet_fv.e2e_images_per_sec", "higher", 0.25),
    ("extra_metrics.e2e.imagenet_fv.overlap_efficiency", "higher", 0.15),
    ("extra_metrics.optimizer.auto_cache.speedup", "higher", 0.30),
    ("extra_metrics.optimizer.autotune.speedup", "higher", 0.30),
    ("extra_metrics.serving.mnist_fft.qps", "higher", 0.30),
    ("extra_metrics.serving.mnist_fft.p99_latency_ms", "lower", 0.50),
    (
        "extra_metrics.serving.mnist_fft.batched_vs_unbatched_qps",
        "higher", 0.30,
    ),
    ("extra_metrics.serving.cifar_conv.qps", "higher", 0.30),
    ("extra_metrics.serving.cifar_conv.p99_latency_ms", "lower", 0.50),
    # ISSUE 12: the wire front-end's socket-path tail latency and the
    # shape router's own routing cost — both lower-is-better so a slow
    # route table or a chatty protocol regresses loudly across rounds.
    ("extra_metrics.serving.wire_p99_ms", "lower", 0.50),
    ("extra_metrics.serving.router_route_overhead_us", "lower", 1.00),
    ("extra_metrics.solve_at_scale.examples_per_sec", "higher", 0.30),
    ("extra_metrics.placement.max_search_overhead_frac", "lower", 1.00),
    # ISSUE 14: the device cost-attribution section — the profiled fused
    # solve's ledger MFU regressing means the solve lost device
    # efficiency (or cost attribution broke); the profiled-serve p99 is
    # lower-is-better so a profiler that starts costing the endpoint real
    # tail latency across rounds fails loudly (the <=5% acceptance bound
    # is enforced in-round by the record itself).
    ("extra_metrics.profiler.solve_mfu", "higher", 0.30),
    ("extra_metrics.serving.profiler_overhead.p99_on_ms", "lower", 0.50),
    # ISSUE 15: the numerics observatory's serving cost — the probed-serve
    # p99 and the probe overhead fraction are both lower-is-better, so an
    # observatory that starts costing the endpoint real tail latency
    # across rounds fails loudly (the <= 5% acceptance bound is enforced
    # in-round by the record's target_frac).
    ("extra_metrics.numerics.probed_serve_p99_ms", "lower", 0.50),
    (
        "extra_metrics.numerics.probe_overhead.probe_overhead_frac",
        "lower", 1.00,
    ),
    # ISSUE 16: elastic serving — the checkpoint->foreign-mesh->serve
    # reshard wall must not creep across rounds, and a live re-anchor
    # must never drop a request (zero stays zero: any nonzero candidate
    # against a zero base is a regression, see compare()).
    ("extra_metrics.serving.reshard_wall_s", "lower", 0.50),
    ("extra_metrics.serving.reanchor_dropped_requests", "lower", 0.00),
    # ISSUE 17: multi-host elastic serving — the 2-process fit+serve wall
    # and its crosshost checkpoint-reshard wall must not creep, the
    # host-loss drill's survivor re-anchor must stay fast, and the fleet
    # must never drop a request across the loss (zero stays zero).  On
    # spawn-less hosts the section records zero-base rows, which compare
    # clean against themselves.
    ("extra_metrics.multihost.fit_serve_wall_s", "lower", 0.50),
    ("extra_metrics.multihost.reshard_wall_s", "lower", 0.50),
    ("extra_metrics.multihost.host_loss.reanchor_wall_s", "lower", 0.50),
    ("extra_metrics.multihost.host_loss.dropped_requests", "lower", 0.00),
    # ISSUE 18: closed-loop model lifecycle — the drift→refit→validate→
    # swap drill's walls must not creep across rounds (a slower warm
    # refit or hot-swap means the serving fleet spends longer answering
    # from a stale model), and the atomic hot-swap must NEVER drop a
    # request (zero stays zero: any nonzero candidate against the zero
    # base is a regression, see compare()).
    ("extra_metrics.lifecycle.refit_wall_s", "lower", 0.50),
    ("extra_metrics.lifecycle.swap_wall_s", "lower", 0.50),
    ("extra_metrics.lifecycle.drift_to_healthy_wall_s", "lower", 0.50),
    ("extra_metrics.lifecycle.dropped_requests", "lower", 0.00),
    # ISSUE 20: fleet observability plane — the live fleet-scrape and
    # pure window-merge walls must not creep across rounds, the attached
    # collector must not start costing the endpoint real tail latency
    # (the <= 5% acceptance is recorded in-round as target_frac; the
    # frac row gets the same loose threshold as the numerics tier
    # because a ratio of two noisy p99s swings hard on shared boxes),
    # the one-file incident capture must stay fast, and the obs-capture
    # drill must never drop a request across the member kill (zero
    # stays zero).
    ("extra_metrics.fleet_observability.scrape_wall_s", "lower", 1.00),
    ("extra_metrics.fleet_observability.merge_wall_s", "lower", 1.00),
    (
        "extra_metrics.fleet_observability.collector_overhead.p99_on_ms",
        "lower", 0.50,
    ),
    (
        "extra_metrics.fleet_observability.collector_overhead."
        "collector_overhead_frac",
        "lower", 1.00,
    ),
    (
        "extra_metrics.fleet_observability.incident_capture_wall_s",
        "lower", 1.00,
    ),
    (
        "extra_metrics.fleet_observability.drill.dropped_requests",
        "lower", 0.00,
    ),
)


def get_path(record: dict, dotted: str):
    """Numeric leaf at ``dotted`` path, or None (missing / non-numeric)."""
    node = record
    for part in dotted.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    if isinstance(node, bool) or not isinstance(node, (int, float)):
        return None
    return float(node)


def load_round(path: str) -> tuple[dict | None, str | None]:
    """(bench record, problem).  Unwraps the driver's ``{"parsed": ...}``
    envelope; a missing file, unparsable JSON, or a null/recordless parse
    (the BENCH_r05 truncation) returns ``(None, reason)``."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        return None, f"unreadable: {e}"
    except json.JSONDecodeError as e:
        return None, f"invalid JSON: {e}"
    record = doc.get("parsed", doc) if isinstance(doc, dict) else doc
    if record is None:
        return None, "record is null (truncated round artifact — no parsed bench line)"
    if not isinstance(record, dict) or "metric" not in record:
        return None, "not a bench record (no 'metric' key)"
    return record, None


def compare(
    base: dict,
    cand: dict,
    metrics=DEFAULT_METRICS,
) -> dict:
    """Diff two bench records metric-by-metric.  Returns the verdict dict
    (``verdict``: ok | regressed | incomparable, plus per-metric rows)."""
    rows = []
    regressions = []
    improvements = []
    for path, direction, threshold in metrics:
        b, c = get_path(base, path), get_path(cand, path)
        if b is None or c is None:
            continue
        if b == 0:
            if direction == "lower":
                # A zero base on a lower-is-better metric is a pin, not a
                # meaningless ratio: dropped-request counts and their kin
                # are REQUIRED to stay zero, so any nonzero candidate is a
                # regression (ratio reported as the raw candidate value).
                ratio = float(c)
                regressed = c > 0
                improved = False
            else:
                continue  # zero-base ratio on higher-is-better: no signal
        else:
            ratio = c / b
            if direction == "higher":
                regressed = ratio < 1.0 - threshold
                improved = ratio > 1.0 + threshold
            else:
                regressed = ratio > 1.0 + threshold
                improved = ratio < 1.0 - threshold
        status = (
            "regressed" if regressed else "improved" if improved else "ok"
        )
        row = {
            "metric": path,
            "direction": direction,
            "threshold": threshold,
            "base": b,
            "cand": c,
            "ratio": round(ratio, 4),
            "status": status,
        }
        rows.append(row)
        if regressed:
            regressions.append(row)
        elif improved:
            improvements.append(row)
    verdict = (
        "incomparable"
        if not rows
        else "regressed" if regressions else "ok"
    )
    return {
        "verdict": verdict,
        "compared": len(rows),
        "regressions": regressions,
        "improvements": improvements,
        "rows": rows,
    }


def diff_files(base_path: str, cand_path: str, metrics=DEFAULT_METRICS) -> dict:
    """File-level wrapper: load both rounds, compare, and fold any load
    problem into an ``incomparable`` verdict instead of crashing — a
    truncated round is a finding, not a tool failure."""
    base, base_problem = load_round(base_path)
    cand, cand_problem = load_round(cand_path)
    record = {
        "metric": "bench_diff",
        "base": os.path.basename(base_path),
        "cand": os.path.basename(cand_path),
    }
    problems = {}
    if base_problem:
        problems["base"] = base_problem
    if cand_problem:
        problems["cand"] = cand_problem
    if problems:
        record.update(
            verdict="incomparable", compared=0,
            regressions=[], improvements=[], rows=[], problems=problems,
        )
        return record
    record.update(compare(base, cand, metrics=metrics))
    return record


def list_rounds(dirpath: str) -> list[tuple[int, str]]:
    """(round number, path) of every BENCH_r*.json, ascending."""
    out = []
    for path in glob.glob(os.path.join(dirpath, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m:
            out.append((int(m.group(1)), path))
    return sorted(out)


def latest_usable_round(dirpath: str) -> tuple[int, str, dict] | None:
    """The newest round whose record actually parses (a truncated newest
    round — BENCH_r05 — falls back to the one before it)."""
    for num, path in reversed(list_rounds(dirpath)):
        record, problem = load_round(path)
        if record is not None:
            return num, path, record
    return None


def parse_metric_overrides(specs: list[str], metrics=DEFAULT_METRICS):
    """``--metric path=threshold[:higher|lower]`` entries merged over the
    default metric set (an unknown path is ADDED, default direction
    ``higher``)."""
    table = {path: (direction, thr) for path, direction, thr in metrics}
    for spec in specs:
        path, _, rest = spec.partition("=")
        if not rest:
            raise ValueError(
                f"--metric {spec!r}: expected path=threshold[:direction]"
            )
        thr_s, _, direction = rest.partition(":")
        thr = float(thr_s)
        if direction and direction not in ("higher", "lower"):
            raise ValueError(
                f"--metric {spec!r}: direction must be higher|lower"
            )
        prev_dir = table.get(path, ("higher", None))[0]
        table[path] = (direction or prev_dir, thr)
    return tuple((p, d, t) for p, (d, t) in table.items())


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench_diff")
    p.add_argument("base", help="base round (BENCH_rNN.json or raw record)")
    p.add_argument("cand", help="candidate round to judge against base")
    p.add_argument(
        "--metric",
        action="append",
        default=[],
        metavar="PATH=THRESH[:DIR]",
        help="override/add a metric threshold, e.g. value=0.10 or "
        "extra_metrics.serving.mnist_fft.p99_latency_ms=0.3:lower",
    )
    a = p.parse_args(argv)
    metrics = parse_metric_overrides(a.metric)
    record = diff_files(a.base, a.cand, metrics=metrics)
    # Machine-readable verdict FIRST, flushed (the bench.py convention) —
    # any tail window that reaches the end has the whole JSON line.
    print(json.dumps(record), flush=True)
    if record.get("problems"):
        for side, why in record["problems"].items():
            print(f"# {side} {record[side]}: {why}")
    for row in record["rows"]:
        mark = {"regressed": "BAD", "improved": "+++", "ok": "ok "}[row["status"]]
        print(
            f"# {mark} {row['metric']}: {row['base']:g} -> {row['cand']:g} "
            f"(x{row['ratio']}, {row['direction']} better, "
            f"threshold {row['threshold']})"
        )
    print(
        f"# bench_diff {record['base']} -> {record['cand']}: "
        f"{record['verdict']} ({record['compared']} metric(s) compared, "
        f"{len(record['regressions'])} regression(s), "
        f"{len(record['improvements'])} improvement(s))"
    )
    return {"ok": 0, "regressed": 1, "incomparable": 2}[record["verdict"]]


if __name__ == "__main__":
    sys.exit(main())
