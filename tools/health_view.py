#!/usr/bin/env python
"""Numeric-health viewer: render the numerics observatory's surface —
per-node/site tensor stats, κ per solve, drift verdicts, NaN provenance —
as human tables from any artifact that embeds it.

Accepts (auto-detected, first match wins):

* a flight-recorder postmortem dump (``keystone.postmortem/1``) — reads
  ``metrics.numerics``;
* a ``/statusz`` snapshot (``keystone.statusz/1``) — reads ``numerics``;
* a bench round record (``BENCH_r*.json``, raw or driver-wrapped) — reads
  ``metrics.numerics`` plus the ``extra_metrics.numerics`` section and any
  per-solve ``conditioning`` in fit reports;
* a workload results / serving record holding ``numerics`` /
  ``output_drift`` / ``conditioning`` keys.

Lifecycle-aware (ISSUE 18): any artifact carrying a
``lifecycle:<label>`` statusz section, a bench round's
``extra_metrics.lifecycle``, or a serve_bench ``--drift-refit`` drill
record additionally renders a ``== model lifecycle ==`` table — the
controller's state (IDLE/REFITTING/VALIDATING/SWAPPING/COOLDOWN),
generation, cooldown, and the last cycle's outcome + walls.

Usage:
    python tools/health_view.py postmortem_serve_output_drift_123_0.json
    python tools/health_view.py BENCH_r06.json

Exit status: 0 = rendered, 2 = neither a numerics nor a lifecycle
surface found in the document.
"""

from __future__ import annotations

import argparse
import json
import sys


def _fmt(v, nd: int = 4) -> str:
    if v is None:
        return "-"
    if isinstance(v, bool):
        return "yes" if v else "no"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def _table(headers: list[str], rows: list[list[str]]) -> str:
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    def line(cells):
        return "  ".join(c.ljust(w) for c, w in zip(cells, widths)).rstrip()
    out = [line(headers), line(["-" * w for w in widths])]
    out.extend(line(r) for r in rows)
    return "\n".join(out)


def extract_numerics(doc) -> dict:
    """Pull every numerics-observatory fragment out of ``doc`` into one
    ``{"sites", "conditioning", "provenance", "drift"}`` dict (keys absent
    when the artifact carries nothing for them)."""
    if not isinstance(doc, dict):
        return {}
    # driver-wrapped bench round: {"parsed": <record>, "tail": ...}
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    candidates = []
    for path in (
        ("numerics",),                      # statusz / results / snapshot()
        ("metrics", "numerics"),            # postmortem / bench metrics
        ("extra_metrics", "numerics"),      # the bench numerics section
    ):
        node = doc
        for part in path:
            node = node.get(part) if isinstance(node, dict) else None
        if isinstance(node, dict):
            candidates.append(node)
    out: dict = {}
    for cand in candidates:
        for key in ("sites", "conditioning", "provenance", "drift"):
            if cand.get(key) and key not in out:
                out[key] = cand[key]
    # drift verdicts embedded by serve_bench / engine / router records
    drifts = out.setdefault("drift", {})
    def adopt_drift(rec):
        if isinstance(rec, dict) and "divergence" in rec:
            drifts.setdefault(rec.get("label", "engine"), rec)
    adopt_drift(doc.get("output_drift"))
    engine = doc.get("engine")
    if isinstance(engine, dict):
        adopt_drift(engine.get("drift"))
    router = doc.get("router")
    if isinstance(router, dict):
        for eng in (router.get("engines") or {}).values():
            if isinstance(eng, dict):
                adopt_drift(eng.get("drift"))
    if not drifts:
        out.pop("drift", None)
    # per-solve conditioning riding fit reports / bench sections
    if "conditioning" not in out:
        for key in ("fit_report", "last_fit_report", "solve"):
            rep = doc.get(key)
            if isinstance(rep, dict) and rep.get("conditioning"):
                out["conditioning"] = rep["conditioning"]
                break
    return {k: v for k, v in out.items() if v}


def extract_lifecycle(doc) -> dict:
    """Every lifecycle-controller section in ``doc`` as
    ``{label: record}`` — statusz ``lifecycle:<label>`` providers, a
    bench round's ``extra_metrics.lifecycle`` (whose controller record
    rides in ``statusz``), a serve_bench ``--drift-refit`` drill, or a
    bare ``lifecycle`` key."""
    if not isinstance(doc, dict):
        return {}
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    out: dict = {}

    def adopt(rec, extra=None):
        if isinstance(rec, dict) and "state" in rec:
            merged = dict(rec)
            if extra:
                merged.update(
                    {k: v for k, v in extra.items() if k not in merged}
                )
            out.setdefault(merged.get("label", "lifecycle"), merged)

    providers = doc.get("providers")
    if isinstance(providers, dict):
        for name, rec in providers.items():
            if str(name).startswith("lifecycle:"):
                adopt(rec)
    ex = doc.get("extra_metrics")
    if isinstance(ex, dict) and isinstance(ex.get("lifecycle"), dict):
        sec = ex["lifecycle"]
        adopt(sec.get("statusz"), extra=sec)
    drill = doc.get("drill")
    if isinstance(drill, dict):
        adopt(drill.get("lifecycle"), extra=drill)
    adopt(doc.get("lifecycle"))
    return out


def render_lifecycle(sections: dict) -> str:
    """The ``== model lifecycle ==`` table (empty string when ``sections``
    is empty)."""
    if not sections:
        return ""
    rows = []
    for label in sorted(sections):
        s = sections[label]
        last = s.get("last_cycle") or {}
        rows.append([
            label,
            _fmt(s.get("state")),
            _fmt(s.get("generation")),
            _fmt(s.get("cooldown_remaining_s")),
            _fmt(s.get("watching")),
            _fmt(last.get("outcome") or s.get("outcome")),
            _fmt(last.get("reason") or s.get("tripped")),
            _fmt(last.get("refit_wall_s", s.get("refit_wall_s"))),
            _fmt(last.get("swap_wall_s", s.get("swap_wall_s"))),
            _fmt(s.get("dropped_requests")),
        ])
    return "== model lifecycle ==\n" + _table(
        ["controller", "state", "gen", "cooldown_s", "watching",
         "last outcome", "reason", "refit_s", "swap_s", "dropped"],
        rows,
    )


def render(numerics: dict) -> str:
    """The numeric-health report as one printable string."""
    parts: list[str] = []
    sites = numerics.get("sites") or {}
    if sites:
        rows = []
        for site in sorted(sites):
            s = sites[site]
            last = s.get("last", {})
            rows.append([
                site,
                _fmt(s.get("sampled")),
                _fmt(last.get("mean")),
                _fmt(last.get("std")),
                _fmt(last.get("min")),
                _fmt(last.get("max")),
                _fmt(last.get("abs_max")),
                _fmt(last.get("zero_frac")),
                _fmt(s.get("nonfinite_total", last.get("nonfinite"))),
            ])
        parts.append("== tensor-stat probe sites ==\n" + _table(
            ["site", "sampled", "mean", "std", "min", "max", "abs_max",
             "zero_frac", "nonfinite"],
            rows,
        ))
    cond = numerics.get("conditioning") or []
    if cond:
        rows = [
            [
                _fmt(r.get("label")),
                _fmt(r.get("block", "-")),
                _fmt(r.get("dim")),
                _fmt(r.get("kappa"), 3),
                _fmt(r.get("lam_max"), 3),
                _fmt(r.get("lam_min"), 3),
                _fmt(r.get("lam"), 3),
                "WARN" if r.get("warned") else "ok",
            ]
            for r in cond
        ]
        parts.append("== conditioning (kappa per solve) ==\n" + _table(
            ["solve", "block", "dim", "kappa", "lam_max", "lam_min",
             "lam", "verdict"],
            rows,
        ))
    drift = numerics.get("drift") or {}
    if drift:
        rows = [
            [
                label,
                _fmt(d.get("kind")),
                _fmt(d.get("observed")),
                _fmt(d.get("divergence")),
                _fmt(d.get("tol")),
                "DRIFTED" if d.get("drifted") else "ok",
                _fmt(d.get("breaches")),
            ]
            for label, d in sorted(drift.items())
        ]
        parts.append("== serving output drift ==\n" + _table(
            ["engine", "sketch", "answers", "divergence", "tol",
             "verdict", "breaches"],
            rows,
        ))
    prov = numerics.get("provenance") or []
    if prov:
        rows = [
            [
                _fmt(p.get("site")),
                _fmt(p.get("kind")),
                ",".join(str(r) for r in p.get("rows", [])[:8]),
                ", ".join(p.get("names", [])[:6])
                + ("..." if len(p.get("names", [])) > 6 else ""),
            ]
            for p in prov
        ]
        parts.append("== non-finite provenance ==\n" + _table(
            ["site", "kind", "rows", "names"], rows,
        ))
    return "\n\n".join(parts)


def _find_fleet_statusz(doc) -> dict:
    """The fleet-statusz snapshot inside ``doc`` (the document itself, a
    collector dump, or a bench/serve_bench record embedding one)."""
    if not isinstance(doc, dict):
        return {}
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if doc.get("schema") == "keystone.fleet_statusz/1":
        return doc
    for path in (
        ("fleet_obs", "statusz"),
        ("fleet_obs",),
        ("fleet_statusz",),
        ("extra_metrics", "fleet_observability", "statusz"),
    ):
        node = doc
        for part in path:
            node = node.get(part) if isinstance(node, dict) else None
        if (
            isinstance(node, dict)
            and node.get("schema") == "keystone.fleet_statusz/1"
        ):
            return node
    return {}


def render_fleet(doc) -> str:
    """ISSUE 20 ``--fleet``: the merged fleet snapshot (or an incident
    bundle) through one tool — the fleet tables first, then every
    member's numerics/lifecycle surfaces through the SAME per-site tables
    the single-process view uses."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from tools import fleet_view

    if isinstance(doc, dict) and doc.get("schema") == "keystone.incident/1":
        return fleet_view.render_incident(doc)
    snap = _find_fleet_statusz(doc)
    if not snap:
        return ""
    parts = [fleet_view.render_fleet_statusz(snap)]
    for key in sorted(snap.get("member_statusz") or {}):
        stz = snap["member_statusz"][key]
        member_parts = [
            s
            for s in (
                render(extract_numerics(stz)),
                render_lifecycle(extract_lifecycle(stz)),
            )
            if s
        ]
        if member_parts:
            parts.append(
                f"---- member {key} ----\n" + "\n\n".join(member_parts)
            )
    return "\n\n".join(parts)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("health_view")
    p.add_argument(
        "record",
        help="postmortem dump, /statusz snapshot, bench round, workload "
        "results, fleet statusz, or incident bundle JSON",
    )
    p.add_argument(
        "--fleet",
        action="store_true",
        help="render a fleet-collector snapshot (or incident bundle): "
        "fleet tables plus every member's numerics/lifecycle surfaces",
    )
    a = p.parse_args(argv)
    try:
        with open(a.record) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"health_view: cannot read {a.record}: {e}", file=sys.stderr)
        return 2
    if a.fleet:
        out = render_fleet(doc)
        if not out:
            print(
                f"health_view: no fleet statusz or incident bundle in "
                f"{a.record} — scrape one with tools/fleet_view.py "
                "--endpoints or pass a collector incident file",
                file=sys.stderr,
            )
            return 2
        print(out)
        return 0
    numerics = extract_numerics(doc)
    lifecycle = extract_lifecycle(doc)
    if not numerics and not lifecycle:
        print(
            f"health_view: no numerics or lifecycle surface in {a.record} "
            "— was the run monitored (KEYSTONE_NUMERICS=1)?",
            file=sys.stderr,
        )
        return 2
    parts = [p for p in (render(numerics), render_lifecycle(lifecycle)) if p]
    print("\n\n".join(parts))
    return 0


if __name__ == "__main__":
    sys.exit(main())
