#!/usr/bin/env python
"""Seeded end-to-end chaos runner (CLI face of tests/chaos.py).

Runs deterministic fault schedules — injected solver OOMs, transient tar
IO, corrupt archive members, NaN-poisoned batches, mid-BCD preemption with
``resume_from=`` restart, and watchdog-bounded hangs — against a real
workload pipeline, and holds every run to the chaos invariant: complete
with predictions equal to the fault-free run, or fail with a typed,
counted, logged error.  Never a silent wrong model.

Usage:
    python tools/chaos_run.py --seed 3              # one schedule
    python tools/chaos_run.py                       # the tier-1 seed set
    python tools/chaos_run.py --full                # the full seed set
    python tools/chaos_run.py --workload cifar      # RandomPatchCifar
    python tools/chaos_run.py --stream              # streaming-ingest families

Exit status is nonzero if ANY schedule violates the invariant.  The first
stdout line is the machine-readable JSON record (truncation-proof, same
convention as bench.py); a short human summary follows.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for p in (_ROOT, os.path.join(_ROOT, "tests")):
    if p not in sys.path:
        sys.path.insert(0, p)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("chaos_run")
    p.add_argument("--seed", type=int, default=None, help="run ONE schedule")
    p.add_argument(
        "--full",
        action="store_true",
        help="run the full seed set instead of the tier-1 subset",
    )
    p.add_argument(
        "--stream",
        action="store_true",
        help="run only the streaming-ingest fault schedules "
        "(stream_corrupt / stream_hang families, core.ingest path)",
    )
    p.add_argument("--workload", default="mnist", choices=("mnist", "cifar"))
    a = p.parse_args(argv)

    import chaos

    if a.seed is not None:
        seeds = (a.seed,)
    else:
        seeds = chaos.FULL_SEEDS if a.full else chaos.TIER1_SEEDS
    if a.stream:
        seeds = tuple(
            s
            for s in (chaos.FULL_SEEDS if a.seed is None else seeds)
            if chaos.make_schedule(s).kind.startswith("stream_")
        )
        if not seeds:
            print("no streaming schedules in the selected seed set")
            return 1

    results = chaos.run_suite(seeds, workload=a.workload)
    violations = [
        r
        for r in results
        if not r.ok() or r.outcome != chaos.expected_outcome(r.fault)
    ]
    record = {
        "metric": "chaos",
        "workload": a.workload,
        "seeds": list(seeds),
        "ok": not violations,
        "outcomes": {r.outcome: sum(1 for x in results if x.outcome == r.outcome) for r in results},
        "results": [r.record() for r in results],
    }
    print(json.dumps(record), flush=True)
    for r in results:
        flag = "ok " if r.ok() and r.outcome == chaos.expected_outcome(r.fault) else "BAD"
        print(
            f"# {flag} seed={r.seed} {r.fault.kind}: {r.outcome}"
            + (f" ({r.error_type})" if r.error_type else "")
            + f" [{r.seconds:.2f}s]"
        )
    print(f"# chaos: {len(results) - len(violations)}/{len(results)} schedules honored the invariant")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
