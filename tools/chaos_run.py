#!/usr/bin/env python
"""Seeded end-to-end chaos runner (CLI face of tests/chaos.py).

Runs deterministic fault schedules — injected solver OOMs, transient tar
IO, corrupt archive members, NaN-poisoned batches, mid-BCD preemption with
``resume_from=`` restart, and watchdog-bounded hangs — against a real
workload pipeline, and holds every run to the chaos invariant: complete
with predictions equal to the fault-free run, or fail with a typed,
counted, logged error.  Never a silent wrong model.

Usage:
    python tools/chaos_run.py --seed 3              # one schedule
    python tools/chaos_run.py                       # the tier-1 seed set
    python tools/chaos_run.py --full                # the full seed set
    python tools/chaos_run.py --workload cifar      # RandomPatchCifar
    python tools/chaos_run.py --stream              # streaming-ingest families
    python tools/chaos_run.py --trace DIR           # one trace per schedule

``--trace DIR`` writes a Chrome-trace JSON per schedule (Perfetto-loadable)
and ADDS an observability invariant to the suite: every injected fault must
appear in its schedule's trace as a counted ``fault`` instant event with a
matching ``kind`` attribute, and a typed-error outcome must be visible as a
failed span carrying the error type — typed-error spans are never silent.
A schedule whose trace misses either fails the run like any other
violation.  The assertion covers ALL 26 fault families (the streaming,
snapshot, decode-worker, serving, wire-protocol, placement, elastic-mesh,
multi-host, and native-entropy families included) and the tier-1 suite runs every schedule
traced
(tests/test_chaos.py), so the invariant is continuously enforced, not just
on demand.

Exit status is nonzero if ANY schedule violates the invariant.  The first
stdout line is the machine-readable JSON record (truncation-proof, same
convention as bench.py); a short human summary follows.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for p in (_ROOT, os.path.join(_ROOT, "tests")):
    if p not in sys.path:
        sys.path.insert(0, p)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("chaos_run")
    p.add_argument("--seed", type=int, default=None, help="run ONE schedule")
    p.add_argument(
        "--full",
        action="store_true",
        help="run the full seed set instead of the tier-1 subset",
    )
    p.add_argument(
        "--stream",
        action="store_true",
        help="run only the streaming-ingest fault schedules "
        "(stream_corrupt / stream_hang / autotune_thrash / "
        "snapshot_corrupt / decode_worker_kill / jpeg_corrupt_entropy / "
        "native_entropy families, core.ingest + core.snapshot paths)",
    )
    p.add_argument(
        "--serve",
        action="store_true",
        help="run only the serving fault schedules (slow_client / "
        "malformed_request / serve_burst_oom / wire_disconnect / "
        "slow_loris families — the core.serve, core.frontend, and "
        "core.wire online paths)",
    )
    p.add_argument("--workload", default="mnist", choices=("mnist", "cifar"))
    p.add_argument(
        "--hosts",
        type=int,
        default=None,
        metavar="N",
        help="size of the serving fleet the host_loss family spawns "
        "(default 2; real subprocesses where spawn is available) — sets "
        "KEYSTONE_CHAOS_HOSTS for the drill",
    )
    p.add_argument(
        "--trace",
        default=None,
        metavar="DIR",
        help="write a Chrome-trace JSON per schedule into DIR and assert "
        "every injected fault appears in it as a counted event "
        "(typed-error spans never silent)",
    )
    a = p.parse_args(argv)

    if a.hosts is not None:
        if a.hosts < 2:
            print("--hosts must be >= 2 (one host must die)", file=sys.stderr)
            return 2
        os.environ["KEYSTONE_CHAOS_HOSTS"] = str(a.hosts)

    # Hermetic placement search: the plan_mispredict oracle (and every
    # bit-equality judge) assumes the COLD search ranking — a trained
    # operator log could legitimately put a different plan at the head,
    # and the harness's synthetic fits must not train the real one.
    # Same posture as tests/conftest.py.
    from keystone_tpu.core.autoshard import hermetic_plan_log

    hermetic_plan_log()

    import chaos

    if a.seed is not None:
        seeds = (a.seed,)
    else:
        seeds = chaos.FULL_SEEDS if a.full else chaos.TIER1_SEEDS
    if a.stream or a.serve:

        def selected(seed: int) -> bool:
            kind = chaos.make_schedule(seed).kind
            if a.stream and (
                kind.startswith("stream_")
                or kind
                in (
                    "autotune_thrash", "snapshot_corrupt",
                    "decode_worker_kill", "jpeg_corrupt_entropy",
                    "native_entropy",
                )
            ):
                return True
            return a.serve and kind in chaos.SERVE_FAMILIES

        seeds = tuple(
            s
            for s in (chaos.FULL_SEEDS if a.seed is None else seeds)
            if selected(s)
        )
        if not seeds:
            print("no matching schedules in the selected seed set")
            return 1

    if a.trace is not None:
        os.makedirs(a.trace, exist_ok=True)
        if os.environ.get("KEYSTONE_TRACE", "").strip():
            # Per-schedule tracing resets the global buffer and retargets
            # the trace path every schedule — an ambient session trace
            # cannot coexist with it.
            print(
                "# WARNING: --trace overrides KEYSTONE_TRACE: per-schedule "
                "traces reset the buffer, so the env-configured session "
                "trace will not be written",
                file=sys.stderr,
            )
    results = chaos.run_suite(seeds, workload=a.workload, trace_dir=a.trace)
    trace_violations: dict[int, list] = {}
    if a.trace is not None:
        for r in results:
            # r.trace_path is the one source of truth for the filename
            # (set by run_schedule) — never re-derived here.
            missing = (
                chaos.verify_trace(r.trace_path, r)
                if r.trace_path is not None
                else ["schedule produced no trace file"]
            )
            if missing:
                trace_violations[r.seed] = missing
    violations = [
        r
        for r in results
        if not r.ok()
        or r.outcome != chaos.expected_outcome(r.fault)
        or r.seed in trace_violations
    ]
    record = {
        "metric": "chaos",
        "workload": a.workload,
        "seeds": list(seeds),
        "ok": not violations,
        "outcomes": {r.outcome: sum(1 for x in results if x.outcome == r.outcome) for r in results},
        "results": [r.record() for r in results],
    }
    if a.trace is not None:
        record["trace"] = {
            "dir": a.trace,
            "violations": {
                str(s): v for s, v in sorted(trace_violations.items())
            },
        }
    print(json.dumps(record), flush=True)
    for r in results:
        bad = (
            not r.ok()
            or r.outcome != chaos.expected_outcome(r.fault)
            or r.seed in trace_violations
        )
        flag = "BAD" if bad else "ok "
        print(
            f"# {flag} seed={r.seed} {r.fault.kind}: {r.outcome}"
            + (f" ({r.error_type})" if r.error_type else "")
            + f" [{r.seconds:.2f}s]"
            + (
                f" TRACE: {'; '.join(trace_violations[r.seed])}"
                if r.seed in trace_violations
                else ""
            )
        )
    print(f"# chaos: {len(results) - len(violations)}/{len(results)} schedules honored the invariant")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
