#!/usr/bin/env python
"""Pretty-print placement-search decisions (core.autoshard output).

Two input kinds:

* a **results JSON** — a workload's ``results["placement"]``, a bench
  record (``extra_metrics.solve_at_scale...solver.placement``,
  ``extra_metrics.placement.shapes[*]``), or any JSON containing
  ``FitReport.record()`` output: every embedded ``PlacementPlan`` record
  is found recursively and printed as a candidate table — rank, mesh,
  the per-operand SPEC assignment the candidate executes (ISSUE 10),
  predicted cost with its calibration provenance (direct / cross-program
  model / pooled), deny reason for pruned candidates, and the chosen
  plan's predicted-vs-actual cost;
* the **plan-outcome log** (``~/.keystone_plans.jsonl`` /
  ``KEYSTONE_PLAN_LOG``, any ``*.jsonl`` path): measured outcomes grouped
  by program fingerprint and candidate — sample counts, ok/oom split, and
  the median measured/predicted ratio (the learned calibration the next
  process will apply).

Usage:
    python tools/plan_view.py results.json
    python tools/plan_view.py ~/.keystone_plans.jsonl [--fingerprint FP]

No jax import — this reads JSON artifacts, it never touches a device.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict

#: keys that identify a dict as a PlacementPlan record
_PLAN_KEYS = {"fingerprint", "candidates", "ranking"}


def find_plans(doc) -> list:
    """Every embedded ``PlacementPlan.record()`` dict, depth-first."""
    out = []
    if isinstance(doc, dict):
        if _PLAN_KEYS <= set(doc) and isinstance(doc.get("candidates"), list):
            out.append(doc)
        else:
            for v in doc.values():
                out.extend(find_plans(v))
    elif isinstance(doc, list):
        for v in doc:
            out.extend(find_plans(v))
    return out


def _fmt_s(v) -> str:
    return f"{v:.4g}s" if isinstance(v, (int, float)) else "-"


def format_plan(plan: dict) -> str:
    """One PlacementPlan record as a human-readable candidate table."""
    lines = [
        f"## {plan.get('label', '?')} [{plan.get('fingerprint', '?')}] "
        f"on {plan.get('devices', '?')} — "
        f"{'trained' if plan.get('trained') else 'untrained'} model, "
        f"margin {plan.get('margin')}x, search "
        f"{_fmt_s(plan.get('search_seconds'))}"
    ]
    header = (
        f"{'rank':>4} {'candidate':<28} {'kind':<12} {'mesh':<8} "
        f"{'specs':<24} {'predicted':>10} {'calib':>7} {'src':<6} "
        f"{'n':>3} {'measured':>10} {'outcome':<8} note"
    )
    lines.append(header)
    lines.append("-" * len(header))
    chosen = plan.get("chosen")
    # execution order first (ranked), then pruned-and-dropped candidates
    cands = sorted(
        plan.get("candidates", []),
        key=lambda c: (c.get("rank") is None, c.get("rank") or 0,
                       c.get("prior_rank", 0)),
    )
    for c in cands:
        mesh = c.get("mesh")
        mesh_s = (
            f"{mesh.get('data', '?')}x{mesh.get('model', '?')}" if mesh else "-"
        )
        specs = c.get("specs")
        specs_s = (
            ",".join(
                f"{k}={'rep' if v == 'replicated' else v}"
                for k, v in sorted(specs.items())
            )
            if specs else "default"
        )
        mark = "*" if c.get("name") == chosen else " "
        note = ""
        if c.get("pruned"):
            note = f"PRUNED: {c.get('reason', '')}"
        lines.append(
            f"{c.get('rank') if c.get('rank') is not None else '-':>4}"
            f"{mark}{c.get('name', '?'):<27} {c.get('kind', '?'):<12} "
            f"{mesh_s:<8} {specs_s:<24} "
            f"{_fmt_s(c.get('predicted_seconds')):>10} "
            f"{c.get('calibration', 1.0):>7.3g} "
            f"{c.get('calibration_source', '-') or '-':<6} "
            f"{c.get('samples', 0):>3} "
            f"{_fmt_s(c.get('measured_seconds')):>10} "
            f"{c.get('outcome') or '-':<8} {note}"
        )
    if chosen is not None:
        pe = plan.get("prediction_error")
        lines.append(
            f"chosen: {chosen} — predicted "
            f"{_fmt_s(plan.get('predicted_seconds'))}, measured "
            f"{_fmt_s(plan.get('measured_seconds'))}"
            + (f", prediction_error {pe}x" if pe is not None else "")
        )
    return "\n".join(lines)


def load_log(path: str) -> list:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # a torn tail line is not an error
    return records


def format_log(records: list, fingerprint: str | None = None) -> str:
    """The outcome log grouped by (fingerprint, candidate): what the
    learned calibration will be next process."""
    groups: dict = defaultdict(list)
    for r in records:
        fp = r.get("fingerprint", "?")
        if fingerprint is not None and fp != fingerprint:
            continue
        groups[(fp, r.get("label", "?"), r.get("candidate", "?"))].append(r)
    if not groups:
        return "(no matching outcome records)"
    lines = [
        f"{'fingerprint':<18} {'label':<12} {'candidate':<28} {'n':>4} "
        f"{'ok':>4} {'oom':>4} {'med(meas/pred)':>15}"
    ]
    lines.append("-" * len(lines[0]))
    for (fp, label, cand), rs in sorted(groups.items()):
        ratios = sorted(
            r["measured_seconds"] / r["predicted_seconds"]
            for r in rs
            if r.get("outcome") == "ok"
            and r.get("predicted_seconds") and r.get("measured_seconds")
        )
        med = ratios[len(ratios) // 2] if ratios else None
        ok = sum(1 for r in rs if r.get("outcome") == "ok")
        oom = sum(1 for r in rs if r.get("outcome") == "oom")
        lines.append(
            f"{fp:<18} {label:<12} {cand:<28} {len(rs):>4} {ok:>4} "
            f"{oom:>4} {f'{med:.3g}x' if med is not None else '-':>15}"
        )
    return "\n".join(lines)


def summarize(path: str, fingerprint: str | None = None) -> str:
    if path.endswith(".jsonl"):
        return format_log(load_log(path), fingerprint)
    with open(path) as f:
        doc = json.load(f)
    plans = find_plans(doc)
    if not plans:
        return f"(no PlacementPlan records found in {path})"
    return "\n\n".join(format_plan(p) for p in plans)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("plan_view")
    p.add_argument(
        "path",
        help="results JSON (embedded PlacementPlan records) or the "
        "plan-outcome .jsonl log",
    )
    p.add_argument(
        "--fingerprint",
        default=None,
        help="log mode: only this program fingerprint",
    )
    a = p.parse_args(argv)
    print(summarize(a.path, a.fingerprint))
    return 0


if __name__ == "__main__":
    sys.exit(main())
