#!/usr/bin/env python
"""Serving front-end bench CLI: the shape-routed endpoint under load,
in-process or over real sockets (``--wire``) from separate client
processes.

Default (in-process) mode builds one deterministic toy engine per
``--shapes`` entry, registers them with a
:class:`~keystone_tpu.core.frontend.ShapeRouter`, and drives a
mixed-shape request stream from concurrent in-process clients — reporting
per-shape p50/p99/QPS, the router's stats (engines, routes, warm adds,
retires), and the ``router_route_overhead_us`` histogram the regression
observatory (tools/bench_diff.py) watches.

``--wire`` additionally binds a :class:`~keystone_tpu.core.wire.WireServer`
and spawns ``--clients`` SEPARATE CLIENT PROCESSES (tools/serve_client.py,
pinned to CPU so they never race the server for an accelerator) driving
real sockets, round-robin over the shapes.  Client records are merged with
exact cross-client percentiles; the headline ``wire_p99_ms`` is the p99
over every request of every client process.  ``--shift`` replays a
request-shape-mix shift over the wire: a shape with no engine goes hot
(RETRY_AFTER backpressure until the router warms an engine for it), then
the retire sweep runs — the record proves the warm add and the retire.

The first stdout line is the machine-readable JSON record (the bench.py
convention); human-readable lines follow.  Exit 0 on success, 1 on any
failed client or lost request.

``--hosts N`` benches the multi-host fleet front (ISSUE 17): N REAL
serve-host worker processes (keystone_tpu.workloads.multihost), each a
host-local ShapeRouter behind a WireServer, fronted by a
:class:`~keystone_tpu.core.frontend.HostFleet`; ``--kill-host R``
additionally SIGKILLs rank R mid-flight and proves the survivors
re-anchor with zero lost requests.

``--drift-refit`` runs the closed-lifecycle drill (ISSUE 18): a shifted
request mix trips the armed drift monitor of a served incumbent, the
:class:`~keystone_tpu.core.lifecycle.LifecycleController` warm-refits on
fresh data, validates, and hot-swaps the router's engine with requests
in flight — the record carries ``drift_to_healthy_wall_s``,
``refit_wall_s``/``swap_wall_s``, and ``dropped_requests`` (pinned 0 by
tools/bench_diff.py; exit 1 on any drop or a cycle that fails to land).

Usage:
    python tools/serve_bench.py                        # in-process
    python tools/serve_bench.py --wire --clients 4     # real sockets
    python tools/serve_bench.py --wire --shift         # + mix-shift replay
    python tools/serve_bench.py --hosts 2              # multi-host fleet
    python tools/serve_bench.py --hosts 3 --kill-host 2  # + host loss
    python tools/serve_bench.py --drift-refit          # lifecycle drill
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

import numpy as np  # noqa: E402


def parse_shapes(raw: str) -> list[tuple]:
    from serve_client import parse_shape

    return [parse_shape(tok) for tok in raw.split(",") if tok.strip()]


def toy_engine(shape: tuple, dtype=np.dtype(np.float32), mesh=None):
    """Deterministic per-shape engine (the chaos harness's
    fusion-invariant mul+max idiom: eager == jit == every bucket, so wire
    answers are byte-verifiable).  ``mesh`` anchors the engine's buckets
    on a device mesh (the elastic --kill-device drill)."""
    import jax.numpy as jnp

    from keystone_tpu.core import frontend, serve as kserve
    from keystone_tpu.core.pipeline import FunctionTransformer

    rng = np.random.default_rng(20260803 + int(np.prod(shape, dtype=np.int64)))
    w = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    b = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    pipe = FunctionTransformer(lambda x: jnp.maximum(x * w, b), name="bench")
    cfg = kserve.ServeConfig.from_env(buckets=(1, 4, 16), max_wait_ms=2.0)
    return kserve.ServingEngine(
        pipe,
        np.zeros(shape, np.float32),
        config=cfg,
        label=frontend.shape_label("serve_bench", shape),
        mesh=mesh,
    )


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    return float(
        sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]
    )


def _shape_key(shape) -> str:
    return "x".join(str(d) for d in shape) or "scalar"


def run_inproc(router, shapes, clients, requests_per_client, timeout) -> dict:
    """Concurrent in-process clients, round-robin over shapes, pipelined
    depth 8 — per-shape latency percentiles from the futures' own
    submit-to-answer clocks."""
    lat_by_shape: dict[str, list] = {_shape_key(s): [] for s in shapes}
    errors: list = []
    lock = threading.Lock()

    def client(cid: int):
        shape = shapes[cid % len(shapes)]
        rng = np.random.default_rng(1000 + cid)
        reqs = rng.standard_normal(
            (requests_per_client, *shape)
        ).astype(np.float32)
        lats = []
        try:
            pending = []
            for r in reqs:
                pending.append(router.submit(r))
                if len(pending) >= 8:
                    fut = pending.pop(0)
                    fut.result(timeout)
                    lats.append(fut.latency_seconds() * 1e3)
            for fut in pending:
                fut.result(timeout)
                lats.append(fut.latency_seconds() * 1e3)
            with lock:
                lat_by_shape[_shape_key(shape)].extend(lats)
        except BaseException as e:  # noqa: BLE001 — surfaced in the record
            errors.append(f"client {cid}: {type(e).__name__}: {e}")

    threads = [
        threading.Thread(target=client, args=(c,)) for c in range(clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    wall = time.perf_counter() - t0
    total = sum(len(v) for v in lat_by_shape.values())
    return {
        "clients": clients,
        "requests": total,
        "wall_seconds": round(wall, 4),
        "qps": round(total / wall, 2) if wall > 0 else 0.0,
        "per_shape": {
            k: {
                "requests": len(v),
                "p50_ms": round(_percentile(sorted(v), 0.50), 3),
                "p99_ms": round(_percentile(sorted(v), 0.99), 3),
            }
            for k, v in lat_by_shape.items()
        },
        "errors": errors,
    }


def run_wire(
    ws, shapes, clients, requests_per_client, timeout
) -> dict:
    """Spawn ``clients`` separate serve_client.py processes against the
    live socket server and merge their records (exact percentiles from
    the pooled per-request latencies)."""
    procs = []
    for cid in range(clients):
        shape = shapes[cid % len(shapes)]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"  # clients never touch the accelerator
        cmd = [
            sys.executable,
            os.path.join(_ROOT, "tools", "serve_client.py"),
            "--port", str(ws.port),
            "--shape", _shape_key(shape),
            "--requests", str(requests_per_client),
            "--seed", str(cid),
            "--timeout", str(timeout),
        ]
        procs.append(
            (cid, shape, subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env, cwd=_ROOT,
            ))
        )
    client_records = []
    errors = []
    for cid, shape, proc in procs:
        try:
            out, err = proc.communicate(timeout=timeout + 120)
        except subprocess.TimeoutExpired:
            proc.kill()
            errors.append(f"client {cid}: timed out")
            continue
        if proc.returncode != 0:
            errors.append(
                f"client {cid}: exit {proc.returncode}: {err[-400:]}"
            )
            continue
        try:
            rec = json.loads(out.splitlines()[0])
        except (json.JSONDecodeError, IndexError) as e:
            errors.append(f"client {cid}: unparsable record: {e}")
            continue
        rec["client"] = cid
        client_records.append(rec)
    lat_by_shape: dict[str, list] = {}
    reqs_by_shape: dict[str, int] = {}
    for rec in client_records:
        key = _shape_key(rec.get("shape", []))
        lat_by_shape.setdefault(key, []).extend(
            rec.get("latencies_ms", [])
        )
        reqs_by_shape[key] = reqs_by_shape.get(key, 0) + rec["requests"]
    all_lat = sorted(v for vals in lat_by_shape.values() for v in vals)
    per_shape = {
        k: {
            "requests": reqs_by_shape[k],
            "p50_ms": round(_percentile(sorted(v), 0.50), 3),
            "p99_ms": round(_percentile(sorted(v), 0.99), 3),
        }
        for k, v in lat_by_shape.items()
    }
    for rec in client_records:
        rec.pop("latencies_ms", None)  # merged above; keep records small
    return {
        "clients": clients,
        "client_processes": [
            {"client": r["client"], "pid_record": r} for r in client_records
        ],
        # answered count from the client records themselves — latencies_ms
        # is a (possibly sampled) distribution, not the request ledger.
        "requests": sum(r["requests"] for r in client_records),
        "per_shape": per_shape,
        "wire_p50_ms": round(_percentile(all_lat, 0.50), 3),
        "wire_p99_ms": round(_percentile(all_lat, 0.99), 3),
        "retry_after_total": sum(
            r.get("retry_after", 0) for r in client_records
        ),
        "errors": errors,
    }


def run_shift(router, ws, shapes, timeout) -> dict:
    """The mix-shift replay over the wire: a NEW shape goes hot (the
    client absorbs RETRY_AFTER pushback until the router warms an engine),
    then the retire sweep reclaims the now-idle original engines —
    warm add + retire proven over a live socket with zero lost requests."""
    new_shape = (int(np.prod(shapes[0], dtype=np.int64)) + 3,)
    warm_before = router.stats.warm_adds
    retire_before = router.stats.retires
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [
        sys.executable,
        os.path.join(_ROOT, "tools", "serve_client.py"),
        "--port", str(ws.port),
        "--shape", _shape_key(new_shape),
        "--requests", "24",
        "--seed", "777",
        "--timeout", str(timeout),
    ]
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout + 120,
        env=env, cwd=_ROOT,
    )
    out: dict = {"new_shape": list(new_shape)}
    if proc.returncode != 0:
        out["error"] = f"shift client failed: {proc.stderr[-400:]}"
        return out
    rec = json.loads(proc.stdout.splitlines()[0])
    rec.pop("latencies_ms", None)
    out["client"] = rec
    out["warm_adds"] = router.stats.warm_adds - warm_before
    # The shifted-away shapes stopped earning traffic — run the retire
    # sweep with a bounded idle threshold so the replay is deterministic
    # (the new engine routed most recently and survives the sweep's
    # idlest-first order + min_engines floor).
    saved = router.config.retire_after_s
    try:
        router.config.retire_after_s = 1.0
        time.sleep(1.1)
        router.adapt()
    finally:
        router.config.retire_after_s = saved
    out["retires"] = router.stats.retires - retire_before
    out["new_shape_live"] = tuple(new_shape) in router.engines()
    # drive() answers every request or dies nonzero (caught above), so a
    # successful client record IS the zero-loss proof.
    out["lost_requests"] = 24 - rec["requests"]
    return out


def drift_refit_drill(tmpdir, *, requests=24, seed=0, timeout=60.0) -> dict:
    """The closed model-lifecycle drill (ISSUE 18), importable by
    bench.py's ``extra_metrics.lifecycle`` section: an incumbent fit on
    pre-drift truth serves an armed router; the request mix shifts (new
    truth), the drift monitor trips, and the
    :class:`~keystone_tpu.core.lifecycle.LifecycleController` runs one
    full cycle — warm refit on fresh data, holdout validation, atomic
    hot-swap — while a pump thread keeps requests in flight across the
    swap.  The record carries the walls bench_diff regresses on
    (``drift_to_healthy_wall_s``, ``refit_wall_s``, ``swap_wall_s``),
    ``dropped_requests`` (must stay 0), the post-swap bit-equality
    verdict, and the controller's ``lifecycle:<label>`` statusz section.
    """
    import jax.numpy as jnp

    from keystone_tpu.core import frontend as kfrontend
    from keystone_tpu.core import numerics as knum
    from keystone_tpu.core import serve as kserve
    from keystone_tpu.core.lifecycle import LifecycleConfig, LifecycleController
    from keystone_tpu.ops.stats import StandardScalerModel
    from keystone_tpu.solvers.block import BlockLeastSquaresEstimator

    rng = np.random.default_rng(seed)
    d, k = 16, 4
    mean0 = rng.normal(size=(d,)).astype(np.float32)
    t1 = rng.normal(size=(d, k)).astype(np.float32)
    t2 = rng.normal(size=(d, k)).astype(np.float32)
    featurizer = StandardScalerModel(jnp.asarray(mean0), None)
    shift = np.zeros(d, np.float32)
    shift[int(np.argmax(np.abs(t1).sum(axis=1)))] = 6.0

    def fit(feats, labels):
        est = BlockLeastSquaresEstimator(block_size=16, num_iter=1, lam=0.0)
        return est.fit(jnp.asarray(feats), jnp.asarray(labels))

    # Pre-drift world: the incumbent's truth is (x - mean0) @ t1.
    xa = rng.normal(size=(128, d)).astype(np.float32)
    feats_a = xa - mean0
    pipe_inc = featurizer.then(fit(feats_a, feats_a @ t1))
    cfg = kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0)
    engine = kserve.ServingEngine(
        pipe_inc, np.zeros(d, np.float32), config=cfg, label="lifedrill_inc"
    )
    baseline = knum.OutputSketch.for_outputs(
        engine.offline(rng.normal(size=(64, d)).astype(np.float32))
    ).record()

    # Post-drift world: shifted requests, new truth (x - mean0) @ t2.
    xb = rng.normal(size=(128, d)).astype(np.float32) + shift
    feats_b = xb - mean0
    labels_b = feats_b @ t2
    hx = rng.normal(size=(64, d)).astype(np.float32) + shift
    hy = (hx - mean0) @ t2
    shifted = rng.normal(size=(max(48, requests), d)).astype(np.float32) + shift
    reqs = rng.normal(size=(requests, d)).astype(np.float32) + shift

    router = kfrontend.ShapeRouter(
        label="lifedrill",
        config=kfrontend.RouterConfig(warm_threshold=1, retire_after_s=300.0),
    )
    record: dict = {"requests": int(requests)}
    dropped = [0]
    pumped = [0]
    ctl = None
    try:
        router.add_engine(engine)
        ctl = LifecycleController(
            router,
            workdir=os.path.join(tmpdir, "lifedrill_wd"),
            featurizer=featurizer,
            fetch=lambda digest: (feats_b, labels_b),
            estimator=lambda: BlockLeastSquaresEstimator(
                block_size=16, num_iter=1, lam=0.0
            ),
            assemble=lambda model: featurizer.then(model),
            holdout=lambda: (hx, hy),
            quality=lambda predict, x, y: -float(
                np.mean((np.asarray(predict(x)) - y) ** 2)
            ),
            example=np.zeros(d, np.float32),
            label="lifedrill",
            serve_config=cfg,
            config=LifecycleConfig(cooldown_s=0.0),
        )
        with knum.monitored(True):
            engine.arm_drift_baseline(baseline)
            t_drift = time.perf_counter()
            for f in [router.submit(r) for r in shifted]:
                f.result(timeout)
            tripped = ctl.check_signals()
            record["tripped"] = tripped
            # Keep requests in flight ACROSS the swap: the drill's
            # zero-drop claim is about live traffic, not a quiesced
            # router.
            stop = threading.Event()

            def pump():
                i = 0
                while not stop.is_set():
                    try:
                        router.submit(reqs[i % len(reqs)]).result(timeout)
                    except Exception:  # noqa: BLE001 — any loss is a drop
                        dropped[0] += 1
                    pumped[0] += 1
                    i += 1

            pump_thread = threading.Thread(
                target=pump, name="lifedrill-pump", daemon=True
            )
            pump_thread.start()
            try:
                cycle = ctl.run_refit(reason=tripped or "operator")
            finally:
                stop.set()
                pump_thread.join(timeout)
            record["drift_to_healthy_wall_s"] = round(
                time.perf_counter() - t_drift, 6
            )
        record["cycle"] = cycle
        for key in ("refit_wall_s", "validate_wall_s", "swap_wall_s",
                    "total_wall_s"):
            record[key] = cycle.get(key)
        # Post-swap answers must be bit-equal to the NEW engine's own
        # eager oracle (the refit pipeline).
        new_engine = router.server_for((d,)).engine
        post = np.stack(
            [router.submit(r).result(timeout) for r in reqs]
        )
        record["swapped_engine"] = new_engine.label
        record["post_swap_bit_equal"] = bool(
            np.array_equal(post, new_engine.offline(reqs))
        )
        record["in_flight_across_swap"] = int(pumped[0])
        record["dropped_requests"] = int(dropped[0])
        record["lifecycle"] = ctl.record()
        record["ok"] = bool(
            tripped == "serve_output_drift"
            and cycle.get("outcome") == "swapped"
            and new_engine is not engine
            and record["post_swap_bit_equal"]
            and dropped[0] == 0
        )
        return record
    finally:
        if ctl is not None:
            ctl.close()
        router.close()


def run_drift_refit(a) -> int:
    """--drift-refit: the lifecycle drill as a CLI record (JSON first
    line, bench.py convention; exit 1 unless the cycle landed with zero
    dropped requests and bit-equal post-swap answers)."""
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="serve_bench_lifecycle_")
    t0 = time.perf_counter()
    try:
        drill = drift_refit_drill(
            tmp, requests=a.requests, timeout=a.timeout
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    record = {
        "metric": "serve_bench",
        "mode": "drift_refit",
        "drill": drill,
        # Top-level copies for the regression observatory's dotted paths.
        "drift_to_healthy_wall_s": drill.get("drift_to_healthy_wall_s"),
        "refit_wall_s": drill.get("refit_wall_s"),
        "swap_wall_s": drill.get("swap_wall_s"),
        "dropped_requests": drill.get("dropped_requests"),
        "ok": drill.get("ok", False),
        "seconds": round(time.perf_counter() - t0, 3),
    }
    print(json.dumps(record), flush=True)
    cyc = drill.get("cycle", {})
    print(
        f"# lifecycle: tripped on {drill.get('tripped')}, cycle outcome "
        f"{cyc.get('outcome')} (g{cyc.get('generation')}), engine "
        f"{drill.get('swapped_engine')}"
    )
    print(
        f"# walls: drift->healthy {drill.get('drift_to_healthy_wall_s')}s "
        f"(refit {drill.get('refit_wall_s')}s, validate "
        f"{drill.get('validate_wall_s')}s, swap {drill.get('swap_wall_s')}s)"
    )
    print(
        f"# traffic: {drill.get('in_flight_across_swap')} request(s) pumped "
        f"across the swap, {drill.get('dropped_requests')} dropped, "
        f"post-swap bit-equal: {drill.get('post_swap_bit_equal')}"
    )
    return 0 if record["ok"] else 1


def run_hosts(a) -> int:
    """--hosts N (ISSUE 17): spawn N REAL serve-host worker processes
    (keystone_tpu.workloads.multihost serve-host, toy scaler mode), front
    them with a :class:`~keystone_tpu.core.frontend.HostFleet`, and drive
    the request stream through the fleet — per-request p50/p99 across
    hosts, per-host request counts, and with ``--kill-host R`` the
    host-loss drill: SIGKILL rank R mid-flight, survivors re-form the
    reduced group and re-anchor (the ack carries ``reanchor_wall_s``)
    while the fleet reissues — zero lost requests or exit 1."""
    import queue
    import tempfile

    from keystone_tpu.core import frontend as kfrontend
    from keystone_tpu.parallel import distributed as kdist
    from keystone_tpu.workloads import multihost as mh

    record: dict = {
        "metric": "serve_bench",
        "hosts": a.hosts,
        "requests_per_client": a.requests,
    }
    if not kdist.spawn_available():
        # Clean single-process degrade: the record says why nothing ran.
        record.update(multihost_unavailable=True, ok=True)
        print(json.dumps(record), flush=True)
        print("# multihost: process spawn unavailable — nothing benched")
        return 0
    if a.kill_host is not None and not 0 <= a.kill_host < a.hosts:
        print(json.dumps({**record, "ok": False,
                          "error": f"--kill-host {a.kill_host} out of range"}))
        return 2

    clients = a.clients or 4
    n = clients * a.requests
    record["clients"] = clients
    rng = np.random.default_rng(7)
    rows = [rng.normal(size=mh.FEAT_DIM).astype(np.float32)
            for _ in range(n)]

    t0 = time.perf_counter()
    tmpdir = tempfile.mkdtemp(prefix="serve_bench_hosts_")
    workers: list = []
    ok = True
    errors: list = []
    results: list = [None] * n
    lat_ms: list = [None] * n
    col = None
    try:
        for r in range(a.hosts):
            env = mh._hermetic_env(
                kdist.worker_env(r, a.hosts, "controller", local_devices=2),
                tmpdir, f"host{r}",
            )
            workers.append(mh._WorkerIO(
                mh._worker_cmd("serve-host", ["--seed", "7"]),
                env, os.path.join(tmpdir, f"host{r}.err"),
            ))
        up = [w.expect("port", a.timeout / 2) for w in workers]
        endpoints = [("127.0.0.1", m["port"]) for m in up]
        record["bringup_seconds"] = round(time.perf_counter() - t0, 3)

        idx_q: "queue.Queue" = queue.Queue()
        for i in range(n):
            idx_q.put(i)

        with kfrontend.HostFleet(endpoints, label="serve_bench") as fleet:
            if a.collect:
                from keystone_tpu.core import fleetobs

                col = fleetobs.FleetCollector(
                    label="serve_bench", interval_s=0.2
                )
                fleet.attach_collector(col)
                col.start()

            def work():
                while True:
                    try:
                        i = idx_q.get_nowait()
                    except queue.Empty:
                        return
                    s = time.perf_counter()
                    try:
                        results[i] = np.asarray(fleet.predict(rows[i]))
                        lat_ms[i] = (time.perf_counter() - s) * 1000.0
                    except Exception as e:  # noqa: BLE001 — judged below
                        errors.append(f"req {i}: {type(e).__name__}: {e}")

            pool = [
                threading.Thread(
                    target=work, name=f"fleet-client-{t}", daemon=True
                )
                for t in range(clients)
            ]
            for t in pool:
                t.start()
            if a.kill_host is not None:
                mh._wait_answered(results, n // 3, a.timeout / 3)
                workers[a.kill_host].kill()
                record["killed_host"] = a.kill_host
                record["killed_at_answered"] = mh._answered(results)
                survivors = [
                    r for r in range(a.hosts) if r != a.kill_host
                ]
                acks = {}
                for r in survivors:
                    workers[r].send(
                        "peer_lost " + " ".join(str(s) for s in survivors)
                    )
                for r in survivors:
                    acks[r] = workers[r].expect("ack", a.timeout / 2)
                record["reanchor_wall_s"] = max(
                    float(acks[r].get("reanchor_wall_s") or 0.0)
                    for r in survivors
                )
            end = time.monotonic() + a.timeout
            for t in pool:
                t.join(max(0.1, end - time.monotonic()))
            if any(t.is_alive() for t in pool):
                errors.append("fleet clients did not drain in time")
            if col is not None:
                from keystone_tpu.core import resilience

                col.stop()
                snap = col.scrape_once()
                hists = snap.get("histograms") or {}
                metric = next(
                    (m for m in ("serve_latency_ms", "wire_request_ms")
                     if m in hists),
                    None,
                )
                p99 = (hists.get(metric) or {}).get("p99")
                record["fleet_obs"] = {
                    "statusz": snap,
                    "pooled_metric": metric,
                    "fleet_p99_ms": round(p99, 3) if p99 is not None
                    else None,
                    "alive": snap.get("alive"),
                    "lost": snap.get("lost"),
                    # Counted in THIS (collector) process, not a member.
                    "obs_member_lost": int(
                        resilience.counters.get("obs_member_lost")
                    ),
                }
            record["fleet"] = fleet.record()
        live = [r for r in range(a.hosts) if r != a.kill_host]
        for r in live:
            workers[r].send("quit")
        record["survivor_counters"] = {
            r: workers[r].expect("final", a.timeout / 4)["final"]["counters"]
            for r in live
        }
    finally:
        if col is not None:
            col.close()
        record["worker_rcs"] = [w.finish() for w in workers]

    answered = sorted(v for v in lat_ms if v is not None)
    dropped = n - len(answered)
    record["bench"] = {
        "requests": len(answered),
        "errors": errors,
        "p50_ms": round(_percentile(answered, 0.50), 3) if answered else None,
        "p99_ms": round(_percentile(answered, 0.99), 3) if answered else None,
    }
    record["dropped_requests"] = int(dropped)
    ok = not errors and dropped == 0
    record["ok"] = bool(ok)
    record["seconds"] = round(time.perf_counter() - t0, 3)
    print(json.dumps(record), flush=True)
    b = record["bench"]
    print(
        f"# fleet: {a.hosts} host process(es), {b['requests']}/{n} "
        f"requests answered, p50 {b['p50_ms']}ms, p99 {b['p99_ms']}ms"
    )
    for h in record["fleet"]["hosts"]:
        print(
            f"# host {h['endpoint']}: alive={h['alive']} "
            f"requests={h['requests']} reissued={h['reissued']}"
        )
    if a.kill_host is not None:
        print(
            f"# host-loss: killed host {a.kill_host} at "
            f"{record.get('killed_at_answered')} answered, reanchor wall "
            f"{record.get('reanchor_wall_s')}s, {dropped} dropped"
        )
    fo = record.get("fleet_obs")
    if fo:
        print(
            f"# fleet-obs: {fo['alive']}/{a.hosts} member(s) up, fleet "
            f"p99 {fo['fleet_p99_ms']}ms from pooled "
            f"{fo['pooled_metric']} windows, "
            f"member_lost={fo['obs_member_lost']}"
        )
    for err in errors:
        print(f"# ERROR {err}")
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser("serve_bench")
    p.add_argument(
        "--shapes", default="16,64",
        help="comma-separated request shapes (16 or 32x32x3)",
    )
    p.add_argument("--clients", type=int, default=None,
                   help="default: 4 in-process, 2 wire processes")
    p.add_argument("--requests", type=int, default=64,
                   help="requests per client")
    p.add_argument("--wire", action="store_true",
                   help="bind a socket server and drive it from separate "
                   "client processes")
    p.add_argument("--port", type=int, default=0,
                   help="wire port (0 = ephemeral)")
    p.add_argument("--shift", action="store_true",
                   help="with --wire: replay a shape-mix shift (warm add "
                   "+ retire over a live socket)")
    p.add_argument(
        "--kill-device", type=int, default=None, metavar="N",
        help="elastic drill (ISSUE 16): anchor the engines on a mesh over "
        "every visible device, then mid-run 'lose' device N — the router "
        "must re-anchor every engine onto the surviving mesh with zero "
        "request loss; the record carries reshard_wall_s and "
        "requests_in_flight_across_swap",
    )
    p.add_argument(
        "--numerics", action="store_true",
        help="turn the numerics observatory on for the run "
        "(KEYSTONE_NUMERICS equivalent): per-bucket output probes + drift "
        "verdicts land in the record's router/numerics sections",
    )
    p.add_argument(
        "--hosts", type=int, default=None, metavar="N",
        help="multi-host fleet bench (ISSUE 17): spawn N serve-host "
        "worker PROCESSES and drive the stream through a HostFleet; "
        "degrades to a no-op record where process spawn is unavailable",
    )
    p.add_argument(
        "--kill-host", type=int, default=None, metavar="R",
        help="with --hosts: SIGKILL worker rank R mid-flight — survivors "
        "re-form the group and re-anchor while the fleet reissues; zero "
        "lost requests or exit 1",
    )
    p.add_argument(
        "--collect", action="store_true",
        help="with --hosts (ISSUE 20): attach a fleet collector scraping "
        "the workers over the obs wire frames — the record gains "
        "fleet_obs: the merged fleet statusz plus the fleet p99 from "
        "pooled latency windows (never averaged percentiles)",
    )
    p.add_argument(
        "--drift-refit", action="store_true",
        help="closed-lifecycle drill (ISSUE 18): trip the drift monitor "
        "with a shifted mix, warm-refit, validate, hot-swap with requests "
        "in flight — zero dropped requests or exit 1",
    )
    p.add_argument("--timeout", type=float, default=120.0)
    a = p.parse_args(argv)

    if a.drift_refit:
        return run_drift_refit(a)
    if a.kill_host is not None and a.hosts is None:
        p.error("--kill-host requires --hosts")
    if a.collect and a.hosts is None:
        p.error("--collect requires --hosts")
    if a.hosts is not None:
        if a.hosts < 2:
            p.error("--hosts must be >= 2 (a fleet)")
        return run_hosts(a)

    import contextlib

    from keystone_tpu.core import frontend, numerics as knum, trace, wire

    shapes = parse_shapes(a.shapes)
    cfg = frontend.RouterConfig.from_env(warm_threshold=2, min_engines=1)
    record: dict = {
        "metric": "serve_bench",
        "wire": bool(a.wire),
        "shapes": [list(s) for s in shapes],
        "requests_per_client": a.requests,
    }
    clients = a.clients or (2 if a.wire else 4)
    expected_requests = clients * a.requests

    factory = toy_engine
    surviving = None
    if a.kill_device is not None:
        import jax

        from keystone_tpu.parallel.mesh import make_mesh, mesh_desc

        devs = list(jax.devices())
        if not 0 <= a.kill_device < len(devs):
            p.error(
                f"--kill-device {a.kill_device}: have {len(devs)} device(s)"
            )
        survivor_devs = [
            d for i, d in enumerate(devs) if i != a.kill_device
        ]
        if not survivor_devs:
            p.error("--kill-device would leave no surviving device")
        full = make_mesh(data=len(devs), model=1, devices=devs)
        surviving = make_mesh(
            data=len(survivor_devs), model=1, devices=survivor_devs
        )
        factory = frontend.MeshEngineFactory(
            lambda shape, dtype, mesh: toy_engine(shape, dtype, mesh=mesh),
            mesh=full,
        )
        record["mesh"] = mesh_desc(full)

    t0 = time.perf_counter()
    router = frontend.ShapeRouter(
        factory, label="serve_bench", config=cfg
    )
    reshard_info: dict = {}

    def _reanchor_drill():
        # Wait for real traffic so the swap demonstrably lands with
        # requests in flight, then lose the device.
        from keystone_tpu.parallel.mesh import mesh_desc

        end = time.monotonic() + a.timeout
        target = max(1, expected_requests // 4)
        while router.stats.routes < target and time.monotonic() < end:
            time.sleep(0.005)
        with router._lock:
            entries = list(router._engines.values())
        answered = sum(e.server.stats.answered for e in entries)
        inflight = max(0, router.stats.routes - answered)
        rec = router.reanchor(
            surviving, why=f"--kill-device {a.kill_device}"
        )
        reshard_info.update(
            killed_device=a.kill_device,
            surviving_mesh=mesh_desc(surviving),
            reshard_wall_s=rec["reshard_wall_s"],
            requests_in_flight_across_swap=int(inflight),
            swapped=len(rec["swapped"]),
            failed=rec["failed"],
        )

    ok = True
    numerics_ctx = knum.monitored(True) if a.numerics else contextlib.nullcontext()
    try:
        numerics_ctx.__enter__()
        for shape in shapes:
            engine = (
                factory(shape, np.dtype(np.float32))
                if a.kill_device is not None
                else toy_engine(shape)
            )
            router.add_engine(engine)
        record["engine_build_seconds"] = round(time.perf_counter() - t0, 3)
        drill = None
        if a.kill_device is not None:
            drill = threading.Thread(
                target=_reanchor_drill, name="serve-bench-kill", daemon=True
            )
            drill.start()
        if a.wire:
            with wire.WireServer(
                router, port=a.port, label="serve_bench"
            ) as ws:
                bench = run_wire(
                    ws, shapes, clients, a.requests, a.timeout
                )
                if a.shift:
                    record["shift"] = run_shift(router, ws, shapes, a.timeout)
                record["wire_server"] = ws.record()
            record["bench"] = bench
            record["wire_p99_ms"] = bench["wire_p99_ms"]
            ok = not bench["errors"] and bench["requests"] == (
                clients * a.requests
            )
            if a.shift:
                sh = record["shift"]
                ok = ok and "error" not in sh and sh["lost_requests"] == 0 \
                    and sh["warm_adds"] >= 1 and sh["retires"] >= 1
        else:
            bench = run_inproc(
                router, shapes, clients, a.requests, a.timeout
            )
            record["bench"] = bench
            ok = not bench["errors"] and bench["requests"] == (
                clients * a.requests
            )
        if drill is not None:
            drill.join(a.timeout)
            dropped = expected_requests - bench["requests"]
            reshard_info["reanchor_dropped_requests"] = int(dropped)
            record["reshard"] = reshard_info
            # Top-level copies for the regression observatory's dotted
            # paths (tools/bench_diff.py): reshard wall must not creep,
            # dropped requests must stay 0.
            record["reshard_wall_s"] = reshard_info.get("reshard_wall_s")
            record["reanchor_dropped_requests"] = int(dropped)
            ok = (
                ok
                and "reshard_wall_s" in reshard_info
                and not reshard_info.get("failed")
                and dropped == 0
            )
        snap = trace.metrics.snapshot()
        overhead = snap["histograms"].get("router_route_overhead_us", {})
        record["router_route_overhead_us"] = {
            k: round(overhead[k], 3)
            for k in ("mean", "p50", "p99")
            if k in overhead
        }
        record["router"] = router.record()
        if a.numerics:
            # The observatory's view of the benched traffic (ISSUE 15):
            # per-site output stats + any drift verdicts.
            record["numerics"] = knum.snapshot()
    finally:
        numerics_ctx.__exit__(None, None, None)
        router.close()
    record["ok"] = bool(ok)
    record["seconds"] = round(time.perf_counter() - t0, 3)
    print(json.dumps(record), flush=True)
    b = record.get("bench", {})
    for key, row in sorted(b.get("per_shape", {}).items()):
        print(
            f"# shape {key}: {row['requests']} requests, p50 "
            f"{row['p50_ms']}ms, p99 {row['p99_ms']}ms"
        )
    stats = record["router"]["stats"]
    print(
        f"# router: {len(record['router']['engines'])} engine(s), "
        f"{stats['routes']} routed, {stats['warm_adds']} warm add(s), "
        f"{stats['retires']} retire(s), {stats['rejected']} pushback(s)"
    )
    if a.wire:
        print(
            f"# wire: {b.get('requests')} requests from "
            f"{b.get('clients')} client process(es), p99 "
            f"{b.get('wire_p99_ms')}ms, "
            f"{b.get('retry_after_total')} retry-after"
        )
    if record.get("reshard"):
        rs = record["reshard"]
        print(
            f"# reshard: killed device {rs.get('killed_device')}, "
            f"surviving mesh {rs.get('surviving_mesh')}, wall "
            f"{rs.get('reshard_wall_s')}s, "
            f"{rs.get('requests_in_flight_across_swap')} in flight across "
            f"the swap, {rs.get('reanchor_dropped_requests')} dropped"
        )
    for err in b.get("errors", []):
        print(f"# ERROR {err}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
