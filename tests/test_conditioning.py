"""f32 conditioning study for ``solve_gram_l2`` vs an f64 oracle (the
ACCURACY.md κ-sweep, VERDICT r5 job 5).

The sweep builds SPD grams with EXACT condition number κ (Q·diag(s)·Qᵀ,
log-spaced spectrum 1..1/κ) and measures the f32 guarded solve against
``np.linalg.solve`` in f64 at λ=0.  Expected behavior: relative error grows
like κ·eps_f32 (eps_f32 ≈ 1.2e-7) while the Cholesky holds, and beyond
κ ≈ 1/eps_f32 the factorization breaks down and the jitter-escalation
ladder (λ·10^k, k ≤ 3) must RECOVER — a finite, logged, regularized
solution instead of NaN weights.

Run ``python tests/test_conditioning.py`` to regenerate the ACCURACY.md
table.
"""

import logging
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

# table-regeneration mode (`python tests/test_conditioning.py`) runs without
# pytest's rootdir on sys.path
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

import keystone_tpu.solvers.normal_equations as ne
from keystone_tpu.solvers.normal_equations import solve_gram_l2

_D, _K = 256, 8


def gram_with_condition(rng, d: int, kappa: float) -> np.ndarray:
    """SPD [d, d] gram with exact condition number ``kappa``: orthogonal
    eigenvectors, eigenvalues log-spaced from 1 down to 1/kappa."""
    q, _ = np.linalg.qr(rng.normal(size=(d, d)))
    s = np.logspace(0.0, -np.log10(kappa), d)
    ata = (q * s) @ q.T
    return np.asarray((ata + ata.T) / 2.0, np.float64)


def sweep_point(kappa: float, seed: int = 0) -> dict:
    """One κ row: f32 guarded solve vs the f64 oracle at λ=0, plus the
    number of jitter escalations the guard needed."""
    rng = np.random.default_rng(seed)
    ata64 = gram_with_condition(rng, _D, kappa)
    x_true = rng.normal(size=(_D, _K))
    atb64 = ata64 @ x_true
    oracle = np.linalg.solve(ata64, atb64)

    messages: list[str] = []
    handler = logging.Handler()
    handler.emit = lambda record: messages.append(record.getMessage())
    ne._logger.addHandler(handler)
    try:
        x32 = np.asarray(
            solve_gram_l2(
                jnp.asarray(ata64, jnp.float32),
                jnp.asarray(atb64, jnp.float32),
                0.0,
            ),
            np.float64,
        )
    finally:
        ne._logger.removeHandler(handler)
    return {
        "kappa": kappa,
        "rel_err": float(
            np.linalg.norm(x32 - oracle) / np.linalg.norm(oracle)
        ),
        "escalations": sum("retrying with jitter" in m for m in messages),
        "finite": bool(np.isfinite(x32).all()),
    }


def test_kappa_sweep_error_tracks_f32_eps():
    """rel_err ≈ κ·eps_f32 through the direct-solve range: each decade of κ
    costs about a decade of accuracy, with NO jitter needed."""
    for kappa, bound in [(1e2, 1e-4), (1e4, 1e-2), (1e6, 1e-1)]:
        row = sweep_point(kappa)
        assert row["finite"]
        assert row["escalations"] == 0, row
        assert row["rel_err"] < bound, row


def test_worst_kappa_regression_pin():
    """The regression pin (ACCURACY.md κ-sweep): the worst direct-solve
    point measured was κ=1e6 at rel_err 1.1e-2 — hold it under 5e-2 so a
    numerics regression (lost symmetrization, dtype downcast, a broken
    guard) fails loudly."""
    row = sweep_point(1e6)
    assert row["escalations"] == 0, row
    assert row["rel_err"] < 5e-2, row


def test_beyond_f32_breakdown_jitter_recovers():
    """κ=1e8 > 1/eps_f32: the unregularized f32 Cholesky breaks down and
    the escalation ladder must recover a FINITE (regularized) solution —
    counted in the log, never NaN weights."""
    row = sweep_point(1e8)
    assert row["finite"], row
    # Either this BLAS build survives the factorization directly or the
    # ladder stepped in; when it did, it must have been logged.
    if row["escalations"]:
        assert row["escalations"] <= 3, row
    # The regularized answer is biased but bounded — an unguarded f32
    # solve at this κ returns garbage orders of magnitude off (or NaN).
    assert row["rel_err"] < 1.0, row


@pytest.mark.parametrize("kappa", [1e2, 1e5])
def test_sweep_is_deterministic(kappa):
    a, b = sweep_point(kappa), sweep_point(kappa)
    assert a["rel_err"] == b["rel_err"]
    assert a["escalations"] == b["escalations"]


if __name__ == "__main__":
    print("| κ | rel. error vs f64 oracle | jitter escalations |")
    print("|---|---|---|")
    for exp in range(1, 9):
        row = sweep_point(10.0**exp)
        print(
            f"| 1e{exp} | {row['rel_err']:.3e} | {row['escalations']} |"
        )
