"""Import-cost discipline: ``import keystone_tpu`` must not import jax.

Every spawned decode worker (core.ingest._decode_worker_main runs under
multiprocessing spawn) re-imports the package in a fresh interpreter; the
eager ``from .core.checkpoint import ...`` chain used to pull jax —
multi-second startup paid per worker, visible as the bench_decode
total-vs-steady rate gap.  The package surface is now lazy (PEP 562
``__getattr__``) and the worker's import path (core.ingest and everything
it imports) is jax-free at module import.  These run in SUBPROCESSES: the
test suite's own interpreter imported jax long ago, so only a fresh
process can observe import-time behavior.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fresh(script: str) -> subprocess.CompletedProcess:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=120,
        env=env,
        cwd=_REPO,
    )


def test_package_import_does_not_import_jax():
    res = _fresh(
        "import sys\n"
        "import keystone_tpu\n"
        "assert 'jax' not in sys.modules, 'import keystone_tpu pulled jax'\n"
        "print('LAZY_OK', keystone_tpu.__version__)\n"
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "LAZY_OK" in res.stdout


def test_decode_worker_import_path_does_not_import_jax():
    """The exact modules a spawned decode worker imports (the pickle of
    ``_decode_worker_main`` resolves keystone_tpu.core.ingest) must stay
    jax-free — the point of the laziness is the worker spawn cost."""
    res = _fresh(
        "import sys\n"
        "import keystone_tpu.core.ingest as ingest\n"
        "assert 'jax' not in sys.modules, (\n"
        "    'importing core.ingest pulled jax — decode workers pay it')\n"
        "assert callable(ingest._decode_worker_main)\n"
        "print('WORKER_LAZY_OK')\n"
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "WORKER_LAZY_OK" in res.stdout


def test_lazy_surface_resolves_every_export():
    """Laziness must not break the public surface: every name in __all__
    resolves (in-process — this may import jax, which is fine here)."""
    import keystone_tpu

    for name in keystone_tpu.__all__:
        assert getattr(keystone_tpu, name) is not None


def test_unknown_attribute_still_raises():
    import keystone_tpu

    with pytest.raises(AttributeError):
        keystone_tpu.definitely_not_a_symbol
