"""Checkpoint roundtrip coverage: every registered node type with fitted
state — and full ``a >> b`` pipelines — must survive save/load with
bit-identical leaves and identical ``__call__`` outputs, including in a
fresh process (the acceptance bar for load-or-fit)."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.core.checkpoint import (
    CheckpointError,
    checkpoint_exists,
    load_or_fit,
    load_pipeline,
    save_pipeline,
)
from keystone_tpu.core.pipeline import Pipeline, transformer
from keystone_tpu.ops.fisher import FisherVector
from keystone_tpu.ops.stats import (
    CosineRandomFeatures,
    NormalizeRows,
    RandomSignNode,
    SignedHellingerMapper,
    StandardScaler,
    StandardScalerModel,
)
from keystone_tpu.ops.util import MatrixVectorizer
from keystone_tpu.solvers.block import BlockLeastSquaresEstimator, BlockLinearMapper
from keystone_tpu.solvers.gmm import GaussianMixtureModel, GaussianMixtureModelEstimator
from keystone_tpu.solvers.linear import LinearMapEstimator, LinearMapper
from keystone_tpu.solvers.naive_bayes import NaiveBayesEstimator
from keystone_tpu.solvers.pca import BatchPCATransformer, PCAEstimator, PCATransformer
from keystone_tpu.solvers.whitening import ZCAWhitenerEstimator


def _assert_leaves_bit_identical(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x = np.asarray(x)
        y = np.asarray(y)
        assert x.dtype == y.dtype
        assert x.shape == y.shape
        np.testing.assert_array_equal(x, y)


def _roundtrip(tmp_path, node, batch, idx=0):
    stem = str(tmp_path / f"ck_{idx}")
    save_pipeline(stem, node)
    loaded = load_pipeline(stem)
    assert type(loaded) is type(node)
    _assert_leaves_bit_identical(node, loaded)
    np.testing.assert_array_equal(
        np.asarray(node(batch)), np.asarray(loaded(batch))
    )
    return loaded


class TestNodeRoundtrips:
    def test_block_linear_mapper(self, tmp_path, rng):
        x = jnp.asarray(rng.normal(size=(24, 10)), jnp.float32)
        y = jnp.asarray(rng.normal(size=(24, 3)), jnp.float32)
        model = BlockLeastSquaresEstimator(block_size=4, num_iter=2, lam=0.1).fit(x, y)
        _roundtrip(tmp_path, model, x)

    def test_pca(self, tmp_path, rng):
        samples = jnp.asarray(rng.normal(size=(50, 12)), jnp.float32)
        node = PCAEstimator(5).fit(samples)
        assert isinstance(node, PCATransformer)
        _roundtrip(tmp_path, node, samples)

    def test_batch_pca(self, tmp_path, rng):
        node = BatchPCATransformer(
            jnp.asarray(rng.normal(size=(12, 5)), jnp.float32)
        )
        batch = jnp.asarray(rng.normal(size=(3, 12, 7)), jnp.float32)
        _roundtrip(tmp_path, node, batch)

    def test_zca(self, tmp_path, rng):
        data = jnp.asarray(rng.normal(size=(40, 9)), jnp.float32)
        node = ZCAWhitenerEstimator().fit(data)
        _roundtrip(tmp_path, node, data)

    def test_gmm(self, tmp_path, rng):
        samples = jnp.asarray(rng.normal(size=(120, 6)), jnp.float32)
        node = GaussianMixtureModelEstimator(4, max_iter=5).fit(samples)
        _roundtrip(tmp_path, node, samples)

    def test_naive_bayes(self, tmp_path, rng):
        feats = rng.integers(0, 5, (30, 11)).astype(np.float32)
        labels = rng.integers(0, 3, 30)
        node = NaiveBayesEstimator(3).fit(feats, labels)
        _roundtrip(tmp_path, node, jnp.asarray(feats))

    def test_standard_scaler(self, tmp_path, rng):
        data = jnp.asarray(rng.normal(size=(25, 7)), jnp.float32)
        node = StandardScaler().fit(data)
        assert isinstance(node, StandardScalerModel)
        _roundtrip(tmp_path, node, data)

    def test_linear_mapper_with_nested_scaler(self, tmp_path, rng):
        x = jnp.asarray(rng.normal(size=(30, 6)), jnp.float32)
        y = jnp.asarray(rng.normal(size=(30, 2)), jnp.float32)
        node = LinearMapEstimator(lam=0.2).fit(x, y)
        assert isinstance(node, LinearMapper)
        assert node.feature_scaler is not None  # nested node roundtrips too
        _roundtrip(tmp_path, node, x)

    def test_fisher_vector_nests_gmm(self, tmp_path, rng):
        gmm = GaussianMixtureModel(
            rng.normal(size=(5, 3)),
            np.abs(rng.normal(size=(5, 3))) + 0.5,
            np.full(3, 1 / 3),
        )
        node = FisherVector(gmm)
        batch = jnp.asarray(rng.normal(size=(2, 5, 9)), jnp.float32)
        _roundtrip(tmp_path, node, batch)

    def test_cosine_random_features(self, tmp_path, rng):
        node = CosineRandomFeatures.create(6, 16, 0.5, jax.random.PRNGKey(0))
        batch = jnp.asarray(rng.normal(size=(4, 6)), jnp.float32)
        _roundtrip(tmp_path, node, batch)

    def test_random_sign_node(self, tmp_path, rng):
        node = RandomSignNode.create(10, jax.random.PRNGKey(1))
        batch = jnp.asarray(rng.normal(size=(3, 10)), jnp.float32)
        _roundtrip(tmp_path, node, batch)


class TestPipelineRoundtrips:
    def test_composed_pipeline(self, tmp_path, rng):
        scaler = StandardScalerModel(
            jnp.asarray(rng.normal(size=(8,)), jnp.float32),
            jnp.asarray(np.abs(rng.normal(size=(8,))) + 0.5, jnp.float32),
        )
        pca = PCATransformer(jnp.asarray(rng.normal(size=(8, 4)), jnp.float32))
        pipe = scaler >> pca >> NormalizeRows()
        batch = jnp.asarray(rng.normal(size=(5, 8)), jnp.float32)
        loaded = _roundtrip(tmp_path, pipe, batch)
        assert isinstance(loaded, Pipeline)
        assert [type(n).__name__ for n in loaded.nodes] == [
            "StandardScalerModel", "PCATransformer", "NormalizeRows",
        ]

    def test_voc_style_fisher_pipeline(self, tmp_path, rng):
        """The acceptance pipeline: PCA >> FisherVector(GMM) >> vectorize >>
        L2 >> hellinger >> L2 >> block linear model, saved as ONE object and
        reproducing predictions exactly."""
        desc_dim, vocab, k_cls = 6, 4, 3
        batch_pca = BatchPCATransformer(
            jnp.asarray(rng.normal(size=(16, desc_dim)), jnp.float32)
        )
        gmm = GaussianMixtureModelEstimator(vocab, max_iter=4).fit(
            jnp.asarray(rng.normal(size=(200, desc_dim)), jnp.float32)
        )
        feat_dim = 2 * desc_dim * vocab
        feats_rng = jnp.asarray(rng.normal(size=(20, feat_dim)), jnp.float32)
        labels = jnp.asarray(rng.normal(size=(20, k_cls)), jnp.float32)
        model = BlockLeastSquaresEstimator(block_size=16, num_iter=1, lam=0.1).fit(
            feats_rng, labels
        )
        pipe = Pipeline(
            [
                batch_pca,
                FisherVector(gmm),
                MatrixVectorizer(),
                NormalizeRows(),
                SignedHellingerMapper(),
                NormalizeRows(),
                model,
            ]
        )
        descs = jnp.asarray(rng.normal(size=(7, 16, 30)), jnp.float32)
        _roundtrip(tmp_path, pipe, descs)

    def test_dict_bundle(self, tmp_path, rng):
        pca = PCATransformer(jnp.asarray(rng.normal(size=(6, 3)), jnp.float32))
        gmm = GaussianMixtureModel(
            rng.normal(size=(3, 2)), np.abs(rng.normal(size=(3, 2))) + 1, [0.5, 0.5]
        )
        stem = str(tmp_path / "bundle")
        save_pipeline(stem, {"pca": pca, "gmm": gmm})
        loaded = load_pipeline(stem)
        assert set(loaded) == {"pca", "gmm"}
        _assert_leaves_bit_identical(pca, loaded["pca"])
        _assert_leaves_bit_identical(gmm, loaded["gmm"])


class TestCheckpointContract:
    def test_load_or_fit_fits_then_loads(self, tmp_path, rng):
        x = jnp.asarray(rng.normal(size=(20, 8)), jnp.float32)
        stem = str(tmp_path / "lof")
        calls = []

        class CountingPCA(PCAEstimator):
            def fit(self, samples):
                calls.append(1)
                return super().fit(samples)

        est = CountingPCA(3)
        first = load_or_fit(stem, est, x)
        assert checkpoint_exists(stem) and len(calls) == 1
        second = load_or_fit(stem, est, x)
        assert len(calls) == 1  # loaded, not refit
        _assert_leaves_bit_identical(first, second)

    def test_function_transformer_is_rejected(self, tmp_path):
        pipe = transformer(lambda b: b * 2)
        with pytest.raises(CheckpointError, match="FunctionTransformer"):
            save_pipeline(str(tmp_path / "bad"), pipe)

    def test_corrupt_manifest_rejected(self, tmp_path, rng):
        pca = PCATransformer(jnp.asarray(rng.normal(size=(4, 2)), jnp.float32))
        stem = str(tmp_path / "ck")
        save_pipeline(stem, pca)
        with open(stem + ".json") as fh:
            manifest = json.load(fh)
        manifest["arrays"]["a0"]["shape"] = [9, 9]
        with open(stem + ".json", "w") as fh:
            json.dump(manifest, fh)
        with pytest.raises(CheckpointError, match="corrupt|schema"):
            load_pipeline(stem)

    def test_version_mismatch_rejected(self, tmp_path, rng):
        pca = PCATransformer(jnp.asarray(rng.normal(size=(4, 2)), jnp.float32))
        stem = str(tmp_path / "ck")
        save_pipeline(stem, pca)
        with open(stem + ".json") as fh:
            manifest = json.load(fh)
        manifest["version"] = 99
        with open(stem + ".json", "w") as fh:
            json.dump(manifest, fh)
        with pytest.raises(CheckpointError, match="version"):
            load_pipeline(stem)

    def test_bfloat16_leaves_roundtrip(self, tmp_path, rng):
        node = PCATransformer(
            jnp.asarray(rng.normal(size=(6, 3)), jnp.bfloat16)
        )
        stem = str(tmp_path / "bf16")
        save_pipeline(stem, node)
        loaded = load_pipeline(stem)
        assert loaded.pca_mat.dtype == jnp.bfloat16
        _assert_leaves_bit_identical(node, loaded)


class TestTopologyGuard:
    """Mesh-aware restore safety: a checkpoint holding SHARDED arrays must
    refuse (typed CheckpointMismatch) to load onto a different device/mesh
    topology; fully-replicated checkpoints stay portable."""

    def _edit_topology(self, stem, **changes):
        with open(stem + ".json") as fh:
            manifest = json.load(fh)
        manifest["topology"].update(changes)
        with open(stem + ".json", "w") as fh:
            json.dump(manifest, fh)

    def test_topology_and_replication_recorded(self, tmp_path, rng, mesh8):
        pca = PCATransformer(jnp.asarray(rng.normal(size=(4, 2)), jnp.float32))
        stem = save_pipeline(str(tmp_path / "topo"), pca)
        with open(stem + ".json") as fh:
            manifest = json.load(fh)
        topo = manifest["topology"]
        assert topo["platform"] == "cpu" and topo["device_count"] == 8
        assert manifest["all_replicated"] is True

        from keystone_tpu.parallel.mesh import row_sharding, use_mesh

        sharded = PCATransformer(
            jax.device_put(
                jnp.asarray(rng.normal(size=(16, 2)), jnp.float32),
                row_sharding(mesh8),
            )
        )
        with use_mesh(mesh8):
            stem2 = save_pipeline(str(tmp_path / "topo_sharded"), sharded)
        with open(stem2 + ".json") as fh:
            manifest2 = json.load(fh)
        assert manifest2["all_replicated"] is False
        assert manifest2["topology"]["mesh"] == {"data": 8, "model": 1}

    def test_sharded_checkpoint_rejects_foreign_topology(
        self, tmp_path, rng, mesh8
    ):
        from keystone_tpu.core.checkpoint import CheckpointMismatch
        from keystone_tpu.parallel.mesh import row_sharding

        node = PCATransformer(
            jax.device_put(
                jnp.asarray(rng.normal(size=(16, 2)), jnp.float32),
                row_sharding(mesh8),
            )
        )
        stem = save_pipeline(str(tmp_path / "foreign"), node)
        self._edit_topology(stem, device_count=16)
        with pytest.raises(CheckpointMismatch, match="topology"):
            load_pipeline(stem)
        # The typed mismatch is still a CheckpointError for callers that
        # catch broadly (load_or_fit error paths).
        assert issubclass(CheckpointMismatch, CheckpointError)

    def test_sharded_checkpoint_rejects_different_mesh(
        self, tmp_path, rng, mesh8
    ):
        from keystone_tpu.core.checkpoint import CheckpointMismatch
        from keystone_tpu.parallel.mesh import row_sharding

        node = PCATransformer(
            jax.device_put(
                jnp.asarray(rng.normal(size=(16, 2)), jnp.float32),
                row_sharding(mesh8),
            )
        )
        stem = save_pipeline(str(tmp_path / "mesh_drift"), node)
        self._edit_topology(stem, mesh={"data": 4, "model": 2})
        with pytest.raises(CheckpointMismatch, match="reshard"):
            load_pipeline(stem)

    def test_replicated_checkpoint_loads_across_topologies(
        self, tmp_path, rng
    ):
        node = PCATransformer(jnp.asarray(rng.normal(size=(4, 2)), jnp.float32))
        stem = save_pipeline(str(tmp_path / "portable"), node)
        self._edit_topology(stem, device_count=1024, platform="tpu")
        loaded = load_pipeline(stem)  # replicated state is portable
        _assert_leaves_bit_identical(node, loaded)

    def test_pre_guard_manifest_still_loads(self, tmp_path, rng):
        """Backward compat: manifests written before the topology guard
        (no ``topology`` key) load with a warning, not a crash."""
        node = PCATransformer(jnp.asarray(rng.normal(size=(4, 2)), jnp.float32))
        stem = save_pipeline(str(tmp_path / "old"), node)
        with open(stem + ".json") as fh:
            manifest = json.load(fh)
        del manifest["topology"]
        del manifest["all_replicated"]
        with open(stem + ".json", "w") as fh:
            json.dump(manifest, fh)
        loaded = load_pipeline(stem)
        _assert_leaves_bit_identical(node, loaded)


class TestFreshProcessReload:
    def test_predictions_identical_in_fresh_process(self, tmp_path, rng):
        """fit -> save -> reload in a NEW interpreter -> identical scores."""
        x = jnp.asarray(rng.normal(size=(24, 10)), jnp.float32)
        y = jnp.asarray(rng.normal(size=(24, 3)), jnp.float32)
        scaler = StandardScaler().fit(x)
        model = BlockLeastSquaresEstimator(block_size=4, num_iter=2, lam=0.1).fit(
            scaler(x), y
        )
        pipe = Pipeline([scaler, model])
        stem = str(tmp_path / "fresh")
        save_pipeline(stem, pipe)
        expected = np.asarray(pipe(x))
        np.save(tmp_path / "input.npy", np.asarray(x))
        np.save(tmp_path / "expected.npy", expected)
        script = (
            "import os; os.environ['JAX_PLATFORMS']='cpu'\n"
            "import numpy as np, jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from keystone_tpu.core.checkpoint import load_pipeline\n"
            f"pipe = load_pipeline({stem!r})\n"
            f"x = np.load({str(tmp_path / 'input.npy')!r})\n"
            f"expected = np.load({str(tmp_path / 'expected.npy')!r})\n"
            "got = np.asarray(pipe(x))\n"
            "np.testing.assert_array_equal(got, expected)\n"
            "print('FRESH_PROCESS_OK')\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        res = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            timeout=300,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert res.returncode == 0, res.stderr[-2000:]
        assert "FRESH_PROCESS_OK" in res.stdout
