"""Fault-tolerance suite: loader skip-and-count, IO retry, solver jitter
recovery, finite-fit guards, and resumable BCD — driven by the injection
harness in tests/faults.py.  All tier-1 fast (no `slow` marks)."""

import logging
import os

import jax.numpy as jnp
import numpy as np
import pytest

import keystone_tpu.loaders.image_loaders as il
from keystone_tpu.core.checkpoint import CheckpointError
from keystone_tpu.core.resilience import (
    assert_all_finite,
    counters,
    retry,
)
from keystone_tpu.solvers.block import (
    BlockLeastSquaresEstimator,
    load_bcd_checkpoint,
)
from keystone_tpu.solvers.normal_equations import solve_gram_l2

from faults import (  # tests/ is on sys.path under pytest's default import mode
    flaky,
    inject_nan,
    make_image_tar,
    rank_deficient_gram,
    transient_faults,
    truncate_tail,
)


@pytest.fixture(autouse=True)
def _reset_counters():
    counters.reset()
    yield
    counters.reset()


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    monkeypatch.setenv("KEYSTONE_IO_BACKOFF", "0.001")


class TestLoaderFaults:
    def test_corrupt_member_mid_tar_is_counted_skip(self, tmp_path, rng):
        tar = str(tmp_path / "imgs.tar")
        make_image_tar(tar, 6, rng, corrupt=(2, 3))
        got = list(il._iter_tar_images(tar, num_threads=1))
        assert len(got) == 4  # the 4 healthy members decode
        assert counters.get("corrupt_image") == 2

    def test_corrupt_member_counted_under_thread_pool(self, tmp_path, rng):
        tar = str(tmp_path / "imgs.tar")
        make_image_tar(tar, 8, rng, corrupt=(0, 5))
        got = list(il._iter_tar_images(tar, num_threads=4))
        assert len(got) == 6
        assert counters.get("corrupt_image") == 2

    def test_truncated_tar_tail_survived(self, tmp_path, rng):
        tar = str(tmp_path / "imgs.tar")
        make_image_tar(tar, 5, rng)
        # cut mid-archive (tar pads to 10 KiB records, so a small trim only
        # removes padding): half the members and the end-of-archive marker
        # are gone
        truncate_tail(tar, os.path.getsize(tar) // 2)
        got = list(il._iter_tar_images(tar, num_threads=1))
        # everything before the cut still loads; the damaged tail is
        # counted (stream/member error or failed decode); nothing crashes
        assert 1 <= len(got) < 5
        total_faults = sum(counters.counts().values())
        assert total_faults >= 1

    def test_transient_open_error_retried(self, tmp_path, rng):
        tar = str(tmp_path / "imgs.tar")
        make_image_tar(tar, 3, rng)
        with transient_faults(il.tarfile, "open", failures=2):
            got = list(il._iter_tar_images(tar, num_threads=1))
        assert len(got) == 3
        assert counters.get("io_retry") == 2

    def test_retry_exhaustion_raises(self, tmp_path, rng):
        tar = str(tmp_path / "imgs.tar")
        make_image_tar(tar, 2, rng)
        with transient_faults(il.tarfile, "open", failures=99):
            with pytest.raises(OSError):
                list(il._iter_tar_images(tar, num_threads=1))

    def test_decode_rejects_corrupt_bytes(self, rng):
        from faults import corrupt_jpeg, make_jpeg_bytes

        good = make_jpeg_bytes(rng)
        assert il.decode_image(good) is not None
        assert il.decode_image(corrupt_jpeg(good, rng)) is None


class TestRetryPrimitive:
    def test_succeeds_after_transient_failures(self):
        fn = flaky(lambda: "ok", failures=2)
        assert retry(fn, attempts=3, backoff=0.001)() == "ok"
        assert fn.state["calls"] == 3

    def test_non_retryable_exception_propagates_immediately(self):
        fn = flaky(lambda: "ok", failures=5, exc=ValueError)
        with pytest.raises(ValueError):
            retry(fn, attempts=5, backoff=0.001)()
        assert fn.state["calls"] == 1  # ValueError is not transient

    def test_timeout_budget_caps_attempts(self):
        fn = flaky(lambda: "ok", failures=50)
        with pytest.raises(OSError):
            retry(fn, attempts=50, backoff=0.05, timeout=0.01)()
        assert fn.state["calls"] < 50


class TestNumericsGuards:
    def test_jitter_retry_recovers_rank_deficient_gram(self, rng, caplog):
        ata, atb = rank_deficient_gram(rng)
        with caplog.at_level(logging.WARNING, "keystone_tpu.solvers.normal_equations"):
            x = solve_gram_l2(jnp.asarray(ata), jnp.asarray(atb), 0.0)
        assert bool(jnp.all(jnp.isfinite(x)))
        assert any("jitter" in r.message for r in caplog.records)

    def test_nonfinite_gram_raises(self, rng):
        ata, atb = rank_deficient_gram(rng)
        ata[0, 0] = np.nan
        with pytest.raises(FloatingPointError):
            solve_gram_l2(jnp.asarray(ata), jnp.asarray(atb), 0.1)

    def test_guard_can_be_disabled(self, rng, monkeypatch):
        monkeypatch.setenv("KEYSTONE_NUMERICS_GUARD", "0")
        ata, atb = rank_deficient_gram(rng)
        x = solve_gram_l2(jnp.asarray(ata), jnp.asarray(atb), 0.0)
        assert not bool(jnp.all(jnp.isfinite(x)))  # unguarded = raw NaNs

    def test_nan_batch_poisons_fit_and_is_caught(self, rng):
        x = rng.normal(size=(32, 8)).astype(np.float32)
        y = rng.normal(size=(32, 2)).astype(np.float32)
        x_bad = inject_nan(x, rng, frac=0.02)
        est = BlockLeastSquaresEstimator(block_size=4, num_iter=1, lam=0.1)
        model = est.fit(jnp.asarray(x_bad), jnp.asarray(y))
        with pytest.raises(FloatingPointError):
            assert_all_finite(model, "poisoned fit")

    def test_assert_all_finite_passes_clean_tree(self, rng):
        x = rng.normal(size=(32, 8)).astype(np.float32)
        y = rng.normal(size=(32, 2)).astype(np.float32)
        est = BlockLeastSquaresEstimator(block_size=4, num_iter=1, lam=0.1)
        model = est.fit(jnp.asarray(x), jnp.asarray(y))
        assert assert_all_finite(model, "clean fit") is model


class _KillAfter(Exception):
    pass


class TestResumableBCD:
    def _data(self, rng, n=96, d=22, k=3):
        x = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
        y = jnp.asarray(rng.normal(size=(n, k)), jnp.float32)
        return x, y

    def test_stepwise_matches_fused(self, rng):
        x, y = self._data(rng)
        est = BlockLeastSquaresEstimator(block_size=6, num_iter=2, lam=0.05)
        fused = est.fit(x, y)
        seen = []
        stepwise = est.fit(x, y, checkpoint=seen.append)
        np.testing.assert_allclose(
            np.asarray(fused(x)), np.asarray(stepwise(x)), atol=1e-4
        )
        # one state per (epoch, block): ceil(22/6)=4 blocks x 2 epochs
        assert len(seen) == 8
        assert (seen[-1]["epoch"], seen[-1]["block"]) == (1, 3)

    def test_interrupted_fit_resumes_from_disk(self, rng, tmp_path):
        x, y = self._data(rng)
        est = BlockLeastSquaresEstimator(block_size=6, num_iter=2, lam=0.05)
        fused = est.fit(x, y)

        path = str(tmp_path / "bcd_state")
        from keystone_tpu.solvers.block import bcd_checkpoint_writer

        write = bcd_checkpoint_writer(path)
        fired = []

        def killer(state):
            write(state)
            fired.append(state["block"])
            if len(fired) == 3:  # die mid-epoch, after block 2 of 4
                raise _KillAfter

        with pytest.raises(_KillAfter):
            est.fit(x, y, checkpoint=killer)
        assert os.path.exists(path + ".npz")

        state = load_bcd_checkpoint(path)
        assert (state["epoch"], state["block"]) == (0, 2)

        resumed = est.fit(x, y, checkpoint=path, resume_from=path)
        np.testing.assert_allclose(
            np.asarray(fused(x)), np.asarray(resumed(x)), atol=1e-4
        )

    def test_resume_rejects_mismatched_fit(self, rng, tmp_path):
        x, y = self._data(rng)
        est = BlockLeastSquaresEstimator(block_size=6, num_iter=2, lam=0.05)
        path = str(tmp_path / "bcd_state")
        est.fit(x, y, checkpoint=path)  # completes; final state on disk
        other = BlockLeastSquaresEstimator(block_size=9, num_iter=2, lam=0.05)
        with pytest.raises(CheckpointError):
            other.fit(x, y, resume_from=path)
        # a different regularizer must also be rejected — resuming with it
        # would mix two lambdas in one model
        relam = BlockLeastSquaresEstimator(block_size=6, num_iter=2, lam=0.5)
        with pytest.raises(CheckpointError, match="lam"):
            relam.fit(x, y, resume_from=path)

    def test_resume_rejects_different_data(self, rng, tmp_path):
        from keystone_tpu.solvers.block import save_bcd_checkpoint

        x, y = self._data(rng)
        est = BlockLeastSquaresEstimator(block_size=6, num_iter=2, lam=0.05)
        path = str(tmp_path / "bcd_state")

        def killer(state):
            save_bcd_checkpoint(path, state)
            raise _KillAfter

        with pytest.raises(_KillAfter):
            est.fit(x, y, checkpoint=killer)
        # same shapes, different content: the data fingerprint must refuse
        with pytest.raises(CheckpointError, match="DIFFERENT data"):
            est.fit(x * 2.0, y, resume_from=path)

    def test_completed_state_resume_is_idempotent(self, rng, tmp_path):
        x, y = self._data(rng)
        est = BlockLeastSquaresEstimator(block_size=6, num_iter=1, lam=0.05)
        path = str(tmp_path / "bcd_state")
        first = est.fit(x, y, checkpoint=path)
        again = est.fit(x, y, checkpoint=path, resume_from=path)
        np.testing.assert_allclose(
            np.asarray(first(x)), np.asarray(again(x)), atol=1e-5
        )

    def test_checkpoint_under_mesh_rejected(self, rng, mesh8):
        x, y = self._data(rng)
        est = BlockLeastSquaresEstimator(
            block_size=6, num_iter=1, lam=0.05, mesh=mesh8
        )
        with pytest.raises(ValueError):
            est.fit(x, y, checkpoint=lambda s: None)


class TestBlockedDesignContract:
    def test_num_features_beyond_matrix_raises(self, rng):
        from keystone_tpu.solvers.block import _blocked_design_matrix

        feats = rng.normal(size=(10, 8)).astype(np.float32)
        with pytest.raises(ValueError, match="num_features"):
            _blocked_design_matrix(feats, block_size=4, num_features=12)

    def test_valid_num_features_still_slices(self, rng):
        from keystone_tpu.solvers.block import _blocked_design_matrix

        feats = rng.normal(size=(10, 8)).astype(np.float32)
        x, widths = _blocked_design_matrix(feats, block_size=4, num_features=6)
        assert widths == (4, 2)
        assert x.shape == (10, 8)  # 2 blocks x bs=4, short block zero-padded
        np.testing.assert_array_equal(np.asarray(x[:, 6:8]), 0.0)


class TestDeadline:
    """The wall-clock watchdog: hangs become typed, counted, phase-named
    ``DeadlineExceeded`` errors — never an indefinite stall."""

    def test_hang_is_interrupted_and_typed(self):
        import time

        from keystone_tpu.core.resilience import DeadlineExceeded, deadline

        before = counters.get("deadline_exceeded")
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded) as exc:
            with deadline(0.2, phase="ingest"):
                time.sleep(30.0)  # the hang — must NOT run to completion
        elapsed = time.monotonic() - t0
        assert elapsed < 5.0  # interrupted mid-sleep, not post-hoc
        assert exc.value.phase == "ingest"
        assert exc.value.seconds == pytest.approx(0.2)
        assert counters.get("deadline_exceeded") == before + 1

    def test_fast_block_passes_untouched(self):
        from keystone_tpu.core.resilience import deadline

        before = counters.get("deadline_exceeded")
        with deadline(30.0, phase="quick"):
            out = 1 + 1
        assert out == 2
        assert counters.get("deadline_exceeded") == before

    def test_nested_deadlines_restore_the_outer_timer(self):
        import time

        from keystone_tpu.core.resilience import DeadlineExceeded, deadline

        with pytest.raises(DeadlineExceeded) as exc:
            with deadline(0.4, phase="outer"):
                with deadline(30.0, phase="inner"):
                    pass  # inner finishes instantly; outer must survive
                time.sleep(30.0)
        assert exc.value.phase == "outer"

    def test_loose_inner_deadline_cannot_suspend_a_tighter_outer(self):
        """Arming a 600s inner deadline under a 0.3s outer one must NOT
        park the outer bound for 600s — the tighter remaining budget
        wins, attributed to the phase that was executing."""
        import time

        from keystone_tpu.core.resilience import DeadlineExceeded, deadline

        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded) as exc:
            with deadline(0.3, phase="outer"):
                with deadline(600.0, phase="inner"):
                    time.sleep(30.0)
        assert time.monotonic() - t0 < 5.0
        assert exc.value.phase == "inner"  # where execution was
        assert exc.value.seconds == pytest.approx(0.3, abs=0.1)

    def test_outer_deadline_bounds_a_hung_recovery_handler(self):
        """An `except DeadlineExceeded:` suite that itself hangs must
        still be bounded by the enclosing deadline: the unwind-race
        postponement is recency-bounded, so only an error raised moments
        ago defers the outer trip — a hung recovery path does not get
        postponed forever."""
        import time

        from keystone_tpu.core.resilience import DeadlineExceeded, deadline

        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded) as exc:
            with deadline(0.6, phase="outer"):
                try:
                    with deadline(0.1, phase="inner"):
                        time.sleep(30.0)
                except DeadlineExceeded:
                    time.sleep(30.0)  # the hung recovery path
        assert exc.value.phase == "outer"
        assert time.monotonic() - t0 < 5.0

    def test_nonpositive_budget_rejected(self):
        from keystone_tpu.core.resilience import deadline

        with pytest.raises(ValueError, match="positive"):
            with deadline(0.0):
                pass

    def test_off_main_thread_falls_back_to_posthoc(self):
        """Signals cannot be armed off the main thread: the fallback still
        converts an overrun into the typed error on exit."""
        import threading

        from keystone_tpu.core.resilience import DeadlineExceeded, deadline

        result = {}

        def work():
            try:
                with deadline(0.05, phase="bg"):
                    import time

                    time.sleep(0.2)
                result["outcome"] = "no_error"
            except DeadlineExceeded as e:
                result["outcome"] = "typed"
                result["phase"] = e.phase

        t = threading.Thread(target=work)
        t.start()
        t.join(10.0)
        assert result == {"outcome": "typed", "phase": "bg"}
