"""Distributed (mesh) execution tests — sharded run == single-device run.

The reference exercises distribution with local multi-partition RDDs
(src/test/scala/pipelines/LocalSparkContext.scala:9-43, e.g. numParts=3 in
BlockWeightedLeastSquaresSuite.scala:66-67); here the analog is the virtual
8-device CPU platform from conftest, with (data, model) meshes, and the
criterion is that every solver's mesh output matches its single-device
output within about_eq tolerance.
"""

import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.util import VectorSplitter
from keystone_tpu.parallel.mesh import use_mesh
from keystone_tpu.solvers.block import BlockLeastSquaresEstimator
from keystone_tpu.solvers.linear import LinearMapEstimator
from keystone_tpu.solvers.normal_equations import (
    bcd_least_squares_l2,
    solve_least_squares,
)
from keystone_tpu.solvers.weighted import BlockWeightedLeastSquaresEstimator
from keystone_tpu.utils.stats import about_eq


def _problem(rng, n=192, d=24, k=4, noise=0.05):
    x_true = rng.normal(size=(d, k))
    a = rng.normal(size=(n, d))
    b = a @ x_true + noise * rng.normal(size=(n, k))
    return jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32)


def test_solve_least_squares_mesh_matches_local(rng, mesh42):
    a, b = _problem(rng)
    local = solve_least_squares(a, b, 0.7)
    sharded = solve_least_squares(a, b, 0.7, mesh=mesh42)
    assert about_eq(np.asarray(sharded), np.asarray(local), 1e-4)


def test_bcd_mesh_matches_local(rng, mesh42):
    a, b = _problem(rng, d=30)
    blocks = [a[:, :10], a[:, 10:20], a[:, 20:]]
    local = bcd_least_squares_l2(blocks, b, 0.5, 3)
    sharded = bcd_least_squares_l2(blocks, b, 0.5, 3, mesh=mesh42)
    for lm, sm in zip(local, sharded):
        assert about_eq(np.asarray(sm), np.asarray(lm), 1e-4)


def test_linear_map_estimator_mesh_matches_local(rng, mesh8):
    # n=190 is NOT divisible by the 8-way data axis: exercises the
    # pad-rows + nvalid masking path end to end.
    a, b = _problem(rng, n=190)
    local = LinearMapEstimator(lam=0.3).fit(a, b)
    sharded = LinearMapEstimator(lam=0.3, mesh=mesh8).fit(a, b)
    assert about_eq(np.asarray(sharded.x), np.asarray(local.x), 1e-4)
    assert about_eq(np.asarray(sharded.b), np.asarray(local.b), 1e-4)
    pred_l = local(a)
    pred_s = sharded(a)
    assert about_eq(np.asarray(pred_s), np.asarray(pred_l), 1e-4)


def test_block_least_squares_ambient_mesh_matches_local(rng, mesh42):
    a, b = _problem(rng, n=188, d=36)
    local = BlockLeastSquaresEstimator(12, num_iter=2, lam=0.4).fit(a, b)
    with use_mesh(mesh42):
        sharded = BlockLeastSquaresEstimator(12, num_iter=2, lam=0.4).fit(a, b)
    for lm, sm in zip(local.xs, sharded.xs):
        assert about_eq(np.asarray(sm), np.asarray(lm), 1e-4)
    assert about_eq(np.asarray(sharded(a)), np.asarray(local(a)), 1e-4)


def test_bwls_mesh42_matches_local(rng, mesh42):
    n, d, k = 120, 18, 5
    labels_int = rng.integers(0, k, size=n)
    feats = rng.normal(size=(n, d)).astype(np.float32)
    labels = (2.0 * np.eye(k)[labels_int] - 1.0).astype(np.float32)
    est = dict(block_size=8, num_iter=2, lam=0.1, mixture_weight=0.4)
    local = BlockWeightedLeastSquaresEstimator(**est, class_chunk=1).fit(
        feats, labels
    )
    sharded = BlockWeightedLeastSquaresEstimator(
        **est, class_chunk=4, mesh=mesh42
    ).fit(feats, labels)
    for lm, sm in zip(local.xs, sharded.xs):
        assert about_eq(np.asarray(sm), np.asarray(lm), 1e-3)
    assert about_eq(np.asarray(sharded.b), np.asarray(local.b), 1e-3)
    assert about_eq(
        np.asarray(sharded(jnp.asarray(feats))),
        np.asarray(local(jnp.asarray(feats))),
        1e-3,
    )


def test_bwls_device_sharded_inputs_match_local(rng, mesh42):
    """fit() fed row-sharded device arrays + nvalid (the workload path —
    no host round-trip) must match the host-input single-device fit."""
    from keystone_tpu.parallel.mesh import padded_shard_rows

    n, d, k = 117, 16, 4  # n deliberately not divisible by the data axis
    labels_int = rng.integers(0, k, size=n)
    feats = rng.normal(size=(n, d)).astype(np.float32)
    labels = (2.0 * np.eye(k)[labels_int] - 1.0).astype(np.float32)
    est = dict(block_size=8, num_iter=2, lam=0.1, mixture_weight=0.4)
    local = BlockWeightedLeastSquaresEstimator(**est, class_chunk=1).fit(
        feats, labels
    )
    feats_dev, nvalid = padded_shard_rows(feats, mesh42)
    labels_dev, _ = padded_shard_rows(labels, mesh42)
    sharded = BlockWeightedLeastSquaresEstimator(
        **est, class_chunk=4, mesh=mesh42
    ).fit(feats_dev, labels_dev, nvalid=nvalid)
    for lm, sm in zip(local.xs, sharded.xs):
        assert about_eq(np.asarray(sm), np.asarray(lm), 1e-3)
    assert about_eq(np.asarray(sharded.b), np.asarray(local.b), 1e-3)


def test_graft_dryrun_impl_in_process(devices):
    """The driver's multi-chip dryrun must drive the real solver path."""
    import os
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo_root)
    try:
        import __graft_entry__ as graft

        graft._dryrun_impl(8)
    finally:
        sys.path.remove(repo_root)


def test_multiblock_bcd_model_sharded_matches_monolithic(rng, mesh42):
    """The 256k-dim analog (VERDICT r2 #9; reference VectorSplitter.scala:10-36
    + ImageNetSiftLcsFV.scala:186-188): the model dimension deliberately
    exceeds a per-device column budget, so the fit MUST run as multi-block
    BCD — and on the 4x2 mesh each block's solve is additionally sharded
    over the model axis.  Both the blocked structure and the sharding must
    be semantically invisible: the blocked sharded fit has to agree with
    the monolithic single-device normal-equations solve."""
    n, d, k = 600, 96, 5
    device_column_budget = 16  # d/budget = 6 blocks; budget splits 2-ways
    a, b = _problem(rng, n=n, d=d, k=k, noise=0.1)

    mono = LinearMapEstimator(lam=0.5).fit(a, b)

    blocks = VectorSplitter(device_column_budget)(a)
    assert len(blocks) == d // device_column_budget  # genuinely multi-block
    est = BlockLeastSquaresEstimator(
        device_column_budget, num_iter=12, lam=0.5, mesh=mesh42
    )
    blocked = est.fit(blocks, b)

    # (a) blocked+sharded converges to the monolithic solution: compare
    # predictions (the model surface the reference equivalence suite uses,
    # BlockLinearMapperSuite.scala:32-53)
    pred_mono = np.asarray(mono(a))
    pred_blocked = np.asarray(blocked(a))
    scale = np.abs(pred_mono).max()
    assert np.abs(pred_blocked - pred_mono).max() < 2e-2 * scale

    # (b) the sharded multi-block fit is numerically the LOCAL multi-block
    # fit (sharding changes nothing but the schedule)
    local_blocked = BlockLeastSquaresEstimator(
        device_column_budget, num_iter=12, lam=0.5
    ).fit(blocks, b)
    for lm, sm in zip(local_blocked.xs, blocked.xs):
        assert about_eq(np.asarray(sm), np.asarray(lm), 1e-3)
