"""Device cost attribution tests (core.profiler, ISSUE 14): the
per-program MFU ledger, the flops-hint audit, the HBM watermark sampler +
plan-drift accounting, triggered XLA capture rate limiting, the
disabled-mode zero-overhead bound, and the cross-process stitched request
waterfall (a REAL two-process serve over sockets)."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.core import autoshard
from keystone_tpu.core import memory as kmem
from keystone_tpu.core import optimize as kopt
from keystone_tpu.core import profiler as kprof
from keystone_tpu.core import serve as kserve
from keystone_tpu.core import telemetry as ktelemetry
from keystone_tpu.core import trace as ktrace
from keystone_tpu.core import wire as kwire
from keystone_tpu.core.pipeline import FunctionTransformer
from keystone_tpu.core.resilience import counters
from keystone_tpu.solvers.block import BlockLeastSquaresEstimator

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_profiler():
    kprof.reset_state()
    yield
    kprof.reset_state()


@pytest.fixture
def fresh_log(tmp_path, monkeypatch):
    """A private plan log (the conftest one is process-shared; drift-row
    tests must not leak evidence into other tests' calibration)."""
    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, path)
    autoshard.clear_outcome_cache()
    yield path
    autoshard.clear_outcome_cache()


def _matmul_compiled(n=64):
    f = jax.jit(lambda x: x @ x)
    x = jnp.asarray(np.ones((n, n), np.float32))
    return f, x, f.lower(x).compile()


# -- the cost-analysis reader and the ledger ----------------------------------


class TestLedger:
    def test_cost_pair_and_jit_cost(self):
        f, x, compiled = _matmul_compiled(64)
        flops, ba = kprof.cost_pair(compiled)
        assert flops and flops >= 2 * 64**3 * 0.9  # ~2n^3 matmul flops
        assert ba and ba > 0
        assert kprof.jit_cost(f, x) == (flops, ba)

    def test_record_program_mfu_math(self):
        _f, _x, compiled = _matmul_compiled(64)
        with kprof.profiled(True):
            row = kprof.record_program("t", compiled, 0.01)
            rates = kprof.device_rates()
            assert row["mfu"] == pytest.approx(
                row["flops"] / 0.01 / rates["peak_flops"], abs=1e-6
            )
            led = kprof.ledger()["t"]
            assert led["runs"] == 1
            assert led["mfu"] == pytest.approx(row["mfu"], rel=1e-3)
            assert led["bound"] in ("compute", "memory")

    def test_ledger_aggregates_runs(self):
        _f, _x, compiled = _matmul_compiled(32)
        with kprof.profiled(True):
            kprof.record_program("agg", compiled, 0.01)
            kprof.record_program("agg", compiled, 0.03)
            led = kprof.ledger()["agg"]
        assert led["runs"] == 2
        assert led["wall_seconds"] == pytest.approx(0.04, rel=1e-6)

    def test_run_ladder_feeds_ledger_and_solver_hint_audited(self, rng):
        """A profiled BCD fit lands its chosen tier in the ledger AND its
        hand-derived flops hint is audited against the compiled
        cost_analysis within the tolerance factor — the regression pin on
        hint/compiler agreement (measured ~1.03x on this shape)."""
        x = jnp.asarray(rng.normal(size=(512, 128)).astype(np.float32))
        y = jnp.asarray(
            2.0 * np.eye(4)[rng.integers(0, 4, 512)] - 1.0, jnp.float32
        )
        with kprof.profiled(True):
            BlockLeastSquaresEstimator(128, 2, 1e-2).fit(x, y)
            led = kprof.ledger()
            audits = kprof.flops_audits()
        rows = {k: v for k, v in led.items() if k.startswith("bcd_fit")}
        assert rows, f"no bcd_fit ledger rows in {sorted(led)}"
        chosen = rows[sorted(rows)[0]]
        assert chosen["runs"] >= 1 and chosen["wall_seconds"] > 0
        assert chosen["flops"]  # cost analysis reached the ledger
        audit = audits.get("bcd_fit:fused")
        assert audit is not None, f"no fused audit in {sorted(audits)}"
        assert audit["ok"], audit
        ratio = audit["ratio"]
        assert 1 / kprof.FLOPS_AUDIT_TOL <= ratio <= kprof.FLOPS_AUDIT_TOL

    def test_flops_hint_mismatch_is_counted(self):
        _f, _x, compiled = _matmul_compiled(64)
        before = counters.get("flops_hint_mismatch")
        with kprof.profiled(True):
            ratio = kprof.audit_flops("bogus", 1e15, compiled)
        assert ratio is not None and ratio > kprof.FLOPS_AUDIT_TOL
        assert counters.get("flops_hint_mismatch") == before + 1
        assert kprof.flops_audits()["bogus"]["ok"] is False


# -- disabled-mode zero overhead ----------------------------------------------


class TestDisabledMode:
    def test_disabled_hooks_are_inert(self, rng):
        """With the profiler OFF (the default), every hook is one flag
        check: nothing lands in the ledger, no sampler thread exists, no
        registry metric moves — the zero-overhead bound the serving and
        solve paths rely on."""
        assert not kprof.enabled()
        _f, _x, compiled = _matmul_compiled(32)
        assert kprof.record_program("off", compiled, 0.01) is None
        assert kprof.audit_flops("off", 1e6, compiled) is None
        x = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))
        y = jnp.asarray(
            2.0 * np.eye(4)[rng.integers(0, 4, 128)] - 1.0, jnp.float32
        )
        before = ktrace.metrics.get("profiler_programs_recorded")
        BlockLeastSquaresEstimator(64, 1, 1e-2).fit(x, y)
        assert kprof.ledger() == {}
        assert kprof.sampler() is None
        assert ktrace.metrics.get("profiler_programs_recorded") == before

    def test_phase_is_noop_when_disabled(self):
        with kprof.phase("anything"):
            pass
        assert kprof.sampler() is None

    def test_profiled_restores_disabled(self):
        with kprof.profiled(True, stats_fn=lambda: 1024):
            assert kprof.enabled()
            assert kprof.sampler() is not None
        assert not kprof.enabled()
        assert kprof.sampler() is None


# -- the HBM watermark sampler + drift accounting -----------------------------


def _plan(total_bytes, label="p") -> kmem.MemoryPlan:
    return kmem.MemoryPlan(
        label=label, admitted=True, reason="test",
        argument_bytes=total_bytes // 2, temp_bytes=total_bytes // 4,
        output_bytes=total_bytes - total_bytes // 2 - total_bytes // 4,
        total_bytes=total_bytes, analyzed=True,
    )


class TestWatermark:
    def test_phase_watermarks(self):
        seq = iter([100, 500, 300, 200])
        with kprof.profiled(
            True, interval_ms=10_000.0, stats_fn=lambda: next(seq)
        ):
            s = kprof.sampler()
            s.sample()  # 100, no phase
            with kprof.phase("solve"):
                s.sample()  # 500 attributed to "solve"
            # phase exit samples once more (300)
            assert s.watermark("solve") == 500
            assert s.watermark() == 500  # global peak
            with kprof.phase("serve"):
                pass  # exit sample: 200
            assert s.watermark("serve") == 200

    def test_phase_reentry_clears_stale_peak(self):
        """A phase name reused for a SMALLER run must not inherit the
        bigger run's watermark — stale peaks would read as spurious
        drift and poison the hbm_drift calibration rows."""
        seq = iter([5000, 100, 100])
        with kprof.profiled(
            True, interval_ms=10_000.0, stats_fn=lambda: next(seq)
        ):
            s = kprof.sampler()
            with kprof.phase("solve"):
                s.sample()  # 5000: the big run
            with kprof.phase("solve"):
                s.sample()  # 100: the small run — fresh watermark
            assert s.watermark("solve") == 100

    def test_audit_skips_without_a_phase_sample(self):
        """No phase watermark -> skipped, never guessed from the
        process-lifetime global peak (which describes whatever ran
        biggest since import, not this plan)."""
        with kprof.profiled(
            True, interval_ms=10_000.0, stats_fn=lambda: 9999
        ):
            kprof.sampler().sample()  # global peak only, no phase
            assert kprof.audit_plan("never-entered", _plan(10)) is None

    def test_backendless_sampler_retires_itself(self):
        with kprof.profiled(True, interval_ms=10_000.0, stats_fn=lambda: None):
            s = kprof.sampler()
            assert s.sample() is False
            assert s.unavailable
            assert kprof.watermark() is None

    def test_drift_within_tolerance_not_counted_but_logged(self, fresh_log):
        before = counters.get("plan_drift")
        with kprof.profiled(
            True, interval_ms=10_000.0, stats_fn=lambda: 1000
        ):
            with kprof.phase("fit:tier"):
                kprof.sampler().sample()
            audit = kprof.audit_plan("fit:tier", _plan(1100))
        assert audit is not None and not audit["drifted"]
        assert counters.get("plan_drift") == before
        autoshard.clear_outcome_cache()
        recs = [
            r for r in autoshard.load_outcomes(fresh_log)
            if r.get("outcome") == "hbm_drift"
        ]
        assert len(recs) == 1  # calibration evidence lands either way
        assert recs[0]["watermark_bytes"] == 1000
        assert recs[0]["charged_bytes"] == 1100

    def test_drift_beyond_tolerance_counted_and_logged(self, fresh_log):
        before = counters.get("plan_drift")
        with kprof.profiled(
            True, interval_ms=10_000.0, stats_fn=lambda: 4000
        ):
            with kprof.phase("fit:tier"):
                kprof.sampler().sample()
            audit = kprof.audit_plan(
                "fit:tier", _plan(1000), fingerprint="fp-A"
            )
        assert audit["drifted"] and audit["drift_ratio"] == pytest.approx(4.0)
        assert counters.get("plan_drift") == before + 1
        autoshard.clear_outcome_cache()
        rows = autoshard.drift_rows(fresh_log)
        assert len(rows) == 1
        fp, feats, ratio = rows[0]
        assert fp == "fp-A"
        assert ratio == pytest.approx(4.0)
        assert feats["kind"] == "hbm" and feats["log_charged"] > 0

    def test_run_ladder_audits_watermark(self, fresh_log, monkeypatch):
        """The generic ladder hook: a profiled fit with a live (injected)
        stats source appends a drift row for its chosen tier, keyed by
        the search fingerprint."""
        x = np.random.default_rng(0).normal(size=(128, 64)).astype(np.float32)
        y = (2.0 * np.eye(4)[np.random.default_rng(1).integers(0, 4, 128)]
             - 1.0).astype(np.float32)
        with kprof.profiled(
            True, interval_ms=10_000.0, stats_fn=lambda: 10 * 2**20
        ):
            BlockLeastSquaresEstimator(64, 1, 1e-2).fit(
                jnp.asarray(x), jnp.asarray(y)
            )
        autoshard.clear_outcome_cache()
        rows = autoshard.drift_rows(fresh_log)
        assert rows, "no drift row appended by the profiled ladder run"
        fp, feats, ratio = rows[0]
        assert fp and fp != "hbm:bcd_fit:fused"  # the REAL fingerprint
        assert ratio > 0

    def test_drift_rows_train_a_calibration_model(self, fresh_log):
        """The predict->measure->learn loop closes: logged drift rows are
        consumed by the cross-program CalibrationModel, and the trained
        byte-drift factor feeds the search's scoring."""
        rng = np.random.default_rng(3)
        for i in range(12):
            arg = float(2 ** (16 + rng.integers(0, 8)))
            feats = autoshard.hbm_features(arg, arg / 4, arg / 8, None)
            autoshard.append_outcome({
                "fingerprint": f"fp-{i % 3}",
                "candidate": f"cand-{i}",
                "outcome": "hbm_drift",
                "drift_ratio": 2.0,  # device holds 2x the charge, always
                "features": feats,
                "ts": time.time(),
            })
        autoshard.clear_outcome_cache()
        rows = autoshard.drift_rows(fresh_log)
        assert len(rows) == 12
        model = kopt.CalibrationModel.fit_rows(rows)
        assert model is not None and model.n_programs == 3
        feats = autoshard.hbm_features(2**20, 2**18, 2**17, None)
        assert model.predict_factor(feats) == pytest.approx(2.0, rel=0.05)
        # ...and the search-side entry point sees the same factor.
        assert autoshard.drift_factor(feats, fresh_log) == pytest.approx(
            2.0, rel=0.05
        )

    def test_untrained_drift_factor_is_exactly_one(self, fresh_log):
        feats = autoshard.hbm_features(2**20, 2**18, 2**17, None)
        assert autoshard.drift_factor(feats, fresh_log) == 1.0

    def test_sampler_crash_is_counted_and_run_survives(self):
        calls = {"n": 0}

        def crashing():
            calls["n"] += 1
            if calls["n"] > 2:
                raise RuntimeError("boom")
            return 512

        before = counters.get("profiler_sampler_crash")
        with kprof.profiled(True, interval_ms=1.0, stats_fn=crashing):
            s = kprof.sampler()
            end = time.monotonic() + 5.0
            while not s.crashed and time.monotonic() < end:
                time.sleep(0.005)
            assert s.crashed
        assert counters.get("profiler_sampler_crash") == before + 1


# -- triggered XLA capture -----------------------------------------------------


@pytest.fixture
def capture_seams(monkeypatch, tmp_path):
    started, stopped = [], []
    monkeypatch.setattr(kprof, "_start_trace", started.append)
    monkeypatch.setattr(kprof, "_stop_trace", lambda: stopped.append(1))
    monkeypatch.setenv(kprof.XPROF_DIR_ENV, str(tmp_path / "xprof"))
    monkeypatch.setenv(kprof.XPROF_WINDOW_ENV, "0.02")
    return started, stopped


class TestCapture:
    def test_rate_limited_per_kind(self, capture_seams):
        started, stopped = capture_seams
        paths = []
        for _ in range(5):
            p = kprof.maybe_capture("slo_burn")
            if p:
                paths.append(p)
            time.sleep(0.05)  # let the window close between attempts
        assert len(paths) == kprof.MAX_CAPTURES_PER_KIND
        # another kind gets its own budget
        assert kprof.maybe_capture("serve_burst_oom") is not None
        time.sleep(0.05)
        assert len(kprof.capture_paths()) == kprof.MAX_CAPTURES_PER_KIND + 1
        assert len(started) == len(kprof.capture_paths())

    def test_single_window_at_a_time(self, capture_seams, monkeypatch):
        monkeypatch.setenv(kprof.XPROF_WINDOW_ENV, "5.0")
        assert kprof.maybe_capture("slo_burn") is not None
        # the window is still open — a second trigger (any kind) is a no-op
        assert kprof.maybe_capture("slo_burn") is None
        assert kprof.maybe_capture("deadline_exceeded") is None

    def test_no_dir_no_capture(self, monkeypatch):
        monkeypatch.delenv(kprof.XPROF_DIR_ENV, raising=False)
        assert kprof.maybe_capture("slo_burn") is None

    def test_start_failure_refunds_the_budget(self, capture_seams, monkeypatch):
        """A transient start_trace failure must not burn the kind's cap:
        no window opened means no budget spent."""
        calls = {"n": 0}

        def flaky_start(path):
            calls["n"] += 1
            if calls["n"] <= 2:
                raise RuntimeError("profiler session busy")

        monkeypatch.setattr(kprof, "_start_trace", flaky_start)
        assert kprof.maybe_capture("slo_burn") is None
        assert kprof.maybe_capture("slo_burn") is None
        # two failures later, the full budget is still available
        assert kprof.maybe_capture("slo_burn") is not None
        time.sleep(0.05)
        assert kprof.maybe_capture("slo_burn") is not None
        time.sleep(0.05)

    def test_postmortem_fault_triggers_capture(self, capture_seams):
        before = len(kprof.capture_paths())
        counters.record("serve_burst_oom", "chaos probe: capture trigger")
        assert len(kprof.capture_paths()) == before + 1
        time.sleep(0.05)

    def test_slo_burn_breach_triggers_capture(self, capture_seams):
        tracker = ktelemetry.SLOTracker(
            "probe", slo_ms=1.0, budget=0.01, window_s=60.0
        )
        for _ in range(tracker.BURN_CAPTURE_MIN_COUNT + 5):
            tracker.observe(50.0, ok=True)  # every one violates the SLO
        assert any(
            "slo_burn" in p for p in kprof.capture_paths()
        ), kprof.capture_paths()
        time.sleep(0.05)


# -- the wire clock handshake + stitched waterfall ----------------------------


class _Echo:
    """Minimal wire target: answers the request array itself."""

    def submit(self, arr):
        fut = kserve.ServeFuture(request_id=1)
        fut._resolve(value=np.asarray(arr))
        return fut


class TestClockSync:
    def test_clock_sync_offset(self):
        with kwire.WireServer(_Echo(), port=0, label="clk") as ws:
            with kwire.WireClient(port=ws.port, timeout=10.0) as client:
                est = client.clock_sync()
        assert est is not None
        assert est["rtt_us"] >= 0
        # Same process, same trace epoch: the two clocks read the same
        # counter, so the estimated offset is ~the rtt scale, not huge.
        assert abs(est["offset_us"]) < 1e6

    def test_traced_request_carries_client_span(self):
        ktrace.reset()
        with kwire.WireServer(_Echo(), port=0, label="span") as ws:
            ktrace.enable(os.devnull)
            try:
                with kwire.WireClient(port=ws.port, timeout=10.0) as client:
                    rid = client.submit(
                        np.zeros(4, np.float32), client_span=77
                    )
                    reply = client.read()
            finally:
                events = ktrace.events()
                ktrace.disable()
                ktrace.reset()
        assert reply.type == kwire.T_RESPONSE and reply.request_id == rid
        req = [
            e for e in events
            if e.get("ph") == "i" and e.get("name") == "wire.request"
        ]
        assert req and req[-1]["args"].get("client_span") == 77


def _stitch_pipe(rng):
    w = jnp.asarray(rng.normal(size=(4,)).astype(np.float32))
    return FunctionTransformer(
        lambda x: jnp.maximum(x * w, 0.0), name="stitch"
    )


class TestStitchedWaterfall:
    def test_two_process_stitch_over_real_sockets(self, rng, tmp_path):
        """The acceptance path: a REAL client process
        (tools/serve_client.py --trace) drives a wire server whose own
        trace is enabled; trace_view --stitch joins the two files by wire
        rid into one waterfall decomposing network vs queue vs device
        time for every request."""
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        import trace_view

        server_trace = str(tmp_path / "server.json")
        client_trace = str(tmp_path / "client.jsonl")
        n_req = 8
        eng = kserve.ServingEngine(
            _stitch_pipe(rng), np.zeros(4, np.float32),
            config=kserve.ServeConfig(buckets=(1, 2), max_wait_ms=1.0),
            label="stitch",
        )
        ktrace.reset()
        ktrace.enable(server_trace)
        try:
            with kserve.Server(eng) as server:
                with kwire.WireServer(server, port=0, label="stitch") as ws:
                    out = subprocess.run(
                        [
                            sys.executable,
                            os.path.join(_REPO, "tools", "serve_client.py"),
                            "--port", str(ws.port), "--shape", "4",
                            "--requests", str(n_req),
                            "--trace", client_trace,
                        ],
                        capture_output=True, text=True, timeout=120,
                        cwd=str(tmp_path),
                    )
            assert out.returncode == 0, out.stderr[-2000:]
            ktrace.flush(server_trace)
        finally:
            ktrace.disable()
            ktrace.reset()

        client_rec = json.loads(out.stdout.splitlines()[0])
        assert client_rec["clock_offset_us"] is not None

        merged = trace_view.stitch(
            trace_view.load_events(server_trace),
            trace_view.load_events(client_trace),
        )
        assert merged["requests"] == n_req
        assert merged["clock"] and "offset_us" in merged["clock"]
        for row in merged["rows"]:
            # every request decomposes: client total = network + server,
            # and the server side carries the serve-phase split
            assert row["client_ms"] > 0 and row["server_ms"] > 0
            assert row["client_ms"] == pytest.approx(
                row["network_ms"] + row["server_ms"], abs=0.01
            )
            assert "queue_wait_ms" in row and "execute_ms" in row
            assert row["client_span"] is not None
        # the CLI face renders the same merge without crashing
        summary = trace_view.stitch_summary(server_trace, client_trace, 3)
        assert "stitched waterfall" in summary

    def test_stitch_pure_function(self):
        """Unit-level join: synthetic client/server events reconstruct
        the expected decomposition exactly."""
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        import trace_view

        client = [
            {"ph": "i", "name": "client.submit", "args": {"rid": 1, "span": 0}},
            {"ph": "i", "name": "client.answer",
             "args": {"rid": 1, "span": 0, "ms": 10.0}},
            {"ph": "i", "name": "client.clock",
             "args": {"offset_us": 5.0, "rtt_us": 2.0}},
        ]
        server = [
            # A SECOND connection with a colliding wire rid (per-conn
            # counters both start at 1): the join must pick conn 2 — the
            # one whose recorded client_span matches this client's span —
            # not whichever connection logged last.
            {"ph": "i", "name": "wire.request",
             "args": {"conn": 1, "wire_rid": 1, "request_id": 4,
                      "client_span": 9}},
            {"ph": "i", "name": "wire.response",
             "args": {"conn": 1, "wire_rid": 1, "ms": 99.0}},
            {"ph": "i", "name": "wire.request",
             "args": {"conn": 2, "wire_rid": 1, "request_id": 9,
                      "client_span": 0}},
            {"ph": "i", "name": "wire.response",
             "args": {"conn": 2, "wire_rid": 1, "ms": 7.5}},
            {"ph": "X", "name": "serve.request", "ts": 0, "dur": 0,
             "args": {"request_id": 9, "queue_wait_ms": 3.0,
                      "execute_ms": 2.0, "h2d_ms": 0.5}},
        ]
        merged = trace_view.stitch(server, client)
        assert merged["requests"] == 1
        assert merged["server_connections"] == 2
        assert merged["connection"] == 2
        row = merged["rows"][0]
        assert row["request_id"] == 9
        assert row["network_ms"] == pytest.approx(2.5)
        assert row["queue_wait_ms"] == 3.0
        assert row["execute_ms"] == 2.0
        assert merged["clock"]["offset_us"] == 5.0
        assert merged["client_submits"] == 1


# -- profiled serving ----------------------------------------------------------


class TestProfiledServe:
    def test_serve_buckets_land_in_ledger_bit_equal(self, rng):
        eng = kserve.ServingEngine(
            _stitch_pipe(rng), np.zeros(4, np.float32),
            config=kserve.ServeConfig(buckets=(1, 2), max_wait_ms=1.0),
            label="prof",
        )
        reqs = rng.normal(size=(6, 4)).astype(np.float32)
        plain = eng.infer(reqs)
        with kprof.profiled(True):
            profiled = eng.infer(reqs)
            led = kprof.ledger()
        assert np.array_equal(plain, profiled)  # profiling changes no bits
        serve_rows = {k: v for k, v in led.items() if k.startswith("serve:prof")}
        assert serve_rows, f"no serve rows in {sorted(led)}"
        assert all(v["runs"] >= 1 for v in serve_rows.values())
