"""Mesh-enabled workload tests: sharded run == single-device run.

The reference runs every pipeline over partitioned RDDs (e.g.
RandomPatchCifar.scala:20-85); here each workload's ``run(..., mesh=...)``
must reproduce the single-device result on the virtual 8-device platform.
"""

import numpy as np

from keystone_tpu.loaders.cifar import cifar_loader
from keystone_tpu.loaders.csv_loader import LabeledData
from keystone_tpu.loaders.timit import timit_features_loader
from keystone_tpu.workloads.cifar_random_patch import RandomCifarConfig
from keystone_tpu.workloads.cifar_random_patch import run as cifar_run
from keystone_tpu.workloads.mnist_random_fft import MnistRandomFFTConfig
from keystone_tpu.workloads.mnist_random_fft import run as mnist_run
from keystone_tpu.workloads.timit import TimitConfig
from keystone_tpu.workloads.timit import run as timit_run

from test_cifar_pipeline import write_synthetic_cifar
from test_timit import write_split


def _mnist_data(rng, n, d=64, k=5, centers=None):
    if centers is None:
        centers = rng.normal(size=(k, d))
    labels = rng.integers(0, k, n)
    data = (centers[labels] + 0.3 * rng.normal(size=(n, d))).astype(np.float32)
    return LabeledData(data=data, labels=labels.astype(np.int32)), centers


def test_mnist_random_fft_mesh_matches_local(rng, mesh42):
    train, centers = _mnist_data(rng, 203)  # deliberately not divisible by 4
    test, _ = _mnist_data(rng, 101, centers=centers)
    conf = MnistRandomFFTConfig(
        num_ffts=2, block_size=512, lam=1e-2, mnist_image_size=64, num_classes=5
    )
    local = mnist_run(conf, train, test)
    sharded = mnist_run(conf, train, test, mesh=mesh42)
    assert abs(sharded["train_error"] - local["train_error"]) < 1e-6
    assert abs(sharded["test_error"] - local["test_error"]) < 1e-6


def test_timit_mesh_matches_local(rng, mesh8, tmp_path):
    d, k = 24, 6
    centers = rng.normal(scale=2.0, size=(k, d))
    tdp, tlp, _ = write_split(tmp_path, "train", 205, rng, centers)
    sdp, slp, _ = write_split(tmp_path, "test", 101, rng, centers)
    data = timit_features_loader(tdp, tlp, sdp, slp)
    conf = TimitConfig(
        num_cosines=2,
        num_cosine_features=128,
        num_epochs=2,
        gamma=0.2,
        lam=1e-3,
        num_classes=k,
        dimension=d,
    )
    local = timit_run(conf, data)
    sharded = timit_run(conf, data, mesh=mesh8)
    assert abs(sharded["test_error"] - local["test_error"]) < 1.1


def test_cifar_random_patch_mesh_matches_local(rng, mesh8, tmp_path):
    train_path = str(tmp_path / "train.bin")
    test_path = str(tmp_path / "test.bin")
    palette = rng.uniform(40, 215, (4, 3))
    write_synthetic_cifar(train_path, 201, rng, base=palette)
    write_synthetic_cifar(test_path, 99, rng, base=palette)
    conf = RandomCifarConfig(
        num_filters=12,
        patch_size=6,
        patch_steps=2,
        lam=10.0,
        whitener_size=1500,
        featurize_chunk=64,
        num_classes=4,
    )
    train, test = cifar_loader(train_path), cifar_loader(test_path)
    local = cifar_run(conf, train, test)
    sharded = cifar_run(conf, train, test, mesh=mesh8)
    assert abs(sharded["train_error"] - local["train_error"]) < 1.1
    assert abs(sharded["test_error"] - local["test_error"]) < 1.1
