"""Mesh-enabled workload tests: sharded run == single-device run.

The reference runs every pipeline over partitioned RDDs (e.g.
RandomPatchCifar.scala:20-85); here each workload's ``run(..., mesh=...)``
must reproduce the single-device result on the virtual 8-device platform.
"""

import numpy as np
import pytest

from keystone_tpu.loaders.cifar import cifar_loader
from keystone_tpu.loaders.csv_loader import LabeledData
from keystone_tpu.loaders.image_loaders import imagenet_loader, voc_loader
from keystone_tpu.loaders.newsgroups import newsgroups_loader
from keystone_tpu.loaders.timit import timit_features_loader
from keystone_tpu.workloads.cifar_random_patch import RandomCifarConfig
from keystone_tpu.workloads.cifar_random_patch import run as cifar_run
from keystone_tpu.workloads.imagenet_sift_lcs_fv import ImageNetSiftLcsFVConfig
from keystone_tpu.workloads.imagenet_sift_lcs_fv import run as imagenet_run
from keystone_tpu.workloads.mnist_random_fft import MnistRandomFFTConfig
from keystone_tpu.workloads.mnist_random_fft import run as mnist_run
from keystone_tpu.workloads.newsgroups import NewsgroupsConfig
from keystone_tpu.workloads.newsgroups import run as newsgroups_run
from keystone_tpu.workloads.timit import TimitConfig
from keystone_tpu.workloads.timit import run as timit_run
from keystone_tpu.workloads.voc_sift_fisher import SIFTFisherConfig
from keystone_tpu.workloads.voc_sift_fisher import run as voc_run

from test_cifar_pipeline import write_synthetic_cifar
from test_fisher_pipelines import write_imagenet_tar, write_voc_tar
from test_timit import write_split


def _mnist_data(rng, n, d=64, k=5, centers=None):
    if centers is None:
        centers = rng.normal(size=(k, d))
    labels = rng.integers(0, k, n)
    data = (centers[labels] + 0.3 * rng.normal(size=(n, d))).astype(np.float32)
    return LabeledData(data=data, labels=labels.astype(np.int32)), centers


def test_mnist_random_fft_mesh_matches_local(rng, mesh42):
    train, centers = _mnist_data(rng, 203)  # deliberately not divisible by 4
    test, _ = _mnist_data(rng, 101, centers=centers)
    conf = MnistRandomFFTConfig(
        num_ffts=2, block_size=512, lam=1e-2, mnist_image_size=64, num_classes=5
    )
    local = mnist_run(conf, train, test)
    sharded = mnist_run(conf, train, test, mesh=mesh42)
    assert abs(sharded["train_error"] - local["train_error"]) < 1e-6
    assert abs(sharded["test_error"] - local["test_error"]) < 1e-6


def test_timit_mesh_matches_local(rng, mesh8, tmp_path):
    d, k = 24, 6
    centers = rng.normal(scale=2.0, size=(k, d))
    tdp, tlp, _ = write_split(tmp_path, "train", 205, rng, centers)
    sdp, slp, _ = write_split(tmp_path, "test", 101, rng, centers)
    data = timit_features_loader(tdp, tlp, sdp, slp)
    conf = TimitConfig(
        num_cosines=2,
        num_cosine_features=128,
        num_epochs=2,
        gamma=0.2,
        lam=1e-3,
        num_classes=k,
        dimension=d,
    )
    local = timit_run(conf, data)
    sharded = timit_run(conf, data, mesh=mesh8)
    # Error is quantized in steps of 100/101 = 0.99pp.  Sharded psum grams
    # sum in a different f32 order than the single-device fit, which may
    # flip at most a borderline example: the band admits exactly ONE flip
    # (two flips = 1.98pp would fail).
    assert abs(sharded["test_error"] - local["test_error"]) < 1.0


def test_cifar_random_patch_mesh_matches_local(rng, mesh8, tmp_path):
    train_path = str(tmp_path / "train.bin")
    test_path = str(tmp_path / "test.bin")
    palette = rng.uniform(40, 215, (4, 3))
    write_synthetic_cifar(train_path, 201, rng, base=palette)
    write_synthetic_cifar(test_path, 99, rng, base=palette)
    conf = RandomCifarConfig(
        num_filters=12,
        patch_size=6,
        patch_steps=2,
        lam=10.0,
        whitener_size=1500,
        featurize_chunk=64,
        num_classes=4,
    )
    train, test = cifar_loader(train_path), cifar_loader(test_path)
    local = cifar_run(conf, train, test)
    sharded = cifar_run(conf, train, test, mesh=mesh8)
    # One-flip bands (f32 reduction-order drift between sharded psum and
    # single-device sums can flip at most a borderline example): train error
    # steps are 100/201 = 0.4975pp, test steps 100/99 = 1.01pp.
    assert abs(sharded["train_error"] - local["train_error"]) < 0.6
    assert abs(sharded["test_error"] - local["test_error"]) < 1.1


@pytest.mark.slow
def test_imagenet_sift_lcs_fv_mesh_matches_local(rng, mesh42, tmp_path):
    """The north-star FV -> BWLS tail, sharded == local: featurization
    buckets row-sharded over the data axis, the class-weighted solve over
    the (data, model) mesh (reference ImageNetSiftLcsFV.scala:150-195)."""
    labels_path = str(tmp_path / "labels.txt")
    write_imagenet_tar(str(tmp_path), labels_path, rng)
    data = imagenet_loader(str(tmp_path), labels_path)
    conf = ImageNetSiftLcsFVConfig(
        lam=1e-3,
        mixture_weight=0.25,
        desc_dim=12,
        vocab_size=4,
        num_pca_samples=4000,
        num_gmm_samples=4000,
        lcs_stride=8,
        lcs_border=16,
        lcs_patch=6,
        num_classes=3,
    )
    local = imagenet_run(conf, data, data)
    sharded = imagenet_run(conf, data, data, mesh=mesh42)
    # 24 images quantize the error to 1/24 steps; identical fits (same
    # seeds, same sampled columns — pad rows never sampled) must land on
    # the same step.
    assert sharded["top1_err_percent"] == local["top1_err_percent"]
    assert sharded["top5_err_percent"] == local["top5_err_percent"]


@pytest.mark.slow
def test_voc_sift_fisher_mesh_matches_local(rng, mesh8, tmp_path):
    labels_csv = str(tmp_path / "labels.csv")
    open(labels_csv, "w").close()
    write_voc_tar(str(tmp_path / "train.tar"), labels_csv, 24, rng)
    data = voc_loader(str(tmp_path / "train.tar"), labels_csv)
    conf = SIFTFisherConfig(
        lam=0.05,
        desc_dim=16,
        vocab_size=8,
        num_pca_samples=6000,
        num_gmm_samples=6000,
        sift_step_size=6,
    )
    local = voc_run(conf, data, data)
    sharded = voc_run(conf, data, data, mesh=mesh8)
    assert np.allclose(sharded["aps"], local["aps"], atol=1e-6), (
        sharded["aps"],
        local["aps"],
    )


def test_newsgroups_mesh_matches_local(rng, mesh8, tmp_path):
    """Mesh NB scoring (shard_map COO contraction) == serial scoring."""
    themes = {
        "comp.graphics": ["pixel", "render", "shader", "gpu", "image"],
        "rec.autos": ["engine", "car", "wheel", "drive", "motor"],
        "sci.space": ["orbit", "rocket", "nasa", "launch", "moon"],
    }
    for split in ("train", "test"):
        for cls, words in themes.items():
            d = tmp_path / split / cls
            d.mkdir(parents=True)
            # test count 7 -> 21 docs, NOT divisible by the 8-way axis
            for i in range(10 if split == "train" else 7):
                body = " ".join(rng.choice(words, 25).tolist())
                (d / f"doc{i}.txt").write_text(body)
    classes = tuple(themes)
    train = newsgroups_loader(str(tmp_path / "train"), list(classes))
    test = newsgroups_loader(str(tmp_path / "test"), list(classes))
    conf = NewsgroupsConfig(n_grams=2, common_features=3000, classes=classes)
    local = newsgroups_run(conf, train, test)
    sharded = newsgroups_run(conf, train, test, mesh=mesh8)
    assert abs(sharded["test_error"] - local["test_error"]) < 1e-9
