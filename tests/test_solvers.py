"""Solver tests, mirroring the reference criteria:
- block-vs-full equivalence (BlockLinearMapperSuite.scala:32-53)
- gradient-norm ≈ 0 at the solution (BlockWeightedLeastSquaresSuite.scala:18-60)
- LinearMapEstimator OLS semantics (LinearMapperSuite)
"""

import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.util import VectorSplitter
from keystone_tpu.parallel.mesh import padded_shard_rows
from keystone_tpu.solvers.block import BlockLeastSquaresEstimator, BlockLinearMapper
from keystone_tpu.solvers.linear import LinearMapEstimator, LinearMapper
from keystone_tpu.solvers.normal_equations import (
    bcd_least_squares_l2,
    solve_least_squares,
)
from keystone_tpu.utils.stats import about_eq


def _make_problem(rng, n=200, d=24, k=3, noise=0.01):
    x_true = rng.normal(size=(d, k))
    a = rng.normal(size=(n, d))
    b = a @ x_true + noise * rng.normal(size=(n, k))
    return (
        jnp.asarray(a, jnp.float32),
        jnp.asarray(b, jnp.float32),
        x_true,
    )


def test_normal_equations_recovers_solution(rng):
    a, b, x_true = _make_problem(rng)
    x = solve_least_squares(a, b, 0.0)
    assert about_eq(x, x_true, 5e-2)


def test_normal_equations_l2_matches_numpy(rng):
    a, b, _ = _make_problem(rng, noise=0.1)
    lam = 3.0
    x = np.asarray(solve_least_squares(a, b, lam))
    an, bn = np.asarray(a, np.float64), np.asarray(b, np.float64)
    expected = np.linalg.solve(an.T @ an + lam * np.eye(an.shape[1]), an.T @ bn)
    assert about_eq(x, expected, 1e-2)


def test_gradient_norm_at_solution(rng):
    """‖AᵀAX - Aᵀb + λX‖ ≈ 0 (the BWLSSuite criterion, :94,124)."""
    a, b, _ = _make_problem(rng, noise=0.1)
    lam = 0.5
    x = solve_least_squares(a, b, lam)
    grad = np.asarray(a).T @ (np.asarray(a) @ np.asarray(x) - np.asarray(b)) + lam * np.asarray(x)
    assert np.linalg.norm(grad) / np.linalg.norm(np.asarray(a).T @ np.asarray(b)) < 1e-2


def test_bcd_matches_full_solve(rng):
    """BCD over 3 blocks converges to the monolithic ridge solution."""
    a, b, _ = _make_problem(rng, n=300, d=30, noise=0.05)
    lam = 1.0
    blocks = VectorSplitter(10)(a)
    models = bcd_least_squares_l2(blocks, b, lam, num_iter=40)
    x_bcd = np.concatenate([np.asarray(m) for m in models], axis=0)
    x_full = np.asarray(solve_least_squares(a, b, lam))
    assert about_eq(x_bcd, x_full, 1e-2)


def test_block_linear_mapper_matches_linear_mapper(rng):
    """Block-vs-monolithic apply equivalence (BlockLinearMapperSuite.scala:32-53)."""
    d, k = 30, 4
    x = jnp.asarray(rng.normal(size=(d, k)), jnp.float32)
    data = jnp.asarray(rng.normal(size=(50, d)), jnp.float32)
    full = LinearMapper(x)
    xs = [x[:10], x[10:20], x[20:]]
    blocked = BlockLinearMapper(xs, 10)
    assert about_eq(blocked(data), full(data), 1e-3)


def test_block_linear_mapper_apply_and_evaluate(rng):
    d, k = 20, 3
    x = jnp.asarray(rng.normal(size=(d, k)), jnp.float32)
    data = jnp.asarray(rng.normal(size=(40, d)), jnp.float32)
    blocked = BlockLinearMapper([x[:10], x[10:]], 10, b=jnp.ones(k))
    outs = []
    blocked.apply_and_evaluate(data, lambda p: outs.append(np.asarray(p)))
    assert len(outs) == 2
    assert about_eq(outs[-1], blocked(data), 1e-4)
    # intercept added exactly once per evaluation
    assert about_eq(outs[0], np.asarray(data[:, :10] @ x[:10]) + 1.0, 1e-3)


def test_linear_map_estimator_centers_and_predicts(rng):
    a, b, _ = _make_problem(rng, n=400, d=10, k=2, noise=0.01)
    a = a + 5.0  # nonzero feature means force the scaler path
    b = b + 2.0
    model = LinearMapEstimator().fit(a, b)
    pred = model(a)
    resid = np.asarray(pred) - np.asarray(b)
    assert np.abs(resid).mean() < 0.05


def test_block_least_squares_estimator_end_to_end(rng):
    a, b, _ = _make_problem(rng, n=300, d=32, k=3, noise=0.02)
    est = BlockLeastSquaresEstimator(block_size=8, num_iter=20, lam=0.1)
    model = est.fit(a, b)
    pred = model(a)
    assert np.abs(np.asarray(pred) - np.asarray(b)).mean() < 0.1
    # matches monolithic ridge on centered data
    am = np.asarray(a) - np.asarray(a).mean(0)
    bm = np.asarray(b) - np.asarray(b).mean(0)
    x_full = np.linalg.solve(
        am.T @ am + 0.1 * np.eye(am.shape[1]), am.T @ bm
    )
    x_blocks = np.concatenate([np.asarray(m) for m in model.xs], axis=0)
    assert about_eq(x_blocks, x_full, 2e-2)


def test_block_least_squares_single_block_equals_ridge(rng):
    """One block, one iter == plain normal equations (degenerate-case path)."""
    a, b, _ = _make_problem(rng, n=100, d=12, noise=0.05)
    model = BlockLeastSquaresEstimator(block_size=12, num_iter=1, lam=0.7).fit(a, b)
    am = np.asarray(a) - np.asarray(a).mean(0)
    bm = np.asarray(b) - np.asarray(b).mean(0)
    expected = np.linalg.solve(am.T @ am + 0.7 * np.eye(12), am.T @ bm)
    assert about_eq(np.asarray(model.xs[0]), expected, 1e-2)


def test_padded_sharded_fit_matches_unpadded(mesh8, rng):
    """Estimator fit on zero-padded sharded data with nvalid == unpadded fit
    (pad rows become -mean after centering; the mask must remove them)."""
    a, b, _ = _make_problem(rng, n=101, d=8, noise=0.05)
    a = a + 3.0
    local = LinearMapEstimator(0.1).fit(a, b)
    a_sh, n = padded_shard_rows(a, mesh8)
    b_sh, _ = padded_shard_rows(b, mesh8)
    sharded = LinearMapEstimator(0.1).fit(a_sh, b_sh, nvalid=n)
    assert about_eq(np.asarray(sharded.x), np.asarray(local.x), 1e-3)
    assert about_eq(
        np.asarray(sharded.feature_scaler.mean), np.asarray(local.feature_scaler.mean), 1e-4
    )
    assert about_eq(np.asarray(sharded(a_sh))[:101], np.asarray(local(a)), 1e-3)

    blk_local = BlockLeastSquaresEstimator(4, 10, 0.2).fit(a, b)
    blk_sh = BlockLeastSquaresEstimator(4, 10, 0.2).fit(a_sh, b_sh, nvalid=n)
    assert about_eq(
        np.concatenate([np.asarray(m) for m in blk_sh.xs]),
        np.concatenate([np.asarray(m) for m in blk_local.xs]),
        1e-3,
    )


def test_solver_sharded_equals_local(mesh8, rng):
    """Sharded gram/solve over the 8-device mesh == single-device result —
    the distributed-correctness invariant replacing Spark local[k] tests."""
    a, b, _ = _make_problem(rng, n=104, d=16, noise=0.05)
    x_local = np.asarray(solve_least_squares(a, b, 0.3))
    a_sh, _ = padded_shard_rows(a, mesh8)
    b_sh, _ = padded_shard_rows(b, mesh8)
    x_sh = np.asarray(solve_least_squares(a_sh, b_sh, 0.3))
    assert about_eq(x_sh, x_local, 1e-3)


def test_fused_fit_matches_stepwise_oracle(rng):
    """The one-program fit (solvers.block._fused_bcd_fit) must reproduce the
    step-at-a-time BCD oracle (bcd_least_squares_l2) run on pre-centered
    blocks — same centering, same update order, same regularization."""
    n, d, k, bs = 40, 22, 3, 8
    a = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(n, k)).astype(np.float32))
    lam, iters = 0.3, 3

    est = BlockLeastSquaresEstimator(bs, num_iter=iters, lam=lam)
    fused = est.fit(a, b)

    # oracle: center labels/blocks by their means, then stepwise BCD
    blocks = [a[:, i : i + bs] for i in range(0, d, bs)]
    centered = [blk - jnp.mean(blk, axis=0) for blk in blocks]
    b_centered = b - jnp.mean(b, axis=0)
    oracle = bcd_least_squares_l2(centered, b_centered, lam, iters)

    for got, want in zip(fused.xs, oracle):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )
    # end-to-end predictions agree too (intercept + scalers included)
    pred_oracle = sum(c @ m for c, m in zip(centered, oracle)) + jnp.mean(b, axis=0)
    np.testing.assert_allclose(
        np.asarray(fused(a)), np.asarray(pred_oracle), rtol=2e-4, atol=2e-4
    )
