"""CoreNLP-equivalent featurizer tests, mirroring the reference suite
(src/test/scala/nodes/nlp/CoreNLPFeatureExtractorSuite.scala)."""

from keystone_tpu.ops.corenlp import CoreNLPFeatureExtractor, lemmatize


class TestLemmatization:
    def test_reference_cases(self):
        """Reference 'lemmatization' test (:9-27)."""
        tokens = set(
            CoreNLPFeatureExtractor(range(1, 4)).apply_item(
                "jumping snakes lakes oceans hunted"
            )
        )
        for lemma in ("jump", "snake", "lake", "ocean", "hunt"):
            assert lemma in tokens, tokens
        for raw in ("jumping", "snakes", "lakes", "oceans", "hunted"):
            assert raw not in tokens

    def test_rules(self):
        assert lemmatize("making") == "make"
        assert lemmatize("hopped") == "hop"
        assert lemmatize("cities") == "city"
        assert lemmatize("churches") == "church"
        assert lemmatize("ran") == "run"
        assert lemmatize("mice") == "mouse"
        assert lemmatize("ring") == "ring"  # not an inflection
        assert lemmatize("glasses") == "glass"


class TestEntityExtraction:
    def test_reference_cases(self):
        """Reference 'entity extraction' test (:29-42)."""
        tokens = set(
            CoreNLPFeatureExtractor(range(1, 4)).apply_item(
                "John likes cake and he lives in Florida"
            )
        )
        assert "PERSON" in tokens
        assert "LOCATION" in tokens
        assert "John" not in tokens and "john" not in tokens
        assert "Florida" not in tokens and "florida" not in tokens

    def test_org_and_number(self):
        tokens = set(
            CoreNLPFeatureExtractor([1]).apply_item(
                "Acme Corp hired 300 people from Google"
            )
        )
        assert "ORGANIZATION" in tokens
        assert "NUMBER" in tokens


class TestNGrams:
    def test_reference_cases(self):
        """Reference '1-2-3-grams' test (:44-66)."""
        tokens = set(CoreNLPFeatureExtractor(range(1, 4)).apply_item("a b c d"))
        for t in ("a", "b", "c", "d", "a b", "b c", "c d", "a b c", "b c d"):
            assert t in tokens

    def test_sentence_boundaries(self):
        """N-grams never cross sentence boundaries (reference :27-33 maps
        per sentence)."""
        tokens = set(
            CoreNLPFeatureExtractor([2]).apply_item("a b. c d")
        )
        assert "a b" in tokens and "c d" in tokens
        assert "b c" not in tokens

    def test_batch_form(self):
        out = CoreNLPFeatureExtractor([1])(["a b", "c"])
        assert out == [["a", "b"], ["c"]]


class TestOpenVocabulary:
    """Property tests on inputs the implementation never hard-coded — the
    gazetteer/lemma tables must not be the only thing the tests exercise
    (reference CoreNLPFeatureExtractor.scala:18-45 handles open vocabulary
    through CoreNLP's models; the stand-in's rules must generalize)."""

    def test_unseen_regular_inflections(self):
        # None of these appear in _IRREGULAR/_NO_STRIP; the suffix rules
        # alone must produce the lemma.
        cases = {
            "computers": "computer",
            "testing": "test",
            "walked": "walk",
            "dropped": "drop",      # consonant un-doubling
            "flipping": "flip",
            "baking": "bake",       # silent-e restoration
            "encoded": "encode",
            "compilers": "compiler",
            "benchmarks": "benchmark",
            "churches": "church",   # -ches
            "boxes": "box",         # -xes
            "berries": "berry",     # -ies -> y
        }
        for word, lemma in cases.items():
            assert lemmatize(word) == lemma, (word, lemmatize(word))

    def test_lemmatize_idempotent_on_unseen_words(self):
        # Applying the lemmatizer to its own output must be a fixed point —
        # a second strip would mangle open-vocabulary stems.
        words = [
            "tokenizers", "sharding", "pipelined", "gemms", "reshaped",
            "collectives", "meshes", "latencies", "fusing", "benchmarked",
            "quantum", "syzygy", "keystone", "tpu", "xla",
        ]
        for w in words:
            once = lemmatize(w)
            assert lemmatize(once) == once, (w, once, lemmatize(once))

    def test_unknown_capitalized_token_is_not_an_entity(self):
        # Capitalization alone (sentence starts, unknown proper nouns) must
        # not fabricate PERSON/LOCATION tags.
        toks = CoreNLPFeatureExtractor([1]).apply_item(
            "Zorblax visited Quuxington yesterday"
        )
        assert "PERSON" not in toks and "LOCATION" not in toks
        assert "zorblax" in toks and "quuxington" in toks

    def test_unknown_org_by_suffix_pattern(self):
        # The ORGANIZATION rule is a *pattern* (Capitalized + org suffix),
        # so it must fire for names far outside any table.
        toks = CoreNLPFeatureExtractor([1]).apply_item(
            "Frobnicatex Corp announced a merger with Zyqqly University"
        )
        assert toks.count("ORGANIZATION") >= 2

    def test_mixed_junk_never_crashes_and_stays_normalized(self):
        docs = [
            "xX9__zz!! 123,456 @@@ ~~~",
            "élève straße 中文 words",
            "a" * 300 + " " + "'''" + " don't",
            "",
            "...!?.",
        ]
        out = CoreNLPFeatureExtractor([1, 2])(docs)
        assert len(out) == len(docs)
        for grams in out:
            for g in grams:
                for tok in g.split(" "):
                    # every token is an entity tag or lowercase alnum
                    assert tok in ("PERSON", "LOCATION", "ORGANIZATION", "NUMBER") or (
                        tok == tok.lower() and tok.replace("'", "").isalnum()
                    ), tok

    def test_numeric_shapes_tag_as_number(self):
        toks = CoreNLPFeatureExtractor([1]).apply_item(
            "raised 4,200 units worth 3.14 each in 2026"
        )
        assert toks.count("NUMBER") == 3

    def test_digit_led_mixed_tokens_stay_whole(self):
        # "3d"/"90s"/"4k" are single tokens (not split "3","d"), are not
        # NUMBER entities, and are not mangled by the suffix lemmatizer.
        toks = CoreNLPFeatureExtractor([1]).apply_item(
            "a 3d scene from the 90s in 4k"
        )
        assert "3d" in toks and "90s" in toks and "4k" in toks
        assert "NUMBER" not in toks

    def test_number_punctuation_does_not_glue_tokens(self):
        # ','/'.' join digits only BETWEEN digits — a missing space after
        # punctuation must not fuse a number onto the following word.
        toks = CoreNLPFeatureExtractor([1]).apply_item(
            "In 2026,Google announced"
        )
        assert "NUMBER" in toks and "ORGANIZATION" in toks

    def test_porter_guard_cases(self):
        # Vowel-measure guards + the -ied/-oes rules: open-vocab shapes the
        # closed tables never listed.
        cases = {
            "carried": "carry",
            "studied": "study",
            "heroes": "hero",
            "echoes": "echo",
            "potatoes": "potato",
            "shoes": "shoe",     # -oe plural exception
            "toes": "toe",
            "throes": "throe",
            "floes": "floe",
            "goes": "go",
            "bling": "bling",    # no-vowel stem: not an inflection
            "zings": "zing",
        }
        for word, lemma in cases.items():
            assert lemmatize(word) == lemma, (word, lemmatize(word))
