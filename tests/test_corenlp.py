"""CoreNLP-equivalent featurizer tests, mirroring the reference suite
(src/test/scala/nodes/nlp/CoreNLPFeatureExtractorSuite.scala)."""

from keystone_tpu.ops.corenlp import CoreNLPFeatureExtractor, lemmatize


class TestLemmatization:
    def test_reference_cases(self):
        """Reference 'lemmatization' test (:9-27)."""
        tokens = set(
            CoreNLPFeatureExtractor(range(1, 4)).apply_item(
                "jumping snakes lakes oceans hunted"
            )
        )
        for lemma in ("jump", "snake", "lake", "ocean", "hunt"):
            assert lemma in tokens, tokens
        for raw in ("jumping", "snakes", "lakes", "oceans", "hunted"):
            assert raw not in tokens

    def test_rules(self):
        assert lemmatize("making") == "make"
        assert lemmatize("hopped") == "hop"
        assert lemmatize("cities") == "city"
        assert lemmatize("churches") == "church"
        assert lemmatize("ran") == "run"
        assert lemmatize("mice") == "mouse"
        assert lemmatize("ring") == "ring"  # not an inflection
        assert lemmatize("glasses") == "glass"


class TestEntityExtraction:
    def test_reference_cases(self):
        """Reference 'entity extraction' test (:29-42)."""
        tokens = set(
            CoreNLPFeatureExtractor(range(1, 4)).apply_item(
                "John likes cake and he lives in Florida"
            )
        )
        assert "PERSON" in tokens
        assert "LOCATION" in tokens
        assert "John" not in tokens and "john" not in tokens
        assert "Florida" not in tokens and "florida" not in tokens

    def test_org_and_number(self):
        tokens = set(
            CoreNLPFeatureExtractor([1]).apply_item(
                "Acme Corp hired 300 people from Google"
            )
        )
        assert "ORGANIZATION" in tokens
        assert "NUMBER" in tokens


class TestNGrams:
    def test_reference_cases(self):
        """Reference '1-2-3-grams' test (:44-66)."""
        tokens = set(CoreNLPFeatureExtractor(range(1, 4)).apply_item("a b c d"))
        for t in ("a", "b", "c", "d", "a b", "b c", "c d", "a b c", "b c d"):
            assert t in tokens

    def test_sentence_boundaries(self):
        """N-grams never cross sentence boundaries (reference :27-33 maps
        per sentence)."""
        tokens = set(
            CoreNLPFeatureExtractor([2]).apply_item("a b. c d")
        )
        assert "a b" in tokens and "c d" in tokens
        assert "b c" not in tokens

    def test_batch_form(self):
        out = CoreNLPFeatureExtractor([1])(["a b", "c"])
        assert out == [["a", "b"], ["c"]]
