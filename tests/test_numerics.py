"""Numerics & model-quality observatory (core.numerics, ISSUE 15).

The contract under test, in order of importance:

1. **Bit-inertness** — enabling the observatory never changes a value on
   any probed path (pipeline apply/profile, streamed featurize, served
   answers): same bytes out, monitored or not.
2. **Zero retained allocation off** — with the observatory disabled every
   hook is one flag check and NO per-site state accumulates.
3. **Sampling-rate math** — ``KEYSTONE_NUMERICS_SAMPLE=N`` reduces one
   probe in N, deterministically (visit 1 always probes).
4. **Stats / conditioning / provenance / drift correctness** — the
   reducer against numpy oracles, the κ estimate against
   ``np.linalg.cond``, the bisect naming the exact poisoned member or
   request, the drift monitor counting exactly once per breach.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.core import checkpoint as kckpt
from keystone_tpu.core import numerics as knum
from keystone_tpu.core import serve as kserve
from keystone_tpu.core import trace
from keystone_tpu.core.ingest import StreamBatch
from keystone_tpu.core.pipeline import FunctionTransformer, Identity, Pipeline
from keystone_tpu.core.resilience import assert_all_finite, counters
from keystone_tpu.solvers.block import BlockLeastSquaresEstimator

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)


@pytest.fixture(autouse=True)
def _clean_numerics(monkeypatch):
    monkeypatch.delenv(knum.NUMERICS_ENV, raising=False)
    monkeypatch.delenv(knum.SAMPLE_ENV, raising=False)
    monkeypatch.delenv(knum.DRIFT_TOL_ENV, raising=False)
    knum.reset_state()
    yield
    knum.reset_state()


# -- the tensor-stat reducer ---------------------------------------------------


def test_tensor_stats_match_numpy_oracle(rng):
    x = rng.normal(size=(16, 8)).astype(np.float32)
    x[3, 2] = 0.0
    for arr in (x, jnp.asarray(x)):
        s = knum.tensor_stats(arr)
        assert s["count"] == x.size
        assert s["nonfinite"] == 0
        np.testing.assert_allclose(s["mean"], x.mean(), rtol=1e-5)
        np.testing.assert_allclose(s["std"], x.std(), rtol=1e-4)
        np.testing.assert_allclose(s["min"], x.min(), rtol=1e-6)
        np.testing.assert_allclose(s["max"], x.max(), rtol=1e-6)
        np.testing.assert_allclose(s["abs_max"], np.abs(x).max(), rtol=1e-6)
        np.testing.assert_allclose(s["zero_frac"], 1.0 / x.size, rtol=1e-5)


def test_tensor_stats_moments_exclude_nonfinite():
    x = np.array([1.0, np.nan, 3.0, np.inf, 0.0], np.float32)
    for arr in (x, jnp.asarray(x)):
        s = knum.tensor_stats(arr)
        assert s["nonfinite"] == 2
        np.testing.assert_allclose(s["mean"], (1 + 3 + 0) / 3, rtol=1e-6)
        assert s["min"] == 0.0 and s["max"] == 3.0


def test_tensor_stats_all_nonfinite_reports_zero_extremes():
    s = knum.tensor_stats(np.full(4, np.nan, np.float32))
    assert s["nonfinite"] == 4
    assert s["min"] == 0.0 and s["max"] == 0.0 and s["abs_max"] == 0.0


def test_nonfinite_rows_bisects_to_exact_rows():
    x = np.ones((13, 3), np.float32)
    x[2, 1] = np.nan
    x[7, 0] = np.inf
    x[12, 2] = -np.inf
    assert knum.nonfinite_rows(x) == [2, 7, 12]
    assert knum.nonfinite_rows(np.ones((5, 2), np.float32)) == []


# -- sampling + disabled-mode discipline ---------------------------------------


def test_probe_sampling_rate_math(monkeypatch):
    monkeypatch.setenv(knum.SAMPLE_ENV, "3")
    x = np.ones(4, np.float32)
    with knum.monitored(True):
        for _ in range(10):
            knum.probe("sample_site", x)
    s = knum.site_stats()["sample_site"]
    # visits 1, 4, 7, 10 probe ((visit-1) % 3 == 0): 4 of 10.
    assert s["visits"] == 10
    assert s["sampled"] == 4


def test_disabled_mode_retains_no_state_and_returns_same_object(rng):
    x = jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))
    assert not knum.active()
    for _ in range(50):
        out = knum.probe("off_site", x)
        assert out is x
    assert knum.site_stats() == {}
    assert knum.snapshot()["sites"] == {}


def test_probe_returns_same_object_when_enabled(rng):
    x = jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))
    with knum.monitored(True):
        assert knum.probe("on_site", x) is x
    assert knum.site_stats()["on_site"]["sampled"] == 1


# -- bit-inertness on every probed path ----------------------------------------


def _toy_pipeline():
    w = jnp.asarray(np.linspace(0.5, 2.0, 8).astype(np.float32))
    return Pipeline(
        [
            FunctionTransformer(lambda x: x * w, name="scale"),
            FunctionTransformer(lambda x: jnp.maximum(x, 0.1), name="clip"),
        ]
    )


def test_pipeline_apply_bit_inert(rng):
    pipe = _toy_pipeline()
    x = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
    plain = np.asarray(pipe(x))
    with knum.monitored(True):
        probed = np.asarray(pipe(x))
    assert plain.tobytes() == probed.tobytes()
    sites = knum.site_stats()
    assert "pipeline.scale" in sites and "pipeline.clip" in sites


def test_pipeline_probe_inert_under_jit(rng):
    pipe = _toy_pipeline()
    x = jnp.asarray(rng.normal(size=(4, 8)).astype(np.float32))
    with knum.monitored(True):
        jitted = np.asarray(jax.jit(pipe.__call__)(x))
        # Tracing must not have created probe sites (Tracer batches skip).
        assert all(not s.startswith("pipeline.") for s in knum.site_stats())
    assert np.array_equal(jitted, np.asarray(pipe(x)))


def test_pipeline_profile_bit_inert(rng):
    pipe = _toy_pipeline()
    x = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
    plain = np.asarray(pipe.profile(x).output)
    with knum.monitored(True):
        probed = np.asarray(pipe.profile(x).output)
    assert plain.tobytes() == probed.tobytes()
    assert "profile.scale" in knum.site_stats()


def test_stream_featurize_bit_inert(rng):
    host = rng.uniform(0, 1, (6, 4, 4, 3)).astype(np.float32)
    feat = jax.jit(lambda x: jnp.mean(x, axis=(1, 2, 3)))

    def batch():
        return StreamBatch(
            index=0,
            indices=np.arange(6),
            names=[f"img{i}.jpg" for i in range(6)],
            host=host.copy(),
        )

    plain = np.asarray(batch().apply(feat))
    with knum.monitored(True):
        probed = np.asarray(batch().apply(feat))
    assert plain.tobytes() == probed.tobytes()
    assert any(s.startswith("stream.featurize.") for s in knum.site_stats())


def _serve_engine(pipe_fn=None, label="numtest", buckets=(1, 2, 4)):
    w = jnp.asarray(np.linspace(-1.0, 1.0, 8).astype(np.float32))
    b = jnp.asarray(np.linspace(0.1, 0.4, 8).astype(np.float32))
    pipe = FunctionTransformer(
        pipe_fn or (lambda x: jnp.maximum(x * w, b)), name=f"{label}_head"
    )
    cfg = kserve.ServeConfig(buckets=buckets, max_wait_ms=2.0)
    return kserve.ServingEngine(
        pipe, np.zeros(8, np.float32), config=cfg, label=label
    )


def test_served_answers_bit_inert(rng):
    engine = _serve_engine(label="inert")
    reqs = rng.normal(size=(24, 8)).astype(np.float32)
    with kserve.Server(engine) as server:
        plain = np.stack(
            [f.result(30.0) for f in [server.submit(r) for r in reqs]]
        )
    with knum.monitored(True):
        with kserve.Server(engine) as server:
            probed = np.stack(
                [f.result(30.0) for f in [server.submit(r) for r in reqs]]
            )
    assert plain.tobytes() == probed.tobytes()
    assert any(s.startswith("serve.inert") for s in knum.site_stats())


# -- NaN provenance ------------------------------------------------------------


def test_stream_nan_provenance_names_the_member():
    host = np.ones((5, 2, 2, 1), np.float32)
    host[3, 0, 0, 0] = np.nan
    sb = StreamBatch(
        index=0,
        indices=np.arange(5),
        names=[f"n{i:03d}.jpg" for i in range(5)],
        host=host,
    )
    before = counters.get("numerics_nonfinite")
    with knum.monitored(True):
        out = sb.apply(lambda x: jnp.mean(x, axis=(1, 2, 3)))
    assert np.isnan(np.asarray(out)[3])  # value untouched — detection only
    assert counters.get("numerics_nonfinite") - before == 1
    note = knum.provenance_note()
    assert note is not None and "n003.jpg" in note and "member" in note
    # The typed error the fit guard raises names the member too.
    with pytest.raises(FloatingPointError, match="n003.jpg"):
        assert_all_finite(out, "poisoned featurize")


def test_serve_nan_provenance_names_the_request(rng):
    # A head that poisons its output whenever feature 0 exceeds 2.5 —
    # submit-side validation passes (inputs are finite), the OUTPUT NaNs.
    def head(x):
        return jnp.where(x[..., :1] > 2.5, jnp.nan, 1.0) * x

    engine = _serve_engine(pipe_fn=head, label="nanserve")
    good = rng.normal(size=(6, 8)).astype(np.float32).clip(-2, 2)
    bad = good[0].copy()
    bad[0] = 3.0
    before = counters.get("numerics_nonfinite")
    with knum.monitored(True):
        with kserve.Server(engine) as server:
            futs = [server.submit(r) for r in good]
            bad_fut = server.submit(bad)
            for f in futs:
                f.result(30.0)
            bad_ans = bad_fut.result(30.0)
    assert np.isnan(bad_ans).any()  # answered, not altered
    assert counters.get("numerics_nonfinite") - before >= 1
    recs = knum.provenance_records()
    assert any(
        r["kind"] == "request" and str(bad_fut.request_id) in r["names"]
        for r in recs
    ), recs


# -- conditioning monitor ------------------------------------------------------


def test_condition_estimate_tracks_true_kappa():
    rng = np.random.default_rng(7)
    q, _ = np.linalg.qr(rng.standard_normal((48, 48)))
    for true_k in (1e2, 1e4):
        vals = np.geomspace(1.0, true_k, 48)
        g = jnp.asarray((q * vals) @ q.T, jnp.float32)
        row = knum.estimate_gram_condition(g, 0.0, "est")
        # Ritz estimate lower-bounds true kappa, within ~one order.
        assert row["kappa"] <= true_k * 1.1
        assert row["kappa"] >= true_k / 20.0


def test_cond_warn_fires_predictively_on_near_singular_gram():
    rng = np.random.default_rng(3)
    a = rng.standard_normal((256, 16)).astype(np.float32)
    a = np.concatenate([a, a], axis=1)  # exact rank deficiency
    g = jnp.asarray(a.T @ a)
    before = counters.get("cond_warn")
    row = knum.estimate_gram_condition(g, 0.0, "rankdef")
    assert row["warned"]
    assert counters.get("cond_warn") - before == 1


def test_fit_report_carries_conditioning(rng):
    x = jnp.asarray(rng.normal(size=(256, 64)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(256, 4)).astype(np.float32))
    est = BlockLeastSquaresEstimator(32, 1, 1e-2)
    with knum.monitored(True):
        est.fit(x, y)
    rep = est.last_fit_report
    assert rep is not None and rep.conditioning
    assert len(rep.conditioning) == 2  # two 32-wide blocks
    for row in rep.conditioning:
        assert row["kappa"] >= 1.0 and not row["warned"]
    assert rep.record()["conditioning"] == rep.conditioning
    # Off-mode fits carry None — no silent recompute.
    est2 = BlockLeastSquaresEstimator(32, 1, 1e-2)
    est2.fit(x, y)
    assert est2.last_fit_report.conditioning is None


def test_condition_estimate_never_raises_on_nonfinite_gram():
    """A NaN gram is the very fault the solver's finite guard converts
    into a typed error — the monitor must step aside (kappa=None), never
    crash the recovery path."""
    g = jnp.asarray(np.full((8, 8), np.nan, np.float32))
    row = knum.estimate_gram_condition(g, 0.0, "nanprobe")
    assert row["kappa"] is None and not row["warned"]
    # The guarded solve still raises its TYPED error with monitoring on.
    from keystone_tpu.solvers.normal_equations import solve_gram_l2

    with knum.monitored(True):
        with pytest.raises(FloatingPointError, match="non-finite"):
            solve_gram_l2(g, jnp.ones((8, 2), jnp.float32), 0.1)


# -- output sketches + drift ---------------------------------------------------


def test_class_histogram_sketch_roundtrip_and_divergence():
    base = knum.OutputSketch.for_outputs(np.array([0, 0, 1, 1, 2, 2]))
    assert base.kind == "class_histogram"
    rec = base.record()
    restored = knum.OutputSketch.from_record(json.loads(json.dumps(rec)))
    same = knum.OutputSketch.for_outputs(np.array([0, 1, 2, 0, 1, 2]))
    assert restored.divergence(same) == pytest.approx(0.0)
    shifted = knum.OutputSketch.for_outputs(np.array([2] * 12))
    assert restored.divergence(shifted) == pytest.approx(2.0 / 3.0)


def test_quantile_sketch_divergence_is_scale_aware(rng):
    vals = rng.normal(size=2000)
    base = knum.OutputSketch.for_outputs(vals.astype(np.float32))
    assert base.kind == "quantile"
    rec = knum.OutputSketch.from_record(base.record())
    same = knum.OutputSketch.for_outputs(vals.astype(np.float32))
    assert rec.divergence(same) == pytest.approx(0.0, abs=1e-9)
    moved = knum.OutputSketch.for_outputs((vals + 5.0).astype(np.float32))
    assert rec.divergence(moved) > 1.0


def test_drift_monitor_counts_once_and_rearms():
    base = knum.OutputSketch.for_outputs(np.zeros(64, np.int64)).record()
    mon = knum.DriftMonitor("drifter", base, tol=0.25)
    before = counters.get("serve_output_drift")
    mon.observe(np.ones(64, np.int64))  # TV = 1.0 — breach
    mon.observe(np.ones(64, np.int64))  # still breached — latched, no recount
    assert counters.get("serve_output_drift") - before == 1
    assert mon.record()["drifted"] and mon.record()["breaches"] == 1
    # Flood with baseline-matching answers until divergence < tol/2 — the
    # latch re-arms and a NEW breach counts again.
    for _ in range(40):
        mon.observe(np.zeros(256, np.int64))
    assert not mon.record()["drifted"]
    for _ in range(80):
        mon.observe(np.ones(512, np.int64))
    assert counters.get("serve_output_drift") - before == 2


def test_class_histogram_drift_detectable_after_long_healthy_prefix(rng):
    """The class sketch windows too: a mix collapse AFTER thousands of
    healthy answers must fire promptly — an accumulate-forever histogram
    would dilute the shift by the healthy prefix's size."""
    base = knum.OutputSketch.for_outputs(
        rng.integers(0, 4, 512).astype(np.int64)
    ).record()
    mon = knum.DriftMonitor("late_class_drifter", base, tol=0.25)
    for _ in range(20):  # 10k+ healthy answers — window saturated
        mon.observe(rng.integers(0, 4, 512).astype(np.int64))
    assert not mon.record()["drifted"]
    before = counters.get("serve_output_drift")
    for _ in range(10):  # the mix collapses onto one class
        mon.observe(np.full(512, 2, np.int64))
    assert mon.record()["drifted"]
    assert counters.get("serve_output_drift") - before == 1


def test_wide_range_integer_outputs_fall_to_quantile_sketch(rng):
    """Negative or wide-range integer heads must NOT become per-value
    class histograms (unbounded counts, ~1.0 TV over near-unique values)."""
    neg = knum.OutputSketch.for_outputs(np.array([-3, 1, 2], np.int64))
    assert neg.kind == "quantile"
    wide = knum.OutputSketch.for_outputs(
        rng.integers(0, 10**9, 256).astype(np.int64)
    )
    assert wide.kind == "quantile"
    classes = knum.OutputSketch.for_outputs(np.array([0, 1, 2], np.int64))
    assert classes.kind == "class_histogram"


def test_quantile_drift_detectable_after_reservoir_saturation(rng):
    """The live sketch is a SLIDING window: drift that begins only after
    the first reservoir-full of healthy answers must still fire (a
    fill-once reservoir would freeze on the healthy prefix forever)."""
    vals = rng.normal(size=4096).astype(np.float32)
    base = knum.OutputSketch.for_outputs(vals).record()
    mon = knum.DriftMonitor("late_drifter", base, tol=0.25)
    # Saturate the live window with healthy traffic first...
    for _ in range(8):
        mon.observe(rng.normal(size=1024).astype(np.float32))
    assert not mon.record()["drifted"]
    before = counters.get("serve_output_drift")
    # ...then the mix moves: the window must roll onto the shifted values.
    for _ in range(8):
        mon.observe((rng.normal(size=1024) + 6.0).astype(np.float32))
    assert mon.record()["drifted"]
    assert counters.get("serve_output_drift") - before == 1


def test_baseline_rides_checkpoint_and_arms_engine(tmp_path, rng):
    stem = str(tmp_path / "drift_pipe")
    baseline = knum.OutputSketch.for_outputs(
        rng.normal(size=512).astype(np.float32)
    ).record()
    kckpt.save_pipeline(stem, Pipeline([Identity()]), numerics_baseline=baseline)
    assert kckpt.load_numerics_baseline(stem) == json.loads(
        json.dumps(baseline)
    )
    # load_pipeline itself is indifferent to the extra manifest entry.
    assert isinstance(kckpt.load_pipeline(stem), Pipeline)
    engine, _cold = kserve.load_engine(
        stem, np.zeros(8, np.float32), label="armtest"
    )
    assert engine.drift is not None
    assert engine.record()["drift"]["kind"] == "quantile"
    # No-baseline artifacts arm nothing.
    stem2 = str(tmp_path / "plain_pipe")
    kckpt.save_pipeline(stem2, Pipeline([Identity()]))
    assert kckpt.load_numerics_baseline(stem2) is None


# -- /statusz + health_view ----------------------------------------------------


def test_statusz_snapshot_schema_and_numerics_surface():
    from keystone_tpu.core import telemetry

    with knum.monitored(True):
        knum.probe("statusz_site", np.ones(4, np.float32))
        snap = telemetry.statusz_snapshot()
    assert snap["schema"] == "keystone.statusz/1"
    for key in ("providers", "slo", "numerics", "faults", "gauges"):
        assert key in snap
    assert "statusz_site" in snap["numerics"]["sites"]
    json.dumps(snap)  # the page must be strict-JSON renderable


def test_health_view_renders_all_sections(rng):
    import health_view

    with knum.monitored(True):
        knum.probe("hv_site", np.ones((4, 2), np.float32))
        knum.estimate_gram_condition(
            jnp.asarray(np.eye(8, dtype=np.float32)), 0.0, "hv_solve"
        )
        base = knum.OutputSketch.for_outputs(np.zeros(64, np.int64)).record()
        mon = knum.DriftMonitor("hv_engine", base, tol=0.25)
        mon.observe(np.ones(64, np.int64))
        doc = {"numerics": knum.snapshot()}
    extracted = health_view.extract_numerics(doc)
    text = health_view.render(extracted)
    assert "hv_site" in text
    assert "hv_solve" in text and "kappa" in text
    assert "hv_engine" in text and "DRIFTED" in text
    # The serving-record embedding path (engine/router drift) works too.
    emb = health_view.extract_numerics(
        {"engine": {"drift": mon.record()}}
    )
    assert "hv_engine" in health_view.render(emb)
    # No numerics surface -> empty extraction (the CLI exits 2 there).
    assert health_view.extract_numerics({"metric": "x"}) == {}
