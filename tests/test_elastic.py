"""Elastic mesh serving (ISSUE 16) — the acceptance surface.

A fit checkpointed under a 4-device mesh must RESUME and SERVE under a
2-device and a 1-device mesh with predictions bit-equal to the
original-mesh run (or a typed, counted refusal — never silent
divergence); the naive load stays a typed ``CheckpointMismatch`` naming
both topologies and the ``mesh=`` escape hatch; and the router's
cross-engine HBM admission re-runs against the SURVIVING mesh's
per-chip budget after a re-anchor.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from keystone_tpu.core import frontend as kfrontend
from keystone_tpu.core import memory as kmem
from keystone_tpu.core import serve as kserve
from keystone_tpu.core.checkpoint import (
    CheckpointMismatch,
    load_pipeline,
    save_pipeline,
)
from keystone_tpu.core.pipeline import FunctionTransformer
from keystone_tpu.core.resilience import counters
from keystone_tpu.ops.stats import StandardScaler, StandardScalerModel
from keystone_tpu.parallel.mesh import (
    DATA_AXIS,
    make_mesh,
    mesh_desc,
    row_sharding,
    use_mesh,
)

WIDTH = 16


@pytest.fixture
def full_mesh(devices):
    return make_mesh(data=4, model=1, devices=devices[:4])


def _fitted_stem(tmp_path, rng, full_mesh, name="elastic"):
    """A fit under the 4-device mesh whose checkpoint holds SHARDED state:
    the scaler is fitted on row-sharded data under ``use_mesh`` and its
    mean is then anchored to the fit placement (data@dim0), so the
    manifest records a real non-replicated spec the reshard loader must
    redistribute."""
    x = jnp.asarray(rng.normal(size=(32, WIDTH)), jnp.float32)
    with use_mesh(full_mesh):
        model = StandardScaler().fit(
            jax.device_put(x, row_sharding(full_mesh))
        )
        model.mean = jax.device_put(
            model.mean, NamedSharding(full_mesh, PartitionSpec(DATA_AXIS))
        )
        stem = save_pipeline(str(tmp_path / name), model)
    test_rows = np.asarray(rng.normal(size=(12, WIDTH)), np.float32)
    original = np.asarray(model(jnp.asarray(test_rows)))
    return stem, test_rows, original


class TestTopologyPortableCheckpoints:
    @pytest.mark.parametrize("survivors", (2, 1))
    def test_resume_and_serve_on_smaller_mesh_bit_equal(
        self, tmp_path, rng, devices, full_mesh, survivors
    ):
        """The acceptance criterion: 4-device fit -> checkpoint ->
        resume AND serve under the surviving mesh, predictions bit-equal
        to the original-mesh run."""
        stem, test_rows, original = _fitted_stem(tmp_path, rng, full_mesh)
        target = make_mesh(data=survivors, model=1, devices=devices[:survivors])

        before = counters.get("ckpt_reshard")
        resumed = load_pipeline(stem, mesh=target)
        assert counters.get("ckpt_reshard") - before >= 1
        np.testing.assert_array_equal(
            np.asarray(resumed(jnp.asarray(test_rows))), original
        )

        engine, cold = kserve.load_engine(
            stem, np.zeros(WIDTH, np.float32),
            config=kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0),
            label=f"elastic_{survivors}", mesh=target,
        )
        assert cold["mesh"] == mesh_desc(target)
        assert engine.parity_ok, engine.parity
        np.testing.assert_array_equal(engine.infer(test_rows), original)
        with kserve.Server(engine) as server:
            served = np.stack([
                f.result(30.0) for f in [server.submit(r) for r in test_rows]
            ])
        np.testing.assert_array_equal(served, original)

    def test_naive_load_refuses_typed_naming_both_topologies(
        self, tmp_path, rng, full_mesh
    ):
        """CheckpointMismatch ergonomics: the refusal names the recorded
        AND the current topology and points at the mesh= reshard path."""
        stem, _, _ = _fitted_stem(tmp_path, rng, full_mesh, name="refuse")
        with pytest.raises(CheckpointMismatch) as exc:
            load_pipeline(stem)
        msg = str(exc.value)
        assert "'data': 4" in msg  # the recorded (fit-time) topology
        assert "mesh=" in msg  # the escape hatch, by name
        assert "refusing" in msg

    def test_manifest_records_per_array_sharding_specs(
        self, tmp_path, rng, full_mesh
    ):
        stem, _, _ = _fitted_stem(tmp_path, rng, full_mesh, name="manifest")
        with open(stem + ".json") as fh:
            manifest = json.load(fh)
        specs = [
            spec.get("sharding", "replicated")
            for spec in manifest["arrays"].values()
        ]
        assert "data@dim0" in specs  # the mean's fit placement
        assert manifest["all_replicated"] is False

    def test_reshard_disabled_stays_the_default(self, tmp_path, rng, full_mesh):
        """mesh=None keeps the typed refusal — resharding is opt-in, a
        surprise topology never silently redistributes."""
        stem, _, _ = _fitted_stem(tmp_path, rng, full_mesh, name="optin")
        with pytest.raises(CheckpointMismatch):
            load_pipeline(stem, mesh=None)


def _relu_build():
    # Shape-agnostic, fusion-invariant arithmetic (one exactly-rounded
    # multiply + max): eager == jit == every bucket on every mesh tier,
    # and any request width builds.
    pipe = FunctionTransformer(
        lambda x: jnp.maximum(x * 1.5, 0.25), name="elastic"
    )

    def build(shape, dtype, mesh):
        cfg = kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0)
        return kserve.ServingEngine(
            pipe, np.zeros(shape, dtype), config=cfg, label="elastic",
            mesh=mesh,
        )

    return build


class TestSurvivingMeshReanchor:
    def test_reanchor_swaps_labels_and_keeps_answers(
        self, devices, full_mesh, rng
    ):
        surviving = make_mesh(data=2, model=1, devices=devices[:2])
        factory = kfrontend.MeshEngineFactory(_relu_build(), mesh=full_mesh)
        router = kfrontend.ShapeRouter(
            factory, label="elastic_swap",
            config=kfrontend.RouterConfig(
                warm_threshold=1, retire_after_s=300.0
            ),
        )
        try:
            engine = factory((WIDTH,), np.float32)
            router.add_engine(engine)
            reqs = np.asarray(rng.normal(size=(8, WIDTH)), np.float32)
            expect = np.asarray(engine.offline(reqs))
            futs = [router.submit(r) for r in reqs[:4]]
            rec = router.reanchor(surviving, why="test shrink")
            futs += [router.submit(r) for r in reqs[4:]]
            got = np.stack([np.asarray(f.result(30.0)) for f in futs])
            np.testing.assert_array_equal(got, expect)
            assert rec["failed"] == [] and len(rec["swapped"]) == 1
            # the replacement must NOT share the retired engine's label —
            # SLO/drift trackers unregister by label at retire
            assert router.engines()[(WIDTH,)] == f"elastic@{mesh_desc(surviving)}"
            r = router.record()
            assert r["mesh"] == mesh_desc(surviving)
            assert r["last_reanchor"]["reshard_wall_s"] > 0
        finally:
            router.close()

    def test_factory_walks_the_ladder_on_denial(self, devices, monkeypatch):
        """A mesh tier whose buckets are all denied per-chip admission
        steps down (counted router_mesh_stepdown) until a tier builds —
        the single-device floor if need be."""
        mesh = make_mesh(data=2, model=1, devices=devices[:2])
        # Per-chip budget of 1 byte on ANY mesh: every mesh-tier bucket is
        # denied; the meshless floor plans against hbm_budget (None here,
        # analytic admission skipped) and builds.
        monkeypatch.setattr(kmem, "min_chip_budget", lambda m: (1, None))
        monkeypatch.setattr(kmem, "hbm_budget", lambda device=None: None)
        before = counters.get("router_mesh_stepdown")
        factory = kfrontend.MeshEngineFactory(_relu_build(), mesh=mesh)
        engine = factory((WIDTH,), np.float32)
        assert engine.mesh is None  # landed on the floor
        assert counters.get("router_mesh_stepdown") - before >= 1

    def test_cross_admission_pins_surviving_mesh_budget(
        self, devices, full_mesh, monkeypatch
    ):
        """Satellite regression (ISSUE 16): after a re-anchor the router's
        cross-engine admission must budget against the SURVIVING mesh's
        min_chip_budget — the dead topology's (or the meshless global)
        budget would over-admit."""
        surviving = make_mesh(data=2, model=1, devices=devices[:2])
        factory = kfrontend.MeshEngineFactory(_relu_build(), mesh=full_mesh)
        router = kfrontend.ShapeRouter(
            factory, label="elastic_admission",
            config=kfrontend.RouterConfig(
                warm_threshold=1, retire_after_s=300.0
            ),
        )
        try:
            router.add_engine(factory((WIDTH,), np.float32))
            router.reanchor(surviving, why="test shrink")

            seen = []

            def spy_min_chip_budget(m):
                seen.append(m)
                return (64, None)  # tiny per-chip budget: must deny

            monkeypatch.setattr(kmem, "min_chip_budget", spy_min_chip_budget)
            # The WRONG budget source (the meshless global) would admit:
            monkeypatch.setattr(kmem, "hbm_budget", lambda device=None: None)
            with pytest.raises(kfrontend.RetryLater):
                router.submit(np.zeros(8, np.float32))  # new shape -> warm
            assert surviving in seen, (
                "cross-engine admission never consulted the surviving "
                "mesh's per-chip budget"
            )
            assert router.stats.admission_denied >= 1
            denied = router.admissions[-1]
            assert denied["admitted"] is False
            assert denied["budget_bytes"] == 64
        finally:
            router.close()
