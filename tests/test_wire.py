"""Wire-protocol tests (core.wire): framing roundtrips, the socket
server/client pair end to end, per-client backpressure windows, typed
errors over the wire, mid-batch disconnects, and slow-loris immunity."""

import socket
import threading
import time

import numpy as np
import pytest

from test_frontend import _make_engine, _reqs

from keystone_tpu.core import frontend, wire
from keystone_tpu.core import serve as kserve
from keystone_tpu.core.resilience import counters

pytestmark = pytest.mark.serve


# -- framing ------------------------------------------------------------------


class TestFraming:
    @pytest.mark.parametrize(
        "arr",
        [
            np.arange(12, dtype=np.float32).reshape(3, 4),
            np.arange(8, dtype=np.float64),
            np.arange(6, dtype=np.int32).reshape(1, 2, 3),
            np.array(3.5, dtype=np.float32),  # rank 0
            np.zeros((0, 4), np.uint8),  # empty
            np.array([True, False, True]),
        ],
    )
    def test_array_roundtrip_bit_exact(self, arr):
        out = wire.decode_array(wire.encode_array(arr))
        assert out.dtype == arr.dtype
        assert out.shape == arr.shape
        assert np.array_equal(out, arr)

    def test_object_dtype_rejected(self):
        with pytest.raises(wire.WireProtocolError, match="object"):
            wire.encode_array(np.array(["x"], dtype=object))

    def test_size_mismatch_rejected(self):
        body = bytearray(wire.encode_array(np.zeros(4, np.float32)))
        with pytest.raises(wire.WireProtocolError, match="declares"):
            wire.decode_array(bytes(body[:-2]))

    def test_frame_extract_handles_partials_byte_by_byte(self):
        arr = np.arange(5, dtype=np.float32)
        frame = wire.encode_frame(
            wire.T_REQUEST, 77, wire.encode_array(arr)
        )
        buf = bytearray()
        out = None
        for byte in frame:
            buf.append(byte)
            got = wire.extract_frame(buf, wire.max_frame_bytes())
            if got is not None:
                out = got
        assert out is not None
        ftype, rid, body = out
        assert (ftype, rid) == (wire.T_REQUEST, 77)
        assert np.array_equal(wire.decode_array(body), arr)
        assert not buf  # fully consumed

    def test_two_frames_in_one_buffer(self):
        f1 = wire.encode_frame(wire.T_PING, 1)
        f2 = wire.encode_frame(wire.T_PING, 2)
        buf = bytearray(f1 + f2)
        assert wire.extract_frame(buf, 2**20)[1] == 1
        assert wire.extract_frame(buf, 2**20)[1] == 2
        assert wire.extract_frame(buf, 2**20) is None

    def test_oversized_and_runt_and_bad_version_rejected(self):
        buf = bytearray(wire._LEN.pack(2**30) + b"xxxx")
        with pytest.raises(wire.WireProtocolError, match="cap"):
            wire.extract_frame(buf, wire.max_frame_bytes())
        buf = bytearray(wire._LEN.pack(2) + b"xx")
        with pytest.raises(wire.WireProtocolError, match="runt"):
            wire.extract_frame(buf, 2**20)
        payload = wire._HEAD.pack(9, wire.T_PING, 1)
        buf = bytearray(wire._LEN.pack(len(payload)) + payload)
        with pytest.raises(wire.WireProtocolError, match="version"):
            wire.extract_frame(buf, 2**20)

    def test_error_and_retry_roundtrip(self):
        _, _, body = wire.extract_frame(
            bytearray(wire.encode_error(5, "MalformedRequest", "bad µ")),
            2**20,
        )
        assert wire.decode_error(body) == ("MalformedRequest", "bad µ")
        _, _, body = wire.extract_frame(
            bytearray(wire.encode_retry_after(6, 0.25, "window full")),
            2**20,
        )
        assert wire.decode_retry_after(body) == (0.25, "window full")


# -- a stalling target (no jax needed) ----------------------------------------


class _StallTarget:
    """Accepts every submit, resolves nothing until told — the in-flight
    window fills deterministically."""

    def __init__(self):
        self.lock = threading.Lock()
        self.futs: list = []

    def submit(self, arr):
        fut = kserve.ServeFuture()
        with self.lock:
            self.futs.append((fut, np.asarray(arr)))
        return fut

    def release_all(self):
        with self.lock:
            futs, self.futs = self.futs, []
        for fut, arr in futs:
            fut._resolve(value=arr * 2.0)


# -- the socket server/client pair --------------------------------------------


class TestWireServer:
    def test_end_to_end_bit_equal_multi_shape(self, rng):
        e16, e8 = _make_engine((16,)), _make_engine((8,))
        with frontend.ShapeRouter(label="wiretest") as router:
            router.add_engine(e16)
            router.add_engine(e8)
            with wire.WireServer(router, port=0) as ws:
                with wire.WireClient(port=ws.port) as client:
                    assert client.ping() < 5.0
                    r16 = _reqs(rng, 12, (16,))
                    r8 = _reqs(rng, 5, (8,))
                    a16 = np.stack(client.predict_many(list(r16), window=4))
                    a8 = np.stack(client.predict_many(list(r8), window=4))
                assert np.array_equal(a16, e16.offline(r16))
                assert np.array_equal(a8, e8.offline(r8))
                rec = ws.record()
                assert rec["stats"]["requests"] >= 17
                assert rec["stats"]["responses"] >= 17
                assert rec["stats"]["protocol_errors"] == 0

    def test_two_concurrent_clients_fair_and_bit_equal(self, rng):
        e16 = _make_engine((16,))
        results: dict = {}
        errors: list = []
        with frontend.ShapeRouter(label="wirefair") as router:
            router.add_engine(e16)
            with wire.WireServer(router, port=0, max_inflight=4) as ws:

                def client(cid, reqs):
                    try:
                        with wire.WireClient(port=ws.port) as c:
                            results[cid] = np.stack(
                                c.predict_many(list(reqs), window=8)
                            )
                    except BaseException as e:  # noqa: BLE001
                        errors.append(e)

                r0, r1 = _reqs(rng, 20, (16,)), _reqs(rng, 20, (16,))
                ts = [
                    threading.Thread(target=client, args=(0, r0)),
                    threading.Thread(target=client, args=(1, r1)),
                ]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(60.0)
                assert not errors, errors
                assert np.array_equal(results[0], e16.offline(r0))
                assert np.array_equal(results[1], e16.offline(r1))
                # window 8 > max_inflight 4: the flood was pushed back at
                # least once and the clients retried their way through.
                assert ws.stats.retry_after >= 1

    def test_inflight_window_pushes_back_retry_after(self):
        target = _StallTarget()
        with wire.WireServer(target, port=0, max_inflight=2) as ws:
            client = wire.WireClient(port=ws.port, timeout=10.0)
            try:
                for _ in range(5):
                    client.submit(np.zeros(4, np.float32))
                retries = 0
                for _ in range(3):
                    reply = client.read()
                    assert reply.type == wire.T_RETRY_AFTER
                    assert reply.retry_after_s > 0
                    retries += 1
                assert retries == 3  # window 2 held, 3 pushed back
                target.release_all()
                got = {client.read().request_id for _ in range(2)}
                assert got == {1, 2}  # the two admitted requests answered
            finally:
                client.close()
            assert ws.stats.retry_after == 3

    def test_typed_errors_cross_the_wire(self, rng):
        e16 = _make_engine((16,))
        with frontend.ShapeRouter(label="wireerr") as router:
            router.add_engine(e16)
            with wire.WireServer(router, port=0) as ws:
                with wire.WireClient(port=ws.port) as client:
                    # wrong shape, no factory -> NoRouteForShape over ERROR
                    with pytest.raises(wire.WireRemoteError) as ei:
                        client.predict(np.zeros(5, np.float32))
                    assert ei.value.etype == "NoRouteForShape"
                    bad = _reqs(rng, 1, (16,))[0]
                    bad[0] = np.nan
                    with pytest.raises(wire.WireRemoteError) as ei:
                        client.predict(bad)
                    assert ei.value.etype == "MalformedRequest"
                    # the connection survives typed errors
                    ok = _reqs(rng, 1, (16,))[0]
                    assert np.array_equal(
                        client.predict(ok), e16.offline(ok[None])[0]
                    )
                assert ws.stats.errors >= 2

    def test_router_backpressure_maps_to_retry_after(self, rng):
        cfg = frontend.RouterConfig(warm_threshold=2, retry_after_s=0.01)
        router = frontend.ShapeRouter(
            _make_engine, label="wirewarm", config=cfg
        )
        try:
            with wire.WireServer(router, port=0) as ws:
                with wire.WireClient(port=ws.port) as client:
                    req = _reqs(rng, 1, (8,))[0]
                    out = client.predict(req, timeout=60.0)
                    assert out is not None
                    assert ws.stats.retry_after >= 1  # the cold-shape pushback
            assert router.stats.warm_adds == 1
        finally:
            router.close()

    def test_client_disconnect_mid_batch_counted_batch_completes(self):
        target = _StallTarget()
        before = counters.get("wire_client_disconnect")
        with wire.WireServer(target, port=0, max_inflight=8) as ws:
            # Client A submits and vanishes with requests in flight.
            a = wire.WireClient(port=ws.port)
            for _ in range(3):
                a.submit(np.ones(4, np.float32))
            deadline = time.monotonic() + 10.0
            while len(target.futs) < 3 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert len(target.futs) == 3
            a.close()
            deadline = time.monotonic() + 10.0
            while (
                ws.stats.mid_batch_disconnects < 1
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            assert ws.stats.mid_batch_disconnects == 1
            assert counters.get("wire_client_disconnect") == before + 1
            # The batch still completes (futures resolve) and a live
            # client keeps being served.
            target.release_all()
            with wire.WireClient(port=ws.port) as b:
                b.submit(np.full(4, 3.0, np.float32))
                deadline = time.monotonic() + 10.0
                while not target.futs and time.monotonic() < deadline:
                    time.sleep(0.01)
                target.release_all()
                reply = b.read()
                assert reply.type == wire.T_RESPONSE
                assert np.array_equal(
                    reply.value, np.full(4, 6.0, np.float32)
                )

    def test_slow_loris_partial_frame_starves_nobody(self):
        target = _StallTarget()
        with wire.WireServer(target, port=0) as ws:
            loris = socket.create_connection(("127.0.0.1", ws.port), 5.0)
            try:
                # Half a length prefix, then silence: the reader parks on
                # ITS buffer; the accept loop and other clients must not.
                loris.sendall(b"\x00\x00")
                time.sleep(0.1)
                t0 = time.monotonic()
                with wire.WireClient(port=ws.port) as c:
                    c.submit(np.ones(4, np.float32))
                    deadline = time.monotonic() + 10.0
                    while not target.futs and time.monotonic() < deadline:
                        time.sleep(0.01)
                    target.release_all()
                    reply = c.read()
                    assert reply.type == wire.T_RESPONSE
                assert time.monotonic() - t0 < 5.0
            finally:
                loris.close()

    def test_protocol_violation_answers_error_and_closes(self):
        target = _StallTarget()
        with wire.WireServer(target, port=0) as ws:
            sock = socket.create_connection(("127.0.0.1", ws.port), 5.0)
            try:
                sock.sendall(wire._LEN.pack(2**31) + b"garbage")
                sock.settimeout(5.0)
                buf = bytearray()
                while True:
                    frame = wire.extract_frame(buf, 2**20)
                    if frame is not None:
                        break
                    chunk = sock.recv(4096)
                    assert chunk, "connection closed with no ERROR frame"
                    buf.extend(chunk)
                ftype, _rid, body = frame
                assert ftype == wire.T_ERROR
                assert wire.decode_error(body)[0] == "WireProtocolError"
                # ... and the connection dies (violators lose their parser)
                deadline = time.monotonic() + 5.0
                closed = False
                while time.monotonic() < deadline:
                    chunk = sock.recv(4096)
                    if not chunk:
                        closed = True
                        break
                assert closed
            finally:
                sock.close()
            assert ws.stats.protocol_errors == 1

    def test_close_is_idempotent_and_joins(self):
        target = _StallTarget()
        ws = wire.WireServer(target, port=0)
        with wire.WireClient(port=ws.port) as c:
            c.ping()
            ws.close()
            ws.close()
        assert not ws._accept_thread.is_alive()
