"""Fleet observability plane (ISSUE 20) — the acceptance surface.

The merge math must be associative and order-independent with fleet
percentiles from POOLED raw windows (never averaged per-host
percentiles, held to a numpy oracle); clock-aligned incident events must
land on one monotone timeline under injected member-clock skew; the
agent payload must ride the existing wire socket; the collector must sum
counters exactly, degrade (never crash) on member death with the fleet
view monotone, and write ONE schema-tagged incident bundle.  The
subprocess drill (``dist`` marker) is the end-to-end acceptance: 2 real
members over sockets, one SIGKILLed mid-scrape, serving bit-equal.
"""

import json
import os

import numpy as np
import pytest

from keystone_tpu.core import fleetobs, telemetry, trace, wire
from keystone_tpu.core.resilience import counters
from keystone_tpu.workloads import multihost


def _window(rng, n):
    samples = np.abs(rng.normal(2.0, 1.0, size=n)).tolist()
    return {
        "count": n,
        "total": float(sum(samples)),
        "min": float(min(samples)),
        "max": float(max(samples)),
        "samples": samples,
    }


# -- merge math ----------------------------------------------------------------


class TestMergeMath:
    def test_merge_is_associative(self, rng):
        ws = [_window(rng, n) for n in (5, 9, 17, 3)]
        left = fleetobs.merge_windows(
            [fleetobs.merge_windows(ws[:2]), fleetobs.merge_windows(ws[2:])]
        )
        flat = fleetobs.merge_windows(ws)
        assert fleetobs.window_summary(left) == fleetobs.window_summary(flat)
        assert left["count"] == flat["count"]
        assert left["min"] == flat["min"] and left["max"] == flat["max"]

    def test_merge_is_order_independent(self, rng):
        ws = [_window(rng, n) for n in (8, 4, 12)]
        fwd = fleetobs.window_summary(fleetobs.merge_windows(ws))
        rev = fleetobs.window_summary(fleetobs.merge_windows(ws[::-1]))
        # percentiles/extrema/count are exactly order-free; the mean's
        # float summation order differs by at most an ulp
        assert fwd.pop("mean") == pytest.approx(rev.pop("mean"))
        assert fwd == rev

    def test_single_member_fleet_summarizes_like_the_member(self, rng):
        """A fleet of one must report exactly what the one reports — the
        pick rule over the pooled (= its own) sorted samples."""
        w = _window(rng, 21)
        s = fleetobs.window_summary(fleetobs.merge_windows([w]))
        m = trace._Hist()
        for x in w["samples"]:
            m.observe(x)
        assert s["p99"] == m.summary()["p99"]
        assert s["p50"] == m.summary()["p50"]
        assert s["count"] == m.summary()["count"]

    def test_fleet_p99_matches_pooled_numpy_oracle(self, rng):
        """ISSUE 20 satellite: fleet p99 from merged windows vs a pooled-
        sample numpy oracle — pooling is exact; AVERAGING the per-member
        p99s (the anti-pattern) is measurably wrong on skewed members."""
        slow = _window(rng, 40)
        slow["samples"] = (np.asarray(slow["samples"]) * 50.0).tolist()
        slow["total"] = float(sum(slow["samples"]))
        slow["min"], slow["max"] = min(slow["samples"]), max(slow["samples"])
        members = [_window(rng, 40), _window(rng, 40), slow]
        merged = fleetobs.merge_windows(members)
        fleet_p99 = fleetobs.window_summary(merged)["p99"]
        pool = np.sort(np.concatenate([m["samples"] for m in members]))
        assert fleet_p99 == pool[min(len(pool) - 1, int(0.99 * len(pool)))]
        oracle = float(np.percentile(pool, 99))
        assert abs(fleet_p99 - oracle) <= 0.25 * abs(oracle)
        averaged = float(
            np.mean([fleetobs.window_summary(m)["p99"] for m in members])
        )
        assert abs(averaged - oracle) > abs(fleet_p99 - oracle)

    def test_empty_and_sampleless_windows(self):
        assert fleetobs.window_summary(fleetobs.merge_windows([])) == {
            "count": 0
        }
        no_samples = {
            "count": 4, "total": 8.0, "min": 1.0, "max": 3.0, "samples": [],
        }
        s = fleetobs.window_summary(fleetobs.merge_windows([no_samples]))
        assert s == {"count": 4, "mean": 2.0, "min": 1.0, "max": 3.0}

    def test_slo_burn_pools_counts_not_rates(self):
        """Fleet burn = pooled violations / pooled count / budget: a
        loaded member must outweigh an idle one."""
        busy = {
            "slo_ms": 50.0, "budget": 0.01,
            "window": {"count": 900, "violations": 0},
            "total": {"requests": 900, "violations": 0},
        }
        idle = {
            "slo_ms": 50.0, "budget": 0.01,
            "window": {"count": 100, "violations": 10},
            "total": {"requests": 100, "violations": 10},
        }
        m = fleetobs.merge_slo([busy, idle])
        assert m["window"]["count"] == 1000
        assert m["window"]["violations"] == 10
        assert m["window"]["burn_rate"] == 1.0  # 1% rate / 1% budget
        assert m["total"]["requests"] == 1000


# -- clock alignment -----------------------------------------------------------


class TestClockAlignment:
    def test_skewed_members_land_on_one_monotone_timeline(self, rng):
        """ISSUE 20 satellite: events that happened in a known TRUE order
        on members whose clocks are skewed by injected offsets must come
        out monotone (the true order) after alignment."""
        true_ts = np.sort(rng.uniform(0, 1e6, size=30))
        skews = {"a": 250_000.0, "b": -125_000.0, "c": 0.0}
        owners = [list(skews)[i % 3] for i in range(30)]
        aligned = []
        for member, skew in skews.items():
            evs = [
                {"name": f"e{i}", "ph": "i", "ts": float(t + skew)}
                for i, t in enumerate(true_ts)
                if owners[i] == member
            ]
            # offset_us = member_clock - collector_clock = skew
            aligned.extend(fleetobs.align_events(evs, skew, member))
        aligned.sort(key=lambda e: e["ts"])
        out_ts = [e["ts"] for e in aligned]
        assert out_ts == sorted(out_ts)
        np.testing.assert_allclose(out_ts, true_ts, atol=1e-6)
        # the member's own stamp is preserved for cross-checking
        assert all("ts_member" in e and "member" in e for e in aligned)

    def test_metadata_events_pass_through_tagged(self):
        out = fleetobs.align_events(
            [{"ph": "M", "name": "process_name"}], 1000.0, "h0"
        )
        assert out == [{"ph": "M", "name": "process_name", "member": "h0"}]


# -- agent payload over the live wire socket -----------------------------------


class TestAgentAndCollector:
    def test_agent_payload_schema(self):
        trace.metrics.observe("fo_lat_ms", 3.0)
        p = fleetobs.agent_payload()
        assert p["schema"] == fleetobs.OBS_SCHEMA
        assert p["pid"] == os.getpid()
        assert p["statusz"]["schema"].startswith("keystone.statusz/")
        assert "fo_lat_ms" in p["hist_windows"]
        assert p["hist_windows"]["fo_lat_ms"]["samples"] == [3.0]
        f = fleetobs.agent_payload("flight")
        assert "flight" in f and "statusz" not in f

    def test_payload_is_json_clean(self):
        json.dumps(fleetobs.agent_payload())
        json.dumps(fleetobs.agent_payload("flight"))

    def test_collector_sums_counters_and_pools_histograms(self, tmp_path):
        trace.metrics.inc("fo_widgets", 5)
        trace.metrics.observe("fo_pool_ms", 1.0)
        trace.metrics.observe("fo_pool_ms", 9.0)
        with fleetobs.ObsAgent(label="t1") as a1, fleetobs.ObsAgent(
            label="t2"
        ) as a2:
            col = fleetobs.FleetCollector(
                [("127.0.0.1", a1.port), ("127.0.0.1", a2.port)],
                interval_s=30.0, label="t",
            )
            with col:
                snap = col.scrape_once()
                assert snap["schema"] == fleetobs.FLEET_STATUSZ_SCHEMA
                assert snap["alive"] == 2 and snap["lost"] == 0
                # both members are THIS process: fleet sum is exactly 2x
                assert snap["counters"]["fo_widgets"] == 10
                h = snap["histograms"]["fo_pool_ms"]
                assert h["count"] == 4 and h["max"] == 9.0
                prom = col.fleet_prometheus()
                assert f'keystone_fo_widgets{{host="127.0.0.1:{a1.port}"}} 5' in prom
                assert "keystone_fleet_fo_widgets 10" in prom
                assert "keystone_fleet_members_alive 2" in prom
                assert col.fleet_healthz() == {
                    "ok": True, "degraded": False, "alive": 2, "members": 2,
                }

    def test_member_death_degrades_counted_and_stays_monotone(
        self, tmp_path
    ):
        """A dead member: counted ``obs_member_lost`` (postmortem family),
        fleet DEGRADED not crashed, its last-known counters retained so
        the fleet totals never step backwards."""
        a1 = fleetobs.ObsAgent(label="m1")
        a2 = fleetobs.ObsAgent(label="m2")
        col = fleetobs.FleetCollector(
            [("127.0.0.1", a1.port), ("127.0.0.1", a2.port)],
            interval_s=30.0, label="t", incident_dir=str(tmp_path),
        )
        try:
            before_snap = col.scrape_once()
            before = counters.counts().get("obs_member_lost", 0)
            a2.close()
            after_snap = col.scrape_once()  # must NOT raise
            assert counters.counts().get("obs_member_lost", 0) == before + 1
            assert after_snap["lost"] == 1 and after_snap["degraded"]
            hz = col.fleet_healthz()
            assert hz["ok"] and hz["degraded"]
            for k, v in before_snap["counters"].items():
                assert after_snap["counters"].get(k, 0) >= v, k
            # the loss itself produced ONE incident bundle
            assert len(col.incident_paths) == 1
            doc = json.load(open(col.incident_paths[0]))
            assert doc["schema"] == fleetobs.INCIDENT_SCHEMA
            assert doc["trigger"]["kind"] == "obs_member_lost"
            key1 = f"127.0.0.1:{a1.port}"
            assert key1 in doc["members"]
            ts = [
                e["ts"] for e in doc["events"]
                if isinstance(e.get("ts"), (int, float))
            ]
            assert ts == sorted(ts)
            # re-scraping the dead member keeps degrading quietly: no new
            # count (the alive->dead edge fired once), never a raise
            col.scrape_once()
            assert counters.counts().get("obs_member_lost", 0) == before + 1
        finally:
            col.close()
            a1.close()
            a2.close()

    def test_incident_bundles_are_capped_per_kind(self, tmp_path):
        with fleetobs.ObsAgent(label="cap") as a:
            col = fleetobs.FleetCollector(
                [("127.0.0.1", a.port)], interval_s=30.0,
                incident_dir=str(tmp_path), label="cap",
            )
            with col:
                col.scrape_once()
                paths = [
                    col.capture_incident("demo_cap", detail=f"n{i}")
                    for i in range(fleetobs.MAX_INCIDENTS_PER_KIND + 2)
                ]
                written = [p for p in paths if p]
                assert len(written) == fleetobs.MAX_INCIDENTS_PER_KIND

    def test_collector_without_incident_dir_never_writes(self, tmp_path):
        with fleetobs.ObsAgent(label="nodir") as a:
            col = fleetobs.FleetCollector(
                [("127.0.0.1", a.port)], interval_s=30.0, label="nodir",
            )
            with col:
                col.scrape_once()
                assert col.capture_incident("demo_nodir") is None
                assert col.incident_paths == []

    def test_register_readmits_known_endpoint(self):
        with fleetobs.ObsAgent(label="readmit") as a:
            col = fleetobs.FleetCollector(interval_s=30.0, label="r")
            with col:
                col.register(("127.0.0.1", a.port), rank=0)
                col.scrape_once()
                key = f"127.0.0.1:{a.port}"
                col._members[key]["alive"] = False  # simulate a loss
                col.register(("127.0.0.1", a.port))
                assert col.members()[key]["alive"]
                assert len(col.members()) == 1  # revived, not duplicated

    def test_obs_frames_live_on_the_serving_socket(self):
        """The serving endpoint IS the obs endpoint: one WireServer
        answers predict AND obs frames."""

        class _Ready:
            def __init__(self, v):
                self._v = v

            def result(self, timeout=None):
                return self._v

        class _Doubler:
            def submit(self, arr):
                return _Ready(np.asarray(arr) * 2.0)

            def record(self):
                return {}

        s = wire.WireServer(_Doubler(), port=0, label="obs_serve")
        try:
            c = wire.WireClient("127.0.0.1", s.port, timeout=10.0)
            try:
                np.testing.assert_array_equal(
                    np.asarray(c.predict(np.ones(4, np.float32))),
                    np.full(4, 2.0, np.float32),
                )
                snap = c.obs_snapshot()
                assert snap["pid"] == os.getpid()
                flight = c.obs_flight()
                assert isinstance(flight["flight"], list)
            finally:
                c.close()
        finally:
            s.close()


# -- HostFleet wiring ----------------------------------------------------------


def test_hostfleet_attach_collector_registers_and_readmits():
    with fleetobs.ObsAgent(label="fa") as a1, fleetobs.ObsAgent(
        label="fb"
    ) as a2:
        eps = [("127.0.0.1", a1.port), ("127.0.0.1", a2.port)]
        col = fleetobs.FleetCollector(interval_s=30.0, label="hf")
        with col, kfleet_ctx(eps) as fleet:
            fleet.attach_collector(col)
            assert set(col.members()) == {
                f"127.0.0.1:{a1.port}", f"127.0.0.1:{a2.port}"
            }
            col._members[f"127.0.0.1:{a2.port}"]["alive"] = False
            fleet.reattach(("127.0.0.1", a2.port))
            assert col.members()[f"127.0.0.1:{a2.port}"]["alive"]


def kfleet_ctx(eps):
    from keystone_tpu.core import frontend as kfrontend

    return kfrontend.HostFleet(eps, label="obs_hf")


# -- the end-to-end acceptance drill ------------------------------------------


@pytest.mark.dist
def test_obs_capture_drill_subprocess_acceptance(tmp_path):
    """ISSUE 20 acceptance: 2 REAL subprocess members over sockets with
    the collector attached — (a) fleet counters equal the sum of
    per-member snapshots, (b) fleet p99 from merged windows matches the
    pooled-sample oracle, (c) after one member is SIGKILLed mid-scrape,
    ONE incident bundle holds every surviving member's flight ring on a
    monotone clock-aligned timeline — and every request answers bit-equal
    to the offline oracle (zero dropped)."""
    rec = multihost.run_obs_capture_drill(
        str(tmp_path), hosts=2, requests=16, subprocess_mode=True,
        timeout_s=180.0,
    )
    assert rec["counter_sum_ok"], rec.get("counter_sum_mismatch")
    assert rec["p99_match"], {
        k: rec.get(k)
        for k in ("p99_fleet", "p99_oracle_pick", "p99_oracle_np")
    }
    assert rec["monotone_ok"], rec.get("monotone_violations")
    assert rec["obs_member_lost"] >= 1
    assert rec["dropped_requests"] == 0
    assert rec["mismatches"] == 0
    inc = rec["incident"]
    assert inc["schema"] == fleetobs.INCIDENT_SCHEMA
    assert inc["survivor_rings_ok"], inc
    assert inc["events_monotone"], inc
    assert rec["fleet_alive"] == 1 and rec["fleet_lost"] == 1
    assert any("obs_member_lost" in p for p in rec["postmortems"])


# -- labeled exposition rides the fleet renderer -------------------------------


def test_fleet_prometheus_uses_labeled_exposition():
    lbl = telemetry.render_labels({"host": "h0", "rank": 1})
    assert lbl == '{host="h0",rank="1"}'
