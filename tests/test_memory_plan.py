"""Memory-resilience suite: HBM admission control (core.memory), the
solver degradation ladder, donation ownership rules, and OOM-retry —
driven by a simulated HBM budget (``KEYSTONE_HBM_BUDGET``) and the
RESOURCE_EXHAUSTED injector in tests/faults.py.  All tier-1 fast.

The ladder-selection tests derive their budget thresholds from the
estimator's OWN preflight report (fit once with a generous budget, read
the per-tier totals, then refit with a budget pinched between two tiers)
so they assert behavior, not hard-coded byte counts.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from faults import oom_faults, resource_exhausted_error

from keystone_tpu.core import memory as kmem
from keystone_tpu.core.resilience import counters
from keystone_tpu.solvers import block as block_mod
from keystone_tpu.solvers import weighted as weighted_mod
from keystone_tpu.solvers.block import BlockLeastSquaresEstimator
from keystone_tpu.solvers.weighted import BlockWeightedLeastSquaresEstimator


# Wide-and-short problem: the fused program's footprint (args + analytic
# temp floor) strictly dominates the stepwise block program's, which
# dominates the host-staged block program's — so a budget can select each
# tier.  (Tall-skinny shapes invert fused vs stepwise on CPU because the
# residual appears in both the block program's args and outputs.)
N, D, K, BS = 32, 1024, 16, 64


def _problem(rng):
    a = rng.normal(size=(N, D)).astype(np.float32)
    b = rng.normal(size=(N, K)).astype(np.float32)
    return a, b


def _fit(a, b, **kw):
    est = BlockLeastSquaresEstimator(BS, num_iter=2, lam=0.5)
    model = est.fit(a, b, **kw)
    return est, np.asarray(model(jnp.asarray(a)))


class TestBudget:
    def test_parse_bytes(self):
        assert kmem.parse_bytes("512M") == 512 * 2**20
        assert kmem.parse_bytes("16G") == 16 * 2**30
        assert kmem.parse_bytes("1.5GB") == int(1.5 * 2**30)
        assert kmem.parse_bytes("2KiB") == 2048
        assert kmem.parse_bytes(4096) == 4096
        with pytest.raises(ValueError, match="cannot parse"):
            kmem.parse_bytes("a lot")

    def test_env_budget_wins(self, monkeypatch):
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "3G")
        assert kmem.hbm_budget() == 3 * 2**30

    def test_no_budget_on_cpu(self, monkeypatch):
        monkeypatch.delenv(kmem.HBM_BUDGET_ENV, raising=False)
        assert kmem.hbm_budget() is None  # CPU devices expose no memory_stats


class TestPlanProgram:
    def test_no_budget_skips_analysis(self, monkeypatch):
        monkeypatch.delenv(kmem.HBM_BUDGET_ENV, raising=False)
        plan = kmem.plan_program(
            jax.jit(lambda x: x @ x.T),
            jax.ShapeDtypeStruct((8, 8), jnp.float32),
            label="skip",
        )
        assert plan.admitted and not plan.analyzed
        assert "admission skipped" in plan.reason

    def test_breakdown_from_memory_analysis(self):
        plan = kmem.plan_program(
            jax.jit(lambda x: x @ x.T),
            jax.ShapeDtypeStruct((64, 64), jnp.float32),
            label="mm",
            require_analysis=True,
        )
        assert plan.analyzed and plan.admitted  # no budget: analyzed, allowed
        assert plan.argument_bytes == 64 * 64 * 4
        assert plan.output_bytes == 64 * 64 * 4
        bd = plan.breakdown()
        assert set(bd) >= {
            "admitted", "argument_gb", "temp_gb", "output_gb", "total_gb",
        }
        assert plan.compiled is not None

    def test_denial_counted(self, monkeypatch):
        before = counters.get("hbm_preflight_denied")
        plan = kmem.plan_program(
            jax.jit(lambda x: x @ x.T),
            jax.ShapeDtypeStruct((128, 128), jnp.float32),
            label="tiny_budget",
            budget=100,
        )
        assert not plan.admitted and "DENIED" in plan.reason
        assert counters.get("hbm_preflight_denied") == before + 1

    def test_extra_and_floor_bytes_count(self):
        arg = jax.ShapeDtypeStruct((16, 16), jnp.float32)
        base = kmem.plan_program(
            jax.jit(lambda x: x + 1), arg, label="b", require_analysis=True
        )
        plus = kmem.plan_program(
            jax.jit(lambda x: x + 1), arg, label="p",
            require_analysis=True, extra_bytes=10_000, min_temp_bytes=5_000,
        )
        assert plus.total_bytes == base.total_bytes + 10_000 + (
            5_000 - base.temp_bytes
        )


class TestOomDetection:
    def test_injected_oom_is_recognized(self):
        assert kmem.is_oom_error(resource_exhausted_error())

    def test_non_oom_errors_pass_through(self):
        assert not kmem.is_oom_error(ValueError("RESOURCE_EXHAUSTED"))
        assert not kmem.is_oom_error(RuntimeError("shape mismatch"))

    def test_ladder_source_lost_is_not_oom(self):
        # The donate-guard error mentions OOM recovery in prose; it must
        # never be classified as a retryable OOM itself.
        e = kmem.LadderSourceLost(
            "donated — refit with donate=False to keep OOM recovery possible"
        )
        assert not kmem.is_oom_error(e)


class TestResidentCredit:
    def test_live_budget_credits_resident_inputs(self, monkeypatch):
        """A live free-bytes budget already excludes device-resident
        inputs; charging them again would deny fits that actually fit."""
        fn = jax.jit(lambda x: x + 0.0)
        arg = jax.ShapeDtypeStruct((64, 64), jnp.float32)
        nbytes = 64 * 64 * 4
        monkeypatch.delenv(kmem.HBM_BUDGET_ENV, raising=False)  # live
        live = kmem.plan_program(
            fn, arg, label="live", budget=nbytes + 100, resident_bytes=nbytes
        )
        assert live.admitted  # total ~2n, minus n resident -> fits n+100
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1G")  # capacity override
        cap = kmem.plan_program(
            fn, arg, label="cap", budget=nbytes + 100, resident_bytes=nbytes
        )
        assert not cap.admitted  # capacity budgets charge resident inputs


class TestBcdLadder:
    def _tier_totals(self, rng, monkeypatch):
        """Per-tier planned totals, walked sequentially: tiers are planned
        lazily (a tier is only planned after every better tier was denied),
        so each refit with the previous tier's total minus one as the
        budget exposes the next rung's plan."""
        a, b = _problem(rng)
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1G")
        est, _ = _fit(a, b)
        totals = {"fused": est.last_fit_report.plans["fused"].total_bytes}
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, str(totals["fused"] - 1))
        est, _ = _fit(a, b)
        totals["stepwise"] = est.last_fit_report.plans["stepwise"].total_bytes
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, str(totals["stepwise"] - 1))
        est, _ = _fit(a, b)
        totals["host_staged"] = est.last_fit_report.plans[
            "host_staged"
        ].total_bytes
        return totals

    def test_generous_budget_admits_fused(self, rng, monkeypatch):
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1G")
        a, b = _problem(rng)
        est, _ = _fit(a, b)
        rep = est.last_fit_report
        assert rep.chosen == "fused" and not rep.denials
        # Lazy planning: an admitted first tier never plans (or compiles)
        # the tiers below it.
        assert list(rep.plans) == ["fused"]
        # The premise every budget-driven selection below rests on:
        totals = self._tier_totals(rng, monkeypatch)
        assert totals["host_staged"] < totals["stepwise"] < totals["fused"]

    def test_budget_denies_fused_selects_stepwise(self, rng, monkeypatch):
        totals = self._tier_totals(rng, monkeypatch)
        a, b = _problem(rng)
        monkeypatch.setenv(
            kmem.HBM_BUDGET_ENV,
            str((totals["stepwise"] + totals["fused"]) // 2),
        )
        est, _ = _fit(a, b)
        assert est.last_fit_report.chosen == "stepwise"
        assert est.last_fit_report.denials == ["fused"]

    def test_all_device_tiers_denied_selects_host_staged(self, rng, monkeypatch):
        totals = self._tier_totals(rng, monkeypatch)
        a, b = _problem(rng)
        monkeypatch.setenv(
            kmem.HBM_BUDGET_ENV,
            str((totals["host_staged"] + totals["stepwise"]) // 2),
        )
        est, _ = _fit(a, b)
        assert est.last_fit_report.chosen == "host_staged"
        assert est.last_fit_report.denials == ["fused", "stepwise"]

    def test_floor_runs_even_when_denied(self, rng, monkeypatch):
        a, b = _problem(rng)
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1000")
        est, preds = _fit(a, b)
        assert est.last_fit_report.chosen == "host_staged"
        assert np.all(np.isfinite(preds))

    def test_ladder_tiers_numerically_identical(self, rng, monkeypatch):
        """On a shape every tier admits, all three tiers are the SAME
        solve: same centering, masking, pad shift, and update order."""
        totals = self._tier_totals(rng, monkeypatch)
        a, b = _problem(rng)
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1G")
        _, p_fused = _fit(a, b)
        monkeypatch.setenv(
            kmem.HBM_BUDGET_ENV,
            str((totals["stepwise"] + totals["fused"]) // 2),
        )
        est_s, p_step = _fit(a, b)
        assert est_s.last_fit_report.chosen == "stepwise"
        monkeypatch.setenv(
            kmem.HBM_BUDGET_ENV,
            str((totals["host_staged"] + totals["stepwise"]) // 2),
        )
        est_h, p_host = _fit(a, b)
        assert est_h.last_fit_report.chosen == "host_staged"
        np.testing.assert_allclose(p_fused, p_step, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(p_fused, p_host, rtol=1e-5, atol=1e-5)

    def test_oom_retry_steps_down_exactly_one_tier(self, rng, monkeypatch):
        monkeypatch.delenv(kmem.HBM_BUDGET_ENV, raising=False)
        a, b = _problem(rng)
        _, p_clean = _fit(a, b)
        before = counters.get("solver_oom_retry")
        with oom_faults(block_mod, "_execute_fused_bcd", failures=1):
            est, p_retry = _fit(a, b)
        rep = est.last_fit_report
        assert rep.oom_retries == ["fused"]
        assert rep.chosen == "stepwise"  # one tier down, not the floor
        assert counters.get("solver_oom_retry") == before + 1
        np.testing.assert_allclose(p_clean, p_retry, rtol=1e-5, atol=1e-5)

    def test_sharded_inputs_without_mesh_fall_back_to_jit(
        self, rng, mesh8, monkeypatch
    ):
        """A mesh-less fit handed row-SHARDED caller arrays while a budget
        is set must not crash on the single-device AOT executable (its
        baked placements reject sharded inputs) — the executor falls back
        to the jitted variant and the result matches the unsharded fit."""
        from keystone_tpu.parallel.mesh import padded_shard_rows

        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1G")
        a, b = _problem(rng)
        _, p_clean = _fit(a, b)
        a_sh, n = padded_shard_rows(a, mesh8)
        b_sh, _ = padded_shard_rows(b, mesh8)
        est = BlockLeastSquaresEstimator(BS, num_iter=2, lam=0.5)
        model = est.fit(a_sh, b_sh, nvalid=n)
        preds = np.asarray(model(jnp.asarray(a)))
        np.testing.assert_allclose(preds, p_clean, rtol=1e-4, atol=1e-4)

    def test_non_oom_failure_propagates(self, rng, monkeypatch):
        monkeypatch.delenv(kmem.HBM_BUDGET_ENV, raising=False)
        a, b = _problem(rng)

        def boom(*args, **kw):
            raise ValueError("not a memory problem")

        monkeypatch.setattr(block_mod, "_execute_fused_bcd", boom)
        with pytest.raises(ValueError, match="not a memory problem"):
            _fit(a, b)


class TestDonation:
    def test_device_passthrough_never_donated(self, rng, monkeypatch):
        monkeypatch.delenv(kmem.HBM_BUDGET_ENV, raising=False)
        a, b = _problem(rng)
        a_dev, b_dev = jnp.asarray(a), jnp.asarray(b)
        est, _ = _fit(a_dev, b_dev)
        # The caller's arrays must survive a default fit untouched.
        assert not a_dev.is_deleted() and not b_dev.is_deleted()
        assert float(jnp.sum(a_dev)) == pytest.approx(float(np.sum(a)), rel=1e-5)

    def test_host_inputs_fit_matches_device_fit(self, rng, monkeypatch):
        # Host inputs take the donating fused variant (the device copies
        # are fit-owned); results must equal the non-donating fit.
        monkeypatch.delenv(kmem.HBM_BUDGET_ENV, raising=False)
        a, b = _problem(rng)
        _, p_host_in = _fit(a, b)
        _, p_dev_in = _fit(jnp.asarray(a), jnp.asarray(b))
        np.testing.assert_allclose(p_host_in, p_dev_in, rtol=1e-6, atol=1e-6)

    def test_bwls_donate_true_frees_caller_inputs(self, rng, monkeypatch):
        monkeypatch.delenv(kmem.HBM_BUDGET_ENV, raising=False)
        n, d, c = 96, 128, 6
        cls = rng.integers(0, c, n)
        x = (rng.normal(size=(n, d)) + 0.1 * cls[:, None]).astype(np.float32)
        y = (2.0 * np.eye(c)[cls] - 1.0).astype(np.float32)
        ref = BlockWeightedLeastSquaresEstimator(32, 1, 0.1, 0.5).fit(x, y)
        p_ref = np.asarray(ref(jnp.asarray(x)))
        xd, yd = jnp.asarray(x), jnp.asarray(y)
        model = BlockWeightedLeastSquaresEstimator(32, 1, 0.1, 0.5).fit(
            xd, yd, donate=True
        )
        assert xd.is_deleted() and yd.is_deleted()
        np.testing.assert_allclose(
            np.asarray(model(jnp.asarray(x))), p_ref, rtol=1e-6, atol=1e-6
        )


class TestBwlsLadder:
    def _bwls_problem(self, rng):
        n, d, c = 96, 256, 8
        cls = rng.integers(0, c, n)
        x = (rng.normal(size=(n, d)) + 0.1 * cls[:, None]).astype(np.float32)
        y = (2.0 * np.eye(c)[cls] - 1.0).astype(np.float32)
        return x, y

    def _bwls_fit(self, x, y):
        est = BlockWeightedLeastSquaresEstimator(
            32, num_iter=2, lam=0.1, mixture_weight=0.5
        )
        model = est.fit(x, y)
        return est, np.asarray(model(jnp.asarray(x)))

    def test_budget_walks_the_ladder(self, rng, monkeypatch):
        x, y = self._bwls_problem(rng)
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1G")
        est, p_fused = self._bwls_fit(x, y)
        rep = est.last_fit_report
        assert rep.chosen == "fused"
        f_tot = rep.plans["fused"].total_bytes

        # Tiers plan lazily: pinch the budget below each rung in turn.
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, str(f_tot - 1))
        est_s, p_step = self._bwls_fit(x, y)
        assert est_s.last_fit_report.chosen == "stepwise"
        assert est_s.last_fit_report.denials == ["fused"]
        s_tot = est_s.last_fit_report.plans["stepwise"].total_bytes
        assert s_tot < f_tot

        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, str(s_tot - 1))
        est_h, p_host = self._bwls_fit(x, y)
        assert est_h.last_fit_report.chosen == "host_staged"
        assert est_h.last_fit_report.denials == ["fused", "stepwise"]
        assert est_h.last_fit_report.plans["host_staged"].total_bytes < s_tot

        np.testing.assert_allclose(p_fused, p_step, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(p_fused, p_host, rtol=1e-5, atol=1e-5)

    def test_oom_retry_steps_down(self, rng, monkeypatch):
        monkeypatch.delenv(kmem.HBM_BUDGET_ENV, raising=False)
        x, y = self._bwls_problem(rng)
        _, p_clean = self._bwls_fit(x, y)
        with oom_faults(weighted_mod, "_execute_fused_bwls", failures=1):
            est, p_retry = self._bwls_fit(x, y)
        assert est.last_fit_report.chosen == "stepwise"
        assert est.last_fit_report.oom_retries == ["fused"]
        np.testing.assert_allclose(p_clean, p_retry, rtol=1e-5, atol=1e-5)


class TestReportPlumbing:
    def test_report_record_is_jsonable(self, rng, monkeypatch):
        import json

        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1G")
        a, b = _problem(rng)
        est, _ = _fit(a, b)
        rec = est.last_fit_report.record()
        blob = json.loads(json.dumps(rec))
        assert blob["chosen_tier"] == "fused"
        # Lazy planning: only the considered (admitted-first) tier appears.
        assert set(blob["tiers"]) == {"fused"}
        assert blob["tiers"]["fused"]["admitted"] is True

    def test_mesh_fit_reports_mesh_tier(self, rng, mesh8):
        a = rng.normal(size=(24, 16)).astype(np.float32)
        b = rng.normal(size=(24, 4)).astype(np.float32)
        est = BlockLeastSquaresEstimator(8, num_iter=1, lam=0.1, mesh=mesh8)
        est.fit(a, b)
        assert est.last_fit_report.chosen == "fused[mesh 8x1]"
        assert est.last_fit_report.mesh_shape == {"data": 8, "model": 1}


class TestAotReuse:
    """ROADMAP leftover from PR 2: the degraded stepwise tier must execute
    the preflight's AOT-compiled per-block executable, not recompile it at
    first jit dispatch — asserted via the plan compile-counter AND the jit
    dispatch cache staying untouched."""

    def test_bcd_stepwise_compiles_per_block_program_exactly_once(
        self, rng, monkeypatch
    ):
        a, b = _problem(rng)
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1G")
        _, p_clean = _fit(a, b)
        est0, _ = _fit(a, b)
        f_tot = est0.last_fit_report.plans["fused"].total_bytes
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, str(f_tot - 1))

        kmem.clear_plan_cache()
        compiles0 = kmem.compile_count("bcd_stepwise")
        jit_cache0 = block_mod._bcd_block_solve._cache_size()
        est, p_step = _fit(a, b)
        assert est.last_fit_report.chosen == "stepwise"
        # Exactly ONE compile of the per-block solve: the preflight's.
        assert kmem.compile_count("bcd_stepwise") == compiles0 + 1
        # ...and no second compile at jit dispatch on the degraded path.
        assert block_mod._bcd_block_solve._cache_size() == jit_cache0
        np.testing.assert_allclose(p_clean, p_step, rtol=1e-5, atol=1e-5)

        # A refit reuses the cached plan executable: zero new compiles.
        est2, _ = _fit(a, b)
        assert est2.last_fit_report.chosen == "stepwise"
        assert kmem.compile_count("bcd_stepwise") == compiles0 + 1
        assert block_mod._bcd_block_solve._cache_size() == jit_cache0

    def test_bwls_stepwise_reuses_preflight_executable(self, rng, monkeypatch):
        n, d, c = 96, 256, 8
        cls = rng.integers(0, c, n)
        x = (rng.normal(size=(n, d)) + 0.1 * cls[:, None]).astype(np.float32)
        y = (2.0 * np.eye(c)[cls] - 1.0).astype(np.float32)

        def fit():
            est = BlockWeightedLeastSquaresEstimator(
                32, num_iter=2, lam=0.1, mixture_weight=0.5
            )
            model = est.fit(x, y)
            return est, np.asarray(model(jnp.asarray(x)))

        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1G")
        est0, p_clean = fit()
        f_tot = est0.last_fit_report.plans["fused"].total_bytes
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, str(f_tot - 1))

        kmem.clear_plan_cache()
        compiles0 = kmem.compile_count("bwls_stepwise")
        jit_cache0 = weighted_mod._class_solves._cache_size()
        est, p_step = fit()
        assert est.last_fit_report.chosen == "stepwise"
        assert kmem.compile_count("bwls_stepwise") == compiles0 + 1
        assert weighted_mod._class_solves._cache_size() == jit_cache0
        np.testing.assert_allclose(p_clean, p_step, rtol=1e-5, atol=1e-5)


class TestMeshAdmission:
    """Per-chip admission math for GSPMD programs on the forced-8-device
    CPU host: per-axis sharded operand division, conservative replicated
    accounting, minimum-free-chip budgets, and the XLA ground-truth
    cross-check."""

    def test_shard_bytes_divides_by_named_axes(self, mesh42):
        full = 64 * 32 * 4
        row = jax.ShapeDtypeStruct(
            (64, 32), jnp.float32,
            sharding=NamedSharding(mesh42, P("data", None)),
        )
        both = jax.ShapeDtypeStruct(
            (64, 32), jnp.float32,
            sharding=NamedSharding(mesh42, P("data", "model")),
        )
        repl = jax.ShapeDtypeStruct(
            (64, 32), jnp.float32, sharding=NamedSharding(mesh42, P())
        )
        bare = jax.ShapeDtypeStruct((64, 32), jnp.float32)
        assert kmem.shard_bytes(row) == full // 4
        assert kmem.shard_bytes(both) == full // 8
        assert kmem.shard_bytes(repl) == full  # replicated charges whole
        assert kmem.shard_bytes(bare) == full  # un-annotated: conservative

    def test_sharded_vs_replicated_operand_accounting(self, mesh42):
        """A row-sharded operand charges its shard; a replicated operand
        charges full size on every chip — and XLA's per-device module
        accounting (plan.reported) agrees exactly on this program."""
        fn = jax.jit(lambda x, y: x @ y)
        x_s = jax.ShapeDtypeStruct(
            (64, 32), jnp.float32,
            sharding=NamedSharding(mesh42, P("data", None)),
        )
        y_s = jax.ShapeDtypeStruct((32, 16), jnp.float32)  # replicated
        plan = kmem.plan_program(
            fn, x_s, y_s, label="mesh_acct", budget=1 << 30, mesh=mesh42
        )
        assert plan.analyzed and plan.admitted
        assert plan.mesh_axes == {"data": 4, "model": 2}
        assert plan.argument_bytes == (64 * 32 * 4) // 4 + 32 * 16 * 4
        assert plan.reported["argument"] == plan.argument_bytes
        assert "per-chip" in plan.reason and "min-free-chip" in plan.reason
        bd = plan.breakdown()
        assert bd["per_chip"] is True and bd["mesh"] == {"data": 4, "model": 2}
        assert "xla_reported_gb" in bd

    def test_min_chip_budget_takes_the_worst_chip(self, mesh42, monkeypatch):
        monkeypatch.delenv(kmem.HBM_BUDGET_ENV, raising=False)
        devices = list(mesh42.devices.flat)
        frees = {d.id: 10**9 for d in devices}
        tight = devices[3]
        frees[tight.id] = 12345
        monkeypatch.setattr(
            kmem, "hbm_budget", lambda device=None: frees[device.id]
        )
        budget, dev = kmem.min_chip_budget(mesh42)
        assert budget == 12345 and dev.id == tight.id

    def test_min_chip_budget_env_override_is_per_chip(self, mesh42, monkeypatch):
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "2G")
        budget, dev = kmem.min_chip_budget(mesh42)
        assert budget == 2 * 2**30 and dev is None

    def test_min_chip_budget_unknowable_chip_skips_admission(
        self, mesh42, monkeypatch
    ):
        monkeypatch.delenv(kmem.HBM_BUDGET_ENV, raising=False)
        devices = list(mesh42.devices.flat)
        frees = {d.id: 10**9 for d in devices}
        frees[devices[5].id] = None  # one chip cannot report
        monkeypatch.setattr(
            kmem, "hbm_budget", lambda device=None: frees[device.id]
        )
        assert kmem.min_chip_budget(mesh42) == (None, None)

    def test_bcd_mesh_plan_within_2x_of_memory_analysis(self, rng, mesh42, monkeypatch):
        """Acceptance bar: analytic per-chip bytes for the (data=4,
        model=2) sharded BCD solve within 2x of the compiled SPMD module's
        own ``memory_analysis()`` on the forced-8-device CPU backend."""
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1G")
        a = rng.normal(size=(512, 256)).astype(np.float32)
        b = rng.normal(size=(512, 8)).astype(np.float32)
        est = BlockLeastSquaresEstimator(64, num_iter=2, lam=0.5, mesh=mesh42)
        est.fit(a, b)
        rep = est.last_fit_report
        assert rep.chosen == "fused[mesh 4x2]"
        plan = rep.plans["fused[mesh 4x2]"]
        assert plan.analyzed and plan.mesh_axes == {"data": 4, "model": 2}
        truth = plan.reported
        analytic_static = plan.argument_bytes + plan.output_bytes
        truth_static = truth["argument"] + truth["output"]
        assert truth_static / 2 <= analytic_static <= truth_static * 2
        # The charged temp never under-admits vs XLA's own number.
        assert plan.temp_bytes >= truth["temp"]


class TestMeshLadder:
    """The mesh degradation ladder: full (data, model) mesh -> model-axis-
    collapsed mesh -> single-device ladder, driven by a shrinking per-chip
    ``KEYSTONE_HBM_BUDGET`` — with every tier producing identical
    predictions (the acceptance bar)."""

    # Tall-skinny: the row-sharded design matrix/residual dominate the
    # per-chip footprint, so collapsing the model axis (data 4 -> 8)
    # strictly shrinks each chip's share and the tier totals decrease
    # monotonically down the ladder.
    N, D, K = 2048, 256, 8

    def _problem(self, rng):
        a = rng.normal(size=(self.N, self.D)).astype(np.float32)
        b = rng.normal(size=(self.N, self.K)).astype(np.float32)
        return a, b

    def _fit(self, a, b, mesh):
        est = BlockLeastSquaresEstimator(64, num_iter=2, lam=0.5, mesh=mesh)
        model = est.fit(a, b)
        return est, np.asarray(model(jnp.asarray(a)))

    def test_budget_walks_full_mesh_reduced_mesh_single_device(
        self, rng, mesh42, monkeypatch
    ):
        a, b = self._problem(rng)

        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1G")
        est, p_full = self._fit(a, b, mesh42)
        rep = est.last_fit_report
        assert rep.chosen == "fused[mesh 4x2]"
        assert rep.mesh_shape == {"data": 4, "model": 2}
        assert list(rep.plans) == ["fused[mesh 4x2]"]  # lazy planning
        t_full = rep.plans["fused[mesh 4x2]"].total_bytes

        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, str(t_full - 1))
        est, p_red = self._fit(a, b, mesh42)
        rep = est.last_fit_report
        assert rep.chosen == "fused[mesh 8x1]"
        assert rep.mesh_shape == {"data": 8, "model": 1}
        assert rep.denials == ["fused[mesh 4x2]"]
        t_red = rep.plans["fused[mesh 8x1]"].total_bytes
        assert t_red < t_full  # collapsing the model axis shrinks per-chip

        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, str(t_red - 1))
        est, p_single = self._fit(a, b, mesh42)
        rep = est.last_fit_report
        assert rep.chosen.startswith("single_device/")
        assert rep.mesh_shape is None
        assert rep.denials[:2] == ["fused[mesh 4x2]", "fused[mesh 8x1]"]

        np.testing.assert_allclose(p_full, p_red, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(p_full, p_single, rtol=1e-5, atol=1e-5)

    def test_runtime_oom_on_mesh_tier_steps_down(self, rng, mesh42, monkeypatch):
        monkeypatch.delenv(kmem.HBM_BUDGET_ENV, raising=False)
        a, b = self._problem(rng)
        _, p_clean = self._fit(a, b, mesh42)
        before = counters.get("solver_oom_retry")
        with oom_faults(block_mod, "_execute_fused_bcd_mesh", failures=1):
            est, p_retry = self._fit(a, b, mesh42)
        rep = est.last_fit_report
        assert rep.oom_retries == ["fused[mesh 4x2]"]
        assert rep.chosen == "fused[mesh 8x1]"  # one tier down, not the floor
        assert rep.mesh_shape == {"data": 8, "model": 1}
        assert counters.get("solver_oom_retry") == before + 1
        np.testing.assert_allclose(p_clean, p_retry, rtol=1e-5, atol=1e-5)

    def test_bwls_mesh_ladder_steps_down(self, rng, mesh42, monkeypatch):
        n, d, c = 512, 128, 8
        cls = rng.integers(0, c, n)
        x = (rng.normal(size=(n, d)) + 0.1 * cls[:, None]).astype(np.float32)
        y = (2.0 * np.eye(c)[cls] - 1.0).astype(np.float32)

        def fit():
            est = BlockWeightedLeastSquaresEstimator(
                32, num_iter=1, lam=0.1, mixture_weight=0.5, mesh=mesh42
            )
            model = est.fit(x, y)
            return est, np.asarray(model(jnp.asarray(x)))

        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1G")
        est, p_full = fit()
        rep = est.last_fit_report
        assert rep.chosen == "fused[mesh 4x2]"
        t_full = rep.plans["fused[mesh 4x2]"].total_bytes

        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, str(t_full - 1))
        est, p_red = fit()
        rep = est.last_fit_report
        assert rep.chosen == "fused[mesh 8x1]"
        assert rep.denials == ["fused[mesh 4x2]"]
        assert rep.mesh_shape == {"data": 8, "model": 1}
        np.testing.assert_allclose(p_full, p_red, rtol=2e-4, atol=2e-4)
