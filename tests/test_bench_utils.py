"""Unit tests for bench.py's measurement scaffolding (the parts that guard
the round artifact — no TPU required), and the bench regression
observatory (tools/bench_diff.py) exercised over the checked-in
BENCH_r01–r05 round records so the observatory itself runs in tier-1
without hardware."""

import json
import os
import sys

import pytest

import bench

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import bench_diff  # noqa: E402  (tools/bench_diff.py)


def test_error_record_shape():
    rec = bench._error_record(ValueError("x" * 500))
    assert rec["error"].startswith("ValueError: ")
    assert len(rec["error"]) <= 300


def test_guarded_returns_error_record_not_exception():
    def boom(_rng):
        raise RuntimeError("chip fell over")

    rec = bench._guarded(boom, None)
    assert rec == {"error": "RuntimeError: chip fell over"}

    def ok(_rng):
        return {"v": 1}

    assert bench._guarded(ok, None) == {"v": 1}


def test_timed_chain_auto_retries_only_noise_floor(monkeypatch):
    calls = []

    def fake_timed_chain(fn, arg, chain_len, repeats=3):
        calls.append(chain_len)
        if chain_len < 64:
            raise bench.NoiseFloorError("too short")
        return 0.001

    monkeypatch.setattr(bench, "timed_chain", fake_timed_chain)
    assert bench.timed_chain_auto(None, None, chain_len=16) == 0.001
    assert calls == [16, 32, 64]  # doubled until the floor cleared


def test_timed_chain_auto_propagates_real_failures(monkeypatch):
    def fake_timed_chain(fn, arg, chain_len, repeats=3):
        raise RuntimeError("XlaRuntimeError: RESOURCE_EXHAUSTED")

    monkeypatch.setattr(bench, "timed_chain", fake_timed_chain)
    try:
        bench.timed_chain_auto(None, None, chain_len=16)
    except RuntimeError as e:
        assert "RESOURCE_EXHAUSTED" in str(e)
    else:
        raise AssertionError("real failure was swallowed")


def test_solve_at_scale_records_fit_report_per_attempt(monkeypatch):
    """Regression for the PR 7 probe fix (BENCH_r05 showed raw-OOM rows
    with no ladder evidence): every probed shape — failures INCLUDED —
    must carry the estimator's own ``last_fit_report`` record in the
    emitted JSON, and (ISSUE 9) the searched ``placement`` table rides in
    it.  Every probe is made to FAIL (injected post-fit OOM, the report
    already populated — the shape a real runtime OOM leaves) so the
    all-attempts-failed worst case is what gets audited."""
    import numpy as np

    class FailingEstimator(bench.BlockLeastSquaresEstimator):
        def fit(self, *args, **kwargs):
            super().fit(*args, **kwargs)
            raise RuntimeError("RESOURCE_EXHAUSTED: injected probe failure")

    monkeypatch.setattr(bench, "BlockLeastSquaresEstimator", FailingEstimator)
    monkeypatch.setattr(
        bench, "_bench_bwls_at_scale", lambda rng, shapes=None, bs=4096: {
            "error": "stubbed", "attempts": [],
        },
    )
    out = bench.bench_solve_at_scale(
        np.random.default_rng(0), shapes=[(256, 128), (128, 128)], bs=64
    )
    assert out["error"] == "no probed shape fit"
    assert len(out["attempts"]) == 2
    for att in out["attempts"]:
        rep = att["solver"]
        assert rep is not None, att  # the ladder's evidence, per attempt
        assert "RESOURCE_EXHAUSTED" in att["error"]
        assert rep["placement"] is not None  # the searched plan (ISSUE 9)
        assert rep["placement"]["candidates"]
        assert rep["placement"]["ranking"]
    json.dumps(out)  # the whole probe record must stay JSON-able


# -- the regression observatory (tools/bench_diff.py, ISSUE 11) ---------------


def _round(n: int) -> str:
    return os.path.join(_REPO, f"BENCH_r{n:02d}.json")


def test_bench_diff_r04_vs_r05_emits_machine_verdict(capsys):
    """The ISSUE 11 acceptance pair: r05's driver artifact was truncated
    (``parsed: null``), so the diff must emit an INCOMPARABLE verdict as
    machine-readable JSON — naming the problem — instead of crashing."""
    rc = bench_diff.main([_round(4), _round(5)])
    assert rc == 2
    first_line = capsys.readouterr().out.splitlines()[0]
    record = json.loads(first_line)
    assert record["metric"] == "bench_diff"
    assert record["verdict"] == "incomparable"
    assert record["compared"] == 0
    assert "null" in record["problems"]["cand"]


def test_bench_diff_r03_vs_r04_is_comparable_and_clean(capsys):
    """r03 -> r04 is the real improvement round (featurize 497k -> 1.19M
    images/sec/chip): comparable, no regressions, improvements named."""
    rc = bench_diff.main([_round(3), _round(4)])
    assert rc == 0
    record = json.loads(capsys.readouterr().out.splitlines()[0])
    assert record["verdict"] == "ok"
    assert record["compared"] >= 3
    assert record["regressions"] == []
    improved = {r["metric"] for r in record["improvements"]}
    assert "value" in improved


def test_bench_diff_every_checked_in_pair_yields_a_verdict():
    """The observatory over the whole round history: every consecutive
    pair produces a structurally-valid verdict (r05's truncated record
    degrades to incomparable, never a crash)."""
    rounds = bench_diff.list_rounds(_REPO)
    assert [n for n, _ in rounds] == [1, 2, 3, 4, 5]
    for (n_a, p_a), (n_b, p_b) in zip(rounds, rounds[1:]):
        record = bench_diff.diff_files(p_a, p_b)
        assert record["verdict"] in ("ok", "regressed", "incomparable"), (
            n_a, n_b, record,
        )
        json.dumps(record)  # machine-readable throughout
        if n_b == 5:
            assert record["verdict"] == "incomparable"
        else:
            assert record["compared"] >= 1, (n_a, n_b)


def test_bench_diff_detects_regression_and_direction():
    base = {
        "metric": "m", "value": 100.0, "solve_seconds": 1.0,
        "extra_metrics": {"serving": {"mnist_fft": {
            "qps": 50.0, "p99_latency_ms": 10.0,
        }}},
    }
    # value collapsed far past its 15% threshold -> regressed
    worse = json.loads(json.dumps(base))
    worse["value"] = 50.0
    out = bench_diff.compare(base, worse)
    assert out["verdict"] == "regressed"
    assert [r["metric"] for r in out["regressions"]] == ["value"]
    # lower-is-better: p99 doubling regresses, halving improves
    slower = json.loads(json.dumps(base))
    slower["extra_metrics"]["serving"]["mnist_fft"]["p99_latency_ms"] = 30.0
    out = bench_diff.compare(base, slower)
    assert any(
        r["metric"].endswith("p99_latency_ms") for r in out["regressions"]
    )
    faster = json.loads(json.dumps(base))
    faster["extra_metrics"]["serving"]["mnist_fft"]["p99_latency_ms"] = 2.0
    out = bench_diff.compare(base, faster)
    assert out["verdict"] == "ok"
    assert any(
        r["metric"].endswith("p99_latency_ms") for r in out["improvements"]
    )


def test_bench_diff_metric_overrides():
    metrics = bench_diff.parse_metric_overrides(
        ["value=0.01", "custom.path=0.2:lower"]
    )
    table = {p: (d, t) for p, d, t in metrics}
    assert table["value"] == ("higher", 0.01)
    assert table["custom.path"] == ("lower", 0.2)
    with pytest.raises(ValueError):
        bench_diff.parse_metric_overrides(["nonsense"])
    with pytest.raises(ValueError):
        bench_diff.parse_metric_overrides(["a=0.1:sideways"])


def test_latest_usable_round_skips_truncated_r05():
    found = bench_diff.latest_usable_round(_REPO)
    assert found is not None
    num, path, record = found
    assert num == 4  # r05 is parsed:null — the newest USABLE round is r04
    assert record["metric"] == "random_patch_cifar_featurize"


def test_bench_self_compare_section(tmp_path):
    """bench.py's in-round observatory: the record self-compares against
    the newest usable prior round and embeds the verdict."""
    base = {"metric": "m", "value": 100.0, "unit": "u"}
    with open(tmp_path / "BENCH_r01.json", "w") as f:
        json.dump({"parsed": base}, f)
    with open(tmp_path / "BENCH_r02.json", "w") as f:
        json.dump({"parsed": None}, f)  # truncated newest -> falls back
    out = bench.bench_self_diff({"metric": "m", "value": 95.0}, str(tmp_path))
    assert out["baseline"] == "BENCH_r01.json"
    assert out["baseline_round"] == 1
    assert out["verdict"] == "ok"
    regressed = bench.bench_self_diff(
        {"metric": "m", "value": 10.0}, str(tmp_path)
    )
    assert regressed["verdict"] == "regressed"
    # no prior rounds at all -> an honest note, not a crash
    empty = bench.bench_self_diff({"metric": "m"}, str(tmp_path / "void"))
    assert "note" in empty


def test_solve_at_scale_success_records_searched_plan(monkeypatch):
    """The landing shape's record carries the searched placement with the
    chosen plan and its predicted-vs-actual cost."""
    import numpy as np

    monkeypatch.setattr(
        bench, "_bench_bwls_at_scale", lambda rng, shapes=None, bs=4096: {
            "error": "stubbed", "attempts": [],
        },
    )
    out = bench.bench_solve_at_scale(
        np.random.default_rng(0), shapes=[(256, 128)], bs=64
    )
    assert "error" not in out
    rep = out["solver"]
    assert rep is not None
    placement = rep["placement"]
    assert placement is not None
    assert placement["chosen"] == rep["chosen_tier"]
    assert placement["measured_seconds"] is not None
    json.dumps(out)


def test_decode_path_breakdown_records_all_three_paths():
    """ISSUE 13 acceptance: the jpeg_decode by-path ledger (CPU tier-1
    scale) records host pool, device decode, and warm device-snapshot DMA
    — with the device path inside golden tolerance of the host decoder
    and the warm device-snapshot epoch doing ZERO host-side decode."""
    import numpy as np

    out = bench._decode_path_breakdown(
        np.random.default_rng(0), batch=6, n_images=12, size=64
    )
    # ISSUE 19 added a fourth leg: the raw entropy-decode A/B (python vs
    # native scan loop) over the same corpus.
    assert set(out) == {
        "host_pool", "device", "device_snapshot_warm", "entropy_native"
    }
    for path in ("host_pool", "device", "device_snapshot_warm"):
        rec = out[path]
        assert rec["images_per_sec"] > 0, path
        assert rec["overlap_efficiency"] > 0, path
    dev = out["device"]
    assert dev["entropy_decoded"] == 12 and dev["fallbacks"] == 0
    assert dev["within_golden_tolerance"], dev["golden_max_abs_vs_host"]
    warm = out["device_snapshot_warm"]
    assert warm["zero_host_decode"]
    assert warm["dma_bytes"] > 0
    ent = out["entropy_native"]
    assert ent["images"] == 12
    assert ent["python_images_per_sec"] > 0
    assert ent["backend_live"] in ("native", "python")
    if "native_images_per_sec" in ent:
        # ISSUE 19 acceptance bar: native entropy decode >= 3x the Python
        # bit-reader over the bench corpus (observed ~30x).
        assert ent["native_images_per_sec"] > 0
        assert ent["speedup"] >= 3.0, ent
    json.dumps(out)
