"""Unit tests for bench.py's measurement scaffolding (the parts that guard
the round artifact — no TPU required)."""

import json

import bench


def test_error_record_shape():
    rec = bench._error_record(ValueError("x" * 500))
    assert rec["error"].startswith("ValueError: ")
    assert len(rec["error"]) <= 300


def test_guarded_returns_error_record_not_exception():
    def boom(_rng):
        raise RuntimeError("chip fell over")

    rec = bench._guarded(boom, None)
    assert rec == {"error": "RuntimeError: chip fell over"}

    def ok(_rng):
        return {"v": 1}

    assert bench._guarded(ok, None) == {"v": 1}


def test_timed_chain_auto_retries_only_noise_floor(monkeypatch):
    calls = []

    def fake_timed_chain(fn, arg, chain_len, repeats=3):
        calls.append(chain_len)
        if chain_len < 64:
            raise bench.NoiseFloorError("too short")
        return 0.001

    monkeypatch.setattr(bench, "timed_chain", fake_timed_chain)
    assert bench.timed_chain_auto(None, None, chain_len=16) == 0.001
    assert calls == [16, 32, 64]  # doubled until the floor cleared


def test_timed_chain_auto_propagates_real_failures(monkeypatch):
    def fake_timed_chain(fn, arg, chain_len, repeats=3):
        raise RuntimeError("XlaRuntimeError: RESOURCE_EXHAUSTED")

    monkeypatch.setattr(bench, "timed_chain", fake_timed_chain)
    try:
        bench.timed_chain_auto(None, None, chain_len=16)
    except RuntimeError as e:
        assert "RESOURCE_EXHAUSTED" in str(e)
    else:
        raise AssertionError("real failure was swallowed")


def test_solve_at_scale_records_fit_report_per_attempt(monkeypatch):
    """Regression for the PR 7 probe fix (BENCH_r05 showed raw-OOM rows
    with no ladder evidence): every probed shape — failures INCLUDED —
    must carry the estimator's own ``last_fit_report`` record in the
    emitted JSON, and (ISSUE 9) the searched ``placement`` table rides in
    it.  Every probe is made to FAIL (injected post-fit OOM, the report
    already populated — the shape a real runtime OOM leaves) so the
    all-attempts-failed worst case is what gets audited."""
    import numpy as np

    class FailingEstimator(bench.BlockLeastSquaresEstimator):
        def fit(self, *args, **kwargs):
            super().fit(*args, **kwargs)
            raise RuntimeError("RESOURCE_EXHAUSTED: injected probe failure")

    monkeypatch.setattr(bench, "BlockLeastSquaresEstimator", FailingEstimator)
    monkeypatch.setattr(
        bench, "_bench_bwls_at_scale", lambda rng, shapes=None, bs=4096: {
            "error": "stubbed", "attempts": [],
        },
    )
    out = bench.bench_solve_at_scale(
        np.random.default_rng(0), shapes=[(256, 128), (128, 128)], bs=64
    )
    assert out["error"] == "no probed shape fit"
    assert len(out["attempts"]) == 2
    for att in out["attempts"]:
        rep = att["solver"]
        assert rep is not None, att  # the ladder's evidence, per attempt
        assert "RESOURCE_EXHAUSTED" in att["error"]
        assert rep["placement"] is not None  # the searched plan (ISSUE 9)
        assert rep["placement"]["candidates"]
        assert rep["placement"]["ranking"]
    json.dumps(out)  # the whole probe record must stay JSON-able


def test_solve_at_scale_success_records_searched_plan(monkeypatch):
    """The landing shape's record carries the searched placement with the
    chosen plan and its predicted-vs-actual cost."""
    import numpy as np

    monkeypatch.setattr(
        bench, "_bench_bwls_at_scale", lambda rng, shapes=None, bs=4096: {
            "error": "stubbed", "attempts": [],
        },
    )
    out = bench.bench_solve_at_scale(
        np.random.default_rng(0), shapes=[(256, 128)], bs=64
    )
    assert "error" not in out
    rep = out["solver"]
    assert rep is not None
    placement = rep["placement"]
    assert placement is not None
    assert placement["chosen"] == rep["chosen_tier"]
    assert placement["measured_seconds"] is not None
    json.dumps(out)
