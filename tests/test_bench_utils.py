"""Unit tests for bench.py's measurement scaffolding (the parts that guard
the round artifact — no TPU required)."""

import bench


def test_error_record_shape():
    rec = bench._error_record(ValueError("x" * 500))
    assert rec["error"].startswith("ValueError: ")
    assert len(rec["error"]) <= 300


def test_guarded_returns_error_record_not_exception():
    def boom(_rng):
        raise RuntimeError("chip fell over")

    rec = bench._guarded(boom, None)
    assert rec == {"error": "RuntimeError: chip fell over"}

    def ok(_rng):
        return {"v": 1}

    assert bench._guarded(ok, None) == {"v": 1}


def test_timed_chain_auto_retries_only_noise_floor(monkeypatch):
    calls = []

    def fake_timed_chain(fn, arg, chain_len, repeats=3):
        calls.append(chain_len)
        if chain_len < 64:
            raise bench.NoiseFloorError("too short")
        return 0.001

    monkeypatch.setattr(bench, "timed_chain", fake_timed_chain)
    assert bench.timed_chain_auto(None, None, chain_len=16) == 0.001
    assert calls == [16, 32, 64]  # doubled until the floor cleared


def test_timed_chain_auto_propagates_real_failures(monkeypatch):
    def fake_timed_chain(fn, arg, chain_len, repeats=3):
        raise RuntimeError("XlaRuntimeError: RESOURCE_EXHAUSTED")

    monkeypatch.setattr(bench, "timed_chain", fake_timed_chain)
    try:
        bench.timed_chain_auto(None, None, chain_len=16)
    except RuntimeError as e:
        assert "RESOURCE_EXHAUSTED" in str(e)
    else:
        raise AssertionError("real failure was swallowed")
