"""Core DSL tests (reference behavior: pipelines/Transformer.scala,
PipelineSuite-style composition checks)."""

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu import (
    FunctionTransformer,
    Identity,
    Pipeline,
    transformer,
)
from keystone_tpu.core.pipeline import FunctionEstimator


def test_transformer_call_and_item():
    t = transformer(lambda x: x * 2.0)
    batch = jnp.arange(6.0).reshape(3, 2)
    assert np.allclose(t(batch), batch * 2)
    assert np.allclose(t.apply_item(jnp.array([1.0, 2.0])), [2.0, 4.0])


def test_then_composition_and_flattening():
    a = transformer(lambda x: x + 1.0)
    b = transformer(lambda x: x * 3.0)
    c = transformer(lambda x: x - 2.0)
    p1 = (a >> b) >> c
    p2 = a >> (b >> c)
    assert len(p1.nodes) == 3 and len(p2.nodes) == 3
    x = jnp.ones((2, 2))
    assert np.allclose(p1(x), p2(x))
    assert np.allclose(p1(x), (1.0 + 1.0) * 3.0 - 2.0)


def test_pipeline_is_jittable_pytree():
    a = transformer(lambda x: x + 1.0)
    b = transformer(lambda x: x * 3.0)
    pipe = a >> b
    jitted = jax.jit(lambda p, x: p(x))
    out = jitted(pipe, jnp.ones((2, 2)))
    assert np.allclose(out, 6.0)


def test_then_estimator_closure_semantics():
    """thenEstimator fits on *transformed* data (Transformer.scala:37-44)."""
    pre = transformer(lambda x: x * 10.0)

    seen = {}

    def fit_fn(data):
        seen["data"] = np.asarray(data)
        mean = jnp.mean(data, axis=0)
        return transformer(lambda x: x - mean)

    est = FunctionEstimator(fit_fn)
    chained = pre.then_estimator(est)
    data = jnp.array([[1.0, 2.0], [3.0, 4.0]])
    fitted = chained.fit(data)
    assert np.allclose(seen["data"], np.asarray(data) * 10.0)
    out = fitted(data)
    assert np.allclose(out, data * 10.0 - np.asarray(data).mean(0) * 10.0)


def test_then_label_estimator():
    from keystone_tpu.core.pipeline import LabelEstimator

    class Thresh(LabelEstimator):
        def fit(self, data, labels):
            shift = jnp.mean(labels)
            return transformer(lambda x: x + shift)

    pre = transformer(lambda x: x * 2.0)
    fitted = pre.then_label_estimator(Thresh()).fit(
        jnp.ones((3, 2)), jnp.array([1.0, 2.0, 3.0])
    )
    assert np.allclose(fitted(jnp.ones((1, 2))), 2.0 + 2.0)


def test_identity_and_repr():
    i = Identity()
    x = jnp.ones((2, 3))
    assert i(x) is x
    assert "Identity" in repr(i)
    p = Pipeline([i, FunctionTransformer(lambda y: y, name="f")])
    assert "f" in repr(p)


def test_cacher_sharding_path(mesh8):
    """Cacher with an explicit sharding commits the value to the mesh layout
    (the one DSL node that touches device placement, Cacher.scala:13-23
    analog) and is the identity under trace."""
    from jax.sharding import NamedSharding, PartitionSpec

    from keystone_tpu import Cacher

    x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    sharding = NamedSharding(mesh8, PartitionSpec("data", None))
    cached = Cacher(name="feats", sharding=sharding)(x)
    assert cached.sharding.is_equivalent_to(sharding, x.ndim)
    np.testing.assert_array_equal(np.asarray(cached), np.asarray(x))
    # pipeline composition: downstream nodes see the sharded value
    pipe = Pipeline([Cacher(sharding=sharding), FunctionTransformer(lambda y: y + 1.0)])
    out = pipe(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x) + 1.0)
    # under jit the node must be a no-op (XLA owns buffers)
    jitted = jax.jit(lambda v: Cacher(sharding=sharding)(v) * 2.0)
    np.testing.assert_array_equal(np.asarray(jitted(x)), np.asarray(x) * 2.0)
