"""Test harness: distributed-without-a-cluster.

The reference boots a local[k] SparkContext per suite
(reference src/test/scala/pipelines/LocalSparkContext.scala:9-43); here the
analog is a virtual 8-device CPU platform so every mesh/collective path is
exercised without TPU hardware.  Must set flags before jax initializes.
"""

import os
import tempfile

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# Hermetic placement search: the plan-outcome log (core.autoshard) defaults
# to ~/.keystone_plans.jsonl and TRAINS the cost model across processes — a
# suite run must neither pollute the operator's log nor inherit a trained
# ranking that deviates from the hand ladder (the bit-identical baselines
# several suites pin).  Every test process gets a fresh, empty log.
os.environ["KEYSTONE_PLAN_LOG"] = os.path.join(
    tempfile.mkdtemp(prefix="keystone_plans_"), "plans.jsonl"
)

import jax  # noqa: E402

# Some environments pin jax_platforms from a sitecustomize hook (e.g. a TPU
# plugin registering itself and setting "axon,cpu"); the env var alone is not
# enough — force the CPU platform before any backend initializes.
jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402
import pytest  # noqa: E402

from keystone_tpu.parallel.mesh import make_mesh  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    """8-way data-parallel mesh (the local[8] analog)."""
    return make_mesh(data=8, model=1)


@pytest.fixture(scope="session")
def mesh42(devices):
    """4x2 data-by-model mesh for mixed-parallel tests."""
    return make_mesh(data=4, model=2)


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running end-to-end pipeline test")
    config.addinivalue_line(
        "markers",
        "serve: serving-subsystem tests (core.serve) — tier-1 runs the "
        "deterministic set; the concurrent-client soak is also marked "
        "slow and runs under -m slow",
    )
    config.addinivalue_line(
        "markers",
        "chaos: full seeded fault-schedule suite (tests/chaos.py) — the "
        "tier-1 run covers a small schedule; select the full set with "
        "-m chaos (full-schedule tests are also marked slow so the tier-1 "
        "'-m not slow' filter excludes them)",
    )
    config.addinivalue_line(
        "markers",
        "dist: multi-process jax.distributed tests — REAL subprocesses on "
        "auto-picked ports; auto-skipped where spawn or port binding is "
        "unavailable (parallel.distributed.spawn_available)",
    )
    config.addinivalue_line(
        "markers",
        "native_entropy: tests that pin the NATIVE entropy-decode backend "
        "(ops.native_entropy) — auto-skipped where the toolchain cannot "
        "build/load the library, so tier-1 stays green on minimal hosts "
        "(the Python-pass and degradation tests carry no marker and always "
        "run)",
    )


def pytest_collection_modifyitems(config, items):
    """dist-marked tests need subprocess spawn + a bindable loopback port;
    on hosts without either they skip with the reason named, they do not
    fail."""
    dist_items = [it for it in items if it.get_closest_marker("dist")]
    if dist_items:
        from keystone_tpu.parallel.distributed import spawn_available

        if not spawn_available():
            skip = pytest.mark.skip(
                reason="multi-process unavailable (no spawn or no bindable "
                "port; see KEYSTONE_DIST_DISABLE)"
            )
            for it in dist_items:
                it.add_marker(skip)
    native_items = [
        it for it in items if it.get_closest_marker("native_entropy")
    ]
    if native_items:
        from keystone_tpu.ops import native_entropy

        if not native_entropy.available():
            skip = pytest.mark.skip(
                reason="native entropy decoder unbuildable/unloadable "
                "(no g++? see KEYSTONE_NATIVE_ENTROPY)"
            )
            for it in native_items:
                it.add_marker(skip)
