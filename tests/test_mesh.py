"""Degradation-ladder substrate coverage (ISSUE 16): ``reduced_mesh`` and
the mesh enumerators over the DEGENERATE survivor shapes the elastic
re-anchor walks — 1xN, Nx1, a single device, odd/prime device counts.
The happy-path 8-device shapes were already exercised by the solver and
autoshard suites; device loss hands these helpers whatever is left."""

import pytest

from keystone_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    enumerate_mesh_shapes,
    enumerate_meshes,
    make_mesh,
    mesh_desc,
    reduced_mesh,
)


def _survivors(devices, n):
    assert len(devices) >= n, f"need {n} of the 8 virtual devices"
    return devices[:n]


class TestReducedMesh:
    def test_collapses_model_axis_onto_data(self, devices):
        mesh = make_mesh(data=2, model=2, devices=_survivors(devices, 4))
        red = reduced_mesh(mesh)
        assert mesh_desc(red) == "4x1"
        # the SAME devices, every one of them — a ladder step trades
        # layout, never capacity
        assert list(red.devices.flat) == list(mesh.devices.flat)

    def test_pure_data_mesh_has_no_rung_below(self, devices):
        for n in (1, 2, 3, 8):
            mesh = make_mesh(data=n, model=1, devices=_survivors(devices, n))
            assert reduced_mesh(mesh) is None

    def test_model_only_survivor_1xn(self, devices):
        """1xN (a data-collapsed survivor that is ALL model axis) still
        reduces to pure data-parallel over the same devices."""
        mesh = make_mesh(data=1, model=4, devices=_survivors(devices, 4))
        red = reduced_mesh(mesh)
        assert mesh_desc(red) == "4x1"
        assert list(red.devices.flat) == list(mesh.devices.flat)

    def test_two_device_model_pair(self, devices):
        mesh = make_mesh(data=1, model=2, devices=_survivors(devices, 2))
        assert mesh_desc(reduced_mesh(mesh)) == "2x1"

    def test_single_device_mesh_is_the_floor(self, devices):
        mesh = make_mesh(data=1, model=1, devices=_survivors(devices, 1))
        assert reduced_mesh(mesh) is None


class TestEnumerateMeshShapes:
    def test_single_device(self):
        assert enumerate_mesh_shapes(1) == [(1, 1)]

    @pytest.mark.parametrize("n", (3, 5, 7))
    def test_prime_counts_yield_the_two_degenerates(self, n):
        assert enumerate_mesh_shapes(n) == [(n, 1), (1, n)]

    def test_odd_composite_count(self):
        # 9 survivors of a 16-device pod: every divisor pair, data-major
        assert enumerate_mesh_shapes(9) == [(9, 1), (3, 3), (1, 9)]

    def test_data_major_descending_and_exhaustive(self):
        shapes = enumerate_mesh_shapes(6)
        assert shapes == [(6, 1), (3, 2), (2, 3), (1, 6)]
        assert all(d * m == 6 for d, m in shapes)
        datas = [d for d, _ in shapes]
        assert datas == sorted(datas, reverse=True)

    def test_zero_devices_refused(self):
        with pytest.raises(ValueError, match=">= 1 device"):
            enumerate_mesh_shapes(0)


class TestEnumerateMeshes:
    @pytest.mark.parametrize("n", (1, 3, 5, 7))
    def test_degenerate_survivor_counts_materialize(self, devices, n):
        """Odd/prime survivor sets — the shapes a device loss actually
        leaves behind — must enumerate real, usable meshes."""
        survivors = _survivors(devices, n)
        meshes = enumerate_meshes(survivors)
        assert [
            (m.shape[DATA_AXIS], m.shape[MODEL_AXIS]) for m in meshes
        ] == enumerate_mesh_shapes(n)
        for m in meshes:
            assert list(m.devices.flat) == list(survivors)

    def test_deterministic_over_one_device_set(self, devices):
        survivors = _survivors(devices, 5)
        a = enumerate_meshes(survivors)
        b = enumerate_meshes(survivors)
        # memoized per device tuple: identical Mesh objects both times
        # (searched-plan determinism), but a fresh mutable list per call
        assert a == b
        assert a is not b

    def test_survivor_order_is_the_cache_key(self, devices):
        fwd = _survivors(devices, 2)
        rev = list(reversed(fwd))
        a = enumerate_meshes(fwd)
        b = enumerate_meshes(rev)
        assert list(a[0].devices.flat) == fwd
        assert list(b[0].devices.flat) == rev


def test_ladder_walk_over_survivors(devices):
    """The exact walk MeshEngineFactory takes: any survivor mesh steps
    full -> reduced (same devices) -> None within two rungs; the floor is
    always reachable."""
    for n, model in ((4, 2), (2, 2), (3, 1), (1, 1)):
        if model > 1:
            mesh = make_mesh(
                data=n // model, model=model, devices=_survivors(devices, n)
            )
        else:
            mesh = make_mesh(data=n, model=1, devices=_survivors(devices, n))
        rungs = 0
        while mesh is not None:
            mesh = reduced_mesh(mesh)
            rungs += 1
            assert rungs <= 2, "ladder failed to reach the floor"
