"""Shape-routed front-end tests (core.frontend): routing, typed
backpressure, cross-engine admission, and the closed-loop engine
add/retire — including the deterministic shape-mix replay that forces one
retire and one warm add with zero request loss (ISSUE 12 satellite)."""

import json
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.core import frontend, telemetry, trace
from keystone_tpu.core import serve as kserve
from keystone_tpu.core.pipeline import FunctionTransformer
from keystone_tpu.core.resilience import counters

pytestmark = pytest.mark.serve


class FakeClock:
    """Injectable monotonic clock: the mix window / retire aging advance
    only when the test says so — the replay is fully deterministic."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _make_engine(shape, dtype=np.dtype(np.float32), label="frontend"):
    """Deterministic per-shape toy engine (the fusion-invariant mul+max
    idiom from test_serve, seeded by the shape so every width gets its own
    stable weights)."""
    shape = tuple(int(d) for d in shape)
    rng = np.random.default_rng(7000 + int(np.prod(shape)))
    w = jnp.asarray(rng.normal(size=shape).astype(np.float32))
    b = jnp.asarray(rng.normal(size=shape).astype(np.float32))
    pipe = FunctionTransformer(lambda x: jnp.maximum(x * w, b), name="toy")
    cfg = kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0)
    return kserve.ServingEngine(
        pipe,
        np.zeros(shape, np.float32),
        config=cfg,
        label=frontend.shape_label(label, shape),
    )


def _reqs(rng, n, shape):
    return rng.normal(size=(n, *shape)).astype(np.float32)


def _router(clock=None, factory=None, **cfg_kw):
    cfg = frontend.RouterConfig(
        warm_threshold=cfg_kw.pop("warm_threshold", 3),
        mix_window_s=cfg_kw.pop("mix_window_s", 5.0),
        retire_after_s=cfg_kw.pop("retire_after_s", 30.0),
        **cfg_kw,
    )
    return frontend.ShapeRouter(
        factory, label="testrouter", config=cfg,
        clock=clock or time.monotonic,
    )


class TestRouting:
    def test_routes_by_shape_bit_equal(self, rng):
        e16, e8 = _make_engine((16,)), _make_engine((8,))
        with _router() as router:
            router.add_engine(e16)
            router.add_engine(e8)
            r16, r8 = _reqs(rng, 9, (16,)), _reqs(rng, 7, (8,))
            futs = [router.submit(r) for r in r16]
            futs8 = [router.submit(r) for r in r8]
            a16 = np.stack([f.result(30.0) for f in futs])
            a8 = np.stack([f.result(30.0) for f in futs8])
            assert np.array_equal(a16, e16.offline(r16))
            assert np.array_equal(a8, e8.offline(r8))
            assert router.stats.routes == 16
            assert router.stats.misses == 0
            rec = router.record()
            json.dumps(rec)
            assert set(rec["engines"]) == {"16", "8"}
        # route overhead is a registry histogram (the bench regresses on it)
        snap = trace.metrics.snapshot()
        assert snap["histograms"]["router_route_overhead_us"]["count"] >= 16

    def test_duplicate_shape_rejected(self):
        with _router() as router:
            router.add_engine(_make_engine((16,)))
            with pytest.raises(ValueError, match="already has a live engine"):
                router.add_engine(_make_engine((16,)))

    def test_no_factory_unserved_shape_is_typed(self, rng):
        with _router() as router:
            router.add_engine(_make_engine((16,)))
            with pytest.raises(frontend.NoRouteForShape):
                router.submit(np.zeros(5, np.float32))
            assert router.stats.no_route == 1

    def test_cold_shape_gets_retry_later_backpressure(self, rng):
        with _router(factory=_make_engine, warm_threshold=5) as router:
            router.add_engine(_make_engine((16,)))
            with pytest.raises(frontend.RetryLater) as ei:
                router.submit(np.zeros(8, np.float32))
            assert ei.value.retry_after_s > 0
            assert router.stats.rejected == 1
            assert router.stats.misses == 1

    def test_closed_router_is_typed(self):
        router = _router()
        router.add_engine(_make_engine((16,)))
        router.close()
        with pytest.raises(kserve.ServingUnavailable):
            router.submit(np.zeros(16, np.float32))

    def test_malformed_payload_propagates_typed(self, rng):
        with _router() as router:
            router.add_engine(_make_engine((16,)))
            bad = _reqs(rng, 1, (16,))[0]
            bad[3] = np.nan
            with pytest.raises(kserve.MalformedRequest):
                router.submit(bad)


class TestClosedLoop:
    def test_shape_mix_replay_retire_and_warm_add_zero_loss(self, rng):
        """The ISSUE 12 acceptance replay: a deterministic shape-mix shift
        (traffic moves from width 16 to width 8) must trigger exactly one
        warm engine add and one engine retire, with the registry gauges
        proving both and EVERY submitted request resolving bit-equal —
        zero request loss across the swap."""
        clock = FakeClock()
        e16 = _make_engine((16,))
        router = _router(
            clock=clock, factory=_make_engine,
            warm_threshold=3, mix_window_s=5.0, retire_after_s=10.0,
        )
        retired_before = trace.metrics.get("router_engine_retired")
        try:
            router.add_engine(e16)
            # Phase 1: the old shape earns traffic.
            r16 = _reqs(rng, 8, (16,))
            futs16 = [router.submit(r) for r in r16]

            # Phase 2: the mix shifts — width-8 requests arrive.  Below
            # the warm threshold they answer typed backpressure; at the
            # threshold the router warms an engine and serves.
            r8 = _reqs(rng, 6, (8,))
            futs8 = []
            rejected = 0
            for r in r8:
                while True:
                    try:
                        futs8.append(router.submit(r))
                        break
                    except frontend.RetryLater:
                        rejected += 1
                        clock.advance(0.1)  # an honest client retries
            assert rejected >= 2  # the first warm_threshold-1 pushed back
            assert router.stats.warm_adds == 1
            assert set(router.engines()) == {(16,), (8,)}
            assert trace.metrics.gauge_value("router_engines") == 2

            # Phase 3: width 16 stops earning traffic; the sweep retires
            # it.  The outstanding width-16 futures were submitted BEFORE
            # the retire — drain-before-close means they all resolve.
            clock.advance(11.0)
            actions = router.adapt()
            assert actions["retired"] == [[16]]
            assert router.stats.retires == 1
            assert set(router.engines()) == {(8,)}
            assert trace.metrics.gauge_value("router_engines") == 1
            assert (
                trace.metrics.get("router_engine_retired")
                == retired_before + 1
            )
            # The retired shape's SLO tracker left the live surface; the
            # survivor's remains.
            slos = telemetry.slo_summaries()
            assert frontend.shape_label("frontend", (16,)) not in slos
            assert frontend.shape_label("frontend", (8,)) in slos

            # Zero loss: every future from both phases resolved bit-equal.
            a16 = np.stack([f.result(30.0) for f in futs16])
            a8 = np.stack([f.result(30.0) for f in futs8])
            assert np.array_equal(a16, e16.offline(r16))
            e8_label = frontend.shape_label("frontend", (8,))
            e8 = next(
                e.engine
                for e in router._engines.values()
                if e.engine.label == e8_label
            )
            assert np.array_equal(a8, e8.offline(r8))
            assert router.stats.routes == len(futs16) + len(futs8)
        finally:
            router.close()

    def test_retire_respects_min_engines_floor(self, rng):
        clock = FakeClock()
        router = _router(clock=clock, retire_after_s=1.0, min_engines=1)
        try:
            router.add_engine(_make_engine((16,)))
            clock.advance(100.0)
            assert router.adapt() == {"retired": []}
            assert set(router.engines()) == {(16,)}
        finally:
            router.close()

    def test_max_engines_evicts_idlest_for_hotter_shape(self, rng):
        clock = FakeClock()
        router = _router(
            clock=clock, factory=_make_engine, warm_threshold=1,
            mix_window_s=2.0, max_engines=1, min_engines=0,
        )
        try:
            router.add_engine(_make_engine((16,)))
            clock.advance(3.0)  # the resident engine goes idle
            fut = router.submit(np.ones(8, np.float32))
            fut.result(30.0)
            assert set(router.engines()) == {(8,)}
            assert router.stats.retires == 1
            assert router.stats.warm_adds == 1
        finally:
            router.close()

    def test_predict_absorbs_backpressure(self, rng):
        with _router(factory=_make_engine, warm_threshold=2) as router:
            req = _reqs(rng, 1, (8,))[0]
            out = router.predict(req, timeout=60.0)
            e8_label = frontend.shape_label("frontend", (8,))
            e8 = next(
                e.engine
                for e in router._engines.values()
                if e.engine.label == e8_label
            )
            assert np.array_equal(out, e8.offline(req[None])[0])
            assert router.stats.warm_adds == 1


class TestCrossAdmission:
    def test_denied_warm_add_is_counted_backpressure(self, rng, monkeypatch):
        """A warm add that would overrun the shared budget answers
        RetryLater (counted router_admission_denied); retiring the
        resident engine frees the headroom and the retry succeeds."""
        clock = FakeClock()
        router = _router(
            clock=clock, factory=_make_engine, warm_threshold=1,
            retire_after_s=5.0, min_engines=0,
        )
        # A budget that fits ONE width-8 engine but not the width-16
        # resident PLUS it makes the cross-engine sum the decider (probe
        # engine measures the real planned peak — same shapes, same plans
        # as the factory will build).
        probe = _make_engine((8,))
        need = router._engine_peak_bytes(probe)
        assert need > 0
        monkeypatch.setattr(
            frontend.kmem, "hbm_budget", lambda device=None: need + 16
        )
        before = counters.get("router_admission_denied")
        try:
            router.add_engine(_make_engine((16,)))
            with pytest.raises(frontend.RetryLater, match="no HBM headroom"):
                router.submit(np.ones(8, np.float32))
            assert router.stats.admission_denied == 1
            assert counters.get("router_admission_denied") == before + 1
            assert router.admissions[-1]["admitted"] is False

            clock.advance(6.0)
            router.adapt()  # the idle resident retires -> headroom frees
            assert set(router.engines()) == set()
            fut = router.submit(np.ones(8, np.float32))
            assert fut.result(30.0) is not None
            assert router.stats.warm_adds == 1
            assert router.admissions[-1]["admitted"] is True
        finally:
            router.close()


class TestConcurrency:
    def test_concurrent_mixed_shape_clients_bit_equal(self, rng):
        e16, e8 = _make_engine((16,)), _make_engine((8,))
        r16, r8 = _reqs(rng, 24, (16,)), _reqs(rng, 24, (8,))
        answers: dict = {}
        errors: list = []
        with _router() as router:
            router.add_engine(e16)
            router.add_engine(e8)

            def client(cid, reqs):
                try:
                    futs = [router.submit(r) for r in reqs]
                    answers[cid] = np.stack(
                        [f.result(30.0) for f in futs]
                    )
                except BaseException as e:  # noqa: BLE001 — surfaced below
                    errors.append(e)

            threads = [
                threading.Thread(target=client, args=(0, r16)),
                threading.Thread(target=client, args=(1, r8)),
                threading.Thread(target=client, args=(2, r16[::-1])),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60.0)
        assert not errors, errors
        assert np.array_equal(answers[0], e16.offline(r16))
        assert np.array_equal(answers[1], e8.offline(r8))
        assert np.array_equal(answers[2], e16.offline(r16[::-1]))


class TestConfig:
    def test_env_seeding(self, monkeypatch):
        monkeypatch.setenv(frontend.WARM_THRESHOLD_ENV, "7")
        monkeypatch.setenv(frontend.MIX_WINDOW_ENV, "2.5")
        monkeypatch.setenv(frontend.RETIRE_AFTER_ENV, "12")
        monkeypatch.setenv(frontend.MAX_ENGINES_ENV, "3")
        cfg = frontend.RouterConfig.from_env()
        assert cfg.warm_threshold == 7
        assert cfg.mix_window_s == 2.5
        assert cfg.retire_after_s == 12.0
        assert cfg.max_engines == 3

    def test_invalid_env_is_typed(self, monkeypatch):
        monkeypatch.setenv(frontend.WARM_THRESHOLD_ENV, "0")
        with pytest.raises(ValueError, match=">= 1"):
            frontend.RouterConfig.from_env()
        monkeypatch.delenv(frontend.WARM_THRESHOLD_ENV)
        monkeypatch.setenv(frontend.MIX_WINDOW_ENV, "banana")
        with pytest.raises(ValueError, match="not a number"):
            frontend.RouterConfig.from_env()

    def test_invalid_config_rejected(self):
        with pytest.raises(ValueError):
            frontend.RouterConfig(warm_threshold=0)
        with pytest.raises(ValueError):
            frontend.RouterConfig(mix_window_s=0)

class TestReplaceEngine:
    """Atomic per-shape engine replacement (ISSUE 18 satellite): the swap
    is add-then-retire under ONE routing-table update, so a continuously
    servable shape never answers a transient ``RetryLater`` and no
    in-flight request is lost."""

    @staticmethod
    def _successor(shape, seed, label="frontend"):
        """A replacement engine with DISTINCT weights (so old/new answers
        are distinguishable) under the SAME shape label as
        :func:`_make_engine` — exercising the same-label rename guard."""
        shape = tuple(int(d) for d in shape)
        srng = np.random.default_rng(seed)
        w = jnp.asarray(srng.normal(size=shape).astype(np.float32))
        b = jnp.asarray(srng.normal(size=shape).astype(np.float32))
        pipe = FunctionTransformer(lambda x: jnp.maximum(x * w, b), name="toy2")
        cfg = kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0)
        return kserve.ServingEngine(
            pipe, np.zeros(shape, np.float32), config=cfg,
            label=frontend.shape_label(label, shape),
        )

    def test_swap_is_atomic_with_zero_request_loss(self, rng):
        e_old = _make_engine((16,))
        e_new = self._successor((16,), seed=99)
        reqs = _reqs(rng, 6, (16,))
        with _router() as router:
            router.add_engine(e_old)
            # Stretch the incumbent's batches so the swap genuinely
            # straddles in-flight work.
            real_exec = e_old._execute
            def slow_execute(bucket, dev):
                time.sleep(0.05)
                return real_exec(bucket, dev)
            e_old._execute = slow_execute
            inflight = [router.submit(r) for r in reqs]
            # Probe the routing table at the most hostile instant: from
            # INSIDE the incumbent's retirement (table already flipped to
            # the successor, drain not yet finished).  The probe must
            # route — a retire-then-add sequence would RetryLater here.
            mid = {}
            real_retire = router._retire_entry
            def retire_probe(entry, why):
                mid["fut"] = router.submit(reqs[0])
                real_retire(entry, why=why)
            router._retire_entry = retire_probe
            try:
                key = router.replace_engine(e_new, why="test swap")
            finally:
                router._retire_entry = real_retire
                e_old._execute = real_exec
            assert key == (16,)
            # Every pre-swap future resolved on the OLD engine, bit-equal
            # (drained, not dropped).
            old_ans = np.stack([f.result(30.0) for f in inflight])
            assert np.array_equal(old_ans, e_old.offline(reqs))
            # The mid-retirement probe answered on the NEW engine.
            probe = np.asarray(mid["fut"].result(30.0))
            assert np.array_equal(probe, e_new.offline(reqs[:1])[0])
            # Post-swap traffic routes to the successor.
            post = np.stack([router.submit(r).result(30.0) for r in reqs])
            assert np.array_equal(post, e_new.offline(reqs))
            assert router.stats.replaces == 1
            assert router.stats.retires == 1
            # No backpressure / miss for a shape that never stopped being
            # servable.
            assert router.stats.rejected == 0
            assert router.stats.no_route == 0
            assert router.stats.misses == 0

    def test_same_label_successor_is_renamed(self):
        """SLO trackers and drift monitors unregister BY LABEL at
        retirement: a same-label successor must be renamed before its
        server registers, or the incumbent's retirement would strip the
        successor's telemetry."""
        e_old = _make_engine((8,))
        e_new = self._successor((8,), seed=41)
        assert e_new.label == e_old.label
        with _router() as router:
            router.add_engine(e_old)
            router.replace_engine(e_new, why="same-label swap")
            assert e_new.label == f"{e_old.label}@swap"
            assert router.engines()[(8,)] == e_new.label
            # The successor's SLO tracker survived the incumbent's
            # label-keyed unregistration.
            assert e_new.label in telemetry.slo_summaries()

    def test_mix_accounting_carries_over(self, rng):
        """``routes``/``last_routed`` carry across the swap so the
        idle-retire clock does not restart on a replacement."""
        clock = FakeClock()
        e_old = _make_engine((16,))
        e_new = self._successor((16,), seed=77)
        with _router(clock=clock) as router:
            router.add_engine(e_old)
            for r in _reqs(rng, 5, (16,)):
                router.submit(r).result(30.0)
            with router._lock:
                before = router._engines[(16,)].routes
            assert before == 5
            router.replace_engine(e_new, why="carry-over check")
            with router._lock:
                entry = router._engines[(16,)]
                assert entry.routes == before
                assert entry.engine is e_new

    def test_replace_without_incumbent_degrades_to_add(self, rng):
        e = _make_engine((16,))
        with _router() as router:
            key = router.replace_engine(e, why="first deploy")
            assert key == (16,)
            assert router.stats.replaces == 0
            assert router.stats.retires == 0
            r = _reqs(rng, 3, (16,))
            ans = np.stack([router.submit(x).result(30.0) for x in r])
            assert np.array_equal(ans, e.offline(r))

    def test_replace_on_closed_router_is_typed(self):
        router = _router()
        router.add_engine(_make_engine((16,)))
        router.close()
        with pytest.raises(kserve.ServingUnavailable):
            router.replace_engine(self._successor((16,), seed=5))

    def test_duplicate_add_still_rejected_after_replace(self):
        """replace_engine is the ONLY path that overwrites a live shape —
        add_engine keeps its collision guard."""
        with _router() as router:
            router.add_engine(_make_engine((16,)))
            router.replace_engine(self._successor((16,), seed=13))
            with pytest.raises(ValueError, match="already has a live engine"):
                router.add_engine(_make_engine((16,)))
