"""Materialized snapshot cache tests (core.snapshot + the ingest and
fv_common integrations): key invalidation (tar identity, chunking, extra
key material, featurizer digest), bit-identical warm reads, counted
stale/corrupt fallbacks, crash-safe commit semantics, and the admin tool.
"""

import glob
import json
import os
import sys

import numpy as np
import pytest

import faults

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

from keystone_tpu.core import ingest
from keystone_tpu.core import snapshot as ksnap
from keystone_tpu.core.resilience import counters


@pytest.fixture
def tar10(tmp_path, rng):
    path = str(tmp_path / "snap.tar")
    names = faults.make_image_tar(path, 10, rng)
    return path, names


def _stream(path, batch, snapshot_dir=None, **kw):
    cfg = ingest.StreamConfig.from_env(snapshot_dir=snapshot_dir, **kw)
    out = []
    with ingest.stream_batches(path, batch, transfer=False, config=cfg) as st:
        for b in st:
            out.append((b.index, b.indices.copy(), list(b.names), b.host.copy()))
    assert st.join(10.0)
    return out, st


def _assert_streams_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert x[0] == y[0]
        assert np.array_equal(x[1], y[1])
        assert x[2] == y[2]
        assert x[3].dtype == y[3].dtype
        assert np.array_equal(x[3], y[3])


# -- keys ---------------------------------------------------------------------


def test_key_is_stable_and_moves_with_inputs(tar10):
    path, _ = tar10
    k = ksnap.snapshot_key(path, batch_size=4)
    assert k == ksnap.snapshot_key(path, batch_size=4)
    # chunk layout depends on batch size -> part of the key
    assert k != ksnap.snapshot_key(path, batch_size=8)
    # extra key material (keep filters, label files) moves the key
    assert k != ksnap.snapshot_key(path, batch_size=4, extra="voc:prefix")
    # touching the tar (new mtime) invalidates
    st = os.stat(path)
    os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 10_000_000))
    assert k != ksnap.snapshot_key(path, batch_size=4)


def test_featurized_key_requires_and_folds_in_digest(tar10):
    path, _ = tar10
    with pytest.raises(ValueError, match="featurizer"):
        ksnap.snapshot_key(path, batch_size=4, mode="featurized")
    ka = ksnap.snapshot_key(
        path, batch_size=4, mode="featurized", featurizer="digest-a"
    )
    kb = ksnap.snapshot_key(
        path, batch_size=4, mode="featurized", featurizer="digest-b"
    )
    assert ka != kb
    # decoded vs featurized never alias even with identical inputs
    assert ka != ksnap.snapshot_key(path, batch_size=4)


def test_featurizer_digest_moves_with_weights():
    from keystone_tpu.solvers.pca import BatchPCATransformer

    import jax.numpy as jnp

    a = ksnap.featurizer_digest(
        BatchPCATransformer(jnp.ones((4, 2), jnp.float32))
    )
    b = ksnap.featurizer_digest(
        BatchPCATransformer(jnp.full((4, 2), 2.0, jnp.float32))
    )
    assert a != b
    # unserializable featurizers refuse rather than key silently
    from keystone_tpu.core.checkpoint import CheckpointError

    with pytest.raises(CheckpointError):
        ksnap.featurizer_digest(lambda x: x)


# -- decoded snapshots through the ingest stream ------------------------------


def test_cold_write_then_warm_read_bit_identical(tmp_path, tar10):
    path, _ = tar10
    root = str(tmp_path / "cache")
    cold, st_cold = _stream(path, 4, snapshot_dir=root)
    assert st_cold.stats.snapshot_chunks_written == len(cold)
    assert st_cold.stats.snapshot_chunks_read == 0
    committed = [s for s in ksnap.list_snapshots(root) if s["valid"]]
    assert len(committed) == 1 and committed[0]["images"] == 10
    warm, st_warm = _stream(path, 4, snapshot_dir=root)
    assert st_warm.stats.snapshot_chunks_read == len(cold)
    _assert_streams_equal(cold, warm)


def test_stale_key_is_counted_and_rewritten(tmp_path, tar10):
    path, _ = tar10
    root = str(tmp_path / "cache")
    cold, _ = _stream(path, 4, snapshot_dir=root)
    st = os.stat(path)
    os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 10_000_000))
    before = counters.get("snapshot_stale")
    again, st2 = _stream(path, 4, snapshot_dir=root)
    assert counters.get("snapshot_stale") == before + 1
    assert st2.stats.snapshot_chunks_read == 0  # stale -> live decode
    _assert_streams_equal(cold, again)  # same bytes, so same chunks
    # the fresh key committed alongside (or over) the stale one
    keys = {s["key"] for s in ksnap.list_snapshots(root) if s["valid"]}
    assert ksnap.snapshot_key(path, batch_size=4) in keys


def test_corrupt_shard_counted_fallback_and_self_heal(tmp_path, tar10):
    path, _ = tar10
    root = str(tmp_path / "cache")
    cold, _ = _stream(path, 4, snapshot_dir=root)
    shard = sorted(glob.glob(os.path.join(root, "snap-*", "chunk_*.npz")))[1]
    with open(shard, "rb") as fh:
        data = fh.read()
    with open(shard, "wb") as fh:
        fh.write(data[: len(data) // 2])
    before = counters.get("snapshot_fallback")
    fb, st_fb = _stream(path, 4, snapshot_dir=root)
    assert counters.get("snapshot_fallback") == before + 1
    _assert_streams_equal(cold, fb)
    # the fallback pass rewrote the snapshot: the next read is clean
    healed, st_h = _stream(path, 4, snapshot_dir=root)
    assert counters.get("snapshot_fallback") == before + 1
    assert st_h.stats.snapshot_chunks_read == len(cold)
    _assert_streams_equal(cold, healed)


def test_fallback_divergence_is_typed_never_scrambled(
    tmp_path, tar10, monkeypatch
):
    """Prefix suppression during a corrupt-shard fallback is only sound
    while the live re-decode reproduces the served chunks exactly.  When a
    transient counted skip shifts the survivor sequence between the two
    passes, the stream must die TYPED (the consumer scatters rows by
    ordinal — continuing would silently scramble them)."""
    from keystone_tpu.loaders import image_loaders

    path, names = tar10
    root = str(tmp_path / "cache")
    cold, _ = _stream(path, 4, snapshot_dir=root)
    shard = sorted(glob.glob(os.path.join(root, "snap-*", "chunk_*.npz")))[1]
    with open(shard, "rb") as fh:
        data = fh.read()
    with open(shard, "wb") as fh:
        fh.write(data[: len(data) // 2])
    # The first member (inside the already-served prefix) now fails decode
    # — a transient counted skip that shifts every later chunk boundary.
    target = dict(image_loaders._iter_tar_members(path))[names[0]]
    real = image_loaders.decode_image

    def flaky(data):
        return None if data == target else real(data)

    monkeypatch.setattr(image_loaders, "decode_image", flaky)
    before = counters.get("snapshot_fallback_divergence")
    cfg = ingest.StreamConfig.from_env(snapshot_dir=root)
    with pytest.raises(ingest.SnapshotFallbackDivergence):
        with ingest.stream_batches(path, 4, transfer=False, config=cfg) as st:
            for _ in st:
                pass
    assert counters.get("snapshot_fallback_divergence") == before + 1
    assert st.join(10.0)


def test_early_consumer_exit_commits_nothing(tmp_path, tar10):
    path, _ = tar10
    root = str(tmp_path / "cache")
    cfg = ingest.StreamConfig.from_env(snapshot_dir=root, ring_capacity=1)
    with ingest.stream_batches(path, 2, transfer=False, config=cfg) as st:
        next(iter(st))  # one chunk, then bail
    assert st.join(10.0)
    assert not [s for s in ksnap.list_snapshots(root) if s["valid"]]
    # and the aborted temp directory was cleaned up, not leaked
    assert not [
        s for s in ksnap.list_snapshots(root) if s["dir"].startswith(".tmp-")
    ]


def test_snapshot_write_failure_degrades_to_live(tmp_path, tar10, monkeypatch):
    """The cache is an optimization: a shard-write failure (full disk) is
    a counted degradation, never a dead stream."""
    path, names = tar10
    root = str(tmp_path / "cache")

    def boom(self, *a, **kw):
        raise OSError("disk full (injected)")

    monkeypatch.setattr(ksnap.SnapshotWriter, "add_chunk", boom)
    before = counters.get("snapshot_write_failed")
    got, st = _stream(path, 4, snapshot_dir=root)
    assert sum(len(c[2]) for c in got) == len(names)  # stream completed
    assert counters.get("snapshot_write_failed") == before + 1
    assert st.stats.snapshot_chunks_written == 0
    assert not [s for s in ksnap.list_snapshots(root) if s.get("valid")]


def test_unusable_snapshot_root_degrades_to_live(tmp_path, tar10):
    """An unusable snapshot ROOT (a path component is a regular file, an
    unwritable parent) is the same counted degradation as a failed shard
    write — the live-decode stream must survive the writer never opening."""
    path, names = tar10
    blocker = tmp_path / "blocker"
    blocker.write_text("a file where a directory must go")
    root = str(blocker / "cache")
    before = counters.get("snapshot_write_failed")
    got, st = _stream(path, 4, snapshot_dir=root)
    assert sum(len(c[2]) for c in got) == len(names)  # stream completed
    assert counters.get("snapshot_write_failed") == before + 1
    assert st.stats.snapshot_chunks_written == 0


def test_featurized_mode_degrades_to_decoded_where_unsupported(
    tmp_path, monkeypatch
):
    """Streams with no featurized wrapper (VOC/ImageNet descriptor passes)
    must not let ``KEYSTONE_SNAPSHOT_MODE=featurized`` leave the cache dir
    silently inert: counted downgrade to decoded caching instead."""
    from keystone_tpu.workloads.fv_common import stream_config_from_flags

    monkeypatch.setenv("KEYSTONE_SNAPSHOT_MODE", "featurized")
    before = counters.get("snapshot_mode_unsupported")
    cfg = stream_config_from_flags(snapshot_dir=str(tmp_path / "c"))
    assert cfg.snapshot_mode == "decoded"
    assert counters.get("snapshot_mode_unsupported") == before + 1
    # a caller that wraps the stream in stream_features_snapshot keeps it
    honored = stream_config_from_flags(
        snapshot_dir=str(tmp_path / "c"), supports_featurized=True
    )
    assert honored.snapshot_mode == "featurized"
    # no cache dir -> nothing is inert, nothing to count
    monkeypatch.delenv("KEYSTONE_SNAPSHOT_DIR", raising=False)
    off = stream_config_from_flags()
    assert off.snapshot_dir is None
    assert counters.get("snapshot_mode_unsupported") == before + 1


def test_keep_filter_without_extra_disables_snapshot(tmp_path, tar10):
    path, names = tar10
    root = str(tmp_path / "cache")
    cfg = ingest.StreamConfig.from_env(snapshot_dir=root)
    with ingest.stream_batches(
        path, 4, transfer=False, config=cfg, keep=lambda n: True
    ) as st:
        got = [b for b in st]
    assert st.join(10.0)
    assert sum(len(b) for b in got) == len(names)
    assert st.stats.snapshot_chunks_written == 0
    assert ksnap.list_snapshots(root) == []


def test_writer_abort_leaves_no_trace(tmp_path):
    root = str(tmp_path / "cache")
    w = ksnap.SnapshotWriter(root, "ab" * 32, mode="decoded")
    w.add_chunk(0, [0], ["x"], np.zeros((1, 4, 4, 3), np.float32))
    w.abort()
    assert ksnap.list_snapshots(root) == []


def test_stale_is_mode_scoped(tmp_path, tar10):
    """A committed FEATURIZED snapshot for the same tar must not make a
    first decoded-mode lookup read as 'stale' — it was never a candidate
    for the decoded key."""
    path, _ = tar10
    root = str(tmp_path / "cache")
    w = ksnap.SnapshotWriter(
        root,
        ksnap.snapshot_key(
            path, batch_size=4, mode="featurized", featurizer="d"
        ),
        mode="featurized",
        meta={"tar": ksnap.tar_identity(path)},
    )
    w.add_chunk(0, [0], ["x"], np.zeros((1, 2), np.float32))
    w.commit()
    snap, reason = ksnap.lookup(
        root, ksnap.snapshot_key(path, batch_size=4), tar_path=path
    )
    assert snap is None and reason == "miss"


def test_evict_rejects_sweeping_prefixes(tmp_path, tar10):
    path, _ = tar10
    root = str(tmp_path / "cache")
    _stream(path, 4, snapshot_dir=root)
    with pytest.raises(ValueError, match="prefix"):
        ksnap.evict(root, key_prefix="")
    with pytest.raises(ValueError, match="prefix"):
        ksnap.evict(root, key_prefix="ab")
    assert len(ksnap.list_snapshots(root)) == 1  # nothing was removed


# -- featurized snapshots (fv_common helper) ----------------------------------


def test_featurized_snapshot_serves_and_invalidates(tmp_path, tar10):
    from keystone_tpu.core.ingest import stream_batches
    from keystone_tpu.workloads.fv_common import stream_features_snapshot

    path, names = tar10
    root = str(tmp_path / "cache")

    def per_batch(batch):
        return np.stack(
            [batch.host.mean(axis=(1, 2, 3)), batch.host.max(axis=(1, 2, 3))],
            axis=1,
        ).astype(np.float32)

    def key(digest):
        return ksnap.snapshot_key(
            path, batch_size=4, mode="featurized", featurizer=digest
        )

    def make_stream():
        return stream_batches(
            path, 4, transfer=False, config=ingest.StreamConfig.from_env()
        )

    live_feats, live_names, st = stream_features_snapshot(
        make_stream, per_batch, root=root, key=key("model-v1"),
        tar_path=path,
    )
    assert st is not None  # live pass streamed
    assert live_names == names
    snap_feats, snap_names, st2 = stream_features_snapshot(
        make_stream, per_batch, root=root, key=key("model-v1"),
        tar_path=path,
    )
    assert st2 is None  # served from the shards, nothing streamed
    assert snap_names == live_names
    assert np.array_equal(snap_feats, live_feats)
    # a refit featurizer (new digest) must MISS — counted as staleness
    # (a same-mode snapshot for this tar exists under the old key), and
    # never replay stale features
    stale_before = counters.get("snapshot_stale")
    refit_feats, _, st3 = stream_features_snapshot(
        make_stream, per_batch, root=root, key=key("model-v2"),
        tar_path=path,
    )
    assert st3 is not None
    assert counters.get("snapshot_stale") == stale_before + 1
    assert np.array_equal(refit_feats, live_feats)
    # corrupt featurized shard -> counted fallback to the live pass
    v1_dir = next(
        s["dir"]
        for s in ksnap.list_snapshots(root)
        if s.get("valid") and s["key"] == key("model-v1")
    )
    shard = sorted(glob.glob(os.path.join(root, v1_dir, "chunk_*.npz")))[0]
    with open(shard, "r+b") as fh:
        data = bytearray(fh.read())
        data[len(data) // 2] ^= 0xFF
        fh.seek(0)
        fh.write(bytes(data))
    before = counters.get("snapshot_fallback")
    fb_feats, fb_names, st4 = stream_features_snapshot(
        make_stream, per_batch, root=root, key=key("model-v1")
    )
    assert counters.get("snapshot_fallback") >= before + 1
    assert st4 is not None and np.array_equal(fb_feats, live_feats)


# -- the admin tool -----------------------------------------------------------


def test_snapshot_admin_list_inspect_evict(tmp_path, tar10, capsys):
    import snapshot_admin

    path, _ = tar10
    root = str(tmp_path / "cache")
    _stream(path, 4, snapshot_dir=root)
    key = ksnap.snapshot_key(path, batch_size=4)

    assert snapshot_admin.main([root, "list"]) == 0
    rec = json.loads(capsys.readouterr().out.splitlines()[0])
    assert rec["op"] == "list" and len(rec["snapshots"]) == 1
    assert rec["snapshots"][0]["key"] == key

    assert snapshot_admin.main([root, "inspect", key[:8]]) == 0
    rec = json.loads(capsys.readouterr().out.splitlines()[0])
    assert rec["ok"]

    # corrupt a shard: inspect must fail loudly
    shard = sorted(glob.glob(os.path.join(root, "snap-*", "chunk_*.npz")))[0]
    with open(shard, "ab") as fh:
        fh.write(b"x")
    assert snapshot_admin.main([root, "inspect", key[:8]]) == 1
    rec = json.loads(capsys.readouterr().out.splitlines()[0])
    assert not rec["ok"] and rec["problems"]

    assert snapshot_admin.main([root, "evict", "--key", key[:8]]) == 0
    rec = json.loads(capsys.readouterr().out.splitlines()[0])
    assert len(rec["removed"]) == 1
    assert ksnap.list_snapshots(root) == []


def test_snapshot_admin_evicts_stale_and_temps(tmp_path, tar10, capsys):
    import snapshot_admin

    path, _ = tar10
    root = str(tmp_path / "cache")
    _stream(path, 4, snapshot_dir=root)
    # a valid FEATURIZED snapshot for the same tar: --stale must not touch
    # it (its key folds in a digest the admin tool cannot recompute)
    wf = ksnap.SnapshotWriter(
        root,
        ksnap.snapshot_key(
            path, batch_size=4, mode="featurized", featurizer="d"
        ),
        mode="featurized",
        meta={"tar": ksnap.tar_identity(path)},
    )
    wf.add_chunk(0, [0], ["x"], np.zeros((1, 2), np.float32))
    feat_dir = os.path.basename(wf.commit())
    # make the committed decoded snapshot stale and add crash debris
    st = os.stat(path)
    os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 10_000_000))
    os.makedirs(os.path.join(root, ".tmp-deadbeef-123"))
    # no --batch: staleness classification reads the manifest's RECORDED
    # chunking, so no guessed probe list is involved
    assert (
        snapshot_admin.main(
            [root, "evict", "--stale", "--tar", path, "--temps"]
        )
        == 0
    )
    rec = json.loads(capsys.readouterr().out.splitlines()[0])
    assert len(rec["removed"]) == 2
    assert feat_dir not in rec["removed"]
    left = ksnap.list_snapshots(root)
    assert [s["dir"] for s in left] == [feat_dir]


def test_snapshot_admin_stale_spares_current_exotic_batch(
    tmp_path, tar10, capsys
):
    """A CURRENT snapshot whose batch size would never appear in a guessed
    probe list must survive ``evict --stale`` (its exact key is recomputed
    from the manifest's recorded chunking), while a genuinely stale
    snapshot for the same tar is evicted in the same pass."""
    import snapshot_admin

    path, _ = tar10
    root = str(tmp_path / "cache")
    # a snapshot under a key that's already dead (the tar will be touched)
    _stream(path, 4, snapshot_dir=root)
    st = os.stat(path)
    os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 10_000_000))
    # a CURRENT snapshot with an exotic batch size (post-touch identity)
    _stream(path, 7, snapshot_dir=root)
    current = os.path.basename(
        ksnap._dir_for(root, ksnap.snapshot_key(path, batch_size=7))
    )
    assert snapshot_admin.main(
        [root, "evict", "--stale", "--tar", path]
    ) == 0
    rec = json.loads(capsys.readouterr().out.splitlines()[0])
    assert len(rec["removed"]) == 1 and current not in rec["removed"]
    left = ksnap.list_snapshots(root)
    assert [s["dir"] for s in left] == [current]
    # a manifest with no recorded chunking cannot prove staleness: --stale
    # must refuse to guess (left alone without --batch)
    mpath = os.path.join(root, current, ksnap.MANIFEST_NAME)
    with open(mpath) as fh:
        manifest = json.load(fh)
    manifest["meta"].pop("batch_size")
    os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 20_000_000))
    with open(mpath, "w") as fh:
        json.dump(manifest, fh)
    assert snapshot_admin.main(
        [root, "evict", "--stale", "--tar", path]
    ) == 0
    rec = json.loads(capsys.readouterr().out.splitlines()[0])
    assert rec["removed"] == []
    # ... until --batch supplies the missing chunking
    assert snapshot_admin.main(
        [root, "evict", "--stale", "--tar", path, "--batch", "7"]
    ) == 0
    rec = json.loads(capsys.readouterr().out.splitlines()[0])
    assert rec["removed"] == [current]


def test_snapshot_admin_evict_invalid_is_surgical(tmp_path, tar10, capsys):
    """--invalid removes exactly the manifest-less directories — including
    ones whose names don't follow the snap- convention — and never a
    valid snapshot."""
    import snapshot_admin

    path, _ = tar10
    root = str(tmp_path / "cache")
    _stream(path, 4, snapshot_dir=root)
    os.makedirs(os.path.join(root, "tmp"))  # stray dir, no manifest
    os.makedirs(os.path.join(root, "snap-0000000000000000"))  # no manifest
    assert snapshot_admin.main([root, "evict", "--invalid"]) == 0
    rec = json.loads(capsys.readouterr().out.splitlines()[0])
    assert sorted(rec["removed"]) == ["snap-0000000000000000", "tmp"]
    left = ksnap.list_snapshots(root)
    assert len(left) == 1 and left[0]["valid"]


# -- write-path compression (KEYSTONE_SNAPSHOT_COMPRESS) ----------------------


def _write_snapshot(root, key, payloads, compress):
    w = ksnap.SnapshotWriter(root, key, mode="decoded", compress=compress)
    for i, p in enumerate(payloads):
        w.add_chunk(i, np.arange(p.shape[0]) + i * p.shape[0],
                    [f"img_{i}_{j}.jpg" for j in range(p.shape[0])], p)
    return w.commit()


def _read_payloads(root, key):
    snap, status = ksnap.lookup(root, key)
    assert status == "hit"
    return [arrs["payload"] for _entry, arrs in snap.iter_chunks()]


def test_compressed_shards_round_trip_bit_identical(tmp_path, rng):
    # integral-f32 pixels: exercises the uint8 compaction + deflate combo
    payloads = [
        rng.integers(0, 256, (4, 8, 8, 3)).astype(np.float32)
        for _ in range(3)
    ]
    root = str(tmp_path / "zcache")
    _write_snapshot(root, "aa" * 32, payloads, compress=True)
    got = _read_payloads(root, "aa" * 32)
    for want, have in zip(payloads, got):
        assert have.dtype == want.dtype
        assert np.array_equal(want, have)


def test_compressed_shards_are_smaller_on_compressible_payloads(tmp_path):
    # constant-ish image data deflates hard; the manifest records both the
    # on-disk and the raw payload bytes so the ratio is auditable
    payloads = [np.full((8, 16, 16, 3), 127, np.float32) for _ in range(2)]
    plain_root = str(tmp_path / "plain")
    comp_root = str(tmp_path / "comp")
    _write_snapshot(plain_root, "bb" * 32, payloads, compress=False)
    _write_snapshot(comp_root, "cc" * 32, payloads, compress=True)

    def shard_bytes(root, key):
        snap, status = ksnap.lookup(root, key)
        assert status == "hit"
        return sum(c["bytes"] for c in snap.manifest["chunks"])

    plain = shard_bytes(plain_root, "bb" * 32)
    comp = shard_bytes(comp_root, "cc" * 32)
    assert comp < plain / 2, (comp, plain)
    snap, _ = ksnap.lookup(comp_root, "cc" * 32)
    assert snap.manifest["compress"] is True
    assert all(c["compressed"] for c in snap.manifest["chunks"])
    assert all(c["payload_bytes"] > 0 for c in snap.manifest["chunks"])


def test_old_uncompressed_shards_stay_readable(tmp_path, rng):
    """A pre-knob snapshot (plain np.savez, no 'compress'/'compressed'
    manifest fields) must keep reading under a compress-on process."""
    payloads = [rng.integers(0, 256, (4, 8, 8, 3)).astype(np.float32)]
    root = str(tmp_path / "old")
    _write_snapshot(root, "dd" * 32, payloads, compress=False)
    # Strip the new manifest fields to simulate a pre-knob artifact.
    [snap_dir] = glob.glob(os.path.join(root, "snap-*"))
    mpath = os.path.join(snap_dir, ksnap.MANIFEST_NAME)
    with open(mpath) as fh:
        manifest = json.load(fh)
    manifest.pop("compress", None)
    chunks = []
    for c in manifest["chunks"]:
        c = dict(c)
        c.pop("compressed", None)
        c.pop("payload_bytes", None)
        chunks.append(c)
    manifest["chunks"] = chunks
    with open(mpath, "w") as fh:
        json.dump(manifest, fh)
    prev = os.environ.get(ksnap.SNAPSHOT_COMPRESS_ENV)
    os.environ[ksnap.SNAPSHOT_COMPRESS_ENV] = "1"
    try:
        got = _read_payloads(root, "dd" * 32)
    finally:
        if prev is None:
            os.environ.pop(ksnap.SNAPSHOT_COMPRESS_ENV, None)
        else:
            os.environ[ksnap.SNAPSHOT_COMPRESS_ENV] = prev
    assert np.array_equal(got[0], payloads[0])


def test_compress_env_knob(tmp_path, monkeypatch):
    monkeypatch.delenv(ksnap.SNAPSHOT_COMPRESS_ENV, raising=False)
    assert ksnap.snapshot_compress_env() is True  # default on
    monkeypatch.setenv(ksnap.SNAPSHOT_COMPRESS_ENV, "0")
    assert ksnap.snapshot_compress_env() is False
    w = ksnap.SnapshotWriter(str(tmp_path), "ee" * 32, mode="decoded")
    assert w._compress is False  # writer defers to the env when unpinned
    w.abort()
