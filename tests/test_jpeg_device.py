"""Device-resident JPEG decode (ops.jpeg_device + core.ingest
decode_mode="device" + the core.snapshot device-format tier, ISSUE 13).

Golden-parity corpus: seeded baseline JPEGs covering 4:4:4 / 4:2:2 /
4:2:0 subsampling, restart markers, odd dimensions, grayscale, and mixed
qualities — the device decode (host entropy pass -> batched dequant +
IDCT + fancy chroma upsample + YCbCr->BGR on the accelerator) must match
the host decoder (native libjpeg, PIL fallback) within the IDCT-rounding
tolerance the snapshot cache already keys decoders by.  The Pallas IDCT
kernel must be BIT-equal to the jnp einsum path in interpret mode.
"""

import io
import os
import tarfile

import numpy as np
import pytest

import faults

from keystone_tpu.core import ingest
from keystone_tpu.core import snapshot as ksnap
from keystone_tpu.core import trace
from keystone_tpu.core.resilience import counters
from keystone_tpu.loaders.image_loaders import decode_image
from keystone_tpu.ops import jpeg_device as jd
from keystone_tpu.workloads.fv_common import scatter_features_streaming


def _jpeg(arr, **kw) -> bytes:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", **kw)
    return buf.getvalue()


def _device_decode_one(data: bytes) -> np.ndarray:
    ci = jd.entropy_decode(data)
    coeffs, qt = jd.stack_coeff_images([ci])
    return np.asarray(jd.decode_batch(ci.geom, coeffs, qt))[0]


def _corpus(rng):
    """(label, jpeg bytes) over the claimed baseline subset.  Noise images
    are the adversarial case (every AC coefficient populated); the smooth
    gradient catches DC/upsample bugs noise would mask."""
    noise = rng.integers(0, 256, (64, 64, 3)).astype(np.uint8)
    yy, xx = np.mgrid[0:64, 0:64]
    smooth = (
        np.stack([(np.sin(yy / 9) + np.cos(xx / 7)) * 60 + 128] * 3, -1)
        .clip(0, 255)
        .astype(np.uint8)
    )
    odd = rng.integers(0, 256, (47, 53, 3)).astype(np.uint8)
    gray = rng.integers(0, 256, (40, 44)).astype(np.uint8)
    cases = []
    for label, arr in (("noise", noise), ("smooth", smooth)):
        for ss in (0, 1, 2):  # 4:4:4, 4:2:2, 4:2:0
            for q in (85, 90, 95):
                cases.append(
                    (f"{label}/ss{ss}/q{q}",
                     _jpeg(arr, quality=q, subsampling=ss))
                )
    for ss in (0, 1, 2):
        cases.append((f"odd/ss{ss}", _jpeg(odd, quality=90, subsampling=ss)))
    cases.append(("gray", _jpeg(gray, quality=90)))
    cases.append(
        ("restart",
         _jpeg(noise, quality=90, subsampling=2, restart_marker_blocks=2))
    )
    return cases


def test_zigzag_is_a_permutation():
    assert sorted(jd.ZIGZAG.tolist()) == list(range(64))


def test_golden_parity_corpus(rng):
    """Device decode vs the host decoder (whatever decode_image resolves —
    native libjpeg or PIL) within GOLDEN_MAX_ABS / GOLDEN_MEAN_ABS per
    corpus member, same shapes, BGR channel order, integral f32."""
    for label, data in _corpus(rng):
        dev = _device_decode_one(data)
        ref = decode_image(data)
        assert ref is not None, label
        assert dev.shape == ref.shape, label
        assert dev.dtype == np.float32
        assert np.array_equal(dev, np.round(dev)), f"{label}: non-integral"
        diff = np.abs(dev - ref)
        assert diff.max() <= jd.GOLDEN_MAX_ABS, (
            f"{label}: max abs {diff.max()} > {jd.GOLDEN_MAX_ABS}"
        )
        assert diff.mean() <= jd.GOLDEN_MEAN_ABS, (
            f"{label}: mean abs {diff.mean()} > {jd.GOLDEN_MEAN_ABS}"
        )


def test_mixed_quality_batch_uses_per_image_quant_tables(rng):
    """Same geometry, different quality: one batched program, per-image
    dequant tables — each image must still match ITS host decode."""
    arr = rng.integers(0, 256, (48, 48, 3)).astype(np.uint8)
    datas = [
        _jpeg(arr, quality=q, subsampling=2) for q in (85, 90, 95)
    ]
    cis = [jd.entropy_decode(d) for d in datas]
    assert len({ci.geom for ci in cis}) == 1  # one geometry bucket
    coeffs, qt = jd.stack_coeff_images(cis)
    batch = np.asarray(jd.decode_batch(cis[0].geom, coeffs, qt))
    for i, data in enumerate(datas):
        diff = np.abs(batch[i] - decode_image(data))
        assert diff.max() <= jd.GOLDEN_MAX_ABS


def test_pallas_idct_bit_equal_to_jnp_in_interpret_mode(rng):
    import jax.numpy as jnp

    blocks = jnp.asarray(
        rng.normal(size=(37, 8, 8)).astype(np.float32) * 50.0
    )
    a = np.asarray(jd.idct_blocks_jnp(blocks))
    b = np.asarray(jd.idct_blocks_pallas(blocks, interpret=True))
    assert np.array_equal(a, b)
    # leading batch dims survive the tile/pad round trip
    blocks4 = jnp.asarray(
        rng.normal(size=(3, 2, 5, 8, 8)).astype(np.float32)
    )
    a4 = np.asarray(jd.idct_blocks_jnp(blocks4))
    b4 = np.asarray(jd.idct_blocks_pallas(blocks4, interpret=True))
    assert np.array_equal(a4, b4)


def test_idct_env_chooser(rng, monkeypatch):
    import jax.numpy as jnp

    blocks = jnp.asarray(rng.normal(size=(9, 8, 8)).astype(np.float32))
    monkeypatch.setenv(jd.PALLAS_IDCT_ENV, "1")
    via_pallas = np.asarray(jd.idct_blocks(blocks))
    monkeypatch.setenv(jd.PALLAS_IDCT_ENV, "0")
    via_jnp = np.asarray(jd.idct_blocks(blocks))
    assert np.array_equal(via_pallas, via_jnp)


def test_unsupported_reasons_are_typed(rng):
    noise = rng.integers(0, 256, (48, 48, 3)).astype(np.uint8)
    base = _jpeg(noise, quality=90, subsampling=0)

    with pytest.raises(jd.JpegDecodeUnsupported) as ei:
        jd.entropy_decode(_jpeg(noise, quality=90, progressive=True))
    assert ei.value.reason == "progressive"

    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(noise).convert("CMYK").save(buf, "JPEG", quality=90)
    with pytest.raises(jd.JpegDecodeUnsupported) as ei:
        jd.entropy_decode(buf.getvalue())
    assert ei.value.reason == "cmyk"

    # arithmetic coding: SOF0 marker patched to SOF9 (header-level reject)
    with pytest.raises(jd.JpegDecodeUnsupported) as ei:
        jd.entropy_decode(base.replace(b"\xff\xc0", b"\xff\xc9", 1))
    assert ei.value.reason == "arithmetic"

    # exotic sampling: Y factors patched to 4x1 in the SOF segment
    sof = base.find(b"\xff\xc0")
    comp0_hv = sof + 2 + 2 + 6 + 1  # marker+len | P,H,W,Nf | C1 id
    assert base[comp0_hv] == 0x11  # 4:4:4 -> (1,1)
    patched = base[:comp0_hv] + b"\x41" + base[comp0_hv + 1 :]
    with pytest.raises(jd.JpegDecodeUnsupported) as ei:
        jd.entropy_decode(patched)
    assert ei.value.reason == "subsampling"

    with pytest.raises(jd.JpegDecodeUnsupported) as ei:
        jd.entropy_decode(b"\x89PNG not a jpeg at all")
    assert ei.value.reason == "not_jpeg"

    # Adobe APP14 transform=0: three components stored RGB — the YCbCr
    # matrix would silently hue-shift them, so it must route to fallback
    app14 = b"\xff\xee\x00\x0eAdobe\x00\x64\x00\x00\x00\x00\x00"
    with pytest.raises(jd.JpegDecodeUnsupported) as ei:
        jd.entropy_decode(base[:2] + app14 + base[2:])
    assert ei.value.reason == "rgb_colorspace"


def test_entropy_corruption_is_typed(rng):
    data = _jpeg(
        rng.integers(0, 256, (48, 48, 3)).astype(np.uint8), quality=90
    )
    for mode in ("truncate", "marker"):
        bad = faults.corrupt_jpeg_entropy(data, mode)
        with pytest.raises(jd.JpegEntropyCorrupt):
            jd.entropy_decode(bad)


# -- the ingest decode_mode="device" path --------------------------------------


def _make_tar(path, members):
    with tarfile.open(path, "w") as tf:
        for name, data in members:
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))


def _feat():
    import jax
    import jax.numpy as jnp

    return jax.jit(
        lambda x: jnp.stack(
            [jnp.mean(x, axis=(1, 2, 3)), jnp.max(x, axis=(1, 2, 3))],
            axis=1,
        )
    )


def _stream(tar_path, batch, **cfg_kw):
    cfg_kw.setdefault("snapshot_dir", "")
    cfg = ingest.StreamConfig.from_env(**cfg_kw)
    with ingest.stream_batches(tar_path, batch, config=cfg) as st:
        feats, names = scatter_features_streaming(st, _feat(), 2)
    assert st.join(10.0), "ingest threads leaked"
    return feats, names, st.stats


def test_device_stream_matches_host_stream(rng, tmp_path):
    """Same tar through decode_mode host and device: identical survivor
    order; per-image pixels within golden tolerance (collected through
    ``dev()``), coefficient chunks visible in the stats."""
    members = [
        (f"{i}.jpg",
         _jpeg(rng.integers(0, 256, (48, 48, 3)).astype(np.uint8),
               quality=90, subsampling=(0, 1, 2)[i % 3]))
        for i in range(9)
    ]
    tar = str(tmp_path / "t.tar")
    _make_tar(tar, members)
    df, dn, ds = _stream(tar, 4, decode_mode="device")
    hf, hn, hs = _stream(tar, 4, decode_mode="host")
    assert dn == hn
    assert ds.entropy_decoded == 9 and ds.device_fallbacks == 0
    assert ds.coeff_bytes > 0
    # features within decode tolerance of the host path (means over
    # pixels in [0,255]: a loose 1.0 bound still catches wrong images)
    assert np.abs(df - hf).max() <= 1.0


def test_mixed_tar_fallbacks_counted_and_bit_correct(rng, tmp_path):
    """A mixed tar (baseline + progressive + PNG + entropy-corrupt):
    fallback members decode BIT-identically to the host path (they ARE
    host-decoded), each fallback is counted per reason, the corrupt scan
    is a typed counted skip, and the survivor order matches the host
    stream's."""
    good = [
        _jpeg(rng.integers(0, 256, (48, 48, 3)).astype(np.uint8),
              quality=90)
        for _ in range(5)
    ]
    prog = _jpeg(
        rng.integers(0, 256, (48, 48, 3)).astype(np.uint8),
        quality=90, progressive=True,
    )
    from PIL import Image

    png_buf = io.BytesIO()
    Image.fromarray(
        rng.integers(0, 256, (48, 48, 3)).astype(np.uint8)
    ).save(png_buf, "PNG")
    corrupt = faults.corrupt_jpeg_entropy(good[0], "truncate")
    members = [
        ("00.jpg", good[0]),
        ("01_prog.jpg", prog),
        ("02.jpg", good[1]),
        ("03_corrupt.jpg", corrupt),
        ("04.png", png_buf.getvalue()),
        ("05.jpg", good[2]),
        ("06.jpg", good[3]),
        ("07.jpg", good[4]),
    ]
    tar = str(tmp_path / "mixed.tar")
    _make_tar(tar, members)
    before = counters.snapshot()
    df, dn, ds = _stream(tar, 4, decode_mode="device")
    delta = {
        k: v - before.get(k, 0) for k, v in counters.snapshot().items()
    }
    assert delta.get("device_decode_fallback", 0) == 2
    assert delta.get("device_decode_fallback_progressive", 0) == 1
    assert delta.get("device_decode_fallback_not_jpeg", 0) == 1
    assert delta.get("jpeg_corrupt_entropy", 0) == 1
    assert ds.device_fallbacks == 2 and ds.entropy_corrupt == 1
    # host oracle over the SURVIVORS only: libjpeg tolerates a truncated
    # scan (pads missing MCUs and warns) where the device path's contract
    # is typed-or-correct — so the corrupt member is excluded from the
    # oracle tar rather than compared against libjpeg's grey fill.
    tar_ok = str(tmp_path / "mixed_ok.tar")
    _make_tar(tar_ok, [m for m in members if m[0] != "03_corrupt.jpg"])
    hf, hn, hs = _stream(tar_ok, 4, decode_mode="host")
    assert dn == hn  # survivor order preserved across the modes
    # the fallback members' feature rows are bit-equal (host decode on
    # both sides); device-decoded members within tolerance
    fallback_rows = [dn.index("01_prog.jpg"), dn.index("04.png")]
    for r in fallback_rows:
        assert np.array_equal(df[r], hf[r])
    assert np.abs(df - hf).max() <= 1.0


def test_decoded_snapshot_disabled_under_device_decode(rng, tmp_path):
    """decode_mode=device + snapshot_mode=decoded is a contradiction
    (host-cached pixels differ within IDCT rounding): the cache must be
    disabled COUNTED, never silently served or silently inert."""
    tar = str(tmp_path / "t.tar")
    _make_tar(
        tar,
        [("0.jpg",
          _jpeg(rng.integers(0, 256, (48, 48, 3)).astype(np.uint8)))],
    )
    before = counters.get("snapshot_mode_unsupported")
    _f, _n, stats = _stream(
        tar, 4, decode_mode="device",
        snapshot_dir=str(tmp_path / "snap"), snapshot_mode="decoded",
    )
    assert counters.get("snapshot_mode_unsupported") - before == 1
    assert stats.snapshot_chunks_written == 0
    assert not list(ksnap.list_snapshots(str(tmp_path / "snap")))


# -- the device-format snapshot tier -------------------------------------------


def test_device_snapshot_warm_epoch_is_pure_dma(rng, tmp_path):
    """Cold pass: host decode + device-format tee (padded, dtype-final,
    uncompressed shards).  Warm pass: BIT-equal features with ZERO host
    decode/transform — no entropy decode, no fallback, no pixel decode;
    shard bytes flow straight to device_put (dma gauge > 0)."""
    members = [
        (f"{i}.jpg",
         _jpeg(rng.integers(0, 256, (48, 48, 3)).astype(np.uint8),
               quality=90))
        for i in range(10)
    ]
    tar = str(tmp_path / "t.tar")
    _make_tar(tar, members)
    snap_root = str(tmp_path / "snap")

    cf, cn, cs = _stream(
        tar, 4, snapshot_dir=snap_root, snapshot_mode="device"
    )
    assert cs.snapshot_chunks_written == 3
    [snap] = [s for s in ksnap.list_snapshots(snap_root) if s["valid"]]
    assert snap["mode"] == "device" and snap["images"] == 10

    # shards: f32 dtype-final, batch dim padded (8-row quantum capped at
    # the stream batch size),
    # uncompressed, valid count recorded
    import glob

    shards = sorted(
        glob.glob(os.path.join(snap_root, snap["dir"], "chunk_*.npz"))
    )
    with np.load(shards[-1]) as zf:
        assert zf["payload"].dtype == np.float32
        assert zf["payload"].shape[0] == 4  # padded (10 = 4+4+2)
        assert int(zf["valid"]) == 2
        assert "payload_cast" not in zf.files  # never compacted

    wf, wn, ws = _stream(
        tar, 4, snapshot_dir=snap_root, snapshot_mode="device"
    )
    assert np.array_equal(cf, wf) and cn == wn
    assert ws.snapshot_chunks_read == 3
    assert ws.snapshot_dma_bytes > 0
    # the acceptance bar: zero host-side decode/transform on the warm
    # epoch — entropy gauge and fallback/decode counters untouched
    assert ws.entropy_decoded == 0
    assert ws.device_fallbacks == 0
    assert ws.coeff_bytes == 0
    gauges = trace.metrics.snapshot().get("gauges", {})
    assert gauges.get("ingest_entropy_decoded", 0) == 0
    assert gauges.get("ingest_snapshot_dma_bytes", 0) > 0


def test_device_snapshot_corrupt_shard_falls_back_counted(rng, tmp_path):
    """A bit-flipped device-format shard mid-read: counted
    ``snapshot_fallback`` to live (host) decode, features bit-equal to
    the cold pass, snapshot self-healed."""
    import glob

    members = [
        (f"{i}.jpg",
         _jpeg(rng.integers(0, 256, (48, 48, 3)).astype(np.uint8)))
        for i in range(8)
    ]
    tar = str(tmp_path / "t.tar")
    _make_tar(tar, members)
    snap_root = str(tmp_path / "snap")
    cf, cn, _cs = _stream(
        tar, 4, snapshot_dir=snap_root, snapshot_mode="device"
    )
    [snap] = [s for s in ksnap.list_snapshots(snap_root) if s["valid"]]
    target = sorted(
        glob.glob(os.path.join(snap_root, snap["dir"], "chunk_*.npz"))
    )[1]
    blob = bytearray(open(target, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(target, "wb").write(bytes(blob))

    before = counters.get("snapshot_fallback")
    wf, wn, _ws = _stream(
        tar, 4, snapshot_dir=snap_root, snapshot_mode="device"
    )
    assert counters.get("snapshot_fallback") - before == 1
    assert np.array_equal(cf, wf) and cn == wn


def test_fused_admission_denied_degrades_counted(rng, tmp_path, monkeypatch):
    """An impossible HBM budget denies the fused decode+featurize program:
    counted ``device_decode_admission_denied``, the stream still completes
    (unfused two-dispatch path) with correct output."""
    members = [
        (f"{i}.jpg",
         _jpeg(rng.integers(0, 256, (50, 50, 3)).astype(np.uint8)))
        for i in range(4)
    ]
    tar = str(tmp_path / "t.tar")
    _make_tar(tar, members)
    hf, hn, _hs = _stream(tar, 4, decode_mode="host")
    monkeypatch.setenv("KEYSTONE_HBM_BUDGET", "1")
    before = counters.get("device_decode_admission_denied")
    df, dn, _ds = _stream(tar, 4, decode_mode="device")
    assert counters.get("device_decode_admission_denied") - before >= 1
    assert dn == hn
    assert np.abs(df - hf).max() <= 1.0


def test_cifar_train_stream_loader_pins_host_decode(rng, tmp_path):
    """An env-seeded KEYSTONE_DEVICE_DECODE=1 must not crash (or change)
    the streamed TRAIN loader: its contract is host-resident pixels
    bit-identical to the eager loader, so device decode is ignored
    COUNTED (``device_decode_unsupported``)."""
    from keystone_tpu.workloads.cifar_random_patch import (
        cifar_tar_loader,
        cifar_tar_stream_loader,
    )

    members = [
        (f"{i % 4}/img_{i:03d}.jpg",
         _jpeg(rng.integers(0, 256, (48, 48, 3)).astype(np.uint8)))
        for i in range(8)
    ]
    tar = str(tmp_path / "train.tar")
    _make_tar(tar, members)
    eager = cifar_tar_loader(tar)
    before = counters.get("device_decode_unsupported")
    cfg = ingest.StreamConfig.from_env(
        decode_mode="device", snapshot_dir=""
    )
    streamed = cifar_tar_stream_loader(tar, batch=4, config=cfg)
    assert counters.get("device_decode_unsupported") - before == 1
    np.testing.assert_array_equal(streamed.images, eager.images)
    np.testing.assert_array_equal(streamed.labels, eager.labels)


def test_featurized_snapshot_key_folds_decode_mode(rng, tmp_path, monkeypatch):
    """Features computed from device-decoded pixels differ (IDCT rounding)
    from host-decoded ones — a host-decode run must MISS a featurized
    snapshot written under device decode, never silently replay it."""
    import dataclasses as _dc

    from keystone_tpu.loaders.cifar import LabeledImageBatch
    from keystone_tpu.workloads.cifar_random_patch import (
        RandomCifarConfig,
        run,
    )

    members = []
    labels = []
    for i in range(12):
        c = i % 4
        arr = np.clip(
            rng.uniform(40, 215, 3)[None, None, :]
            + rng.normal(0, 25, (48, 48, 3)),
            0, 255,
        ).astype(np.uint8)
        members.append((f"{c}/img_{i:03d}.jpg", _jpeg(arr, quality=90)))
        labels.append(c)
    tar = str(tmp_path / "t.tar")
    _make_tar(tar, members)
    from keystone_tpu.loaders.image_loaders import _iter_tar_images

    decoded = list(_iter_tar_images(tar, num_threads=1))
    train = LabeledImageBatch(
        np.stack([img for _, img in decoded]),
        np.asarray(labels, np.int32),
    )
    snap_dir = str(tmp_path / "snap")
    monkeypatch.setenv("KEYSTONE_SNAPSHOT_MODE", "featurized")
    conf = RandomCifarConfig(
        num_filters=4, patch_steps=6, lam=10.0, whitener_size=64,
        featurize_chunk=4, num_classes=4, stream_test_tar=tar,
        snapshot_dir=snap_dir,
    )
    run(_dc.replace(conf, device_decode=True), train, train)
    [dev_snap] = [
        s for s in ksnap.list_snapshots(snap_dir) if s["valid"]
    ]
    before = counters.get("snapshot_stale")
    run(conf, train, train)  # host decode: must MISS (stale), not replay
    assert counters.get("snapshot_stale") - before >= 1
    snaps = [s for s in ksnap.list_snapshots(snap_dir) if s["valid"]]
    assert len(snaps) == 2  # a second, differently-keyed snapshot
    assert {s["dir"] for s in snaps} > {dev_snap["dir"]}
