"""End-to-end VOCSIFTFisher and ImageNetSiftLcsFV on synthetic tar datasets
(the reference tests loaders on miniature tars and checks solver/MAP behavior
downstream; here the full pipelines run on small separable data)."""

import io
import tarfile

import numpy as np
import pytest

from keystone_tpu.loaders.image_loaders import imagenet_loader, voc_loader
from keystone_tpu.workloads.imagenet_sift_lcs_fv import (
    ImageNetSiftLcsFVConfig,
    run as run_imagenet,
)
from keystone_tpu.workloads.voc_sift_fisher import SIFTFisherConfig, run as run_voc


def _img_bytes(arr):
    from PIL import Image as PILImage

    buf = io.BytesIO()
    PILImage.fromarray(arr.astype(np.uint8)).save(buf, format="JPEG", quality=95)
    return buf.getvalue()


def _class_image(rng, c, size=64):
    """Class-dependent color + oriented texture."""
    palette = np.array(
        [[200, 60, 60], [60, 200, 60], [60, 60, 200], [200, 200, 60]], np.float64
    )
    yy, xx = np.mgrid[0:size, 0:size]
    img = np.zeros((size, size, 3))
    img += palette[c]
    img[:, :, c % 3] += 50 * np.sin((xx * (c + 1) + yy * (3 - c)) / 4.0)
    img += rng.normal(0, 12, img.shape)
    return np.clip(img, 0, 255)


def write_voc_tar(path, labels_csv, n, rng, num_classes=4):
    prefix = "VOCdevkit/VOC2007/JPEGImages"
    rows = ["\"id\",\"class\",\"classname\",\"traintesteval\",\"filename\""]
    with tarfile.open(path, "w") as tf:
        for i in range(n):
            c = int(rng.integers(0, num_classes))
            name = f"{prefix}/{i:06d}.jpg"
            data = _img_bytes(_class_image(rng, c))
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
            rows.append(f'{i},{c + 1},"c{c}",1,"{name}"')
    with open(labels_csv, "a") as fh:
        fh.write("\n".join(rows[1:] if fh.tell() else rows) + "\n")


def write_imagenet_tar(dirpath, labels_path, rng, classes=(0, 1, 2), per_class=8):
    with open(labels_path, "w") as fh:
        for c in classes:
            fh.write(f"syn{c:03d} {c}\n")
    for c in classes:
        with tarfile.open(f"{dirpath}/syn{c:03d}.tar", "w") as tf:
            for i in range(per_class):
                data = _img_bytes(_class_image(rng, c))
                info = tarfile.TarInfo(f"syn{c:03d}/img_{i}.JPEG")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))


@pytest.mark.slow
class TestVOCSIFTFisherE2E:
    def test_map_beats_chance(self, tmp_path, rng):
        labels_csv = str(tmp_path / "labels.csv")
        open(labels_csv, "w").close()
        write_voc_tar(str(tmp_path / "train.tar"), labels_csv, 24, rng)
        # one tar serves both splits (self-test on separable data)
        conf = SIFTFisherConfig(
            lam=0.05,  # FV features are unit-norm; heavy λ underfits tiny n
            desc_dim=16,
            vocab_size=8,
            num_pca_samples=6000,
            num_gmm_samples=6000,
            sift_step_size=6,
        )
        data = voc_loader(str(tmp_path / "train.tar"), labels_csv)
        assert len(data) == 24
        results = run_voc(conf, data, data)
        # 16 of the 20 VOC classes have no positives (AP 0 by definition);
        # the criterion is the AP of the 4 present classes (chance ~0.25)
        assert np.all(results["aps"][:4] > 0.9), results


@pytest.mark.slow
class TestImageNetSiftLcsFVE2E:
    def test_top1_error_low(self, tmp_path, rng):
        labels_path = str(tmp_path / "labels.txt")
        write_imagenet_tar(str(tmp_path), labels_path, rng)
        data = imagenet_loader(str(tmp_path), labels_path)
        assert len(data) == 24
        conf = ImageNetSiftLcsFVConfig(
            lam=1e-3,
            mixture_weight=0.25,
            desc_dim=12,
            vocab_size=4,
            num_pca_samples=4000,
            num_gmm_samples=4000,
            lcs_stride=8,
            lcs_border=16,
            lcs_patch=6,
            num_classes=3,
        )
        results = run_imagenet(conf, data, data)
        # k=min(5,3)=3 makes top-k trivial; the real criterion is top-1
        # self-classification on separable color/texture classes
        assert results["top5_err_percent"] == 0.0, results
        assert results["top1_err_percent"] < 15.0, results
