"""NLP node + NaiveBayes + NewsgroupsPipeline tests (reference
src/test/scala/nodes/nlp/*, NaiveBayesModelSuite criteria, and an e2e run
on a synthetic 20-newsgroups-format directory)."""

import numpy as np
import pytest

from keystone_tpu.loaders.newsgroups import newsgroups_loader
from keystone_tpu.ops.nlp import (
    LowerCase,
    NGramsFeaturizer,
    TermFrequency,
    Tokenizer,
    Trim,
    fit_word_frequency_encoder,
)
from keystone_tpu.ops.sparse import (
    AllSparseFeatures,
    CommonSparseFeatures,
    SparseFeatureVectorizer,
)
from keystone_tpu.solvers.naive_bayes import NaiveBayesEstimator
from keystone_tpu.workloads.newsgroups import NewsgroupsConfig, run


class TestStringNodes:
    def test_trim_lower_tokenize(self):
        out = Tokenizer()(LowerCase()(Trim()(["  Hello, World!  "])))
        assert out == [["hello", "world"]]

    def test_tokenizer_keeps_leading_empty(self):
        # Scala split keeps a leading empty string when the line starts
        # with a separator ("a,b".split -> ["a","b"], ",a" -> ["", "a"])
        assert Tokenizer()([",a b"]) == [["", "a", "b"]]
        assert Tokenizer()(["a b,"]) == [["a", "b"]]


class TestNGrams:
    def test_orders_1_to_3(self):
        # reference NGramsFeaturizerSuite-style: all 1..3-grams in order
        out = NGramsFeaturizer(range(1, 4))([["a", "b", "c"]])[0]
        assert ("a",) in out and ("a", "b") in out and ("a", "b", "c") in out
        assert ("b", "c") in out and ("c",) in out
        assert len(out) == 6

    def test_non_consecutive_orders_rejected(self):
        with pytest.raises(ValueError, match="consecutive"):
            NGramsFeaturizer([1, 3])

    def test_emission_order_matches_reference(self):
        # at each position: min-order gram, then extensions
        out = NGramsFeaturizer([1, 2])([["x", "y", "z"]])[0]
        assert out == [("x",), ("x", "y"), ("y",), ("y", "z"), ("z",)]


class TestTermFrequencySparse:
    def test_term_frequency_weighting(self):
        out = TermFrequency(lambda x: x * 10)([["a", "a", "b"]])[0]
        assert dict(out) == {"a": 20, "b": 10}

    def test_common_sparse_features_top_k(self):
        docs = [[("a", 1.0), ("b", 1.0)], [("a", 1.0)], [("a", 1.0), ("c", 1.0)]]
        vec = CommonSparseFeatures(2).fit(docs)
        assert "a" in vec.feature_space and len(vec.feature_space) == 2
        csr = vec(docs)
        assert csr.shape == (3, 2)
        dense = csr.to_dense()
        assert dense[:, vec.feature_space["a"]].tolist() == [1.0, 1.0, 1.0]

    def test_all_sparse_features(self):
        docs = [[("x", 2.0)], [("y", 3.0)]]
        vec = AllSparseFeatures().fit(docs)
        assert vec(docs).shape == (2, 2)

    def test_unseen_features_dropped(self):
        vec = SparseFeatureVectorizer({"a": 0})
        csr = vec([[("zzz", 5.0), ("a", 1.0)]])
        assert csr.to_dense().tolist() == [[1.0]]


class TestNaiveBayes:
    def test_matches_closed_form(self, rng):
        # hand-computable smoothed counts (MLlib semantics)
        feats = np.array([[2.0, 0.0], [1.0, 1.0], [0.0, 3.0]])
        labels = np.array([0, 0, 1])
        model = NaiveBayesEstimator(2, lam=1.0).fit(feats, labels)
        pi = np.asarray(model.pi)
        theta = np.asarray(model.theta)
        np.testing.assert_allclose(
            pi, [np.log(3 / 5), np.log(2 / 5)], atol=1e-6
        )
        # class 0 counts: [3, 1]; theta[0] = log((c+1)/(4+2))
        np.testing.assert_allclose(
            theta[0], [np.log(4 / 6), np.log(2 / 6)], atol=1e-6
        )

    def test_csr_and_dense_agree(self, rng):
        from keystone_tpu.ops.sparse import AllSparseFeatures

        docs = [
            [("a", 2.0), ("b", 1.0)],
            [("b", 3.0)],
            [("a", 1.0), ("c", 2.0)],
        ]
        labels = np.array([0, 1, 0])
        vec = AllSparseFeatures().fit(docs)
        csr = vec(docs)
        model = NaiveBayesEstimator(2).fit(csr, labels)
        dense_scores = np.asarray(model(csr.to_dense()))
        csr_scores = np.asarray(model(csr))
        np.testing.assert_allclose(dense_scores, csr_scores, atol=1e-4)

    def test_learns_separable_text(self, rng):
        n = 60
        vocab_a = ["apple", "orange", "banana"]
        vocab_b = ["engine", "wheel", "brake"]
        docs, labels = [], []
        for i in range(n):
            c = i % 2
            words = rng.choice(vocab_a if c == 0 else vocab_b, 20).tolist()
            words += rng.choice(vocab_a + vocab_b, 3).tolist()  # noise
            docs.append([(w, 1.0) for w in set(words)])
            labels.append(c)
        vec = AllSparseFeatures().fit(docs)
        model = NaiveBayesEstimator(2).fit(vec(docs), np.array(labels))
        pred = np.argmax(np.asarray(model(vec(docs))), axis=1)
        assert (pred == np.array(labels)).mean() > 0.95


class TestWordFrequencyEncoder:
    def test_rank_and_oov(self):
        enc = fit_word_frequency_encoder([["a", "a", "b"], ["a", "c", "b"]])
        assert enc.word_index["a"] == 0
        out = enc([["a", "zzz", "b"]])
        assert out == [[0, -1, enc.word_index["b"]]]


class TestNewsgroupsE2E:
    def test_pipeline_classifies_synthetic_groups(self, tmp_path, rng):
        themes = {
            "comp.graphics": ["pixel", "render", "shader", "gpu", "image"],
            "rec.autos": ["engine", "car", "wheel", "drive", "motor"],
            "sci.space": ["orbit", "rocket", "nasa", "launch", "moon"],
        }
        for split in ("train", "test"):
            for cls, words in themes.items():
                d = tmp_path / split / cls
                d.mkdir(parents=True)
                for i in range(20 if split == "train" else 8):
                    body = " ".join(rng.choice(words, 30).tolist())
                    noise = " ".join(rng.choice(["the", "and", "is"], 10).tolist())
                    (d / f"doc{i}.txt").write_text(f"{body} {noise}")
        classes = tuple(themes)
        train = newsgroups_loader(str(tmp_path / "train"), list(classes))
        test = newsgroups_loader(str(tmp_path / "test"), list(classes))
        conf = NewsgroupsConfig(n_grams=2, common_features=5000, classes=classes)
        results = run(conf, train, test)
        assert results["test_error"] < 5.0, results["test_error"]
