"""Indexer + Stupid Backoff tests mirroring the reference suites
(src/test/scala/nodes/nlp/NGramIndexerSuite.scala,
src/test/scala/pipelines/nlp/StupidBackoffSuite.scala — including the
hand-computed backoff scores and the context-colocation invariant)."""

import pytest

from keystone_tpu.ops.ngram_lm import (
    NaiveBitPackIndexer,
    NGramIndexerImpl,
    NGramsCounts,
    StupidBackoffEstimator,
    shard_by_initial_bigram,
    sharded_scores,
)
from keystone_tpu.ops.nlp import NGramsFeaturizer, Tokenizer

DATA = ["Winter is coming", "Finals are coming", "Summer is coming really soon"]


def featurize(orders, mode="default"):
    toks = Tokenizer()(DATA)
    grams = NGramsFeaturizer(orders)(toks)
    return NGramsCounts(mode)(grams)


class TestNaiveBitPackIndexer:
    def test_pack(self):
        # NGramIndexerSuite "pack()" exact values
        assert NaiveBitPackIndexer.pack([1]) == 2**40
        assert NaiveBitPackIndexer.pack([1, 1]) == 2**40 + 2**20 + 2**60
        assert NaiveBitPackIndexer.pack([1, 1, 1]) == 1 + 2**40 + 2**20 + 2**61

    def test_remove_farthest_word(self):
        for ix in (NaiveBitPackIndexer, NGramIndexerImpl()):
            assert ix.remove_farthest_word(ix.pack([1, 2, 3])) == ix.pack([2, 3])
            assert ix.remove_farthest_word(ix.pack([1, 2])) == ix.pack([2])

    def test_remove_current_word(self):
        for ix in (NaiveBitPackIndexer, NGramIndexerImpl()):
            assert ix.remove_current_word(ix.pack([1, 2, 3])) == ix.pack([1, 2])
            assert ix.remove_current_word(ix.pack([1, 2])) == ix.pack([1])

    def test_unpack_roundtrip(self):
        packed = NaiveBitPackIndexer.pack([7, 42, 99])
        assert [NaiveBitPackIndexer.unpack(packed, p) for p in range(3)] == [7, 42, 99]
        assert NaiveBitPackIndexer.ngram_order(packed) == 3

    def test_rejects_large_word_ids(self):
        with pytest.raises(ValueError, match="2\\^20"):
            NaiveBitPackIndexer.pack([1 << 20])


class TestStupidBackoff:
    def _fit(self):
        ngrams = featurize(range(2, 6), "noAdd")
        unigrams = {k[0]: v for k, v in featurize([1])}
        return StupidBackoffEstimator(unigrams).fit(ngrams)

    def test_hand_computed_scores(self):
        # StupidBackoffSuite "calculates correct scores" (:60-76)
        lm = self._fit()
        assert lm.score(("is", "coming")) == 2.0 / 2.0
        assert lm.score(("is", "coming", "really")) == 1.0 / 2.0
        assert lm.score(("is", "unseen-coming")) == 0.0
        assert lm.score(("is-unseen", "coming")) == lm.alpha * 3.0 / lm.num_tokens

    def test_all_scores_in_unit_interval(self):
        lm = self._fit()
        scores = lm.scores()
        assert scores and all(0.0 <= s <= 1.0 for s in scores.values())

    @pytest.mark.parametrize("num_shards", (1, 2, 4, 16))
    def test_sharded_scores_equal_single_table(self, num_shards):
        # The sharded scoring path (InitialBigramPartitioner executable,
        # StupidBackoff.scala:25-58): shard-local scoring with backoff
        # re-routing must reproduce the single-table scores exactly, at
        # any shard count.
        lm = self._fit()
        want = lm.scores()
        got, shard_sizes = sharded_scores(
            lm.ngram_counts, lm.unigram_counts, num_shards, alpha=lm.alpha
        )
        assert got == want
        assert sum(shard_sizes.values()) == len(lm.ngram_counts)
        assert set(shard_sizes) <= set(range(num_shards))

    def test_sharded_scores_route_cross_shard_backoffs(self):
        # Counted ngrams score in one shard-local round; UNSEEN queries
        # back off — removing the farthest word changes the first two
        # words, i.e. usually the shard — so these only score right if the
        # between-round re-route (the multi-host shuffle analog) works.
        lm = self._fit()
        unseen = [
            ("is-unseen", "coming"),           # -> unigram "coming"
            ("summer", "finals", "coming"),    # -> ("finals","coming") -> unigram
            ("winter", "is", "soon"),          # -> ("is","soon") -> unigram
        ]
        got, _ = sharded_scores(
            lm.ngram_counts, lm.unigram_counts, 8, alpha=lm.alpha,
            queries=unseen,
        )
        for q in unseen:
            assert got[q] == lm.score(q), q

    def test_sharded_scores_unigram_query_parity(self):
        # A DIRECT order-1 query reads the ngram table (single-table
        # semantics: usually 0 — unigrams live in the broadcast table),
        # while a backed-off unigram reads the unigram table; both must
        # match the single-table model.
        lm = self._fit()
        got, _ = sharded_scores(
            lm.ngram_counts, lm.unigram_counts, 8, alpha=lm.alpha,
            queries=[("coming",)],
        )
        assert got[("coming",)] == lm.score(("coming",))

    def test_context_colocation_invariant(self):
        # requireNGramColocation (:27-46): every ngram's backoff context maps
        # to the same shard under the initial-bigram sharding
        lm = self._fit()
        ix = NGramIndexerImpl()
        num_shards = 4
        for ngram in lm.ngram_counts:
            curr = ngram
            while ix.ngram_order(curr) > 2:
                ctx = ix.remove_current_word(curr)
                assert shard_by_initial_bigram(
                    curr, num_shards
                ) == shard_by_initial_bigram(ctx, num_shards)
                curr = ctx
