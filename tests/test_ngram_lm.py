"""Indexer + Stupid Backoff tests mirroring the reference suites
(src/test/scala/nodes/nlp/NGramIndexerSuite.scala,
src/test/scala/pipelines/nlp/StupidBackoffSuite.scala — including the
hand-computed backoff scores and the context-colocation invariant)."""

import pytest

from keystone_tpu.ops.ngram_lm import (
    NaiveBitPackIndexer,
    NGramIndexerImpl,
    NGramsCounts,
    StupidBackoffEstimator,
    shard_by_initial_bigram,
)
from keystone_tpu.ops.nlp import NGramsFeaturizer, Tokenizer

DATA = ["Winter is coming", "Finals are coming", "Summer is coming really soon"]


def featurize(orders, mode="default"):
    toks = Tokenizer()(DATA)
    grams = NGramsFeaturizer(orders)(toks)
    return NGramsCounts(mode)(grams)


class TestNaiveBitPackIndexer:
    def test_pack(self):
        # NGramIndexerSuite "pack()" exact values
        assert NaiveBitPackIndexer.pack([1]) == 2**40
        assert NaiveBitPackIndexer.pack([1, 1]) == 2**40 + 2**20 + 2**60
        assert NaiveBitPackIndexer.pack([1, 1, 1]) == 1 + 2**40 + 2**20 + 2**61

    def test_remove_farthest_word(self):
        for ix in (NaiveBitPackIndexer, NGramIndexerImpl()):
            assert ix.remove_farthest_word(ix.pack([1, 2, 3])) == ix.pack([2, 3])
            assert ix.remove_farthest_word(ix.pack([1, 2])) == ix.pack([2])

    def test_remove_current_word(self):
        for ix in (NaiveBitPackIndexer, NGramIndexerImpl()):
            assert ix.remove_current_word(ix.pack([1, 2, 3])) == ix.pack([1, 2])
            assert ix.remove_current_word(ix.pack([1, 2])) == ix.pack([1])

    def test_unpack_roundtrip(self):
        packed = NaiveBitPackIndexer.pack([7, 42, 99])
        assert [NaiveBitPackIndexer.unpack(packed, p) for p in range(3)] == [7, 42, 99]
        assert NaiveBitPackIndexer.ngram_order(packed) == 3

    def test_rejects_large_word_ids(self):
        with pytest.raises(ValueError, match="2\\^20"):
            NaiveBitPackIndexer.pack([1 << 20])


class TestStupidBackoff:
    def _fit(self):
        ngrams = featurize(range(2, 6), "noAdd")
        unigrams = {k[0]: v for k, v in featurize([1])}
        return StupidBackoffEstimator(unigrams).fit(ngrams)

    def test_hand_computed_scores(self):
        # StupidBackoffSuite "calculates correct scores" (:60-76)
        lm = self._fit()
        assert lm.score(("is", "coming")) == 2.0 / 2.0
        assert lm.score(("is", "coming", "really")) == 1.0 / 2.0
        assert lm.score(("is", "unseen-coming")) == 0.0
        assert lm.score(("is-unseen", "coming")) == lm.alpha * 3.0 / lm.num_tokens

    def test_all_scores_in_unit_interval(self):
        lm = self._fit()
        scores = lm.scores()
        assert scores and all(0.0 <= s <= 1.0 for s in scores.values())

    def test_context_colocation_invariant(self):
        # requireNGramColocation (:27-46): every ngram's backoff context maps
        # to the same shard under the initial-bigram sharding
        lm = self._fit()
        ix = NGramIndexerImpl()
        num_shards = 4
        for ngram in lm.ngram_counts:
            curr = ngram
            while ix.ngram_order(curr) > 2:
                ctx = ix.remove_current_word(curr)
                assert shard_by_initial_bigram(
                    curr, num_shards
                ) == shard_by_initial_bigram(ctx, num_shards)
                curr = ctx
