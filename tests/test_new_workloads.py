"""E2E tests for LinearPixels, RandomCifar, StupidBackoffPipeline
(reference LinearPixels.scala:14-55, RandomCifar.scala:17-70,
StupidBackoffPipeline.scala:9-59)."""

import numpy as np

from keystone_tpu.loaders.cifar import cifar_loader
from keystone_tpu.workloads.linear_pixels import LinearPixelsConfig
from keystone_tpu.workloads.linear_pixels import run as lp_run
from keystone_tpu.workloads.random_cifar import RandomCifarWorkloadConfig
from keystone_tpu.workloads.random_cifar import run as rc_run
from keystone_tpu.workloads.stupid_backoff import StupidBackoffConfig
from keystone_tpu.workloads.stupid_backoff import run as sb_run

from test_cifar_pipeline import write_synthetic_cifar


def _cifar_pair(tmp_path, rng, n_train=200, n_test=80, palette=None):
    train_path = str(tmp_path / "train.bin")
    test_path = str(tmp_path / "test.bin")
    if palette is None:
        palette = rng.uniform(40, 215, (4, 3))
    write_synthetic_cifar(train_path, n_train, rng, base=palette)
    write_synthetic_cifar(test_path, n_test, rng, base=palette)
    return cifar_loader(train_path), cifar_loader(test_path)


# LinearPixels featurizes to GRAYSCALE pixels: the class palette must stay
# separable after NTSC luminance collapse.
_LUMA_PALETTE = np.array(
    [[40.0, 40.0, 40.0], [100.0, 100.0, 100.0], [160.0, 160.0, 160.0], [220.0, 220.0, 220.0]]
)


def test_linear_pixels_learns_color_classes(tmp_path, rng):
    # n > d=1024: unregularized OLS needs an overdetermined system (the
    # reference runs this on 50k-row CIFAR).
    train, test = _cifar_pair(
        tmp_path, rng, n_train=1600, n_test=200, palette=_LUMA_PALETTE
    )
    conf = LinearPixelsConfig(num_classes=4)
    results = lp_run(conf, train, test)
    # Luminance-separable blobs: well above 25% chance.
    assert results["train_accuracy"] > 0.5, results
    assert results["test_accuracy"] > 0.5, results


def test_linear_pixels_mesh_matches_local(tmp_path, rng, mesh8):
    train, test = _cifar_pair(
        tmp_path, rng, n_train=1601, n_test=101, palette=_LUMA_PALETTE
    )
    conf = LinearPixelsConfig(num_classes=4)
    local = lp_run(conf, train, test)
    sharded = lp_run(conf, train, test, mesh=mesh8)
    assert abs(sharded["test_accuracy"] - local["test_accuracy"]) < 0.03


def test_random_cifar_learns_synthetic_classes(tmp_path, rng):
    train, test = _cifar_pair(tmp_path, rng, n_train=300, n_test=100)
    conf = RandomCifarWorkloadConfig(
        num_filters=16, lam=10.0, num_classes=4, featurize_chunk=64
    )
    results = rc_run(conf, train, test)
    assert results["test_error"] < 25.0, results


def test_stupid_backoff_pipeline(rng):
    corpus = [
        "the cat sat on the mat",
        "the cat ate the fish",
        "a dog sat on the mat",
        "the dog and the cat",
    ] * 3
    conf = StupidBackoffConfig(num_parts=4, n=3)
    results = sb_run(conf, corpus)
    assert results["num_tokens"] == sum(len(l.split()) for l in corpus)
    assert results["vocab_size"] == len(
        {w for l in corpus for w in l.split()}
    )
    assert results["num_ngrams"] > 0
    # every counted ngram scored within [0, 1] (asserted inside scores());
    # the shard layout must cover <= num_parts shards
    assert set(results["shard_sizes"]) <= set(range(conf.num_parts))
    # the sharded scoring path ran and matched the single-table model
    # (run() raises on divergence)
    assert results["sharded_scoring_equal"]
    assert sum(results["shard_sizes"].values()) == results["num_ngrams"]


def test_stupid_backoff_cli_end_to_end(tmp_path):
    """Deliver-or-declare (VERDICT r5 job 7): the CLI entry point runs the
    whole pipeline — file -> tokenize -> encode -> ngrams -> backoff scores
    -> sharded-scoring parity — end to end."""
    from keystone_tpu.workloads.stupid_backoff import main as sb_main

    corpus = tmp_path / "corpus.txt"
    corpus.write_text(
        "the cat sat on the mat\nthe dog ate the fish\n"
        "a cat and a dog sat\n" * 2
    )
    results = sb_main(
        ["--trainData", str(corpus), "--numParts", "8", "--n", "4"]
    )
    assert results["num_ngrams"] > 0
    assert results["sharded_scoring_equal"]
