"""Fault-injection harness for the resilience suite (NOT a test module —
imported by tests/test_resilience.py and usable from the REPL to shake any
pipeline).

Spark gave the reference a substrate that was *constantly* injected with
faults in production (task preemption, straggler kills, bad input records);
our JAX port has to earn that hardness on purpose.  Three fault families:

* **corrupt data**: ``corrupt_jpeg`` mangles a valid JPEG stream (keeps the
  SOI marker so the native decoder engages and must fail cleanly);
  ``make_image_tar`` builds tar archives with chosen members corrupted or
  truncated — the loader must skip-and-count, never crash.
* **transient IO**: ``flaky`` / ``transient_faults`` wrap a callable (or
  patch a module attribute) to raise ``OSError`` for the first N calls and
  then behave — exercising core.resilience.retry's backoff path.
* **poisoned numerics**: ``inject_nan`` sprinkles NaN into a batch;
  ``rank_deficient_gram`` builds a gram whose unregularized Cholesky is
  guaranteed to fail — exercising the solver jitter-retry and the
  ``assert_all_finite`` fit guards.
* **device memory exhaustion**: ``resource_exhausted_error`` builds the
  exact exception XLA raises on HBM OOM (``XlaRuntimeError`` carrying
  RESOURCE_EXHAUSTED); ``oom_faults`` patches a callable to die with it
  for the first N calls — exercising the solvers' degradation-ladder
  step-down (core.memory.run_ladder) without needing a real OOM.
"""

from __future__ import annotations

import contextlib
import io
import tarfile

import numpy as np


def make_jpeg_bytes(rng, h: int = 48, w: int = 48, quality: int = 90) -> bytes:
    """A valid random-texture JPEG."""
    from PIL import Image as PILImage

    arr = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
    buf = io.BytesIO()
    PILImage.fromarray(arr).save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


def corrupt_jpeg(data: bytes, rng) -> bytes:
    """Mangle a JPEG stream: keep the SOI marker (so decoders engage rather
    than reject on sniffing), truncate the tail, and scramble a slice of
    the entropy-coded body."""
    n = len(data)
    keep = max(8, n // 3)
    body = bytearray(data[:keep])
    lo = min(6, len(body) - 1)
    scramble = rng.integers(0, 256, max(0, keep - lo), dtype=np.uint8)
    body[lo:] = scramble.tobytes()
    return bytes(body[:2] + body[2:])  # SOI preserved at [:2]


def corrupt_jpeg_entropy(data: bytes, mode: str = "truncate") -> bytes:
    """Damage ONLY the entropy-coded scan of a baseline JPEG — every
    header (SOF/DQT/DHT/SOS) stays intact, so a decoder that validates
    headers engages the scan and must fail there, typed.  Two
    deterministic modes: ``truncate`` chops the scan mid-stream (bits run
    out inside an MCU), ``marker`` splices an early EOI into the scan
    (the MCU count comes up short).  Both are guaranteed-detectable, so
    the ``jpeg_corrupt_entropy`` chaos family never depends on random
    bytes happening to form an invalid Huffman sequence."""
    sos = data.find(b"\xff\xda")
    if sos < 0:
        raise ValueError("not a JPEG with an SOS marker")
    seg_len = (data[sos + 2] << 8) | data[sos + 3]
    scan = sos + 2 + seg_len
    keep = scan + max(4, (len(data) - scan) // 3)
    if mode == "truncate":
        return data[:keep]
    if mode == "marker":
        return data[:keep] + b"\xff\xd9"
    raise ValueError(f"unknown entropy corruption mode {mode!r}")


def make_image_tar(
    path: str,
    n_images: int,
    rng,
    corrupt: tuple[int, ...] = (),
    h: int = 48,
    w: int = 48,
    name_fmt: str = "img_{:04d}.jpg",
    corrupt_fn=None,
) -> list[str]:
    """Write a tar of JPEGs; members whose index is in ``corrupt`` carry
    mangled JPEG bytes (decode must fail, mid-archive, without breaking
    the members after them).  ``corrupt_fn(data)`` overrides HOW a member
    is mangled (default: :func:`corrupt_jpeg`; the ``jpeg_corrupt_entropy``
    chaos family passes :func:`corrupt_jpeg_entropy` to damage only the
    scan).  Returns the member names."""
    names = []
    with tarfile.open(path, "w") as tf:
        for i in range(n_images):
            data = make_jpeg_bytes(rng, h, w)
            if i in corrupt:
                data = (
                    corrupt_fn(data)
                    if corrupt_fn is not None
                    else corrupt_jpeg(data, rng)
                )
            info = tarfile.TarInfo(name_fmt.format(i))
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
            names.append(info.name)
    return names


def truncate_tail(path: str, nbytes: int = 1024) -> None:
    """Chop the last ``nbytes`` off an archive — a partially-transferred
    tar whose final member (and end-of-archive blocks) are gone."""
    import os

    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(max(0, size - nbytes))


def flaky(fn, failures: int, exc: type[BaseException] = OSError, message: str = "injected transient fault"):
    """Wrap ``fn`` to raise ``exc`` for its first ``failures`` calls, then
    delegate.  The wrapper exposes ``.calls`` and ``.failures_left``."""
    state = {"calls": 0, "left": failures}

    def wrapped(*args, **kwargs):
        state["calls"] += 1
        if state["left"] > 0:
            state["left"] -= 1
            raise exc(f"{message} (call {state['calls']})")
        return fn(*args, **kwargs)

    wrapped.state = state
    return wrapped


@contextlib.contextmanager
def transient_faults(
    obj,
    attr: str,
    failures: int,
    exc: type[BaseException] = OSError,
    message: str = "injected transient fault",
):
    """Patch ``obj.attr`` with a :func:`flaky` wrapper for the duration of
    the block — e.g. ``transient_faults(image_loaders.tarfile, "open", 2)``
    makes the next two tar opens fail with OSError."""
    original = getattr(obj, attr)
    wrapper = flaky(original, failures, exc, message)
    setattr(obj, attr, wrapper)
    try:
        yield wrapper
    finally:
        setattr(obj, attr, original)


def xla_runtime_error_type() -> type[BaseException]:
    """The exception type XLA raises at dispatch/execution time (falls back
    to RuntimeError on jaxlib layouts that do not export it — the OOM
    detector keys on the RESOURCE_EXHAUSTED text either way)."""
    try:
        from jaxlib.xla_extension import XlaRuntimeError

        return XlaRuntimeError
    except ImportError:  # pragma: no cover - jaxlib always has it today
        return RuntimeError


def resource_exhausted_error(nbytes: int = 1 << 33) -> BaseException:
    """An exception indistinguishable from XLA's device-memory exhaustion:
    same type, same RESOURCE_EXHAUSTED grammar as a real TPU allocator
    failure — what ``core.memory.is_oom_error`` must recognize."""
    return xla_runtime_error_type()(
        f"RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        f"{nbytes} bytes. (injected fault)"
    )


@contextlib.contextmanager
def oom_faults(obj, attr: str, failures: int = 1):
    """Patch ``obj.attr`` to raise RESOURCE_EXHAUSTED for its first
    ``failures`` calls — e.g. ``oom_faults(block, "_execute_fused_bcd", 1)``
    makes the next fused BCD dispatch die exactly the way a too-small HBM
    does, driving the fit ladder's one-tier step-down."""
    with transient_faults(
        obj,
        attr,
        failures,
        exc=xla_runtime_error_type(),
        message="RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "8589934592 bytes. (injected fault)",
    ) as wrapper:
        yield wrapper


def inject_nan(batch, rng, frac: float = 0.01):
    """Copy of ``batch`` with ~``frac`` of entries replaced by NaN.

    ``order="C"`` matters: the default ``np.array`` copy preserves the
    source's memory layout, and on a transposed input (e.g. the CIFAR
    loader's NHWC images) ``reshape(-1)`` of that layout is a COPY — the
    NaN writes would be silently discarded and the injection a no-op."""
    out = np.array(batch, copy=True, order="C")
    flat = out.reshape(-1)
    k = max(1, int(frac * flat.size))
    idx = rng.choice(flat.size, k, replace=False)
    flat[idx] = np.nan
    return out


def rank_deficient_gram(rng, n: int = 32, d: int = 8, k: int = 2):
    """(AᵀA, AᵀB) from a design matrix with duplicated columns — the
    unregularized gram is singular, so ``cho_factor`` yields non-finite
    values and only jitter recovery can solve it."""
    a = rng.normal(size=(n, d)).astype(np.float32)
    a[:, d // 2 :] = a[:, : d - d // 2]
    b = rng.normal(size=(n, k)).astype(np.float32)
    return a.T @ a, a.T @ b
