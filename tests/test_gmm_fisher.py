"""GMM / Fisher vector tests mirroring the reference criteria
(src/test/scala/utils/external/EncEvalSuite.scala: planted-mixture recovery;
naive-equivalence replaces the FV golden-file test because the reference's
feats.csv fixture is absent from its own test resources)."""

import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.fisher import FisherVector, fisher_vector
from keystone_tpu.solvers.gmm import GaussianMixtureModel, GaussianMixtureModelEstimator
from keystone_tpu.utils.stats import about_eq


class TestGMM:
    def test_recovers_planted_1d_mixture(self, rng):
        # EncEvalSuite "Compute a GMM from scala" (:42-64): two 1-D gaussians
        n = 10000
        x = rng.normal(-1.0, 0.5, n)
        y = rng.normal(5.0, 1.0, n)
        z = np.concatenate([x, y])[:, None].astype(np.float32)
        rng.shuffle(z)
        gmm = GaussianMixtureModelEstimator(2).fit(jnp.asarray(z))
        means = np.sort(np.asarray(gmm.means).ravel())
        sds = np.sort(np.sqrt(np.asarray(gmm.variances).ravel()))
        assert abs(means[0] - (-1.0)) < 1e-1
        assert abs(means[1] - 5.0) < 1e-1
        assert abs(sds[0] - 0.5) < 1e-1
        assert abs(sds[1] - 1.0) < 1e-1
        assert about_eq(np.asarray(gmm.weights).sum(), 1.0, 1e-5)

    def test_recovers_planted_2d_mixture(self, rng):
        centers = np.array([[0.0, 0.0], [4.0, 4.0], [-4.0, 4.0]])
        samples = np.concatenate(
            [c + 0.5 * rng.normal(size=(2000, 2)) for c in centers]
        ).astype(np.float32)
        rng.shuffle(samples)
        gmm = GaussianMixtureModelEstimator(3).fit(jnp.asarray(samples))
        got = np.sort(np.asarray(gmm.means).T, axis=0)  # [k, d] sorted
        expected = np.sort(centers, axis=0)
        assert np.all(np.abs(got - expected) < 0.2), (got, expected)

    def test_posteriors_sum_to_one(self, rng):
        x = rng.normal(size=(50, 4)).astype(np.float32)
        gmm = GaussianMixtureModelEstimator(5, max_iter=5).fit(jnp.asarray(x))
        q = np.asarray(gmm(jnp.asarray(x)))
        assert q.shape == (50, 5)
        np.testing.assert_allclose(q.sum(axis=1), 1.0, atol=1e-5)

    def test_load_from_csv(self, tmp_path):
        means = np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])  # d=3, k=2
        variances = np.ones((3, 2))
        weights = np.array([0.4, 0.6])
        np.savetxt(tmp_path / "m.csv", means, delimiter=",")
        np.savetxt(tmp_path / "v.csv", variances, delimiter=",")
        np.savetxt(tmp_path / "w.csv", weights[None], delimiter=",")
        gmm = GaussianMixtureModel.load(
            str(tmp_path / "m.csv"), str(tmp_path / "v.csv"), str(tmp_path / "w.csv")
        )
        assert gmm.dim == 3 and gmm.k == 2
        np.testing.assert_allclose(np.asarray(gmm.means), means)


def naive_fisher(x, means, variances, weights):
    """Direct per-descriptor-loop improved-FV (mean+var gradients)."""
    n, d = x.shape
    k = weights.shape[0]
    sigma = np.sqrt(variances)
    # posteriors
    q = np.zeros((n, k))
    for i in range(n):
        logp = np.zeros(k)
        for j in range(k):
            diff = (x[i] - means[:, j]) / sigma[:, j]
            logp[j] = (
                np.log(weights[j])
                - 0.5 * np.sum(diff**2)
                - 0.5 * np.sum(np.log(2 * np.pi * variances[:, j]))
            )
        p = np.exp(logp - logp.max())
        q[i] = p / p.sum()
    g_mean = np.zeros((d, k))
    g_var = np.zeros((d, k))
    for j in range(k):
        for i in range(n):
            u = (x[i] - means[:, j]) / sigma[:, j]
            g_mean[:, j] += q[i, j] * u
            g_var[:, j] += q[i, j] * (u**2 - 1.0)
        g_mean[:, j] /= n * np.sqrt(weights[j])
        g_var[:, j] /= n * np.sqrt(2.0 * weights[j])
    return np.concatenate([g_mean, g_var], axis=1)


class TestFisherVector:
    def _random_gmm(self, rng, d, k):
        means = rng.normal(size=(d, k)).astype(np.float32)
        variances = rng.uniform(0.5, 2.0, (d, k)).astype(np.float32)
        w = rng.uniform(0.5, 1.5, k)
        weights = (w / w.sum()).astype(np.float32)
        return GaussianMixtureModel(means, variances, weights)

    def test_matches_naive(self, rng):
        d, k, n = 6, 4, 30
        gmm = self._random_gmm(rng, d, k)
        x = rng.normal(size=(n, d)).astype(np.float32)
        got = np.asarray(
            fisher_vector(jnp.asarray(x), gmm.means, gmm.variances, gmm.weights)
        )
        expected = naive_fisher(
            x,
            np.asarray(gmm.means),
            np.asarray(gmm.variances),
            np.asarray(gmm.weights),
        )
        assert got.shape == (d, 2 * k)
        assert about_eq(got, expected, 1e-3)

    def test_batched_node_shape_and_layout(self, rng):
        d, k, cols, n_imgs = 5, 3, 20, 4
        gmm = self._random_gmm(rng, d, k)
        batch = rng.normal(size=(n_imgs, d, cols)).astype(np.float32)
        fv = FisherVector(gmm)
        out = np.asarray(fv(jnp.asarray(batch)))
        assert out.shape == (n_imgs, d, 2 * k)
        assert fv.num_features == d * k * 2
        for i in range(n_imgs):
            expected = naive_fisher(
                batch[i].T,
                np.asarray(gmm.means),
                np.asarray(gmm.variances),
                np.asarray(gmm.weights),
            )
            assert about_eq(out[i], expected, 1e-3)

    def test_mask_equals_truncation(self, rng):
        d, k, cols, valid = 5, 3, 20, 12
        gmm = self._random_gmm(rng, d, k)
        mat = rng.normal(size=(d, cols)).astype(np.float32)
        mask = (np.arange(cols) < valid).astype(np.float32)
        fv = FisherVector(gmm)
        with_mask = np.asarray(
            fv(jnp.asarray(mat[None]), jnp.asarray(mask[None]))
        )[0]
        truncated = np.asarray(fv(jnp.asarray(mat[:, :valid][None])))[0]
        assert about_eq(with_mask, truncated, 1e-4)

    def test_descriptors_from_gmm_give_small_fv(self, rng):
        # FV measures deviation from the generative model: sampling from the
        # GMM itself must give a near-zero encoding
        d, k = 4, 2
        means = np.array([[0.0, 5.0]] * d, np.float32)
        variances = np.ones((d, k), np.float32)
        weights = np.array([0.5, 0.5], np.float32)
        comp = rng.integers(0, k, 4000)
        x = (means[:, comp].T + rng.normal(size=(4000, d))).astype(np.float32)
        out = np.asarray(
            fisher_vector(jnp.asarray(x), jnp.asarray(means), jnp.asarray(variances), jnp.asarray(weights))
        )
        assert np.abs(out).max() < 0.1, np.abs(out).max()


class TestFvPallasKernel:
    """The fused Pallas stats kernel (ops/fv_pallas.py) must match the XLA
    formulation exactly (same math, reassociated) — run in interpret mode on
    the CPU test platform; on TPU hardware the same kernel compiles via
    Mosaic and FisherVector routes to it under KEYSTONE_PALLAS=1."""

    def _case(self, rng, n=3, cols=700, d=24, k=8, ragged=True):
        x = rng.normal(size=(n, cols, d)).astype(np.float32)
        means = rng.normal(size=(d, k)).astype(np.float32)
        variances = rng.uniform(0.5, 2.0, (d, k)).astype(np.float32)
        weights = rng.dirichlet(np.ones(k)).astype(np.float32)
        counts = None
        if ragged:
            counts = rng.integers(cols // 2, cols + 1, size=n).astype(np.int32)
        return x, counts, means, variances, weights

    def test_stats_match_xla(self, rng):
        from keystone_tpu.ops.fisher import fisher_vector
        from keystone_tpu.ops.fv_pallas import fv_stats_pallas
        from keystone_tpu.ops.fisher import _fv_from_stats

        x, counts, means, variances, weights = self._case(rng)
        s0, s1, s2 = fv_stats_pallas(
            jnp.asarray(np.swapaxes(x, 1, 2)),  # [N, d, D] descriptor columns
            jnp.asarray(counts), means, variances, weights,
            chunk=256, interpret=True,
        )
        got = np.asarray(
            _fv_from_stats(
                s0, s1, s2, means, variances, weights,
                jnp.asarray(counts, jnp.float32),
            )
        )
        mask = (np.arange(x.shape[1])[None, :] < counts[:, None]).astype(np.float32)
        want = np.stack([
            np.asarray(fisher_vector(x[i], means, variances, weights, jnp.asarray(mask[i])))
            for i in range(x.shape[0])
        ])
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_no_counts_and_unaligned_chunk(self, rng):
        from keystone_tpu.ops.fisher import fisher_vector
        from keystone_tpu.ops.fv_pallas import fv_stats_pallas
        from keystone_tpu.ops.fisher import _fv_from_stats

        # cols deliberately not a multiple of chunk: padded rows must fall
        # outside the implicit all-valid count
        x, _, means, variances, weights = self._case(rng, cols=333, ragged=False)
        s0, s1, s2 = fv_stats_pallas(
            jnp.asarray(np.swapaxes(x, 1, 2)), None, means, variances, weights,
            chunk=128, interpret=True,
        )
        n_valid = jnp.full((x.shape[0],), x.shape[1], jnp.float32)
        got = np.asarray(
            _fv_from_stats(s0, s1, s2, means, variances, weights, n_valid)
        )
        want = np.stack([
            np.asarray(fisher_vector(x[i], means, variances, weights))
            for i in range(x.shape[0])
        ])
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
