"""Seeded chaos suite: every fault schedule must end in either
predictions-equal-to-fault-free or a typed, counted error — never a
silent wrong model, never a bare traceback (see tests/chaos.py).

Tier-1 runs ``chaos.TIER1_SEEDS`` on the MNIST pipeline (plus two
schedules on the conv CIFAR pipeline); the full seed set runs only under
``-m chaos`` (it is also marked slow so the tier-1 ``-m 'not slow'``
filter keeps excluding it).
"""

import pytest

import chaos

#: The counter each family must bump — faults are COUNTED, not just
#: survived, so operators can see them (the "structured, counted, logged"
#: leg of the chaos invariant).
EXPECTED_COUNTER = {
    "solver_oom": "solver_oom_retry",
    "oom_cascade": "solver_oom_retry",
    "io_transient": "io_retry",
    "corrupt_members": "corrupt_image",
    "nan_input": "nonfinite_model",
    "preempt_resume": "chaos_preemption",
    "deadline": "deadline_exceeded",
    "stream_corrupt": "corrupt_image",
    "stream_hang": "deadline_exceeded",
    "autotune_thrash": "chaos_autotune_thrash",
    "snapshot_corrupt": "snapshot_fallback",
    "decode_worker_kill": "decode_worker_respawn",
    "slow_client": "chaos_slow_client",
    "malformed_request": "serve_malformed_request",
    "serve_burst_oom": "serve_burst_oom",
    "plan_mispredict": "autoshard_stepdown",
    "spec_mispredict": "autoshard_stepdown",
    "wire_disconnect": "wire_client_disconnect",
    "slow_loris": "chaos_slow_loris",
    "jpeg_corrupt_entropy": "jpeg_corrupt_entropy",
    "profiler_crash": "profiler_sampler_crash",
    "output_drift": "serve_output_drift",
    "mesh_shrink": "mesh_reanchor",
    "host_loss": "host_reanchor",
    "drift_refit": "lifecycle_refit",
    "native_entropy": "jpeg_corrupt_entropy",
    "obs_capture": "obs_member_lost",
}


def _check(r):
    assert r.ok(), r.record()
    assert r.outcome == chaos.expected_outcome(r.fault), r.record()
    counter = EXPECTED_COUNTER[r.fault.kind]
    assert r.counters_delta.get(counter, 0) >= 1, (
        f"schedule survived but its fault went uncounted "
        f"({counter} delta 0): {r.record()}"
    )


@pytest.mark.parametrize("seed", chaos.TIER1_SEEDS)
def test_chaos_schedule_mnist(seed, tmp_path):
    """Every tier-1 schedule runs TRACED and its trace is held to the
    never-silent bar (the ``chaos_run.py --trace`` invariant, extended
    from the original 10 families to all 27): every counted fault appears
    as a kind-tagged ``fault`` instant, every typed error as a failed
    span or fault event."""
    trace_path = str(tmp_path / f"chaos_seed{seed}.json")
    r = chaos.run_schedule(
        seed, "mnist", tmpdir=str(tmp_path), trace_path=trace_path
    )  # 27 families as of ISSUE 20 (obs_capture)
    _check(r)
    violations = chaos.verify_trace(trace_path, r)
    assert violations == [], {
        "seed": seed, "family": r.fault.kind, "violations": violations,
    }


@pytest.mark.parametrize("seed", (0, 4))  # OOM step-down + NaN guard
def test_chaos_schedule_cifar(seed, tmp_path):
    _check(chaos.run_schedule(seed, "cifar", tmpdir=str(tmp_path)))


def test_tier1_seed_set_meets_the_chaos_bar():
    """The in-tier-1 schedule set is the acceptance floor: >= 10 seeded
    schedules covering EVERY fault family, including one
    preempt-then-resume and one deadline/watchdog trip."""
    assert len(chaos.TIER1_SEEDS) >= 10
    kinds = {chaos.make_schedule(s).kind for s in chaos.TIER1_SEEDS}
    assert kinds == set(chaos.FAMILIES)
    assert {"preempt_resume", "deadline"} <= kinds
    # Streaming-ingest coverage (ISSUE 4): >= 2 streaming schedules in tier-1
    assert {"stream_corrupt", "stream_hang"} <= kinds
    # Mid-stream retune coverage (ISSUE 6): the typed-or-equal invariant
    # must be exercised under oscillating autotuner knob motion
    assert "autotune_thrash" in kinds
    # Decode-wall coverage (ISSUE 7): corrupt snapshot shards must fall
    # back counted-and-bit-equal, and a SIGKILLed decode worker must
    # respawn counted — never a hung ring
    assert {"snapshot_corrupt", "decode_worker_kill"} <= kinds
    # Serving coverage (ISSUE 8): the typed-or-equal invariant extends to
    # the online path — slow clients, malformed requests, burst OOM
    assert set(chaos.SERVE_FAMILIES) <= kinds
    # Placement-search coverage (ISSUE 9): a mispredicted top-ranked plan
    # must step down the SEARCHED ranking typed + counted
    assert "plan_mispredict" in kinds
    # Spec-execution coverage (ISSUE 10): a mispredicted SPEC-SHARDED
    # (GSPMD-layout) top plan must step down counted and stay bit-equal
    # to the fault-free mesh run
    assert "spec_mispredict" in kinds
    # Wire-protocol coverage (ISSUE 12): a client disconnect mid-batch
    # must be counted with the batch still completing, and slow-loris
    # partial frames must never stall the accept loop or starve honest
    # connections
    assert {"wire_disconnect", "slow_loris"} <= kinds
    # Device-decode coverage (ISSUE 13): a damaged entropy-coded scan
    # under decode_mode="device" must become a typed, counted skip with
    # the rest of the batch surviving bit-equal — never silent wrong
    # pixels
    assert "jpeg_corrupt_entropy" in kinds
    # Profiler coverage (ISSUE 14): the HBM watermark sampler thread
    # dying mid-run must be a counted degradation with the run completing
    # bit-equal to an unprofiled run — observability may die, the
    # workload may not
    assert "profiler_crash" in kinds
    # Numerics-observatory coverage (ISSUE 15): a shifted request mix
    # against a drift-armed served engine must be counted
    # serve_output_drift with a postmortem, every answer bit-equal to an
    # unmonitored engine
    assert "output_drift" in kinds
    # Elastic-serving coverage (ISSUE 16): device loss mid-serve must
    # re-anchor every engine onto the surviving mesh with zero request
    # loss (counted mesh_reanchor), and a full-mesh-sharded checkpoint
    # must resume onto the survivors predictions-equal — never a silent
    # divergence, never a crash for a mesh the process still has
    assert "mesh_shrink" in kinds
    # Multi-host coverage (ISSUE 17): a serving HOST dying mid-flight
    # must be counted fleet_host_lost with its in-flight requests
    # reissued to survivors, the reduced group re-formed (dist_reform)
    # and every survivor re-anchored (host_reanchor, postmortem-linked)
    # — zero dropped requests, every answer bit-equal to the offline
    # oracle
    assert "host_loss" in kinds
    # Lifecycle coverage (ISSUE 18): a drifted served model must be
    # detected, warm-refit, validated, and hot-swapped with zero dropped
    # requests and post-swap answers bit-equal to an offline refit;
    # injected refit OOM / validation rejection / mid-swap kill must each
    # degrade typed+counted to the incumbent — never a silent wrong or
    # missing answer
    assert "drift_refit" in kinds
    # Native-entropy coverage (ISSUE 19): the C scan loop must be
    # indistinguishable from the Python pass — corrupt scans through the
    # native backend are the same typed counted skips with survivors
    # bit-equal to a forced-Python stream, and an unexpected native
    # failure degrades per-image counted, never a crash
    assert "native_entropy" in kinds
    # Fleet-observability coverage (ISSUE 20): a member SIGKILLed
    # mid-scrape must degrade the collector (obs_member_lost,
    # postmortem-linked), keep the fleet view monotone for survivors
    # with counters summed and p99 pooled from raw windows, and produce
    # ONE clock-aligned incident bundle holding every surviving member's
    # flight ring — with serving answers bit-equal to an uncollected
    # fleet
    assert "obs_capture" in kinds


def test_schedules_are_deterministic():
    for seed in chaos.TIER1_SEEDS:
        a, b = chaos.make_schedule(seed), chaos.make_schedule(seed)
        assert a.kind == b.kind and a.params == b.params


def test_deadline_names_the_phase(tmp_path):
    """The watchdog schedule's error must carry the phase name — a hang
    report that cannot say WHAT hung is barely better than the hang."""
    seed = next(
        s for s in chaos.TIER1_SEEDS
        if chaos.make_schedule(s).kind == "deadline"
    )
    r = chaos.run_schedule(seed, "mnist", tmpdir=str(tmp_path))
    assert r.error_type == "DeadlineExceeded"
    assert r.phase == "solve"


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_full_schedule_mnist():
    results = chaos.run_suite(chaos.FULL_SEEDS, workload="mnist")
    bad = [
        r.record()
        for r in results
        if not r.ok() or r.outcome != chaos.expected_outcome(r.fault)
    ]
    assert not bad, bad


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_full_schedule_cifar():
    results = chaos.run_suite(chaos.FULL_SEEDS[: len(chaos.FAMILIES)], workload="cifar")
    bad = [
        r.record()
        for r in results
        if not r.ok() or r.outcome != chaos.expected_outcome(r.fault)
    ]
    assert not bad, bad
