"""Workload-level streaming-ingest wiring (ISSUE 4 tentpole): the streaming
descriptor/featurize paths of VOCSIFTFisher, ImageNetSiftLcsFV and
RandomPatchCifar must produce features (and downstream predictions)
identical to the eager decode-everything-first paths on the same tar
fixture.

Images here are >= 36 px: the loaders' MIN_DIM rule (reference
ImageUtils.loadImage) rejects smaller ones, so a true-32px CIFAR JPEG tar
would decode to nothing — the streamed CIFAR fixtures use 48 px.
"""

import dataclasses
import io
import tarfile

import jax
import numpy as np
import pytest

from test_fisher_pipelines import (
    _class_image,
    _img_bytes,
    write_imagenet_tar,
    write_voc_tar,
)

from keystone_tpu.loaders.image_loaders import (
    _iter_tar_images,
    imagenet_loader,
    voc_loader,
)
from keystone_tpu.workloads.cifar_random_patch import (
    RandomCifarConfig,
    build_conv_pipeline,
    cifar_tar_label,
    featurize_chunked,
    featurize_stream,
    learn_filters,
)
from keystone_tpu.workloads.imagenet_sift_lcs_fv import (
    ImageNetSiftLcsFVConfig,
    ImageNetStreamSource,
    lcs_descriptor_buckets,
    sift_descriptor_buckets,
)
from keystone_tpu.workloads.voc_sift_fisher import (
    SIFTFisherConfig,
    VOCStreamSource,
    extract_sift_buckets,
)
from keystone_tpu.core.ingest import stream_batches
from keystone_tpu.loaders.cifar import LabeledImageBatch


def _buckets_equal(a: dict, b: dict):
    assert set(a) == set(b)
    for shape in a:
        idx_a, desc_a = a[shape]
        idx_b, desc_b = b[shape]
        np.testing.assert_array_equal(np.asarray(idx_a), np.asarray(idx_b))
        np.testing.assert_array_equal(np.asarray(desc_a), np.asarray(desc_b))


def test_voc_streaming_sift_buckets_equal_eager(tmp_path, rng):
    labels_csv = str(tmp_path / "labels.csv")
    open(labels_csv, "w").close()
    tar = str(tmp_path / "voc.tar")
    write_voc_tar(tar, labels_csv, 8, rng)
    conf = SIFTFisherConfig(desc_dim=8, vocab_size=4, sift_step_size=8)

    data = voc_loader(tar, labels_csv)
    eager = extract_sift_buckets(conf, data.images)

    src = VOCStreamSource(tar, labels_csv, batch_size=3)
    stream = extract_sift_buckets(conf, src.images)

    _buckets_equal(eager, stream)
    assert len(src) == len(data)
    assert src.labels == data.labels


def test_imagenet_streaming_branches_equal_eager(tmp_path, rng):
    labels_path = str(tmp_path / "labels.txt")
    write_imagenet_tar(str(tmp_path), labels_path, rng, classes=(0, 1), per_class=4)
    conf = ImageNetSiftLcsFVConfig(
        desc_dim=8, vocab_size=4, lcs_stride=8, lcs_border=16, lcs_patch=6
    )

    data = imagenet_loader(str(tmp_path), labels_path)
    eager_sift = sift_descriptor_buckets(conf, data.images)
    eager_lcs = lcs_descriptor_buckets(conf, data.images)

    src = ImageNetStreamSource(str(tmp_path), labels_path, batch_size=3)
    stream_sift = sift_descriptor_buckets(conf, src.images)
    # the second branch pass must observe the identical survivor order
    # (record_names asserts it — a drift would zip mismatched features)
    stream_lcs = lcs_descriptor_buckets(conf, src.images)

    _buckets_equal(eager_sift, stream_sift)
    _buckets_equal(eager_lcs, stream_lcs)
    assert len(src) == len(data)
    np.testing.assert_array_equal(src.labels, data.labels)


def _write_cifar_tar(path, n, rng, num_classes=4, size=48):
    labels = rng.integers(0, num_classes, n)
    with tarfile.open(path, "w") as tf:
        for i, c in enumerate(labels):
            data = _img_bytes(_class_image(rng, int(c), size=size))
            info = tarfile.TarInfo(f"{int(c)}/img_{i:04d}.jpg")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    return labels.astype(np.int32)


def test_cifar_featurize_stream_equals_chunked(tmp_path, rng):
    tar = str(tmp_path / "cifar48.tar")
    labels = _write_cifar_tar(tar, 12, rng)
    decoded = list(_iter_tar_images(tar, num_threads=1))
    images = np.stack([img for _, img in decoded])
    conf = RandomCifarConfig(
        num_filters=4, patch_steps=6, whitener_size=64, featurize_chunk=4
    )
    filters, whitener = learn_filters(conf, images)
    feat_fn = jax.jit(build_conv_pipeline(conf, filters, whitener).__call__)

    eager = np.asarray(featurize_chunked(feat_fn, images, conf.featurize_chunk))
    with stream_batches(tar, conf.featurize_chunk) as st:
        streamed, names = featurize_stream(feat_fn, st, conf.featurize_chunk)

    np.testing.assert_array_equal(streamed, eager)
    assert names == [name for name, _ in decoded]
    np.testing.assert_array_equal(
        np.asarray([cifar_tar_label(n) for n in names], np.int32), labels
    )


def test_cifar_tar_stream_loader_bit_identical_to_eager(tmp_path, rng):
    """Streamed TRAIN path (ISSUE 9 satellite, ROADMAP carry-over): the
    resident train subset decoded through core.ingest — and through the
    snapshot cache on a warm repeat — must equal the eager tar loader
    bit-for-bit: same images, same labels, same (tar member) order."""
    from keystone_tpu.workloads.cifar_random_patch import (
        cifar_tar_loader,
        cifar_tar_stream_loader,
    )
    from keystone_tpu.workloads.fv_common import stream_config_from_flags

    tar = str(tmp_path / "cifar48.tar")
    _write_cifar_tar(tar, 11, rng)  # odd count: a ragged final batch
    eager = cifar_tar_loader(tar)
    streamed = cifar_tar_stream_loader(tar, batch=4)
    np.testing.assert_array_equal(streamed.images, eager.images)
    np.testing.assert_array_equal(streamed.labels, eager.labels)

    # Snapshot-cache path: cold pass materializes, warm pass streams the
    # shards at IO speed — both bit-identical to the eager loader.
    snap = str(tmp_path / "snap")
    cfg = lambda: stream_config_from_flags(snapshot_dir=snap)  # noqa: E731
    cold = cifar_tar_stream_loader(tar, batch=4, config=cfg())
    warm = cifar_tar_stream_loader(tar, batch=4, config=cfg())
    np.testing.assert_array_equal(cold.images, eager.images)
    np.testing.assert_array_equal(warm.images, eager.images)
    np.testing.assert_array_equal(warm.labels, eager.labels)


def test_cifar_run_from_streamed_train_matches_eager(tmp_path, rng):
    """RandomPatchCifar fit from the STREAMED train split: filter learning
    and the solve see the same resident subset, so predictions equal the
    eager-loaded run's bit-for-bit."""
    from keystone_tpu.workloads.cifar_random_patch import (
        cifar_tar_loader,
        cifar_tar_stream_loader,
        run,
    )

    tar = str(tmp_path / "cifar48.tar")
    _write_cifar_tar(tar, 16, rng)
    conf = RandomCifarConfig(
        num_filters=4,
        patch_steps=6,
        lam=10.0,
        whitener_size=64,
        featurize_chunk=8,
        num_classes=4,
    )
    eager_train = cifar_tar_loader(tar)
    streamed_train = cifar_tar_stream_loader(tar, batch=8)
    base = run(conf, eager_train, eager_train)
    res = run(conf, streamed_train, eager_train)
    np.testing.assert_array_equal(
        res["test_predictions"], base["test_predictions"]
    )


@pytest.mark.slow
def test_cifar_run_with_stream_test_tar_matches_eager(tmp_path, rng):
    """Full RandomPatchCifar run with the streamed test path: predictions
    must equal the eager run's bit-for-bit (same model, same features)."""
    from keystone_tpu.workloads.cifar_random_patch import run

    tar = str(tmp_path / "cifar48.tar")
    labels = _write_cifar_tar(tar, 20, rng)
    decoded = list(_iter_tar_images(tar, num_threads=1))
    images = np.stack([img for _, img in decoded])
    train = LabeledImageBatch(images, labels)
    conf = RandomCifarConfig(
        num_filters=4,
        patch_steps=6,
        lam=10.0,
        whitener_size=64,
        featurize_chunk=8,
        num_classes=4,
    )
    base = run(conf, train, train)
    res = run(dataclasses.replace(conf, stream_test_tar=tar), train, train)
    np.testing.assert_array_equal(
        res["test_predictions"], base["test_predictions"]
    )


def test_cifar_stream_featurized_snapshot_roundtrip(tmp_path, rng, monkeypatch):
    """RandomPatchCifar --streamTestTar --snapshotDir under
    KEYSTONE_SNAPSHOT_MODE=featurized: the first run materializes the conv
    FEATURES keyed by the fitted featurizer's digest; a rerun serves them
    from the shards and must score bit-identically.  A different model
    (new filters) must MISS the cache, never replay stale features."""
    from keystone_tpu.core import snapshot as ksnap
    from keystone_tpu.workloads.cifar_random_patch import run

    monkeypatch.setenv("KEYSTONE_SNAPSHOT_MODE", "featurized")
    tar = str(tmp_path / "cifar48.tar")
    labels = _write_cifar_tar(tar, 12, rng)
    decoded = list(_iter_tar_images(tar, num_threads=1))
    images = np.stack([img for _, img in decoded])
    train = LabeledImageBatch(images, labels)
    snap_root = str(tmp_path / "cache")
    conf = RandomCifarConfig(
        num_filters=4,
        patch_steps=6,
        lam=10.0,
        whitener_size=64,
        featurize_chunk=4,
        num_classes=4,
        stream_test_tar=tar,
        snapshot_dir=snap_root,
    )
    cold = run(conf, train, train)
    committed = [
        s for s in ksnap.list_snapshots(snap_root)
        if s.get("valid") and s["mode"] == "featurized"
    ]
    assert len(committed) == 1
    warm = run(conf, train, train)
    np.testing.assert_array_equal(
        warm["test_predictions"], cold["test_predictions"]
    )
    # a refit with different filters keys a NEW snapshot (digest moved)
    refit = run(dataclasses.replace(conf, num_filters=6), train, train)
    assert refit["test_predictions"].shape[0] == len(labels)
    keys = {
        s["key"]
        for s in ksnap.list_snapshots(snap_root)
        if s.get("valid") and s["mode"] == "featurized"
    }
    assert len(keys) == 2
