"""RandomPatchCifar end-to-end on synthetic CIFAR binaries (the reference
exercises loaders on miniature datasets in test resources, SURVEY §4.6)."""

import numpy as np
import pytest

from keystone_tpu.loaders.cifar import RECORD_BYTES, cifar_loader
from keystone_tpu.workloads.cifar_random_patch import RandomCifarConfig, run


def write_synthetic_cifar(path, n, rng, num_classes=4, base=None):
    """Class-colored blobs + noise: separable but not trivial.  ``base`` (the
    class color palette) must be shared between train and test splits."""
    labels = rng.integers(0, num_classes, n).astype(np.uint8)
    if base is None:
        base = rng.uniform(40, 215, (num_classes, 3))
    recs = np.zeros((n, RECORD_BYTES), np.uint8)
    for i in range(n):
        img = base[labels[i]][:, None, None] + rng.normal(0, 25, (3, 32, 32))
        # add class-dependent spatial structure
        yy, xx = np.mgrid[0:32, 0:32]
        img[labels[i] % 3] += 30 * np.sin(xx / (2.0 + labels[i]))
        recs[i, 0] = labels[i]
        recs[i, 1:] = np.clip(img, 0, 255).astype(np.uint8).reshape(-1)
    recs.tofile(path)
    return labels


class TestCifarLoader:
    def test_roundtrip(self, tmp_path, rng):
        path = str(tmp_path / "train.bin")
        labels = write_synthetic_cifar(path, 10, rng)
        batch = cifar_loader(path)
        assert batch.images.shape == (10, 32, 32, 3)
        assert batch.images.dtype == np.float32
        np.testing.assert_array_equal(batch.labels, labels.astype(np.int32))
        assert batch.images.min() >= 0.0 and batch.images.max() <= 255.0

    def test_rejects_truncated_file(self, tmp_path):
        path = str(tmp_path / "bad.bin")
        np.zeros(RECORD_BYTES + 7, np.uint8).tofile(path)
        with pytest.raises(ValueError):
            cifar_loader(path)


class TestRandomPatchCifarE2E:
    def test_learns_synthetic_classes(self, tmp_path, rng):
        train_path = str(tmp_path / "train.bin")
        test_path = str(tmp_path / "test.bin")
        palette = rng.uniform(40, 215, (4, 3))
        write_synthetic_cifar(train_path, 300, rng, base=palette)
        write_synthetic_cifar(test_path, 100, rng, base=palette)

        conf = RandomCifarConfig(
            num_filters=16,
            patch_size=6,
            patch_steps=2,
            pool_size=14,
            pool_stride=13,
            alpha=0.25,
            lam=10.0,
            whitener_size=2000,
            featurize_chunk=64,
            num_classes=4,
        )
        results = run(conf, cifar_loader(train_path), cifar_loader(test_path))
        # chance is 75% error; separable color blobs should be nearly solved
        assert results["test_error"] < 15.0, results
        assert results["train_error"] < 10.0, results
