"""Placement search (core.autoshard, ISSUE 9): candidate enumeration from
mesh factorizations and avals, the zero-cost batch preflight prune, the
analytic-prior x learned-calibration cost model, margin-bucketed ranking
(untrained search == hand ladder bit-for-bit), the plan-outcome log, and
the ranked run_ladder execution contract — plus the parallel/mesh.py
enumeration edge cases and tools/plan_view.py rendering.
"""

import json
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.core import autoshard
from keystone_tpu.core import memory as kmem
from keystone_tpu.core import optimize as kopt
from keystone_tpu.parallel.mesh import (
    enumerate_mesh_shapes,
    enumerate_meshes,
    make_mesh,
    mesh_desc,
    reduced_mesh,
)
from keystone_tpu.solvers.block import BlockLeastSquaresEstimator

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
)
import plan_view  # noqa: E402  (tools/plan_view.py)


# -- parallel/mesh.py enumeration edge cases ----------------------------------


def test_enumerate_mesh_shapes_one_device():
    assert enumerate_mesh_shapes(1) == [(1, 1)]


def test_enumerate_mesh_shapes_prime_count():
    # A prime count has exactly the two degenerate factorizations.
    assert enumerate_mesh_shapes(7) == [(7, 1), (1, 7)]


def test_enumerate_mesh_shapes_composite_data_major_descending():
    assert enumerate_mesh_shapes(8) == [(8, 1), (4, 2), (2, 4), (1, 8)]
    for n in (2, 6, 12):
        shapes = enumerate_mesh_shapes(n)
        assert all(d * m == n for d, m in shapes)
        assert [d for d, _ in shapes] == sorted(
            (d for d, _ in shapes), reverse=True
        )


def test_enumerate_mesh_shapes_rejects_zero():
    with pytest.raises(ValueError):
        enumerate_mesh_shapes(0)


def test_reduced_mesh_on_already_collapsed_mesh_is_none():
    # Pure data-parallel: nothing left to collapse — the ladder's next
    # rung is the single-device floor, not another mesh.
    collapsed = make_mesh(data=8, model=1)
    assert reduced_mesh(collapsed) is None
    # And collapsing a real (data, model) mesh yields the collapsed form
    # whose own reduction is again None.
    full = make_mesh(data=4, model=2)
    rm = reduced_mesh(full)
    assert mesh_desc(rm) == "8x1"
    assert reduced_mesh(rm) is None


def test_enumerate_meshes_deterministic_over_fixed_devices():
    import jax

    devices = jax.devices()
    a = enumerate_meshes(devices)
    b = enumerate_meshes(devices)
    assert [mesh_desc(m) for m in a] == [mesh_desc(m) for m in b]
    assert [mesh_desc(m) for m in a] == [
        f"{d}x{m}" for d, m in enumerate_mesh_shapes(len(devices))
    ]
    # Same devices in the same order for every candidate mesh.
    for m in a:
        assert list(m.devices.flat) == list(devices)


# -- sharding-spec enumeration from avals -------------------------------------


def test_spec_candidates_generated_from_aval_dims():
    aval = jnp.zeros((8, 6), jnp.float32)
    specs = {
        c["spec"]: c["per_chip_bytes"]
        for c in autoshard.spec_candidates(aval, {"data": 2, "model": 3})
    }
    total = 8 * 6 * 4
    # replicated always legal; data over any dim divisible by 2; model
    # over any dim divisible by 3 — all from the aval, no hand list.
    assert specs == {
        "replicated": total,
        "data@dim0": total // 2,
        "data@dim1": total // 2,
        "model@dim1": total // 3,
    }


def test_best_spec_minimizes_per_chip_bytes_and_replicates_when_odd():
    aval = jnp.zeros((8, 6), jnp.float32)
    best = autoshard.best_spec(aval, {"data": 4, "model": 2})
    assert best["spec"] == "data@dim0"
    assert best["per_chip_bytes"] == 8 * 6 * 4 // 4
    # Nothing divides a prime dim: replicated is the only legal spec.
    odd = jnp.zeros((7,), jnp.float32)
    assert autoshard.best_spec(odd, {"data": 4, "model": 2})["spec"] == (
        "replicated"
    )


# -- spec strings -> executable layouts (ISSUE 10) -----------------------------


def test_spec_pspec_lowers_every_vocabulary_entry():
    from jax.sharding import PartitionSpec as P

    assert autoshard.spec_pspec("replicated", 2) == P(None, None)
    assert autoshard.spec_pspec("data@dim0", 2) == P("data", None)
    assert autoshard.spec_pspec("model@dim1", 2) == P(None, "model")
    assert autoshard.spec_pspec("model@dim2", 3) == P(None, None, "model")
    with pytest.raises(ValueError):
        autoshard.spec_pspec("bogus", 2)
    with pytest.raises(ValueError):
        autoshard.spec_pspec("data@dim5", 2)  # names a missing dim


def test_spec_sharding_places_arrays_per_spec(mesh42):
    import jax

    a = jnp.zeros((8, 6), jnp.float32)
    sharded = jax.device_put(
        a, autoshard.spec_sharding("data@dim0", mesh42, 2)
    )
    # data axis 4: each chip holds a [2, 6] shard
    shard_shape = sharded.sharding.shard_shape((8, 6))
    assert shard_shape == (2, 6)
    rep = jax.device_put(
        a, autoshard.spec_sharding("replicated", mesh42, 2)
    )
    assert rep.sharding.shard_shape((8, 6)) == (8, 6)


def test_spec_chip_bytes_matches_enumeration():
    mesh_shape = {"data": 2, "model": 3}
    aval = jnp.zeros((8, 6), jnp.float32)
    for c in autoshard.spec_candidates(aval, mesh_shape):
        assert autoshard.spec_chip_bytes(
            (8, 6), jnp.float32, c["spec"], mesh_shape
        ) == c["per_chip_bytes"]
    with pytest.raises(ValueError):
        autoshard.spec_chip_bytes((7,), jnp.float32, "data@dim0", mesh_shape)


def test_spec_candidates_bytes_lower_bound_of_compiled_layouts(mesh42):
    """The invariant the preflight pruning depends on (ISSUE 10 satellite):
    for every enumerated spec, the analytic per-chip bytes are a true
    LOWER bound of what the compiled admission charges for that executed
    layout (max of the analytic shard division and XLA's own
    memory_analysis, exactly as plan_program's mesh mode charges)."""
    import jax

    shapes = [(64, 48), (32, 8, 6)]
    for shape in shapes:
        aval = jnp.zeros(shape, jnp.float32)
        for c in autoshard.spec_candidates(aval, dict(mesh42.shape)):
            sharding = autoshard.spec_sharding(c["spec"], mesh42, len(shape))
            s = jax.ShapeDtypeStruct(shape, jnp.float32, sharding=sharding)
            compiled = jax.jit(lambda a: a * 2.0).lower(s).compile()
            ma = compiled.memory_analysis()
            charged = max(
                kmem.shard_bytes(s), int(ma.argument_size_in_bytes)
            )
            assert c["per_chip_bytes"] <= charged, (
                shape, c, charged, int(ma.argument_size_in_bytes),
            )


def test_spec_tag_compact():
    assert autoshard.spec_tag(None) == "default"
    assert autoshard.spec_tag(
        {"models": "replicated", "labels": "model@dim1"}
    ) == "labels=model@dim1,models=rep"


# -- the zero-cost batch preflight --------------------------------------------


def test_plan_bytes_admits_and_denies_analytically():
    ok = kmem.plan_bytes(
        "t", argument_bytes=100, temp_bytes=50, budget=1000
    )
    assert ok.admitted and not ok.analyzed  # no compile happened
    deny = kmem.plan_bytes("t", argument_bytes=2000, budget=1000)
    assert not deny.admitted
    assert "DENIED" in deny.reason
    assert deny.total_bytes == 2000


def test_plan_bytes_without_budget_skips_admission():
    plan = kmem.plan_bytes("t", argument_bytes=1 << 50, budget=None)
    assert plan.admitted
    assert "skipped" in plan.reason


def test_plan_batch_turns_planner_crash_into_deny():
    out = kmem.plan_batch([
        ("good", lambda: kmem.plan_bytes("good", argument_bytes=1, budget=10)),
        ("bad", lambda: (_ for _ in ()).throw(RuntimeError("boom"))),
    ])
    assert out["good"].admitted
    assert not out["bad"].admitted
    assert "boom" in out["bad"].reason


# -- fingerprints and the plan-outcome log ------------------------------------


def test_fingerprint_stable_and_shape_sensitive():
    a = autoshard.fingerprint("bcd", 100, 10, "f32")
    assert a == autoshard.fingerprint("bcd", 100, 10, "f32")
    assert a != autoshard.fingerprint("bcd", 200, 10, "f32")
    assert len(a) == 16


def _log_record(fp, cand, predicted, measured, outcome="ok"):
    return {
        "fingerprint": fp, "label": "t", "candidate": cand,
        "predicted_seconds": predicted, "measured_seconds": measured,
        "outcome": outcome, "devices": "cpu x1", "ts": 0.0,
    }


def test_outcome_log_roundtrip_and_calibration(tmp_path, monkeypatch):
    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, path)
    autoshard.clear_outcome_cache()
    try:
        fp = "f" * 16
        for _ in range(autoshard.MIN_TRAIN - 1):
            autoshard.append_outcome(_log_record(fp, "a", 1.0, 3.0))
        autoshard.clear_outcome_cache()
        # Below MIN_TRAIN: the analytic prior stands (factor 1.0).
        factor, n = autoshard.calibration(fp, "a")
        assert (factor, n) == (1.0, autoshard.MIN_TRAIN - 1)
        autoshard.append_outcome(_log_record(fp, "a", 1.0, 3.0))
        autoshard.clear_outcome_cache()
        factor, n = autoshard.calibration(fp, "a")
        assert n == autoshard.MIN_TRAIN
        assert factor == pytest.approx(3.0)
        # OOM outcomes never train the ratio; a torn tail line is skipped.
        autoshard.append_outcome(_log_record(fp, "a", 1.0, 9.0, outcome="oom"))
        with open(path, "a") as f:
            f.write('{"torn": ')
        autoshard.clear_outcome_cache()
        assert autoshard.calibration(fp, "a")[0] == pytest.approx(3.0)
    finally:
        autoshard.clear_outcome_cache()


def test_outcome_log_disabled_by_env(monkeypatch):
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, "off")
    assert autoshard.plan_log_path() is None
    autoshard.append_outcome({"x": 1})  # must be a no-op, not a crash
    assert autoshard.load_outcomes() == []


def test_outcome_log_read_once_per_process(tmp_path, monkeypatch):
    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, path)
    autoshard.clear_outcome_cache()
    try:
        assert autoshard.load_outcomes() == []
        # Outcomes appended DURING the process train the NEXT process: the
        # cached (empty) read stands, so a ranking can never flip between
        # a baseline and a comparison fit mid-process.
        autoshard.append_outcome(_log_record("a" * 16, "a", 1.0, 2.0))
        assert autoshard.load_outcomes() == []
        autoshard.clear_outcome_cache()
        assert len(autoshard.load_outcomes()) == 1
    finally:
        autoshard.clear_outcome_cache()


# -- search: prune, score, rank -----------------------------------------------


def _mk_cand(name, prior, dispatches, floor=False, hand=True, arg_bytes=0):
    def run(_plan, name=name):
        return f"{name}:ran"

    return autoshard.Candidate(
        name, "fused",
        plan=lambda name=name: kmem.MemoryPlan(
            label=name, admitted=True, reason="test"
        ),
        run=run,
        hints={"dispatches": dispatches, "arg_bytes": arg_bytes},
        prior_rank=prior, floor=floor, hand=hand,
    )


_FP = "0123456789abcdef"


def _search(cands, budget=kmem._UNSET):
    # Fixed CostModel: device-independent predicted seconds (1 ms per
    # dispatch), so the ranking assertions hold on any test platform.
    return autoshard.search(
        "t", cands, fingerprint=_FP, budget=budget, model=kopt.CostModel()
    )


def test_untrained_search_keeps_hand_order_within_margin():
    # b's analytic prior is ~1.4x better than a's — inside the 4x cold
    # margin, so the proven hand order stands (the bit-identical bar).
    plan = _search([_mk_cand("a", 0, 10), _mk_cand("b", 1, 7)])
    assert plan.ranking == ["a", "b"]
    assert not plan.trained
    assert plan.margin == autoshard.UNTRAINED_MARGIN


def test_untrained_search_reorders_on_decisive_analytic_advantage():
    # c is 10x faster analytically — clears the cold margin.
    plan = _search([_mk_cand("a", 0, 10), _mk_cand("c", 1, 1)])
    assert plan.ranking == ["c", "a"]


def test_margin_is_relative_not_bucketed():
    # 17 vs 15 dispatches: a 1.13x gap that straddles a power-of-4
    # boundary (0.017s vs 0.015s around 4^-3) — absolute log buckets
    # would split them and reorder; the relative margin must not.
    plan = _search([_mk_cand("a", 0, 17), _mk_cand("b", 1, 15)])
    assert plan.ranking == ["a", "b"]


def test_calibration_falls_back_to_program_median(tmp_path, monkeypatch):
    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, path)
    autoshard.clear_outcome_cache()
    try:
        fp = "e" * 16
        for _ in range(autoshard.MIN_TRAIN):
            autoshard.append_outcome(_log_record(fp, "a", 1.0, 3.0))
        autoshard.clear_outcome_cache()
        # "b" never ran: it inherits the PROGRAM-level median factor but
        # reports 0 direct samples (the pooled fallback must not count as
        # trained-ness for the tight margin).
        factor, n = autoshard.calibration(fp, "b")
        assert n == 0
        assert factor == pytest.approx(3.0)
    finally:
        autoshard.clear_outcome_cache()


def test_one_sided_training_cannot_flip_toward_unmeasured_plan(
    tmp_path, monkeypatch
):
    # Equal analytic priors; the chosen plan "a" trains to a 5x honest
    # slowdown while "b" never ran.  The program-median fallback gives
    # "b" the SAME constant factor, so the proven hand order stands —
    # one-sided measurements must never hand the ranking to whatever
    # never ran.
    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, path)
    autoshard.clear_outcome_cache()
    try:
        for _ in range(autoshard.MIN_TRAIN):
            autoshard.append_outcome(_log_record(_FP, "a", 0.01, 0.05))
        autoshard.clear_outcome_cache()
        plan = _search([_mk_cand("a", 0, 10), _mk_cand("b", 1, 10)])
        assert plan.ranking == ["a", "b"]
        assert not plan.trained  # "b" has no DIRECT measurements
        assert plan.candidate("b").calibration == pytest.approx(5.0)
    finally:
        autoshard.clear_outcome_cache()


def test_floor_pinned_last_regardless_of_score():
    plan = _search([
        _mk_cand("a", 0, 10),
        _mk_cand("cheap_floor", 1, 1, floor=True),
    ])
    assert plan.ranking == ["a", "cheap_floor"]
    rec = plan.candidate("cheap_floor")
    assert "floor" in rec.reason


def test_pruned_hand_candidate_stays_in_execution_order():
    # The over-budget hand candidate is denied for free by the analytic
    # preflight but keeps its hand position in the walk, so the ladder
    # records the denial exactly where the hand contract puts it.
    plan = _search(
        [
            _mk_cand("big", 0, 1, arg_bytes=10_000),
            _mk_cand("small", 1, 10),
        ],
        budget=1000,
    )
    assert plan.ranking == ["big", "small"]
    big = plan.candidate("big")
    assert big.pruned and big.outcome == "denied"
    assert "DENIED" in big.reason
    assert "big" in plan.analytic_plans  # cached deny, never re-planned


def test_pruned_extra_candidate_dropped_from_ranking():
    plan = _search(
        [
            _mk_cand("hand", 0, 10),
            _mk_cand("extra", 1, 1, hand=False, arg_bytes=10_000),
        ],
        budget=1000,
    )
    assert plan.ranking == ["hand"]
    # ...but the table still shows why the enumerated candidate lost.
    extra = plan.candidate("extra")
    assert extra.pruned and extra.outcome == "denied"


def test_search_deterministic_same_fingerprint_same_ranking():
    cands = lambda: [  # noqa: E731
        _mk_cand("a", 0, 10), _mk_cand("b", 1, 7), _mk_cand("c", 2, 2),
        _mk_cand("floor", 3, 30, floor=True),
    ]
    a, b = _search(cands()), _search(cands())
    assert a.ranking == b.ranking
    assert a.fingerprint == b.fingerprint
    assert [c.record() for c in a.candidates] == [
        c.record() for c in b.candidates
    ]


def test_trained_calibration_reorders_past_margin(tmp_path, monkeypatch):
    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, path)
    autoshard.clear_outcome_cache()
    try:
        # Equal analytic priors; measurements say b is 100x faster.  Once
        # every survivor is calibrated the margin tightens to
        # TRAINED_MARGIN and b takes the head.
        for _ in range(autoshard.MIN_TRAIN):
            autoshard.append_outcome(_log_record(_FP, "a", 0.01, 0.01))
            autoshard.append_outcome(_log_record(_FP, "b", 0.01, 0.0001))
        autoshard.clear_outcome_cache()
        plan = _search([_mk_cand("a", 0, 10), _mk_cand("b", 1, 10)])
        assert plan.trained
        assert plan.margin == autoshard.TRAINED_MARGIN
        assert plan.ranking == ["b", "a"]
        rec = plan.candidate("b")
        assert rec.samples == autoshard.MIN_TRAIN
        assert rec.calibration == pytest.approx(0.01)
    finally:
        autoshard.clear_outcome_cache()


# -- run_search: the ranked execution contract --------------------------------


def test_run_search_hand_mode_walks_hand_ladder_without_placement():
    report = kmem.FitReport(label="t")
    out = autoshard.run_search(
        "t",
        [_mk_cand("a", 0, 10), _mk_cand("x", 1, 1, hand=False)],
        report, fingerprint=_FP, plan=False,
    )
    assert out == "a:ran"
    assert report.placement is None  # the hand ladder leaves no search


def test_run_search_executes_ranked_head_and_records_placement():
    report = kmem.FitReport(label="t")
    out = autoshard.run_search(
        "t", [_mk_cand("a", 0, 10), _mk_cand("c", 1, 1)],
        report, fingerprint=_FP, plan=True,
        model=kopt.CostModel(),
    )
    assert out == "c:ran"  # decisive analytic advantage took the head
    assert report.chosen == "c"
    p = report.placement
    assert p["chosen"] == "c"
    assert p["ranking"][0] == "c"
    chosen = [c for c in p["candidates"] if c["name"] == "c"][0]
    assert chosen["outcome"] == "ok"
    assert chosen["measured_seconds"] is not None


def test_run_search_forced_ranking_keeps_floor_last():
    report = kmem.FitReport(label="t")
    out = autoshard.run_search(
        "t",
        [
            _mk_cand("a", 0, 10),
            _mk_cand("b", 1, 10),
            _mk_cand("floor", 2, 10, floor=True),
        ],
        report, fingerprint=_FP, plan=["floor", "b"],
        model=kopt.CostModel(),
    )
    # The override names the floor first, but the floor is the backstop:
    # it stays pinned last and the first non-floor named plan runs.
    assert out == "b:ran"
    assert report.placement["ranking"] == ["b", "a", "floor"]


def test_run_search_rejects_bad_plan_arg():
    with pytest.raises(TypeError):
        autoshard.run_search(
            "t", [_mk_cand("a", 0, 1)],
            kmem.FitReport(label="t"), fingerprint=_FP, plan=42,
        )


def test_run_search_runtime_oom_steps_down_ranked_list_counted():
    from keystone_tpu.core.resilience import counters

    calls = {"a": 0}

    def dying_run(_plan):
        calls["a"] += 1
        raise RuntimeError("RESOURCE_EXHAUSTED: injected")

    top = autoshard.Candidate(
        "a", "fused",
        plan=lambda: kmem.MemoryPlan(label="a", admitted=True, reason="test"),
        run=dying_run, hints={"dispatches": 1}, prior_rank=0,
    )
    report = kmem.FitReport(label="t")
    before = counters.get("autoshard_stepdown")
    out = autoshard.run_search(
        "t", [top, _mk_cand("b", 1, 10)], report,
        fingerprint=_FP, plan=True, model=kopt.CostModel(),
    )
    assert out == "b:ran"
    assert calls["a"] == 1
    assert report.chosen == "b"
    assert "a" in report.oom_retries
    assert counters.get("autoshard_stepdown") - before >= 1
    p = report.placement
    assert [c for c in p["candidates"] if c["name"] == "a"][0]["outcome"] == (
        "oom"
    )


def test_run_search_typed_failure_not_recorded_as_oom():
    # A non-OOM failure propagates (run_ladder's contract) and the audit
    # trail must say "error", not fabricate a memory misprediction.
    def dying_run(_plan):
        raise ValueError("bad data, not memory")

    top = autoshard.Candidate(
        "a", "fused",
        plan=lambda: kmem.MemoryPlan(label="a", admitted=True, reason="test"),
        run=dying_run, hints={"dispatches": 1}, prior_rank=0,
    )
    report = kmem.FitReport(label="t")
    with pytest.raises(ValueError):
        autoshard.run_search(
            "t", [top], report, fingerprint=_FP, plan=True,
            model=kopt.CostModel(),
        )
    rec = [c for c in report.placement["candidates"] if c["name"] == "a"][0]
    assert rec["outcome"] == "error"


# -- solver-level integration -------------------------------------------------


def _small_problem(rng, n=256, d=128, k=4):
    x = jnp.asarray(rng.standard_normal((n, d), dtype=np.float32))
    y = jnp.asarray(
        2.0 * np.eye(k, dtype=np.float32)[rng.integers(0, k, n)] - 1.0
    )
    return x, y


def test_fit_searched_bit_identical_to_hand_ladder(rng):
    x, y = _small_problem(rng)
    hand = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0).fit(
        x, y, plan=False
    )
    est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0)
    searched = est.fit(x, y, plan=True)
    np.testing.assert_array_equal(np.asarray(hand.b), np.asarray(searched.b))
    for a, b in zip(hand.xs, searched.xs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    p = est.last_fit_report.placement
    assert p is not None
    assert p["chosen"] == est.last_fit_report.chosen
    assert p["ranking"], p
    # The searched table carries a scored or denied rationale per row.
    assert all(c["reason"] for c in p["candidates"])


def test_fit_searched_plan_deterministic_under_fixed_devices(rng):
    x, y = _small_problem(rng)

    def one():
        est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0)
        est.fit(x, y, plan=True)
        return est.last_fit_report.placement

    a, b = one(), one()
    assert a["fingerprint"] == b["fingerprint"]
    assert a["ranking"] == b["ranking"]
    assert [c["name"] for c in a["candidates"]] == [
        c["name"] for c in b["candidates"]
    ]


def test_fit_plan_replay_accepts_placement_plan_and_name_list(rng):
    x, y = _small_problem(rng)
    est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0)
    base = est.fit(x, y, plan=True)
    prev = est.last_fit_report.placement

    est2 = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0)
    replay = est2.fit(x, y, plan=list(prev["ranking"]))
    assert est2.last_fit_report.placement["ranking"] == prev["ranking"]
    np.testing.assert_array_equal(np.asarray(base.b), np.asarray(replay.b))


def test_fit_mesh_search_enumerates_factorizations_deterministically(rng):
    import jax

    n_dev = len(jax.devices())
    if n_dev < 4:
        pytest.skip("needs >= 4 devices (conftest forces 8 CPU devices)")
    mesh = make_mesh(data=n_dev // 2, model=2)
    x, y = _small_problem(rng, n=256, d=128, k=4)

    def one():
        est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0, mesh=mesh)
        est.fit(x, y, plan=True)
        return est.last_fit_report

    rep = one()
    p = rep.placement
    # Every (data, model) factorization of the device set is a candidate,
    # plus the single-device floor.
    meshes = {
        f"{c['mesh']['data']}x{c['mesh']['model']}"
        for c in p["candidates"] if c["mesh"]
    }
    assert meshes == {
        f"{d}x{m}" for d, m in enumerate_mesh_shapes(n_dev)
    }
    assert p["ranking"][-1] == "single_device"  # the floor stays last
    # Determinism under the fixed device set: same fingerprint, same
    # ranking, run to run.
    rep2 = one()
    assert rep2.placement["fingerprint"] == p["fingerprint"]
    assert rep2.placement["ranking"] == p["ranking"]


def test_fit_report_record_carries_placement(rng):
    x, y = _small_problem(rng)
    est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0)
    est.fit(x, y, plan=True)
    rec = est.last_fit_report.record()
    assert rec["placement"] is not None
    json.dumps(rec)  # the whole audit trail must stay JSON-able


# -- executed sharding specs (ISSUE 10) ---------------------------------------


def test_mesh_search_enumerates_spec_candidates(rng):
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices (conftest forces 8 CPU devices)")
    mesh = make_mesh(data=len(jax.devices()) // 2, model=2)
    x, y = _small_problem(rng, n=256, d=128, k=4)
    est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0, mesh=mesh)
    est.fit(x, y, plan=True)
    p = est.last_fit_report.placement
    spec_cands = [c for c in p["candidates"] if c.get("specs")]
    assert spec_cands, "no spec-assignment candidates enumerated"
    # the advertised layouts include the wide-class one on the hand mesh
    tags = {(str(c["mesh"]), str(c["specs"])) for c in spec_cands}
    assert any("model@dim1" in t for _m, t in tags)
    # spec candidates are extras: the untrained head stays the hand rung
    # (default layout), so the search is bit-compatible cold
    head = [c for c in p["candidates"] if c["name"] == p["ranking"][0]][0]
    assert head["specs"] is None
    # every candidate row carries a calibration source for the audit trail
    assert all(
        c["calibration_source"] in ("direct", "model", "pooled", "none")
        for c in p["candidates"] if not c["pruned"]
    )


def test_forced_spec_plan_executes_layout_bit_identical(rng):
    """A spec-assignment candidate EXECUTES its NamedSharding layout (not
    just byte accounting) and, on the same mesh shape, reproduces the
    default layout's model bit-for-bit — layout changes placement, never
    results."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    mesh = make_mesh(data=len(jax.devices()) // 2, model=2)
    x, y = _small_problem(rng, n=256, d=128, k=4)
    est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0, mesh=mesh)
    base = est.fit(x, y, plan=True)
    p = est.last_fit_report.placement
    head_mesh = [
        c for c in p["candidates"] if c["name"] == p["ranking"][0]
    ][0]["mesh"]
    spec_names = [
        c["name"] for c in p["candidates"]
        if c.get("specs") and c["mesh"] == head_mesh
    ]
    assert spec_names
    for name in spec_names:
        est2 = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0, mesh=mesh)
        replay = est2.fit(x, y, plan=[name])
        assert est2.last_fit_report.chosen == name
        chosen = [
            c for c in est2.last_fit_report.placement["candidates"]
            if c["name"] == name
        ][0]
        assert chosen["outcome"] == "ok" and chosen["specs"]
        np.testing.assert_array_equal(
            np.asarray(base.b), np.asarray(replay.b)
        )
        for a, b in zip(base.xs, replay.xs):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bwls_mesh_search_spec_candidates_execute(rng):
    import jax

    from keystone_tpu.solvers.weighted import (
        BlockWeightedLeastSquaresEstimator,
    )

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    mesh = make_mesh(data=len(jax.devices()) // 2, model=2)
    x, _ = _small_problem(rng, n=256, d=128, k=4)
    y = jnp.asarray(
        2.0 * np.eye(8, dtype=np.float32)[rng.integers(0, 8, 256)] - 1.0
    )
    est = BlockWeightedLeastSquaresEstimator(64, 1, 0.5, 0.5, mesh=mesh)
    base = est.fit(x, y, plan=True)
    p = est.last_fit_report.placement
    head_mesh = [
        c for c in p["candidates"] if c["name"] == p["ranking"][0]
    ][0]["mesh"]
    wide = [
        c["name"] for c in p["candidates"]
        if c.get("specs") == {"labels": "model@dim1"}
        and c["mesh"] == head_mesh
    ]
    assert wide, "wide-class (model-axis-sharded labels) candidate missing"
    est2 = BlockWeightedLeastSquaresEstimator(64, 1, 0.5, 0.5, mesh=mesh)
    replay = est2.fit(x, y, plan=[wide[0]])
    assert est2.last_fit_report.chosen == wide[0]
    for a, b in zip(base.xs, replay.xs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_specs_env_disables_spec_dimension(rng, monkeypatch):
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    monkeypatch.setenv(autoshard.SPECS_ENV, "0")
    mesh = make_mesh(data=len(jax.devices()) // 2, model=2)
    x, y = _small_problem(rng, n=256, d=128, k=4)
    est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0, mesh=mesh)
    est.fit(x, y, plan=True)
    p = est.last_fit_report.placement
    assert not any(c.get("specs") for c in p["candidates"])


def test_searched_featurize_placement(rng, mesh42):
    """fv_common's featurize placement rides the same search machinery:
    hand row-sharded layout at the untrained head (bit-identical default),
    a placement record with the spec column, single-device floor last."""
    from keystone_tpu.workloads.fv_common import (
        bucket_by_shape,
        searched_bucket_featurize,
        shard_batch,
    )

    images = [
        rng.integers(0, 255, (24, 16, 3)).astype(np.uint8) for _ in range(6)
    ] + [
        rng.integers(0, 255, (16, 16, 3)).astype(np.uint8) for _ in range(4)
    ]
    per_batch = lambda dev: jnp.asarray(dev, jnp.float32).sum(  # noqa: E731
        axis=(1, 2, 3), keepdims=True
    )[:, :, None]
    out, placement = searched_bucket_featurize(
        "test_featurize", images, per_batch, mesh42
    )
    assert placement is not None
    assert placement["ranking"][0].startswith("row_sharded[mesh 4x2]")
    assert placement["ranking"][-1] == "single_device"
    assert placement["chosen"] == placement["ranking"][0]
    # bit-identical to the hand path
    hand = {
        shape: (idx, per_batch(shard_batch(batch, mesh42)))
        for shape, (idx, batch) in bucket_by_shape(images).items()
    }
    assert set(out) == set(hand)
    for shape in out:
        np.testing.assert_array_equal(
            np.asarray(out[shape][1]), np.asarray(hand[shape][1])
        )
    # no mesh -> plain hand path, no record
    out2, rec2 = searched_bucket_featurize(
        "test_featurize", images, per_batch, None
    )
    assert rec2 is None and set(out2) == set(hand)


# -- the cross-program calibration model (ISSUE 10) ----------------------------


def _feat(kind="fused", bytes_=1e6, flops=1e9, data=1, model=1):
    return autoshard.plan_features(
        kind, {"data": data, "model": model},
        {"arg_bytes": bytes_, "flops": flops, "dispatches": 1},
    )


def test_calibration_model_learns_constant_ratio():
    from keystone_tpu.core import optimize as kopt

    rows = [
        (f"fp{i}", _feat(bytes_=10.0 ** (5 + i % 3)), 3.0) for i in range(10)
    ]
    model = kopt.CalibrationModel.fit_rows(rows)
    assert model is not None
    assert model.n_programs == 10
    # a constant measured/prior ratio is learned to ~3x for any features
    assert model.predict_factor(_feat(bytes_=2e6)) == pytest.approx(
        3.0, rel=0.05
    )


def test_calibration_model_factor_clipped():
    from keystone_tpu.core import optimize as kopt

    rows = [("a", _feat(bytes_=1e5), 1e9), ("b", _feat(bytes_=1e6), 1e9)]
    model = kopt.CalibrationModel.fit_rows(rows)
    assert model.predict_factor(_feat(bytes_=1e7)) <= 32.0


def test_calibrate_uses_cross_program_model_for_unseen_program(
    tmp_path, monkeypatch
):
    """Outcomes logged for OTHER programs train a model that transfers to
    a fingerprint the log never saw — the source says 'model', the direct
    sample count stays 0 (so the margin stays cold: conservative rules
    preserved)."""
    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, path)
    autoshard.clear_outcome_cache()
    try:
        for i in range(10):
            rec = _log_record(f"{i:016x}", "fused", 1.0, 4.0)
            rec["raw_seconds"] = 1.0
            rec["features"] = _feat(bytes_=10.0 ** (5 + i % 3))
            autoshard.append_outcome(rec)
        autoshard.clear_outcome_cache()
        factor, n, source = autoshard.calibrate(
            "f" * 16, "fused", features=_feat(bytes_=2e6)
        )
        assert source == "model"
        assert n == 0
        assert factor == pytest.approx(4.0, rel=0.1)
        # featureless lookups keep the old direct->pooled->1.0 ladder
        assert autoshard.calibration("f" * 16, "fused") == (1.0, 0)
    finally:
        autoshard.clear_outcome_cache()


def test_empty_log_keeps_untrained_hand_order_with_model_path(
    tmp_path, monkeypatch
):
    # The acceptance bar: with an EMPTY plan log the searched ranking
    # (specs included) reproduces the hand order — no model, no pooled
    # median, factor 1.0 everywhere.
    monkeypatch.setenv(
        autoshard.PLAN_LOG_ENV, str(tmp_path / "empty.jsonl")
    )
    autoshard.clear_outcome_cache()
    try:
        plan = _search([_mk_cand("a", 0, 10), _mk_cand("b", 1, 7)])
        assert plan.ranking == ["a", "b"]
        assert all(
            c.calibration == 1.0 and c.calibration_source == "none"
            for c in plan.candidates
        )
    finally:
        autoshard.clear_outcome_cache()


# -- plan-log cap + compaction (ISSUE 10 satellite) ----------------------------


def test_plan_log_cap_compacts_oldest_first(tmp_path, monkeypatch):
    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, path)
    monkeypatch.setenv(autoshard.PLAN_LOG_MAX_ENV, "50")
    autoshard.clear_outcome_cache()
    try:
        # Pre-seed an OVERSIZED log: an old fingerprint with constant
        # ratio 2.0 spread over many stale records, then a hot one.
        with open(path, "w") as f:
            for i in range(400):
                f.write(json.dumps(_log_record("old" + "0" * 13, "fused",
                                               1.0, 2.0)) + "\n")
            for i in range(40):
                f.write(json.dumps(_log_record("hot" + "0" * 13, "fused",
                                               1.0, 5.0)) + "\n")
        autoshard.append_outcome(_log_record("hot" + "0" * 13, "fused",
                                             1.0, 5.0))
        with open(path) as f:
            lines = [ln for ln in f if ln.strip()]
        assert len(lines) <= 51  # cap + the appended record
        autoshard.clear_outcome_cache()
        # medians stable through compaction (constant per-pair ratios)
        assert autoshard.calibration("old" + "0" * 13, "fused")[0] == (
            pytest.approx(2.0)
        )
        assert autoshard.calibration("hot" + "0" * 13, "fused")[0] == (
            pytest.approx(5.0)
        )
    finally:
        autoshard.clear_outcome_cache()


def test_plan_log_cap_disabled_by_env(tmp_path, monkeypatch):
    monkeypatch.setenv(autoshard.PLAN_LOG_MAX_ENV, "off")
    assert autoshard.plan_log_max() is None
    monkeypatch.delenv(autoshard.PLAN_LOG_MAX_ENV)
    assert autoshard.plan_log_max() == 20_000


def test_plan_log_cap_malformed_env_never_crashes_append(
    tmp_path, monkeypatch
):
    """Telemetry must never crash a solve: a malformed or negative
    KEYSTONE_PLAN_LOG_MAX raises at plan_log_max() (fail-fast grammar)
    but append_outcome degrades counted — and never wipes the log."""
    from keystone_tpu.core.resilience import counters

    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, path)
    autoshard.clear_outcome_cache()
    try:
        # seed one good record under a valid cap
        autoshard.append_outcome(_log_record("a" * 16, "fused", 1.0, 2.0))
        seeded = open(path).read()
        assert seeded
        for bad in ("unlimited", "-5"):
            monkeypatch.setenv(autoshard.PLAN_LOG_MAX_ENV, bad)
            with pytest.raises(ValueError):
                autoshard.plan_log_max()
            before = counters.get("plan_log_write_failed")
            autoshard.append_outcome(_log_record("a" * 16, "fused", 1.0, 2.0))
            assert counters.get("plan_log_write_failed") - before == 1
        # the seeded record survived — no negative-cap wipe, no torn write
        assert open(path).read() == seeded
    finally:
        autoshard.clear_outcome_cache()


def test_compact_log_tiny_cap_trims_never_wipes(tmp_path):
    # cap below the per-pair keep tail: a single-pair log must TRIM to
    # the watermark, not evict its only pair (wiping all history).
    path = str(tmp_path / "plans.jsonl")
    with open(path, "w") as f:
        for i in range(30):
            f.write(json.dumps(_log_record("a" * 16, "fused", 1.0,
                                           float(i))) + "\n")
    n = autoshard.compact_log(path, 5)
    assert 1 <= n <= 5
    kept = [json.loads(ln) for ln in open(path)]
    assert len(kept) == n
    # survivors are the NEWEST records
    assert kept[-1]["measured_seconds"] == 29.0


def test_compact_log_keeps_newest_per_pair(tmp_path):
    path = str(tmp_path / "plans.jsonl")
    with open(path, "w") as f:
        for i in range(30):
            r = _log_record("a" * 16, "fused", 1.0, float(i))
            f.write(json.dumps(r) + "\n")
    n = autoshard.compact_log(path, 10)
    assert n <= 10
    kept = [json.loads(ln) for ln in open(path)]
    # oldest-first: the survivors are the NEWEST records
    assert [r["measured_seconds"] for r in kept] == list(
        range(30 - len(kept), 30)
    )


# -- mesh-enumeration memoization (ISSUE 10 satellite) -------------------------


def test_enumerate_meshes_memoized_per_device_tuple():
    import jax

    devices = jax.devices()
    a = enumerate_meshes(devices)
    b = enumerate_meshes(devices)
    # same Mesh OBJECTS back (the construction happened once), but a
    # fresh list each call (callers may mutate their copy)
    assert a is not b
    assert all(x is y for x, y in zip(a, b))


def test_enumerate_mesh_shapes_memoized_returns_fresh_list():
    a = enumerate_mesh_shapes(8)
    b = enumerate_mesh_shapes(8)
    assert a == b and a is not b
    a.append(("junk", 0))
    assert enumerate_mesh_shapes(8) == b  # cache not polluted


# -- tools/plan_view.py -------------------------------------------------------


def test_plan_view_renders_placement_from_results_json(rng, tmp_path):
    x, y = _small_problem(rng)
    est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0)
    est.fit(x, y, plan=True)
    doc = {"nested": {"solver": est.last_fit_report.record()}}
    path = tmp_path / "results.json"
    path.write_text(json.dumps(doc))
    out = plan_view.summarize(str(path))
    assert "bcd_fit" in out
    assert "chosen:" in out
    for name in est.last_fit_report.placement["ranking"]:
        assert name in out


def test_plan_view_finds_all_embedded_plans():
    plan = {
        "label": "t", "fingerprint": "f", "devices": "cpu x1",
        "ranking": ["a"], "candidates": [], "chosen": None,
    }
    doc = {"a": [plan, {"b": plan}], "c": plan}
    assert len(plan_view.find_plans(doc)) == 3


def test_plan_view_renders_spec_column(rng, tmp_path):
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    mesh = make_mesh(data=len(jax.devices()) // 2, model=2)
    x, y = _small_problem(rng, n=256, d=128, k=4)
    est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0, mesh=mesh)
    est.fit(x, y, plan=True)
    doc = {"solver": est.last_fit_report.record()}
    path = tmp_path / "results.json"
    path.write_text(json.dumps(doc))
    out = plan_view.summarize(str(path))
    assert "specs" in out  # the spec column header
    assert "labels=model@dim1" in out  # a spec assignment rendered
    assert "default" in out  # hand rungs show the default layout


def test_plan_view_summarizes_outcome_log(tmp_path):
    path = tmp_path / "plans.jsonl"
    rows = [
        _log_record("ab" * 8, "fused", 1.0, 2.0),
        _log_record("ab" * 8, "fused", 1.0, 4.0),
        _log_record("ab" * 8, "fused", 1.0, 0.0, outcome="oom"),
        _log_record("cd" * 8, "stepwise", 1.0, 1.0),
    ]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    out = plan_view.summarize(str(path))
    assert "fused" in out and "stepwise" in out
    filtered = plan_view.summarize(str(path), fingerprint="cd" * 8)
    assert "stepwise" in filtered and "fused" not in filtered
