"""Placement search (core.autoshard, ISSUE 9): candidate enumeration from
mesh factorizations and avals, the zero-cost batch preflight prune, the
analytic-prior x learned-calibration cost model, margin-bucketed ranking
(untrained search == hand ladder bit-for-bit), the plan-outcome log, and
the ranked run_ladder execution contract — plus the parallel/mesh.py
enumeration edge cases and tools/plan_view.py rendering.
"""

import json
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.core import autoshard
from keystone_tpu.core import memory as kmem
from keystone_tpu.core import optimize as kopt
from keystone_tpu.parallel.mesh import (
    enumerate_mesh_shapes,
    enumerate_meshes,
    make_mesh,
    mesh_desc,
    reduced_mesh,
)
from keystone_tpu.solvers.block import BlockLeastSquaresEstimator

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
)
import plan_view  # noqa: E402  (tools/plan_view.py)


# -- parallel/mesh.py enumeration edge cases ----------------------------------


def test_enumerate_mesh_shapes_one_device():
    assert enumerate_mesh_shapes(1) == [(1, 1)]


def test_enumerate_mesh_shapes_prime_count():
    # A prime count has exactly the two degenerate factorizations.
    assert enumerate_mesh_shapes(7) == [(7, 1), (1, 7)]


def test_enumerate_mesh_shapes_composite_data_major_descending():
    assert enumerate_mesh_shapes(8) == [(8, 1), (4, 2), (2, 4), (1, 8)]
    for n in (2, 6, 12):
        shapes = enumerate_mesh_shapes(n)
        assert all(d * m == n for d, m in shapes)
        assert [d for d, _ in shapes] == sorted(
            (d for d, _ in shapes), reverse=True
        )


def test_enumerate_mesh_shapes_rejects_zero():
    with pytest.raises(ValueError):
        enumerate_mesh_shapes(0)


def test_reduced_mesh_on_already_collapsed_mesh_is_none():
    # Pure data-parallel: nothing left to collapse — the ladder's next
    # rung is the single-device floor, not another mesh.
    collapsed = make_mesh(data=8, model=1)
    assert reduced_mesh(collapsed) is None
    # And collapsing a real (data, model) mesh yields the collapsed form
    # whose own reduction is again None.
    full = make_mesh(data=4, model=2)
    rm = reduced_mesh(full)
    assert mesh_desc(rm) == "8x1"
    assert reduced_mesh(rm) is None


def test_enumerate_meshes_deterministic_over_fixed_devices():
    import jax

    devices = jax.devices()
    a = enumerate_meshes(devices)
    b = enumerate_meshes(devices)
    assert [mesh_desc(m) for m in a] == [mesh_desc(m) for m in b]
    assert [mesh_desc(m) for m in a] == [
        f"{d}x{m}" for d, m in enumerate_mesh_shapes(len(devices))
    ]
    # Same devices in the same order for every candidate mesh.
    for m in a:
        assert list(m.devices.flat) == list(devices)


# -- sharding-spec enumeration from avals -------------------------------------


def test_spec_candidates_generated_from_aval_dims():
    aval = jnp.zeros((8, 6), jnp.float32)
    specs = {
        c["spec"]: c["per_chip_bytes"]
        for c in autoshard.spec_candidates(aval, {"data": 2, "model": 3})
    }
    total = 8 * 6 * 4
    # replicated always legal; data over any dim divisible by 2; model
    # over any dim divisible by 3 — all from the aval, no hand list.
    assert specs == {
        "replicated": total,
        "data@dim0": total // 2,
        "data@dim1": total // 2,
        "model@dim1": total // 3,
    }


def test_best_spec_minimizes_per_chip_bytes_and_replicates_when_odd():
    aval = jnp.zeros((8, 6), jnp.float32)
    best = autoshard.best_spec(aval, {"data": 4, "model": 2})
    assert best["spec"] == "data@dim0"
    assert best["per_chip_bytes"] == 8 * 6 * 4 // 4
    # Nothing divides a prime dim: replicated is the only legal spec.
    odd = jnp.zeros((7,), jnp.float32)
    assert autoshard.best_spec(odd, {"data": 4, "model": 2})["spec"] == (
        "replicated"
    )


# -- the zero-cost batch preflight --------------------------------------------


def test_plan_bytes_admits_and_denies_analytically():
    ok = kmem.plan_bytes(
        "t", argument_bytes=100, temp_bytes=50, budget=1000
    )
    assert ok.admitted and not ok.analyzed  # no compile happened
    deny = kmem.plan_bytes("t", argument_bytes=2000, budget=1000)
    assert not deny.admitted
    assert "DENIED" in deny.reason
    assert deny.total_bytes == 2000


def test_plan_bytes_without_budget_skips_admission():
    plan = kmem.plan_bytes("t", argument_bytes=1 << 50, budget=None)
    assert plan.admitted
    assert "skipped" in plan.reason


def test_plan_batch_turns_planner_crash_into_deny():
    out = kmem.plan_batch([
        ("good", lambda: kmem.plan_bytes("good", argument_bytes=1, budget=10)),
        ("bad", lambda: (_ for _ in ()).throw(RuntimeError("boom"))),
    ])
    assert out["good"].admitted
    assert not out["bad"].admitted
    assert "boom" in out["bad"].reason


# -- fingerprints and the plan-outcome log ------------------------------------


def test_fingerprint_stable_and_shape_sensitive():
    a = autoshard.fingerprint("bcd", 100, 10, "f32")
    assert a == autoshard.fingerprint("bcd", 100, 10, "f32")
    assert a != autoshard.fingerprint("bcd", 200, 10, "f32")
    assert len(a) == 16


def _log_record(fp, cand, predicted, measured, outcome="ok"):
    return {
        "fingerprint": fp, "label": "t", "candidate": cand,
        "predicted_seconds": predicted, "measured_seconds": measured,
        "outcome": outcome, "devices": "cpu x1", "ts": 0.0,
    }


def test_outcome_log_roundtrip_and_calibration(tmp_path, monkeypatch):
    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, path)
    autoshard.clear_outcome_cache()
    try:
        fp = "f" * 16
        for _ in range(autoshard.MIN_TRAIN - 1):
            autoshard.append_outcome(_log_record(fp, "a", 1.0, 3.0))
        autoshard.clear_outcome_cache()
        # Below MIN_TRAIN: the analytic prior stands (factor 1.0).
        factor, n = autoshard.calibration(fp, "a")
        assert (factor, n) == (1.0, autoshard.MIN_TRAIN - 1)
        autoshard.append_outcome(_log_record(fp, "a", 1.0, 3.0))
        autoshard.clear_outcome_cache()
        factor, n = autoshard.calibration(fp, "a")
        assert n == autoshard.MIN_TRAIN
        assert factor == pytest.approx(3.0)
        # OOM outcomes never train the ratio; a torn tail line is skipped.
        autoshard.append_outcome(_log_record(fp, "a", 1.0, 9.0, outcome="oom"))
        with open(path, "a") as f:
            f.write('{"torn": ')
        autoshard.clear_outcome_cache()
        assert autoshard.calibration(fp, "a")[0] == pytest.approx(3.0)
    finally:
        autoshard.clear_outcome_cache()


def test_outcome_log_disabled_by_env(monkeypatch):
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, "off")
    assert autoshard.plan_log_path() is None
    autoshard.append_outcome({"x": 1})  # must be a no-op, not a crash
    assert autoshard.load_outcomes() == []


def test_outcome_log_read_once_per_process(tmp_path, monkeypatch):
    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, path)
    autoshard.clear_outcome_cache()
    try:
        assert autoshard.load_outcomes() == []
        # Outcomes appended DURING the process train the NEXT process: the
        # cached (empty) read stands, so a ranking can never flip between
        # a baseline and a comparison fit mid-process.
        autoshard.append_outcome(_log_record("a" * 16, "a", 1.0, 2.0))
        assert autoshard.load_outcomes() == []
        autoshard.clear_outcome_cache()
        assert len(autoshard.load_outcomes()) == 1
    finally:
        autoshard.clear_outcome_cache()


# -- search: prune, score, rank -----------------------------------------------


def _mk_cand(name, prior, dispatches, floor=False, hand=True, arg_bytes=0):
    def run(_plan, name=name):
        return f"{name}:ran"

    return autoshard.Candidate(
        name, "fused",
        plan=lambda name=name: kmem.MemoryPlan(
            label=name, admitted=True, reason="test"
        ),
        run=run,
        hints={"dispatches": dispatches, "arg_bytes": arg_bytes},
        prior_rank=prior, floor=floor, hand=hand,
    )


_FP = "0123456789abcdef"


def _search(cands, budget=kmem._UNSET):
    # Fixed CostModel: device-independent predicted seconds (1 ms per
    # dispatch), so the ranking assertions hold on any test platform.
    return autoshard.search(
        "t", cands, fingerprint=_FP, budget=budget, model=kopt.CostModel()
    )


def test_untrained_search_keeps_hand_order_within_margin():
    # b's analytic prior is ~1.4x better than a's — inside the 4x cold
    # margin, so the proven hand order stands (the bit-identical bar).
    plan = _search([_mk_cand("a", 0, 10), _mk_cand("b", 1, 7)])
    assert plan.ranking == ["a", "b"]
    assert not plan.trained
    assert plan.margin == autoshard.UNTRAINED_MARGIN


def test_untrained_search_reorders_on_decisive_analytic_advantage():
    # c is 10x faster analytically — clears the cold margin.
    plan = _search([_mk_cand("a", 0, 10), _mk_cand("c", 1, 1)])
    assert plan.ranking == ["c", "a"]


def test_margin_is_relative_not_bucketed():
    # 17 vs 15 dispatches: a 1.13x gap that straddles a power-of-4
    # boundary (0.017s vs 0.015s around 4^-3) — absolute log buckets
    # would split them and reorder; the relative margin must not.
    plan = _search([_mk_cand("a", 0, 17), _mk_cand("b", 1, 15)])
    assert plan.ranking == ["a", "b"]


def test_calibration_falls_back_to_program_median(tmp_path, monkeypatch):
    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, path)
    autoshard.clear_outcome_cache()
    try:
        fp = "e" * 16
        for _ in range(autoshard.MIN_TRAIN):
            autoshard.append_outcome(_log_record(fp, "a", 1.0, 3.0))
        autoshard.clear_outcome_cache()
        # "b" never ran: it inherits the PROGRAM-level median factor but
        # reports 0 direct samples (the pooled fallback must not count as
        # trained-ness for the tight margin).
        factor, n = autoshard.calibration(fp, "b")
        assert n == 0
        assert factor == pytest.approx(3.0)
    finally:
        autoshard.clear_outcome_cache()


def test_one_sided_training_cannot_flip_toward_unmeasured_plan(
    tmp_path, monkeypatch
):
    # Equal analytic priors; the chosen plan "a" trains to a 5x honest
    # slowdown while "b" never ran.  The program-median fallback gives
    # "b" the SAME constant factor, so the proven hand order stands —
    # one-sided measurements must never hand the ranking to whatever
    # never ran.
    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, path)
    autoshard.clear_outcome_cache()
    try:
        for _ in range(autoshard.MIN_TRAIN):
            autoshard.append_outcome(_log_record(_FP, "a", 0.01, 0.05))
        autoshard.clear_outcome_cache()
        plan = _search([_mk_cand("a", 0, 10), _mk_cand("b", 1, 10)])
        assert plan.ranking == ["a", "b"]
        assert not plan.trained  # "b" has no DIRECT measurements
        assert plan.candidate("b").calibration == pytest.approx(5.0)
    finally:
        autoshard.clear_outcome_cache()


def test_floor_pinned_last_regardless_of_score():
    plan = _search([
        _mk_cand("a", 0, 10),
        _mk_cand("cheap_floor", 1, 1, floor=True),
    ])
    assert plan.ranking == ["a", "cheap_floor"]
    rec = plan.candidate("cheap_floor")
    assert "floor" in rec.reason


def test_pruned_hand_candidate_stays_in_execution_order():
    # The over-budget hand candidate is denied for free by the analytic
    # preflight but keeps its hand position in the walk, so the ladder
    # records the denial exactly where the hand contract puts it.
    plan = _search(
        [
            _mk_cand("big", 0, 1, arg_bytes=10_000),
            _mk_cand("small", 1, 10),
        ],
        budget=1000,
    )
    assert plan.ranking == ["big", "small"]
    big = plan.candidate("big")
    assert big.pruned and big.outcome == "denied"
    assert "DENIED" in big.reason
    assert "big" in plan.analytic_plans  # cached deny, never re-planned


def test_pruned_extra_candidate_dropped_from_ranking():
    plan = _search(
        [
            _mk_cand("hand", 0, 10),
            _mk_cand("extra", 1, 1, hand=False, arg_bytes=10_000),
        ],
        budget=1000,
    )
    assert plan.ranking == ["hand"]
    # ...but the table still shows why the enumerated candidate lost.
    extra = plan.candidate("extra")
    assert extra.pruned and extra.outcome == "denied"


def test_search_deterministic_same_fingerprint_same_ranking():
    cands = lambda: [  # noqa: E731
        _mk_cand("a", 0, 10), _mk_cand("b", 1, 7), _mk_cand("c", 2, 2),
        _mk_cand("floor", 3, 30, floor=True),
    ]
    a, b = _search(cands()), _search(cands())
    assert a.ranking == b.ranking
    assert a.fingerprint == b.fingerprint
    assert [c.record() for c in a.candidates] == [
        c.record() for c in b.candidates
    ]


def test_trained_calibration_reorders_past_margin(tmp_path, monkeypatch):
    path = str(tmp_path / "plans.jsonl")
    monkeypatch.setenv(autoshard.PLAN_LOG_ENV, path)
    autoshard.clear_outcome_cache()
    try:
        # Equal analytic priors; measurements say b is 100x faster.  Once
        # every survivor is calibrated the margin tightens to
        # TRAINED_MARGIN and b takes the head.
        for _ in range(autoshard.MIN_TRAIN):
            autoshard.append_outcome(_log_record(_FP, "a", 0.01, 0.01))
            autoshard.append_outcome(_log_record(_FP, "b", 0.01, 0.0001))
        autoshard.clear_outcome_cache()
        plan = _search([_mk_cand("a", 0, 10), _mk_cand("b", 1, 10)])
        assert plan.trained
        assert plan.margin == autoshard.TRAINED_MARGIN
        assert plan.ranking == ["b", "a"]
        rec = plan.candidate("b")
        assert rec.samples == autoshard.MIN_TRAIN
        assert rec.calibration == pytest.approx(0.01)
    finally:
        autoshard.clear_outcome_cache()


# -- run_search: the ranked execution contract --------------------------------


def test_run_search_hand_mode_walks_hand_ladder_without_placement():
    report = kmem.FitReport(label="t")
    out = autoshard.run_search(
        "t",
        [_mk_cand("a", 0, 10), _mk_cand("x", 1, 1, hand=False)],
        report, fingerprint=_FP, plan=False,
    )
    assert out == "a:ran"
    assert report.placement is None  # the hand ladder leaves no search


def test_run_search_executes_ranked_head_and_records_placement():
    report = kmem.FitReport(label="t")
    out = autoshard.run_search(
        "t", [_mk_cand("a", 0, 10), _mk_cand("c", 1, 1)],
        report, fingerprint=_FP, plan=True,
        model=kopt.CostModel(),
    )
    assert out == "c:ran"  # decisive analytic advantage took the head
    assert report.chosen == "c"
    p = report.placement
    assert p["chosen"] == "c"
    assert p["ranking"][0] == "c"
    chosen = [c for c in p["candidates"] if c["name"] == "c"][0]
    assert chosen["outcome"] == "ok"
    assert chosen["measured_seconds"] is not None


def test_run_search_forced_ranking_keeps_floor_last():
    report = kmem.FitReport(label="t")
    out = autoshard.run_search(
        "t",
        [
            _mk_cand("a", 0, 10),
            _mk_cand("b", 1, 10),
            _mk_cand("floor", 2, 10, floor=True),
        ],
        report, fingerprint=_FP, plan=["floor", "b"],
        model=kopt.CostModel(),
    )
    # The override names the floor first, but the floor is the backstop:
    # it stays pinned last and the first non-floor named plan runs.
    assert out == "b:ran"
    assert report.placement["ranking"] == ["b", "a", "floor"]


def test_run_search_rejects_bad_plan_arg():
    with pytest.raises(TypeError):
        autoshard.run_search(
            "t", [_mk_cand("a", 0, 1)],
            kmem.FitReport(label="t"), fingerprint=_FP, plan=42,
        )


def test_run_search_runtime_oom_steps_down_ranked_list_counted():
    from keystone_tpu.core.resilience import counters

    calls = {"a": 0}

    def dying_run(_plan):
        calls["a"] += 1
        raise RuntimeError("RESOURCE_EXHAUSTED: injected")

    top = autoshard.Candidate(
        "a", "fused",
        plan=lambda: kmem.MemoryPlan(label="a", admitted=True, reason="test"),
        run=dying_run, hints={"dispatches": 1}, prior_rank=0,
    )
    report = kmem.FitReport(label="t")
    before = counters.get("autoshard_stepdown")
    out = autoshard.run_search(
        "t", [top, _mk_cand("b", 1, 10)], report,
        fingerprint=_FP, plan=True, model=kopt.CostModel(),
    )
    assert out == "b:ran"
    assert calls["a"] == 1
    assert report.chosen == "b"
    assert "a" in report.oom_retries
    assert counters.get("autoshard_stepdown") - before >= 1
    p = report.placement
    assert [c for c in p["candidates"] if c["name"] == "a"][0]["outcome"] == (
        "oom"
    )


def test_run_search_typed_failure_not_recorded_as_oom():
    # A non-OOM failure propagates (run_ladder's contract) and the audit
    # trail must say "error", not fabricate a memory misprediction.
    def dying_run(_plan):
        raise ValueError("bad data, not memory")

    top = autoshard.Candidate(
        "a", "fused",
        plan=lambda: kmem.MemoryPlan(label="a", admitted=True, reason="test"),
        run=dying_run, hints={"dispatches": 1}, prior_rank=0,
    )
    report = kmem.FitReport(label="t")
    with pytest.raises(ValueError):
        autoshard.run_search(
            "t", [top], report, fingerprint=_FP, plan=True,
            model=kopt.CostModel(),
        )
    rec = [c for c in report.placement["candidates"] if c["name"] == "a"][0]
    assert rec["outcome"] == "error"


# -- solver-level integration -------------------------------------------------


def _small_problem(rng, n=256, d=128, k=4):
    x = jnp.asarray(rng.standard_normal((n, d), dtype=np.float32))
    y = jnp.asarray(
        2.0 * np.eye(k, dtype=np.float32)[rng.integers(0, k, n)] - 1.0
    )
    return x, y


def test_fit_searched_bit_identical_to_hand_ladder(rng):
    x, y = _small_problem(rng)
    hand = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0).fit(
        x, y, plan=False
    )
    est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0)
    searched = est.fit(x, y, plan=True)
    np.testing.assert_array_equal(np.asarray(hand.b), np.asarray(searched.b))
    for a, b in zip(hand.xs, searched.xs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    p = est.last_fit_report.placement
    assert p is not None
    assert p["chosen"] == est.last_fit_report.chosen
    assert p["ranking"], p
    # The searched table carries a scored or denied rationale per row.
    assert all(c["reason"] for c in p["candidates"])


def test_fit_searched_plan_deterministic_under_fixed_devices(rng):
    x, y = _small_problem(rng)

    def one():
        est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0)
        est.fit(x, y, plan=True)
        return est.last_fit_report.placement

    a, b = one(), one()
    assert a["fingerprint"] == b["fingerprint"]
    assert a["ranking"] == b["ranking"]
    assert [c["name"] for c in a["candidates"]] == [
        c["name"] for c in b["candidates"]
    ]


def test_fit_plan_replay_accepts_placement_plan_and_name_list(rng):
    x, y = _small_problem(rng)
    est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0)
    base = est.fit(x, y, plan=True)
    prev = est.last_fit_report.placement

    est2 = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0)
    replay = est2.fit(x, y, plan=list(prev["ranking"]))
    assert est2.last_fit_report.placement["ranking"] == prev["ranking"]
    np.testing.assert_array_equal(np.asarray(base.b), np.asarray(replay.b))


def test_fit_mesh_search_enumerates_factorizations_deterministically(rng):
    import jax

    n_dev = len(jax.devices())
    if n_dev < 4:
        pytest.skip("needs >= 4 devices (conftest forces 8 CPU devices)")
    mesh = make_mesh(data=n_dev // 2, model=2)
    x, y = _small_problem(rng, n=256, d=128, k=4)

    def one():
        est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0, mesh=mesh)
        est.fit(x, y, plan=True)
        return est.last_fit_report

    rep = one()
    p = rep.placement
    # Every (data, model) factorization of the device set is a candidate,
    # plus the single-device floor.
    meshes = {
        f"{c['mesh']['data']}x{c['mesh']['model']}"
        for c in p["candidates"] if c["mesh"]
    }
    assert meshes == {
        f"{d}x{m}" for d, m in enumerate_mesh_shapes(n_dev)
    }
    assert p["ranking"][-1] == "single_device"  # the floor stays last
    # Determinism under the fixed device set: same fingerprint, same
    # ranking, run to run.
    rep2 = one()
    assert rep2.placement["fingerprint"] == p["fingerprint"]
    assert rep2.placement["ranking"] == p["ranking"]


def test_fit_report_record_carries_placement(rng):
    x, y = _small_problem(rng)
    est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0)
    est.fit(x, y, plan=True)
    rec = est.last_fit_report.record()
    assert rec["placement"] is not None
    json.dumps(rec)  # the whole audit trail must stay JSON-able


# -- tools/plan_view.py -------------------------------------------------------


def test_plan_view_renders_placement_from_results_json(rng, tmp_path):
    x, y = _small_problem(rng)
    est = BlockLeastSquaresEstimator(64, num_iter=1, lam=1.0)
    est.fit(x, y, plan=True)
    doc = {"nested": {"solver": est.last_fit_report.record()}}
    path = tmp_path / "results.json"
    path.write_text(json.dumps(doc))
    out = plan_view.summarize(str(path))
    assert "bcd_fit" in out
    assert "chosen:" in out
    for name in est.last_fit_report.placement["ranking"]:
        assert name in out


def test_plan_view_finds_all_embedded_plans():
    plan = {
        "label": "t", "fingerprint": "f", "devices": "cpu x1",
        "ranking": ["a"], "candidates": [], "chosen": None,
    }
    doc = {"a": [plan, {"b": plan}], "c": plan}
    assert len(plan_view.find_plans(doc)) == 3


def test_plan_view_summarizes_outcome_log(tmp_path):
    path = tmp_path / "plans.jsonl"
    rows = [
        _log_record("ab" * 8, "fused", 1.0, 2.0),
        _log_record("ab" * 8, "fused", 1.0, 4.0),
        _log_record("ab" * 8, "fused", 1.0, 0.0, outcome="oom"),
        _log_record("cd" * 8, "stepwise", 1.0, 1.0),
    ]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    out = plan_view.summarize(str(path))
    assert "fused" in out and "stepwise" in out
    filtered = plan_view.summarize(str(path), fingerprint="cd" * 8)
    assert "stepwise" in filtered and "fused" not in filtered
