"""Unified tracing & metrics (core.trace) — the ISSUE 5 acceptance set:

* span nesting/threading correctness (depth/parents never cross threads);
* disabled-mode overhead guard: no retained allocation growth;
* Chrome trace_event (Perfetto) JSON schema validation + JSONL export;
* ``Pipeline.profile`` per-node bytes/dtype/shape on a 3-node pipeline;
* streaming-ingest overlap efficiency recomputed from span intervals
  matches the bench ``e2e`` methodology within 5%;
* solver ladder tier spans with the FitReport linked in;
* ``resilience.counters`` atomic ``snapshot(reset=)`` (no read/reset race)
  and fault instants in the trace (chaos ``--trace`` invariant);
* ``stage_timer`` back-compat (same log line, now also a span) and the
  ``KEYSTONE_LOG_LEVEL`` env knob.
"""

import gc
import io
import json
import logging
import os
import sys
import tarfile
import threading
import time
import tracemalloc

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.core import ingest, trace
from keystone_tpu.core.logging import configure_logging, stage_timer
from keystone_tpu.core.pipeline import FunctionTransformer, Pipeline
from keystone_tpu.core.resilience import FaultCounters, counters
from keystone_tpu.loaders import image_loaders
from keystone_tpu.solvers.block import BlockLeastSquaresEstimator

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))

import trace_view  # noqa: E402  (tools/trace_view.py)


@pytest.fixture(autouse=True)
def _clean_trace():
    """Every test starts and ends with tracing off and the buffer empty —
    the module is process-global state."""
    trace.disable()
    trace.reset()
    yield
    trace.disable()
    trace.reset()


def _trace_to(tmp_path, name="t.json"):
    path = str(tmp_path / name)
    trace.enable(path)
    return path


def _spans_by_name(events):
    out = {}
    for ev in events:
        if ev.get("ph") == "X":
            out.setdefault(ev["name"], []).append(ev)
    return out


# -- span nesting / threading -------------------------------------------------


def test_span_nesting_depth_and_parent(tmp_path):
    path = _trace_to(tmp_path)
    with trace.span("outer"):
        with trace.span("inner"):
            pass
    with trace.span("sibling"):
        pass
    trace.flush(path)
    spans = _spans_by_name(trace_view.load_events(path))
    outer, inner, sib = spans["outer"][0], spans["inner"][0], spans["sibling"][0]
    assert outer["args"]["depth"] == 0 and "parent" not in outer["args"]
    assert inner["args"]["depth"] == 1 and inner["args"]["parent"] == "outer"
    assert sib["args"]["depth"] == 0
    # time containment: the child interval sits inside the parent's
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3


def test_span_threads_have_independent_stacks(tmp_path):
    path = _trace_to(tmp_path)
    barrier = threading.Barrier(2)

    def worker(tag):
        barrier.wait()
        with trace.span(f"{tag}_outer"):
            time.sleep(0.01)
            with trace.span(f"{tag}_inner"):
                time.sleep(0.01)

    threads = [
        threading.Thread(target=worker, args=(t,), name=f"w-{t}")
        for t in ("a", "b")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    trace.flush(path)
    spans = _spans_by_name(trace_view.load_events(path))
    for tag in ("a", "b"):
        inner = spans[f"{tag}_inner"][0]
        # nesting resolves within the thread, never across: a_inner's
        # parent is a_outer even though b_outer was open concurrently
        assert inner["args"]["parent"] == f"{tag}_outer"
        assert inner["args"]["depth"] == 1
        assert inner["tid"] == spans[f"{tag}_outer"][0]["tid"]
    assert spans["a_outer"][0]["tid"] != spans["b_outer"][0]["tid"]


def test_generator_hosted_span_abort_is_not_an_error(tmp_path):
    # ingest.consume spans live across a generator yield: a consumer that
    # stops early (or raises OUTSIDE the generator frame) delivers
    # GeneratorExit at the yield — that is an abort, not the pipeline's
    # failure, and must never masquerade as the span's error type.
    path = _trace_to(tmp_path)

    def gen():
        with trace.span("hosted"):
            yield 1

    g = gen()
    next(g)
    g.close()  # delivers GeneratorExit at the yield point
    trace.flush(path)
    args = _spans_by_name(trace_view.load_events(path))["hosted"][0]["args"]
    assert args.get("aborted") is True
    assert "error" not in args


def test_span_error_attribute_recorded(tmp_path):
    path = _trace_to(tmp_path)
    with pytest.raises(ValueError):
        with trace.span("doomed"):
            raise ValueError("boom")
    trace.flush(path)
    spans = _spans_by_name(trace_view.load_events(path))
    assert spans["doomed"][0]["args"]["error"] == "ValueError"


# -- disabled-mode overhead ---------------------------------------------------


def test_disabled_mode_no_allocation_growth():
    """With tracing off, retained memory attributable to the trace module
    must be CONSTANT once the flight-recorder ring is warm: disabled
    spans/instants buffer nothing into the trace event list, and the ring
    is bounded by construction — old events fall off as new ones land, so
    5000 further spans retain no net growth."""
    assert not trace.enabled()
    assert trace.flight_depth() > 0  # the always-on ring is the default
    filters = [tracemalloc.Filter(True, trace.__file__)]
    tracemalloc.start()
    try:
        # Warm past the ring's capacity INSIDE the traced window so the
        # before-snapshot sees it full of TRACKED entries — from here on,
        # every append evicts one (this is the boundedness claim).
        warm = trace.flight_depth() + 200
        for _ in range(warm):
            with trace.span("warm", k=1):
                pass
            trace.instant("warm", n=1)
        gc.collect()
        before = tracemalloc.take_snapshot().filter_traces(filters)
        for _ in range(5000):
            with trace.span("hot"):
                pass
            trace.instant("hot", n=1)
        gc.collect()
        after = tracemalloc.take_snapshot().filter_traces(filters)
    finally:
        tracemalloc.stop()
    size_before = sum(s.size for s in before.statistics("filename"))
    size_after = sum(s.size for s in after.statistics("filename"))
    # No net retained growth attributable to the trace module (8 KB slack
    # for allocator bookkeeping / dict-churn noise in the full ring).
    assert size_after - size_before < 8192, (
        f"disabled tracing retained {size_after - size_before} bytes "
        "across 5000 spans (flight ring unbounded, or events buffered?)"
    )
    assert trace.events() == []
    assert len(trace.flight_events()) <= trace.flight_depth()


# -- flight recorder ----------------------------------------------------------


def test_flight_ring_records_with_tracing_disabled():
    assert not trace.enabled()
    with trace.span("flight_probe", cat="t", bytes=4):
        pass
    trace.instant("flight_point", n=2)
    # nothing buffered for export...
    assert trace.events() == []
    # ...but the ring has the last moments, span attrs included
    names = {e["name"]: e for e in trace.flight_events()}
    assert "flight_probe" in names and "flight_point" in names
    assert names["flight_probe"]["args"]["bytes"] == 4
    assert names["flight_probe"]["ph"] == "X"
    assert names["flight_point"]["ph"] == "i"


def test_flight_ring_is_bounded_and_resizable():
    prev = trace.flight_depth()
    try:
        trace.set_flight_depth(8)
        for i in range(50):
            trace.instant("ring_fill", i=i)
        evs = trace.flight_events()
        assert len(evs) <= 8
        # the ring keeps the MOST RECENT events
        assert evs[-1]["args"]["i"] == 49
        trace.set_flight_depth(0)
        trace.instant("ring_off")
        assert trace.flight_events() == []
    finally:
        trace.set_flight_depth(prev)


def test_flight_ring_rides_along_when_tracing_enabled(tmp_path):
    path = _trace_to(tmp_path)
    with trace.span("both_worlds"):
        pass
    trace.flush(path)
    assert any(e["name"] == "both_worlds" for e in trace.flight_events())
    assert any(
        e["name"] == "both_worlds"
        for e in trace_view.load_events(path)
    )


def test_thread_seen_in_flight_mode_gets_named_on_enable(tmp_path):
    """A thread first registered while tracing was OFF (flight-only mode)
    must still get its thread_name metadata when tracing is enabled later
    — lanes in the flushed trace stay labeled."""
    assert not trace.enabled()
    done = threading.Event()

    def worker():
        with trace.span("pre_enable_span"):
            pass
        done.set()

    t = threading.Thread(target=worker, name="flight-first-thread")
    t.start()
    t.join()
    assert done.is_set()
    path = _trace_to(tmp_path)
    with trace.span("post_enable"):
        pass
    trace.flush(path)
    metas = [
        ev for ev in trace_view.load_events(path)
        if ev.get("ph") == "M" and ev.get("name") == "thread_name"
    ]
    assert any(
        m["args"]["name"] == "flight-first-thread" for m in metas
    ), metas


def test_fault_counter_lands_in_flight_ring_untraced():
    # The chaos postmortem path: a counted fault must be in the ring even
    # when tracing was never enabled.
    assert not trace.enabled()
    counters.record("flight_fault_probe", "ring check")
    faults = [
        e for e in trace.flight_events()
        if e.get("name") == "fault"
        and e.get("args", {}).get("kind") == "flight_fault_probe"
    ]
    assert faults, "counted fault missing from the flight ring"


# -- exporters ----------------------------------------------------------------


def test_perfetto_chrome_trace_schema(tmp_path):
    path = _trace_to(tmp_path)
    with trace.span("stage_a", cat="stage", bytes=1024):
        with trace.span("child"):
            pass
    trace.instant("hbm_admission", admitted=True, charged_gb=0.5)
    counters.record("trace_test_fault", "schema probe")
    trace.flush(path)

    with open(path) as f:
        doc = json.load(f)  # must be valid JSON wholesale
    assert isinstance(doc, dict) and isinstance(doc["traceEvents"], list)
    assert doc["traceEvents"], "no events exported"
    phases = set()
    for ev in doc["traceEvents"]:
        assert isinstance(ev["name"], str) and ev["name"]
        assert ev["ph"] in ("X", "i", "M")
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        phases.add(ev["ph"])
        if ev["ph"] in ("X", "i"):
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
            assert isinstance(ev.get("args", {}), dict)
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        if ev["ph"] == "M":
            assert ev["name"] == "thread_name"
    # complete spans, instants, and thread metadata all present
    assert phases == {"X", "i", "M"}
    # the fault counter landed as a kind-tagged instant (chaos invariant)
    kinds = {
        ev["args"].get("kind")
        for ev in doc["traceEvents"]
        if ev["ph"] == "i" and ev["name"] == "fault"
    }
    assert "trace_test_fault" in kinds


def test_flush_is_crash_safe_atomic(tmp_path, monkeypatch):
    """The checkpoint atomic-write idiom on trace.flush: a failure mid-
    write must leave the previously-flushed trace intact and no temp
    litter — never a truncated Perfetto JSON."""
    path = _trace_to(tmp_path)
    with trace.span("survivor"):
        pass
    trace.flush(path)
    good = open(path).read()
    json.loads(good)  # valid JSON on disk

    def exploding_dump(*a, **kw):
        raise RuntimeError("injected crash mid-flush")

    monkeypatch.setattr(trace.json, "dump", exploding_dump)
    with trace.span("doomed_flush"):
        pass
    with pytest.raises(RuntimeError, match="mid-flush"):
        trace.flush(path)
    monkeypatch.undo()
    # the old trace survived byte-for-byte and no temp files remain
    assert open(path).read() == good
    leftovers = [p for p in os.listdir(tmp_path) if ".tmp" in p]
    assert leftovers == [], leftovers


def test_jsonl_export(tmp_path):
    path = _trace_to(tmp_path, "t.jsonl")
    with trace.span("a"):
        pass
    trace.instant("b")
    trace.flush(path)
    with open(path) as f:
        events = [json.loads(line) for line in f if line.strip()]
    names = {ev["name"] for ev in events}
    assert {"a", "b"} <= names


# -- Pipeline.profile ---------------------------------------------------------


def test_pipeline_profile_per_node_bytes(tmp_path):
    path = _trace_to(tmp_path)
    pipe = Pipeline(
        [
            FunctionTransformer(lambda b: b * 2.0, name="double"),
            FunctionTransformer(
                lambda b: jnp.concatenate([b, b], axis=1), name="widen"
            ),
            FunctionTransformer(lambda b: jnp.sum(b, axis=1), name="reduce"),
        ]
    )
    batch = jnp.ones((4, 8), jnp.float32)
    prof = pipe.profile(batch)
    trace.flush(path)

    assert [n.name for n in prof.nodes] == ["double", "widen", "reduce"]
    assert [n.output_bytes for n in prof.nodes] == [
        4 * 8 * 4,  # [4, 8] f32
        4 * 16 * 4,  # [4, 16] f32
        4 * 4,  # [4] f32
    ]
    assert [n.shape for n in prof.nodes] == [(4, 8), (4, 16), (4,)]
    assert all(n.dtype == "float32" for n in prof.nodes)
    assert all(n.seconds >= 0 for n in prof.nodes)
    assert prof.total_seconds >= sum(n.seconds for n in prof.nodes) * 0.5
    assert prof.input_bytes == 4 * 8 * 4
    np.testing.assert_allclose(np.asarray(prof.output), np.full(4, 32.0))
    json.dumps(prof.record())  # JSON-able for bench artifacts
    assert "double" in prof.summary()

    # the profile is also a span tree in the trace
    spans = _spans_by_name(trace_view.load_events(path))
    assert "pipeline.profile" in spans
    node_span = spans["node:widen"][0]
    assert node_span["args"]["parent"] == "pipeline.profile"
    assert node_span["args"]["output_bytes"] == 4 * 16 * 4


# -- solver ladder spans ------------------------------------------------------


def test_block_solve_emits_tier_spans_with_report(tmp_path, rng):
    path = _trace_to(tmp_path)
    x = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32))
    y = jnp.asarray(
        2.0 * np.eye(4)[rng.integers(0, 4, 64)] - 1.0, jnp.float32
    )
    est = BlockLeastSquaresEstimator(16, num_iter=1, lam=1e-2)
    est.fit(x, y)
    trace.flush(path)
    events = trace_view.load_events(path)
    spans = _spans_by_name(events)
    solve = spans["solve:bcd_fit"][0]
    # FitReport linked into the solve span
    assert solve["args"]["report"]["chosen_tier"] == est.last_fit_report.chosen
    tier = spans[f"tier:{est.last_fit_report.chosen}"][0]
    assert tier["args"]["parent"] == "solve:bcd_fit"
    assert tier["args"]["solve"] == "bcd_fit"
    # every admission decision is an instant on the same timeline
    admissions = [
        ev
        for ev in events
        if ev.get("ph") == "i" and ev["name"] == "hbm_admission"
    ]
    assert admissions and all(
        "admitted" in ev["args"] and "reason" in ev["args"]
        for ev in admissions
    )


def test_forced_degradation_denials_visible_in_trace(tmp_path, rng, monkeypatch):
    # A pinched budget denies the fused tier: the denial must be visible
    # as a non-admitted hbm_admission instant AND the chosen degraded tier
    # as a span — the trace tells the whole ladder story.
    monkeypatch.setenv("KEYSTONE_HBM_BUDGET", "10K")
    path = _trace_to(tmp_path)
    x = rng.normal(size=(128, 64)).astype(np.float32)
    y = (2.0 * np.eye(4)[rng.integers(0, 4, 128)] - 1.0).astype(np.float32)
    est = BlockLeastSquaresEstimator(32, num_iter=1, lam=1e-2)
    est.fit(x, y)
    trace.flush(path)
    assert est.last_fit_report.denials  # the budget actually bit
    events = trace_view.load_events(path)
    denied = [
        ev
        for ev in events
        if ev.get("ph") == "i"
        and ev["name"] == "hbm_admission"
        and not ev["args"]["admitted"]
    ]
    assert denied
    spans = _spans_by_name(events)
    assert f"tier:{est.last_fit_report.chosen}" in spans


# -- ingest spans & overlap ---------------------------------------------------


def _sleepy_tar(tmp_path, n):
    """Tar whose members are placeholder bytes — decode is patched."""
    path = str(tmp_path / "sleepy.tar")
    with tarfile.open(path, "w") as tf:
        for i in range(n):
            data = b"x" * 64
            info = tarfile.TarInfo(f"img_{i:03d}.jpg")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    return path


def test_ingest_overlap_from_spans_matches_bench_methodology(
    tmp_path, monkeypatch
):
    """The bench ``e2e`` overlap efficiency = e2e_rate / min(decode_rate,
    featurize_rate), measured from three passes.  The trace recomputation
    (``max(decode_busy, consume_busy) / wall`` over span intervals of the
    ONE e2e pass) must land within 12% of it.  Decode/featurize costs are
    pinned by sleeps so the comparison is about the span plumbing, not
    scheduler noise — decode-bound, the realistic streaming regime."""
    # Jitter budget: the decode pool's width floor is HOST CORES (the
    # max_decode_threads default), so on a 2-core host TWO sleeps overlap
    # and the decode pass runs ~24 x 0.05 / 2 = 0.6 s; cross-pass
    # scheduler hiccups of ~80 ms were observed on loaded 2-core hosts,
    # so the band is 12% (~70 ms) — a real span-accounting bug skews the
    # two methodologies far past that (dropping the consume spans alone
    # moves it > 30%).
    n_images, batch = 24, 4
    decode_s, feat_s = 0.05, 0.015  # per image / per batch
    img = np.zeros((40, 40, 3), np.float32)

    def slow_decode(data):
        time.sleep(decode_s)
        return img

    monkeypatch.setattr(image_loaders, "decode_image", slow_decode)
    tar = _sleepy_tar(tmp_path, n_images)
    kw = dict(num_threads=1, decode_ahead_slots=2, transfer=False)

    # pass 1: decode-only ceiling (bench's decode_images_per_sec)
    t0 = time.perf_counter()
    with ingest.stream_batches(tar, batch, **kw) as st:
        chunks = [b.host for b in st]
    t_decode = time.perf_counter() - t0
    assert st.join(10.0)
    assert sum(c.shape[0] for c in chunks) == n_images

    # pass 2: featurize-only ceiling (bench's featurize_images_per_sec)
    t0 = time.perf_counter()
    for _ in chunks:
        time.sleep(feat_s)
    t_feat = time.perf_counter() - t0

    # pass 3: the overlapped e2e pipeline, traced
    path = _trace_to(tmp_path)
    t0 = time.perf_counter()
    with ingest.stream_batches(tar, batch, **kw) as st:
        for b in st:
            time.sleep(feat_s)  # the "featurize" of this chunk
    t_e2e = time.perf_counter() - t0
    assert st.join(10.0)
    trace.flush(path)
    trace.disable()

    rate_e2e = n_images / t_e2e
    bench_eff = rate_e2e / min(n_images / t_decode, n_images / t_feat)

    overlap = trace_view.overlap_from_spans(trace_view.load_events(path))
    assert overlap is not None
    assert overlap["decode_spans"] == n_images
    assert overlap["consume_spans"] == -(-n_images // batch)
    trace_eff = overlap["overlap_efficiency"]
    assert trace_eff is not None
    assert abs(trace_eff - bench_eff) <= 0.12 * bench_eff, (
        f"trace-recomputed overlap {trace_eff} vs bench-methodology "
        f"{bench_eff:.3f} (decode {t_decode:.3f}s, feat {t_feat:.3f}s, "
        f"e2e {t_e2e:.3f}s)"
    )
    # decode-bound stream: overlap should be high by construction
    assert trace_eff > 0.8


def test_early_stopped_stream_leaves_no_suspended_span(tmp_path, monkeypatch):
    """A consumer that abandons a stream mid-iteration must not leave the
    generator-hosted ingest.consume span suspended on this thread's span
    stack (it would corrupt every later span's depth/parent and the
    flight recorder's view): Stream.close() closes the drain generator,
    the span exits as aborted, the stack returns to its prior depth."""
    img = np.zeros((40, 40, 3), np.float32)
    monkeypatch.setattr(image_loaders, "decode_image", lambda data: img)
    tar = _sleepy_tar(tmp_path, 8)
    depth_before = len(trace._stack())
    with ingest.stream_batches(tar, 2, num_threads=1, transfer=False) as st:
        for _b in st:
            break  # abandon the stream mid-iteration
    assert st.join(10.0)
    assert len(trace._stack()) == depth_before, [
        s.name for s in trace._stack()
    ]
    aborted = [
        e for e in trace.flight_events()
        if e.get("name") == "ingest.consume"
        and e.get("args", {}).get("aborted")
    ]
    assert aborted, "abandoned consume span did not record its abort"


def test_ingest_producer_span_records_stats(tmp_path, monkeypatch):
    img = np.zeros((40, 40, 3), np.float32)
    monkeypatch.setattr(image_loaders, "decode_image", lambda data: img)
    tar = _sleepy_tar(tmp_path, 6)
    path = _trace_to(tmp_path)
    with ingest.stream_batches(tar, 2, num_threads=1, transfer=False) as st:
        list(st)
    assert st.join(10.0)
    trace.flush(path)
    spans = _spans_by_name(trace_view.load_events(path))
    prod = spans["ingest.produce"][0]
    assert prod["args"]["decoded"] == 6
    assert prod["args"]["batches"] == 3
    assert "ingest.ring_put" in spans and "ingest.ring_get" in spans


# -- metrics registry ---------------------------------------------------------


def test_metrics_registry_counters_gauges_histograms():
    m = trace.Metrics()
    assert m.inc("requests") == 1
    assert m.inc("requests", 4) == 5
    m.gauge("ring_depth", 3.0)
    for v in range(100):
        m.observe("latency_ms", float(v))
    snap = m.snapshot()
    assert snap["counters"] == {"requests": 5}
    assert snap["gauges"] == {"ring_depth": 3.0}
    h = snap["histograms"]["latency_ms"]
    assert h["count"] == 100 and h["min"] == 0.0 and h["max"] == 99.0
    assert 45.0 <= h["mean"] <= 55.0
    assert 45.0 <= h["p50"] <= 55.0 and h["p90"] >= h["p50"]
    json.dumps(snap)  # bench embeds this verbatim

    # snapshot(reset=True) clears atomically
    snap2 = m.snapshot(reset=True)
    assert snap2["counters"] == {"requests": 5}
    assert m.snapshot()["counters"] == {}


def test_metrics_snapshot_includes_fault_group():
    before = trace.metrics.snapshot()["faults"].get("trace_group_probe", 0)
    counters.record("trace_group_probe")
    snap = trace.metrics.snapshot()
    assert snap["faults"]["trace_group_probe"] == before + 1
    # the registry snapshot is what bench.py embeds — must be JSON-able
    json.dumps(snap)


def test_fault_counters_snapshot_reset_is_atomic():
    fc = FaultCounters()
    quiet = logging.getLogger("keystone_tpu.resilience")
    prev = quiet.level
    quiet.setLevel(logging.CRITICAL)
    try:
        stop = threading.Event()
        produced = {"n": 0}

        def hammer():
            while not stop.is_set():
                fc.record("hammered")
                produced["n"] += 1

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        for t in threads:
            t.start()
        collected = 0
        deadline = time.monotonic() + 0.5
        while time.monotonic() < deadline:
            collected += fc.snapshot(reset=True).get("hammered", 0)
        stop.set()
        for t in threads:
            t.join()
        collected += fc.snapshot(reset=True).get("hammered", 0)
    finally:
        quiet.setLevel(prev)
    # atomic snapshot+reset: every record lands in exactly one snapshot
    assert collected == produced["n"]
    assert fc.counts() == {}


# -- stage_timer & log level --------------------------------------------------


def test_stage_timer_same_log_line_and_span(tmp_path, caplog):
    path = _trace_to(tmp_path)
    with caplog.at_level(logging.INFO, logger="keystone_tpu"):
        with stage_timer("probe_stage"):
            pass
    assert any(
        "probe_stage took" in rec.getMessage() and rec.getMessage().endswith(" s")
        for rec in caplog.records
    )
    trace.flush(path)
    spans = _spans_by_name(trace_view.load_events(path))
    assert spans["probe_stage"][0]["cat"] == "stage"


def test_keystone_log_level_env(monkeypatch):
    root = logging.getLogger("keystone_tpu")
    prev = root.level
    try:
        monkeypatch.setenv("KEYSTONE_LOG_LEVEL", "DEBUG")
        configure_logging()
        assert root.level == logging.DEBUG
        monkeypatch.setenv("KEYSTONE_LOG_LEVEL", "warning")  # case-insensitive
        configure_logging()
        assert root.level == logging.WARNING
        monkeypatch.setenv("KEYSTONE_LOG_LEVEL", "15")  # numeric form
        configure_logging()
        assert root.level == 15
        # an explicit level always wins over the env
        configure_logging(logging.ERROR)
        assert root.level == logging.ERROR
        monkeypatch.setenv("KEYSTONE_LOG_LEVEL", "NOT_A_LEVEL")
        with pytest.raises(ValueError):
            configure_logging()
    finally:
        root.setLevel(prev)


# -- chaos --trace ------------------------------------------------------------


def test_chaos_schedule_trace_holds_never_silent_bar(tmp_path):
    import chaos

    # seed 4 -> nan_input: a typed FloatingPointError with a counted
    # nonfinite_model fault — both must be visible in the trace.
    path = str(tmp_path / "chaos_seed4.json")
    r = chaos.run_schedule(4, workload="mnist", trace_path=path)
    assert r.outcome == "typed_error"
    assert r.error_type == "FloatingPointError"
    assert chaos.verify_trace(path, r) == []
    # and the trace itself names the failure on a span
    events = trace_view.load_events(path)
    assert any(
        ev.get("args", {}).get("error") == "FloatingPointError"
        for ev in events
        if ev.get("ph") == "X"
    )


# -- trace_view CLI -----------------------------------------------------------


def test_trace_view_summarizes(tmp_path, capsys):
    path = _trace_to(tmp_path)
    with trace.span("stage_one", cat="stage"):
        time.sleep(0.01)
    with trace.span("stage_two", cat="stage"):
        pass
    counters.record("view_probe_fault")
    trace.flush(path)
    assert trace_view.main([path]) == 0
    out = capsys.readouterr().out
    assert "per-stage totals" in out
    assert "stage_one" in out and "stage_two" in out
    assert "view_probe_fault" in out
    assert "top 10 spans" in out
