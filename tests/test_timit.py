"""TIMIT loader + pipeline e2e on synthetic separable phone data."""

import numpy as np

from keystone_tpu.loaders.timit import timit_features_loader
from keystone_tpu.workloads.timit import TimitConfig, run


def write_split(tmp_path, name, n, rng, centers):
    k, d = centers.shape
    labels = rng.integers(0, k, n)
    data = centers[labels] + 0.4 * rng.normal(size=(n, d))
    data_path = tmp_path / f"{name}.csv"
    labels_path = tmp_path / f"{name}.labels"
    np.savetxt(data_path, data, delimiter=",", fmt="%.5f")
    with open(labels_path, "w") as fh:
        for i, l in enumerate(labels):
            fh.write(f"{i + 1} {l + 1}\n")  # 1-indexed rows and labels
    return str(data_path), str(labels_path), labels


class TestTimitLoader:
    def test_roundtrip(self, tmp_path, rng):
        centers = rng.normal(size=(5, 8))
        dp, lp, labels = write_split(tmp_path, "train", 20, rng, centers)
        data = timit_features_loader(dp, lp, dp, lp)
        assert data.train.data.shape == (20, 8)
        np.testing.assert_array_equal(data.train.labels, labels)


class TestTimitPipelineE2E:
    def test_learns_synthetic_phones(self, tmp_path, rng):
        d, k = 24, 6
        centers = rng.normal(scale=2.0, size=(k, d))
        tdp, tlp, _ = write_split(tmp_path, "train", 300, rng, centers)
        sdp, slp, _ = write_split(tmp_path, "test", 100, rng, centers)
        data = timit_features_loader(tdp, tlp, sdp, slp)
        conf = TimitConfig(
            num_cosines=3,
            num_cosine_features=128,
            num_epochs=2,
            gamma=0.2,
            lam=1e-3,
            num_classes=k,
            dimension=d,
        )
        results = run(conf, data)
        assert results["test_error"] < 10.0, results
