"""Image node tests, mirroring the reference suites' criteria
(src/test/scala/nodes/images/ConvolverSuite.scala, PoolingSuite.scala,
WindowingSuite.scala) plus naive-loop equivalence checks of the TPU-native
formulations."""

import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.images import (
    Convolver,
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
    Pooler,
    SymmetricRectifier,
    Windower,
)
from keystone_tpu.solvers.whitening import ZCAWhitenerEstimator
from keystone_tpu.utils.stats import about_eq


def naive_convolve(img, filters_flat, ws, normalize, var_constant, means=None):
    """Direct im2col reimplementation of reference Convolver.scala:93-136."""
    h, w, c = img.shape
    rh, rw = h - ws + 1, w - ws + 1
    rows = []
    for y in range(rh):
        for x in range(rw):
            # patch layout c + pox*C + poy*C*ws == [ky, kx, c] row-major
            rows.append(img[y : y + ws, x : x + ws, :].reshape(-1))
    patches = np.stack(rows)  # [rh*rw, ws*ws*c]
    if normalize:
        mu = patches.mean(axis=1, keepdims=True)
        var = patches.var(axis=1, ddof=1, keepdims=True)
        patches = (patches - mu) / np.sqrt(var + var_constant)
    if means is not None:
        patches = patches - means
    out = patches @ filters_flat.T  # [rh*rw, F]
    return out.reshape(rh, rw, filters_flat.shape[0])


class TestConvolver:
    def test_shapes_1x1(self, rng):
        # ConvolverSuite "1x1 patches convolutions": 4x4x3 image, 2 filters
        img = rng.normal(size=(1, 4, 4, 3)).astype(np.float32)
        filters = np.zeros((2, 1 * 1 * 3), np.float32)
        filters[0, 2] = 1.0
        filters[1, :] = 0.33
        conv = Convolver(filters, img_channels=3)
        out = conv(jnp.asarray(img))
        assert out.shape == (1, 4, 4, 2)

    def test_matches_naive_im2col(self, rng):
        img = rng.normal(size=(10, 10, 3)).astype(np.float32)
        filters = rng.normal(size=(4, 3 * 3 * 3)).astype(np.float32)
        conv = Convolver(filters, img_channels=3, normalize_patches=False)
        out = conv(jnp.asarray(img[None]))[0]
        expected = naive_convolve(img, filters, 3, False, 10.0)
        assert about_eq(out, expected, 1e-3)

    def test_matches_naive_with_normalization(self, rng):
        img = rng.normal(size=(8, 8, 3)).astype(np.float32)
        filters = rng.normal(size=(5, 3 * 3 * 3)).astype(np.float32)
        conv = Convolver(filters, img_channels=3, normalize_patches=True)
        out = conv(jnp.asarray(img[None]))[0]
        expected = naive_convolve(img, filters, 3, True, 10.0)
        assert about_eq(out, expected, 1e-3)

    def test_matches_naive_with_whitener_means(self, rng):
        img = rng.normal(size=(6, 6, 2)).astype(np.float32)
        filters = rng.normal(size=(3, 3 * 3 * 2)).astype(np.float32)
        means = rng.normal(size=(3 * 3 * 2,)).astype(np.float32)
        conv = Convolver(
            filters, whitener_means=means, img_channels=2, normalize_patches=True
        )
        out = conv(jnp.asarray(img[None]))[0]
        expected = naive_convolve(img, filters, 3, True, 10.0, means)
        assert about_eq(out, expected, 1e-3)


class TestPooler:
    def test_max_pooling_reference_values(self):
        # PoolingSuite "pooling": get(x,y) = 4x + y on a 4x4 grid; with the
        # [H, W] layout that image is value[y, x] = y*4 + x... the reference
        # fixture is transposed, so assert against its semantics directly:
        # pools of [0:2)x[0:2) blocks, max.
        img = np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)
        pool = Pooler(2, 2, None, "max")
        out = np.asarray(pool(jnp.asarray(img)))[0, :, :, 0]
        assert out[0, 0] == 5.0 and out[0, 1] == 7.0
        assert out[1, 0] == 13.0 and out[1, 1] == 15.0

    def test_sum_pooling_matches_naive(self, rng):
        img = rng.normal(size=(2, 9, 9, 3)).astype(np.float32)
        stride, ps = 3, 4
        pool = Pooler(stride, ps, jnp.abs, "sum")
        out = np.asarray(pool(jnp.asarray(img)))
        # naive per reference Pooler.scala:33-63
        ss = ps // 2
        npx = int(np.ceil((9 - ss) / stride))
        expected = np.zeros((2, npx, npx, 3), np.float32)
        for n in range(2):
            for iy, y in enumerate(range(ss, 9, stride)):
                for ix, x in enumerate(range(ss, 9, stride)):
                    y0, y1 = y - ps // 2, min(y + ps // 2, 9)
                    x0, x1 = x - ps // 2, min(x + ps // 2, 9)
                    block = np.abs(img[n, y0:y1, x0:x1, :])
                    expected[n, iy, ix, :] = block.sum(axis=(0, 1))
        assert about_eq(out, expected, 1e-3)

    def test_odd_pool_sizes_run(self, rng):
        # PoolingSuite "pooling odd": various conv/pool size combos must not crash
        for conv_size in [1, 2, 3, 4, 6, 8]:
            dim = 14 - conv_size + 1
            pool_reqd = int(np.ceil(dim / 2.0))
            ps = int(np.ceil(pool_reqd / 2.0) * 2)
            stride = dim - ps
            if stride <= 0:
                continue
            img = rng.normal(size=(1, dim, dim, 4)).astype(np.float32)
            out = Pooler(stride, ps, None, "sum")(jnp.asarray(img))
            assert out.shape[0] == 1 and out.shape[3] == 4


class TestWindower:
    def test_windows_match_naive(self, rng):
        img = rng.normal(size=(2, 6, 6, 3)).astype(np.float32)
        win = Windower(stride=2, window_size=3)
        out = np.asarray(win(jnp.asarray(img)))
        # reference Windower.scala:27-28: x outer, y inner
        expected = []
        for n in range(2):
            for x in range(0, 6 - 3 + 1, 2):
                for y in range(0, 6 - 3 + 1, 2):
                    expected.append(img[n, y : y + 3, x : x + 3, :])
        expected = np.stack(expected)
        assert out.shape == expected.shape
        assert about_eq(out, expected, 1e-6)


class TestSimpleNodes:
    def test_symmetric_rectifier(self, rng):
        img = rng.normal(size=(2, 4, 4, 3)).astype(np.float32)
        out = np.asarray(SymmetricRectifier(alpha=0.25)(jnp.asarray(img)))
        assert out.shape == (2, 4, 4, 6)
        assert about_eq(out[..., :3], np.maximum(0.0, img - 0.25), 1e-6)
        assert about_eq(out[..., 3:], np.maximum(0.0, -img - 0.25), 1e-6)

    def test_pixel_scaler(self):
        img = jnp.full((1, 2, 2, 3), 255.0)
        assert about_eq(PixelScaler()(img), np.ones((1, 2, 2, 3)), 1e-6)

    def test_grayscale_bgr(self, rng):
        img = rng.uniform(size=(1, 3, 3, 3)).astype(np.float32)
        out = np.asarray(GrayScaler()(jnp.asarray(img)))
        expected = (
            0.2989 * img[..., 2] + 0.5870 * img[..., 1] + 0.1140 * img[..., 0]
        )[..., None]
        assert about_eq(out, expected, 1e-5)

    def test_grayscale_non_rgb(self, rng):
        img = rng.uniform(size=(1, 3, 3, 5)).astype(np.float32)
        out = np.asarray(GrayScaler()(jnp.asarray(img)))
        expected = np.sqrt((img**2).mean(axis=-1))[..., None]
        assert about_eq(out, expected, 1e-5)

    def test_vectorizer_channel_major_order(self):
        # element (y, x, c) must land at index c + x*C + y*C*W
        img = np.zeros((1, 2, 3, 4), np.float32)
        img[0, 1, 2, 3] = 7.0
        vec = np.asarray(ImageVectorizer()(jnp.asarray(img)))[0]
        assert vec[3 + 2 * 4 + 1 * 4 * 3] == 7.0


class TestZCA:
    def test_whitened_covariance_near_identity(self, rng):
        # PCA-suite-style property: strongly-correlated data whitens to ~I
        n, d = 2000, 8
        base = rng.normal(size=(n, d)).astype(np.float32)
        mixed = base @ rng.normal(size=(d, d)).astype(np.float32) * 3.0
        zca = ZCAWhitenerEstimator().fit_single(jnp.asarray(mixed))
        out = np.asarray(zca(jnp.asarray(mixed)))
        cov = out.T @ out / (n - 1)
        # 0.1 shrinkage keeps it slightly below I on strong components
        assert np.all(np.abs(cov - np.eye(d)) < 0.15)

    def test_matches_direct_formula(self, rng):
        n, d = 50, 6
        x = rng.normal(size=(n, d)).astype(np.float32)
        zca = ZCAWhitenerEstimator().fit_single(jnp.asarray(x))
        xc = x - x.mean(axis=0)
        _, s, vt = np.linalg.svd(xc, full_matrices=True)
        s2 = np.zeros(d, np.float32)
        s2[: len(s)] = s * s / (n - 1.0)
        w = (vt.T * (s2 + 0.1) ** -0.5) @ vt
        assert about_eq(zca.whitener, w, 1e-2)

    def test_underdetermined_uses_full_v(self, rng):
        # n < d: null-space components get the 0.1^-0.5 gain, not zero
        n, d = 5, 12
        x = rng.normal(size=(n, d)).astype(np.float32)
        zca = ZCAWhitenerEstimator().fit_single(jnp.asarray(x))
        assert zca.whitener.shape == (d, d)
        eigvals = np.linalg.eigvalsh(np.asarray(zca.whitener))
        assert np.sum(np.abs(eigvals - 0.1**-0.5) < 1e-3) >= d - n
