"""Loader tests on the reference's real miniature datasets
(mirroring VOCLoaderSuite.scala / ImageNetLoaderSuite.scala criteria) and
MAP evaluator tests with hand-computed average precisions."""

import os

import numpy as np
import pytest

from keystone_tpu.evaluation.map import mean_average_precision
from keystone_tpu.loaders.image_loaders import imagenet_loader, voc_loader

REF_IMG = "/root/reference/src/test/resources/images"


@pytest.mark.skipif(not os.path.exists(REF_IMG), reason="reference fixtures absent")
class TestVOCLoader:
    def test_loads_sample(self):
        data = voc_loader(f"{REF_IMG}/voc", f"{REF_IMG}/voclabels.csv")
        # VOCLoaderSuite criteria (:16-32)
        assert len(data) == 10
        pm = [i for i, f in enumerate(data.filenames) if f.endswith("000104.jpg")]
        assert len(pm) == 1
        assert 14 in data.labels[pm[0]] and 19 in data.labels[pm[0]]
        all_labels = [l for ls in data.labels for l in ls]
        assert len(all_labels) == 13
        assert len(set(all_labels)) == 9
        for img in data.images:
            assert img.ndim == 3 and img.shape[2] == 3
            assert img.dtype == np.float32


@pytest.mark.skipif(not os.path.exists(REF_IMG), reason="reference fixtures absent")
class TestImageNetLoader:
    def test_loads_sample(self):
        data = imagenet_loader(
            f"{REF_IMG}/imagenet", f"{REF_IMG}/imagenet-test-labels"
        )
        # ImageNetLoaderSuite criteria (:10-25)
        assert len(data) == 5
        assert set(data.labels.tolist()) == {12}
        assert all(f.startswith("n15075141") for f in data.filenames)


class TestMeanAveragePrecision:
    def test_perfect_ranking_gives_ap_one(self):
        # class 0: items 0,1 positive and ranked top -> AP = 1
        actual = [[0], [0], [1], [1]]
        scores = np.array([[0.9, 0.1], [0.8, 0.2], [0.1, 0.9], [0.2, 0.8]])
        aps = mean_average_precision(actual, scores, 2)
        np.testing.assert_allclose(aps, [1.0, 1.0], atol=1e-9)

    def test_hand_computed_ap(self):
        # class 0 positives at ranks 1 and 3 (scores descending):
        # precisions at positive hits: 1/1 and 2/3; recalls 0.5, 1.0
        # 11-point AP: levels 0-0.5 -> max prec with recall>=t = 1.0 (6 pts),
        # levels 0.6-1.0 -> 2/3 (5 pts) => (6*1 + 5*2/3)/11
        actual = [[0], [], [0], []]
        scores = np.array([[0.9], [0.8], [0.7], [0.1]])
        aps = mean_average_precision(actual, scores, 1)
        expected = (6 * 1.0 + 5 * (2.0 / 3.0)) / 11.0
        np.testing.assert_allclose(aps, [expected], atol=1e-9)

    def test_no_positives_gives_zero(self):
        aps = mean_average_precision([[1], [1]], np.zeros((2, 2)), 2)
        assert aps[0] == 0.0
