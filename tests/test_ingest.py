"""Streaming-ingest tests (core.ingest): ring-buffer semantics — ordering,
backpressure, clean shutdown (no leaked threads under pytest), empty tar,
batch-size remainder — and eager-vs-streaming feature equality.

The decode path is the REAL one (JPEG tars built by tests/faults.py, the
native/PIL decoder), so these tests also hold the streaming pipeline to the
eager loaders' resilience contract: corrupt members are counted skips,
producer failures surface typed on the consumer, and a hung decoder is
interruptible by ``resilience.deadline`` instead of deadlocking the ring.
"""

import glob
import io
import tarfile
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import faults

from keystone_tpu.core import ingest
from keystone_tpu.core.resilience import DeadlineExceeded, counters, deadline
from keystone_tpu.loaders import image_loaders
from keystone_tpu.workloads.fv_common import (
    bucket_by_shape,
    scatter_features_streaming,
    stream_descriptor_buckets,
)


def _make_tar(path, sizes, rng, corrupt=()):
    """Tar of JPEGs with per-member (h, w) ``sizes`` (mixed shapes bucket
    into separate chunks).  Returns member names."""
    names = []
    with tarfile.open(path, "w") as tf:
        for i, (h, w) in enumerate(sizes):
            data = faults.make_jpeg_bytes(rng, h, w)
            if i in corrupt:
                data = faults.corrupt_jpeg(data, rng)
            info = tarfile.TarInfo(f"img_{i:04d}.jpg")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
            names.append(info.name)
    return names


@pytest.fixture
def tar_uniform(tmp_path, rng):
    """10 same-shape JPEGs — batch 4 yields 4+4+2 (remainder)."""
    path = str(tmp_path / "uniform.tar")
    names = _make_tar(path, [(48, 48)] * 10, rng)
    return path, names


@pytest.fixture
def tar_mixed(tmp_path, rng):
    """12 JPEGs in two shapes, interleaved — exercises shape bucketing."""
    sizes = [(48, 48), (64, 40)] * 6
    path = str(tmp_path / "mixed.tar")
    names = _make_tar(path, sizes, rng)
    return path, names


def _eager(path):
    """The eager loader's (name, image) order — the streaming oracle."""
    return list(image_loaders._iter_tar_images(path, num_threads=1))


def test_stream_yields_every_image_in_order(tar_uniform):
    path, _ = tar_uniform
    eager = _eager(path)
    got = {}
    with ingest.stream_batches(path, 4, transfer=False) as st:
        for batch in st:
            assert batch.host.shape[0] == len(batch.names) == len(batch.indices)
            for i, name, img in zip(
                batch.indices.tolist(), batch.names, batch.host
            ):
                got[i] = (name, img)
    assert st.join(10.0)
    assert sorted(got) == list(range(len(eager)))
    for i, (name, img) in enumerate(eager):
        assert got[i][0] == name
        np.testing.assert_array_equal(got[i][1], img)


def test_batch_size_remainder(tar_uniform):
    path, _ = tar_uniform
    with ingest.stream_batches(path, 4, transfer=False) as st:
        sizes = [len(b) for b in st]
    assert sizes == [4, 4, 2]
    assert st.stats.decoded == 10 and st.stats.batches == 3


def test_mixed_shapes_bucket_and_preserve_ordinals(tar_mixed):
    path, _ = tar_mixed
    eager = _eager(path)
    with ingest.stream_batches(path, 3, transfer=False) as st:
        batches = list(st)
    # every chunk is single-shape
    for b in batches:
        assert b.host.shape[1:3] == b.shape
        assert len({img.shape for img in b.host}) == 1
    # ordinals cover the stream exactly once, in decode-survival order
    all_idx = np.concatenate([b.indices for b in batches])
    assert sorted(all_idx.tolist()) == list(range(len(eager)))
    name_of = {
        i: n
        for b in batches
        for i, n in zip(b.indices.tolist(), b.names)
    }
    assert [name_of[i] for i in range(len(eager))] == [n for n, _ in eager]


def test_empty_tar(tmp_path, rng):
    path = str(tmp_path / "empty.tar")
    _make_tar(path, [], rng)
    with ingest.stream_batches(path, 4, transfer=False) as st:
        assert list(st) == []
    assert st.join(10.0)
    assert st.stats.decoded == 0 and st.stats.batches == 0


def test_backpressure_producer_blocks_at_capacity(tar_uniform):
    path, _ = tar_uniform
    st = ingest.stream_batches(
        path, 2, capacity=1, num_threads=2, transfer=False
    )
    with st:
        first = next(iter(st))
        assert len(first) == 2
        # A full ring must stall the producer rather than let decode run
        # unboundedly ahead; give it time to fill the single slot and block.
        deadline_t = time.monotonic() + 5.0
        while (
            st.stats.producer_stalls == 0 and time.monotonic() < deadline_t
        ):
            time.sleep(0.02)
        assert st.stats.producer_stalls >= 1
        assert st.stats.ring_max_depth <= 1
        rest = list(st)
    assert st.join(10.0)
    assert sum(len(b) for b in rest) == 10 - 2


def test_early_consumer_exit_joins_all_threads(tar_uniform):
    path, _ = tar_uniform
    before = {t.name for t in threading.enumerate()}
    st = ingest.stream_batches(path, 2, capacity=1, transfer=False)
    for batch in st:
        break  # consumer bails after ONE batch (e.g. an exception upstream)
    st.close()
    assert st.join(10.0), "decoder/producer threads leaked past close()"
    leaked = {
        t.name
        for t in threading.enumerate()
        if t.name.startswith(("keystone-ingest", "keystone-decode"))
    } - before
    assert not leaked, leaked


def test_exhausted_stream_joins_all_threads(tar_uniform):
    path, _ = tar_uniform
    with ingest.stream_batches(path, 4, transfer=False) as st:
        list(st)
    assert st.join(10.0)


def test_producer_error_surfaces_on_consumer(tmp_path):
    st = ingest.stream_batches(str(tmp_path / "nope.tar"), 4, transfer=False)
    with pytest.raises(FileNotFoundError):
        next(iter(st))
    assert st.join(10.0)


def test_corrupt_member_is_counted_skip(tmp_path, rng):
    path = str(tmp_path / "corrupt.tar")
    names = _make_tar(path, [(48, 48)] * 6, rng, corrupt=(2, 4))
    before = counters.get("corrupt_image")
    with ingest.stream_batches(path, 3, transfer=False) as st:
        got = [n for b in st for n in b.names]
    assert counters.get("corrupt_image") - before == 2
    assert st.stats.skipped == 2
    assert got == [n for i, n in enumerate(names) if i not in (2, 4)]


def test_transfer_stage_yields_device_batches(tar_uniform):
    path, _ = tar_uniform
    with ingest.stream_batches(path, 4) as st:
        for batch in st:
            assert batch.device is not None
            assert isinstance(batch.device, jax.Array)
            np.testing.assert_array_equal(
                np.asarray(batch.device), batch.host
            )


def test_decode_ahead_env(monkeypatch):
    monkeypatch.setenv("KEYSTONE_DECODE_AHEAD", "3")
    assert image_loaders.decode_ahead() == 3
    monkeypatch.setenv("KEYSTONE_DECODE_AHEAD", "")
    assert image_loaders.decode_ahead() == image_loaders._DECODE_AHEAD
    monkeypatch.setenv("KEYSTONE_DECODE_AHEAD", "nope")
    with pytest.raises(ValueError):
        image_loaders.decode_ahead()
    monkeypatch.setenv("KEYSTONE_DECODE_AHEAD", "-1")
    with pytest.raises(ValueError):
        image_loaders.decode_ahead()


def test_ring_capacity_env(monkeypatch):
    monkeypatch.setenv("KEYSTONE_RING_CAPACITY", "7")
    assert ingest.ring_capacity() == 7
    monkeypatch.setenv("KEYSTONE_RING_CAPACITY", "0")
    with pytest.raises(ValueError):
        ingest.ring_capacity()


def test_streaming_features_equal_eager(tar_mixed):
    """The acceptance oracle: streaming features bit-identical to the eager
    decode-then-featurize path on the same tar fixture."""
    path, _ = tar_mixed
    feat = jax.jit(
        lambda x: jnp.stack(
            [jnp.mean(x, axis=(1, 2, 3)), jnp.max(x, axis=(1, 2, 3))], axis=1
        )
    )
    eager = _eager(path)
    images = [img for _, img in eager]
    buckets = bucket_by_shape(images)
    out_eager = np.zeros((len(images), 2), np.float32)
    for _shape, (idx, batch) in buckets.items():
        out_eager[idx] = np.asarray(feat(jnp.asarray(batch)))
    with ingest.stream_batches(path, 3) as st:
        out_stream, names = scatter_features_streaming(st, feat, 2)
    assert names == [n for n, _ in eager]
    np.testing.assert_array_equal(out_stream, out_eager)


def test_stream_descriptor_buckets_match_eager_layout(tar_mixed):
    path, _ = tar_mixed
    per_image = jax.jit(lambda x: jnp.mean(x, axis=3))  # [b, H, W]
    eager = _eager(path)
    images = [img for _, img in eager]
    eager_buckets = {
        shape: (idx, np.asarray(per_image(jnp.asarray(batch))))
        for shape, (idx, batch) in bucket_by_shape(images).items()
    }
    with ingest.stream_batches(path, 3) as st:
        stream_buckets, names = stream_descriptor_buckets(st, per_image)
    assert names == [n for n, _ in eager]
    assert set(stream_buckets) == set(eager_buckets)
    for shape, (idx_e, desc_e) in eager_buckets.items():
        idx_s, desc_s = stream_buckets[shape]
        np.testing.assert_array_equal(np.asarray(idx_s), np.asarray(idx_e))
        np.testing.assert_array_equal(np.asarray(desc_s), desc_e)


def test_stream_bucket_order_matches_eager_first_occurrence(tmp_path, rng):
    """Bucket dict ORDER must equal eager first-occurrence order even when
    a later shape completes its first batch earlier: seeded column
    sampling (fv_common.sample_columns) iterates the dict from one rng, so
    chunk-emission order would silently change PCA/GMM sampling."""
    # shape A first at ordinal 0, but shape B fills a 3-batch first
    sizes = [(48, 48), (64, 40), (64, 40), (64, 40), (48, 48), (48, 48)]
    path = str(tmp_path / "order.tar")
    _make_tar(path, sizes, rng)
    per_image = jax.jit(lambda x: jnp.mean(x, axis=3))
    eager_order = list(
        bucket_by_shape([img for _, img in _eager(path)])
    )
    with ingest.stream_batches(path, 3) as st:
        stream_buckets, _ = stream_descriptor_buckets(st, per_image)
    assert list(stream_buckets) == eager_order == [(48, 48), (64, 40)]


def test_hung_decoder_trips_deadline_not_deadlock(tar_uniform, monkeypatch):
    """A decoder thread that hangs must surface as a typed DeadlineExceeded
    on the consumer (resilience.deadline) — never a deadlocked ring."""
    path, _ = tar_uniform
    real = image_loaders.decode_image
    calls = {"n": 0}

    def hanging(data):
        calls["n"] += 1
        if calls["n"] == 3:
            time.sleep(2.5)  # outlives the watchdog budget below
        return real(data)

    monkeypatch.setattr(image_loaders, "decode_image", hanging)
    st = ingest.stream_batches(path, 4, num_threads=2)
    with pytest.raises(DeadlineExceeded):
        with deadline(0.6, phase="ingest"):
            for batch in st:
                np.asarray(batch.host)
    st.close()
    # The producer abandons the hung future; only the one sleeping worker
    # remains until its sleep ends — it must exit by then (no leak).
    assert st._thread.is_alive() is False or st.join(5.0)
    assert st.join(5.0)


# -- the multiprocess shared-memory decode backend ----------------------------


def _devshm_segments():
    return set(glob.glob("/dev/shm/psm_*"))


def test_process_backend_bit_identical_to_thread(tar_mixed):
    """The spawned-worker backend must reproduce the thread path exactly:
    same chunks, same ordinals, same member names, same pixels — over a
    mixed-shape tar (bucketing exercised across the IPC boundary)."""
    path, _ = tar_mixed
    with ingest.stream_batches(path, 4, transfer=False) as st:
        thread_chunks = [
            (b.index, b.indices.copy(), list(b.names), b.host.copy())
            for b in st
        ]
    assert st.join(10.0)
    cfg = ingest.StreamConfig.from_env(
        decode_backend="process", decode_procs=2
    )
    with ingest.stream_batches(path, 4, transfer=False, config=cfg) as st2:
        proc_chunks = [
            (b.index, b.indices.copy(), list(b.names), b.host.copy())
            for b in st2
        ]
    assert st2.join(20.0), "decode worker processes leaked"
    assert len(thread_chunks) == len(proc_chunks)
    for a, b in zip(thread_chunks, proc_chunks):
        assert a[0] == b[0] and a[2] == b[2]
        assert np.array_equal(a[1], b[1])
        assert np.array_equal(a[3], b[3])


def test_process_backend_early_exit_leaks_no_shm(tar_uniform):
    """Early consumer exit with worker-decoded images still in flight:
    every shared-memory block must be released (the pool registry drains
    and /dev/shm gains nothing) and every worker process joined."""
    path, _ = tar_uniform
    before = _devshm_segments()
    cfg = ingest.StreamConfig.from_env(
        decode_backend="process", decode_procs=2, ring_capacity=1
    )
    st = ingest.stream_batches(path, 2, transfer=False, config=cfg)
    next(iter(st))  # one chunk, then bail with decodes still in flight
    st.close()
    assert st.join(20.0), "decode worker processes leaked"
    assert st._proc_pool is not None
    assert st._proc_pool._live_shm == {}
    # allow the kernel a beat to reap unlinked names
    for _ in range(50):
        leaked = _devshm_segments() - before
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"leaked /dev/shm segments: {leaked}"


def test_process_backend_corrupt_member_counted_skip(tmp_path, rng):
    """A corrupt member decoded in a worker process honors the same
    counted-skip contract as the thread path."""
    path = str(tmp_path / "bad.tar")
    names = _make_tar(path, [(48, 48)] * 6, rng, corrupt=(2,))
    before = counters.get("corrupt_image")
    cfg = ingest.StreamConfig.from_env(
        decode_backend="process", decode_procs=2
    )
    with ingest.stream_batches(path, 3, transfer=False, config=cfg) as st:
        got = [n for b in st for n in b.names]
    assert st.join(20.0)
    assert counters.get("corrupt_image") == before + 1
    assert got == [n for i, n in enumerate(names) if i != 2]
    assert st.stats.skipped == 1


def test_decode_backend_env_and_validation(monkeypatch):
    monkeypatch.setenv("KEYSTONE_DECODE_BACKEND", "process")
    assert ingest.StreamConfig.from_env().decode_backend == "process"
    monkeypatch.setenv("KEYSTONE_DECODE_BACKEND", "gpu")
    with pytest.raises(ValueError, match="KEYSTONE_DECODE_BACKEND"):
        ingest.StreamConfig.from_env()
    with pytest.raises(ValueError, match="decode_backend"):
        ingest.StreamConfig(
            decode_threads=1, decode_ahead=0, ring_capacity=1,
            decode_backend="gpu",
        )
    # decode_procs resolves to the decode width when unset
    cfg = ingest.StreamConfig(
        decode_threads=3, decode_ahead=0, ring_capacity=1
    )
    assert cfg.decode_procs == 3
    # the env knob agrees with the field on the meaning of 0 (= auto)
    monkeypatch.setenv("KEYSTONE_DECODE_BACKEND", "thread")
    monkeypatch.setenv("KEYSTONE_DECODE_PROCS", "0")
    cfg = ingest.StreamConfig.from_env(decode_threads=2)
    assert cfg.decode_procs == 2
    monkeypatch.setenv("KEYSTONE_DECODE_PROCS", "-1")
    with pytest.raises(ValueError, match="KEYSTONE_DECODE_PROCS"):
        ingest.StreamConfig.from_env()
