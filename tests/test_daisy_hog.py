"""DAISY / HOG tests: naive-loop transcriptions of the reference Scala code
(DaisyExtractorSuite/HogExtractorSuite analogs) as oracles, plus structural
invariants."""

import math

import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.daisy import DaisyExtractor
from keystone_tpu.ops.hog import HogExtractor
from keystone_tpu.utils.stats import about_eq


def conv2d_same(img, xfilt, yfilt):
    """Reference ImageUtils.conv2D: zero pad (len-1) split floor/ceil, true
    convolution, same output size.  img [H, W]."""
    h, w = img.shape
    xl, yl = len(xfilt), len(yfilt)
    ph_lo = (yl - 1) // 2
    pw_lo = (xl - 1) // 2
    padded = np.zeros((h + yl - 1, w + xl - 1))
    padded[ph_lo : ph_lo + h, pw_lo : pw_lo + w] = img
    xr, yr = xfilt[::-1], yfilt[::-1]
    mid = np.zeros((h, w + xl - 1))
    for y in range(h):
        for x in range(w + xl - 1):
            mid[y, x] = sum(padded[y + i, x] * yr[i] for i in range(yl))
    out = np.zeros((h, w))
    for y in range(h):
        for x in range(w):
            out[y, x] = sum(mid[y, x + i] * xr[i] for i in range(xl))
    return out


def naive_daisy(img, ext: DaisyExtractor):
    """Transcription of DaisyExtractor.apply (:106-191) on [H, W]."""
    h, w = img.shape
    T, Q, R, H = ext.daisy_t, ext.daisy_q, ext.daisy_r, ext.daisy_h
    f1, f2 = [1.0, 0.0, -1.0], [1.0, 2.0, 1.0]
    ix = conv2d_same(img, f1, f2)
    iy = conv2d_same(img, f2, f1)

    sigma_sq = [(R * q / (2 * Q)) ** 2 for q in range(Q + 1)]
    diff = [b - a for a, b in zip(sigma_sq, sigma_sq[1:])]
    gs = []
    for t in diff:
        rad = int(math.ceil(math.sqrt(-2 * t * math.log(1e-6) - t * math.log(2 * math.pi * t))))
        ns = np.arange(-rad, rad + 1)
        gs.append(np.exp(-(ns**2) / (2 * t)) / math.sqrt(2 * math.pi * t))

    layers = [[None] * H for _ in range(Q)]
    for a_i in range(H):
        ang = 2 * math.pi * a_i / H
        m = np.maximum(math.cos(ang) * ix + math.sin(ang) * iy, 0.0)
        layers[0][a_i] = conv2d_same(m, gs[0], gs[0])
        for l in range(1, Q):
            layers[l][a_i] = conv2d_same(layers[l - 1][a_i], gs[l], gs[l])

    def norm_hist(v):
        nv = np.linalg.norm(v)
        return v / nv if nv > 1e-8 else np.zeros_like(v)

    xs = list(range(ext.pixel_border, w - ext.pixel_border, ext.stride))
    ys = list(range(ext.pixel_border, h - ext.pixel_border, ext.stride))
    out = np.zeros((len(xs) * len(ys), ext.feature_size), np.float64)
    for i, x in enumerate(xs):
        for j, y in enumerate(ys):
            row = i * len(ys) + j
            center = norm_hist(np.array([layers[0][hh][y, x] for hh in range(H)]))
            out[row, :H] = center
            for l in range(Q):
                rad = R * (1.0 + l) / Q
                for ac in range(T):
                    th = 2 * math.pi * (ac - 1) / T
                    sx = x + int(round(rad * math.sin(th)))
                    sy = y + int(round(rad * math.cos(th)))
                    hist = norm_hist(np.array([layers[l][hh][sy, sx] for hh in range(H)]))
                    col0 = H + ac * Q * H + l * H
                    out[row, col0 : col0 + H] = hist
    return out


def naive_hog(img, bin_size):
    """Transcription of HogExtractor (:65-296) on [H, W, C] (x = col)."""
    h, w, c = img.shape
    nx, ny = round(w / bin_size), round(h / bin_size)
    hist = np.zeros(nx * ny * 18)
    for x in range(1, nx * bin_size - 1):
        for y in range(1, ny * bin_size - 1):
            best = (-np.inf, None, None)
            for ch in (2, 1, 0):
                dx = img[y, x + 1, ch] - img[y, x - 1, ch]
                dy = img[y + 1, x, ch] - img[y - 1, x, ch]
                m2 = dx * dx + dy * dy
                if m2 > best[0]:
                    best = (m2, dx, dy)
            m2, dx, dy = best
            mag = math.sqrt(m2)
            from keystone_tpu.ops.hog import UU, VV

            bd, bi = 0.0, 0
            for o in range(9):
                dot = UU[o] * dy + VV[o] * dx
                if dot > bd:
                    bd, bi = dot, o
                elif -dot > bd:
                    bd, bi = -dot, o + 9
            yp = (y + 0.5) / bin_size - 0.5
            xp = (x + 0.5) / bin_size - 0.5
            iyp, ixp = math.floor(yp), math.floor(xp)
            vy0, vx0 = yp - iyp, xp - ixp
            for (cy, cx, wgt) in (
                (iyp, ixp, (1 - vy0) * (1 - vx0)),
                (iyp + 1, ixp, vy0 * (1 - vx0)),
                (iyp, ixp + 1, (1 - vy0) * vx0),
                (iyp + 1, ixp + 1, vy0 * vx0),
            ):
                if 0 <= cx < nx and 0 <= cy < ny:
                    hist[cx + cy * nx + bi * nx * ny] += wgt * mag
    norm = np.zeros(nx * ny)
    for o in range(9):
        for y in range(ny):
            for x in range(nx):
                v = hist[x + y * nx + o * nx * ny] + hist[x + y * nx + (o + 9) * nx * ny]
                norm[x + y * nx] += v * v
    nxf, nyf = max(nx - 2, 0), max(ny - 2, 0)
    feats = np.zeros((nxf * nyf, 32))
    for x in range(nxf):
        for y in range(nyf):
            row = y + x * nyf

            def bn(y0, x0):
                off = y0 * nx + x0
                return 1.0 / math.sqrt(
                    norm[off] + norm[off + 1] + norm[off + nx] + norm[off + nx + 1] + 0.0001
                )

            n1, n2, n3, n4 = bn(y + 1, x + 1), bn(y + 1, x), bn(y, x + 1), bn(y, x)
            t = [0.0] * 4
            fo = 0
            for o in range(18):
                hv = hist[(y + 1) * nx + (x + 1) + o * nx * ny]
                hs = [min(hv * nk, 0.2) for nk in (n1, n2, n3, n4)]
                feats[row, fo] = 0.5 * sum(hs)
                for i in range(4):
                    t[i] += hs[i]
                fo += 1
            for o in range(9):
                hv = hist[(y + 1) * nx + (x + 1) + o * nx * ny] + hist[
                    (y + 1) * nx + (x + 1) + (o + 9) * nx * ny
                ]
                hs = [min(hv * nk, 0.2) for nk in (n1, n2, n3, n4)]
                feats[row, fo] = 0.5 * sum(hs)
                fo += 1
            for i in range(4):
                feats[row, fo] = 0.2357 * t[i]
                fo += 1
            feats[row, fo] = 0.0
    return feats


class TestDaisy:
    def test_matches_naive_transcription(self, rng):
        img = rng.uniform(size=(40, 40)).astype(np.float32)
        ext = DaisyExtractor()
        got = np.asarray(ext(jnp.asarray(img[None])))[0]
        expected = naive_daisy(img.astype(np.float64), ext)
        assert got.shape == expected.shape == (4, 200)
        assert about_eq(got, expected, 1e-3)

    def test_feature_size(self):
        assert DaisyExtractor().feature_size == 8 * (8 * 3 + 1)

    def test_flat_image_matches_naive(self):
        # constant image: interior gradients are zero but the zero-padded
        # 'same' conv creates border energy that normalization amplifies —
        # the naive transcription must agree exactly (reference behavior)
        img = np.full((40, 40), 0.7, np.float32)
        ext = DaisyExtractor()
        got = np.asarray(ext(jnp.asarray(img[None])))[0]
        expected = naive_daisy(img.astype(np.float64), ext)
        assert about_eq(got, expected, 1e-3)


class TestHog:
    def test_matches_naive_transcription(self, rng):
        img = rng.uniform(0, 255, size=(20, 24, 3)).astype(np.float32)
        got = np.asarray(HogExtractor(4)(jnp.asarray(img[None] / 255.0)))[0]
        expected = naive_hog(img.astype(np.float64) / 255.0, 4)
        assert got.shape == expected.shape
        assert about_eq(got, expected, 1e-3)

    def test_truncation_feature_zero_and_shapes(self, rng):
        img = rng.uniform(size=(1, 32, 32, 3)).astype(np.float32)
        out = np.asarray(HogExtractor(8)(jnp.asarray(img)))
        nx = ny = 4
        assert out.shape == (1, (nx - 2) * (ny - 2), 32)
        assert np.all(out[..., 31] == 0.0)

    def test_too_small_image_gives_empty(self, rng):
        img = rng.uniform(size=(1, 8, 8, 3)).astype(np.float32)
        out = np.asarray(HogExtractor(4)(jnp.asarray(img)))
        assert out.shape == (1, 0, 32)
