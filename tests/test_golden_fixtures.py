"""Golden-fixture tests against the reference's own shipped artifacts
(reference EncEvalSuite.scala:14-40, VLFeatSuite.scala:12-52).

The reference checkout ships a real VOC GMM codebook
(src/test/resources/images/voc_codebook/{means.csv,variances.csv,priors})
and a real VOC image (images/000012.jpg).  Its golden CSV dumps
(`images/feats.csv`, `images/feats128.csv` — the MATLAB vl_phow outputs the
suites compare against) are NOT present in the checkout, so the exact
FV-sum constant (40.109097, EncEvalSuite.scala:38) and the +/-1/99.5% SIFT
envelope (VLFeatSuite.scala:48-51) cannot be reproduced here.  What CAN be
grounded on the real artifacts, and is below:

* the GMM loader reads the real codebook files byte-for-byte
  (format parity with GaussianMixtureModel.scala:83-90);
* dense SIFT runs on the real image at the reference suite's exact
  parameters (stepSize=3, binSize=4, 4 scales, scaleStep=0 —
  VLFeatSuite.scala:19-26) and satisfies every property the kernel
  contract promises (count formula, 128-dim, quantization range,
  low-contrast zeroing);
* the vectorized Fisher-vector encoder agrees with an independent float64
  NumPy transcription of the enceval formulas on REAL descriptors encoded
  against the REAL codebook (not synthetic data).
"""

import os

import numpy as np
import pytest

from keystone_tpu.loaders.image_loaders import decode_image
from keystone_tpu.ops.fisher import fisher_vector
from keystone_tpu.ops.images import GrayScaler, PixelScaler
from keystone_tpu.ops.sift import DESC_DIM, SIFTExtractor
from keystone_tpu.solvers.gmm import GaussianMixtureModel
from keystone_tpu.solvers.pca import compute_pca

REF_IMG = "/root/reference/src/test/resources/images"
CODEBOOK = f"{REF_IMG}/voc_codebook"

pytestmark = pytest.mark.skipif(
    not os.path.exists(CODEBOOK), reason="reference fixtures absent"
)


def load_codebook() -> GaussianMixtureModel:
    return GaussianMixtureModel.load(
        f"{CODEBOOK}/means.csv", f"{CODEBOOK}/variances.csv", f"{CODEBOOK}/priors"
    )


def real_image_gray() -> np.ndarray:
    """000012.jpg -> [1, H, W, 1] grayscale in [0, 1], the exact preprocessing
    of VLFeatSuite.scala:13-15 (mapPixels(_/255) then toGrayScale)."""
    raw = decode_image(open(f"{REF_IMG}/000012.jpg", "rb").read())
    batch = raw[None]  # [1, H, W, 3] BGR in [0, 255]
    return np.asarray(GrayScaler()(PixelScaler()(batch)))


class TestVocCodebook:
    def test_loads_real_codebook(self):
        """Format parity with GaussianMixtureModel.load (scala :83-90): the
        VOC codebook is 80-dim (PCA'd SIFT) x 256 centers; priors one value
        per line."""
        gmm = load_codebook()
        assert gmm.dim == 80
        assert gmm.k == 256
        w = np.asarray(gmm.weights)
        assert abs(w.sum() - 1.0) < 1e-3
        assert (w > 0).all()
        assert (np.asarray(gmm.variances) > 0).all()
        assert np.isfinite(np.asarray(gmm.means)).all()


class TestSiftRealImage:
    """VLFeatSuite.scala:12-52 analog on the real image, real parameters."""

    PARAMS = dict(step_size=3, bin_size=4, scales=4, scale_step=0)

    def test_descriptor_grid_and_quantization(self):
        gray = real_image_gray()
        ext = SIFTExtractor(**self.PARAMS)
        descs = np.asarray(ext(gray))  # [1, 128, D]
        n, d, cols = descs.shape
        assert n == 1 and d == DESC_DIM
        # "Resulting SIFTs must be 128-dimensional" + the count is exactly
        # the multi-scale keypoint-grid formula (VLFeat.cxx:93-108)
        assert cols == ext.num_descriptors(gray.shape[1], gray.shape[2])
        assert cols > 10_000  # a 333x500 image yields a dense grid
        # quantization contract: min(floor(512 v), 255) as integers in [0,255]
        assert descs.min() >= 0.0 and descs.max() <= 255.0
        assert np.all(descs == np.floor(descs))
        assert descs.max() > 64  # real image energy actually lands in bins

    def test_low_contrast_zeroing_on_real_image(self):
        """Descriptors in flat regions (sky) are zeroed by the contrast
        threshold (VLFeat.cxx:167-169); textured regions are not.  On this
        image the overwhelming majority of the dense grid is textured."""
        gray = real_image_gray()
        descs = np.asarray(SIFTExtractor(**self.PARAMS)(gray))[0]  # [128, D]
        norms = np.linalg.norm(descs, axis=0)
        nonzero_frac = float((norms > 0).mean())
        assert nonzero_frac > 0.5
        # zeroed columns are exactly zero, not merely small
        zeroed = descs[:, norms == 0]
        assert zeroed.size == 0 or np.all(zeroed == 0)


class TestFisherVectorRealData:
    """EncEvalSuite.scala:14-40 analog: encode real descriptors of the real
    image against the real VOC codebook; verify the vectorized encoder
    against an independent float64 transcription of the enceval formulas
    (gmm-fisher fisher.cxx mean/variance gradients, alpha=1, pnorm=0)."""

    @staticmethod
    def naive_fv64(x, means, variances, weights):
        """Independent NumPy float64 FV: explicit per-descriptor loop."""
        x = x.astype(np.float64)
        means = means.astype(np.float64)
        variances = variances.astype(np.float64)
        weights = weights.astype(np.float64)
        n, d = x.shape
        k = means.shape[1]
        sigma = np.sqrt(variances)
        # posteriors, numerically stable
        log_pdf = np.empty((n, k))
        for j in range(k):
            u = (x - means[:, j]) / sigma[:, j]
            log_pdf[:, j] = (
                -0.5 * np.sum(u * u, axis=1)
                - np.sum(np.log(sigma[:, j]))
                - 0.5 * d * np.log(2 * np.pi)
                + np.log(weights[j])
            )
        log_norm = log_pdf.max(axis=1, keepdims=True)
        q = np.exp(log_pdf - log_norm)
        q /= q.sum(axis=1, keepdims=True)
        g_mean = np.zeros((d, k))
        g_var = np.zeros((d, k))
        for i in range(n):
            for j in range(k):
                u = (x[i] - means[:, j]) / sigma[:, j]
                g_mean[:, j] += q[i, j] * u
                g_var[:, j] += q[i, j] * (u * u - 1.0)
        g_mean /= n * np.sqrt(weights)
        g_var /= n * np.sqrt(2.0 * weights)
        return np.concatenate([g_mean, g_var], axis=1)

    def test_real_descriptors_real_codebook_match_naive(self):
        gray = real_image_gray()
        descs = np.asarray(
            SIFTExtractor(**TestSiftRealImage.PARAMS)(gray)
        )[0].T  # [D, 128] descriptors as rows
        # project to the codebook's 80 dims the way the VOC pipeline does
        # (VOCSIFTFisher.scala PCA to descDim=80), fitting on this image's own
        # descriptors since the pipeline's PCA matrix isn't a shipped artifact
        pca = np.asarray(compute_pca(descs.astype(np.float32), 80))  # [128, 80]
        x = descs @ pca  # [D, 80]
        gmm = load_codebook()
        # subsample for the O(n*k*d) python loop; fixed stride = deterministic
        sub = x[:: max(1, x.shape[0] // 400)][:400]
        got = np.asarray(
            fisher_vector(
                sub.astype(np.float32), gmm.means, gmm.variances, gmm.weights
            )
        )
        want = self.naive_fv64(
            sub,
            np.asarray(gmm.means),
            np.asarray(gmm.variances),
            np.asarray(gmm.weights),
        )
        assert got.shape == (80, 512)  # [d, 2K], FisherVector.scala:33-34
        assert np.isfinite(got).all()
        # f32 vectorized vs f64 loop on real data
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-4)

    def test_full_image_fv_finite_and_nontrivial(self):
        """Whole-image FV (all ~20k+ real descriptors) against the real
        codebook is finite and carries signal in most blocks."""
        gray = real_image_gray()
        descs = np.asarray(
            SIFTExtractor(**TestSiftRealImage.PARAMS)(gray)
        )[0].T
        pca = np.asarray(compute_pca(descs.astype(np.float32), 80))
        x = (descs @ pca).astype(np.float32)
        gmm = load_codebook()
        fv = np.asarray(
            fisher_vector(x, gmm.means, gmm.variances, gmm.weights)
        )
        assert fv.shape == (80, 512)
        assert np.isfinite(fv).all()
        assert float(np.abs(fv).sum()) > 1.0


class TestSiftExternalOracle:
    """External-oracle grounding (SURVEY §2.8): our dense SIFT vs OpenCV's
    independent SIFT implementation on the real image.

    The reference's own oracle (MATLAB vl_phow dump, VLFeatSuite.scala:41)
    is absent from its checkout; OpenCV is the available independent
    implementation.  Conventions differ in known ways: OpenCV weights the
    descriptor window with a Gaussian (vl_dsift flat window here), and its
    gradient convention measures angles with y UP (dy = I[y-1]-I[y+1],
    calcSIFTDescriptor) versus atan2(gy down, gx) here — so orientation
    bins map through a reflection plus a 1-bin circular shift.  A keypoint
    of size 2*(binSize*SIFT_DESCR_SCL_FCTR^-1)... concretely size =
    2*b/3 makes OpenCV's histogram bin width equal our b.  With exactly
    that predicted mapping (no per-pair search), the two implementations
    agree strongly on real data — the criterion is cosine similarity, not
    the reference's +/-1 envelope, because the flat-vs-Gaussian window is a
    real (documented) difference, not a bug."""

    def test_descriptor_agreement_with_opencv(self):
        cv2 = pytest.importorskip("cv2")
        import jax.numpy as jnp

        from keystone_tpu.ops.sift import _scale_geometry

        gray = real_image_gray()[0, :, :, 0]
        h, w = gray.shape
        b, step = 4, 3
        ext = SIFTExtractor(step_size=step, bin_size=b, scales=1, scale_step=0)
        ours = np.asarray(ext(jnp.asarray(gray[None])))[0]  # [128, D]
        ys, xs = _scale_geometry(h, w, step, b, 1, 0)
        centers = [(x + 1.5 * b, y + 1.5 * b) for y in ys for x in xs]
        idx = np.arange(0, len(centers), 37)[:400]

        kps = [
            cv2.KeyPoint(float(centers[i][0]), float(centers[i][1]), 2 * b / 3.0, 0.0, 1, 0)
            for i in idx
        ]
        kps_out, desc_cv = cv2.SIFT_create().compute(
            (gray * 255).astype(np.uint8), kps
        )
        assert desc_cv is not None and len(kps_out) == len(idx)

        a = ours[:, idx].T.astype(np.float64)
        bm = desc_cv.astype(np.float64)
        an = a / np.maximum(np.linalg.norm(a, axis=1, keepdims=True), 1e-9)
        bn = bm / np.maximum(np.linalg.norm(bm, axis=1, keepdims=True), 1e-9)
        # fixed convention mapping: reflect orientation axis, shift 1
        mapped = np.roll(an.reshape(-1, 4, 4, 8)[..., ::-1], 1, axis=3).reshape(-1, 128)
        cos = np.sum(mapped * bn, axis=1)
        assert np.median(cos) > 0.85, np.median(cos)
        assert np.mean(cos) > 0.80, np.mean(cos)
        # sanity: the agreement is specific to the derived mapping — a wrong
        # orientation shift must score clearly worse
        wrong = np.roll(an.reshape(-1, 4, 4, 8)[..., ::-1], 5, axis=3).reshape(-1, 128)
        assert np.median(np.sum(wrong * bn, axis=1)) < np.median(cos) - 0.1
