"""FusedConvFeaturizer vs the op-by-op chain (the fused path is the cifar
workload default; equivalence here is what licenses that swap — reference
chain RandomPatchCifar.scala:53-56, ConvolverSuite/PoolingSuite spirit)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops.conv_fused import FusedConvFeaturizer
from keystone_tpu.ops.images import (
    Convolver,
    ImageVectorizer,
    Pooler,
    SymmetricRectifier,
)
from keystone_tpu.core.pipeline import Pipeline


def _unfused(filters, means, alpha, stride, size):
    return Pipeline(
        [
            Convolver(filters, whitener_means=means, normalize_patches=True,
                      img_channels=filters.shape[-1]),
            SymmetricRectifier(alpha=alpha),
            Pooler(stride, size, None, "sum"),
            ImageVectorizer(),
        ]
    )


@pytest.mark.parametrize(
    "h,w,fsz,ws,stride,size",
    [
        (32, 32, 100, 6, 13, 14),  # the RandomPatchCifar shape
        (20, 24, 7, 5, 4, 6),      # uneven dims, truncated edge pools
        (16, 16, 3, 3, 5, 5),      # odd pool size (span ps-1 semantics)
    ],
)
def test_fused_matches_unfused_f32(rng, h, w, fsz, ws, stride, size):
    imgs = jnp.asarray(rng.uniform(0, 255, (5, h, w, 3)).astype(np.float32))
    filters = jnp.asarray(rng.normal(size=(fsz, ws, ws, 3)).astype(np.float32))
    means = jnp.asarray(rng.normal(size=(ws * ws * 3,)).astype(np.float32))
    ref = np.asarray(_unfused(filters, means, 0.25, stride, size)(imgs))
    got = np.asarray(
        FusedConvFeaturizer(
            filters, whitener_means=means, pool_stride=stride, pool_size=size,
            alpha=0.25, activation_dtype=jnp.float32,
        )(imgs)
    )
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5 * np.abs(ref).max())


def test_fused_bf16_within_storage_rounding(rng):
    imgs = jnp.asarray(rng.uniform(0, 255, (4, 32, 32, 3)).astype(np.float32))
    filters = jnp.asarray(rng.normal(size=(24, 6, 6, 3)).astype(np.float32))
    means = jnp.asarray(rng.normal(size=(108,)).astype(np.float32))
    ref = np.asarray(_unfused(filters, means, 0.25, 13, 14)(imgs))
    got = np.asarray(
        FusedConvFeaturizer(
            filters, whitener_means=means, pool_stride=13, pool_size=14,
            alpha=0.25,  # default bf16 activations
        )(imgs)
    )
    # bf16 storage rounds each activation once (~2^-8 relative); pooled sums
    # of 196 activations stay within ~1% of the f32 chain.
    err = np.abs(got - ref).max() / np.abs(ref).max()
    assert err < 1e-2, err


def test_fused_no_normalization_no_means(rng):
    imgs = jnp.asarray(rng.uniform(0, 1, (3, 16, 16, 2)).astype(np.float32))
    filters = jnp.asarray(rng.normal(size=(5, 4, 4, 2)).astype(np.float32))
    ref = np.asarray(
        Pipeline(
            [
                Convolver(filters, normalize_patches=False, img_channels=2),
                SymmetricRectifier(alpha=0.1),
                Pooler(4, 4, None, "sum"),
                ImageVectorizer(),
            ]
        )(imgs)
    )
    got = np.asarray(
        FusedConvFeaturizer(
            filters, pool_stride=4, pool_size=4, alpha=0.1,
            normalize_patches=False, activation_dtype=jnp.float32,
        )(imgs)
    )
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5 * np.abs(ref).max())


def test_pallas_rect_pool_matches_xla(rng, monkeypatch):
    """The opt-in Pallas rect+pool stage (KEYSTONE_PALLAS=1) must match the
    XLA two-reduce_window form — it is kept as a measured-slower template
    (ops/rect_pool_pallas.py verdict), so correctness is its whole value.
    The reference is pinned to the XLA branch (env var cleared) so this
    never degenerates into comparing the kernel with itself."""
    from keystone_tpu.ops.rect_pool_pallas import rect_pool_pallas

    monkeypatch.delenv("KEYSTONE_PALLAS", raising=False)
    imgs = jnp.asarray(rng.uniform(0, 255, (4, 32, 32, 3)).astype(np.float32))
    filters = jnp.asarray(rng.normal(size=(24, 6, 6, 3)).astype(np.float32))
    means = jnp.asarray(rng.normal(size=(108,)).astype(np.float32))
    node_ = FusedConvFeaturizer(
        filters, whitener_means=means, pool_stride=13, pool_size=14,
        alpha=0.25, activation_dtype=jnp.float32,
    )
    ref = np.asarray(node_(imgs))
    got = np.asarray(
        rect_pool_pallas(
            node_.conv(imgs), pool_stride=13, pool_size=14, alpha=0.25,
            images_per_step=2, interpret=True,
        )
    )
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-4 * np.abs(ref).max())
