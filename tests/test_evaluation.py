"""Evaluator tests (reference src/test/scala/evaluation/*Suite.scala)."""


from keystone_tpu.evaluation.multiclass import (
    BinaryClassifierEvaluator,
    MulticlassClassifierEvaluator,
)


def test_multiclass_perfect():
    actual = [0, 1, 2, 1, 0]
    m = MulticlassClassifierEvaluator(actual, actual, 3)
    assert m.total_accuracy == 1.0
    assert m.total_error == 0.0
    assert m.macro_precision == 1.0


def test_multiclass_confusion_and_metrics():
    actual = [0, 0, 1, 1, 2, 2]
    pred = [0, 1, 1, 1, 2, 0]
    m = MulticlassClassifierEvaluator(pred, actual, 3)
    cm = m.confusion_matrix  # rows=actual, cols=pred
    assert cm[0, 0] == 1 and cm[0, 1] == 1
    assert cm[1, 1] == 2
    assert cm[2, 2] == 1 and cm[2, 0] == 1
    assert abs(m.total_error - 2.0 / 6.0) < 1e-9
    assert abs(m.total_accuracy - 4.0 / 6.0) < 1e-9
    # class-1 precision: predicted 1 three times, 2 correct
    assert abs(m.class_metrics[1].precision - 2.0 / 3.0) < 1e-9
    s = m.summary(["a", "b", "c"])
    assert "Total Accuracy" in s and "Macro F1" in s


def test_binary_metrics():
    pred = [True, True, False, False, True]
    act = [True, False, False, True, True]
    b = BinaryClassifierEvaluator(pred, act)
    assert b.tp == 2 and b.fp == 1 and b.tn == 1 and b.fn == 1
    assert abs(b.accuracy - 3.0 / 5.0) < 1e-9
    assert abs(b.precision - 2.0 / 3.0) < 1e-9
    assert abs(b.recall - 2.0 / 3.0) < 1e-9
    assert abs(b.f_score() - 2.0 / 3.0) < 1e-9


def test_multiclass_matches_sklearn_style_micro(rng):
    n, k = 500, 7
    actual = rng.integers(0, k, n)
    pred = actual.copy()
    flip = rng.random(n) < 0.3
    pred[flip] = (pred[flip] + 1 + rng.integers(0, k - 1, flip.sum())) % k
    m = MulticlassClassifierEvaluator(pred, actual, k)
    acc = (pred == actual).mean()
    assert abs(m.total_accuracy - acc) < 1e-9
    assert abs(m.total_error - (1 - acc)) < 1e-9
