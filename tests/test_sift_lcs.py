"""Dense SIFT / LCS extractor tests.

The reference's golden-file fixtures (feats128.csv for SIFT) are absent from
its own test resources, so the criteria here are: structural invariants
(shape, quantization range, descriptor count), naive-loop equivalence for
LCS against a direct transcription of the reference's per-pixel code, and
behavioral SIFT properties (rotation shifts orientation mass, flat images
give zero descriptors, contrast threshold)."""

import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.lcs import LCSExtractor, _same_conv2d_zero
from keystone_tpu.ops.sift import SIFTExtractor
from keystone_tpu.utils.stats import about_eq


class TestSIFT:
    def test_shapes_and_quantization(self, rng):
        img = rng.uniform(size=(2, 48, 48)).astype(np.float32)
        ext = SIFTExtractor(step_size=4, bin_size=4, scales=2, scale_step=0)
        out = np.asarray(ext(jnp.asarray(img)))
        assert out.shape[0] == 2 and out.shape[1] == 128
        assert out.shape[2] == ext.num_descriptors(48, 48)
        assert out.min() >= 0.0 and out.max() <= 255.0
        assert np.all(out == np.floor(out))  # quantized
        assert out.max() > 0  # something fired on random texture

    def test_flat_image_zero_descriptors(self):
        img = jnp.full((1, 40, 40), 0.5, jnp.float32)
        ext = SIFTExtractor(step_size=4, bin_size=4, scales=2, scale_step=0)
        out = np.asarray(ext(img))
        # no gradient -> norms below contrast threshold -> all zeroed
        assert np.all(out == 0.0)

    def test_contrast_threshold_zeroes_weak_regions(self, rng):
        # left half flat, right half textured: descriptors fully inside the
        # flat half must be zero, textured ones nonzero
        img = np.full((1, 60, 60), 0.5, np.float32)
        img[0, :, 30:] = rng.uniform(size=(60, 30)).astype(np.float32)
        ext = SIFTExtractor(step_size=3, bin_size=4, scales=1, scale_step=0)
        out = np.asarray(ext(jnp.asarray(img)))
        col_norms = np.linalg.norm(out[0], axis=0)
        assert (col_norms == 0).any() and (col_norms > 0).any()

    def test_90deg_rotation_permutes_orientations(self, rng):
        # rotating the image by 90° must keep descriptor energy but move it
        # across orientation bins: total energy is preserved ~exactly
        img = rng.uniform(size=(36, 36)).astype(np.float32)
        ext = SIFTExtractor(step_size=3, bin_size=4, scales=1, scale_step=0)
        a = np.asarray(ext(jnp.asarray(img[None])))
        b = np.asarray(ext(jnp.asarray(np.rot90(img).copy()[None])))
        assert a.shape == b.shape
        assert abs(a.sum() - b.sum()) / max(a.sum(), 1.0) < 0.05

    def test_multiscale_grids_nested_when_steps_equal(self):
        # scaleStep=0: all scales share step; offsets are arranged so frame
        # centers coincide (VLFeat.cxx:92-95)
        ext = SIFTExtractor(step_size=2, bin_size=4, scales=3, scale_step=0)
        from keystone_tpu.ops.sift import _scale_geometry

        centers = []
        for s in range(3):
            b = 4 + 2 * s
            ys, xs = _scale_geometry(64, 64, 2, b, 3, s)
            centers.append(ys[0] + 1.5 * b)  # first frame center
        assert centers[0] == centers[1] == centers[2]


def naive_lcs(img, stride, stride_start, sub):
    """Direct transcription of LCSExtractor.scala:52-126 (with x = column
    axis, y = row axis; spatially symmetric ops make the convention moot)."""
    h, w, c = img.shape
    box = np.full(sub, 1.0 / sub)

    def conv_same(plane):
        padded = np.zeros((h + sub - 1, w + sub - 1))
        lo = (sub - 1) // 2
        padded[lo : lo + h, lo : lo + w] = plane
        mid = np.zeros((h, w + sub - 1))
        for y in range(h):
            for x in range(w + sub - 1):
                acc = 0.0
                for i in range(sub):
                    acc += padded[y + i, x] * box[sub - 1 - i]
                mid[y, x] = acc
        out = np.zeros((h, w))
        for y in range(h):
            for x in range(w):
                acc = 0.0
                for i in range(sub):
                    acc += mid[y, x + i] * box[sub - 1 - i]
                out[y, x] = acc
        return out

    means = [conv_same(img[:, :, ch]) for ch in range(c)]
    stds = [
        np.sqrt(np.maximum(conv_same(img[:, :, ch] ** 2) - means[ch] ** 2, 0))
        for ch in range(c)
    ]
    xs = list(range(stride_start, w - stride_start, stride))
    ys = list(range(stride_start, h - stride_start, stride))
    nbr = list(range(-2 * sub + sub // 2 - 1, sub + sub // 2 - 1 + 1, sub))
    cols = []
    for x in xs:
        for y in ys:
            vals = []
            for ch in range(c):
                for nx in nbr:
                    for ny in nbr:
                        vals.append(means[ch][y + ny, x + nx])
                        vals.append(stds[ch][y + ny, x + nx])
            cols.append(vals)
    return np.array(cols).T  # [descDim, K]


class TestLCS:
    def test_conv_same_matches_reference_padding(self, rng):
        img = rng.uniform(size=(1, 7, 9, 1)).astype(np.float32)
        box = np.full(4, 0.25, np.float32)
        got = np.asarray(_same_conv2d_zero(jnp.asarray(img), box, box))[0, :, :, 0]
        h, w = 7, 9
        padded = np.zeros((h + 3, w + 3))
        padded[1 : 1 + h, 1 : 1 + w] = img[0, :, :, 0]  # lo = (4-1)//2 = 1
        full = np.zeros((h, w))
        for y in range(h):
            for x in range(w):
                acc = 0.0
                for i in range(4):
                    for j in range(4):
                        acc += padded[y + i, x + j] * box[3 - i] * box[3 - j]
                full[y, x] = acc
        assert about_eq(got, full, 1e-4)

    def test_matches_naive_transcription(self, rng):
        img = rng.uniform(size=(32, 32, 3)).astype(np.float32)
        ext = LCSExtractor(stride=5, stride_start=12, sub_patch_size=3)
        got = np.asarray(ext(jnp.asarray(img[None])))[0]
        expected = naive_lcs(img.astype(np.float64), 5, 12, 3)
        assert got.shape == expected.shape
        assert about_eq(got, expected, 1e-3)

    def test_descriptor_dim_96_for_rgb(self, rng):
        # the canonical config: 4x4 neighborhood x 3 channels x (mean, std)
        img = rng.uniform(size=(1, 64, 64, 3)).astype(np.float32)
        ext = LCSExtractor(stride=4, stride_start=16, sub_patch_size=6)
        out = np.asarray(ext(jnp.asarray(img)))
        assert out.shape[1] == 96
        assert out.shape[2] == ext.num_keypoints(64, 64)
