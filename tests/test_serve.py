"""Serving subsystem tests (core.serve): fused per-bucket AOT inference,
the dynamic request batcher, typed online failure, the SLO bench, and the
fresh-process cold start.

The invariant under test is the chaos harness's, extended online: every
served answer is BIT-EQUAL to the offline ``pipeline(x)`` apply, or the
failure is typed and counted — never a silent wrong answer, never a dead
thread, never a poisoned batch.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import faults
from keystone_tpu.core import serve as kserve
from keystone_tpu.core.pipeline import FunctionTransformer, Pipeline
from keystone_tpu.core.resilience import counters

pytestmark = pytest.mark.serve

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _toy_pipe(rng, d=16):
    # Deliberately fusion-invariant arithmetic: a matmul's batch-1 gemv
    # path rounds differently than the batched gemm, and even an
    # elementwise mul+add chain changes bits when XLA contracts it to an
    # fma — either would make the engine's parity check drop buckets
    # nondeterministically across backends.  One multiply + one max are
    # each exactly rounded with no fusion opportunity, so eager == jit ==
    # every bucket, and the tests get a deterministic (1, 2, 4) live set.
    # (The parity-drop behaviors have their own dedicated tests.)
    w = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
    return FunctionTransformer(lambda x: jnp.maximum(x * w, b), name="toy")


@pytest.fixture
def engine(rng):
    cfg = kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0)
    return kserve.ServingEngine(
        _toy_pipe(rng), np.zeros(16, np.float32), config=cfg, label="test"
    )


def _requests(rng, n):
    return rng.normal(size=(n, 16)).astype(np.float32)


# -- config -------------------------------------------------------------------


class TestServeConfig:
    def test_env_seeding(self, monkeypatch):
        monkeypatch.setenv(kserve.BUCKETS_ENV, "8,2,2,32")
        monkeypatch.setenv(kserve.MAX_WAIT_ENV, "7.5")
        cfg = kserve.ServeConfig.from_env()
        assert cfg.buckets == (2, 8, 32)  # sorted, deduped
        assert cfg.max_wait_ms == 7.5
        assert cfg.max_batch == 32
        assert cfg.eager_flush is True

    def test_max_batch_cap_and_extend(self, monkeypatch):
        monkeypatch.setenv(kserve.BUCKETS_ENV, "1,4,16,64")
        monkeypatch.setenv(kserve.MAX_BATCH_ENV, "8")
        assert kserve.ServeConfig.from_env().buckets == (1, 4, 8)
        monkeypatch.setenv(kserve.MAX_BATCH_ENV, "128")
        assert kserve.ServeConfig.from_env().buckets == (1, 4, 16, 64, 128)

    def test_eager_flush_knob(self, monkeypatch):
        monkeypatch.setenv(kserve.EAGER_FLUSH_ENV, "0")
        assert kserve.ServeConfig.from_env().eager_flush is False

    def test_invalid_env_is_typed(self, monkeypatch):
        monkeypatch.setenv(kserve.BUCKETS_ENV, "1,banana")
        with pytest.raises(ValueError, match="comma-separated"):
            kserve.ServeConfig.from_env()
        monkeypatch.setenv(kserve.BUCKETS_ENV, "0,4")
        with pytest.raises(ValueError, match=">= 1"):
            kserve.ServeConfig.from_env()
        monkeypatch.delenv(kserve.BUCKETS_ENV)
        monkeypatch.setenv(kserve.MAX_WAIT_ENV, "-2")
        with pytest.raises(ValueError, match=">= 0"):
            kserve.ServeConfig.from_env()

    def test_bad_buckets_rejected(self):
        with pytest.raises(ValueError):
            kserve.ServeConfig(buckets=())
        with pytest.raises(ValueError):
            kserve.ServeConfig(buckets=(0, 2))


# -- the fused AOT engine -----------------------------------------------------


class TestServingEngine:
    def test_infer_bit_equal_to_offline_every_size(self, engine, rng):
        # covers in-bucket, padded-remainder, and multi-chunk paths
        for n in (1, 2, 3, 4, 5, 7, 9, 12):
            reqs = _requests(rng, n)
            assert np.array_equal(engine.infer(reqs), engine.offline(reqs)), n

    def test_every_bucket_planned_and_recorded(self, engine):
        assert sorted(engine.memory_plans) == [1, 2, 4]
        rec = engine.record()
        json.dumps(rec)  # JSON-able for bench artifacts
        assert rec["live_buckets"] == [1, 2, 4]
        assert rec["parity_ok"] is True
        assert set(rec["memory_plans"]) == {"1", "2", "4"}
        # the preflight compiled the very executables that serve
        assert all(b in engine._exec for b in (1, 2, 4))

    def test_warmup_drops_bucket_that_breaks_parity(self, rng):
        cfg = kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0)
        eng = kserve.ServingEngine(
            _toy_pipe(rng), np.zeros(16, np.float32), config=cfg,
            label="parity", warmup=False,
        )
        real = eng._exec[1]

        def skewed(pipe, batch):
            return real(pipe, batch) + jnp.float32(1e-7)

        eng._exec[1] = skewed
        before = counters.get("serve_bucket_parity_dropped")
        eng.warmup()
        assert eng.parity_ok is True
        assert eng.buckets() == (2, 4)  # bucket 1 dropped, counted
        assert counters.get("serve_bucket_parity_dropped") == before + 1
        # the engine still answers single requests (padded into bucket 2)
        reqs = _requests(rng, 1)
        assert np.array_equal(eng.infer(reqs), eng.offline(reqs))

    def test_all_buckets_failing_parity_reanchors_self_consistent(self, rng):
        cfg = kserve.ServeConfig(buckets=(1, 2), max_wait_ms=2.0)
        eng = kserve.ServingEngine(
            _toy_pipe(rng), np.zeros(16, np.float32), config=cfg,
            label="noparity", warmup=False,
        )
        execs = dict(eng._exec)

        def skew(b):
            return lambda pipe, batch: execs[b](pipe, batch) + jnp.float32(2e-7)

        eng._exec[1] = skew(1)
        eng._exec[2] = skew(2)
        before = counters.get("serve_parity_unverified")
        eng.warmup()
        assert eng.parity_ok is False
        assert counters.get("serve_parity_unverified") == before + 1
        # both buckets agree with each other -> both survive re-anchoring
        assert eng.buckets() == (1, 2)

    def test_runtime_oom_retires_bucket_and_reanswers(self, engine, rng):
        real = engine._execute
        state = {"n": 0}

        def failing(bucket, dev):
            if bucket == 4 and state["n"] < 1:
                state["n"] += 1
                raise faults.resource_exhausted_error()
            return real(bucket, dev)

        engine._execute = failing
        before = counters.get("serve_burst_oom")
        reqs = _requests(rng, 6)
        try:
            out = engine.infer(reqs)
        finally:
            engine._execute = real
        assert np.array_equal(out, engine.offline(reqs))
        assert engine.buckets() == (1, 2)
        assert counters.get("serve_burst_oom") == before + 1

    def test_oom_on_last_bucket_is_typed(self, rng):
        cfg = kserve.ServeConfig(buckets=(2,), max_wait_ms=1.0)
        eng = kserve.ServingEngine(
            _toy_pipe(rng), np.zeros(16, np.float32), config=cfg, label="solo"
        )
        eng._execute = lambda b, d: (_ for _ in ()).throw(
            faults.resource_exhausted_error()
        )
        with pytest.raises(kserve.ServingUnavailable):
            eng.infer(_requests(rng, 2))


# -- the dynamic request batcher ----------------------------------------------


class TestServer:
    def test_concurrent_clients_bit_equal_in_order(self, engine, rng):
        reqs = _requests(rng, 40)
        offline = engine.offline(reqs)
        answers = [None] * len(reqs)
        errors = []

        def client(cid, stride=4):
            try:
                futs = [
                    (i, server.submit(reqs[i]))
                    for i in range(cid, len(reqs), stride)
                ]
                for i, f in futs:
                    answers[i] = f.result(30.0)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        with kserve.Server(engine) as server:
            ts = [
                threading.Thread(target=client, args=(c,)) for c in range(4)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(30.0)
            stats = server.stats
        assert not errors, errors
        assert np.array_equal(np.stack(answers), offline)
        assert stats.answered == len(reqs)
        assert stats.batches >= 1
        assert server.join(5.0), "server threads leaked"

    def test_malformed_requests_typed_counted_never_poison(self, engine, rng):
        good = _requests(rng, 6)
        before = counters.get("serve_malformed_request")
        with kserve.Server(engine) as server:
            with pytest.raises(kserve.MalformedRequest, match="shape"):
                server.submit(np.zeros(7, np.float32))
            nan = good[0].copy()
            nan[3] = np.nan
            with pytest.raises(kserve.MalformedRequest, match="NaN"):
                server.submit(nan)
            with pytest.raises(kserve.MalformedRequest, match="castable"):
                server.submit(np.array(["x"] * 16, dtype=object))
            futs = [server.submit(r) for r in good]
            answers = np.stack([f.result(30.0) for f in futs])
            assert server.stats.malformed == 3
        assert counters.get("serve_malformed_request") == before + 3
        assert np.array_equal(answers, engine.offline(good))

    def test_burst_oom_degrades_never_wrong(self, engine, rng):
        real = engine._execute
        state = {"n": 0}

        def failing(bucket, dev):
            if bucket == 4 and state["n"] < 1:
                state["n"] += 1
                raise faults.resource_exhausted_error()
            return real(bucket, dev)

        engine._execute = failing
        reqs = _requests(rng, 12)
        try:
            with kserve.Server(engine) as server:
                futs = [server.submit(r) for r in reqs]
                answers = np.stack([f.result(30.0) for f in futs])
        finally:
            engine._execute = real
        assert np.array_equal(answers, engine.offline(reqs))
        assert 4 not in engine.buckets()

    def test_deadline_flush_answers_partial_buckets(self, rng):
        # strict two-trigger flushing (eager idle flush off): a single
        # request must still be answered within ~max_wait, not wait for a
        # full largest bucket that will never arrive
        cfg = kserve.ServeConfig(
            buckets=(1, 2, 4), max_wait_ms=20.0, eager_flush=False
        )
        eng = kserve.ServingEngine(
            _toy_pipe(rng), np.zeros(16, np.float32), config=cfg, label="ddl"
        )
        req = _requests(rng, 1)[0]
        with kserve.Server(eng) as server:
            t0 = time.perf_counter()
            out = server.predict(req, timeout=30.0)
            dt = time.perf_counter() - t0
            assert server.stats.flush_deadline >= 1
            assert server.stats.flush_idle == 0
        assert np.array_equal(out, eng.offline(req[None])[0])
        assert dt < 5.0  # answered by the deadline, not a stuck queue

    def test_close_answers_pending_typed_and_joins(self, engine, rng):
        real = engine._execute

        def slow(bucket, dev):
            time.sleep(0.2)
            return real(bucket, dev)

        engine._execute = slow
        reqs = _requests(rng, 12)
        try:
            server = kserve.Server(engine)
            futs = [server.submit(r) for r in reqs]
            server.close()
            assert server.join(10.0), "server threads leaked after close"
        finally:
            engine._execute = real
        resolved = 0
        for f in futs:
            try:
                f.result(5.0)
                resolved += 1
            except kserve.ServingUnavailable:
                pass  # the typed pending-at-close answer
        assert resolved < len(futs)  # at least some were failed typed
        with pytest.raises(kserve.ServingUnavailable):
            server.submit(reqs[0])

    def test_future_timeout_is_typed(self, engine, rng):
        fut = kserve.ServeFuture()
        with pytest.raises(TimeoutError):
            fut.result(0.01)

    def test_close_racing_submit_resolves_every_future(self, engine, rng):
        """ISSUE 12 regression: a close() racing in-flight submits from a
        second thread must fail every accepted future typed
        (ServingUnavailable) — never leave one unresolved forever.  The
        slowed execute keeps collected multi-chunk batches in the
        assembler's hands when close lands, the historical leak (the
        not-yet-chunked tail of a collected batch was failed by nobody)."""
        real = engine._execute

        def slow(bucket, dev):
            time.sleep(0.05)
            return real(bucket, dev)

        engine._execute = slow
        try:
            for round_ in range(3):
                server = kserve.Server(engine)
                futs: list = []
                stop_submitting = threading.Event()

                def submitter():
                    reqs = _requests(rng, 64)
                    for r in reqs:
                        if stop_submitting.is_set():
                            return
                        try:
                            futs.append(server.submit(r))
                        except kserve.ServingUnavailable:
                            return  # closed — the typed post-close answer

                threads = [
                    threading.Thread(target=submitter) for _ in range(2)
                ]
                for t in threads:
                    t.start()
                time.sleep(0.02 * (round_ + 1))  # vary where close lands
                server.close()
                assert server.join(10.0), "server threads leaked after close"
                stop_submitting.set()
                for t in threads:
                    t.join(10.0)
                for f in futs:
                    try:
                        # Every ACCEPTED submit resolves: an answer or the
                        # typed close error — a hang here is the bug.
                        f.result(5.0)
                    except kserve.ServingUnavailable:
                        pass
                assert server.outstanding() == 0
                st = server.stats
                assert st.answered + st.failed == st.requests == len(futs)
        finally:
            engine._execute = real

    def test_drain_waits_for_all_outstanding(self, engine, rng):
        with kserve.Server(engine) as server:
            futs = [server.submit(r) for r in _requests(rng, 16)]
            assert server.drain(30.0)
            assert server.outstanding() == 0
            assert all(f.done() for f in futs)
            assert server.stats.answered == 16


# -- SLO bench + observability ------------------------------------------------


class TestServeBench:
    def test_record_fields_and_equality(self, engine, rng):
        reqs = _requests(rng, 32)
        rec = kserve.serve_bench(engine, reqs, clients=3, depth=4)
        json.dumps(rec)
        assert rec["requests"] == 32
        assert rec["predictions_bit_identical"] is True
        assert rec["unbatched_bit_identical"] is True
        assert rec["qps"] > 0 and rec["unbatched_qps"] > 0
        assert rec["p99_latency_ms"] >= rec["p50_latency_ms"] > 0
        assert 0 < rec["batcher"]["mean_occupancy"] <= 1
        assert rec["batched_vs_unbatched_qps"] > 0

    def test_request_spans_and_metrics_land(self, engine, rng, tmp_path):
        from keystone_tpu.core import trace

        trace.reset()
        trace.enable(str(tmp_path / "serve.json"))
        try:
            with kserve.Server(engine) as server:
                futs = [server.submit(r) for r in _requests(rng, 8)]
                for f in futs:
                    f.result(30.0)
        finally:
            trace.disable()
        evs = trace.events()
        trace.reset()
        req_spans = [e for e in evs if e.get("name") == "serve.request"]
        assert len(req_spans) == 8
        for sp in req_spans:
            args = sp["args"]
            assert {"bucket", "queue_wait_ms", "execute_ms", "d2h_ms",
                    "latency_ms"} <= set(args)
        assert any(e.get("name") == "serve.execute" for e in evs)
        assert any(e.get("name") == "serve.h2d" for e in evs)
        snap = trace.metrics.snapshot()
        assert snap["histograms"].get("serve_latency_ms", {}).get("count", 0) >= 8

    @pytest.mark.slow
    def test_concurrent_client_soak(self, rng):
        """The long soak: many clients, jittered think times, request count
        well past every bucket boundary — every answer bit-equal, no
        leaked thread.  Tier-1 runs the small deterministic bench above;
        this runs under -m slow."""
        cfg = kserve.ServeConfig(buckets=(1, 4, 16), max_wait_ms=2.0)
        eng = kserve.ServingEngine(
            _toy_pipe(rng), np.zeros(16, np.float32), config=cfg, label="soak"
        )
        reqs = _requests(rng, 600)
        offline = eng.offline(reqs)
        answers = [None] * len(reqs)
        errors = []
        jitter = np.random.default_rng(7)

        def client(cid, clients=8):
            try:
                with_jitter = jitter.random() < 0.5
                pending = []
                for i in range(cid, len(reqs), clients):
                    pending.append((i, server.submit(reqs[i])))
                    if with_jitter and i % 97 == 0:
                        time.sleep(0.005)
                    if len(pending) >= 6:
                        j, f = pending.pop(0)
                        answers[j] = f.result(60.0)
                for j, f in pending:
                    answers[j] = f.result(60.0)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        with kserve.Server(eng) as server:
            ts = [threading.Thread(target=client, args=(c,)) for c in range(8)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(120.0)
        assert not errors, errors
        assert server.join(10.0)
        assert np.array_equal(np.stack(answers), offline)


# -- cold start ---------------------------------------------------------------


def _fitted_servable(rng):
    """A checkpointable fitted chain (registered nodes only): scaler ->
    block linear model -> argmax."""
    from keystone_tpu.ops.stats import StandardScaler
    from keystone_tpu.ops.util import (
        ClassLabelIndicatorsFromIntLabels,
        MaxClassifier,
    )
    from keystone_tpu.solvers.block import BlockLeastSquaresEstimator

    x = jnp.asarray(rng.normal(size=(48, 12)), jnp.float32)
    y = rng.integers(0, 3, 48)
    scaler = StandardScaler().fit(x)
    model = BlockLeastSquaresEstimator(12, 1, 0.1).fit(
        scaler(x), ClassLabelIndicatorsFromIntLabels(3)(jnp.asarray(y))
    )
    return Pipeline([scaler, model, MaxClassifier()]), np.asarray(x)


class TestColdStart:
    def test_load_engine_measures_cold_start(self, tmp_path, rng):
        from keystone_tpu.core.checkpoint import save_pipeline

        pipe, x = _fitted_servable(rng)
        stem = str(tmp_path / "servable")
        save_pipeline(stem, pipe)
        cfg = kserve.ServeConfig(buckets=(1, 4), max_wait_ms=2.0)
        engine, cold = kserve.load_engine(
            stem, jax.ShapeDtypeStruct((12,), np.float32), config=cfg,
            label="cold",
        )
        assert set(cold) == {
            "checkpoint_load_seconds", "compile_seconds", "warmup_seconds",
            "cold_start_seconds",
        }
        assert cold["cold_start_seconds"] > 0
        reqs = x[:6]
        assert np.array_equal(
            engine.infer(reqs), np.asarray(pipe(jnp.asarray(reqs)))
        )

    def test_fresh_process_serving_cold_start(self, tmp_path, rng):
        """The ISSUE 8 acceptance path: save a fitted pipeline, spawn a NEW
        interpreter, warm-load it into a serving endpoint, answer one
        request through the batcher, and assert the prediction bit-equals
        the in-process apply (extends the fresh-process reload test to the
        online path)."""
        from keystone_tpu.core.checkpoint import save_pipeline

        pipe, x = _fitted_servable(rng)
        stem = str(tmp_path / "fresh_serve")
        save_pipeline(stem, pipe)
        request = np.asarray(x[0], np.float32)
        expected = np.asarray(pipe(jnp.asarray(request)[None]))[0]
        np.save(tmp_path / "request.npy", request)
        np.save(tmp_path / "expected.npy", expected)
        script = (
            "import os; os.environ['JAX_PLATFORMS']='cpu'\n"
            "import json\n"
            "import numpy as np, jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from keystone_tpu.core import serve as kserve\n"
            f"request = np.load({str(tmp_path / 'request.npy')!r})\n"
            f"expected = np.load({str(tmp_path / 'expected.npy')!r})\n"
            "cfg = kserve.ServeConfig(buckets=(1, 2), max_wait_ms=2.0)\n"
            "engine, cold = kserve.load_engine(\n"
            f"    {stem!r}, jax.ShapeDtypeStruct((12,), np.float32),\n"
            "    config=cfg, label='fresh')\n"
            "with kserve.Server(engine) as server:\n"
            "    answer = server.predict(request, timeout=60.0)\n"
            "np.testing.assert_array_equal(np.asarray(answer), expected)\n"
            "assert cold['cold_start_seconds'] > 0\n"
            "print('FRESH_SERVE_OK', json.dumps(cold))\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        res = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            timeout=300,
            env=env,
            cwd=_REPO,
        )
        assert res.returncode == 0, res.stderr[-2000:]
        assert "FRESH_SERVE_OK" in res.stdout


# -- the workload serving glue (serve_common) ---------------------------------


class TestServeFitted:
    def test_demo_path_routes_through_shape_router(self, tmp_path, rng):
        """ISSUE 12 satellite: the workload --serve demo path rides the
        ShapeRouter front-end, and the serving record carries router stats
        (engines, routes, retires) alongside the phase breakdown."""
        from keystone_tpu.core.checkpoint import save_pipeline
        from keystone_tpu.workloads.serve_common import serve_fitted

        pipe, x = _fitted_servable(rng)
        stem = str(tmp_path / "routed_servable")
        save_pipeline(stem, pipe)
        record = serve_fitted(
            stem,
            jax.ShapeDtypeStruct((12,), np.float32),
            x[:24],
            label="routed",
        )
        served = record["served"]
        healthy = served["predictions_bit_identical"] or served.get(
            "predictions_deterministic", False
        )
        assert healthy
        router = served["router"]
        json.dumps(router)  # JSON-able for results["serving"]
        assert router["stats"]["routes"] == 24
        assert router["stats"]["retires"] == 0
        assert router["stats"]["misses"] == 0
        assert len(router["engines"]) == 1
        (eng_rec,) = router["engines"].values()
        assert eng_rec["label"] == "routed"
        assert served["batcher"]["answered"] == 24
