"""BlockWeightedLeastSquares tests.

Criteria mirror the reference suite
(src/test/scala/nodes/learning/BlockWeightedLeastSquaresSuite.scala): the
analytically-computed weighted-LS gradient vanishes (‖∇‖ < 1e-2) at the
solution on the reference's own fixture matrices, and the solver is invariant
to input row order.  Additionally the implementation is checked against a
direct numpy transcription of the reference algorithm (the BCD fixed point is
only approximately stationary on arbitrary data, so the transcription is the
oracle for synthetic problems).
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.solvers.weighted import BlockWeightedLeastSquaresEstimator

REF_RES = "/root/reference/src/test/resources"


def compute_gradient(features, labels, lam, mixture_weight, x, b):
    """Reference BWLSSuite.computeGradient (:18-60): per-example weights are
    (1-w)/n everywhere, plus w/n_c on the true-class column."""
    n = features.shape[0]
    class_idx = np.argmax(labels, axis=1)
    counts = np.bincount(class_idx, minlength=labels.shape[1])
    neg_wt = (1.0 - mixture_weight) / n
    wts = np.full(labels.shape, neg_wt)
    wts[np.arange(n), class_idx] += mixture_weight / counts[class_idx]
    out = features @ x + b - labels
    return features.T @ (out * wts) + lam * x


def naive_bwls(feats, labels, block_size, num_iter, lam, w):
    """Direct numpy transcription of reference trainWithL2 (:106-312), with
    one 'partition' per class."""
    n, num_classes = labels.shape
    ci = np.argmax(labels, 1)
    order = np.argsort(ci, kind="stable")
    feats, labels, ci = feats[order], labels[order], ci[order]
    xc = [feats[ci == c] for c in range(num_classes)]
    yc = [labels[ci == c] for c in range(num_classes)]
    counts = np.array([len(x) for x in xc])
    jlm = 2 * w + 2 * (1 - w) * counts / n - 1
    d = feats.shape[1]
    blocks = [slice(i, min(i + block_size, d)) for i in range(0, d, block_size)]
    models = [np.zeros((b.stop - b.start, num_classes)) for b in blocks]
    resid = [yc[c] - jlm for c in range(num_classes)]
    rmean = sum(r.mean(0) for r in resid) / num_classes
    stats = [None] * len(blocks)
    for _ in range(num_iter):
        for bi, bsl in enumerate(blocks):
            xb = [x[:, bsl] for x in xc]
            if stats[bi] is None:
                xall = np.concatenate(xb)
                pop_mean = xall.mean(0)
                ata = sum(x.T @ x for x in xb)
                pop_cov = ata / n - np.outer(pop_mean, pop_mean)
                jm = np.stack([x.mean(0) * w + pop_mean * (1 - w) for x in xb])
                stats[bi] = (pop_cov, pop_mean, jm)
            pop_cov, pop_mean, jm = stats[bi]
            pop_xtr = sum(x.T @ r for x, r in zip(xb, resid)) / n
            dws = []
            for c in range(num_classes):
                x, rl, nc = xb[c], resid[c][:, c], counts[c]
                cm = x.mean(0)
                zm = x - cm
                ccov = zm.T @ zm / nc
                cxtr = x.T @ rl / nc
                md = cm - pop_mean
                jxtx = pop_cov * (1 - w) + ccov * w + np.outer(md, md) * (1 - w) * w
                mmw = rmean[c] * (1 - w) + w * rl.mean()
                jxtr = pop_xtr[:, c] * (1 - w) + cxtr * w - jm[c] * mmw
                db = jxtx.shape[0]
                dws.append(
                    np.linalg.solve(
                        jxtx + lam * np.eye(db), jxtr - models[bi][:, c] * lam
                    )
                )
            dw = np.stack(dws, 1)
            models[bi] += dw
            resid = [resid[c] - xb[c] @ dw for c in range(num_classes)]
            rmean = sum(r.mean(0) for r in resid) / num_classes
    w_full = np.concatenate(models)
    jmc = np.concatenate([s[2] for s in stats], axis=1)
    b = jlm - np.einsum("cd,dc->c", jmc, w_full)
    return w_full, b


def make_problem(rng, n=90, d=8, num_classes=3):
    means = rng.normal(scale=2.0, size=(num_classes, d))
    class_idx = rng.integers(0, num_classes, n)
    feats = (means[class_idx] + rng.normal(size=(n, d))).astype(np.float32)
    labels = (2.0 * np.eye(num_classes)[class_idx] - 1.0).astype(np.float32)
    return feats, labels


def fit_full(feats, labels, block_size, num_iter, lam, w):
    est = BlockWeightedLeastSquaresEstimator(block_size, num_iter, lam, w)
    m = est.fit(jnp.asarray(feats), jnp.asarray(labels))
    return np.asarray(jnp.concatenate(m.xs, 0)), np.asarray(m.b)


class TestBlockWeightedLeastSquares:
    @pytest.mark.skipif(
        not os.path.exists(f"{REF_RES}/aMat.csv"), reason="reference fixture absent"
    )
    def test_gradient_near_zero_on_reference_fixture(self):
        # the reference suite's exact config and criterion (:73-95)
        a = np.loadtxt(f"{REF_RES}/aMat.csv", delimiter=",").astype(np.float32)
        b_mat = np.loadtxt(f"{REF_RES}/bMat.csv", delimiter=",").astype(np.float32)
        x, b = fit_full(a, b_mat, 4, 10, 0.1, 0.3)
        grad = compute_gradient(
            a.astype(np.float64), b_mat.astype(np.float64), 0.1, 0.3, x, b
        )
        assert np.linalg.norm(grad.ravel()) < 1e-2, np.linalg.norm(grad.ravel())

    def test_matches_reference_transcription(self, rng):
        feats, labels = make_problem(rng)
        x, b = fit_full(feats, labels, 4, 3, 0.1, 0.3)
        xn, bn = naive_bwls(
            feats.astype(np.float64), labels.astype(np.float64), 4, 3, 0.1, 0.3
        )
        np.testing.assert_allclose(x, xn, atol=5e-4)
        np.testing.assert_allclose(b, bn, atol=5e-4)

    def test_unsorted_input_matches_sorted(self, rng):
        feats, labels = make_problem(rng)
        x1, b1 = fit_full(feats, labels, 4, 3, 0.1, 0.3)
        perm = rng.permutation(feats.shape[0])
        x2, b2 = fit_full(feats[perm], labels[perm], 4, 3, 0.1, 0.3)
        np.testing.assert_allclose(x1, x2, atol=1e-5)
        np.testing.assert_allclose(b1, b2, atol=1e-5)

    def test_imbalanced_classes_match_transcription(self, rng):
        d = 6
        sizes = [5, 40, 17]
        means = rng.normal(scale=2.0, size=(3, d))
        feats = np.concatenate(
            [means[c] + rng.normal(size=(s, d)) for c, s in enumerate(sizes)]
        ).astype(np.float32)
        labels = np.concatenate(
            [np.tile(2.0 * np.eye(3)[c] - 1.0, (s, 1)) for c, s in enumerate(sizes)]
        ).astype(np.float32)
        x, b = fit_full(feats, labels, 6, 5, 0.1, 0.3)
        xn, bn = naive_bwls(
            feats.astype(np.float64), labels.astype(np.float64), 6, 5, 0.1, 0.3
        )
        np.testing.assert_allclose(x, xn, atol=5e-4)
        np.testing.assert_allclose(b, bn, atol=5e-4)

    def test_missing_class_raises(self, rng):
        feats = rng.normal(size=(10, 4)).astype(np.float32)
        labels = np.tile(2.0 * np.eye(3)[0] - 1.0, (10, 1)).astype(np.float32)
        est = BlockWeightedLeastSquaresEstimator(4, 1, 0.1, 0.3)
        with pytest.raises(ValueError, match="no examples"):
            est.fit(jnp.asarray(feats), jnp.asarray(labels))


def test_regroup_plan_matches_host_sort(rng, mesh42):
    """The all_to_all class-regroup (each row crosses the ICI once) must
    reproduce the host-side sort+pad exactly, including the zero tail."""
    import jax
    from keystone_tpu.parallel.mesh import DATA_AXIS, row_sharding
    from keystone_tpu.solvers.weighted import _RegroupPlan

    d_size = mesh42.shape[DATA_AXIS]
    n, n_src, cols = 37, 40, 5          # n_src divisible by data axis (4)
    assert n_src % d_size == 0
    p_tot = 48                           # sorted rows + zero tail, divisible
    x_host = rng.normal(size=(n_src, cols)).astype(np.float32)
    class_idx = rng.integers(0, 6, n)
    order = np.argsort(class_idx, kind="stable")

    expect = np.zeros((p_tot, cols), np.float32)
    expect[:n] = x_host[order]

    x_dev = jax.device_put(jnp.asarray(x_host), row_sharding(mesh42))
    got = _RegroupPlan(order, n_src, p_tot, d_size).apply(mesh42, x_dev)
    np.testing.assert_array_equal(np.asarray(got), expect)


def test_regroup_skew_guard_falls_back_exactly(rng, mesh42):
    """Class-SORTED input (near-identity permutation) makes every (src,dst)
    bucket land on the diagonal, so the all_to_all plan's padding would
    approach the full block — the skew guard must reject it and the chunked
    fallback must still produce the exact sorted+padded result."""
    import jax
    from keystone_tpu.parallel.mesh import DATA_AXIS, row_sharding, use_mesh
    from keystone_tpu.solvers.weighted import (
        BlockWeightedLeastSquaresEstimator,
        _RegroupPlan,
    )

    d_size = mesh42.shape[DATA_AXIS]
    n, cols = 64, 6
    class_idx = np.sort(rng.integers(0, 4, n))  # already grouped by class
    order = np.argsort(class_idx, kind="stable")
    plan = _RegroupPlan(order, n, n + 16, d_size)
    assert not plan.usable  # diagonal buckets -> padding ~ rows_in

    # End-to-end: the estimator on device-sharded, class-sorted features
    # must match the host-input fit exactly (fallback path).
    x = rng.normal(size=(n, 10)).astype(np.float32)
    y = (2.0 * np.eye(4)[class_idx] - 1.0).astype(np.float32)
    host_fit = BlockWeightedLeastSquaresEstimator(4, 1, 0.1, 0.5).fit(x, y)
    with use_mesh(mesh42):
        x_dev = jax.device_put(jnp.asarray(x), row_sharding(mesh42))
        y_dev = jax.device_put(jnp.asarray(y), row_sharding(mesh42))
        dev_fit = BlockWeightedLeastSquaresEstimator(4, 1, 0.1, 0.5).fit(
            x_dev, y_dev
        )
    for a, b in zip(host_fit.xs, dev_fit.xs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-5)
