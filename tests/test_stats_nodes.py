"""Stats node tests (reference src/test/scala/nodes/stats/*Suite.scala)."""

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.stats import (
    CosineRandomFeatures,
    LinearRectifier,
    NormalizeRows,
    PaddedFFT,
    RandomSignNode,
    SignedHellingerMapper,
    StandardScaler,
    next_power_of_two,
)
from keystone_tpu.parallel.mesh import padded_shard_rows
from keystone_tpu.utils.stats import about_eq


def test_standard_scaler_matches_numpy(rng):
    x = jnp.asarray(rng.normal(2.0, 3.0, (100, 7)).astype(np.float32))
    model = StandardScaler().fit(x)
    assert about_eq(model.mean, np.asarray(x).mean(0), 1e-4)
    assert about_eq(model.std, np.asarray(x).std(0, ddof=1), 1e-3)
    out = model(x)
    assert about_eq(np.asarray(out).mean(0), np.zeros(7), 1e-4)
    assert about_eq(np.asarray(out).std(0, ddof=1), np.ones(7), 1e-3)


def test_standard_scaler_zero_variance_guard(rng):
    x = jnp.asarray(np.full((10, 3), 5.0, np.float32))
    model = StandardScaler().fit(x)
    assert about_eq(model.std, np.ones(3), 1e-6)  # eps guard -> 1.0


def test_standard_scaler_mean_only(rng):
    x = jnp.asarray(rng.normal(size=(50, 4)).astype(np.float32))
    model = StandardScaler(normalize_std_dev=False).fit(x)
    assert model.std is None


def test_standard_scaler_sharded_equals_local(mesh8, rng):
    """Distributed mean/var over the 8-device mesh == local computation
    (the treeAggregate parity check, StandardScaler.scala:46-48)."""
    x = rng.normal(size=(101, 5)).astype(np.float32)  # non-divisible N
    xs, n = padded_shard_rows(jnp.asarray(x), mesh8)
    model = StandardScaler().fit(xs, nvalid=n)
    assert about_eq(model.mean, x.mean(0), 1e-4)
    assert about_eq(model.std, x.std(0, ddof=1), 1e-3)


def test_cosine_random_features_mapping(rng):
    """Exact cos mapping (reference CosineRandomFeaturesSuite.scala:16-34)."""
    W = jnp.asarray(rng.normal(size=(8, 5)).astype(np.float32))
    b = jnp.asarray(rng.uniform(size=(8,)).astype(np.float32))
    crf = CosineRandomFeatures(W, b)
    x = jnp.asarray(rng.normal(size=(10, 5)).astype(np.float32))
    expected = np.cos(np.asarray(x) @ np.asarray(W).T + np.asarray(b))
    assert about_eq(crf(x), expected, 1e-5)


def test_cosine_random_features_distribution():
    crf = CosineRandomFeatures.create(400, 1000, 0.5, jax.random.PRNGKey(0))
    w = np.asarray(crf.W)
    assert abs(w.mean()) < 0.01
    assert abs(w.std() - 0.5) < 0.01  # gamma-scaled gaussian
    bvals = np.asarray(crf.b)
    assert 0 <= bvals.min() and bvals.max() <= 2 * np.pi


def test_padded_fft_semantics():
    """d=784 -> pad 1024 -> 512 real features (PaddedFFT.scala:13-21)."""
    assert next_power_of_two(784) == 1024
    x = np.random.default_rng(0).normal(size=(3, 784)).astype(np.float32)
    out = PaddedFFT()(jnp.asarray(x))
    assert out.shape == (3, 512)
    padded = np.zeros((3, 1024))
    padded[:, :784] = x
    expected = np.fft.fft(padded, axis=1).real[:, :512]
    assert about_eq(out, expected, 1e-2)


def test_padded_fft_exact_power_of_two():
    x = np.ones((2, 8), np.float32)
    out = PaddedFFT()(jnp.asarray(x))
    assert out.shape == (2, 4)


def test_random_sign_node():
    node = RandomSignNode.create(1000, jax.random.PRNGKey(3))
    s = np.asarray(node.signs)
    assert set(np.unique(s)) == {-1.0, 1.0}
    assert abs(s.mean()) < 0.1
    x = jnp.ones((2, 1000))
    assert about_eq(node(x), np.broadcast_to(s, (2, 1000)), 1e-6)


def test_linear_rectifier():
    x = jnp.asarray([[-1.0, 0.5, 2.0]])
    assert about_eq(LinearRectifier(0.0, 0.0)(x), [[0.0, 0.5, 2.0]], 1e-6)
    assert about_eq(LinearRectifier(0.0, 1.0)(x), [[0.0, 0.0, 1.0]], 1e-6)


def test_normalize_rows():
    x = jnp.asarray([[3.0, 4.0], [0.0, 0.0]])
    out = np.asarray(NormalizeRows()(x))
    assert about_eq(out[0], [0.6, 0.8], 1e-6)
    assert np.all(np.isfinite(out[1]))  # eps floor, no NaN


def test_signed_hellinger():
    x = jnp.asarray([[4.0, -9.0, 0.0]])
    assert about_eq(SignedHellingerMapper()(x), [[2.0, -3.0, 0.0]], 1e-6)
