"""Multi-host elastic serving (ISSUE 17) — the acceptance surface.

A REAL 2-process ``jax.distributed`` CPU fit+serve must be bit-identical
to the single-process run on the same data; the host-loss drill must end
with every request answered bit-equal (zero dropped), the loss counted
and the survivors re-anchored; bring-up faults (dead coordinator,
``EADDRINUSE``) must be typed and counted, never hangs; shutdown must
leak no service threads; and with no group configured every new path is
inert.  Multi-process tests carry the ``dist`` marker (auto-skipped where
spawn/ports are unavailable, see conftest).
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from keystone_tpu.core import frontend as kfrontend
from keystone_tpu.core import serve as kserve
from keystone_tpu.core import wire
from keystone_tpu.core.ingest import host_shards
from keystone_tpu.core.resilience import DeadlineExceeded, counters
from keystone_tpu.parallel import distributed as kdist
from keystone_tpu.parallel.mesh import host_local_mesh, make_mesh
from keystone_tpu.workloads import multihost

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def clean_group():
    """Tests that form a (membership-only) group must never leak it into
    the rest of the suite."""
    assert not kdist.is_initialized(), "a prior test leaked a process group"
    yield
    kdist.shutdown_process_group()


# -- inert single-process discipline ------------------------------------------


class TestInertWithoutAGroup:
    def test_process_count_and_index_answer_solo(self):
        assert not kdist.is_initialized()
        assert kdist.process_count() == 1
        assert kdist.process_index() == 0

    def test_shutdown_is_idempotent_noop(self):
        assert kdist.shutdown_process_group() == []

    def test_init_with_nothing_configured_is_inert(self, clean_group):
        st = kdist.init_process_group()
        assert (st.world, st.rank, st.jax_initialized) == (1, 0, False)

    def test_distributed_module_import_is_jax_free(self):
        """The decode-worker discipline (tests/test_lazy_import.py)
        extends to the new module: importing it must not pull jax."""
        res = subprocess.run(
            [
                sys.executable, "-c",
                "import sys\n"
                "import keystone_tpu.parallel.distributed as d\n"
                "assert 'jax' not in sys.modules\n"
                "assert d.process_count() == 1\n"
                "print('DIST_LAZY_OK')\n",
            ],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=_REPO,
        )
        assert res.returncode == 0, res.stderr[-2000:]
        assert "DIST_LAZY_OK" in res.stdout


# -- shard partitioning and the fit math --------------------------------------


class TestHostShards:
    def test_partition_is_disjoint_and_covers(self):
        paths = [f"/data/shard_{i:03d}.tar" for i in range(7)]
        got = [host_shards(paths, r, 3) for r in range(3)]
        assert sorted(p for g in got for p in g) == sorted(paths)
        assert [len(g) for g in got] == [3, 2, 2]

    def test_world_one_returns_all_sorted(self):
        assert host_shards(["b.tar", "a.tar"]) == ["a.tar", "b.tar"]

    def test_rank_out_of_world_is_typed(self):
        with pytest.raises(ValueError):
            host_shards(["a.tar"], 3, 2)


def test_fit_from_moments_matches_scaler_math(rng):
    feats = rng.normal(size=(40, multihost.FEAT_DIM)).astype(np.float32)
    packed = np.concatenate(
        [
            feats.sum(axis=0, dtype=np.float32),
            (feats * feats).sum(axis=0, dtype=np.float32),
            [np.float32(len(feats))],
        ]
    )
    mean, std = multihost.fit_from_moments(packed)
    np.testing.assert_allclose(mean, feats.mean(axis=0), rtol=1e-5)
    np.testing.assert_allclose(
        std, feats.std(axis=0, ddof=1), rtol=1e-3
    )
    # degenerate column -> std guard of 1.0, never a divide-by-zero
    const = np.concatenate(
        [np.full(8, 12.0, np.float32) * 4, np.full(8, 144.0, np.float32) * 4,
         [np.float32(4)]]
    )
    _, stdc = multihost.fit_from_moments(const)
    assert np.all(stdc == 1.0)


# -- fleet membership (reform_group) ------------------------------------------


class TestReformGroup:
    def test_reform_reduces_world_and_counts(self, clean_group):
        kdist.init_process_group(
            coordinator="controller", world=3, rank=1, use_jax=False
        )
        before = counters.get("dist_reform")
        new = kdist.reform_group([0, 1])
        assert (new.world, new.rank, new.epoch) == (2, 1, 1)
        assert new.lost == (2,)
        assert not new.jax_initialized
        assert counters.get("dist_reform") - before == 1
        assert kdist.process_count() == 2

    def test_survivor_set_must_contain_self(self, clean_group):
        kdist.init_process_group(
            coordinator="controller", world=2, rank=1, use_jax=False
        )
        with pytest.raises(ValueError, match="not among survivors"):
            kdist.reform_group([0])

    def test_reform_without_group_is_typed(self):
        with pytest.raises(RuntimeError, match="no process group"):
            kdist.reform_group([0])


# -- bring-up hardening (typed faults, counted) -------------------------------


class TestBringUpHardening:
    def test_eaddrinuse_retries_then_succeeds_counted(
        self, clean_group, monkeypatch
    ):
        import jax

        calls = {"n": 0}

        def flaky_initialize(**kw):
            calls["n"] += 1
            if calls["n"] <= 2:
                raise RuntimeError(
                    "Failed to bind: Address already in use (98)"
                )

        monkeypatch.setattr(jax.distributed, "initialize", flaky_initialize)
        monkeypatch.setattr(jax.distributed, "shutdown", lambda: None)
        # The real gloo flip is exercised in the subprocess tests; flipped
        # HERE it would poison this process's CPU backend (gloo demands a
        # live distributed client at backend init).
        monkeypatch.setattr(kdist, "_enable_cpu_collectives", lambda: None)
        before = counters.get("dist_port_retry")
        st = kdist.init_process_group(
            coordinator="127.0.0.1:1", world=2, rank=0,
            join_timeout_s=5.0, port_retries=4,
        )
        assert st.jax_initialized and calls["n"] == 3
        assert counters.get("dist_port_retry") - before == 2

    def test_eaddrinuse_on_nonzero_rank_propagates(
        self, clean_group, monkeypatch
    ):
        """Only the coordinator owns the port; a joiner seeing the error
        must not spin on it."""
        import jax

        def always_in_use(**kw):
            raise RuntimeError("Address already in use")

        monkeypatch.setattr(jax.distributed, "initialize", always_in_use)
        monkeypatch.setattr(kdist, "_enable_cpu_collectives", lambda: None)
        with pytest.raises(RuntimeError, match="already in use"):
            kdist.init_process_group(
                coordinator="127.0.0.1:1", world=2, rank=1,
                join_timeout_s=5.0, port_retries=4,
            )

    def test_join_timeout_is_typed_and_counted(self, clean_group, monkeypatch):
        import jax

        def never_joins(**kw):
            raise RuntimeError(
                "DEADLINE_EXCEEDED: Barrier timed out. Barrier name: "
                "PjRT_Client_Connect"
            )

        monkeypatch.setattr(jax.distributed, "initialize", never_joins)
        monkeypatch.setattr(kdist, "_enable_cpu_collectives", lambda: None)
        before = counters.get("dist_join_timeout")
        with pytest.raises(DeadlineExceeded) as ei:
            kdist.init_process_group(
                coordinator="127.0.0.1:1", world=2, rank=1,
                join_timeout_s=2.0,
            )
        assert "dist_join[1/2]" in str(ei.value)
        assert counters.get("dist_join_timeout") - before == 1
        assert not kdist.is_initialized()

    @pytest.mark.dist
    def test_missing_peer_is_a_typed_fault_in_a_real_process(self):
        """The real thing, no monkeypatch: a coordinator whose peer never
        arrives blocks inside ``client.connect()`` under XLA's ~1h
        cluster-register timeout — the exact hang the join deadline
        exists to convert.  A real process must come back typed + counted
        in ~the budget, never the hour."""
        script = (
            "import json, sys, time\n"
            "from keystone_tpu.core.resilience import DeadlineExceeded, "
            "counters\n"
            "from keystone_tpu.parallel import distributed as kdist\n"
            "t0 = time.monotonic()\n"
            "try:\n"
            "    kdist.init_process_group(kdist.pick_coordinator(), 2, 0, "
            "join_timeout_s=2.0)\n"
            "except DeadlineExceeded as e:\n"
            "    print(json.dumps({'typed': True, 'phase': str(e), "
            "'wall_s': time.monotonic() - t0, "
            "'counted': counters.get('dist_join_timeout')}))\n"
            "    sys.exit(0)\n"
            "sys.exit(3)\n"
        )
        res = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, timeout=120,
            env=dict(
                os.environ, JAX_PLATFORMS="cpu",
                XLA_FLAGS="--xla_force_host_platform_device_count=2",
            ),
            cwd=_REPO,
        )
        assert res.returncode == 0, (res.stdout + res.stderr)[-2000:]
        rec = json.loads(res.stdout.strip().splitlines()[-1])
        assert rec["typed"] and rec["counted"] >= 1
        assert "dist_join[0/2]" in rec["phase"]
        assert rec["wall_s"] < 30.0, "the deadline did not bound the join"

    @pytest.mark.dist
    def test_dead_coordinator_joiner_is_typed_not_a_hang(self):
        """A joiner whose coordinator is dead: left to jax, its internal
        RegisterTask deadline fires inside C++ and TERMINATES the process
        (client.h fatal) — no Python frame ever sees it.  The keystone
        clock sits in FRONT of jax's, so the joiner gets the typed,
        counted fault and exits on its own terms."""
        dead = kdist.pick_coordinator()  # picked then never bound
        script = (
            "import json, sys, time\n"
            "from keystone_tpu.core.resilience import DeadlineExceeded, "
            "counters\n"
            "from keystone_tpu.parallel import distributed as kdist\n"
            "t0 = time.monotonic()\n"
            "try:\n"
            f"    kdist.init_process_group({dead!r}, 2, 1, "
            "join_timeout_s=2.0)\n"
            "except DeadlineExceeded:\n"
            "    print(json.dumps({'typed': True, "
            "'wall_s': time.monotonic() - t0, "
            "'counted': counters.get('dist_join_timeout')}))\n"
            "    sys.exit(0)\n"
            "sys.exit(3)\n"
        )
        t0 = time.monotonic()
        res = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, timeout=120,
            env=dict(
                os.environ, JAX_PLATFORMS="cpu",
                XLA_FLAGS="--xla_force_host_platform_device_count=2",
            ),
            cwd=_REPO,
        )
        assert res.returncode == 0, (res.stdout + res.stderr)[-2000:]
        rec = json.loads(res.stdout.strip().splitlines()[-1])
        assert rec["typed"] and rec["counted"] >= 1
        assert time.monotonic() - t0 < 60.0

    @pytest.mark.dist
    def test_shutdown_leaks_no_service_threads(self):
        """The coordinator service's threads must be GONE after
        ``shutdown_process_group`` — asserted the way a stream's
        ``join()`` is asserted, in a real process that ran a real
        (world-1) group."""
        script = (
            "import json\n"
            "from keystone_tpu.parallel import distributed as kdist\n"
            "st = kdist.init_process_group(kdist.pick_coordinator(), 1, 0, "
            "join_timeout_s=30.0)\n"
            "assert st.jax_initialized\n"
            "import jax\n"
            "assert jax.process_count() == 1\n"
            "leaked = kdist.shutdown_process_group()\n"
            "print(json.dumps({'leaked': leaked}))\n"
        )
        res = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, timeout=120,
            env=dict(
                os.environ, JAX_PLATFORMS="cpu",
                XLA_FLAGS="--xla_force_host_platform_device_count=2",
            ),
            cwd=_REPO,
        )
        assert res.returncode == 0, (res.stdout + res.stderr)[-2000:]
        rec = json.loads(res.stdout.strip().splitlines()[-1])
        assert rec["leaked"] == []


# -- the tentpole: 2-process fit+serve, bit-identical -------------------------


@pytest.mark.dist
def test_two_process_fit_serve_bit_identical_to_single(tmp_path):
    """ISSUE 17 acceptance: a REAL 2-process ``jax.distributed`` CPU
    fit+serve (per-host tar shards through core.ingest, deterministic
    rank-ordered aggregation, cross-host checkpoint reshard) produces
    predictions bit-equal to the single-process run on the same data."""
    j = multihost.run_two_process_fit_serve(str(tmp_path), timeout_s=240.0)
    assert j["bit_identical"], {
        k: j["records"][k].get("mean") for k in ("ref", 0, 1)
    }
    assert j["mesh_spans"], "the global mesh never spanned processes"
    assert j["crosshost_reshard"] >= 1, (
        "load_pipeline(mesh=) never took the destination-pull path"
    )
    assert j["crosshost_bit_equal"], (
        "a resharded shard's bytes differ from the fit's"
    )
    assert j["leaked_threads"] == []
    assert j["parity_ok"]
    assert j["n_images"] == 24  # both fits saw every shard exactly once


# -- host fleet front-end ------------------------------------------------------


class _Ready:
    """Already-resolved future (the wire server awaits ``result``)."""

    def __init__(self, value):
        self._value = value

    def result(self, timeout=None):
        return self._value


class _Doubler:
    def submit(self, arr):
        return _Ready(np.asarray(arr) * 2.0)

    def record(self):
        return {}


class TestHostFleet:
    def test_failover_reissues_and_counts(self, clean_group):
        s0 = wire.WireServer(_Doubler(), port=0, label="fleet_a")
        s1 = wire.WireServer(_Doubler(), port=0, label="fleet_b")
        try:
            fleet = kfrontend.HostFleet(
                [("127.0.0.1", s0.port), ("127.0.0.1", s1.port)],
                label="t_fleet",
            )
            with fleet:
                rows = [np.full(4, float(i), np.float32) for i in range(6)]
                for r in rows[:2]:
                    np.testing.assert_array_equal(
                        np.asarray(fleet.predict(r)), np.asarray(r) * 2.0
                    )
                before = counters.get("fleet_host_lost")
                s1.close()  # abrupt: sockets die under the clients
                for r in rows[2:]:
                    np.testing.assert_array_equal(
                        np.asarray(fleet.predict(r)), np.asarray(r) * 2.0
                    )
                assert counters.get("fleet_host_lost") - before == 1
                rec = fleet.record()
                assert len(fleet.alive_hosts()) == 1
                assert sum(h["reissued"] for h in rec["hosts"]) >= 1
        finally:
            s0.close()
            s1.close()

    def test_all_hosts_down_is_typed(self):
        s0 = wire.WireServer(_Doubler(), port=0, label="fleet_solo")
        fleet = kfrontend.HostFleet(
            [("127.0.0.1", s0.port)], label="t_fleet_down"
        )
        with fleet:
            s0.close()
            with pytest.raises(kfrontend.ServingUnavailable):
                fleet.predict(np.zeros(4, np.float32))

    def test_remote_typed_errors_pass_through_not_failover(self):
        """A typed remote answer (the server computed and refused) must
        reach the caller — reissuing it on another host would duplicate
        work the fleet already has an answer for."""

        class Refuser:
            def submit(self, arr):
                raise ValueError("typed refusal from the engine")

            def record(self):
                return {}

        s0 = wire.WireServer(Refuser(), port=0, label="fleet_refuse")
        try:
            with kfrontend.HostFleet(
                [("127.0.0.1", s0.port)], label="t_fleet_refuse"
            ) as fleet:
                with pytest.raises(wire.WireRemoteError, match="ValueError"):
                    fleet.predict(np.zeros(4, np.float32))
                assert len(fleet.alive_hosts()) == 1  # NOT marked lost
        finally:
            s0.close()


# -- host-loss drill (the in-process face; chaos drives both) -----------------


def test_host_loss_drill_inprocess_zero_loss_bit_equal(tmp_path, clean_group):
    rec = multihost.run_host_loss_drill(
        str(tmp_path), subprocess_mode=False, requests=16, timeout_s=120.0
    )
    assert rec["dropped_requests"] == 0
    assert rec["mismatches"] == 0
    assert rec["errors"] == []
    sc = rec["survivor_counters"][0]
    assert sc.get("fleet_host_lost", 0) >= 1
    assert sc.get("dist_reform", 0) >= 1
    assert sc.get("host_reanchor", 0) >= 1


# -- satellite: reanchor under live wire traffic, windows full ----------------


def test_reanchor_under_live_wire_traffic_full_windows(devices, rng):
    """The swap happens while wire clients keep the server's per-client
    in-flight window FULL: backpressure answers RETRY_AFTER (clients
    absorb and resubmit), the re-anchor swaps engines underneath, and at
    the end every request is answered correctly — zero dropped, the
    bench's ``reanchor_dropped_requests`` invariant as a tier-1 test."""
    from keystone_tpu.ops.stats import StandardScalerModel

    import jax.numpy as jnp

    model = StandardScalerModel(
        jnp.asarray(rng.normal(size=8).astype(np.float32)),
        jnp.asarray((np.abs(rng.normal(size=8)) + 0.5).astype(np.float32)),
    )
    full = make_mesh(data=2, model=1, devices=devices[:2])
    surviving = make_mesh(data=2, model=1, devices=devices[2:4])

    def build(shape, dtype, mesh):
        return kserve.ServingEngine(
            model, np.zeros(shape, dtype),
            config=kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0),
            label="wire_swap", mesh=mesh,
        )

    factory = kfrontend.MeshEngineFactory(build, mesh=full)
    router = kfrontend.ShapeRouter(factory, label="wire_swap")
    router.add_engine(factory((8,), np.float32))
    n_clients, per_client = 3, 20
    rows = np.asarray(
        rng.normal(size=(n_clients * per_client, 8)), np.float32
    )
    expected = np.asarray(model(jnp.asarray(rows)))
    answers: dict = {}
    errors: list = []
    server = wire.WireServer(
        router, port=0, max_inflight=2, retry_after_s=0.005,
        label="wire_swap",
    )
    try:
        def client(c):
            idx = list(range(c * per_client, (c + 1) * per_client))
            try:
                cl = wire.WireClient("127.0.0.1", server.port)
                try:
                    # window 8 >> max_inflight 2: the server's window is
                    # full the whole run, RETRY_AFTER is the steady state.
                    got = cl.predict_many(
                        [rows[i] for i in idx], window=8, timeout=60.0
                    )
                finally:
                    cl.close()
                for i, g in zip(idx, got):
                    answers[i] = np.asarray(g)
            except Exception as e:  # noqa: BLE001 — judged below
                errors.append(f"client{c}: {type(e).__name__}: {e}")

        threads = [
            threading.Thread(target=client, args=(c,), daemon=True)
            for c in range(n_clients)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30.0
        while server.stats.requests < n_clients * 2:
            assert time.monotonic() < deadline, "traffic never started"
            time.sleep(0.002)
        rec = router.reanchor(surviving, why="test: swap under full windows")
        for t in threads:
            t.join(60.0)
        assert not any(t.is_alive() for t in threads)
    finally:
        server.close()
        router.close()
    assert errors == []
    assert len(answers) == len(rows), (
        f"dropped {len(rows) - len(answers)} request(s) across the swap"
    )
    got = np.stack([answers[i] for i in range(len(rows))])
    np.testing.assert_array_equal(got, expected)
    assert rec["failed"] == [] and len(rec["swapped"]) == 1
    assert server.stats.retry_after >= 1, (
        "the in-flight window never filled — the test lost its point"
    )
