"""Deterministic end-to-end chaos harness (NOT a test module — driven by
tests/test_chaos.py in-suite and tools/chaos_run.py from the CLI).

Spark subjected the reference to production chaos for free: task
preemption, stragglers, flaky DFS reads, bad records.  This harness earns
that hardness on purpose — a SEED maps to a fault schedule drawn from the
injector families in tests/faults.py, the schedule is applied to a real
workload pipeline (MnistRandomFFT or RandomPatchCifar on synthetic data),
and the outcome is judged against one invariant:

    every run either COMPLETES with predictions equal to the fault-free
    run, or fails with a TYPED, COUNTED, LOGGED error — never a silent
    wrong model, never a bare traceback.

Fault families (``seed % len(FAMILIES)`` picks the family, the seeded rng
draws its parameters — fully deterministic):

* ``solver_oom`` / ``oom_cascade`` — injected RESOURCE_EXHAUSTED at fused
  (and stepwise) dispatch: the degradation ladder must step down and the
  degraded tiers must reproduce the fault-free predictions exactly.
* ``io_transient`` — tar opens fail transiently during an image-tar ingest
  phase: core.resilience.retry must absorb them (counted ``io_retry``).
* ``corrupt_members`` — mangled JPEG members mid-archive: the loader must
  skip-and-count each (``corrupt_image``), decode every survivor.
* ``nan_input`` — NaN poisoning of the training batch: the workload's
  finite-model guard must fail TYPED (FloatingPointError), counted.
* ``preempt_resume`` — a simulated preemption mid-BCD (after a completed
  block checkpoint) followed by a ``resume_from=`` restart that must land
  on the fault-free predictions.
* ``deadline`` — an injected hang in the solve, bounded by
  ``resilience.deadline``: the run must die with a typed
  ``DeadlineExceeded`` naming the phase (counted ``deadline_exceeded``).
* ``stream_corrupt`` — a corrupt member MID-STREAM on the streaming
  ingest path (core.ingest): the stream must skip-and-count it and the
  streamed features must equal a fault-free stream over the surviving
  images bit-for-bit.
* ``stream_hang`` — an injected decoder-thread hang under the streaming
  path, bounded by ``resilience.deadline``: typed ``DeadlineExceeded``,
  never a deadlocked ring.
* ``autotune_thrash`` — forced OSCILLATING retunes of every ingest knob
  (decode width, ring depth, decode-ahead) at every chunk boundary
  mid-stream: the typed-or-equal invariant must hold under retuning —
  streamed features bit-equal to a static-knob stream, every thread
  joined.
* ``snapshot_corrupt`` — a truncated/bit-flipped snapshot shard under the
  materialized decode cache (core.snapshot): the stream must fall back to
  live decode with a counted ``snapshot_fallback`` and features
  BIT-EQUAL to the fault-free pass — never silently stale pixels.
* ``decode_worker_kill`` — SIGKILL of a process-backend decode worker
  mid-stream: the pool must respawn it (counted
  ``decode_worker_respawn``) and finish with features bit-equal to the
  thread-path oracle — never a hung ring, never a lost image.
* ``slow_client`` — one client trickles requests with long think times
  while another hammers the SAME endpoint (core.serve): the batcher's
  deadline/idle flush must keep answering the fast client (never wait for
  a full bucket that the slow client will not fill), every answer
  bit-equal to the offline apply.
* ``malformed_request`` — wrong-shape / NaN / uncastable payloads
  interleaved with good requests: each dies at ``submit`` with a typed,
  counted :class:`~keystone_tpu.core.serve.MalformedRequest` and NEVER
  enters a batch — the good batchmates' answers stay bit-equal.
* ``serve_burst_oom`` — injected RESOURCE_EXHAUSTED on the largest batch
  bucket under a request burst: the engine retires the bucket (counted
  ``serve_burst_oom``), re-answers the same requests through smaller
  buckets, and every answer stays bit-equal — degradation, never a
  silent wrong answer and never a dead endpoint.
* ``plan_mispredict`` — a cost-model misprediction made real: the
  placement search's TOP-RANKED plan dies RESOURCE_EXHAUSTED at runtime
  (injected at dispatch).  The fit must step down to the NEXT plan in the
  searched ranking (``results["placement"]`` proves the order), count an
  ``autoshard_stepdown``, and land predictions bit-equal to the
  fault-free fit — a wrong cost model degrades loudly, never silently.
* ``spec_mispredict`` — the SPEC-sharded analog (ISSUE 10): the workload
  runs under a mesh, so the search's top-ranked plan is a real
  ``NamedSharding``-layout (spec-executing) mesh plan; injected
  RESOURCE_EXHAUSTED at its GSPMD dispatch forces a counted
  ``autoshard_stepdown`` to the next-ranked plan, and predictions must
  stay bit-equal to the fault-free MESH run — a mispredicted sharded
  layout degrades loudly, never silently.
* ``wire_disconnect`` — a wire client vanishes MID-BATCH (socket closed
  with requests in flight, core.wire + core.frontend): the disconnect is
  counted (``wire_client_disconnect``), the micro-batches its requests
  ride in still COMPLETE (every serve future resolves — batchmates are
  never poisoned, answers for the dead client are discarded), and a
  second client on the same endpoint gets every answer bit-equal.
* ``slow_loris`` — clients trickle PARTIAL frames and stall (half a
  length prefix; a declared payload with one byte sent): each parks only
  its own connection's reader — the accept loop keeps accepting, and
  concurrent well-behaved clients get every answer bit-equal and timely,
  never starved behind the stalled parser.
* ``jpeg_corrupt_entropy`` — truncated scan data / an early marker in the
  entropy-coded stream MID-BATCH under device decode
  (``decode_mode="device"``, ops.jpeg_device): the damaged member becomes
  a typed, counted skip (``jpeg_corrupt_entropy``) with the rest of the
  batch surviving, and the streamed features equal a fault-free
  device-decode stream over the survivors bit-for-bit — never silent
  wrong pixels.
* ``native_entropy`` — the NATIVE entropy-decode backend
  (ops.native_entropy, the C port of the scan hot loop) under the same
  damage and under its own failure: corrupt-scan members through the
  native-preferred device stream are the SAME typed counted skips
  (``jpeg_corrupt_entropy``) with survivor features bit-equal to a
  fault-free FORCED-PYTHON stream (the portable baseline every backend
  must bit-match), and a mid-stream UNEXPECTED native failure degrades
  that one image to the Python pass counted
  (``native_entropy_fallback``) with the stream still bit-equal — never
  a crash, never a silent difference between backends.
* ``profiler_crash`` — the device cost-attribution layer's HBM watermark
  sampler thread (core.profiler) is killed MID-RUN by an injected stats
  failure: the crash is a counted degradation (``profiler_sampler_crash``),
  the profiled run COMPLETES, and its outputs are bit-equal to an
  unprofiled run — observability may die, the workload may not, and a
  dead profiler must never change a single bit of the answer.
* ``output_drift`` — a deterministically SHIFTED request mix replayed
  against a served classifier engine whose output-drift monitor
  (core.numerics, KEYSTONE_NUMERICS) is armed with a fit-time baseline:
  the divergence must be counted (``serve_output_drift``) with a
  flight-recorder postmortem dumped, and every answer must stay
  bit-equal to an UNMONITORED engine serving the same mix — detection
  fires loudly, the answers never change.
* ``mesh_shrink`` — device loss mid-serve (ISSUE 16): a mesh-anchored
  router's engines are re-anchored onto the SURVIVING mesh while requests
  are in flight — every one answered bit-equal to the offline apply
  (zero request loss across the hot swap), the event counted
  ``mesh_reanchor`` — and a fit checkpointed SHARDED under the full mesh
  must refuse a naive load (typed ``CheckpointMismatch`` naming the
  ``mesh=`` reshard path) then resume onto the survivors via
  ``load_pipeline(mesh=)`` with predictions bit-equal to the fault-free
  full-mesh run.
* ``host_loss`` — a serving HOST dies mid-flight (ISSUE 17): a fleet of
  wire-served host routers (REAL subprocesses where spawn is available,
  in-process wire servers otherwise) loses one member under live
  traffic — the front-end counts the loss (``fleet_host_lost``) and
  reissues the dead host's in-flight requests to survivors, the
  survivors re-form the reduced group (``dist_reform``), reshard the
  checkpointed state host-locally and hot-swap their engines (counted
  ``host_reanchor``, postmortem-linked); every request is answered
  bit-equal to the offline oracle — zero dropped, never a silent wrong
  answer.
* ``drift_refit`` — the closed lifecycle loop (ISSUE 18): a served
  model's request mix shifts mid-serve and the drift monitor trips
  (``serve_output_drift``); the :class:`~.core.lifecycle.
  LifecycleController` must warm-refit the model over fresh-mix data,
  validate it (finite + parity + holdout-quality gates), and hot-swap
  the router atomically (counted ``lifecycle_refit``, postmortem-linked,
  drift re-armed on the candidate's baseline) with requests IN FLIGHT
  across the swap — zero dropped, every pre-swap answer bit-equal to the
  incumbent's offline apply and every post-swap answer bit-equal to an
  OFFLINE refit on the same data.  Injected refit OOM, validation
  rejection (a candidate WORSE than the incumbent), and a mid-swap kill
  must each degrade typed + counted (``refit_failed`` /
  ``refit_rejected``) to the incumbent model — never a silent wrong
  answer, never a gap in service — and a trip inside the cooldown is a
  counted suppression (``refit_suppressed``), not a refit storm.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import shutil
import tarfile
import tempfile
import time

import numpy as np

import faults

from keystone_tpu.core import checkpoint as ckpt_mod
from keystone_tpu.core import ingest
from keystone_tpu.core import memory as kmem
from keystone_tpu.core import trace
from keystone_tpu.core.resilience import (
    DeadlineExceeded,
    counters,
    deadline,
)
from keystone_tpu.loaders import image_loaders
from keystone_tpu.loaders.cifar import cifar_loader
from keystone_tpu.loaders.csv_loader import LabeledData
from keystone_tpu.solvers import block as block_mod
from keystone_tpu.solvers.block import bcd_checkpoint_writer

#: Exception types that count as a STRUCTURED failure — anything else
#: escaping a chaos run is a bare traceback, i.e. a harness violation.
TYPED_ERRORS = (
    FloatingPointError,
    DeadlineExceeded,
    ckpt_mod.CheckpointError,  # includes CheckpointMismatch
    kmem.LadderSourceLost,
)

FAMILIES = (
    "solver_oom",
    "oom_cascade",
    "io_transient",
    "corrupt_members",
    "nan_input",
    "preempt_resume",
    "deadline",
    "stream_corrupt",
    "stream_hang",
    "autotune_thrash",
    "snapshot_corrupt",
    "decode_worker_kill",
    "slow_client",
    "malformed_request",
    "serve_burst_oom",
    "plan_mispredict",
    "spec_mispredict",
    "wire_disconnect",
    "slow_loris",
    "jpeg_corrupt_entropy",
    "profiler_crash",
    "output_drift",
    "mesh_shrink",
    "host_loss",
    "drift_refit",
    "native_entropy",
    "obs_capture",
)

#: The serving-path families (core.serve / core.frontend / core.wire),
#: selectable via ``tools/chaos_run.py --serve``.
SERVE_FAMILIES = (
    "slow_client",
    "malformed_request",
    "serve_burst_oom",
    "wire_disconnect",
    "slow_loris",
    "output_drift",
)

#: Seeds the tier-1 suite runs (small schedule, covers every family);
#: ``-m chaos`` / ``tools/chaos_run.py --full`` runs the full schedule.
TIER1_SEEDS = tuple(range(27))
FULL_SEEDS = tuple(range(54))

_DATA_SEED = 20260803  # fixed: the fault-free baseline is schedule-invariant
_N_TAR_IMAGES = 6
_N_STREAM_IMAGES = 10  # streaming-path tars (corrupt picked mid-stream)


class SimulatedPreemption(RuntimeError):
    """Injected mid-fit preemption (the chaos analog of a TPU VM being
    reclaimed between BCD blocks) — expected and consumed by the
    ``preempt_resume`` schedule, never a final outcome."""


class ChaosOracleError(AssertionError):
    """The resilience contract itself broke (wrong skip count, missing
    expected failure, survivors lost) — surfaces as a failed outcome."""


@dataclasses.dataclass
class Fault:
    kind: str
    params: dict

    def record(self) -> dict:
        return {"kind": self.kind, **self.params}


@dataclasses.dataclass
class ChaosResult:
    seed: int
    workload: str
    fault: Fault
    outcome: str  # completed_equal | typed_error | SILENT_WRONG_MODEL |
    #             UNTYPED_ERROR | ORACLE_FAILED
    error_type: str | None = None
    error: str | None = None
    phase: str | None = None
    counters_delta: dict = dataclasses.field(default_factory=dict)
    seconds: float = 0.0
    #: where this schedule's trace landed (run_schedule(trace_path=)) —
    #: the ONE place the per-schedule filename lives; verifiers read it
    #: from here instead of re-deriving the naming convention.
    trace_path: str | None = None

    def ok(self) -> bool:
        return self.outcome in ("completed_equal", "typed_error")

    def record(self) -> dict:
        return {
            "seed": self.seed,
            "workload": self.workload,
            "fault": self.fault.record(),
            "outcome": self.outcome,
            "error_type": self.error_type,
            "error": self.error[:200] if self.error else None,
            "phase": self.phase,
            "counters_delta": dict(self.counters_delta),
            "seconds": round(self.seconds, 3),
            "trace_path": self.trace_path,
        }


def make_schedule(seed: int) -> Fault:
    """seed -> fault schedule, deterministically: the family cycles so any
    contiguous seed range covers all of them, the parameters are drawn
    from ``default_rng(seed)``."""
    rng = np.random.default_rng(seed)
    kind = FAMILIES[seed % len(FAMILIES)]
    if kind == "solver_oom":
        return Fault(kind, {"failures": 1})
    if kind == "oom_cascade":
        return Fault(kind, {"failures": 2})
    if kind == "io_transient":
        return Fault(kind, {"io_failures": int(rng.integers(1, 3))})
    if kind == "corrupt_members":
        k = int(rng.integers(1, 4))
        corrupt = tuple(
            sorted(int(i) for i in rng.choice(_N_TAR_IMAGES, k, replace=False))
        )
        return Fault(kind, {"corrupt": corrupt})
    if kind == "nan_input":
        return Fault(kind, {"frac": float(rng.uniform(0.002, 0.02))})
    if kind == "preempt_resume":
        return Fault(kind, {"preempt_after_blocks": 1})
    if kind == "stream_corrupt":
        k = int(rng.integers(1, 3))
        corrupt = tuple(  # strictly mid-stream members
            sorted(
                int(i)
                for i in rng.choice(
                    np.arange(1, _N_STREAM_IMAGES - 1), k, replace=False
                )
            )
        )
        return Fault(kind, {"corrupt": corrupt, "batch": 4})
    if kind == "stream_hang":
        return Fault(
            kind,
            {"hang_at": int(rng.integers(1, 6)), "seconds": 0.8},
        )
    if kind == "autotune_thrash":
        return Fault(
            kind,
            {"batch": int(rng.integers(2, 5)), "period": int(rng.integers(1, 3))},
        )
    if kind == "snapshot_corrupt":
        return Fault(
            kind,
            {
                "batch": int(rng.integers(2, 5)),
                "shard": int(rng.integers(0, 4)),
                "corruption": ("truncate", "bitflip")[int(rng.integers(0, 2))],
            },
        )
    if kind == "decode_worker_kill":
        return Fault(kind, {"batch": 4, "procs": 2})
    if kind == "slow_client":
        return Fault(
            kind,
            {
                "slow_requests": int(rng.integers(2, 5)),
                "think_seconds": 0.05,
                "fast_requests": int(rng.integers(12, 25)),
            },
        )
    if kind == "malformed_request":
        return Fault(
            kind,
            {"bad": int(rng.integers(2, 5)), "good": int(rng.integers(8, 17))},
        )
    if kind == "serve_burst_oom":
        return Fault(
            kind,
            {"burst": int(rng.integers(9, 17)), "failures": 1},
        )
    if kind == "plan_mispredict":
        return Fault(kind, {"failures": 1})
    if kind == "spec_mispredict":
        return Fault(kind, {"failures": 1})
    if kind == "wire_disconnect":
        return Fault(
            kind,
            {"requests": int(rng.integers(6, 13)), "hold_seconds": 0.25},
        )
    if kind == "slow_loris":
        return Fault(
            kind,
            {"requests": int(rng.integers(6, 13)),
             "lorises": int(rng.integers(1, 3))},
        )
    if kind == "jpeg_corrupt_entropy":
        k = int(rng.integers(1, 3))
        corrupt = tuple(  # strictly mid-stream members
            sorted(
                int(i)
                for i in rng.choice(
                    np.arange(1, _N_STREAM_IMAGES - 1), k, replace=False
                )
            )
        )
        return Fault(
            kind,
            {
                "corrupt": corrupt,
                "batch": 4,
                "mode": ("truncate", "marker")[int(rng.integers(0, 2))],
            },
        )
    if kind == "native_entropy":
        k = int(rng.integers(1, 3))
        corrupt = tuple(  # strictly mid-stream members
            sorted(
                int(i)
                for i in rng.choice(
                    np.arange(1, _N_STREAM_IMAGES - 1), k, replace=False
                )
            )
        )
        return Fault(
            kind,
            {
                "corrupt": corrupt,
                "batch": 4,
                "mode": ("truncate", "marker")[int(rng.integers(0, 2))],
                # which decode_scan call the injected native failure hits —
                # <= 8 so it always lands inside the survivor stream
                # (>= _N_STREAM_IMAGES - 2 survivors)
                "fail_at": int(rng.integers(1, 9)),
            },
        )
    if kind == "profiler_crash":
        return Fault(
            kind,
            {"batch": 4, "crash_after": int(rng.integers(1, 5))},
        )
    if kind == "output_drift":
        return Fault(
            kind,
            {
                "reference": int(rng.integers(48, 81)),
                # Must clear numerics.DRIFT_MIN_COUNT with margin so the
                # monitor is allowed to judge the shifted mix.
                "shifted": int(rng.integers(48, 81)),
                "shift_scale": float(rng.uniform(4.0, 8.0)),
            },
        )
    if kind == "mesh_shrink":
        return Fault(
            kind,
            {
                "requests": int(rng.integers(6, 13)),
                # how much of the 4-device full mesh survives the loss
                "survivors": int(rng.integers(1, 3)),
                "hold_seconds": 0.25,
            },
        )
    if kind == "host_loss":
        return Fault(
            kind,
            {
                "hosts": 2,  # tools/chaos_run.py --hosts N overrides via env
                "requests": int(rng.integers(14, 25)),
            },
        )
    if kind == "obs_capture":
        return Fault(
            kind,
            {
                "hosts": 2,
                "requests": int(rng.integers(12, 21)),
            },
        )
    if kind == "drift_refit":
        return Fault(
            kind,
            {
                # fit-time reference + shifted-mix sizes both clear
                # numerics.DRIFT_MIN_COUNT with margin
                "reference": int(rng.integers(48, 81)),
                "shifted": int(rng.integers(48, 81)),
                "shift_scale": float(rng.uniform(4.0, 8.0)),
                # refit training rows (fresh post-shift world)
                "rows": int(rng.integers(96, 161)),
                # requests in flight across the hot-swap
                "requests": int(rng.integers(6, 13)),
                "hold_seconds": 0.2,
            },
        )
    return Fault("deadline", {"seconds": 1.0})


# -- workload cases -----------------------------------------------------------


def _mnist_case():
    rng = np.random.default_rng(_DATA_SEED)
    d, k = 64, 5
    centers = rng.normal(size=(k, d))

    def split(n):
        labels = rng.integers(0, k, n)
        data = (centers[labels] + 0.3 * rng.normal(size=(n, d))).astype(
            np.float32
        )
        return LabeledData(data=data, labels=labels.astype(np.int32))

    return split(160), split(80)


_mnist_data_cache: list = []


def _run_mnist(train_override=None, mesh=None, **conf_kw):
    from keystone_tpu.workloads.mnist_random_fft import (
        MnistRandomFFTConfig,
        run,
    )

    if not _mnist_data_cache:
        _mnist_data_cache.append(_mnist_case())
    train, test = _mnist_data_cache[0]
    if train_override is not None:
        train = train_override(train)
    conf = MnistRandomFFTConfig(
        num_ffts=2,
        block_size=512,
        lam=1e-2,
        mnist_image_size=64,
        num_classes=5,
        **conf_kw,
    )
    return run(conf, train, test, mesh=mesh)


_cifar_paths_cache: list = []


def _write_synthetic_cifar(path, n, rng, num_classes=4, base=None):
    """Class-colored blobs + noise in CIFAR binary record format."""
    from keystone_tpu.loaders.cifar import RECORD_BYTES

    labels = rng.integers(0, num_classes, n).astype(np.uint8)
    if base is None:
        base = rng.uniform(40, 215, (num_classes, 3))
    recs = np.zeros((n, RECORD_BYTES), np.uint8)
    yy, xx = np.mgrid[0:32, 0:32]
    del yy
    for i in range(n):
        img = base[labels[i]][:, None, None] + rng.normal(0, 25, (3, 32, 32))
        img[labels[i] % 3] += 30 * np.sin(xx / (2.0 + labels[i]))
        recs[i, 0] = labels[i]
        recs[i, 1:] = np.clip(img, 0, 255).astype(np.uint8).reshape(-1)
    recs.tofile(path)


def _run_cifar(train_override=None, mesh=None, **conf_kw):
    from keystone_tpu.workloads.cifar_random_patch import (
        RandomCifarConfig,
        run,
    )

    if not _cifar_paths_cache:
        d = tempfile.mkdtemp(prefix="chaos_cifar_")
        rng = np.random.default_rng(_DATA_SEED)
        palette = rng.uniform(40, 215, (4, 3))
        tr, te = os.path.join(d, "train.bin"), os.path.join(d, "test.bin")
        _write_synthetic_cifar(tr, 72, rng, base=palette)
        _write_synthetic_cifar(te, 36, rng, base=palette)
        _cifar_paths_cache.append((tr, te))
    tr, te = _cifar_paths_cache[0]
    conf = RandomCifarConfig(
        num_filters=8,
        patch_size=6,
        patch_steps=4,
        lam=10.0,
        whitener_size=300,
        featurize_chunk=36,
        num_classes=4,
        **conf_kw,
    )
    train, test = cifar_loader(tr), cifar_loader(te)
    if train_override is not None:
        train = train_override(train)
    return run(conf, train, test, mesh=mesh)


def _run_workload(workload: str, train_override=None, mesh=None, **conf_kw):
    if workload == "mnist":
        return _run_mnist(train_override=train_override, mesh=mesh, **conf_kw)
    if workload == "cifar":
        return _run_cifar(train_override=train_override, mesh=mesh, **conf_kw)
    raise ValueError(f"unknown chaos workload {workload!r}")


_spec_mesh_cache: list = []


def _spec_mesh():
    """The mesh the ``spec_mispredict`` family runs under: all live
    devices, (data, model=2) when the count divides — so the search's
    top-ranked plan is a real spec-executing GSPMD layout.  Cached: the
    baseline and every faulted run must fit on the SAME mesh for the
    bit-equality judgement to mean anything."""
    if not _spec_mesh_cache:
        import jax

        from keystone_tpu.parallel.mesh import make_mesh

        n = len(jax.devices())
        model = 2 if n >= 2 and n % 2 == 0 else 1
        _spec_mesh_cache.append(make_mesh(data=n // model, model=model))
    return _spec_mesh_cache[0]


_baselines: dict[tuple, dict] = {}


def baseline(workload: str, mesh: bool = False) -> dict:
    """The fault-free run every schedule is judged against (cached — one
    per workload per process; also pre-warms every jit cache so faulted
    runs measure fault handling, not compilation).  ``mesh=True``: the
    fault-free MESH run (the ``spec_mispredict`` oracle — a sharded
    faulted run must be judged against a sharded baseline)."""
    key = (workload, bool(mesh))
    if key not in _baselines:
        _baselines[key] = _run_workload(
            workload, mesh=_spec_mesh() if mesh else None
        )
    return _baselines[key]


def _preds_equal(a, b) -> bool:
    a, b = np.asarray(a), np.asarray(b)
    return a.shape == b.shape and bool(np.array_equal(a, b))


@contextlib.contextmanager
def _patched(obj, attr, replacement):
    original = getattr(obj, attr)
    setattr(obj, attr, replacement)
    try:
        yield
    finally:
        setattr(obj, attr, original)


@contextlib.contextmanager
def _clean_env():
    """Chaos runs start from the default resilience posture: no HBM budget
    override (ladders start at the fused tier), the numerics guard on, and
    the profiler OFF (profiler_crash enables it itself, scoped)."""
    saved = {
        k: os.environ.pop(k, None)
        for k in (
            kmem.HBM_BUDGET_ENV, "KEYSTONE_NUMERICS_GUARD",
            "KEYSTONE_PROFILER", "KEYSTONE_NUMERICS",
            "KEYSTONE_DRIFT_TOL", "KEYSTONE_POSTMORTEM_DIR",
        )
    }
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# -- the per-family drivers ---------------------------------------------------


def _ingest_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """The tar-ingest chaos phase (io_transient / corrupt_members): build a
    seeded JPEG tar (optionally with mangled members), stream-decode it
    under the injected faults, and hold the loader to its contract —
    every survivor decoded in order, every corrupt member a COUNTED skip."""
    rng = np.random.default_rng(seed)
    tar_path = os.path.join(tmpdir, f"chaos_ingest_{seed}.tar")
    corrupt = tuple(fault.params.get("corrupt", ()))
    names = faults.make_image_tar(
        tar_path, _N_TAR_IMAGES, rng, corrupt=corrupt
    )
    before_skip = counters.get("corrupt_image")
    before_retry = counters.get("io_retry")
    io_failures = int(fault.params.get("io_failures", 0))
    ctx = (
        faults.transient_faults(image_loaders.tarfile, "open", io_failures)
        if io_failures
        else contextlib.nullcontext()
    )
    with ctx:
        decoded = [
            name
            for name, _img in image_loaders._iter_tar_images(
                tar_path, num_threads=1
            )
        ]
    survivors = [n for i, n in enumerate(names) if i not in corrupt]
    if decoded != survivors:
        raise ChaosOracleError(
            f"ingest lost data: decoded {decoded} != survivors {survivors}"
        )
    skipped = counters.get("corrupt_image") - before_skip
    if skipped != len(corrupt):
        raise ChaosOracleError(
            f"{len(corrupt)} corrupt member(s) but {skipped} counted skips — "
            "a corrupt member was swallowed uncounted"
        )
    if io_failures and counters.get("io_retry") - before_retry < io_failures:
        raise ChaosOracleError(
            f"{io_failures} injected open failure(s) but fewer io_retry "
            "counts — a transient fault was absorbed invisibly"
        )


def _stream_featurize(tar_path: str, batch: int, config=None, tuner=None):
    """The streaming-path probe pipeline: core.ingest stream -> per-image
    device featurize -> scatter back to stream order (the real consumer
    API, fv_common.scatter_features_streaming)."""
    import jax
    import jax.numpy as jnp

    from keystone_tpu.workloads.fv_common import scatter_features_streaming

    feat = jax.jit(
        lambda x: jnp.stack(
            [jnp.mean(x, axis=(1, 2, 3)), jnp.max(x, axis=(1, 2, 3))], axis=1
        )
    )
    with ingest.stream_batches(
        tar_path, batch, config=config, tuner=tuner
    ) as st:
        feats, names = scatter_features_streaming(st, feat, 2)
    if not st.join(10.0):
        raise ChaosOracleError(
            "streaming ingest left decoder/producer threads alive"
        )
    return feats, names


def _stream_corrupt_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """Corrupt member mid-stream: the streaming path must count the skip
    and produce features BIT-IDENTICAL to a fault-free stream over the
    surviving images (tar rebuilt from the same member bytes)."""
    rng = np.random.default_rng(seed)
    corrupt = tuple(fault.params["corrupt"])
    batch = int(fault.params["batch"])
    tar_bad = os.path.join(tmpdir, f"chaos_stream_{seed}.tar")
    names = faults.make_image_tar(
        tar_bad, _N_STREAM_IMAGES, rng, corrupt=corrupt
    )
    survivors = {n for i, n in enumerate(names) if i not in corrupt}
    # The fault-free oracle tar: the SAME member bytes minus the corrupt
    # ones, so decoded survivors are identical by construction.
    tar_ok = os.path.join(tmpdir, f"chaos_stream_{seed}_ok.tar")
    with tarfile.open(tar_bad) as src, tarfile.open(tar_ok, "w") as dst:
        for m in src:
            if m.name in survivors:
                dst.addfile(m, src.extractfile(m))

    before = counters.get("corrupt_image")
    faulted_feats, faulted_names = _stream_featurize(tar_bad, batch)
    skipped = counters.get("corrupt_image") - before
    if skipped != len(corrupt):
        raise ChaosOracleError(
            f"{len(corrupt)} corrupt member(s) but {skipped} counted skips "
            "on the streaming path — a corrupt member was swallowed "
            "uncounted"
        )
    clean_feats, clean_names = _stream_featurize(tar_ok, batch)
    if faulted_names != clean_names:
        raise ChaosOracleError(
            f"streaming ingest lost data: {faulted_names} != {clean_names}"
        )
    if not np.array_equal(faulted_feats, clean_feats):
        raise ChaosOracleError(
            "streamed features under a corrupt member differ from the "
            "fault-free stream on the surviving images"
        )


def _jpeg_corrupt_entropy_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """Damaged entropy-coded scan mid-batch under DEVICE decode
    (ops.jpeg_device): headers parse, so the member reaches the entropy
    decoder and must die there as a typed, COUNTED skip
    (``jpeg_corrupt_entropy``) — the rest of the batch survives and the
    streamed features equal a fault-free device-decode stream over the
    surviving members bit-for-bit (both passes decode on-device, so
    bit-equality is exact, not tolerance)."""
    rng = np.random.default_rng(seed)
    corrupt = tuple(fault.params["corrupt"])
    batch = int(fault.params["batch"])
    mode = fault.params["mode"]
    tar_bad = os.path.join(tmpdir, f"chaos_jpeg_{seed}.tar")
    names = faults.make_image_tar(
        tar_bad, _N_STREAM_IMAGES, rng, corrupt=corrupt,
        corrupt_fn=lambda data: faults.corrupt_jpeg_entropy(data, mode),
    )
    survivors = {n for i, n in enumerate(names) if i not in corrupt}
    tar_ok = os.path.join(tmpdir, f"chaos_jpeg_{seed}_ok.tar")
    with tarfile.open(tar_bad) as src, tarfile.open(tar_ok, "w") as dst:
        for m in src:
            if m.name in survivors:
                dst.addfile(m, src.extractfile(m))

    def device_cfg():
        # snapshot pinned OFF: an ambient KEYSTONE_SNAPSHOT_DIR would turn
        # the device-decode probe into a shard-read pass with no entropy
        # decode to corrupt.
        return ingest.StreamConfig.from_env(
            decode_mode="device", snapshot_dir=""
        )

    before = counters.get("jpeg_corrupt_entropy")
    faulted_feats, faulted_names = _stream_featurize(
        tar_bad, batch, config=device_cfg()
    )
    skipped = counters.get("jpeg_corrupt_entropy") - before
    if skipped != len(corrupt):
        raise ChaosOracleError(
            f"{len(corrupt)} entropy-corrupt member(s) but {skipped} "
            "counted jpeg_corrupt_entropy skips — a damaged scan was "
            "swallowed uncounted (or decoded into silent wrong pixels)"
        )
    clean_feats, clean_names = _stream_featurize(
        tar_ok, batch, config=device_cfg()
    )
    if faulted_names != clean_names:
        raise ChaosOracleError(
            "device-decode stream lost data under entropy corruption: "
            f"{faulted_names} != {clean_names}"
        )
    if not np.array_equal(faulted_feats, clean_feats):
        raise ChaosOracleError(
            "device-decoded features under entropy corruption differ "
            "from the fault-free device stream on the surviving images"
        )


def _native_entropy_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """The native entropy backend (ops.native_entropy) held to the
    backend-indistinguishability bar, in two legs:

    1. a corrupt-scan member through the NATIVE-preferred device stream
       is the same typed counted skip (``jpeg_corrupt_entropy``) and the
       survivors are BIT-equal to a fault-free FORCED-PYTHON stream —
       the portable baseline every backend must bit-match;
    2. an UNEXPECTED native failure mid-stream (decode_scan raises on
       call ``fail_at``) degrades that one image to the Python pass
       counted ``native_entropy_fallback`` with the stream still
       bit-equal — never a crash.

    Both legs inject at the ``native_entropy.decode_scan`` boundary the
    dispatch resolves at call time, so the family exercises the
    degradation contract even on hosts where the library cannot build
    (there decode_scan returns False and leg 1 runs the Python pass —
    still bit-equal by definition)."""
    from keystone_tpu.ops import native_entropy as ne

    rng = np.random.default_rng(seed)
    corrupt = tuple(fault.params["corrupt"])
    batch = int(fault.params["batch"])
    mode = fault.params["mode"]
    fail_at = int(fault.params["fail_at"])
    tar_bad = os.path.join(tmpdir, f"chaos_native_{seed}.tar")
    names = faults.make_image_tar(
        tar_bad, _N_STREAM_IMAGES, rng, corrupt=corrupt,
        corrupt_fn=lambda data: faults.corrupt_jpeg_entropy(data, mode),
    )
    survivors = {n for i, n in enumerate(names) if i not in corrupt}
    tar_ok = os.path.join(tmpdir, f"chaos_native_{seed}_ok.tar")
    with tarfile.open(tar_bad) as src, tarfile.open(tar_ok, "w") as dst:
        for m in src:
            if m.name in survivors:
                dst.addfile(m, src.extractfile(m))

    def device_cfg():
        # snapshot pinned OFF (see _jpeg_corrupt_entropy_phase)
        return ingest.StreamConfig.from_env(
            decode_mode="device", snapshot_dir=""
        )

    # KEYSTONE_NATIVE_ENTROPY is managed per leg (not in _clean_env's
    # fixed key list): "0" pins the Python oracle, unset prefers native.
    saved_env = os.environ.pop(ne.NATIVE_ENTROPY_ENV, None)
    try:
        os.environ[ne.NATIVE_ENTROPY_ENV] = "0"
        clean_feats, clean_names = _stream_featurize(
            tar_ok, batch, config=device_cfg()
        )
        del os.environ[ne.NATIVE_ENTROPY_ENV]

        # -- leg 1: corrupt scan through the native-preferred stream ----
        before = counters.get("jpeg_corrupt_entropy")
        faulted_feats, faulted_names = _stream_featurize(
            tar_bad, batch, config=device_cfg()
        )
        skipped = counters.get("jpeg_corrupt_entropy") - before
        if skipped != len(corrupt):
            raise ChaosOracleError(
                f"{len(corrupt)} entropy-corrupt member(s) but {skipped} "
                "counted jpeg_corrupt_entropy skips through the native "
                "backend — a damaged scan was swallowed uncounted (or "
                "classified differently than the Python pass)"
            )
        if faulted_names != clean_names:
            raise ChaosOracleError(
                "native-backend stream lost data under entropy "
                f"corruption: {faulted_names} != {clean_names}"
            )
        if not np.array_equal(faulted_feats, clean_feats):
            raise ChaosOracleError(
                "native-backend features differ from the forced-Python "
                "stream on the surviving images — the backends are "
                "distinguishable"
            )

        # -- leg 2: forced native failure mid-stream --------------------
        calls = [0]
        orig = ne.decode_scan

        def flaky(*args, **kwargs):
            calls[0] += 1
            if calls[0] == fail_at:
                raise RuntimeError("chaos: injected native entropy failure")
            return orig(*args, **kwargs)

        before_fb = counters.get("native_entropy_fallback")
        with _patched(ne, "decode_scan", flaky):
            leg2_feats, leg2_names = _stream_featurize(
                tar_ok, batch, config=device_cfg()
            )
        fell_back = counters.get("native_entropy_fallback") - before_fb
        if fell_back < 1:
            raise ChaosOracleError(
                "injected native entropy failure was not counted "
                "native_entropy_fallback — it was swallowed silently "
                f"(decode_scan called {calls[0]} time(s), fail_at "
                f"{fail_at})"
            )
        if leg2_names != clean_names:
            raise ChaosOracleError(
                "stream lost data across a per-image native->Python "
                f"degradation: {leg2_names} != {clean_names}"
            )
        if not np.array_equal(leg2_feats, clean_feats):
            raise ChaosOracleError(
                "features differ after a per-image native->Python "
                "degradation — the fallback image was not re-decoded "
                "cleanly"
            )
    finally:
        if saved_env is None:
            os.environ.pop(ne.NATIVE_ENTROPY_ENV, None)
        else:
            os.environ[ne.NATIVE_ENTROPY_ENV] = saved_env


def _profiler_crash_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """The HBM watermark sampler thread (core.profiler) dies MID-RUN from
    an injected stats failure: the crash must be a counted degradation
    (``profiler_sampler_crash``), the profiled run must COMPLETE, and its
    streamed features must be bit-equal to an unprofiled run — a dead
    observability thread may cost telemetry, never correctness."""
    from keystone_tpu.core import profiler as kprof

    rng = np.random.default_rng(seed)
    batch = int(fault.params["batch"])
    crash_after = int(fault.params["crash_after"])
    tar_path = os.path.join(tmpdir, f"chaos_prof_{seed}.tar")
    faults.make_image_tar(tar_path, _N_STREAM_IMAGES, rng)

    # The unprofiled oracle (the default posture: profiler off).
    base_feats, base_names = _stream_featurize(tar_path, batch)

    calls = {"n": 0}

    def crashing_stats():
        calls["n"] += 1
        if calls["n"] > crash_after:
            raise RuntimeError("injected HBM sampler crash")
        return 123 * 2**20  # a plausible bytes-in-use figure until then

    before = counters.get("profiler_sampler_crash")
    kprof.reset_state()
    try:
        with kprof.profiled(
            True, interval_ms=1.0, stats_fn=crashing_stats
        ):
            feats, names = _stream_featurize(tar_path, batch)
            # The thread polls every 1ms — wait (bounded) for the injected
            # crash to land so the count below is deterministic.
            s = kprof.sampler()
            end = time.monotonic() + 5.0
            while (
                s is not None and not s.crashed and time.monotonic() < end
            ):
                time.sleep(0.01)
    finally:
        kprof.reset_state()
    crashed = counters.get("profiler_sampler_crash") - before
    if crashed != 1:
        raise ChaosOracleError(
            f"sampler crash injected but {crashed} counted "
            "profiler_sampler_crash — a dead profiler thread went "
            "unnoticed (or died more than once)"
        )
    if names != base_names:
        raise ChaosOracleError(
            "profiled stream lost data under a sampler crash: "
            f"{names} != {base_names}"
        )
    if not np.array_equal(feats, base_feats):
        raise ChaosOracleError(
            "profiled features differ from the unprofiled run — the "
            "cost-attribution layer changed the answer"
        )


def _stream_hang_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """Injected decoder-thread hang: the consumer's resilience.deadline
    must convert it into a typed DeadlineExceeded — the ring must never
    deadlock.  Raises (the schedule's expected outcome is typed_error)."""
    rng = np.random.default_rng(seed)
    tar_path = os.path.join(tmpdir, f"chaos_hang_{seed}.tar")
    faults.make_image_tar(tar_path, _N_STREAM_IMAGES, rng)
    budget = float(fault.params["seconds"])
    hang_at = int(fault.params["hang_at"])
    calls = {"n": 0}
    real = image_loaders.decode_image

    def hanging(data):
        calls["n"] += 1
        if calls["n"] == hang_at:
            time.sleep(4.0 * budget)  # outlives the watchdog budget
        return real(data)

    # The patch must be live BEFORE the stream constructs: the producer
    # thread starts submitting decode_image calls immediately, and a
    # late patch could race past the hang_at'th decode entirely.
    st = None
    try:
        with _patched(image_loaders, "decode_image", hanging):
            st = ingest.stream_batches(tar_path, 4, num_threads=2)
            with deadline(budget, phase="ingest"):
                for batch in st:
                    np.asarray(batch.host)
    finally:
        if st is not None:
            st.close()
    raise ChaosOracleError(
        "hung decoder thread did not trip the ingest deadline — the "
        "stream completed (or deadlocked silently)"
    )


class _ThrashTuner:
    """Adversarial autotuner: flip EVERY ingest knob between its extremes
    every ``period`` chunks — the worst-case retune schedule a closed-loop
    controller could emit.  The typed-or-equal invariant says knob motion
    may change speed, never results."""

    def __init__(self, period: int):
        self._period = max(1, period)
        self._chunks = 0
        self._cfg = None
        self.retunes = 0

    def attach(self, stream) -> None:
        self._cfg = stream.config

    def on_chunk(self, stream) -> None:
        self._chunks += 1
        if self._chunks % self._period:
            return
        cfg = self._cfg
        wide = cfg.decode_threads == 1
        cfg.decode_threads = cfg.max_decode_threads if wide else 1
        cfg.decode_ahead = 8 if wide else 0
        cfg.ring_capacity = 8 if wide else 1
        self.retunes += 1


def _autotune_thrash_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """Oscillating mid-stream retunes: features must stay BIT-IDENTICAL to
    a static-knob stream over the same tar, with every retune observed and
    every thread joined."""
    rng = np.random.default_rng(seed)
    tar_path = os.path.join(tmpdir, f"chaos_thrash_{seed}.tar")
    faults.make_image_tar(tar_path, _N_STREAM_IMAGES, rng)
    batch = int(fault.params["batch"])
    static_feats, static_names = _stream_featurize(tar_path, batch)

    tuner = _ThrashTuner(int(fault.params["period"]))
    cfg = ingest.StreamConfig(
        decode_threads=1, decode_ahead=0, ring_capacity=1,
        max_decode_threads=4,
    )
    thrash_feats, thrash_names = _stream_featurize(
        tar_path, batch, config=cfg, tuner=tuner
    )
    if tuner.retunes < 1:
        raise ChaosOracleError(
            "thrash tuner never retuned — the oscillation schedule did not "
            "exercise mid-stream reconfiguration"
        )
    if thrash_names != static_names:
        raise ChaosOracleError(
            "retuned stream lost/reordered data: "
            f"{thrash_names} != {static_names}"
        )
    if not np.array_equal(thrash_feats, static_feats):
        raise ChaosOracleError(
            "streamed features under knob thrash differ from the "
            "static-knob stream — retuning changed RESULTS, not just speed"
        )
    counters.record(
        "chaos_autotune_thrash",
        f"seed {seed}: {tuner.retunes} oscillating retune(s), output "
        "bit-equal",
    )


def _snapshot_corrupt_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """Corrupt snapshot shard (core.snapshot): a cold pass materializes the
    decoded chunks, one shard is truncated/bit-flipped, and the warm pass
    must fall back to live decode COUNTED (``snapshot_fallback``) with
    features bit-equal to the fault-free pass — never silently stale
    pixels."""
    import glob as _glob

    from keystone_tpu.core import snapshot as ksnap

    rng = np.random.default_rng(seed)
    tar_path = os.path.join(tmpdir, f"chaos_snap_{seed}.tar")
    faults.make_image_tar(tar_path, _N_STREAM_IMAGES, rng)
    snap_root = os.path.join(tmpdir, f"chaos_snap_{seed}_cache")
    batch = int(fault.params["batch"])

    def cfg():
        # snapshot_mode pinned: an ambient KEYSTONE_SNAPSHOT_MODE=featurized
        # would stop the ingest tee from committing a decoded snapshot and
        # fail the family with nothing to corrupt (same hazard bench.py's
        # no_snap() pins against).
        return ingest.StreamConfig.from_env(
            snapshot_dir=snap_root, snapshot_mode="decoded"
        )

    clean_feats, clean_names = _stream_featurize(tar_path, batch, config=cfg())
    committed = [
        s for s in ksnap.list_snapshots(snap_root) if s.get("valid")
    ]
    if not committed:
        raise ChaosOracleError(
            "cold snapshot pass committed no snapshot — the corruption "
            "schedule has nothing to corrupt"
        )
    shards = sorted(
        _glob.glob(
            os.path.join(snap_root, committed[0]["dir"], "chunk_*.npz")
        )
    )
    if not shards:
        raise ChaosOracleError("committed snapshot holds no shards")
    target = shards[int(fault.params["shard"]) % len(shards)]
    with open(target, "rb") as fh:
        data = bytearray(fh.read())
    if fault.params["corruption"] == "truncate":
        data = data[: max(1, len(data) // 2)]
    else:
        data[len(data) // 3] ^= 0xFF
    with open(target, "wb") as fh:
        fh.write(bytes(data))

    before = counters.get("snapshot_fallback")
    faulted_feats, faulted_names = _stream_featurize(
        tar_path, batch, config=cfg()
    )
    if counters.get("snapshot_fallback") - before < 1:
        raise ChaosOracleError(
            "corrupt snapshot shard produced no counted snapshot_fallback "
            "— the reader either served corrupt bytes or fell back "
            "invisibly"
        )
    if faulted_names != clean_names:
        raise ChaosOracleError(
            "snapshot fallback lost/reordered data: "
            f"{faulted_names} != {clean_names}"
        )
    if not np.array_equal(faulted_feats, clean_feats):
        raise ChaosOracleError(
            "features under a corrupt snapshot shard differ from live "
            "decode — the fallback is not bit-equal"
        )


def _decode_worker_kill_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """SIGKILL a process-backend decode worker mid-stream: the pool must
    respawn it (counted ``decode_worker_respawn``), resubmit its pending
    members, and finish with features bit-equal to the thread-path oracle
    — never a hung ring."""
    import signal

    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    tar_path = os.path.join(tmpdir, f"chaos_kill_{seed}.tar")
    faults.make_image_tar(tar_path, _N_STREAM_IMAGES + 6, rng)
    batch = int(fault.params["batch"])
    clean_feats, clean_names = _stream_featurize(tar_path, batch)

    feat = jax.jit(
        lambda x: jnp.stack(
            [jnp.mean(x, axis=(1, 2, 3)), jnp.max(x, axis=(1, 2, 3))], axis=1
        )
    )
    cfg = ingest.StreamConfig(
        decode_threads=2, decode_ahead=2, ring_capacity=1,
        decode_backend="process", decode_procs=int(fault.params["procs"]),
    )
    before = counters.get("decode_worker_respawn")
    parts, name_pairs, n = [], [], 0
    killed = False
    st = ingest.stream_batches(tar_path, batch, config=cfg)
    try:
        for b in st:
            if not killed:
                pool = st._proc_pool
                if pool is None:
                    raise ChaosOracleError(
                        "process backend configured but no decode pool "
                        "spun up — the kill schedule has no target"
                    )
                live = [w for w in pool._workers if w.proc.is_alive()]
                if live:
                    os.kill(live[0].proc.pid, signal.SIGKILL)
                    killed = True
            parts.append((b.indices, np.asarray(feat(b.dev()))))
            name_pairs.extend(zip(b.indices.tolist(), b.names))
            n += len(b)
    finally:
        st.close()
    if not st.join(20.0):
        raise ChaosOracleError(
            "worker-kill stream left decode threads/processes alive"
        )
    if not killed:
        raise ChaosOracleError(
            "no live decode worker to kill — the schedule never exercised "
            "the crash path"
        )
    if counters.get("decode_worker_respawn") - before < 1:
        raise ChaosOracleError(
            "killed decode worker was never respawned-and-counted"
        )
    from keystone_tpu.workloads.fv_common import _scatter_parts

    feats, names = _scatter_parts(parts, name_pairs, n)
    if names != clean_names:
        raise ChaosOracleError(
            f"worker kill lost/reordered data: {names} != {clean_names}"
        )
    if not np.array_equal(feats, clean_feats):
        raise ChaosOracleError(
            "features under a worker kill differ from the thread-path "
            "oracle — process decode is not bit-equal after respawn"
        )
    counters.record(
        "chaos_decode_worker_kill",
        f"seed {seed}: worker killed, respawned, stream bit-equal",
    )


# -- the serving-path phases (core.serve) -------------------------------------


def _serve_engine(buckets=(1, 2, 4)):
    """A tiny deterministic warm endpoint: fixed-weight row-wise pipeline,
    parity-verified per-bucket AOT executables.  Weights are seeded from
    the schedule-invariant data seed so the offline oracle is stable."""
    import jax.numpy as jnp

    from keystone_tpu.core import serve as kserve
    from keystone_tpu.core.pipeline import FunctionTransformer

    rng = np.random.default_rng(_DATA_SEED)
    # Fusion-invariant arithmetic (one exactly-rounded multiply + max, no
    # fma/gemv rounding variance): eager == jit == every bucket on every
    # backend, so the phases' offline-oracle equality checks test the
    # BATCHER's behavior, not XLA's rounding moods.
    w = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))

    pipe = FunctionTransformer(
        lambda x: jnp.maximum(x * w, b), name="chaos_serve"
    )
    cfg = kserve.ServeConfig(buckets=tuple(buckets), max_wait_ms=2.0)
    return kserve.ServingEngine(
        pipe, np.zeros(16, np.float32), config=cfg, label="chaos"
    )


def _serve_requests(rng, n: int) -> np.ndarray:
    return rng.normal(size=(n, 16)).astype(np.float32)


def _slow_client_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """One trickling client + one hammering client on the same endpoint:
    the deadline/idle flush must answer the fast client without waiting
    for buckets the slow client never fills — every answer bit-equal."""
    import threading

    from keystone_tpu.core import serve as kserve

    rng = np.random.default_rng(seed)
    engine = _serve_engine()
    n_slow = int(fault.params["slow_requests"])
    n_fast = int(fault.params["fast_requests"])
    think = float(fault.params["think_seconds"])
    slow_reqs = _serve_requests(rng, n_slow)
    fast_reqs = _serve_requests(rng, n_fast)
    slow_ans = [None] * n_slow
    fast_ans = [None] * n_fast
    errors: list = []

    with kserve.Server(engine) as server:

        def slow():
            try:
                for i, r in enumerate(slow_reqs):
                    slow_ans[i] = server.submit(r).result(30.0)
                    time.sleep(think)  # the think time: a slow client
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        def fast():
            try:
                futs = [server.submit(r) for r in fast_reqs]
                for i, f in enumerate(futs):
                    fast_ans[i] = f.result(30.0)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        ts = [threading.Thread(target=slow), threading.Thread(target=fast)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60.0)
        stats = server.stats
    if errors:
        raise errors[0]
    if not np.array_equal(np.stack(slow_ans), engine.offline(slow_reqs)):
        raise ChaosOracleError(
            "slow client's answers differ from the offline apply"
        )
    if not np.array_equal(np.stack(fast_ans), engine.offline(fast_reqs)):
        raise ChaosOracleError(
            "fast client's answers differ from the offline apply — a slow "
            "batchmate changed RESULTS, not just latency"
        )
    if stats.answered != n_slow + n_fast:
        raise ChaosOracleError(
            f"{stats.answered} answered != {n_slow + n_fast} submitted"
        )
    # The trickle must have been answered by deadline/idle flushes (a
    # strict full-bucket batcher would stall the slow client forever).
    if stats.flush_deadline + stats.flush_idle < 1:
        raise ChaosOracleError(
            "no deadline/idle flush fired — the slow client was only "
            "answered because the fast client happened to fill buckets"
        )
    counters.record(
        "chaos_slow_client",
        f"seed {seed}: {n_slow} trickled + {n_fast} hammered requests "
        "answered bit-equal",
    )


def _malformed_request_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """Malformed payloads interleaved with good requests: each dies TYPED
    at submit (counted serve_malformed_request), no batchmate poisoned."""
    from keystone_tpu.core import serve as kserve

    rng = np.random.default_rng(seed)
    engine = _serve_engine()
    n_bad = int(fault.params["bad"])
    n_good = int(fault.params["good"])
    good = _serve_requests(rng, n_good)
    bad_payloads = []
    for i in range(n_bad):
        kind = i % 3
        if kind == 0:  # wrong shape
            bad_payloads.append(np.zeros(7, np.float32))
        elif kind == 1:  # NaN-poisoned
            r = _serve_requests(rng, 1)[0]
            r[int(rng.integers(0, 16))] = np.nan
            bad_payloads.append(r)
        else:  # uncastable dtype
            bad_payloads.append(np.array(["x"] * 16, dtype=object))

    before = counters.get("serve_malformed_request")
    rejected = 0
    with kserve.Server(engine) as server:
        futs = []
        for j in range(n_good + n_bad):
            if j % 2 == 0 and j // 2 < n_bad:
                try:
                    server.submit(bad_payloads[j // 2])
                except kserve.MalformedRequest:
                    rejected += 1
                else:
                    raise ChaosOracleError(
                        "malformed request was ACCEPTED into the queue"
                    )
            if j < n_good:
                futs.append(server.submit(good[j]))
        answers = np.stack([f.result(30.0) for f in futs])
    if rejected != n_bad:
        raise ChaosOracleError(
            f"{n_bad} malformed payloads but {rejected} typed rejections"
        )
    if counters.get("serve_malformed_request") - before != n_bad:
        raise ChaosOracleError(
            "malformed rejections were not all counted "
            "(serve_malformed_request delta != injected)"
        )
    if not np.array_equal(answers, engine.offline(good)):
        raise ChaosOracleError(
            "good requests' answers differ from the offline apply — a "
            "malformed batchmate poisoned the batch"
        )


def _serve_burst_oom_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """RESOURCE_EXHAUSTED on the largest bucket under a burst: the engine
    must retire the bucket (counted serve_burst_oom), re-answer the same
    requests through smaller buckets, and stay bit-equal — the endpoint
    degrades, it never dies and never serves a wrong answer."""
    from keystone_tpu.core import serve as kserve

    rng = np.random.default_rng(seed)
    engine = _serve_engine(buckets=(1, 2, 4))
    burst = int(fault.params["burst"])
    failures = int(fault.params["failures"])
    top = engine.buckets()[-1]
    real_execute = engine._execute
    state = {"n": 0}

    def failing_execute(bucket, dev_batch):
        if bucket == top and state["n"] < failures:
            state["n"] += 1
            raise faults.resource_exhausted_error()
        return real_execute(bucket, dev_batch)

    requests = _serve_requests(rng, burst)
    before = counters.get("serve_burst_oom")
    engine._execute = failing_execute
    try:
        with kserve.Server(engine) as server:
            futs = [server.submit(r) for r in requests]
            answers = np.stack([f.result(30.0) for f in futs])
    finally:
        engine._execute = real_execute
    if state["n"] < failures:
        raise ChaosOracleError(
            "the burst never dispatched the largest bucket — the OOM "
            "schedule did not exercise the degradation path"
        )
    if counters.get("serve_burst_oom") - before < 1:
        raise ChaosOracleError(
            "bucket OOM was not counted under serve_burst_oom"
        )
    if top in engine.buckets():
        raise ChaosOracleError(
            f"bucket {top} survived its RESOURCE_EXHAUSTED — it must be "
            "retired, not retried in place"
        )
    if not np.array_equal(answers, engine.offline(requests)):
        raise ChaosOracleError(
            "answers under burst OOM differ from the offline apply — "
            "degradation changed RESULTS, not just batch shape"
        )


def _wire_disconnect_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """A wire client vanishes mid-batch: the disconnect must be COUNTED
    (``wire_client_disconnect``), every request it submitted must still
    ride its micro-batch to completion (futures resolve; batchmates are
    never poisoned), and a concurrent surviving client must get every
    answer bit-equal to the offline apply."""
    from keystone_tpu.core import frontend as kfrontend
    from keystone_tpu.core import wire as kwire

    rng = np.random.default_rng(seed)
    engine = _serve_engine()
    n = int(fault.params["requests"])
    hold = float(fault.params["hold_seconds"])
    reqs_a = _serve_requests(rng, n)
    reqs_b = _serve_requests(rng, n)
    real_execute = engine._execute

    def slow_execute(bucket, dev_batch):
        # Stretch the batch so the disconnect demonstrably lands while
        # requests are IN FLIGHT (EOF with a full window, not after it).
        time.sleep(hold)
        return real_execute(bucket, dev_batch)

    before = counters.get("wire_client_disconnect")
    router = kfrontend.ShapeRouter(label=f"chaos_wire_{seed}")
    server_ref = None
    try:
        key = router.add_engine(engine)
        server_ref = router.server_for(key)
        engine._execute = slow_execute
        with kwire.WireServer(router, port=0, label="chaos") as ws:
            victim = kwire.WireClient(port=ws.port)
            for r in reqs_a:
                victim.submit(r)
            victim.close()  # mid-batch: the first micro-batch is still held
            with kwire.WireClient(port=ws.port) as survivor:
                answers = np.stack(
                    survivor.predict_many(list(reqs_b), window=8, timeout=60.0)
                )
        engine._execute = real_execute
        if not server_ref.drain(30.0):
            raise ChaosOracleError(
                "serve futures did not drain after the disconnect — the "
                "victim's batch never completed"
            )
    finally:
        engine._execute = real_execute
        router.close()
    if counters.get("wire_client_disconnect") - before < 1:
        raise ChaosOracleError(
            "a client vanished with requests in flight but no "
            "wire_client_disconnect was counted"
        )
    if not np.array_equal(answers, engine.offline(reqs_b)):
        raise ChaosOracleError(
            "the surviving client's answers differ from the offline apply "
            "— a dead batchmate changed RESULTS, not just who gets bytes"
        )
    st = server_ref.stats
    if st.answered != 2 * n or st.failed != 0:
        raise ChaosOracleError(
            f"batch completion broke under the disconnect: answered "
            f"{st.answered} / failed {st.failed}, expected {2 * n} / 0"
        )


def _slow_loris_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """Slow-loris clients trickle partial frames and stall: each must park
    only its OWN connection's reader — the accept loop keeps accepting and
    concurrent honest clients are answered bit-equal and timely."""
    import socket as _socket
    import threading

    from keystone_tpu.core import frontend as kfrontend
    from keystone_tpu.core import wire as kwire

    rng = np.random.default_rng(seed)
    engine = _serve_engine()
    n = int(fault.params["requests"])
    lorises = int(fault.params["lorises"])
    reqs = [_serve_requests(rng, n), _serve_requests(rng, n)]
    answers: dict = {}
    errors: list = []

    router = kfrontend.ShapeRouter(label=f"chaos_loris_{seed}")
    try:
        router.add_engine(engine)
        with kwire.WireServer(router, port=0, label="chaos") as ws:
            stuck = []
            for i in range(lorises):
                s = _socket.create_connection(("127.0.0.1", ws.port), 5.0)
                if i % 2 == 0:
                    s.sendall(b"\x00\x00")  # half a length prefix
                else:
                    # a declared 64-byte payload with ONE byte delivered
                    s.sendall(kwire._LEN.pack(64) + b"\x01")
                stuck.append(s)
            time.sleep(0.1)  # the loris frames reach the readers first

            def good_client(cid):
                try:
                    with kwire.WireClient(port=ws.port) as c:
                        answers[cid] = np.stack(
                            c.predict_many(
                                list(reqs[cid]), window=8, timeout=30.0
                            )
                        )
                except BaseException as e:  # noqa: BLE001 — surfaced below
                    errors.append(e)

            t0 = time.monotonic()
            ts = [
                threading.Thread(target=good_client, args=(c,))
                for c in range(2)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(60.0)
            elapsed = time.monotonic() - t0
            # The accept loop must still be accepting WHILE the lorises
            # hold their sockets open mid-frame.
            with kwire.WireClient(port=ws.port) as probe:
                probe.ping()
            for s in stuck:
                s.close()
    finally:
        router.close()
    if errors:
        raise errors[0]
    if elapsed > 30.0:
        raise ChaosOracleError(
            f"honest clients took {elapsed:.1f}s behind {lorises} "
            "slow-loris connection(s) — partial frames starved the service"
        )
    for cid in range(2):
        if not np.array_equal(answers[cid], engine.offline(reqs[cid])):
            raise ChaosOracleError(
                f"client {cid}'s answers differ from the offline apply "
                "under slow-loris load"
            )
    counters.record(
        "chaos_slow_loris",
        f"seed {seed}: {lorises} stalled partial-frame connection(s), "
        f"2x{n} honest requests answered bit-equal in {elapsed:.2f}s",
    )


def _output_drift_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """A deterministically shifted request mix against a served classifier
    engine whose output-drift monitor (core.numerics) is armed with a
    fit-time baseline: the divergence must be COUNTED
    (``serve_output_drift``) with a flight-recorder postmortem dumped, and
    every answer must stay bit-equal to an UNMONITORED engine serving the
    same mix — the observatory detects, it never alters an answer."""
    import glob as _glob

    import jax.numpy as jnp

    from keystone_tpu.core import numerics as knum
    from keystone_tpu.core import serve as kserve
    from keystone_tpu.core import telemetry as ktelemetry
    from keystone_tpu.core.pipeline import FunctionTransformer

    rng = np.random.default_rng(seed)
    n_ref = int(fault.params["reference"])
    n_shift = int(fault.params["shifted"])
    scale = float(fault.params["shift_scale"])

    # A classifier head built from fusion-invariant arithmetic (exactly-
    # rounded multiply + max, like _serve_engine) so eager == jit == every
    # bucket and the bit-equality oracle tests the MONITOR, not XLA's
    # rounding moods.  Weights are schedule-invariant.
    wrng = np.random.default_rng(_DATA_SEED)
    w_np = wrng.normal(size=(16,)).astype(np.float32)
    b_np = wrng.normal(size=(16,)).astype(np.float32)
    w, b = jnp.asarray(w_np), jnp.asarray(b_np)
    pipe = FunctionTransformer(
        lambda x: jnp.argmax(jnp.maximum(x * w, b), axis=-1),
        name="chaos_drift_head",
    )
    cfg = kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0)
    engine = kserve.ServingEngine(
        pipe, np.zeros(16, np.float32), config=cfg, label="chaos_drift"
    )

    # The fit-time reference: the engine's own offline answers over an
    # unshifted request population.
    ref = _serve_requests(rng, n_ref)
    baseline = knum.OutputSketch.for_outputs(engine.offline(ref)).record()

    # The deterministic shift: push the feature with the LARGEST positive
    # weight, so the shifted mix's argmax collapses onto that class and
    # the answer distribution demonstrably leaves the baseline.
    shift = np.zeros(16, np.float32)
    shift[int(np.argmax(w_np))] = scale
    shifted = _serve_requests(rng, n_shift) + shift

    # The unmonitored oracle: the SAME engine, observatory off.
    with kserve.Server(engine) as server:
        unmon = np.stack(
            [f.result(30.0) for f in [server.submit(r) for r in shifted]]
        )

    pm_dir = os.path.join(tmpdir, f"chaos_drift_{seed}_pm")
    # Re-open the per-kind postmortem budget for THIS schedule (earlier
    # suite activity may have spent the process cap).
    with ktelemetry._pm_lock:
        ktelemetry._pm_counts.pop("serve_output_drift", None)
    before = counters.get("serve_output_drift")
    os.environ["KEYSTONE_POSTMORTEM_DIR"] = pm_dir
    try:
        with knum.monitored(True):
            engine.arm_drift_baseline(baseline)
            with kserve.Server(engine) as server:
                mon = np.stack(
                    [
                        f.result(30.0)
                        for f in [server.submit(r) for r in shifted]
                    ]
                )
            drift_rec = engine.drift.record()
    finally:
        os.environ.pop("KEYSTONE_POSTMORTEM_DIR", None)
        knum.reset_state()
    if counters.get("serve_output_drift") - before < 1:
        raise ChaosOracleError(
            f"shifted request mix (divergence {drift_rec['divergence']}, "
            f"tol {drift_rec['tol']}) produced no counted "
            "serve_output_drift — the monitor missed a real distribution "
            "shift"
        )
    dumps = _glob.glob(
        os.path.join(pm_dir, "postmortem_serve_output_drift_*.json")
    )
    if not dumps:
        raise ChaosOracleError(
            "serve_output_drift was counted but no flight-recorder "
            "postmortem was dumped — the drift fired without evidence"
        )
    if not np.array_equal(mon, unmon):
        raise ChaosOracleError(
            "monitored engine's answers differ from the unmonitored "
            "engine's — the observatory changed RESULTS, not just what "
            "is observed"
        )
    if not np.array_equal(mon, engine.offline(shifted)):
        raise ChaosOracleError(
            "served answers under drift detection differ from the "
            "offline apply"
        )


def _mesh_shrink_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """Device loss mid-serve (ISSUE 16), both halves of the elastic story.

    Leg 1 (live re-anchor): a router anchored on a 4-device mesh has
    requests IN FLIGHT (the engine's execute is stretched so the loss
    demonstrably straddles live batches) when the mesh shrinks to the
    schedule's survivor count; ``reanchor`` must hot-swap every engine
    onto the surviving mesh with every future resolving bit-equal to the
    offline apply — zero request loss — and the event counted
    ``mesh_reanchor``.

    Leg 2 (reshard-resume): fitted state saved SHARDED under the full
    mesh must refuse a naive load with a typed ``CheckpointMismatch``
    that names the ``mesh=`` escape hatch, then resume onto the surviving
    mesh via ``load_pipeline(mesh=)`` (counted ``ckpt_reshard``) with
    predictions bit-equal to the fault-free full-mesh run.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from keystone_tpu.core import frontend as kfrontend
    from keystone_tpu.core import serve as kserve
    from keystone_tpu.core.checkpoint import (
        CheckpointMismatch,
        load_pipeline,
        save_pipeline,
    )
    from keystone_tpu.core.pipeline import FunctionTransformer
    from keystone_tpu.ops.stats import StandardScalerModel
    from keystone_tpu.parallel.mesh import DATA_AXIS, make_mesh, use_mesh

    rng = np.random.default_rng(seed)
    n = int(fault.params["requests"])
    survivors = int(fault.params["survivors"])
    hold = float(fault.params["hold_seconds"])
    devs = jax.devices()
    # The tier-1 substrate has 8 virtual devices (full = 4x1); a
    # standalone single-device chaos_run still exercises the swap
    # machinery on whatever mesh the host actually has.
    n_full = min(4, len(devs))
    survivors = min(survivors, n_full)
    full = make_mesh(data=n_full, model=1, devices=devs[:n_full])
    surviving = make_mesh(data=survivors, model=1, devices=devs[:survivors])

    # -- leg 1: live re-anchor with requests in flight ------------------------
    wrng = np.random.default_rng(_DATA_SEED)
    w = jnp.asarray(wrng.normal(size=(16,)).astype(np.float32))
    b = jnp.asarray(wrng.normal(size=(16,)).astype(np.float32))
    # Fusion-invariant arithmetic (see _serve_engine): eager == jit ==
    # every bucket on every mesh tier, so the bit-equality oracle tests
    # the SWAP, not XLA's rounding moods.
    pipe = FunctionTransformer(
        lambda x: jnp.maximum(x * w, b), name="chaos_mesh_shrink"
    )

    def build(shape, dtype, mesh):
        cfg = kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0)
        return kserve.ServingEngine(
            pipe, np.zeros(shape, dtype), config=cfg,
            label=f"chaos_shrink_{seed}", mesh=mesh,
        )

    reqs = _serve_requests(rng, 2 * n)
    factory = kfrontend.MeshEngineFactory(build, mesh=full)
    router = kfrontend.ShapeRouter(
        factory, label=f"chaos_shrink_{seed}",
        config=kfrontend.RouterConfig(warm_threshold=1, retire_after_s=300.0),
    )
    before = counters.get("mesh_reanchor")
    try:
        engine = factory((16,), np.float32)
        router.add_engine(engine)
        offline = np.asarray(engine.offline(reqs))
        real_execute = engine._execute

        def slow_execute(bucket, dev_batch):
            # Stretch the doomed mesh's batches so the loss demonstrably
            # lands while requests are IN FLIGHT, not between them.
            time.sleep(hold)
            return real_execute(bucket, dev_batch)

        engine._execute = slow_execute
        try:
            futs = [router.submit(r) for r in reqs[:n]]
            rec = router.reanchor(
                surviving, why=f"chaos seed {seed}: device loss"
            )
        finally:
            engine._execute = real_execute
        futs += [router.submit(r) for r in reqs[n:]]
        answers = np.stack([np.asarray(f.result(60.0)) for f in futs])
    finally:
        router.close()
    if rec["failed"]:
        raise ChaosOracleError(
            f"re-anchor left shapes on the dead mesh: {rec['failed']}"
        )
    if counters.get("mesh_reanchor") - before < 1:
        raise ChaosOracleError(
            "engines re-anchored onto the surviving mesh but no "
            "mesh_reanchor was counted"
        )
    if not np.array_equal(answers, offline):
        raise ChaosOracleError(
            "answers across the re-anchor differ from the offline apply — "
            "the surviving mesh changed RESULTS, not just placement"
        )

    # -- leg 2: checkpoint on mesh A, resume on surviving mesh B --------------
    mean = jax.device_put(
        jnp.asarray(wrng.normal(size=(16,)).astype(np.float32)),
        NamedSharding(full, PartitionSpec(DATA_AXIS)),
    )
    std = jnp.abs(jnp.asarray(wrng.normal(size=(16,)).astype(np.float32))) + 1.0
    scaler = StandardScalerModel(mean, std)
    test_rows = _serve_requests(rng, n)
    fault_free = np.asarray(
        StandardScalerModel(np.asarray(jax.device_get(mean)), np.asarray(std))(
            test_rows
        )
    )
    stem = os.path.join(tmpdir, f"chaos_shrink_{seed}_ckpt")
    with use_mesh(full):
        stem = save_pipeline(stem, scaler)
    if n_full >= 2:
        # Arrays sharded over >1 device: the naive load must REFUSE typed.
        # (On a 1-device host the state is effectively replicated and the
        # strict load legitimately succeeds — nothing to refuse.)
        try:
            load_pipeline(stem)
        except CheckpointMismatch as e:
            if "mesh=" not in str(e):
                raise ChaosOracleError(
                    f"the topology refusal does not name the mesh= reshard "
                    f"path: {e}"
                )
        else:
            raise ChaosOracleError(
                "a checkpoint holding full-mesh-sharded arrays loaded "
                "silently onto a different topology"
            )
    before_rs = counters.get("ckpt_reshard")
    resumed = load_pipeline(stem, mesh=surviving)
    if counters.get("ckpt_reshard") - before_rs < 1:
        raise ChaosOracleError(
            "the checkpoint resumed on the surviving mesh but no "
            "ckpt_reshard was counted"
        )
    got = np.asarray(resumed(jnp.asarray(test_rows)))
    if not np.array_equal(got, fault_free):
        raise ChaosOracleError(
            "predictions resumed on the surviving mesh differ from the "
            "fault-free full-mesh run"
        )


def _host_loss_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """A serving host dies mid-flight (ISSUE 17): drive the multi-host
    drill (real subprocesses where spawn is available, the in-process
    wire fleet otherwise) and hold it to the never-silent bar — every
    request answered bit-equal to the offline oracle, zero dropped, the
    loss counted ``fleet_host_lost``, the survivors re-formed
    (``dist_reform``) and re-anchored (``host_reanchor``,
    postmortem-linked)."""
    from keystone_tpu.workloads.multihost import run_host_loss_drill

    hosts = int(
        os.environ.get("KEYSTONE_CHAOS_HOSTS", fault.params["hosts"])
    )
    lost_before = counters.get("fleet_host_lost")
    reanchor_before = counters.get("host_reanchor")
    rec = run_host_loss_drill(
        tmpdir,
        hosts=hosts,
        requests=int(fault.params["requests"]),
        seed=seed,
        timeout_s=180.0,
    )
    if rec["dropped_requests"] != 0:
        raise ChaosOracleError(
            f"host loss dropped {rec['dropped_requests']} request(s) "
            f"({rec['answered']}/{rec['requests']} answered; "
            f"errors: {rec['errors']})"
        )
    if rec["mismatches"] != 0:
        raise ChaosOracleError(
            f"{rec['mismatches']} answer(s) differ from the offline "
            "oracle after the host loss — silent wrong answers"
        )
    if rec["errors"]:
        raise ChaosOracleError(
            f"fleet clients saw errors across the loss: {rec['errors']}"
        )
    for r, sc in rec["survivor_counters"].items():
        if sc.get("dist_reform", 0) < 1:
            raise ChaosOracleError(
                f"survivor {r} never re-formed the group: {sc}"
            )
        if sc.get("host_reanchor", 0) < 1:
            raise ChaosOracleError(
                f"survivor {r} never re-anchored its engines: {sc}"
            )
    if counters.get("fleet_host_lost") - lost_before < 1:
        raise ChaosOracleError(
            "the front-end never counted the host loss (fleet_host_lost)"
        )
    if counters.get("host_reanchor") - reanchor_before < 1:
        raise ChaosOracleError(
            "the re-anchor was never counted controller-side "
            "(host_reanchor)"
        )
    pm = [p for p in rec["postmortems"] if "host_reanchor" in p]
    if not pm:
        raise ChaosOracleError(
            f"no host_reanchor postmortem dumped (got {rec['postmortems']})"
        )


def _obs_capture_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """A fleet member is SIGKILLed mid-scrape (ISSUE 20): drive the
    fleet-observability drill (real subprocess members where spawn is
    available, in-process wire fleet otherwise) and hold the collector to
    its bar — fleet counters equal the sum of per-member snapshots, fleet
    p99 comes from the pooled sample windows, the loss is counted
    ``obs_member_lost`` (postmortem-linked) with the fleet view monotone
    for the survivors, ONE clock-aligned incident bundle holds every
    surviving member's flight ring, and every request still answers
    bit-equal to the offline oracle — collection never touches serving."""
    from keystone_tpu.workloads.multihost import run_obs_capture_drill

    hosts = int(
        os.environ.get("KEYSTONE_CHAOS_HOSTS", fault.params["hosts"])
    )
    lost_before = counters.get("obs_member_lost")
    rec = run_obs_capture_drill(
        tmpdir,
        hosts=hosts,
        requests=int(fault.params["requests"]),
        seed=seed,
        timeout_s=180.0,
    )
    if rec["dropped_requests"] != 0:
        raise ChaosOracleError(
            f"obs drill dropped {rec['dropped_requests']} request(s) "
            f"({rec['answered']}/{rec['requests']} answered; "
            f"errors: {rec['errors']})"
        )
    if rec["mismatches"] != 0:
        raise ChaosOracleError(
            f"{rec['mismatches']} answer(s) differ from the offline "
            "oracle with the collector attached — collection touched "
            "the serving answers"
        )
    if not rec.get("counter_sum_ok"):
        raise ChaosOracleError(
            "fleet counters != sum of per-member snapshots: "
            f"{rec.get('counter_sum_mismatch')}"
        )
    if not rec.get("p99_match"):
        raise ChaosOracleError(
            f"fleet p99 {rec.get('p99_fleet')} does not come from the "
            f"pooled windows (pick oracle {rec.get('p99_oracle_pick')}, "
            f"numpy oracle {rec.get('p99_oracle_np')}, "
            f"pool n={rec.get('p99_pool_n')})"
        )
    if not rec.get("monotone_ok"):
        raise ChaosOracleError(
            "fleet counters stepped BACKWARDS across the member loss: "
            f"{rec.get('monotone_violations')}"
        )
    if counters.get("obs_member_lost") - lost_before < 1:
        raise ChaosOracleError(
            "the collector never counted the member loss "
            "(obs_member_lost)"
        )
    incident = rec.get("incident") or {}
    if incident.get("error"):
        raise ChaosOracleError(
            f"incident capture wrote {incident['error']} for one member "
            "loss — expected exactly one bundle"
        )
    if incident.get("schema") != "keystone.incident/1":
        raise ChaosOracleError(
            f"incident bundle is not schema-tagged: {incident}"
        )
    if not incident.get("survivor_rings_ok"):
        raise ChaosOracleError(
            "the incident bundle is missing a surviving member's flight "
            f"ring: {incident}"
        )
    if not incident.get("events_monotone"):
        raise ChaosOracleError(
            "incident bundle events are not on one monotone clock-aligned "
            "timeline"
        )
    pm = [p for p in rec["postmortems"] if "obs_member_lost" in p]
    if not pm:
        raise ChaosOracleError(
            f"no obs_member_lost postmortem dumped (got {rec['postmortems']})"
        )


def _stepdown_oracle(
    res: dict,
    stepdown_delta: int,
    *,
    require_specs: bool = False,
    require_mesh: bool = False,
) -> None:
    """Shared oracle of the plan/spec-mispredict families: the searched
    placement record must prove the top-ranked plan died and the fit
    chose the NEXT-ranked one, with the step-down counted.
    ``require_mesh``/``require_specs`` additionally pin that the killed
    plan was a mesh plan / a non-default spec-assignment layout."""
    placement = res.get("placement")
    if placement is None:
        raise ChaosOracleError(
            "no searched placement in results — the mispredict families "
            "require the placement search to be active"
        )
    ranking, chosen = placement["ranking"], placement["chosen"]
    top_rec = next(
        (
            c for c in placement["candidates"]
            if ranking and c["name"] == ranking[0]
        ),
        {},
    )
    if require_mesh and not top_rec.get("mesh"):
        raise ChaosOracleError(
            f"top-ranked plan {ranking[0] if ranking else None!r} is not "
            "a mesh plan — the schedule did not exercise a sharded layout"
        )
    if require_specs and not top_rec.get("specs"):
        raise ChaosOracleError(
            f"top-ranked plan {ranking[0] if ranking else None!r} carries "
            "no spec assignment — the schedule killed the default layout, "
            "not a searched spec layout"
        )
    if len(ranking) < 2 or chosen != ranking[1]:
        raise ChaosOracleError(
            f"top-ranked plan {ranking[0] if ranking else None!r} died "
            f"but the fit chose {chosen!r}, not the next-ranked "
            f"{ranking[1] if len(ranking) > 1 else None!r}"
        )
    if stepdown_delta < 1:
        raise ChaosOracleError(
            "the top-ranked plan died RESOURCE_EXHAUSTED but no "
            f"autoshard_stepdown was counted (top candidate: {top_rec})"
        )


def _drift_refit_phase(fault: Fault, tmpdir: str, seed: int) -> None:
    """The closed lifecycle loop end-to-end (ISSUE 18) plus its fault
    legs — see the module docstring's ``drift_refit`` bullet.

    One deployment, five legs in sequence: (0) a shifted request mix
    trips the armed drift monitor and the controller SEES the trip;
    (A) a refit that OOMs materializing fresh features degrades typed +
    counted ``refit_failed`` to the incumbent; (B) a candidate refit
    over garbage labels is REJECTED by the holdout gate (counted
    ``refit_rejected``) — never swapped; (C) a mid-swap kill (the router
    dying under the replace) degrades typed + counted to the incumbent;
    (D) the clean cycle lands: warm refit, validation, atomic hot-swap
    with requests in flight (counted ``lifecycle_refit``, postmortem
    dumped, drift re-armed on the candidate's baseline, zero dropped,
    post-swap answers bit-equal to an OFFLINE refit); and (E) a trip
    inside the fresh cooldown is a counted suppression
    (``refit_suppressed``), not a refit storm."""
    import glob as _glob

    import jax.numpy as jnp

    from keystone_tpu.core import frontend as kfrontend
    from keystone_tpu.core import numerics as knum
    from keystone_tpu.core import serve as kserve
    from keystone_tpu.core import telemetry as ktelemetry
    from keystone_tpu.core.lifecycle import LifecycleConfig, LifecycleController
    from keystone_tpu.ops.stats import StandardScalerModel
    from keystone_tpu.solvers.block import BlockLeastSquaresEstimator

    rng = np.random.default_rng(seed)
    n_ref = int(fault.params["reference"])
    n_shift = int(fault.params["shifted"])
    scale = float(fault.params["shift_scale"])
    n_rows = int(fault.params["rows"])
    n_req = int(fault.params["requests"])
    hold = float(fault.params["hold_seconds"])

    # Two worlds, one deployment: before the drift the truth is
    # ``(x - mean0) @ T1``; after the mix shifts the truth is
    # ``(x - mean0) @ T2`` — so the incumbent is genuinely WRONG on the
    # new mix and a refit on fresh data genuinely fixes it (the quality
    # gate has something real to judge).  Featurizer (mean-subtract) is
    # exactly-rounded elementwise arithmetic, weights schedule-invariant.
    wrng = np.random.default_rng(_DATA_SEED)
    mean0 = wrng.normal(size=(16,)).astype(np.float32)
    t1 = wrng.normal(size=(16, 4)).astype(np.float32)
    t2 = wrng.normal(size=(16, 4)).astype(np.float32)
    featurizer = StandardScalerModel(jnp.asarray(mean0), None)
    shift = np.zeros(16, np.float32)
    shift[int(np.argmax(np.abs(t1).sum(axis=1)))] = scale

    def fit_model(feats, labels, checkpoint=None):
        est = BlockLeastSquaresEstimator(block_size=16, num_iter=1, lam=0.0)
        return est.fit(
            jnp.asarray(feats), jnp.asarray(labels), checkpoint=checkpoint
        )

    # Incumbent: fit on the pre-drift world, served behind a router.
    xa = _serve_requests(rng, n_rows)
    feats_a = xa - mean0
    pipe_inc = featurizer.then(fit_model(feats_a, feats_a @ t1))
    cfg = kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0)
    engine_inc = kserve.ServingEngine(
        pipe_inc, np.zeros(16, np.float32), config=cfg,
        label=f"chaos_refit_inc_{seed}",
    )
    ref = _serve_requests(rng, n_ref)
    baseline = knum.OutputSketch.for_outputs(engine_inc.offline(ref)).record()

    # Post-drift world: shifted requests, new truth, fresh training data.
    xb = _serve_requests(rng, n_rows) + shift
    feats_b = xb - mean0
    labels_b = feats_b @ t2
    # Big enough that the noise-fit candidate's holdout MSE dwarfs even a
    # badly-wrong incumbent's — the rejection leg must be unambiguous.
    labels_noise = rng.normal(size=labels_b.shape).astype(np.float32) * 50.0
    hx = _serve_requests(rng, 64) + shift
    hy = (hx - mean0) @ t2
    shifted = _serve_requests(rng, n_shift) + shift
    reqs_mid = _serve_requests(rng, n_req) + shift
    reqs_post = _serve_requests(rng, n_req) + shift

    # The OFFLINE refit oracle: same fresh data, fit outside the
    # controller — post-swap served answers must be bit-equal to it.
    pipe_offline = featurizer.then(fit_model(feats_b, labels_b))
    offline_refit = np.asarray(pipe_offline(jnp.asarray(reqs_post)))

    mode = {"fetch": "good"}

    def fetch(digest):
        if mode["fetch"] == "oom":
            raise faults.resource_exhausted_error()
        if mode["fetch"] == "noise":
            return feats_b, labels_noise
        return feats_b, labels_b

    def quality(predict, x, y):
        return -float(np.mean((np.asarray(predict(x)) - y) ** 2))

    pm_dir = os.path.join(tmpdir, f"chaos_refit_{seed}_pm")
    with ktelemetry._pm_lock:
        ktelemetry._pm_counts.pop("serve_output_drift", None)
        ktelemetry._pm_counts.pop("lifecycle_refit", None)
    before = {
        k: counters.get(k)
        for k in (
            "serve_output_drift", "refit_failed", "refit_rejected",
            "lifecycle_refit", "drift_rearmed", "refit_suppressed",
        )
    }

    def delta(kind):
        return counters.get(kind) - before[kind]

    router = kfrontend.ShapeRouter(
        label=f"chaos_refit_{seed}",
        config=kfrontend.RouterConfig(warm_threshold=1, retire_after_s=300.0),
    )
    os.environ["KEYSTONE_POSTMORTEM_DIR"] = pm_dir
    ctl = None
    try:
        router.add_engine(engine_inc)
        ctl = LifecycleController(
            router,
            workdir=os.path.join(tmpdir, f"chaos_refit_{seed}_wd"),
            featurizer=featurizer,
            fetch=fetch,
            estimator=lambda: BlockLeastSquaresEstimator(
                block_size=16, num_iter=1, lam=0.0
            ),
            assemble=lambda model: featurizer.then(model),
            holdout=lambda: (hx, hy),
            quality=quality,
            example=np.zeros(16, np.float32),
            label=f"chaos_refit_{seed}",
            serve_config=cfg,
            config=LifecycleConfig(cooldown_s=0.0, poll_interval_s=0.05),
        )
        with knum.monitored(True):
            engine_inc.arm_drift_baseline(baseline)
            # -- leg 0: the shifted mix trips the armed monitor ---------------
            futs = [router.submit(r) for r in shifted]
            mon = np.stack([np.asarray(f.result(30.0)) for f in futs])
            if not np.array_equal(mon, engine_inc.offline(shifted)):
                raise ChaosOracleError(
                    "served answers under drift detection differ from the "
                    "incumbent's offline apply"
                )
            if delta("serve_output_drift") < 1:
                raise ChaosOracleError(
                    "shifted request mix produced no counted "
                    "serve_output_drift — the monitor missed the shift"
                )
            reason = ctl.check_signals()
            if reason != "serve_output_drift":
                raise ChaosOracleError(
                    f"the lifecycle watcher did not see the drift trip "
                    f"(check_signals -> {reason!r})"
                )

            def incumbent_still_serving(leg):
                table = router.engines()
                if table.get((16,)) != engine_inc.label:
                    raise ChaosOracleError(
                        f"{leg}: the failed cycle touched the routing table "
                        f"({table}) — a half-swapped model is serving"
                    )
                probe = _serve_requests(rng, 3) + shift
                got = np.stack(
                    [
                        np.asarray(f.result(30.0))
                        for f in [router.submit(r) for r in probe]
                    ]
                )
                if not np.array_equal(got, engine_inc.offline(probe)):
                    raise ChaosOracleError(
                        f"{leg}: post-fault answers differ from the "
                        "incumbent's offline apply — silent wrong answers"
                    )

            # -- leg A: refit OOM degrades typed + counted --------------------
            mode["fetch"] = "oom"
            rec = ctl.run_refit(reason=reason)
            if rec["outcome"] != "refit_failed" or delta("refit_failed") < 1:
                raise ChaosOracleError(
                    f"injected refit OOM was not a counted typed "
                    f"degradation: {rec}"
                )
            incumbent_still_serving("refit OOM")

            # -- leg B: a WORSE candidate is rejected, never swapped ----------
            mode["fetch"] = "noise"
            rec = ctl.run_refit(reason="operator")
            if rec["outcome"] != "rejected" or delta("refit_rejected") < 1:
                raise ChaosOracleError(
                    f"a candidate refit worse than the incumbent was not "
                    f"rejected+counted: {rec}"
                )
            incumbent_still_serving("validation rejection")

            # -- leg C: a mid-swap kill degrades typed + counted --------------
            mode["fetch"] = "good"
            real_replace = router.replace_engine
            failed_before = delta("refit_failed")
            try:
                def killed_replace(engine, **kw):
                    raise kserve.ServingUnavailable("injected mid-swap kill")

                router.replace_engine = killed_replace
                rec = ctl.run_refit(reason="operator")
            finally:
                router.replace_engine = real_replace
            if (
                rec["outcome"] != "refit_failed"
                or rec.get("phase") != "swap"
                or delta("refit_failed") <= failed_before
            ):
                raise ChaosOracleError(
                    f"a mid-swap kill was not a counted typed degradation "
                    f"to the incumbent: {rec}"
                )
            incumbent_still_serving("mid-swap kill")

            # -- leg D: the clean cycle lands, requests in flight -------------
            ctl.config.cooldown_s = 300.0  # leg E exercises the storm guard
            inflight_mid = []
            real_execute = engine_inc._execute

            def slow_execute(bucket, dev_batch):
                # Stretch the incumbent's batches so the swap demonstrably
                # straddles live requests (drain-after-unroute resolves
                # them on the OLD engine — zero loss).
                time.sleep(hold)
                return real_execute(bucket, dev_batch)

            def replace_with_traffic(engine, **kw):
                inflight_mid.extend(router.submit(r) for r in reqs_mid)
                return real_replace(engine, **kw)

            try:
                engine_inc._execute = slow_execute
                router.replace_engine = replace_with_traffic
                rec = ctl.run_refit(reason=reason)
            finally:
                engine_inc._execute = real_execute
                router.replace_engine = real_replace
            if rec["outcome"] != "swapped" or delta("lifecycle_refit") < 1:
                raise ChaosOracleError(
                    f"the clean drift->refit->swap cycle did not land "
                    f"counted: {rec}"
                )
            if delta("drift_rearmed") < 1:
                raise ChaosOracleError(
                    "the swap landed but the drift monitor was not "
                    "re-armed on the candidate's baseline"
                )
            dropped = 0
            mid_answers = []
            for f in inflight_mid:
                try:
                    mid_answers.append(np.asarray(f.result(60.0)))
                except Exception:  # noqa: BLE001 — counted as a drop
                    dropped += 1
            if dropped:
                raise ChaosOracleError(
                    f"{dropped} request(s) in flight across the hot-swap "
                    "were dropped — the swap opened a service gap"
                )
            if not np.array_equal(
                np.stack(mid_answers), engine_inc.offline(reqs_mid)
            ):
                raise ChaosOracleError(
                    "in-flight answers across the swap differ from the "
                    "incumbent's offline apply"
                )
            engine_new = router.server_for((16,)).engine
            if engine_new is engine_inc:
                raise ChaosOracleError("the swap left the incumbent routed")
            post = np.stack(
                [
                    np.asarray(f.result(30.0))
                    for f in [router.submit(r) for r in reqs_post]
                ]
            )
            if not np.array_equal(post, offline_refit):
                raise ChaosOracleError(
                    "post-swap answers differ from the offline refit — "
                    "the lifecycle served a model that is not the refit"
                )
            dumps = _glob.glob(
                os.path.join(pm_dir, "postmortem_lifecycle_refit_*.json")
            )
            if not dumps:
                raise ChaosOracleError(
                    "lifecycle_refit was counted but no flight-recorder "
                    "postmortem was dumped — the swap left no evidence"
                )

            # -- leg E: the cooldown storm guard ------------------------------
            rec = ctl.run_refit(reason="operator")
            if (
                rec["outcome"] != "suppressed"
                or delta("refit_suppressed") < 1
            ):
                raise ChaosOracleError(
                    f"a trip inside the cooldown was not a counted "
                    f"suppression: {rec}"
                )
    finally:
        if ctl is not None:
            ctl.close()
        router.close()
        os.environ.pop("KEYSTONE_POSTMORTEM_DIR", None)
        knum.reset_state()


def _run_faulted(fault: Fault, workload: str, tmpdir: str, seed: int):
    """Apply one schedule to the workload; returns the results dict (or
    raises).  Each branch is the minimal faithful injection for its
    family — all patches restored on exit."""
    if fault.kind == "solver_oom":
        with faults.oom_faults(
            block_mod, "_execute_fused_bcd", failures=fault.params["failures"]
        ):
            return _run_workload(workload)

    if fault.kind == "oom_cascade":
        # Fused dies, then the stepwise per-block solve dies too: the
        # ladder must walk fused -> stepwise -> host_staged.
        with faults.oom_faults(block_mod, "_execute_fused_bcd", failures=1):
            with faults.oom_faults(block_mod, "_bcd_block_solve", failures=1):
                return _run_workload(workload)

    if fault.kind in ("io_transient", "corrupt_members"):
        _ingest_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "stream_corrupt":
        _stream_corrupt_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "jpeg_corrupt_entropy":
        _jpeg_corrupt_entropy_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "native_entropy":
        _native_entropy_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "profiler_crash":
        _profiler_crash_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "output_drift":
        _output_drift_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "mesh_shrink":
        _mesh_shrink_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "host_loss":
        _host_loss_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "obs_capture":
        _obs_capture_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "drift_refit":
        _drift_refit_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "stream_hang":
        return _stream_hang_phase(fault, tmpdir, seed)  # always raises

    if fault.kind == "autotune_thrash":
        _autotune_thrash_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "snapshot_corrupt":
        _snapshot_corrupt_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "decode_worker_kill":
        _decode_worker_kill_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "slow_client":
        _slow_client_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "malformed_request":
        _malformed_request_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "serve_burst_oom":
        _serve_burst_oom_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "wire_disconnect":
        _wire_disconnect_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "slow_loris":
        _slow_loris_phase(fault, tmpdir, seed)
        return _run_workload(workload)

    if fault.kind == "plan_mispredict":
        # The cost model's top-ranked plan (fused, on these shapes) is made
        # WRONG at runtime: injected RESOURCE_EXHAUSTED at its dispatch.
        # Oracle: the fit walks to the NEXT plan in the SEARCHED ranking
        # (the placement record proves the order), the step-down is
        # counted, and the judge then holds predictions to bit-equality.
        from keystone_tpu.core.resilience import counters as _counters

        before = _counters.get("autoshard_stepdown")
        with faults.oom_faults(
            block_mod, "_execute_fused_bcd", failures=fault.params["failures"]
        ):
            res = _run_workload(workload)
        _stepdown_oracle(res, _counters.get("autoshard_stepdown") - before)
        return res

    if fault.kind == "spec_mispredict":
        # The spec-ASSIGNMENT analog (ISSUE 10): the fault-free mesh
        # baseline's placement table names the enumerated spec candidates;
        # one on the head mesh shape is FORCED to the top of the faulted
        # run's ranking (conf.solve_plan -> fit(plan=[name])), so the plan
        # that dies at the GSPMD dispatch is a real non-default
        # NamedSharding layout lowered from searched spec strings — not
        # the same default rung plan_mispredict already kills.  The fit
        # must step down the ranking (counted autoshard_stepdown) onto
        # the default plan, and the judge then holds predictions
        # bit-equal to the fault-free MESH baseline.
        from keystone_tpu.core.resilience import counters as _counters

        base_pl = baseline(workload, mesh=True).get("placement")
        forced = None
        if base_pl and base_pl.get("ranking"):
            head = next(
                (
                    c for c in base_pl["candidates"]
                    if c["name"] == base_pl["ranking"][0]
                ),
                {},
            )
            forced = next(
                (
                    [c["name"]] for c in base_pl["candidates"]
                    if c.get("specs") and not c["pruned"]
                    and c["mesh"] == head.get("mesh")
                ),
                None,
            )
        before = _counters.get("autoshard_stepdown")
        with faults.oom_faults(
            block_mod, "_execute_fused_bcd_mesh",
            failures=fault.params["failures"],
        ):
            res = _run_workload(
                workload, mesh=_spec_mesh(), solve_plan=forced
            )
        _stepdown_oracle(
            res,
            _counters.get("autoshard_stepdown") - before,
            # With >= 2 devices a spec candidate always exists; a 1x1 mesh
            # has no non-default layouts, so the oracle degrades to the
            # mesh-plan check there instead of passing vacuously.
            require_specs=forced is not None,
            require_mesh=True,
        )
        return res

    if fault.kind == "nan_input":
        frac = fault.params["frac"]
        rng = np.random.default_rng(seed)

        def poison(train):
            if hasattr(train, "data"):  # LabeledData
                return dataclasses.replace(
                    train, data=faults.inject_nan(train.data, rng, frac)
                )
            return dataclasses.replace(  # LabeledImageBatch
                train, images=faults.inject_nan(train.images, rng, frac)
            )

        return _run_workload(workload, train_override=poison)

    if fault.kind == "preempt_resume":
        ckpt_path = os.path.join(tmpdir, f"chaos_bcd_{workload}_{seed}")
        writer = bcd_checkpoint_writer(ckpt_path)
        after = int(fault.params["preempt_after_blocks"])
        calls = {"n": 0}

        def preempting_cb(state):
            writer(state)
            calls["n"] += 1
            if calls["n"] >= after:
                raise SimulatedPreemption(
                    f"injected preemption after block {state['block']} "
                    f"of epoch {state['epoch']}"
                )

        try:
            _run_workload(workload, solve_checkpoint=preempting_cb)
        except SimulatedPreemption:
            pass
        else:
            raise ChaosOracleError(
                "preemption callback never fired — the checkpointing "
                "stepwise path was not taken"
            )
        counters.record(
            "chaos_preemption", f"{workload} seed {seed}: resuming from "
            f"{ckpt_path}"
        )
        return _run_workload(
            workload,
            solve_checkpoint=ckpt_path,
            solve_resume=ckpt_path,
        )

    if fault.kind == "deadline":
        budget = float(fault.params["seconds"])
        real = block_mod._execute_fused_bcd

        def hanging_execute(*a, **kw):
            time.sleep(600.0)  # interrupted by the deadline watchdog
            return real(*a, **kw)

        with _patched(block_mod, "_execute_fused_bcd", hanging_execute):
            with deadline(budget, phase="solve"):
                return _run_workload(workload)

    raise ValueError(f"unknown fault family {fault.kind!r}")


def expected_outcome(fault: Fault) -> str:
    """What a HEALTHY system does under this schedule."""
    if fault.kind in ("nan_input", "deadline", "stream_hang"):
        return "typed_error"
    return "completed_equal"


def run_schedule(
    seed: int,
    workload: str = "mnist",
    tmpdir: str | None = None,
    trace_path: str | None = None,
) -> ChaosResult:
    """Run ONE seeded fault schedule end-to-end and judge the outcome.

    ``trace_path``: write a per-schedule Chrome-trace JSON of the faulted
    run — every counted fault lands in it as an instant event (kind attr)
    and every failed span carries the error type, so
    :func:`verify_trace` can hold the trace to the never-silent bar."""
    fault = make_schedule(seed)
    own_tmp = tmpdir is None
    if own_tmp:
        tmpdir = tempfile.mkdtemp(prefix="chaos_")
    t0 = time.monotonic()
    result = ChaosResult(seed=seed, workload=workload, fault=fault, outcome="")
    with _clean_env():
        # spec_mispredict runs under a mesh, so it is judged against the
        # fault-free MESH baseline (same devices, same mesh shape).
        base = baseline(workload, mesh=fault.kind == "spec_mispredict")
        if trace_path is not None:
            # Per-schedule timeline: clear the buffer so this trace holds
            # exactly this schedule's events (baseline is pre-cached above).
            trace.reset()
            trace.enable(trace_path)
        before = counters.snapshot()
        try:
            result.outcome = _judge_schedule(
                result, fault, workload, tmpdir, seed, base
            )
        finally:
            after = counters.snapshot()
            result.counters_delta = {
                k: after[k] - before.get(k, 0)
                for k in after
                if after[k] != before.get(k, 0)
            }
            if trace_path is not None:
                # finally: even an unexpected (KeyboardInterrupt-class)
                # escape must not leave tracing globally enabled with
                # _path aimed at this schedule's file.
                trace.flush(trace_path)
                trace.disable()
                result.trace_path = trace_path
    result.seconds = time.monotonic() - t0
    if own_tmp:
        shutil.rmtree(tmpdir, ignore_errors=True)
    return result


def _judge_schedule(result, fault, workload, tmpdir, seed, base) -> str:
    """Run one faulted schedule and return the judged outcome (filling
    ``result``'s error fields as a side effect)."""
    try:
        res = _run_faulted(fault, workload, tmpdir, seed)
    except TYPED_ERRORS as e:
        result.error_type = type(e).__name__
        result.error = str(e)
        result.phase = getattr(e, "phase", None)
        return "typed_error"
    except ChaosOracleError as e:
        result.error_type = type(e).__name__
        result.error = str(e)
        return "ORACLE_FAILED"
    except Exception as e:  # noqa: BLE001 — the contract violation case
        result.error_type = type(e).__name__
        result.error = str(e)
        return "UNTYPED_ERROR"
    got = res.get("test_predictions")
    want = base.get("test_predictions")
    if got is None or want is None:
        # A missing prediction vector must never score as equal — that
        # would be the oracle passing vacuously.
        result.error = (
            "no test_predictions to compare "
            f"(faulted: {got is not None}, baseline: {want is not None})"
        )
        return "ORACLE_FAILED"
    if _preds_equal(got, want):
        return "completed_equal"
    result.error = (
        "run completed but predictions differ from the fault-free baseline"
    )
    return "SILENT_WRONG_MODEL"


def verify_trace(trace_path: str, result: ChaosResult) -> list[str]:
    """Hold one schedule's trace to the never-silent bar.  Returns the
    violations (empty = clean):

    * every fault kind counted during the schedule must appear as a
      ``fault`` instant event with a matching ``kind`` attribute;
    * a typed-error outcome must also be visible as a span that FAILED
      with that error type (spans record ``error`` on exception) or as a
      counted fault event — a typed error that left no trace evidence is
      an observability regression even when the run itself was judged ok.
    """
    import json as _json

    with open(trace_path) as f:
        if trace_path.endswith(".jsonl"):
            events = [_json.loads(line) for line in f if line.strip()]
        else:
            doc = _json.load(f)
            events = (
                doc.get("traceEvents", []) if isinstance(doc, dict) else doc
            )
    fault_kinds = {
        ev.get("args", {}).get("kind")
        for ev in events
        if ev.get("ph") == "i" and ev.get("name") == "fault"
    }
    span_errors = {
        ev.get("args", {}).get("error")
        for ev in events
        if ev.get("ph") == "X" and ev.get("args", {}).get("error")
    }
    missing = [
        f"counted fault {kind!r} has no trace event"
        for kind in sorted(result.counters_delta)
        if kind not in fault_kinds
    ]
    if (
        result.outcome == "typed_error"
        and result.error_type not in span_errors
        and not fault_kinds
    ):
        missing.append(
            f"typed error {result.error_type} appears in no span and no "
            "fault event — a silent typed failure"
        )
    return missing


def run_suite(
    seeds, workload: str = "mnist", trace_dir: str | None = None
) -> list[ChaosResult]:
    tmpdir = tempfile.mkdtemp(prefix="chaos_suite_")
    try:
        results = []
        for s in seeds:
            tp = (
                os.path.join(trace_dir, f"chaos_seed{s}.json")
                if trace_dir is not None
                else None
            )
            results.append(
                run_schedule(s, workload=workload, tmpdir=tmpdir, trace_path=tp)
            )
        return results
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
