"""Closed-loop lifecycle tests (core.lifecycle, ISSUE 18): the warm
refit must actually be WARM (featurized snapshots reused, zero
featurizer recompute, measurably cheaper than a cold pass) and
bit-equal to the cold fit; the controller's cycle must swap only
validated candidates, debounce under cooldown, and force a cold
featurize pass the moment the featurizer digest moves."""

import io
import tarfile
import time

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.core import frontend as kfrontend
from keystone_tpu.core import lifecycle
from keystone_tpu.core import numerics as knum
from keystone_tpu.core import serve as kserve
from keystone_tpu.core import telemetry
from keystone_tpu.core.resilience import counters
from keystone_tpu.ops.stats import StandardScalerModel
from keystone_tpu.solvers.block import BlockLeastSquaresEstimator

pytestmark = pytest.mark.serve


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _tiny_tar(path) -> str:
    """snapshot_key folds in the input tar's identity — the refit stream
    stand-in only needs to EXIST and be stable."""
    data = b"keystone refit stream stand-in"
    with tarfile.open(path, "w") as tf:
        info = tarfile.TarInfo("member_0000.bin")
        info.size = len(data)
        tf.addfile(info, io.BytesIO(data))
    return str(path)


def _world(seed=20260806, d=16, k=4, rows=128):
    """One linear world: requests x, truth ``(x - mean) @ t`` — labels
    exactly linear in the featurized inputs, so a clean refit recovers
    the truth near-exactly and the quality gate has a crisp decision."""
    rng = np.random.default_rng(seed)
    mean = rng.normal(size=(d,)).astype(np.float32)
    t = rng.normal(size=(d, k)).astype(np.float32)
    x = rng.normal(size=(rows, d)).astype(np.float32)
    feats = x - mean
    return {
        "rng": rng, "mean": mean, "t": t, "x": x,
        "feats": feats, "labels": feats @ t,
        "featurizer": StandardScalerModel(jnp.asarray(mean), None),
    }


def _fit(feats, labels, checkpoint=None):
    est = BlockLeastSquaresEstimator(block_size=16, num_iter=1, lam=0.0)
    return est.fit(jnp.asarray(feats), jnp.asarray(labels), checkpoint=checkpoint)


class TestFeaturizedTrainingSet:
    def test_warm_refit_reuses_featurized_snapshot(self, tmp_path):
        """The satellite pin: an unchanged featurizer streams features
        straight from the committed snapshot — ``compute`` never runs
        again (zero featurizer recompute), ``snapshot_stale`` stays 0,
        the warm pass is measurably cheaper, and the model fit from the
        snapshot is bit-equal to the one fit from the live pass."""
        w = _world()
        tar = _tiny_tar(tmp_path / "stream.tar")
        root = str(tmp_path / "snaps")
        calls = {"n": 0}

        def compute():
            calls["n"] += 1
            time.sleep(0.1)  # stand-in for the real featurize pass
            return w["feats"], w["labels"]

        stale_before = counters.get("snapshot_stale")
        t0 = time.perf_counter()
        f1, l1, info1 = lifecycle.featurized_training_set(
            root, tar_path=tar, featurizer=w["featurizer"], compute=compute
        )
        cold_wall = time.perf_counter() - t0
        assert info1["source"] == "computed"
        assert calls["n"] == 1

        t0 = time.perf_counter()
        f2, l2, info2 = lifecycle.featurized_training_set(
            root, tar_path=tar, featurizer=w["featurizer"], compute=compute
        )
        warm_wall = time.perf_counter() - t0
        assert info2["source"] == "snapshot"
        assert info2["key"] == info1["key"]
        assert calls["n"] == 1  # zero featurizer recompute
        assert counters.get("snapshot_stale") - stale_before == 0
        assert not info2["stale"]
        assert warm_wall < cold_wall  # measurably cheaper than cold

        # Bit-equal data in, bit-equal model out: the warm (stepwise,
        # checkpoint=) fit matches the cold fused fit exactly.
        assert np.array_equal(f1, f2)
        assert np.array_equal(l1, l2)
        probe = jnp.asarray(w["rng"].normal(size=(8, 16)).astype(np.float32))
        warm = _fit(f2, l2, checkpoint=str(tmp_path / "bcd"))
        cold = _fit(f1, l1)
        assert np.array_equal(np.asarray(warm(probe)), np.asarray(cold(probe)))

    def test_changed_featurizer_moves_key_and_counts_stale(self, tmp_path):
        """A CHANGED featurizer must never silently reuse stale features:
        the digest moves the snapshot key, the old snapshot classifies
        STALE (counted), and the cold pass runs."""
        w = _world()
        tar = _tiny_tar(tmp_path / "stream.tar")
        root = str(tmp_path / "snaps")
        calls = {"n": 0}

        def compute():
            calls["n"] += 1
            return w["feats"], w["labels"]

        _, _, info1 = lifecycle.featurized_training_set(
            root, tar_path=tar, featurizer=w["featurizer"], compute=compute
        )
        moved = StandardScalerModel(jnp.asarray(w["mean"] + 1.0), None)
        stale_before = counters.get("snapshot_stale")
        _, _, info2 = lifecycle.featurized_training_set(
            root, tar_path=tar, featurizer=moved, compute=compute
        )
        assert info2["digest"] != info1["digest"]
        assert info2["key"] != info1["key"]
        assert info2["source"] == "computed"
        assert info2["stale"]
        assert calls["n"] == 2
        assert counters.get("snapshot_stale") - stale_before == 1


def _deploy(tmp_path, *, featurizer=None, fetch=None, quality_margin=0.0,
            cooldown_s=0.0, clock=None, label="lifetest"):
    """One served deployment behind a router + its controller: incumbent
    fit on the world's truth, fresh-data fetch defaulting to the same
    world (a clean refit should always pass the gate)."""
    w = _world()
    pipe_inc = w["featurizer"].then(_fit(w["feats"], w["labels"]))
    cfg = kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0)
    engine = kserve.ServingEngine(
        pipe_inc, np.zeros(16, np.float32), config=cfg, label=f"{label}_inc"
    )
    router = kfrontend.ShapeRouter(
        label=f"{label}_router",
        config=kfrontend.RouterConfig(warm_threshold=1, retire_after_s=300.0),
    )
    router.add_engine(engine)
    hx = w["rng"].normal(size=(64, 16)).astype(np.float32)
    hy = (hx - w["mean"]) @ w["t"]

    def default_fetch(digest):
        return w["feats"], w["labels"]

    def quality(predict, x, y):
        return -float(np.mean((np.asarray(predict(x)) - y) ** 2))

    ctl = lifecycle.LifecycleController(
        router,
        workdir=str(tmp_path / f"{label}_wd"),
        featurizer=featurizer if featurizer is not None else w["featurizer"],
        fetch=fetch or default_fetch,
        estimator=lambda: BlockLeastSquaresEstimator(
            block_size=16, num_iter=1, lam=0.0
        ),
        assemble=lambda model: w["featurizer"].then(model),
        holdout=lambda: (hx, hy),
        quality=quality,
        example=np.zeros(16, np.float32),
        label=label,
        serve_config=cfg,
        config=lifecycle.LifecycleConfig(
            cooldown_s=cooldown_s, quality_margin=quality_margin
        ),
        clock=clock or time.monotonic,
    )
    return w, router, engine, ctl


class TestLifecycleController:
    def test_clean_cycle_swaps_and_rearms(self, tmp_path, rng):
        w, router, engine, ctl = _deploy(tmp_path, label="lc_swap")
        before = {k: counters.get(k)
                  for k in ("lifecycle_refit", "drift_rearmed")}
        try:
            with ctl:
                rec = ctl.run_refit(reason="operator")
                assert rec["outcome"] == "swapped", rec
                assert rec["generation"] == 1
                assert not rec["cold_fit"]
                # The successor is routed and answers for the shape.
                entry_label = router.engines()[(16,)]
                assert entry_label == rec["engine_label"]
                assert entry_label != engine.label
                new_engine = router.server_for((16,)).engine
                reqs = rng.normal(size=(4, 16)).astype(np.float32)
                ans = np.stack(
                    [router.submit(r).result(30.0) for r in reqs]
                )
                assert np.array_equal(ans, new_engine.offline(reqs))
                # Landed + re-armed, both counted (load_engine arms the
                # monitor from the persisted baseline, so the swap's
                # rearm_drift_baseline takes the rearm path).
                assert counters.get("lifecycle_refit") - before["lifecycle_refit"] == 1
                assert counters.get("drift_rearmed") - before["drift_rearmed"] == 1
                # The swapped engine watches drift on the CANDIDATE's baseline.
                mon = knum.drift_monitors().get(entry_label)
                assert mon is not None and not mon["drifted"]
                # statusz carries the controller section.
                doc = telemetry.statusz_snapshot()
                sect = doc["providers"]["lifecycle:lc_swap"]
                assert sect["state"] in lifecycle.STATES
                assert sect["generation"] == 1
                assert sect["last_cycle"]["outcome"] == "swapped"
        finally:
            router.close()

    def test_rejected_candidate_never_swapped(self, tmp_path):
        """The no-unvalidated-model invariant: a candidate refit over
        garbage labels loses the holdout gate, is counted
        ``refit_rejected``, and the routing table is untouched."""
        w = _world()
        noise = w["rng"].normal(size=w["labels"].shape).astype(np.float32) * 50.0
        _, router, engine, ctl = _deploy(
            tmp_path, fetch=lambda digest: (w["feats"], noise), label="lc_rej"
        )
        before = counters.get("refit_rejected")
        try:
            with ctl:
                rec = ctl.run_refit(reason="operator")
                assert rec["outcome"] == "rejected", rec
                assert rec["quality"]["candidate"] < rec["quality"]["incumbent"]
                assert counters.get("refit_rejected") - before == 1
                # Incumbent untouched: same engine object still routed.
                assert router.server_for((16,)).engine is engine
                assert router.stats.replaces == 0
        finally:
            router.close()

    def test_cooldown_suppresses_then_decays(self, tmp_path):
        """The storm guard: a trip inside the cooldown is a counted
        suppression, and the window decays on the (injected) clock."""
        clock = FakeClock()
        _, router, _, ctl = _deploy(
            tmp_path, cooldown_s=100.0, clock=clock, label="lc_cool"
        )
        before = counters.get("refit_suppressed")
        try:
            with ctl:
                rec1 = ctl.run_refit(reason="operator")
                assert rec1["outcome"] == "swapped", rec1
                assert ctl.state == "COOLDOWN"
                rec2 = ctl.run_refit(reason="operator")
                assert rec2["outcome"] == "suppressed"
                assert rec2["why"] == "cooldown"
                assert counters.get("refit_suppressed") - before == 1
                assert ctl.generation == 1  # no cycle ran
                clock.advance(200.0)
                assert ctl.state == "IDLE"  # lazy decay
                rec3 = ctl.run_refit(reason="operator")
                assert rec3["outcome"] == "swapped"
                assert ctl.generation == 2
        finally:
            router.close()

    def test_changed_featurizer_counts_cold_fit(self, tmp_path):
        """A featurizer change between cycles moves the digest: the next
        refit is a COLD fit (counted ``refit_cold_fit``) — never a
        silent warm start over stale features."""
        w = _world()
        cell = {"mean": w["mean"]}

        def provider():
            return StandardScalerModel(jnp.asarray(cell["mean"]), None)

        def fetch(digest):
            feats = w["x"] - cell["mean"]
            return feats, feats @ w["t"]

        _, router, _, ctl = _deploy(
            tmp_path, featurizer=provider, fetch=fetch,
            quality_margin=1e-3, label="lc_cold",
        )
        before = counters.get("refit_cold_fit")
        try:
            with ctl:
                rec1 = ctl.run_refit(reason="operator")
                assert rec1["outcome"] == "swapped", rec1
                assert not rec1["cold_fit"]
                cell["mean"] = w["mean"] + 0.5  # the featurizer moves
                rec2 = ctl.run_refit(reason="operator")
                assert rec2["cold_fit"]
                assert counters.get("refit_cold_fit") - before == 1
        finally:
            router.close()

    def test_check_signals_sees_drift_counter(self, tmp_path):
        """The watcher's poll trips on a ``serve_output_drift`` delta
        exactly once (the baseline re-bases so one breach is one trip)."""
        _, router, _, ctl = _deploy(tmp_path, label="lc_sig")
        try:
            with ctl:
                assert ctl.check_signals() is None
                counters.record(
                    "serve_output_drift", "test: synthetic drift breach"
                )
                assert ctl.check_signals() == "serve_output_drift"
                assert ctl.check_signals() is None  # re-based, no re-trip
                ctl.request_refit("operator")  # sets the event...
                # ...and with no watcher thread the cycle ran synchronously
                assert ctl._last_cycle is not None
        finally:
            router.close()


class TestDriftRearm:
    def test_rearm_resets_latch_and_window(self):
        """DriftMonitor.rearm (ISSUE 18 satellite): new baseline in, live
        window + latch out, counted ``drift_rearmed``."""
        rng = np.random.default_rng(7)
        base_a = knum.OutputSketch.for_outputs(
            rng.normal(size=(200, 4)).astype(np.float32)
        ).record()
        mon = knum.DriftMonitor("rearm_test", base_a, tol=0.25)
        before = counters.get("drift_rearmed")
        try:
            shifted = rng.normal(size=(200, 4)).astype(np.float32) + 100.0
            mon.observe(shifted)
            assert mon.latched
            assert mon.breaches == 1
            base_b = knum.OutputSketch.for_outputs(shifted).record()
            mon.rearm(base_b)
            assert not mon.latched
            assert mon.live.observed == 0
            assert mon.last_divergence is None
            assert mon.breaches == 1  # lifetime ledger survives the re-arm
            assert counters.get("drift_rearmed") - before == 1
            # Judged against the NEW baseline the same mix is healthy.
            mon.observe(rng.normal(size=(200, 4)).astype(np.float32) + 100.0)
            assert not mon.latched
        finally:
            knum.unregister_drift("rearm_test")
