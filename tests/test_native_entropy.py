"""Native entropy-decode backend (ops/native_entropy + the jpeg_device
dispatch): the C hot loop must be INDISTINGUISHABLE from the pure-Python
pass — bit-identical CoeffImages over the golden corpus, identical typed
error classification on damaged scans, identical survivor order through
the device-mode stream — and every way it can be absent (env-gated off,
unbuildable toolchain, mid-call failure) must degrade to the Python pass
counted, bit-equal, never a crash.

Tests that PIN the native backend carry ``@pytest.mark.native_entropy``
and auto-skip where the library cannot build (conftest, like ``dist``);
the degradation tests run everywhere — they are the contract for minimal
hosts.
"""

import numpy as np
import pytest

import faults
from test_jpeg_device import _corpus, _jpeg, _make_tar, _stream

from keystone_tpu.core.resilience import counters
from keystone_tpu.ops import jpeg_device as jd
from keystone_tpu.ops import native_entropy as ne


def _coeff_equal(a, b):
    assert a.geom == b.geom
    assert np.array_equal(a.qt, b.qt)
    assert len(a.coeffs) == len(b.coeffs)
    for ca, cb in zip(a.coeffs, b.coeffs):
        assert ca.dtype == cb.dtype == np.int16
        assert np.array_equal(ca, cb)


# -- bit-identity + error parity (native backend pinned) -----------------------


@pytest.mark.native_entropy
def test_golden_corpus_bit_equality(rng):
    """Every corpus member (4:4:4/4:2:2/4:2:0 x quality, odd dims, gray,
    restart markers) decodes to the SAME CoeffImage — geometry, int16
    coefficient planes, quant tables — through both hot loops."""
    for label, data in _corpus(rng):
        py = jd.entropy_decode(data, backend="python")
        nat = jd.entropy_decode(data, backend="native")
        try:
            _coeff_equal(py, nat)
        except AssertionError as exc:
            raise AssertionError(f"{label}: {exc}") from exc


@pytest.mark.native_entropy
def test_error_classification_parity(rng):
    """Damaged scans classify IDENTICALLY: same exception type, same
    message, at every truncation point and under both fault modes — the
    native loop mirrors the Python loop check-for-check."""
    base = _jpeg(
        rng.integers(0, 256, (48, 48, 3)).astype(np.uint8),
        quality=90, subsampling=2, restart_marker_blocks=2,
    )
    bads = [faults.corrupt_jpeg_entropy(base, m)
            for m in ("truncate", "marker")]
    bads += [base[:cut] for cut in range(len(base) - 40, len(base), 7)]

    def outcome(data, backend):
        try:
            jd.entropy_decode(data, backend=backend)
            return ("ok", "")
        except jd.JpegDecodeUnsupported as exc:
            return ("unsupported", exc.reason)
        except jd.JpegEntropyCorrupt as exc:
            return ("corrupt", str(exc))

    for i, bad in enumerate(bads):
        assert outcome(bad, "python") == outcome(bad, "native"), i


@pytest.mark.native_entropy
def test_native_stream_bit_equal_to_python_stream(rng, tmp_path, monkeypatch):
    """The same mixed tar (good members + one entropy-corrupt) through
    decode_mode="device" with the native backend on vs forced-Python
    (``KEYSTONE_NATIVE_ENTROPY=0``): identical survivor names, BIT-equal
    features, the same counted corrupt skip — and the stats record which
    backend ran."""
    good = [
        (f"{i:02d}.jpg",
         _jpeg(rng.integers(0, 256, (48, 48, 3)).astype(np.uint8),
               quality=90, subsampling=(0, 1, 2)[i % 3]))
        for i in range(7)
    ]
    corrupt = faults.corrupt_jpeg_entropy(good[2][1], "truncate")
    members = good[:3] + [("03_bad.jpg", corrupt)] + good[3:]
    tar = str(tmp_path / "mix.tar")
    _make_tar(tar, members)

    monkeypatch.delenv(ne.NATIVE_ENTROPY_ENV, raising=False)
    nf, nn, ns = _stream(tar, 4, decode_mode="device")
    assert ns.entropy_backend == "native"
    monkeypatch.setenv(ne.NATIVE_ENTROPY_ENV, "0")
    pf, pn, ps = _stream(tar, 4, decode_mode="device")
    assert ps.entropy_backend == "python"

    assert nn == pn
    assert np.array_equal(nf, pf)
    assert ns.entropy_corrupt == ps.entropy_corrupt == 1
    assert ns.entropy_decoded == ps.entropy_decoded == 7


@pytest.mark.native_entropy
def test_thread_and_process_backend_ingest_bit_identity(rng, tmp_path):
    """decode_backend thread vs process with the native pass on: the
    entropy pass always runs on the (GIL-releasing) thread pool, so both
    settings must produce bit-identical device-mode streams."""
    members = [
        (f"{i}.jpg",
         _jpeg(rng.integers(0, 256, (48, 48, 3)).astype(np.uint8),
               quality=90))
        for i in range(6)
    ]
    tar = str(tmp_path / "t.tar")
    _make_tar(tar, members)
    tf, tn, ts = _stream(tar, 3, decode_mode="device",
                         decode_backend="thread")
    pf, pn, ps = _stream(tar, 3, decode_mode="device",
                         decode_backend="process")
    assert tn == pn
    assert np.array_equal(tf, pf)
    assert ts.entropy_backend == ps.entropy_backend == "native"


# -- degradation contract (runs on every host, toolchain or not) ---------------


def test_env_zero_forces_python_pass(rng, monkeypatch):
    """``KEYSTONE_NATIVE_ENTROPY=0`` keeps the native loop out of the
    call path entirely (no build attempt, no library call) and the output
    stays correct."""
    data = _jpeg(
        rng.integers(0, 256, (40, 40, 3)).astype(np.uint8), quality=90
    )
    oracle = jd.entropy_decode(data, backend="python")
    calls = []

    def spy(*a, **kw):
        calls.append(a)
        return False

    monkeypatch.setattr(ne, "decode_scan", spy)
    monkeypatch.setenv(ne.NATIVE_ENTROPY_ENV, "0")
    _coeff_equal(oracle, jd.entropy_decode(data))
    assert calls == []
    assert not ne.available()
    assert jd.entropy_backend() == "python"


def test_forced_native_failure_degrades_per_image_counted(rng, monkeypatch):
    """An UNEXPECTED native failure mid-call (not a typed corrupt error)
    degrades that image to the Python pass — bit-equal output, counted
    ``native_entropy_fallback``, never a crash.  Injected at the
    decode_scan boundary so the test runs with or without a toolchain."""
    data = _jpeg(
        rng.integers(0, 256, (44, 36, 3)).astype(np.uint8), quality=88
    )
    oracle = jd.entropy_decode(data, backend="python")

    def boom(segments, planes, *a, **kw):
        # scribble on the planes first: the dispatch must re-zero them
        # before the Python re-decode or the fallback would be wrong
        for p in planes:
            p[...] = 7
        raise RuntimeError("injected native fault")

    monkeypatch.setattr(ne, "decode_scan", boom)
    monkeypatch.delenv(ne.NATIVE_ENTROPY_ENV, raising=False)
    before = counters.snapshot().get("native_entropy_fallback", 0)
    _coeff_equal(oracle, jd.entropy_decode(data))
    after = counters.snapshot().get("native_entropy_fallback", 0)
    assert after == before + 1


def test_typed_corrupt_error_from_native_is_not_a_fallback(rng, monkeypatch):
    """JpegEntropyCorrupt raised by the native loop IS the classification
    — it must propagate as the counted skip, not trigger a Python
    re-decode (which would double-classify the stream)."""
    data = _jpeg(
        rng.integers(0, 256, (40, 40, 3)).astype(np.uint8), quality=90
    )

    def typed(*a, **kw):
        raise jd.JpegEntropyCorrupt("injected corrupt classification")

    monkeypatch.setattr(ne, "decode_scan", typed)
    monkeypatch.delenv(ne.NATIVE_ENTROPY_ENV, raising=False)
    before = counters.snapshot().get("native_entropy_fallback", 0)
    with pytest.raises(jd.JpegEntropyCorrupt, match="injected corrupt"):
        jd.entropy_decode(data)
    assert counters.snapshot().get("native_entropy_fallback", 0) == before


def test_unbuildable_library_degrades_counted_once(rng):
    """No g++ / failed build: the stream stays bit-equal on the Python
    pass with ``native_entropy_unavailable`` counted ONCE per process
    (not per image), and a PINNED native backend raises instead of
    silently comparing Python against itself."""
    data = _jpeg(
        rng.integers(0, 256, (40, 40, 3)).astype(np.uint8), quality=90
    )
    oracle = jd.entropy_decode(data, backend="python")
    orig_lib, orig_build = ne._LIB, ne._build
    ne.reset()
    ne._LIB = orig_lib + ".missing"
    ne._build = lambda: False
    try:
        before = counters.snapshot().get("native_entropy_unavailable", 0)
        _coeff_equal(oracle, jd.entropy_decode(data))
        _coeff_equal(oracle, jd.entropy_decode(data))
        after = counters.snapshot().get("native_entropy_unavailable", 0)
        assert after == before + 1  # once per process, not per image
        assert jd.entropy_backend() == "python"
        with pytest.raises(RuntimeError, match="native"):
            jd.entropy_decode(data, backend="native")
    finally:
        ne._LIB, ne._build = orig_lib, orig_build
        ne.reset()


def test_backend_argument_is_validated(rng):
    data = _jpeg(
        rng.integers(0, 256, (24, 24, 3)).astype(np.uint8), quality=90
    )
    with pytest.raises(ValueError, match="unknown entropy backend"):
        jd.entropy_decode(data, backend="cuda")
