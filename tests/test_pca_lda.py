"""PCA / LDA tests mirroring the reference suite criteria
(src/test/scala/nodes/learning/PCASuite.scala: projected covariance must be
diagonal; LinearDiscriminantAnalysisSuite.scala: known projection vectors,
diagonal covariance after projection)."""

import jax.numpy as jnp
import numpy as np

from keystone_tpu.solvers.pca import (
    BatchPCATransformer,
    LinearDiscriminantAnalysis,
    PCAEstimator,
    compute_pca,
)
from keystone_tpu.utils.stats import about_eq


class TestPCA:
    def test_projected_covariance_diagonal(self, rng):
        # PCASuite criterion: covariance of PCA-projected data is diagonal
        n, d, dims = 500, 10, 4
        base = rng.normal(size=(n, d)).astype(np.float32)
        mixed = base @ rng.normal(size=(d, d)).astype(np.float32)
        pca = PCAEstimator(dims).fit(jnp.asarray(mixed))
        out = np.asarray(pca(jnp.asarray(mixed)))
        cov = np.cov(out, rowvar=False)
        off = cov - np.diag(np.diag(cov))
        assert np.all(np.abs(off) < 1e-2 * np.max(np.diag(cov)))

    def test_matches_numpy_svd(self, rng):
        n, d, dims = 60, 8, 5
        x = rng.normal(size=(n, d)).astype(np.float32)
        got = np.asarray(compute_pca(jnp.asarray(x), dims))
        xc = x - x.mean(axis=0)
        _, _, vt = np.linalg.svd(xc, full_matrices=True)
        pca = vt.T
        signs = np.where(pca.max(axis=0) == np.abs(pca).max(axis=0), 1.0, -1.0)
        expected = (pca * signs)[:, :dims]
        # SVD sign/column conventions agree after the MATLAB sign fix
        assert about_eq(np.abs(got), np.abs(expected), 1e-3)
        assert about_eq(got, expected, 1e-3)

    def test_variance_ordering(self, rng):
        # first component captures the dominant direction
        n = 1000
        x = np.zeros((n, 3), np.float32)
        x[:, 0] = rng.normal(scale=10.0, size=n)
        x[:, 1] = rng.normal(scale=1.0, size=n)
        x[:, 2] = rng.normal(scale=0.1, size=n)
        pca_mat = np.asarray(compute_pca(jnp.asarray(x), 3))
        assert abs(pca_mat[0, 0]) > 0.99  # component 0 ≈ axis 0
        assert abs(pca_mat[1, 1]) > 0.99

    def test_batch_pca_transformer(self, rng):
        mats = rng.normal(size=(3, 8, 11)).astype(np.float32)  # [N, d, cols]
        pca_mat = rng.normal(size=(8, 4)).astype(np.float32)
        out = np.asarray(BatchPCATransformer(jnp.asarray(pca_mat))(jnp.asarray(mats)))
        assert out.shape == (3, 4, 11)
        for i in range(3):
            assert about_eq(out[i], pca_mat.T @ mats[i], 1e-4)


def naive_lda(data, labels, k):
    """Direct eig(inv(Sw) Sb) per the reference, via numpy."""
    classes = np.unique(labels)
    mu = data.mean(axis=0)
    d = data.shape[1]
    sw = np.zeros((d, d))
    sb = np.zeros((d, d))
    for c in classes:
        xc = data[labels == c]
        mc = xc.mean(axis=0)
        xm = xc - mc
        sw += xm.T @ xm
        dm = (mc - mu)[:, None]
        sb += len(xc) * dm @ dm.T
    vals, vecs = np.linalg.eig(np.linalg.inv(sw) @ sb)
    order = np.argsort(-np.abs(vals))[:k]
    w = np.real(vecs[:, order])
    return w / np.linalg.norm(w, axis=0, keepdims=True)


class TestLDA:
    def test_matches_direct_eig(self, rng):
        n_per, d, k = 80, 5, 2
        means = rng.normal(scale=3.0, size=(3, d))
        data = np.concatenate(
            [means[c] + rng.normal(size=(n_per, d)) for c in range(3)]
        ).astype(np.float64)
        labels = np.repeat(np.arange(3), n_per)
        lm = LinearDiscriminantAnalysis(k).fit(jnp.asarray(data), jnp.asarray(labels))
        got = np.asarray(lm.x)
        expected = naive_lda(data, labels, k)
        for j in range(k):  # sign-insensitive, per the reference suite
            assert about_eq(got[:, j], expected[:, j], 1e-2) or about_eq(
                got[:, j], -expected[:, j], 1e-2
            ), (got[:, j], expected[:, j])

    def test_separates_classes(self, rng):
        n_per, d = 100, 6
        means = rng.normal(scale=4.0, size=(4, d))
        data = np.concatenate(
            [means[c] + rng.normal(size=(n_per, d)) for c in range(4)]
        ).astype(np.float32)
        labels = np.repeat(np.arange(4), n_per)
        lm = LinearDiscriminantAnalysis(3).fit(jnp.asarray(data), jnp.asarray(labels))
        proj = np.asarray(lm(jnp.asarray(data)))
        # between-class spread dominates within-class spread after projection
        centroids = np.stack([proj[labels == c].mean(axis=0) for c in range(4)])
        within = np.mean(
            [proj[labels == c].std(axis=0).mean() for c in range(4)]
        )
        between = np.std(centroids, axis=0).mean()
        assert between > 3.0 * within
