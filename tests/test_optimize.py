"""Cost-based optimizer tests (core.optimize): the auto-Cacher decision
table on synthetic profiles (cache/no-cache boundary, budget-denied ->
cheapest wins dropped first, reuse=1 never cached), memoizing-Cacher
pipeline semantics (one recompute saved, bit-identical outputs, test
inputs untouched), StreamConfig env seeding / live mutation, and the
closed-loop ingest autotuner converging on a stall-injected synthetic
stream with bit-equal output."""

import io
import json
import tarfile
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import faults

from keystone_tpu.core import ingest, optimize
from keystone_tpu.core import memory as kmem
from keystone_tpu.core.pipeline import (
    Cacher,
    ChainedEstimator,
    Estimator,
    FunctionTransformer,
    Pipeline,
    PipelineProfile,
    track_reuse,
)
from keystone_tpu.loaders import image_loaders


def cand(name, seconds, nbytes, reuse, index=0):
    return optimize.CacheCandidate(
        index=index, name=name, seconds=seconds, output_bytes=nbytes,
        reuse=reuse,
    )


@pytest.fixture
def no_budget(monkeypatch):
    monkeypatch.delenv(kmem.HBM_BUDGET_ENV, raising=False)


# -- decision table on synthetic profiles -------------------------------------


class TestPlanCaches:
    def test_reuse_one_never_cached(self, no_budget):
        plan = optimize.plan_caches([cand("expensive", 100.0, 1024, reuse=1)])
        d = plan.decisions[0]
        assert not d.cached
        assert "reuse" in d.reason
        assert plan.cached_bytes == 0

    def test_cache_no_cache_boundary(self, no_budget):
        # gbps=1.0: 1 GiB costs 1 s amortized.  win = seconds * (reuse-1).
        gib = 2**30
        plan = optimize.plan_caches(
            [
                cand("worth_it", seconds=2.0, nbytes=gib, reuse=2, index=0),
                cand("not_worth_it", seconds=0.5, nbytes=gib, reuse=2, index=1),
            ],
            gbps=1.0,
        )
        worth, not_worth = plan.decisions
        assert worth.cached and worth.win_seconds == pytest.approx(2.0)
        assert not not_worth.cached
        assert "amortized" in not_worth.reason

    def test_budget_denied_drops_cheapest_win_first(self, monkeypatch):
        # Budget admits ~1.5 MB of cache (3M * 0.5 headroom): only the
        # bigger win fits; the cheaper one is dropped and the denial
        # recorded — never an over-budget cache.
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, str(3 * 2**20))
        mb = 2**20
        plan = optimize.plan_caches(
            [
                cand("small_win", seconds=10.0, nbytes=mb, reuse=2, index=0),
                cand("big_win", seconds=100.0, nbytes=mb, reuse=2, index=1),
            ],
            gbps=1.0,
        )
        by_name = {d.name: d for d in plan.decisions}
        assert by_name["big_win"].cached
        assert not by_name["small_win"].cached
        assert plan.dropped == ["small_win"]
        assert plan.denials == ["small_win"]
        assert plan.cached_bytes == mb

    def test_oversized_win_does_not_abandon_smaller_fits(self, monkeypatch):
        # Greedy knapsack, not first-failure abort: a biggest-win cache
        # over budget is dropped, but a smaller one that fits is kept.
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, str(4 * 2**20))
        plan = optimize.plan_caches(
            [
                cand("small_fits", seconds=10.0, nbytes=2**20, reuse=2, index=0),
                cand("huge_win", seconds=1000.0, nbytes=2**30, reuse=2, index=1),
            ],
            gbps=1000.0,  # both pass the inequality
        )
        by_name = {d.name: d for d in plan.decisions}
        assert not by_name["huge_win"].cached
        assert by_name["small_fits"].cached
        assert plan.dropped == ["huge_win"]
        assert plan.cached_bytes == 2**20

    def test_budget_denial_is_counted(self, monkeypatch):
        from keystone_tpu.core.resilience import counters

        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1K")
        before = counters.get("cache_admission_denied")
        plan = optimize.plan_caches([cand("x", 100.0, 2**20, reuse=3)])
        assert not plan.decisions[0].cached
        assert counters.get("cache_admission_denied") == before + 1

    def test_no_budget_admits_eligible(self, no_budget):
        plan = optimize.plan_caches([cand("x", 100.0, 2**20, reuse=3)])
        assert plan.decisions[0].cached
        assert plan.cached_bytes == 2**20

    def test_reuse_scales_the_win(self, no_budget):
        # reuse=3 doubles the win of reuse=2 — the KeystoneML inequality
        # counts SAVED recomputes, not touches.
        p2 = optimize.plan_caches([cand("x", 1.0, 0, reuse=2)])
        p3 = optimize.plan_caches([cand("x", 1.0, 0, reuse=3)])
        assert p3.decisions[0].win_seconds == pytest.approx(
            2 * p2.decisions[0].win_seconds
        )

    def test_to_json_round_trips(self, no_budget):
        plan = optimize.plan_caches(
            [cand("a", 5.0, 1024, reuse=2), cand("b", 0.0, 9, reuse=1)],
            dataset_rows=1000,
            sample_rows=10,
        )
        doc = json.loads(plan.to_json())
        assert doc["cached"] == ["a"]
        assert doc["dataset_rows"] == 1000
        assert len(doc["decisions"]) == 2
        assert all("reason" in d for d in doc["decisions"])


def test_pipeline_profile_to_json_round_trips():
    pipe = Pipeline([
        FunctionTransformer(lambda x: x * 2, name="double"),
        FunctionTransformer(lambda x: x + 1, name="inc"),
    ])
    prof = pipe.profile(jnp.ones((4, 3), jnp.float32))
    back = PipelineProfile.from_json(prof.to_json())
    assert [n.name for n in back.nodes] == ["double", "inc"]
    assert back.nodes[0].output_bytes == prof.nodes[0].output_bytes
    assert back.input_bytes == prof.input_bytes
    # embeddable: the JSON parses as one document
    assert json.loads(prof.to_json())["nodes"][1]["name"] == "inc"


# -- reuse tracking and the memoizing Cacher ----------------------------------


class _MeanCenter(Estimator):
    def fit(self, data):
        m = float(np.asarray(data).mean())
        return FunctionTransformer(lambda x, m=m: x - m, name="center")


def _counting_node(calls, name="expensive"):
    def fn(x):
        calls[name] = calls.get(name, 0) + 1
        return x * 2.0

    return FunctionTransformer(fn, name=name)


class TestReuseAndMemo:
    def test_track_reuse_counts_chained_fit_pattern(self):
        calls = {}
        node = _counting_node(calls)
        chain = node.then_estimator(_MeanCenter())
        x = np.ones((8, 4), np.float32)
        with track_reuse() as counts:
            fitted = chain.fit(x)
            fitted(x)
        # fit pushes through the xform once, the fitted apply again
        assert counts[id(node)] == 2

    def test_measure_chain_reuse(self):
        calls = {}
        node = _counting_node(calls)
        chain = node.then_estimator(_MeanCenter())
        reuse = optimize.measure_chain_reuse(chain, np.ones((4, 2), np.float32))
        assert reuse == {0: 2}

    def test_memoizing_cacher_saves_the_recompute(self):
        calls = {}
        pipe = Pipeline([
            _counting_node(calls),
            Cacher(name="auto", memoize=True),
            FunctionTransformer(lambda x: x + 1.0, name="inc"),
        ])
        x = np.ones((4, 2), np.float32)
        out1 = pipe(x)
        out2 = pipe(x)  # same object -> memo hit, no recompute
        assert calls["expensive"] == 1
        assert np.array_equal(np.asarray(out1), np.asarray(out2))

    def test_memo_is_keyed_on_input_identity(self):
        calls = {}
        pipe = Pipeline([
            _counting_node(calls), Cacher(name="auto", memoize=True),
        ])
        a = np.ones((4, 2), np.float32)
        b = np.ones((4, 2), np.float32)  # equal VALUES, different object
        out_a = pipe(a)
        out_b = pipe(b)  # must recompute: identity, not value, is the key
        assert calls["expensive"] == 2
        assert np.array_equal(np.asarray(out_a), np.asarray(out_b))
        # ...and the second input did not evict the armed entry
        pipe(a)
        assert calls["expensive"] == 2

    def test_clear_memo_releases_the_entry(self):
        calls = {}
        cacher = Cacher(name="auto", memoize=True)
        pipe = Pipeline([_counting_node(calls), cacher])
        x = np.ones((2, 2), np.float32)
        pipe(x)
        optimize.release_caches(pipe)
        pipe(x)
        assert calls["expensive"] == 2

    def test_memoizing_cacher_is_inert_under_jit(self):
        pipe = Pipeline([
            FunctionTransformer(lambda x: x * 2.0, name="double"),
            Cacher(name="auto", memoize=True),
        ])
        out = jax.jit(pipe.__call__)(jnp.ones((2, 2), jnp.float32))
        assert np.allclose(np.asarray(out), 2.0)

    def test_non_memoizing_cacher_unchanged(self):
        # The pre-existing Cacher contract: a pure materialization barrier.
        pipe = Pipeline([FunctionTransformer(lambda x: x + 1, name="inc"), Cacher()])
        x = jnp.ones((2, 2), jnp.float32)
        assert np.allclose(np.asarray(pipe(x)), 2.0)
        assert pipe._memo_cachers == ()


class TestAutoCacheChain:
    def test_cached_chain_computes_once_and_matches(self, no_budget):
        calls = {}
        chain = _counting_node(calls).then_estimator(_MeanCenter())
        x = np.arange(64, dtype=np.float32).reshape(16, 4)
        fitted_u = chain.fit(x)
        out_u = fitted_u(x)
        assert calls["expensive"] == 2  # the uncached fit pattern

        calls.clear()
        opt, plan = optimize.auto_cache_chain(
            _counting_node(calls).then_estimator(_MeanCenter()),
            x[:4], dataset_rows=16,
        )
        assert [d.name for d in plan.cached()] == ["expensive"]
        calls.clear()
        fitted_c = opt.fit(x)
        out_c = fitted_c(x)
        assert calls["expensive"] == 1  # the Cacher replayed the fit value
        assert np.array_equal(np.asarray(out_u), np.asarray(out_c))
        # a DIFFERENT input (the test split) computes normally
        y = x + 1.0
        calls.clear()
        fitted_c(y)
        assert calls["expensive"] == 1

    def test_budget_denied_chain_is_uncached_but_equal(self, monkeypatch):
        calls = {}
        x = np.arange(64, dtype=np.float32).reshape(16, 4)
        fitted_u = (
            _counting_node(calls).then_estimator(_MeanCenter()).fit(x)
        )
        out_u = fitted_u(x)
        monkeypatch.setenv(kmem.HBM_BUDGET_ENV, "1")
        calls.clear()
        opt, plan = optimize.auto_cache_chain(
            _counting_node(calls).then_estimator(_MeanCenter()),
            x[:4], dataset_rows=16,
        )
        assert plan.cached() == [] and plan.dropped == ["expensive"]
        # no Cacher inserted: node count unchanged
        assert len(opt.xform.nodes) == 1
        calls.clear()
        out_c = opt.fit(x)(x)
        assert calls["expensive"] == 2
        assert np.array_equal(np.asarray(out_u), np.asarray(out_c))


# -- StreamConfig -------------------------------------------------------------


class TestStreamConfig:
    def test_from_env_seeds_the_initial_values(self, monkeypatch):
        monkeypatch.setenv("KEYSTONE_DECODE_THREADS", "3")
        monkeypatch.setenv("KEYSTONE_DECODE_AHEAD", "5")
        monkeypatch.setenv("KEYSTONE_RING_CAPACITY", "7")
        monkeypatch.setenv("KEYSTONE_AUTOTUNE", "1")
        monkeypatch.setenv("KEYSTONE_AUTOTUNE_INTERVAL", "9")
        cfg = ingest.StreamConfig.from_env()
        assert (cfg.decode_threads, cfg.decode_ahead, cfg.ring_capacity) == (3, 5, 7)
        assert cfg.autotune and cfg.autotune_interval == 9
        assert cfg.max_decode_threads >= cfg.decode_threads

    def test_overrides_beat_env(self, monkeypatch):
        monkeypatch.setenv("KEYSTONE_DECODE_THREADS", "3")
        cfg = ingest.StreamConfig.from_env(decode_threads=2, ring_capacity=1)
        assert cfg.decode_threads == 2 and cfg.ring_capacity == 1

    def test_validation(self):
        with pytest.raises(ValueError):
            ingest.StreamConfig(decode_threads=0, decode_ahead=0, ring_capacity=1)
        with pytest.raises(ValueError):
            ingest.StreamConfig(decode_threads=1, decode_ahead=-1, ring_capacity=1)
        with pytest.raises(ValueError):
            ingest.StreamConfig(decode_threads=1, decode_ahead=0, ring_capacity=0)
        # an EXPLICIT tuner cap below the width is a contradiction, never
        # silently widened past the caller's bound
        with pytest.raises(ValueError, match="max_decode_threads"):
            ingest.StreamConfig(
                decode_threads=4, decode_ahead=0, ring_capacity=1,
                max_decode_threads=2,
            )

    def test_legacy_kwargs_are_validated(self, tmp_path, rng):
        path = str(tmp_path / "v.tar")
        _small_tar(path, 2, rng)
        cfg = ingest.StreamConfig(
            decode_threads=2, decode_ahead=0, ring_capacity=2
        )
        with pytest.raises(ValueError):
            ingest.stream_batches(path, 2, config=cfg, num_threads=0)

    def test_legacy_kwargs_override_config(self, tmp_path, rng):
        path = str(tmp_path / "t.tar")
        _small_tar(path, 4, rng)
        cfg = ingest.StreamConfig(
            decode_threads=4, decode_ahead=4, ring_capacity=4
        )
        with ingest.stream_batches(path, 2, config=cfg, num_threads=1, capacity=2) as st:
            list(st)
        assert st.config is cfg
        assert cfg.decode_threads == 1 and cfg.ring_capacity == 2
        assert st.join(10.0)


def _small_tar(path, n, rng, size=48):
    with tarfile.open(path, "w") as tf:
        for i in range(n):
            data = faults.make_jpeg_bytes(rng, size, size)
            info = tarfile.TarInfo(f"img_{i:04d}.jpg")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))


def _collect(path, batch, config=None, tuner=None):
    with ingest.stream_batches(path, batch, config=config, tuner=tuner) as st:
        out = [
            (b.indices.copy(), b.host.copy(), list(b.names)) for b in st
        ]
    assert st.join(10.0)
    return out, st


def _streams_equal(a, b):
    return len(a) == len(b) and all(
        np.array_equal(x[0], y[0])
        and np.array_equal(x[1], y[1])
        and x[2] == y[2]
        for x, y in zip(a, b)
    )


# -- the closed-loop autotuner ------------------------------------------------


class TestIngestAutotuner:
    def test_converges_on_a_stall_injected_stream(self, tmp_path, rng, monkeypatch):
        """Decode slowed artificially -> the consumer stalls on an empty
        ring -> the controller must widen decode from its static default,
        and the retuned stream's output must be BIT-EQUAL to the static
        run (typed-or-equal: retuning changes speed, never results)."""
        path = str(tmp_path / "stall.tar")
        _small_tar(path, 24, rng)

        real = image_loaders.decode_image

        def slow(data):
            time.sleep(0.01)  # the injected stall: decode-bound by fiat
            return real(data)

        monkeypatch.setattr(image_loaders, "decode_image", slow)

        static_cfg = ingest.StreamConfig(
            decode_threads=1, decode_ahead=0, ring_capacity=2,
            max_decode_threads=8,
        )
        static, _ = _collect(path, 4, config=static_cfg)

        tuned_cfg = ingest.StreamConfig(
            decode_threads=1, decode_ahead=0, ring_capacity=2,
            max_decode_threads=8, autotune=True, autotune_interval=2,
        )
        tuned, st = _collect(path, 4, config=tuned_cfg)

        rec = st.tuner.record()
        assert rec["retunes"] >= 1, rec
        # at least one knob moved off its static default
        assert tuned_cfg.decode_threads > 1, rec
        assert _streams_equal(static, tuned)

    def test_quiet_stream_is_left_alone(self, tmp_path, rng):
        """No stall signal -> no retune (the controller must not thrash a
        converged pipeline)."""
        path = str(tmp_path / "quiet.tar")
        _small_tar(path, 8, rng)
        cfg = ingest.StreamConfig(
            decode_threads=2, decode_ahead=2, ring_capacity=4,
            autotune=True, autotune_interval=1,
        )
        tuner = optimize.IngestAutotuner()
        with ingest.stream_batches(path, 2, config=cfg, tuner=tuner) as st:
            for b in st:
                time.sleep(0.02)  # consumer slower than decode, ring fills
        assert st.join(10.0)
        # producer-blocked intervals may deepen the ring / narrow decode,
        # but the decode-bound escalation must not fire
        assert cfg.decode_threads <= 2

    def test_manual_mid_stream_retune_is_bit_equal(self, tmp_path, rng):
        """StreamConfig is a programmatic surface: mutating it mid-stream
        (no tuner at all) must preserve output identity."""
        path = str(tmp_path / "manual.tar")
        _small_tar(path, 12, rng)
        baseline, _ = _collect(path, 3)

        cfg = ingest.StreamConfig(
            decode_threads=1, decode_ahead=0, ring_capacity=1,
            max_decode_threads=4,
        )
        got = []
        with ingest.stream_batches(path, 3, config=cfg) as st:
            for i, b in enumerate(st):
                got.append((b.indices.copy(), b.host.copy(), list(b.names)))
                if i == 1:
                    cfg.decode_threads = 4
                    cfg.decode_ahead = 6
                    cfg.ring_capacity = 8
        assert st.join(10.0)
        assert _streams_equal(baseline, got)

    def test_retunes_land_in_metrics_and_trajectory(self, tmp_path, rng, monkeypatch):
        from keystone_tpu.core import trace

        path = str(tmp_path / "metrics.tar")
        _small_tar(path, 16, rng)
        real = image_loaders.decode_image
        monkeypatch.setattr(
            image_loaders, "decode_image",
            lambda data: (time.sleep(0.01), real(data))[1],
        )
        cfg = ingest.StreamConfig(
            decode_threads=1, decode_ahead=0, ring_capacity=2,
            max_decode_threads=4, autotune=True, autotune_interval=1,
        )
        before = trace.metrics.get("ingest_retunes")
        _, st = _collect(path, 4, config=cfg)
        rec = st.tuner.record()
        assert trace.metrics.get("ingest_retunes") - before == rec["retunes"]
        for entry in rec["trajectory"]:
            assert set(entry) == {
                "chunk", "producer_stalls_delta", "consumer_stalls_delta",
                "changes",
            }
            for knob, (old, new) in entry["changes"].items():
                assert knob in (
                    "decode_threads", "decode_ahead", "ring_capacity",
                    "decode_backend", "decode_procs",
                )
                assert old != new
        assert rec["final_config"] == cfg.record()


class TestBackendPromotion:
    """The autotuner's decode-backend knob (ISSUE 7): a decode-width
    doubling that buys < SCALING_FLOOR (1.3x) chunk throughput while the
    stream stays decode-bound reads as GIL-bound, and promotes the stream
    to the spawned-process backend."""

    def _tuner_on(self, cfg):
        import types

        stats = ingest.StreamStats()
        stream = types.SimpleNamespace(config=cfg, stats=stats)
        tuner = optimize.IngestAutotuner(interval=1)
        tuner.attach(stream)
        clock = {"t": 0.0}
        tuner._now = lambda: clock["t"]

        def tick(dt, consumer_stalls=1, producer_stalls=0):
            stats.consumer_stalls += consumer_stalls
            stats.producer_stalls += producer_stalls
            clock["t"] += dt
            tuner.on_chunk(stream)

        return tuner, tick

    def test_flat_scaling_promotes_to_process(self):
        cfg = ingest.StreamConfig(
            decode_threads=2, decode_ahead=0, ring_capacity=2,
            max_decode_threads=8,
        )
        tuner, tick = self._tuner_on(cfg)
        tick(1.0)  # warm-up interval, discarded
        tick(1.0)  # decode-bound at rate 1.0 -> widen 2->4, rate remembered
        assert cfg.decode_threads == 4 and cfg.decode_backend == "thread"
        tick(0.9)  # rate 1.11: a 2x widen bought 1.11x < 1.3x -> GIL-bound
        assert cfg.decode_backend == "process"
        # the pool width follows the TUNED decode width, not the starved
        # initial decode_procs resolution (a 1-worker "parallel" pool
        # would defeat the promotion)
        assert cfg.decode_procs == cfg.decode_threads == 4
        assert any(
            "decode_backend" in e["changes"] for e in tuner.trajectory
        )

    def test_capped_widen_scales_the_promotion_floor(self):
        """A ceiling-capped widen (7->8, ratio 1.14) only promises ~1.04x
        even core-bound — holding it to the full-doubling 1.3x floor would
        misread perfect linear scaling as GIL-bound and promote."""
        cfg = ingest.StreamConfig(
            decode_threads=7, decode_ahead=0, ring_capacity=2,
            max_decode_threads=8,
        )
        _tuner, tick = self._tuner_on(cfg)
        tick(1.0)  # warm-up
        tick(1.0)  # decode-bound at rate 1.0 -> widen 7->8 (NOT a 2x)
        assert cfg.decode_threads == 8 and cfg.decode_backend == "thread"
        tick(0.875)  # rate 8/7: perfect linear scaling for a 7->8 widen
        assert cfg.decode_backend == "thread"  # core-bound, not GIL-bound

    def test_real_scaling_keeps_widening_threads(self):
        cfg = ingest.StreamConfig(
            decode_threads=2, decode_ahead=0, ring_capacity=2,
            max_decode_threads=8,
        )
        _tuner, tick = self._tuner_on(cfg)
        tick(1.0)  # warm-up
        tick(1.0)  # widen 2->4 at rate 1.0
        tick(0.4)  # rate 2.5: the widen scaled -> widen again, no promotion
        assert cfg.decode_backend == "thread"
        assert cfg.decode_threads == 8

    def test_promotion_can_be_disallowed(self):
        import types

        cfg = ingest.StreamConfig(
            decode_threads=2, decode_ahead=0, ring_capacity=2,
            max_decode_threads=8,
        )
        stats = ingest.StreamStats()
        stream = types.SimpleNamespace(config=cfg, stats=stats)
        tuner = optimize.IngestAutotuner(
            interval=1, allow_backend_switch=False
        )
        tuner.attach(stream)
        clock = {"t": 0.0}
        tuner._now = lambda: clock["t"]
        for dt in (1.0, 1.0, 0.9, 0.9, 0.9):
            stats.consumer_stalls += 1
            clock["t"] += dt
            tuner.on_chunk(stream)
        assert cfg.decode_backend == "thread"

    def test_consumer_bound_interval_resets_the_evidence(self):
        cfg = ingest.StreamConfig(
            decode_threads=2, decode_ahead=0, ring_capacity=2,
            max_decode_threads=8,
        )
        _tuner, tick = self._tuner_on(cfg)
        tick(1.0)  # warm-up
        tick(1.0)  # widen, rate remembered
        tick(1.0, consumer_stalls=0, producer_stalls=1)  # device-bound now
        tick(0.9)  # decode-bound again, but stale evidence was dropped
        assert cfg.decode_backend == "thread"


class TestSnapshotAdvisor:
    def test_repeat_epochs_with_cheap_io_advise(self):
        adv = optimize.advise_snapshot(
            images=1000, bytes_per_image=1000,
            decode_images_per_sec=100.0, epochs=5, gbps=1.0,
        )
        assert adv.advise
        assert adv.live_seconds == pytest.approx(50.0)
        # decode once + 5x (tiny) shard IO
        assert adv.snapshot_seconds < adv.live_seconds

    def test_single_epoch_never_advises(self):
        adv = optimize.advise_snapshot(
            images=1000, bytes_per_image=1000,
            decode_images_per_sec=100.0, epochs=1, gbps=1.0,
        )
        assert not adv.advise and "single pass" in adv.reason

    def test_slow_disk_declines(self):
        adv = optimize.advise_snapshot(
            images=1000, bytes_per_image=10**6,
            decode_images_per_sec=10**6, epochs=5, gbps=0.001,
        )
        assert not adv.advise

    def test_record_is_jsonable(self):
        import json

        adv = optimize.advise_snapshot(
            images=10, bytes_per_image=10,
            decode_images_per_sec=1.0, epochs=2,
        )
        assert json.loads(json.dumps(adv.record()))["epochs"] == 2

    def test_validation(self):
        with pytest.raises(ValueError):
            optimize.advise_snapshot(
                images=1, bytes_per_image=1,
                decode_images_per_sec=0.0, epochs=2,
            )
